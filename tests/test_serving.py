"""Serving subsystem: paged KV cache, continuous batcher, elastic pool.

The decisive properties, in dependency order:

- **allocator**: exhaustion / free / reuse / double-free are exact — a
  silently double-freed block would hand one page to two sequences;
- **paged == contiguous, bitwise**: the gather → ragged decode → scatter
  step over block tables produces exactly the tokens the contiguous-cache
  ``generate`` produces, for greedy AND sampled requests, through ragged
  joins (a fresh prefill entering a batch of mid-decode sequences), and
  regardless of what the null block holds;
- **admission/retirement state machine**: block reservation is
  all-or-nothing, head-of-line FIFO, bounded by the join-at-step prefill
  budget; retirement frees every block immediately;
- **elastic pool**: a dead replica (hang, crash, or silent heartbeat
  death — the latter driven by the injectable ``_wall`` clock) drains its
  in-flight requests to survivors and the pool finishes everything,
  degraded instead of failed;
- **on-demand admission + preemption** (PR 11): prompt-blocks-only
  admission grows per block boundary, keeps more sequences resident than
  reservation at equal pool memory, and mid-decode exhaustion preempts
  the newest sequence (swap-out or recompute) with resume that continues
  to exactly ``generate``'s tokens — including a resume that lands
  mid-block, and through the replica pool's drain/re-route.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flextree_tpu.models.generate import generate, prefill
from flextree_tpu.models.transformer import TransformerConfig, init_params
from flextree_tpu.serving import (
    NULL_BLOCK,
    BatcherConfig,
    BlockAllocator,
    CacheExhausted,
    ContinuousBatcher,
    PagedCacheConfig,
    PoolConfig,
    ReplicaPool,
    Request,
    ServingEngine,
    gather_seq,
    init_pools,
    paged_decode_step,
    write_prefill,
)


def _cfg(**kw):
    base = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64)
    base.update(kw)
    return TransformerConfig(**base)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _pcfg(**kw):
    base = dict(num_blocks=32, block_size=8, blocks_per_seq=6)  # max_len 48
    base.update(kw)
    return PagedCacheConfig(**base)


def _prompt(rng, t):
    return rng.integers(0, 64, (t,)).astype(np.int32)


# ---------------------------------------------------------------- allocator


def test_allocator_exhaustion_is_all_or_nothing():
    a = BlockAllocator(num_blocks=5)  # 4 allocatable (block 0 reserved)
    assert a.num_free == 4
    got = a.alloc(3)
    assert len(got) == 3 and NULL_BLOCK not in got
    with pytest.raises(CacheExhausted, match="FT_CACHE_EXHAUSTED"):
        a.alloc(2)
    assert a.num_free == 1  # the failed alloc took nothing


def test_allocator_free_reuse_and_double_free():
    a = BlockAllocator(num_blocks=6)
    x = a.alloc(5)
    assert a.num_free == 0
    a.free(x[:2])
    assert a.num_free == 2
    y = a.alloc(2)
    assert set(y) == set(x[:2])  # LIFO reuse of just-freed blocks
    with pytest.raises(ValueError, match="duplicate"):
        a.free(y + y[:1])  # one call, overlapping ids: loud, takes nothing
    assert a.num_free == 0
    # precise double-free: free once is fine, twice is loud
    a.free(y)
    with pytest.raises(ValueError, match="not allocated"):
        a.free(y)


def test_allocator_never_hands_out_null_block():
    a = BlockAllocator(num_blocks=8)
    assert NULL_BLOCK not in a.alloc(7)
    with pytest.raises(ValueError):
        BlockAllocator(num_blocks=1)
    with pytest.raises(ValueError):
        a.free([NULL_BLOCK])


def test_allocator_churn_property():
    """Random alloc/free interleavings (the on-demand allocator's real
    life): the null block is never handed out, no block is ever owned
    twice, and the free list never acquires duplicates or foreign ids —
    across 200 seeded episodes of mixed traffic."""
    rng = np.random.default_rng(42)
    a = BlockAllocator(num_blocks=17)  # 16 allocatable
    held: list = []  # lists of blocks, freed in random order/groups
    for step in range(200):
        # invariants, every step
        free = set(a._free)
        owned = set(a._allocated)
        assert NULL_BLOCK not in free and NULL_BLOCK not in owned
        assert len(a._free) == len(free), "free list acquired duplicates"
        assert not (free & owned), "a block is both free and allocated"
        assert free | owned == set(range(1, 17)), "foreign or lost ids"
        if held and (rng.random() < 0.45 or a.num_free == 0):
            grp = held.pop(rng.integers(len(held)))
            # split the group: partial frees interleave with allocs
            cut = int(rng.integers(len(grp) + 1))
            if cut:
                a.free(grp[:cut])
            if grp[cut:]:
                held.append(grp[cut:])
        else:
            want = int(rng.integers(1, 5))
            if want > a.num_free:
                with pytest.raises(CacheExhausted):
                    a.alloc(want)
            else:
                got = a.alloc(want)
                assert len(set(got)) == len(got), "double-allocated"
                assert NULL_BLOCK not in got
                held.append(got)
    for grp in held:
        a.free(grp)
    assert a.num_free == 16


def test_allocator_free_rejects_foreign_ids():
    a = BlockAllocator(num_blocks=6)
    got = a.alloc(2)
    with pytest.raises(ValueError, match="not allocated"):
        a.free(got + [99])  # foreign id: loud, and the call takes nothing
    assert a.num_free == 3


def test_paged_cache_config_validation():
    assert _pcfg().max_len == 48
    assert _pcfg().blocks_for(1) == 1
    assert _pcfg().blocks_for(8) == 1
    assert _pcfg().blocks_for(9) == 2
    with pytest.raises(ValueError):
        PagedCacheConfig(num_blocks=1)
    with pytest.raises(ValueError):
        PagedCacheConfig(num_blocks=4, block_size=0)


# ------------------------------------------------- gather/scatter equivalence


def test_write_prefill_gather_roundtrip_bitwise(model):
    """Prefill K/V scattered into pool blocks gathers back bitwise."""
    cfg, params = model
    pcfg = _pcfg()
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(_prompt(rng, 13))[None]
    _, cache = prefill(params, prompt, cfg, max_len=pcfg.max_len)
    blocks = BlockAllocator(pcfg.num_blocks).alloc(pcfg.blocks_for(13))
    pools = write_prefill(init_pools(cfg, pcfg), cache, blocks)
    view = gather_seq(pools, blocks, length=13)
    for l in range(cfg.n_layers):
        np.testing.assert_array_equal(
            np.asarray(view["k"][l]), np.asarray(cache["k"][l][0, :13])
        )
        np.testing.assert_array_equal(
            np.asarray(view["v"][l]), np.asarray(cache["v"][l][0, :13])
        )


def test_null_block_content_is_invisible(model):
    """The bitwise contract's load-bearing property: whatever the null
    block holds sits beyond every causal bound, where the mask drives its
    softmax weight to exactly 0.0 — logits AND scattered K/V must be
    bitwise identical under a poisoned null block."""
    cfg, params = model
    pcfg = _pcfg(num_blocks=8)
    rng = np.random.default_rng(1)
    prompt = jnp.asarray(_prompt(rng, 11))[None]
    _, cache = prefill(params, prompt, cfg, max_len=pcfg.max_len)
    blocks = BlockAllocator(pcfg.num_blocks).alloc(pcfg.blocks_for(11 + 1))
    tables = np.full((1, pcfg.blocks_per_seq), NULL_BLOCK, np.int32)
    tables[0, : len(blocks)] = blocks
    lengths = np.asarray([11], np.int32)
    tokens = np.asarray([7], np.int32)

    outs = []
    for poison in (False, True):
        pools = write_prefill(init_pools(cfg, pcfg), cache, blocks)
        if poison:
            for kind in ("k", "v"):
                pools[kind] = [
                    p.at[NULL_BLOCK].set(1e30) for p in pools[kind]
                ]
        outs.append(paged_decode_step(
            params, pools, tables, lengths, tokens, cfg
        ))
    np.testing.assert_array_equal(np.asarray(outs[0][0]), np.asarray(outs[1][0]))
    for l in range(cfg.n_layers):
        np.testing.assert_array_equal(
            np.asarray(outs[0][1]["k"][l][1:]), np.asarray(outs[1][1]["k"][l][1:])
        )


# --------------------------------------------------- engine bitwise contract


def test_engine_greedy_bitwise_matches_generate_ragged_joins(model):
    """The acceptance floor, in-suite: staggered ragged requests through
    one shared pool produce exactly generate()'s tokens per request."""
    cfg, params = model
    pcfg = _pcfg()
    eng = ServingEngine(params, cfg, pcfg, BatcherConfig(slots=3))
    rng = np.random.default_rng(2)
    reqs = [
        Request(rid=i, prompt=_prompt(rng, t), max_new_tokens=m)
        for i, (t, m) in enumerate([(5, 6), (9, 4), (13, 8), (7, 5), (11, 7)])
    ]
    # stagger: 3 up front (fill every slot), the rest join mid-decode
    for r in reqs[:3]:
        assert eng.submit(r)
    eng.step()
    for r in reqs[3:]:
        assert eng.submit(r)
    eng.run_until_idle()
    for r in reqs:
        want = np.asarray(
            generate(params, jnp.asarray(r.prompt)[None], cfg,
                     max_new_tokens=r.max_new_tokens, max_len=pcfg.max_len)
        )[0]
        np.testing.assert_array_equal(eng.completed[r.rid].tokens, want)
    # every reserved block came back
    assert eng.batcher.allocator.num_free == pcfg.num_blocks - 1


def test_engine_sampled_request_matches_generate_key_schedule(model):
    cfg, params = model
    pcfg = _pcfg()
    eng = ServingEngine(params, cfg, pcfg, BatcherConfig(slots=2))
    rng = np.random.default_rng(3)
    prompt = _prompt(rng, 6)
    eng.submit(Request(rid=0, prompt=prompt, max_new_tokens=8,
                       temperature=0.8, top_k=4, seed=17))
    eng.run_until_idle()
    want = np.asarray(
        generate(params, jnp.asarray(prompt)[None], cfg, max_new_tokens=8,
                 max_len=pcfg.max_len, temperature=0.8, top_k=4,
                 key=jax.random.PRNGKey(17))
    )[0]
    np.testing.assert_array_equal(eng.completed[0].tokens, want)


def test_engine_sampled_without_seed_rejected_at_submit(model):
    """Discovered mid-prefill this would wedge the slot (blocks reserved,
    no sampler key) — so it must be refused BEFORE admission."""
    cfg, params = model
    eng = ServingEngine(params, cfg, _pcfg(), BatcherConfig(slots=1))
    assert not eng.submit(Request(rid=0, prompt=np.arange(4, dtype=np.int32),
                                  max_new_tokens=2, temperature=1.0))
    assert "seed" in eng.batcher.rejected[0][1]
    assert eng.idle


def test_engine_stop_token_retires_and_frees(model):
    cfg, params = model
    pcfg = _pcfg()
    rng = np.random.default_rng(4)
    prompt = _prompt(rng, 7)
    free_run = np.asarray(
        generate(params, jnp.asarray(prompt)[None], cfg, max_new_tokens=8,
                 max_len=pcfg.max_len)
    )[0]
    stop_tok = int(free_run[2])
    first = int(np.argmax(free_run == stop_tok))
    eng = ServingEngine(params, cfg, pcfg, BatcherConfig(slots=2))
    eng.submit(Request(rid=9, prompt=prompt, max_new_tokens=8,
                       stop_tokens=(stop_tok,)))
    eng.run_until_idle()
    np.testing.assert_array_equal(
        eng.completed[9].tokens, free_run[: first + 1]
    )
    assert eng.batcher.allocator.num_free == pcfg.num_blocks - 1


def test_engine_bf16_paged_matches_generate():
    cfg = _cfg(dtype=jnp.bfloat16)
    params = init_params(jax.random.PRNGKey(0), cfg)
    pcfg = _pcfg()
    eng = ServingEngine(params, cfg, pcfg, BatcherConfig(slots=2))
    rng = np.random.default_rng(5)
    reqs = [Request(rid=i, prompt=_prompt(rng, t), max_new_tokens=4)
            for i, t in enumerate([6, 10])]
    for r in reqs:
        eng.submit(r)
    eng.run_until_idle()
    for r in reqs:
        want = np.asarray(
            generate(params, jnp.asarray(r.prompt)[None], cfg,
                     max_new_tokens=4, max_len=pcfg.max_len)
        )[0]
        np.testing.assert_array_equal(eng.completed[r.rid].tokens, want)


def test_engine_oversized_request_rejected_not_queued(model):
    cfg, params = model
    pcfg = _pcfg()  # max_len 48
    eng = ServingEngine(params, cfg, pcfg, BatcherConfig(slots=1))
    assert not eng.submit(Request(rid=0, prompt=np.arange(40, dtype=np.int32),
                                  max_new_tokens=20))
    assert eng.batcher.rejected and eng.idle


def test_engine_capacity_pressure_completes_all(model):
    """More concurrent demand than the pool holds: admission waits for
    retirements, everything still finishes, blocks never go negative."""
    cfg, params = model
    pcfg = _pcfg(num_blocks=9)  # 8 allocatable; each request needs 2-3
    eng = ServingEngine(params, cfg, pcfg, BatcherConfig(slots=4))
    rng = np.random.default_rng(6)
    reqs = [Request(rid=i, prompt=_prompt(rng, 9), max_new_tokens=6)
            for i in range(7)]
    for r in reqs:
        assert eng.submit(r)
    eng.run_until_idle()
    assert sorted(eng.completed) == list(range(7))
    assert eng.batcher.allocator.num_free == 8


# ----------------------------------------------- batcher state machine (pure)


def test_admission_reserves_all_or_nothing():
    pcfg = _pcfg(num_blocks=6)  # 5 allocatable
    b = ContinuousBatcher(pcfg, BatcherConfig(slots=4))
    # needs ceil((17+15)/8) = 4 blocks
    b.submit(Request(rid=0, prompt=np.zeros(17, np.int32), max_new_tokens=15))
    # needs 3 blocks — must NOT jump the queue when 0 admits first
    b.submit(Request(rid=1, prompt=np.zeros(9, np.int32), max_new_tokens=9))
    admitted = b.try_admit()
    assert [s.rid for _, s in admitted] == [0]
    assert b.allocator.num_free == 1  # 4 reserved up front
    # head-of-line: rid 1 waits even though a slot is free
    assert b.try_admit() == []
    assert [r.rid for r in b.queue] == [1]
    # retirement frees everything and admits the waiter
    b.slots[admitted[0][0]].done = True
    assert [s.rid for _, s in b.retire_ready()] == [0]
    assert b.allocator.num_free == 5
    assert [s.rid for _, s in b.try_admit()] == [1]


def test_admission_prefill_token_budget_joins_at_step():
    pcfg = _pcfg(num_blocks=32)
    b = ContinuousBatcher(
        pcfg, BatcherConfig(slots=4, max_prefill_tokens_per_step=10)
    )
    for i, t in enumerate([8, 8, 8]):
        b.submit(Request(rid=i, prompt=np.zeros(t, np.int32), max_new_tokens=4))
    # one 8-token prefill fits the 10-token budget; the second would blow it
    assert [s.rid for _, s in b.try_admit()] == [0]
    assert [s.rid for _, s in b.try_admit()] == [1]  # next step admits more
    # a prompt longer than the whole budget still admits when it is first
    b2 = ContinuousBatcher(
        pcfg, BatcherConfig(slots=2, max_prefill_tokens_per_step=4)
    )
    b2.submit(Request(rid=9, prompt=np.zeros(8, np.int32), max_new_tokens=4))
    assert [s.rid for _, s in b2.try_admit()] == [9]


def test_batch_arrays_masks_inactive_slots():
    pcfg = _pcfg()
    b = ContinuousBatcher(pcfg, BatcherConfig(slots=3))
    b.submit(Request(rid=0, prompt=np.zeros(9, np.int32), max_new_tokens=4))
    [(slot, state)] = b.try_admit()
    b.record_first_token(slot, 42, now_s=1.0)
    tables, lengths, tokens, active = b.batch_arrays()
    assert active.tolist() == [i == slot for i in range(3)]
    assert lengths[slot] == 9 and tokens[slot] == 42
    other = [i for i in range(3) if i != slot]
    assert (tables[other] == NULL_BLOCK).all()
    assert (lengths[other] == 0).all()
    # decode advances length and re-arms the pending token
    b.record_decode_token(slot, 7, now_s=2.0)
    assert b.slots[slot].length == 10
    assert b.slots[slot].generated == [42, 7]
    # max_new reached after 4 tokens
    b.record_decode_token(slot, 8, now_s=3.0)
    b.record_decode_token(slot, 9, now_s=4.0)
    assert b.slots[slot].done and b.slots[slot].done_s == 4.0


# -------------------------------------------- on-demand admission/preemption


def test_ondemand_admits_on_prompt_blocks_only():
    pcfg = _pcfg(num_blocks=8)  # 7 allocatable
    b = ContinuousBatcher(
        pcfg, BatcherConfig(slots=4, admission="ondemand")
    )
    # reservation would need ceil((9+30)/8) = 5 blocks each: one admits.
    # on-demand needs ceil(9/8) = 2: three admit concurrently.
    for i in range(3):
        assert b.submit(Request(rid=i, prompt=np.zeros(9, np.int32),
                                max_new_tokens=30))
    admitted = b.try_admit()
    assert [s.rid for _, s in admitted] == [0, 1, 2]
    assert b.allocator.num_free == 1  # 3 x 2 prompt blocks
    # the same traffic under reservation: head-of-line blocks after one
    br = ContinuousBatcher(pcfg, BatcherConfig(slots=4, admission="reserve"))
    for i in range(3):
        br.submit(Request(rid=i, prompt=np.zeros(9, np.int32),
                          max_new_tokens=30))
    assert [s.rid for _, s in br.try_admit()] == [0]
    assert br.admit_blocked is not None  # rid 1 blocked on blocks


def test_ondemand_grow_allocates_at_block_boundary():
    pcfg = _pcfg(num_blocks=16)
    b = ContinuousBatcher(pcfg, BatcherConfig(slots=2, admission="ondemand"))
    b.submit(Request(rid=0, prompt=np.zeros(8, np.int32), max_new_tokens=12))
    [(slot, s)] = b.try_admit()
    assert len(s.block_ids) == 1  # exactly the prompt's block
    b.record_first_token(slot, 1, now_s=0.0)
    # length 8 = block boundary: the first decode write needs block 2
    assert b.grow_for_decode() == [slot]
    assert len(s.block_ids) == 2
    # mid-block positions need nothing
    b.record_decode_token(slot, 2, now_s=0.0)  # length 9
    assert b.grow_for_decode() == []
    for _ in range(7):
        b.record_decode_token(slot, 2, now_s=0.0)  # length 16: boundary
    assert b.grow_for_decode() == [slot]
    assert len(s.block_ids) == 3


def test_pick_victim_is_newest_and_never_the_last():
    pcfg = _pcfg(num_blocks=32)
    b = ContinuousBatcher(pcfg, BatcherConfig(slots=3, admission="ondemand"))
    for i in range(2):
        b.submit(Request(rid=i, prompt=np.zeros(4, np.int32),
                         max_new_tokens=4))
    (s0, st0), (s1, st1) = b.try_admit()
    assert st1.admit_seq > st0.admit_seq
    assert b.pick_victim() == s1  # newest
    b.record_first_token(s0, 1, 0.0)
    b.record_first_token(s1, 1, 0.0)
    kv = None
    b.preempt(s1, kv)
    assert b.pick_victim() is None  # one resident: nothing to evict
    assert [p.state.rid for p in b.preempted] == [1]
    assert st1.block_ids == [] and st1.preempts == 1


def test_preempted_resume_has_priority_over_fresh_admissions():
    pcfg = _pcfg(num_blocks=32)
    b = ContinuousBatcher(pcfg, BatcherConfig(slots=2, admission="ondemand"))
    b.submit(Request(rid=0, prompt=np.zeros(4, np.int32), max_new_tokens=8))
    [(slot, st)] = b.try_admit()
    b.record_first_token(slot, 1, 0.0)
    b.preempt(slot, None)
    b.submit(Request(rid=1, prompt=np.zeros(4, np.int32), max_new_tokens=8))
    # fresh admission must refuse while a preempted sequence waits
    assert b.try_admit() == []
    [(rslot, rstate, kv)] = b.try_resume()
    assert rstate.rid == 0 and kv is None
    assert len(rstate.block_ids) == rstate.length // pcfg.block_size + 1
    # with the resume done, the fresh request admits
    assert [s.rid for _, s in b.try_admit()] == [1]


def test_submit_rejects_requests_the_pool_can_never_hold():
    pcfg = _pcfg(num_blocks=4)  # 3 allocatable, max_len still 48
    for mode in ("reserve", "ondemand"):
        b = ContinuousBatcher(pcfg, BatcherConfig(slots=2, admission=mode))
        assert not b.submit(
            Request(rid=0, prompt=np.zeros(20, np.int32), max_new_tokens=20)
        )  # needs 5 blocks, pool holds 3: wedge (reserve) or livelock (ondemand)
        assert "pool holds" in b.rejected[-1][1]


@pytest.mark.parametrize("preempt", ["swap", "recompute"])
def test_engine_preemption_resume_matches_generate(model, preempt):
    """Injected exhaustion: a pool too small for the traffic preempts
    mid-decode; every sequence still finishes with exactly generate()'s
    tokens (swap-in restores the exact K/V bytes; recompute replays
    prefill), blocks all return, and the preempt/resume accounting shows
    the machinery actually fired."""
    cfg, params = model
    pcfg = _pcfg(num_blocks=10)  # 9 allocatable blocks
    eng = ServingEngine(
        params, cfg, pcfg,
        BatcherConfig(slots=4, admission="ondemand", preempt=preempt),
    )
    rng = np.random.default_rng(11)
    # prompts of 9 -> length hits boundaries mid-run; 4 resident sequences
    # want up to 4 x ceil((9+20)/8) = 16 blocks against 9: must preempt
    reqs = [Request(rid=i, prompt=_prompt(rng, 9), max_new_tokens=20)
            for i in range(5)]
    for r in reqs:
        assert eng.submit(r)
    eng.run_until_idle()
    snap = eng.metrics.snapshot()["counters"]
    assert snap.get("serve.preempts", 0) >= 1
    assert snap.get("serve.resumes", 0) == snap.get("serve.preempts")
    if preempt == "swap":
        assert snap.get("serve.swap_outs", 0) >= 1
    assert sorted(eng.completed) == list(range(5))
    for r in reqs:
        want = np.asarray(
            generate(params, jnp.asarray(r.prompt)[None], cfg,
                     max_new_tokens=20, max_len=pcfg.max_len)
        )[0]
        np.testing.assert_array_equal(eng.completed[r.rid].tokens, want)
    assert eng.batcher.allocator.num_free == 9


def test_engine_midblock_swap_resume_is_bit_identical(model):
    """Force a victim whose length is NOT a block multiple, resume it,
    and check its restored K/V bytes equal the swapped bytes exactly —
    the bit-identical-resume contract at the pool level."""
    cfg, params = model
    pcfg = _pcfg(num_blocks=12)
    eng = ServingEngine(
        params, cfg, pcfg,
        BatcherConfig(slots=2, admission="ondemand", preempt="swap"),
    )
    rng = np.random.default_rng(12)
    req = Request(rid=0, prompt=_prompt(rng, 9), max_new_tokens=8)
    eng.submit(req)
    eng.step()  # prefill + first decode: length 9, mid-block
    state = eng.batcher.slots[0]
    assert state.length % pcfg.block_size != 0
    saved = gather_seq(eng.pools, state.block_ids, length=state.length)
    saved = {k: [np.asarray(x) for x in v] for k, v in saved.items()}
    eng._preempt_slot(0)
    assert eng.batcher.preempted and state.block_ids == []
    [(slot, rstate, kv)] = eng.batcher.try_resume()
    eng._resume_slot(slot, rstate, kv)
    restored = gather_seq(eng.pools, rstate.block_ids, length=rstate.length)
    for l in range(cfg.n_layers):
        np.testing.assert_array_equal(
            np.asarray(restored["k"][l]), saved["k"][l]
        )
        np.testing.assert_array_equal(
            np.asarray(restored["v"][l]), saved["v"][l]
        )
    eng.run_until_idle()
    want = np.asarray(
        generate(params, jnp.asarray(req.prompt)[None], cfg,
                 max_new_tokens=8, max_len=pcfg.max_len)
    )[0]
    np.testing.assert_array_equal(eng.completed[0].tokens, want)


def test_engine_sampled_request_survives_preemption(model):
    """The per-request key schedule is a pure function of the seed:
    eviction and resume must not shift it."""
    cfg, params = model
    pcfg = _pcfg(num_blocks=9)  # 8 allocatable: 3 residents x 3 blocks > 8
    eng = ServingEngine(
        params, cfg, pcfg,
        BatcherConfig(slots=3, admission="ondemand", preempt="swap"),
    )
    rng = np.random.default_rng(13)
    reqs = [
        Request(rid=i, prompt=_prompt(rng, 9), max_new_tokens=16,
                temperature=0.7, top_k=8, seed=100 + i)
        for i in range(4)
    ]
    for r in reqs:
        assert eng.submit(r)
    eng.run_until_idle()
    assert eng.metrics.counter("serve.preempts").value >= 1
    for r in reqs:
        want = np.asarray(
            generate(params, jnp.asarray(r.prompt)[None], cfg,
                     max_new_tokens=16, max_len=pcfg.max_len,
                     temperature=0.7, top_k=8,
                     key=jax.random.PRNGKey(r.seed))
        )[0]
        np.testing.assert_array_equal(eng.completed[r.rid].tokens, want)


def test_engine_gather_path_still_bitwise(model):
    """The oracle must stay covered now that fused is the default: an
    explicit fused=False engine reproduces generate() bitwise."""
    cfg, params = model
    pcfg = _pcfg()
    eng = ServingEngine(params, cfg, pcfg, BatcherConfig(slots=2),
                        fused=False)
    rng = np.random.default_rng(14)
    reqs = [Request(rid=i, prompt=_prompt(rng, t), max_new_tokens=6)
            for i, t in enumerate([5, 11])]
    for r in reqs:
        eng.submit(r)
    eng.run_until_idle()
    for r in reqs:
        want = np.asarray(
            generate(params, jnp.asarray(r.prompt)[None], cfg,
                     max_new_tokens=6, max_len=pcfg.max_len)
        )[0]
        np.testing.assert_array_equal(eng.completed[r.rid].tokens, want)


def test_engine_report_carries_cache_pressure_metrics(model):
    cfg, params = model
    eng = ServingEngine(
        params, cfg, _pcfg(num_blocks=10),
        BatcherConfig(slots=4, admission="ondemand"),
    )
    rng = np.random.default_rng(15)
    for i in range(5):
        eng.submit(Request(rid=i, prompt=_prompt(rng, 9), max_new_tokens=20))
    eng.run_until_idle()
    rep = eng.report()
    assert "serve.free_blocks" in rep["gauges"]
    assert "serve.active_blocks" in rep["gauges"]
    assert rep["gauges"]["serve.active_blocks"] == 0  # all retired
    occ = rep["histograms"]["serve.cache_occupancy"]
    assert occ["count"] == eng.steps and 0.0 < occ["max"] <= 1.0
    assert rep["counters"]["serve.preempts"] >= 1


def test_pool_drain_reroutes_preempted_sequences(model, tmp_path):
    """A replica dying WITH a parked preempted sequence must re-route it
    like any other in-flight request — the exactly-once machinery covers
    the preempted queue too."""
    cfg, params = model
    pcfg = _pcfg(num_blocks=10)
    engines = [
        ServingEngine(params, cfg, pcfg,
                      BatcherConfig(slots=3, admission="ondemand"))
        for _ in range(2)
    ]
    pool = ReplicaPool(
        engines,
        PoolConfig(heartbeat_dir=str(tmp_path / "hb"), step_timeout_s=120.0,
                   lease_s=30.0, max_suspect_strikes=2),
    )
    rng = np.random.default_rng(16)
    reqs = [Request(rid=200 + i, prompt=_prompt(rng, 9), max_new_tokens=20)
            for i in range(6)]
    for r in reqs:
        pool.submit(r)
    # run until replica 1 has actually preempted something, then kill it
    for _ in range(40):
        pool.step()
        if engines[1].batcher.preempted:
            break
    assert engines[1].batcher.preempted, "scenario did not reach preemption"
    parked = [p.state.rid for p in engines[1].batcher.preempted]
    pool.kill(1, mode="raise")
    rep = pool.run_until_idle()
    assert rep["completed"] == 6 and rep["degraded"]
    for r in reqs:
        want = np.asarray(
            generate(params, jnp.asarray(r.prompt)[None], cfg,
                     max_new_tokens=20, max_len=pcfg.max_len)
        )[0]
        np.testing.assert_array_equal(pool.completed[r.rid].tokens, want)
    assert all(rid in pool.completed for rid in parked)
    pool.shutdown()


# ----------------------------------------------------------- elastic pool


def _mk_pool(model, tmp_path, n=2, **cfg_kw):
    cfg, params = model
    pcfg = _pcfg(num_blocks=24)
    engines = [
        ServingEngine(params, cfg, pcfg, BatcherConfig(slots=2))
        for _ in range(n)
    ]
    # the default watchdog deadline is deliberately generous: pool tests
    # step UNWARMED engines, and a prefill/decode compile landing inside
    # a tight deadline on a loaded host strikes out a healthy replica (a
    # flake observed at 5 s).  Tests of the hang path pass their own
    # step_timeout_s and warm their engines first.
    kw = dict(heartbeat_dir=str(tmp_path / "hb"), step_timeout_s=120.0,
              lease_s=30.0, max_suspect_strikes=2)
    kw.update(cfg_kw)
    return ReplicaPool(engines, PoolConfig(**kw)), pcfg


def _reqs(n, seed=7):
    rng = np.random.default_rng(seed)
    return [Request(rid=100 + i, prompt=_prompt(rng, 5 + i), max_new_tokens=5)
            for i in range(n)]


def test_pool_routes_balanced_and_completes(model, tmp_path):
    pool, pcfg = _mk_pool(model, tmp_path)
    cfg, params = model
    reqs = _reqs(6)
    for r in reqs:
        pool.submit(r)
    pool.step()
    loads = [len(r.assigned) for r in pool.replicas]
    assert sorted(loads) == [3, 3]
    rep = pool.run_until_idle()
    assert rep["completed"] == 6 and not rep["degraded"]
    for r in reqs:
        want = np.asarray(
            generate(params, jnp.asarray(r.prompt)[None], cfg,
                     max_new_tokens=5, max_len=pcfg.max_len)
        )[0]
        np.testing.assert_array_equal(pool.completed[r.rid].tokens, want)
    pool.shutdown()


def test_pool_rejected_request_is_recorded_not_lost(model, tmp_path):
    """A request a replica refuses (oversized for its pool) must surface
    in the POOL report — a silently vanished request is the one outcome
    a serving layer may never have."""
    pool, pcfg = _mk_pool(model, tmp_path)
    good = _reqs(2)
    for r in good:
        pool.submit(r)
    pool.submit(Request(rid=999, prompt=np.zeros(40, np.int32),
                        max_new_tokens=20))  # > max_len 48
    rep = pool.run_until_idle()
    assert rep["completed"] == 2
    assert [rid for rid, _ in rep["rejected"]] == [999]
    pool.shutdown()


def test_pool_reroute_preserves_original_arrival_stamp(model, tmp_path,
                                                       monkeypatch):
    """TTFT of a re-routed request must include the time it sat on the
    dead replica: arrival is stamped once, at pool intake."""
    from flextree_tpu.serving import engine as eng_mod

    t = {"now": 100.0}
    monkeypatch.setattr(eng_mod, "_now", lambda: t["now"])
    pool, _ = _mk_pool(model, tmp_path)
    reqs = _reqs(4)
    for r in reqs:
        pool.submit(r)
    t["now"] = 101.0
    pool.step()
    t["now"] = 105.0  # the doomed replica holds them for 4 "seconds"
    pool.kill(1, mode="raise")
    rep = pool.run_until_idle()
    assert rep["completed"] == 4 and rep["reroutes"] > 0
    # every completion's TTFT counts from the ORIGINAL intake at t=100
    for done in pool.completed.values():
        assert done.arrival_s == 100.0
        assert done.ttft_s >= 0
    rerouted_ttfts = [d.ttft_s for d in pool.completed.values()
                      if d.first_token_s >= 105.0]
    assert rerouted_ttfts and all(x >= 5.0 for x in rerouted_ttfts)
    pool.shutdown()


def test_pool_crash_kill_drains_and_reroutes(model, tmp_path):
    pool, _ = _mk_pool(model, tmp_path)
    reqs = _reqs(6)
    for r in reqs:
        pool.submit(r)
    pool.step()
    pool.kill(1, mode="raise")
    rep = pool.run_until_idle()
    assert rep["completed"] == 6
    assert rep["degraded"] and rep["alive"] == 1 and rep["reroutes"] > 0
    pool.shutdown()


def test_pool_silent_death_confirmed_by_lease_wall_clock(model, tmp_path, monkeypatch):
    """The membership verdict end-to-end on the injectable clock: a
    replica whose heartbeat dies silently (engine still stepping) is
    drained once its lease expires — no watchdog strike involved."""
    from flextree_tpu.runtime import supervisor as sup_mod

    t = {"now": 1000.0}
    monkeypatch.setattr(sup_mod, "_wall", lambda: t["now"])
    pool, _ = _mk_pool(model, tmp_path, lease_s=3.0, straggler_s=1.0)
    reqs = _reqs(4)
    for r in reqs:
        pool.submit(r)
    pool.step()
    pool.kill(0, mode="silent")
    # inside the lease: still counted alive
    pool.step()
    assert len(pool.alive_replicas) == 2
    # jump the clock past the lease; survivors re-beat at the new time
    t["now"] += 10.0
    pool.replicas[1].supervisor.beat_now()
    pool.step()
    assert [r.rank for r in pool.alive_replicas] == [1]
    rep = pool.run_until_idle()
    assert rep["completed"] == 4 and rep["degraded"] and rep["reroutes"] > 0
    pool.shutdown()


def test_pool_hang_kill_watchdog_converts_to_drain(model, tmp_path):
    pool, _ = _mk_pool(model, tmp_path, step_timeout_s=0.5,
                       max_suspect_strikes=3)
    cfg, params = model
    for r in pool.replicas:  # compiles must not eat the deadline
        r.engine.warmup([5, 6, 7, 8])
    reqs = _reqs(4)
    for r in reqs:
        pool.submit(r)
    pool.step()
    pool.kill(0, mode="hang")
    rep = pool.run_until_idle()
    assert rep["completed"] == 4 and rep["degraded"] and rep["reroutes"] > 0
    pool.shutdown()


def test_pool_results_are_exactly_once(model, tmp_path):
    """A drained request recomputes on a survivor; the pool records one
    result per rid and greedy recompute is bit-identical."""
    pool, pcfg = _mk_pool(model, tmp_path)
    cfg, params = model
    reqs = _reqs(6)
    for r in reqs:
        pool.submit(r)
    for _ in range(3):
        pool.step()
    pool.kill(1, mode="raise")
    rep = pool.run_until_idle()
    assert rep["completed"] == 6 == len(set(pool.completed))
    for r in reqs:
        want = np.asarray(
            generate(params, jnp.asarray(r.prompt)[None], cfg,
                     max_new_tokens=5, max_len=pcfg.max_len)
        )[0]
        np.testing.assert_array_equal(pool.completed[r.rid].tokens, want)
    pool.shutdown()


def test_engine_timestamps_on_injected_clock(model, monkeypatch):
    from flextree_tpu.serving import engine as eng_mod

    t = {"now": 0.0}

    def fake_now():
        t["now"] += 0.5
        return t["now"]

    monkeypatch.setattr(eng_mod, "_now", fake_now)
    cfg, params = model
    eng = ServingEngine(params, cfg, _pcfg(), BatcherConfig(slots=1))
    rng = np.random.default_rng(8)
    eng.submit(Request(rid=0, prompt=_prompt(rng, 5), max_new_tokens=3))
    eng.run_until_idle()
    done = eng.completed[0]
    assert done.arrival_s < done.first_token_s < done.done_s
    assert done.ttft_s > 0 and done.per_token_s > 0
    assert done.n_tokens == 3


# ------------------------------------ serving-side feedback (ISSUE 15)


def test_decode_cost_estimate_is_positive_and_split(model):
    from flextree_tpu.serving.costs import (
        predict_decode_round_us,
        predict_prefill_us,
    )

    cfg, _params = model
    pcfg = _pcfg()
    pred = predict_decode_round_us(cfg, pcfg, n_active=3, max_len=24)
    assert pred["predicted_us"] > 0
    assert pred["predicted_us"] == pytest.approx(
        pred["compute_us"] + pred["bytes_us"]
    )
    # empty round costs nothing; longer frontiers cost more
    assert predict_decode_round_us(cfg, pcfg, 0, 24)["predicted_us"] == 0.0
    longer = predict_decode_round_us(cfg, pcfg, 3, 48)
    assert longer["predicted_us"] > pred["predicted_us"]
    assert predict_prefill_us(cfg, 16) > predict_prefill_us(cfg, 4)


def test_engine_emits_serve_round_measured_spans(model, tmp_path):
    from flextree_tpu.obs import flight_recorder
    from flextree_tpu.obs.timeline import read_dir

    cfg, params = model
    eng = ServingEngine(params, cfg, _pcfg(), BatcherConfig(slots=2))
    rng = np.random.default_rng(5)
    with flight_recorder(tmp_path, rank=0):
        assert eng.submit(
            Request(rid=1, prompt=_prompt(rng, 6), max_new_tokens=4)
        )
        eng.run_until_idle()
    events, _ = read_dir(str(tmp_path))
    rounds = [e for e in events if e["kind"] == "serve_round_measured"]
    assert rounds, "decode rounds left no measured spans"
    for ev in rounds:
        assert ev["measured_us"] > 0
        assert ev["predicted_us"] > 0
        assert ev["predicted_us"] == pytest.approx(
            ev["compute_us"] + ev["bytes_us"], rel=1e-3
        )
        assert ev["n_active"] >= 1
    prefills = [e for e in events if e["kind"] == "serve_prefill"]
    assert prefills and all(
        e["predicted_us"] > 0 and e["measured_us"] > 0 for e in prefills
    )
    # the residual instrument is a view over the same rounds
    snap = eng.report()
    assert snap["histograms"]["serve.round_residual"]["count"] == len(rounds)
