"""Tests for the schedule validator (the race-detection analog, SURVEY §5)
and the profiling/tracing utilities (the SHOW_TIME / FT_DEBUG analogs)."""

import glob
import os

import pytest

from flextree_tpu.schedule import (
    ScheduleError,
    Topology,
    validate,
    validate_ring,
    validate_topology,
)
from flextree_tpu.utils import PhaseTimer, debug_dump_schedule, debug_enabled, trace


ALL_SHAPES = [
    (8, (8,)),
    (8, (2, 2, 2)),
    (8, (4, 2)),
    (8, (2, 4)),
    (12, (3, 4)),
    (12, (2, 3, 2)),
    (6, (2, 3)),
    (30, (2, 3, 5)),
    (16, (2, 2, 2, 2)),
    (1, (1,)),
]


class TestValidateTopology:
    @pytest.mark.parametrize("n,widths", ALL_SHAPES)
    def test_valid_shapes_pass(self, n, widths):
        stats = validate(Topology(n, widths))
        assert stats.num_nodes == n
        assert stats.widths == widths

    def test_message_count_matches_topo(self):
        # tree p2p rounds: each rank exchanges with (w-1) peers per stage,
        # twice (both phases) — the 2*sum(wi-1) per-rank step count scaled
        # by N ranks (SURVEY §3.2).
        topo = Topology(8, (4, 2))
        stats = validate_topology(topo)
        assert stats.p2p_messages == 8 * 2 * sum(w - 1 for w in (4, 2))

    @pytest.mark.parametrize("n", [2, 3, 4, 7, 8, 12])
    def test_ring_passes(self, n):
        stats = validate_ring(n)
        assert stats.num_nodes == n

    def test_ring_sentinel_dispatch(self):
        assert validate(Topology.ring(5)).widths == (1,)

    def test_corrupted_plan_caught(self, monkeypatch):
        """Sabotage send_plan and check the partition invariant trips."""
        import importlib

        V = importlib.import_module("flextree_tpu.schedule.validate")
        from flextree_tpu.schedule.plan import Operation, send_plan as real_send

        def bad_send(topo, rank):
            plan = real_send(topo, rank)
            if rank == 0:
                # drop a block from the first op of stage 0
                op = plan[0][0]
                plan[0][0] = Operation(op.peer, op.blocks[1:])
            return plan

        monkeypatch.setattr(V, "send_plan", bad_send)
        with pytest.raises(ScheduleError, match="send set != owned"):
            V.validate_topology(Topology(8, (4, 2)))

    def test_double_count_caught(self, monkeypatch):
        import importlib

        V = importlib.import_module("flextree_tpu.schedule.validate")
        from flextree_tpu.schedule.plan import Operation, send_plan as real_send

        def bad_send(topo, rank):
            plan = real_send(topo, rank)
            if rank == 1:
                a, b = plan[0][0], plan[0][1]
                # peer b also claims one of peer a's blocks
                plan[0][1] = Operation(b.peer, b.blocks + (a.blocks[0],))
            return plan

        monkeypatch.setattr(V, "send_plan", bad_send)
        with pytest.raises(ScheduleError, match="double count"):
            V.validate_topology(Topology(8, (4, 2)))


    def test_recv_overclaim_caught(self, monkeypatch):
        """A recv plan claiming blocks the rank never held must trip the
        plan-derived ownership tracking."""
        import importlib

        V = importlib.import_module("flextree_tpu.schedule.validate")
        from flextree_tpu.schedule.plan import Operation, recv_plan as real_recv

        def bad_recv(topo, rank):
            plan = real_recv(topo, rank)
            if rank == 2:
                # stage 1 suddenly claims a block outside rank 2's chain
                op = plan[1][0]
                foreign = (op.blocks[0] + 1) % topo.num_nodes
                plan[1] = [Operation(o.peer, o.blocks + (foreign,)) for o in plan[1]]
            return plan

        monkeypatch.setattr(V, "recv_plan", bad_recv)
        with pytest.raises(ScheduleError):
            V.validate_topology(Topology(8, (4, 2)))

    def test_large_ring_fast(self):
        """validate_ring must stay polynomial-friendly (plans built once)."""
        import time

        t0 = time.perf_counter()
        validate_ring(256)
        assert time.perf_counter() - t0 < 10.0


class TestPhaseTimer:
    def test_checkpoints(self):
        pt = PhaseTimer()
        pt.checkpoint("a")
        pt.checkpoint("b")
        names = [n for n, _ in pt.phases]
        assert names == ["a", "b"]
        assert all(dt >= 0 for _, dt in pt.phases)
        assert "total" in pt.summary()

    def test_reset(self):
        pt = PhaseTimer()
        pt.checkpoint("a")
        pt.reset()
        assert pt.phases == []


class TestDebugDump:
    def test_off_by_default(self, monkeypatch):
        monkeypatch.delenv("FT_DEBUG", raising=False)
        assert not debug_enabled()
        assert debug_dump_schedule(Topology(4, (4,))) is None

    @pytest.mark.parametrize("val", ["0", "false", "no", "off", "  "])
    def test_falsy_values(self, monkeypatch, val):
        monkeypatch.setenv("FT_DEBUG", val)
        assert not debug_enabled()

    def test_env_enables(self, monkeypatch):
        monkeypatch.setenv("FT_DEBUG", "1")
        assert debug_enabled()
        out = debug_dump_schedule(Topology(4, (2, 2)), rank=0)
        assert "node 0" in out and "stage0" in out and "stage1" in out

    def test_force_all_ranks(self, monkeypatch):
        monkeypatch.delenv("FT_DEBUG", raising=False)
        out = debug_dump_schedule(Topology(4, (4,)), force=True)
        assert out.count("plan of node") == 4


class TestProfilerTrace:
    def test_trace_writes_xplane(self, tmp_path):
        import jax
        import jax.numpy as jnp

        with trace(str(tmp_path)):
            jax.block_until_ready(jax.jit(lambda x: x * 2)(jnp.ones(128)))
        dumped = glob.glob(str(tmp_path / "**" / "*.xplane.pb"), recursive=True)
        assert dumped, f"no xplane trace written under {tmp_path}"


class TestNamedScopesCompile:
    def test_allreduce_still_correct_with_scopes(self):
        """Named scopes must not perturb results (smoke over shard_map)."""
        import numpy as np
        import jax.numpy as jnp

        from flextree_tpu.parallel import allreduce_over_mesh, flat_mesh

        mesh = flat_mesh(8)
        x = np.arange(8 * 40, dtype=np.float32).reshape(8, 40)
        out = np.asarray(allreduce_over_mesh(jnp.asarray(x), mesh, topo="4,2"))
        np.testing.assert_allclose(out, np.broadcast_to(x.sum(0), (8, 40)), rtol=1e-6)
