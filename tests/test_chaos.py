"""The executed failure model (docs/FAILURE_MODEL.md).

Three layers of chaos, each pinned against the acceptance contract "no
injected fault yields a silently wrong allreduce result":

- **Simulator matrix**: a ``FaultPlan`` drives every schedule family
  (tree, ring, lonely) through drop / duplicate / reorder / corrupt /
  delay / kill.  Detected faults must raise :class:`FaultDetected` naming
  the faulty (stage, src, dst); recovered faults (duplicate, reorder, a
  lonely rank dying after its contribution is folded) must leave the
  result bitwise-identical to the fault-free run and leave an audit trail
  in ``plan.events``.
- **Checkpoint corruption**: a truncated or bit-flipped newest checkpoint
  must fail verification and fall back one checkpoint, and a ``fit``
  resume through that fallback must be bitwise-exact.
- **Training-loop anomalies**: injected NaN losses are skipped (with
  ``RunReport`` accounting), cured by rewind-to-checkpoint, or — when the
  divergence persists past the rewind budget — rejected with
  :class:`TrainingDiverged`.

The kill/restart/degrade bring-up of a real two-process world lives in
``tools/chaos_bringup.py`` and runs here under the ``slow`` marker.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from flextree_tpu.backends import (
    Fault,
    FaultDetected,
    FaultPlan,
    StageTimeout,
    simulate_allreduce,
)
from flextree_tpu.backends.simulator import WHOLE_PAYLOAD, ScheduleViolation
from flextree_tpu.parallel.loop import FitConfig, TrainingDiverged, fit
from flextree_tpu.utils.checkpoint import (
    CheckpointCorrupt,
    latest_checkpoint,
    list_checkpoints,
    restore_checkpoint,
    restore_train_state,
    save_train_state,
    verify_checkpoint,
)

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RNG = np.random.default_rng(7)

# one representative of each schedule family the entry point can route to
TOPOS = [
    pytest.param(8, "4,2", id="tree-4,2"),
    pytest.param(8, "2,2,2", id="tree-2,2,2"),
    pytest.param(8, "1", id="ring"),
    pytest.param(7, "3,2+1", id="lonely-3,2+1"),
]

# fault kinds the transport cannot mask and must therefore *detect*
DETECTED_KINDS = ("drop", "corrupt", "delay")
# fault kinds the tag-matched mailbox absorbs and must *recover*
RECOVERED_KINDS = ("duplicate", "reorder")


def _dense_sum(data):
    return np.tile(data.sum(axis=0), (data.shape[0], 1))


# ----------------------------------------------------- simulator matrix


@pytest.mark.parametrize("kind", DETECTED_KINDS)
@pytest.mark.parametrize("n,topo", TOPOS)
def test_fault_detected_with_named_coordinates(n, topo, kind):
    """A sniped stage-0 message from rank 0 to rank 1 (every family has
    one) must surface as FaultDetected carrying the exact coordinates —
    structured fields AND the human-readable diagnostic."""
    data = RNG.standard_normal((n, 64))
    plan = FaultPlan(faults=(Fault(kind, stage=0, src=0, dst=1),))
    with pytest.raises(FaultDetected) as ei:
        simulate_allreduce(data, topo, faults=plan)
    e = ei.value
    assert e.kind == kind
    assert (e.stage, e.src, e.dst) == (0, 0, 1)
    assert "stage 0" in str(e) and "src 0 -> dst 1" in str(e)
    actions = {ev.action for ev in plan.events if ev.kind == kind}
    assert {"injected", "detected"} <= actions, plan.events


@pytest.mark.parametrize("kind", DETECTED_KINDS)
@pytest.mark.parametrize("n,topo", TOPOS)
def test_blanket_fault_never_silently_wrong(n, topo, kind):
    """Faulting EVERY message (wildcard) must still detect, never return."""
    data = RNG.standard_normal((n, 32))
    with pytest.raises(FaultDetected):
        simulate_allreduce(data, topo, faults=(Fault(kind),))


@pytest.mark.parametrize("kind", RECOVERED_KINDS)
@pytest.mark.parametrize("n,topo", TOPOS)
def test_recovered_fault_keeps_result_exact(n, topo, kind):
    """Duplicates are deduplicated by tag and reorders are absorbed by tag
    matching: the result must equal the fault-free run bit for bit, and
    the plan must show the faults were exercised, not unmatched."""
    data = RNG.standard_normal((n, 64))
    clean = simulate_allreduce(data, topo)
    plan = FaultPlan(faults=(Fault(kind),))  # every message, all stages
    out = simulate_allreduce(data, topo, faults=plan)
    np.testing.assert_array_equal(out, clean)
    assert any(
        ev.kind == kind and ev.action == "injected" for ev in plan.events
    ), "wildcard fault was never exercised"
    if kind == "duplicate":
        assert any(
            ev.kind == kind and ev.action == "recovered" for ev in plan.events
        ), "no dedup recovery recorded"


@pytest.mark.parametrize("n,topo", TOPOS)
def test_killed_rank_detected_by_surviving_peer(n, topo):
    """Kill rank 1 before its first message: the first survivor that
    needs its data must name the dead source."""
    data = RNG.standard_normal((n, 64))
    plan = FaultPlan(kill={1: 0})
    with pytest.raises(FaultDetected) as ei:
        simulate_allreduce(data, topo, faults=plan)
    e = ei.value
    assert e.kind == "kill"
    assert e.src == 1
    assert "rank 1 died at stage 0" in str(e)


@pytest.mark.parametrize("n,topo", TOPOS)
def test_hung_sender_times_out_typed_with_recv_deadline(n, topo):
    """The in-run straggler/hang class (ISSUE 4): a sender that stalls
    mid-stage (SIGSTOP signature — never posts, never dies) must surface
    as a typed StageTimeout carrying FT_STEP_TIMEOUT and the exact
    coordinates, when the mailbox runs deadline-wrapped."""
    data = RNG.standard_normal((n, 64))
    plan = FaultPlan(
        faults=(Fault("hang", stage=0, src=0, dst=1),), recv_timeout=1.5
    )
    with pytest.raises(StageTimeout) as ei:
        simulate_allreduce(data, topo, faults=plan)
    e = ei.value
    assert e.kind == "hang"
    assert e.code == "FT_STEP_TIMEOUT"
    assert (e.stage, e.src, e.dst) == (0, 0, 1)
    assert e.timeout_s == 1.5
    assert "FT_STEP_TIMEOUT" in str(e) and "recv deadline" in str(e)
    actions = {ev.action for ev in plan.events if ev.kind == "hang"}
    assert {"injected", "detected"} <= actions, plan.events


def test_hang_without_recv_deadline_is_refused_not_silent():
    """Without a recv deadline a hang would block forever on real
    hardware; the simulator refuses to model that silently and names the
    missing watchdog — the detect-or-recover contract has no third
    'hang forever quietly' outcome."""
    data = RNG.standard_normal((8, 64))
    plan = FaultPlan(faults=(Fault("hang", stage=0, src=0, dst=1),))
    with pytest.raises(ScheduleViolation, match="block FOREVER.*recv deadline"):
        simulate_allreduce(data, "4,2", faults=plan)


def test_blanket_hang_detected_on_first_needed_message():
    """Wildcard-hang every message: with the deadline configured the run
    must end in StageTimeout — never a wrong result."""
    data = RNG.standard_normal((8, 32))
    plan = FaultPlan(faults=(Fault("hang"),), recv_timeout=0.5)
    with pytest.raises(StageTimeout):
        simulate_allreduce(data, "2,2,2", faults=plan)


def test_lonely_fold_hop_is_chaos_reachable():
    """The lonely buddy fold rides the mailbox too: drop the lonely
    rank's whole-payload hop and the buddy must detect it at phase 0."""
    data = RNG.standard_normal((7, 64))
    plan = FaultPlan(faults=(Fault("drop", src=6, dst=0),))
    with pytest.raises(FaultDetected) as ei:
        simulate_allreduce(data, "3,2+1", faults=plan)
    e = ei.value
    assert (e.kind, e.phase, e.src, e.dst) == ("drop", 0, 6, 0)
    assert e.block == WHOLE_PAYLOAD and "whole payload" in str(e)


def test_dead_lonely_rank_degrades_to_survivors():
    """A lonely rank dying AFTER its payload is folded must not sink the
    collective: survivors complete with the full sum (its contribution
    was already in) and the skip is recorded, not silent."""
    n, spec = 7, "3,2+1"
    data = RNG.standard_normal((n, 64))
    # (3,2) tree has 2 stages -> schedule times 0..3; the buddy-return hop
    # runs at time 4, so a kill at 4 hits only the result return
    plan = FaultPlan(kill={6: 4})
    out = simulate_allreduce(data, spec, faults=plan)
    np.testing.assert_allclose(out[:6], _dense_sum(data)[:6], rtol=1e-12)
    assert any(
        ev.kind == "kill" and ev.action == "recovered" for ev in plan.events
    )


def test_blanket_corrupt_with_empty_tail_blocks_still_detects():
    """count < n leaves zero-length tail blocks in flight; a wildcard
    corrupt fault must skip them (no bytes to flip) and still be detected
    on the first non-empty payload — not crash on the empty one."""
    data = RNG.standard_normal((5, 3))
    for topo in ("1", "5"):
        with pytest.raises(FaultDetected) as ei:
            simulate_allreduce(data, topo, faults=(Fault("corrupt"),))
        assert ei.value.kind == "corrupt"


def test_fault_plan_rejects_unknown_kind():
    with pytest.raises(ValueError, match="unknown fault kind"):
        Fault("gamma-ray")


def test_faultless_plan_leaves_result_and_events_untouched():
    data = RNG.standard_normal((8, 64))
    plan = FaultPlan()
    out = simulate_allreduce(data, "4,2", faults=plan)
    np.testing.assert_array_equal(out, simulate_allreduce(data, "4,2"))
    assert plan.events == []


# ------------------------------------------------- checkpoint integrity


def _truncate(path, frac=0.6):
    with open(path, "rb") as f:
        raw = f.read()
    with open(path, "wb") as f:
        f.write(raw[: int(len(raw) * frac)])


def _state(step, scale=1.0):
    return {
        "step": np.int64(step),
        "w": np.arange(16, dtype=np.float64) * scale,
        "opt": {"m": np.ones((4, 4)) * scale},
    }


def test_truncated_checkpoint_fails_verification(tmp_path):
    save_train_state(tmp_path, _state(4))
    path = latest_checkpoint(tmp_path)
    assert verify_checkpoint(path)
    _truncate(path)
    assert not verify_checkpoint(path)
    with pytest.raises(CheckpointCorrupt):
        restore_checkpoint(path)


def test_bitflipped_leaf_fails_checksum(tmp_path):
    """Rewrite one leaf without updating the recorded CRC: the structure
    descriptor's per-leaf checksum must catch the tamper."""
    save_train_state(tmp_path, _state(4))
    path = latest_checkpoint(tmp_path)
    with np.load(path) as data:
        arrs = {k: np.array(data[k]) for k in data.files}
    fat = max(
        (k for k in arrs if k.startswith("leaf_")), key=lambda k: arrs[k].nbytes
    )
    arrs[fat].view(np.uint8).flat[0] ^= 0xFF
    np.savez(path, **arrs)
    with pytest.raises(CheckpointCorrupt, match="checksum mismatch"):
        restore_checkpoint(path)


def test_restore_falls_back_past_corrupt_newest(tmp_path):
    save_train_state(tmp_path, _state(4, scale=1.0))
    save_train_state(tmp_path, _state(8, scale=2.0))
    newest = latest_checkpoint(tmp_path)
    _truncate(newest)
    rejected = []
    got = restore_train_state(
        tmp_path, on_fallback=lambda p, e: rejected.append(p)
    )
    assert int(got["step"]) == 4
    np.testing.assert_array_equal(got["w"], _state(4)["w"])
    assert rejected == [newest]


def test_restore_raises_when_every_checkpoint_is_corrupt(tmp_path):
    save_train_state(tmp_path, _state(4))
    save_train_state(tmp_path, _state(8))
    for _, path in list_checkpoints(tmp_path):
        _truncate(path)
    with pytest.raises(CheckpointCorrupt, match="every checkpoint"):
        restore_train_state(tmp_path)


# ------------------------------------------------ crash-safe training loop


class _ToyData:
    """Deterministic step-addressed batches (mean of batch s is s+1).
    Deliberately lacks ``iter_from`` so ``fit`` uses direct addressing."""

    def batch_at(self, step):
        tok = np.full((2, 4), float(step + 1))
        return tok, tok


def _toy_step(poison: set | None = None):
    """A linear 'model': w -= 0.01 * mean(batch).  Steps whose index is in
    ``poison`` produce a NaN loss exactly once (the set is consumed), the
    way a transient numeric anomaly would."""
    poison = poison if poison is not None else set()

    def step_fn(state, tokens, targets):
        s = int(np.asarray(state["step"]))
        g = np.float64(tokens.mean())
        if s in poison:
            poison.discard(s)
            g = np.float64("nan")
        return (
            {"step": np.int64(s + 1), "w": np.asarray(state["w"]) - 0.01 * g},
            {"loss": g},
        )

    return step_fn


def _w0():
    return {"step": np.int64(0), "w": np.zeros(4, dtype=np.float64)}


def _expected_w(applied_steps):
    return -0.01 * sum(s + 1 for s in applied_steps) * np.ones(4)


def test_nan_step_is_skipped_and_counted(tmp_path):
    """Acceptance (a): an injected NaN loss at step k is skipped — the
    poisoned update is discarded, the run completes, and the RunReport
    (returned and persisted as run_report.json) carries the accounting."""
    ck = str(tmp_path / "ck")
    res = fit(
        _w0(), _toy_step(poison={3}), _ToyData(),
        FitConfig(num_steps=8, ckpt_dir=ck, ckpt_every=100, log_every=0),
    )
    assert res.steps_run == 8
    assert res.report.anomalies == 1
    assert res.report.skipped_steps == [3]
    np.testing.assert_allclose(
        res.state["w"], _expected_w(s for s in range(8) if s != 3)
    )
    with open(os.path.join(ck, "run_report.json")) as f:
        persisted = json.load(f)
    assert persisted["anomalies"] == 1 and persisted["skipped_steps"] == [3]


def test_nan_burst_cured_by_rewind(tmp_path):
    """max_bad_steps consecutive anomalies trigger a rewind to the last
    checkpoint; a transient burst is then replayed clean, so the final
    parameters match an undisturbed run exactly."""
    ck = str(tmp_path / "ck")
    res = fit(
        _w0(), _toy_step(poison={4, 5, 6}), _ToyData(),
        FitConfig(
            num_steps=12, ckpt_dir=ck, ckpt_every=4, log_every=0,
            max_bad_steps=3, max_rewinds=2,
        ),
    )
    assert res.report.rewinds == 1
    assert res.report.anomalies == 3
    # rewound to step 4, replayed 4..11 clean: every update applied
    np.testing.assert_allclose(res.state["w"], _expected_w(range(12)))
    clean = fit(
        _w0(), _toy_step(), _ToyData(),
        FitConfig(num_steps=12, log_every=0),
    )
    np.testing.assert_array_equal(res.state["w"], clean.state["w"])


def test_persistent_divergence_raises_after_rewind_budget(tmp_path):
    """A divergence that reappears after every rewind must end in
    TrainingDiverged, not an infinite rewind loop."""
    ck = str(tmp_path / "ck")
    # re-arm the poison on every pass: steps >= 4 are always NaN
    class _AlwaysPoisoned(set):
        def discard(self, item):
            pass

    with pytest.raises(TrainingDiverged, match="rewind"):
        fit(
            _w0(), _toy_step(poison=_AlwaysPoisoned(range(4, 100))), _ToyData(),
            FitConfig(
                num_steps=12, ckpt_dir=ck, ckpt_every=2, log_every=0,
                max_bad_steps=3, max_rewinds=1,
            ),
        )


def test_run_report_persisted_when_training_diverges(tmp_path):
    """The accounting matters most for the run that dies: run_report.json
    must exist (anomalies + rewinds recorded) after TrainingDiverged."""
    ck = str(tmp_path / "ck")

    class _AlwaysPoisoned(set):
        def discard(self, item):
            pass

    with pytest.raises(TrainingDiverged):
        fit(
            _w0(), _toy_step(poison=_AlwaysPoisoned(range(4, 100))), _ToyData(),
            FitConfig(
                num_steps=12, ckpt_dir=ck, ckpt_every=2, log_every=0,
                max_bad_steps=3, max_rewinds=1,
            ),
        )
    with open(os.path.join(ck, "run_report.json")) as f:
        persisted = json.load(f)
    assert persisted["rewinds"] == 1
    assert persisted["anomalies"] == 6  # 3 before the rewind, 3 after


def test_divergence_without_checkpoint_raises(tmp_path):
    with pytest.raises(TrainingDiverged, match="no checkpoint"):
        fit(
            _w0(), _toy_step(poison={0, 1, 2}), _ToyData(),
            FitConfig(num_steps=8, log_every=0, max_bad_steps=3),
        )


def test_nan_guard_off_restores_fail_fast(tmp_path):
    """nan_guard=False: the poisoned update flows through unguarded (the
    pre-chaos loop), pinning that the guard is opt-out, not silent."""
    res = fit(
        _w0(), _toy_step(poison={2}), _ToyData(),
        FitConfig(num_steps=4, log_every=0, nan_guard=False),
    )
    assert not np.isfinite(np.asarray(res.state["w"])).all()


def test_fit_resumes_exactly_through_corrupt_newest_checkpoint(tmp_path):
    """Acceptance (b): corrupt the newest checkpoint of an interrupted
    run; the resume must fall back one checkpoint and still reproduce the
    straight-through run bitwise."""
    ck = str(tmp_path / "ck")
    straight = fit(
        _w0(), _toy_step(), _ToyData(), FitConfig(num_steps=12, log_every=0)
    )
    half = fit(
        _w0(), _toy_step(), _ToyData(),
        FitConfig(num_steps=8, ckpt_dir=ck, ckpt_every=4, log_every=0),
    )
    assert half.steps_run == 8
    _truncate(latest_checkpoint(ck))  # ckpt_00000008 dies mid-write
    resumed = fit(
        _w0(), _toy_step(), _ToyData(),
        FitConfig(num_steps=12, ckpt_dir=ck, ckpt_every=4, log_every=0),
    )
    assert resumed.resumed_from == 4
    assert resumed.report.ckpt_fallbacks == 1
    assert resumed.steps_run == 8
    np.testing.assert_array_equal(resumed.state["w"], straight.state["w"])


# ---------------------------------------- NaN containment in attention


def test_varying_zeros_stays_finite_for_poisoned_input():
    """ADVICE r5: masked ring/zigzag hops derived their zeros as ``q * 0``,
    which is NaN wherever q is non-finite — a poisoned shard then leaks
    into hops the causal mask says contribute nothing.  The replacement
    must be exact zeros for ANY input, preserving dtype."""
    import jax.numpy as jnp

    from flextree_tpu.parallel.ring_attention import varying_zeros

    q = jnp.array([jnp.nan, jnp.inf, -jnp.inf, 1.0, 0.0])
    assert np.isnan(np.asarray(q * 0)).any()  # the bug being guarded against
    z = varying_zeros(q)
    np.testing.assert_array_equal(np.asarray(z), np.zeros(5))
    assert z.dtype == q.dtype
    z32 = varying_zeros(q, jnp.float32)
    assert z32.dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(z32), np.zeros(5, np.float32))


# -------------------------------------------- bring-up retry/backoff


def _clean_ft_env(monkeypatch):
    for var in ("FT_COORDINATOR", "FT_NUM_PROCESSES", "FT_PROCESS_ID",
                "FT_INIT_TIMEOUT", "FT_INIT_RETRIES"):
        monkeypatch.delenv(var, raising=False)


def test_init_retries_transient_failures_with_backoff(monkeypatch):
    from flextree_tpu.parallel import launch as launch_mod
    from flextree_tpu.parallel.launch import ClusterConfig, init_distributed

    _clean_ft_env(monkeypatch)
    naps, calls = [], []

    def flaky_init(**kw):
        calls.append(kw)
        if len(calls) < 3:
            raise RuntimeError("connection refused (transient)")

    monkeypatch.setattr(launch_mod, "_sleep", naps.append)
    monkeypatch.setattr(launch_mod.jax.distributed, "initialize", flaky_init)
    report = init_distributed(ClusterConfig("h0:1234", 2, 0), retries=5)
    assert report.attempts == 3
    assert len(report.errors) == 2
    assert all("transient" in e for e in report.errors)
    assert len(naps) == 2 and naps[1] >= naps[0]  # exponential, jittered


def test_init_exhausted_budget_carries_error_taxonomy(monkeypatch):
    from flextree_tpu.parallel import launch as launch_mod
    from flextree_tpu.parallel.launch import (
        BringupTimeout, ClusterConfig, init_distributed,
    )

    _clean_ft_env(monkeypatch)
    monkeypatch.setattr(launch_mod, "_sleep", lambda s: None)

    def doomed_init(**kw):
        raise RuntimeError("DEADLINE_EXCEEDED")

    monkeypatch.setattr(launch_mod.jax.distributed, "initialize", doomed_init)
    with pytest.raises(BringupTimeout) as ei:
        init_distributed(ClusterConfig("h0:1234", 2, 0), retries=2)
    assert ei.value.attempts == 3  # first try + 2 retries
    assert len(ei.value.errors) == 3
    assert all("DEADLINE_EXCEEDED" in e for e in ei.value.errors)


def test_malformed_config_fails_fast_without_retry(tmp_path, monkeypatch):
    from flextree_tpu.parallel.launch import BringupConfigError, init_distributed

    _clean_ft_env(monkeypatch)
    bad = tmp_path / "cluster.json"
    bad.write_text(json.dumps({"coordinator": "h0:1", "bogus_key": 1}))
    with pytest.raises(BringupConfigError, match="bogus_key"):
        init_distributed(bad)


def test_nonzero_rank_probes_coordinator_before_handshake(monkeypatch):
    """With a deadline configured, a non-coordinator waits for the
    coordinator port OUTSIDE initialize (a deadline inside the handshake
    hard-aborts the process on this JAX pin); the coordinator itself never
    probes its own port."""
    from flextree_tpu.parallel import launch as launch_mod
    from flextree_tpu.parallel.launch import ClusterConfig, init_distributed

    _clean_ft_env(monkeypatch)
    probes, calls = [], []
    monkeypatch.setattr(
        launch_mod, "_probe_coordinator", lambda c, b: probes.append((c, b))
    )
    monkeypatch.setattr(
        launch_mod.jax.distributed, "initialize", lambda **kw: calls.append(kw)
    )
    init_distributed(ClusterConfig("h0:1234", 2, 1), timeout=7)
    assert probes == [("h0:1234", 7)]
    assert calls[-1]["initialization_timeout"] == 7
    probes.clear()
    init_distributed(ClusterConfig("h0:1234", 2, 0), timeout=7)
    assert probes == []


def test_degrade_decided_from_launcher_liveness(monkeypatch):
    """A liveness source reporting a short world degrades upfront — the
    doomed full-world barrier is never attempted — and the env process
    count must not stomp the degraded world size."""
    from flextree_tpu.parallel import launch as launch_mod
    from flextree_tpu.parallel.launch import (
        ClusterConfig, init_distributed_or_degrade,
    )

    _clean_ft_env(monkeypatch)
    monkeypatch.setenv("FT_NUM_PROCESSES", "8")  # launcher-configured world
    calls = []
    monkeypatch.setattr(
        launch_mod.jax.distributed, "initialize", lambda **kw: calls.append(kw)
    )
    report, plan = init_distributed_or_degrade(
        ClusterConfig("h0:1234", 8, 0), nbytes=1 << 20, survivors=lambda: 7
    )
    assert report.degraded_to == 7
    assert calls == [
        {"coordinator_address": "h0:1234", "num_processes": 7, "process_id": 0}
    ]
    assert plan is not None and plan.topology.num_nodes == 7
    assert any("DEGRADED WORLD" in note for note in plan.advisory)


def test_replan_for_survivors_validates_and_annotates():
    from flextree_tpu.planner import replan_for_survivors

    plan = replan_for_survivors(7, 1 << 20, configured=8)
    assert plan.topology.num_nodes == 7
    assert any("DEGRADED WORLD: 7/8" in note for note in plan.advisory)
    with pytest.raises(ValueError, match="exceeds"):
        replan_for_survivors(9, 1 << 20, configured=8)
    with pytest.raises(ValueError, match=">= 1"):
        replan_for_survivors(0, 1 << 20)


# ------------------------------------------- executed two-process chaos


@pytest.mark.slow
def test_chaos_bringup_kill_restart_degrade():
    """Acceptance (c), executed for real: late coordinator (retry/backoff
    reconnect), killed-then-restarted process, and a never-joining process
    (degrade-to-survivors with a replanned topology) — three scenarios of
    ``tools/chaos_bringup.py`` against genuine local processes."""
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_bringup.py"),
         "--no-artifact", "--port", "19951"],
        capture_output=True,
        text=True,
        timeout=540,
        cwd=REPO,
    )
    assert p.returncode == 0, f"chaos bring-up failed:\n{p.stdout[-4000:]}"
    for scenario in ("retry", "restart", "degrade"):
        assert f"scenario {scenario}: OK" in p.stdout, p.stdout[-4000:]


def _load_tool(name):
    import importlib.util

    path = os.path.join(REPO, "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_chaos_bringup_exits_nonzero_on_unrecovered_scenario(monkeypatch):
    """The CI gate: a scenario that fails to recover — or whose driver
    crashes outright — must surface as a non-zero exit, never a green
    exit with a failed scenario buried in the JSON."""
    cb = _load_tool("chaos_bringup")
    failed = {"scenario": "retry", "ok": False, "returncodes": [1],
              "reports": [], "logs": [["[proc 1] FAIL: injected"]]}
    monkeypatch.setattr(cb, "run_retry", lambda port: failed)
    assert cb.main(["--scenario", "retry", "--no-artifact"]) == 1
    monkeypatch.setattr(cb, "run_retry", lambda port: {**failed, "ok": True})
    assert cb.main(["--scenario", "retry", "--no-artifact"]) == 0

    def crash(port):
        raise RuntimeError("driver exploded")

    monkeypatch.setattr(cb, "run_retry", crash)
    assert cb.main(["--scenario", "retry", "--no-artifact"]) == 1


def test_chaos_runtime_exits_nonzero_on_unrecovered_scenario(monkeypatch):
    """Same gate for the runtime driver (tools/chaos_runtime.py)."""
    cr = _load_tool("chaos_runtime")
    ok = {"scenario": "sigterm", "recovered": True, "checks": {}, "log": []}
    monkeypatch.setattr(cr, "run_sigterm", lambda wd: ok)
    assert cr.main(["--scenario", "sigterm", "--no-artifact"]) == 0
    monkeypatch.setattr(
        cr, "run_sigterm", lambda wd: {**ok, "recovered": False}
    )
    assert cr.main(["--scenario", "sigterm", "--no-artifact"]) == 1


@pytest.mark.slow
def test_chaos_runtime_sigkill_sigstop_sigterm():
    """The runtime chaos matrix, executed against real processes and real
    signals: mid-run SIGKILL -> live shrink-to-survivors resume; SIGSTOP
    -> straggler flagged within the lease budget (no shrink); SIGTERM ->
    preemption checkpoint within one step + exact resume.  The committed
    CHAOS_RUNTIME.json is this run's artifact form."""
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_runtime.py"),
         "--no-artifact"],
        capture_output=True,
        text=True,
        timeout=540,
        cwd=REPO,
    )
    assert p.returncode == 0, f"runtime chaos failed:\n{p.stdout[-4000:]}"
    for scenario in ("sigkill", "sigstop", "sigterm"):
        assert f"scenario {scenario}: RECOVERED" in p.stdout, p.stdout[-4000:]
