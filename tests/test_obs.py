"""Unified telemetry (flextree_tpu.obs): flight recorder, metrics
registry, cross-rank timeline merger — plus the ISSUE-10 satellite
contracts (result-file disambiguation, SpanLedger suffix parsing,
rank-aware logging)."""

from __future__ import annotations

import json
import logging
import math
import os
import threading

import numpy as np
import pytest

from flextree_tpu.obs import (
    FlightRecorder,
    MetricsRegistry,
    bucket_provenance,
    dump_current,
    flight_recorder,
    get_registry,
    merge_dir,
    merge_events,
    record_event,
    validate_trace,
    write_trace,
)
from flextree_tpu.obs.metrics import Histogram
from flextree_tpu.obs.recorder import current_recorder
from flextree_tpu.obs.timeline import read_dir, read_events


# ---------------------------------------------------------------- recorder


class TestFlightRecorder:
    def test_record_and_ring_bound(self, tmp_path):
        rec = FlightRecorder(tmp_path, rank=0, capacity=10, spill_every=3)
        for i in range(25):
            rec.record("tick", i=i)
        assert len(rec.events) == 10  # ring bounded
        assert rec.recorded == 25
        assert [e["i"] for e in rec.events] == list(range(15, 25))
        rec.close()
        # every event spilled to the JSONL, in seq order, none lost
        events = read_events(rec.event_path)
        assert [e["i"] for e in events] == list(range(25))
        assert [e["seq"] for e in events] == sorted(e["seq"] for e in events)

    def test_flush_kind_spills_immediately(self, tmp_path):
        rec = FlightRecorder(tmp_path, rank=0, spill_every=1000)
        rec.record("step_start", step=0)
        # buffered: spill_every not reached, no flush kind yet
        assert read_events(rec.event_path) == []
        rec.record("step_end", step=0)  # FLUSH_KINDS member
        events = read_events(rec.event_path)
        assert [e["kind"] for e in events] == ["step_start", "step_end"]
        rec.close()

    def test_event_ordering_under_rotation_and_threads(self, tmp_path):
        rec = FlightRecorder(tmp_path, rank=3, capacity=16, spill_every=5)

        def worker(tid):
            for i in range(200):
                rec.record("tick", tid=tid, i=i)

        threads = [
            threading.Thread(target=worker, args=(t,)) for t in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        rec.close()
        events = read_events(rec.event_path)
        assert len(events) == 800  # nothing lost to rotation
        seqs = [e["seq"] for e in events]
        assert seqs == sorted(seqs) and len(set(seqs)) == 800
        for tid in range(4):  # per-thread order preserved
            mine = [e["i"] for e in events if e["tid"] == tid]
            assert mine == list(range(200))

    def test_dump_sidecar(self, tmp_path):
        rec = FlightRecorder(tmp_path, rank=1, capacity=5)
        for i in range(8):
            rec.record("tick", i=i)
        path = rec.dump("test_failure", step=7)
        assert path and os.path.exists(path)
        with open(path) as f:
            dump = json.load(f)
        assert dump["reason"] == "test_failure"
        assert dump["rank"] == 1 and dump["step"] == 7
        # ring context: the last `capacity` events, incl. the marker
        assert dump["events"][-1]["kind"] == "dump"
        assert [e["i"] for e in dump["events"][:-1]] == [4, 5, 6, 7]
        rec.close()

    def test_memory_only_recorder(self):
        rec = FlightRecorder(None, rank=0)
        rec.record("tick")
        assert rec.dump("r") is None and rec.event_path is None

    def test_dump_nonblocking_skips_under_held_lock(self, tmp_path):
        # a signal handler runs ON the interrupted thread: if that frame
        # holds the recorder lock, the handler must skip, never block
        rec = FlightRecorder(tmp_path, rank=0)
        rec.record("tick")
        with rec._lock:
            assert rec.dump_nonblocking("signal", signum=15) is None
        # lock free again: the dump goes through
        path = rec.dump_nonblocking("signal", signum=15)
        assert path and os.path.exists(path)
        rec.close()

    def test_spill_failure_drops_batch_never_duplicates(self, tmp_path):
        rec = FlightRecorder(tmp_path, rank=0, spill_every=2)
        rec.record("a")

        class _FailOnce:
            def __init__(self, fh):
                self.fh, self.fail = fh, True

            def write(self, s):
                return self.fh.write(s)  # buffered write "succeeds"

            def flush(self):
                if self.fail:
                    self.fail = False
                    raise OSError("ENOSPC")
                return self.fh.flush()

            def close(self):
                return self.fh.close()

        rec._fh = _FailOnce(rec._fh)
        rec.record("b")  # spill_every hit -> flush raises -> batch dropped
        assert rec.spill_errors == 1
        rec.record("c")
        rec.record("d")  # next spill succeeds
        rec.close()
        events = read_events(rec.event_path)
        # no duplicated seq (the partially-landed batch is never
        # re-written); the dropped events are still in the ring
        seqs = [e["seq"] for e in events]
        assert len(seqs) == len(set(seqs))
        assert [e["kind"] for e in rec.events] == ["a", "b", "c", "d"]

    def test_ambient_install_and_noop(self, tmp_path):
        assert current_recorder() is None
        record_event("ignored")  # no recorder: must be a silent no-op
        assert dump_current("ignored") is None
        with flight_recorder(tmp_path, rank=2) as rec:
            assert current_recorder() is rec
            record_event("step_end", step=1)
            get_registry().counter("x").inc(3)
        assert current_recorder() is None and get_registry() is None
        events = read_events(rec.event_path)
        assert [e["kind"] for e in events] == ["step_end"]
        assert events[0]["rank"] == 2
        with open(tmp_path / "metrics_00002.json") as f:
            assert json.load(f)["counters"]["x"] == 3

    def test_nested_install_restores_outer(self, tmp_path):
        with flight_recorder(tmp_path / "a", rank=0) as outer:
            with flight_recorder(tmp_path / "b", rank=1) as inner:
                assert current_recorder() is inner
            assert current_recorder() is outer


# ----------------------------------------------------------------- metrics


class TestMetrics:
    def test_counter_gauge(self):
        reg = MetricsRegistry()
        reg.counter("a").inc()
        reg.counter("a").inc(2)
        reg.gauge("g").set(7.5)
        with pytest.raises(ValueError):
            reg.counter("a").inc(-1)
        with pytest.raises(TypeError):
            reg.gauge("a")  # kind mismatch is loud, never shadowed
        snap = reg.snapshot()
        assert snap["counters"]["a"] == 3
        assert snap["gauges"]["g"] == 7.5

    @pytest.mark.parametrize("dist", ["uniform", "lognormal", "exponential"])
    def test_percentiles_vs_numpy_oracle(self, dist):
        rng = np.random.default_rng(hash(dist) % (2**32))
        vals = {
            "uniform": rng.uniform(0, 90, 5000),
            "lognormal": rng.lognormal(1.0, 1.0, 5000),
            "exponential": rng.exponential(20.0, 5000),
        }[dist]
        h = Histogram()  # DEFAULT_MS_BUCKETS
        for v in vals:
            h.observe(v)
        edges = (0.0,) + h.edges
        for q in (50, 90, 95, 99):
            got = h.percentile(q)
            want = float(np.percentile(vals, q))
            # "within bucket resolution": the bucket containing the true
            # percentile bounds the error
            i = int(np.searchsorted(h.edges, want))
            lo = edges[i]
            hi = h.edges[i] if i < len(h.edges) else float(np.max(vals))
            width = hi - lo
            assert abs(got - want) <= width + 1e-9, (q, got, want, width)

    def test_percentile_edges(self):
        h = Histogram(buckets=(1.0, 10.0))
        assert math.isnan(h.percentile(50))
        h.observe(5.0)
        assert h.percentile(0) <= h.percentile(100) <= 10.0
        with pytest.raises(ValueError):
            h.percentile(101)

    def test_overflow_clamps_to_max(self):
        h = Histogram(buckets=(1.0,))
        for v in (50.0, 60.0, 70.0):
            h.observe(v)
        assert h.percentile(99) <= 70.0
        assert h.to_payload()["buckets"] == {"+inf": 3}

    def test_histogram_payload_schema(self):
        h = Histogram(buckets=(1.0, 2.0))
        h.observe(0.5)
        h.observe(1.5)
        p = h.to_payload()
        assert p["count"] == 2 and p["sum"] == 2.0
        assert p["min"] == 0.5 and p["max"] == 1.5
        assert set(p["buckets"]) == {"1.0", "2.0"}
        json.dumps(p)  # snapshot must be JSON-stable


# ---------------------------------------------------------------- timeline


def _mk(rank, seq, ts, kind, **fields):
    return {"ts": ts, "rank": rank, "src": "train", "seq": seq,
            "kind": kind, **fields}


class TestTimeline:
    def test_step_pairing_and_duration(self):
        doc = merge_events(
            [
                _mk(0, 0, 10.0, "step_start", step=0),
                _mk(0, 1, 10.25, "step_end", step=0),
                _mk(1, 0, 10.1, "step_start", step=0),
                _mk(1, 1, 10.2, "step_end", step=0),
            ]
        )
        assert validate_trace(doc) == []
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert {e["pid"] for e in xs} == {0, 1}
        r0 = next(e for e in xs if e["pid"] == 0)
        assert r0["dur"] == pytest.approx(0.25e6, rel=1e-3)

    def test_unfinished_step_surfaces(self):
        doc = merge_events(
            [
                _mk(0, 0, 1.0, "step_start", step=9),  # never finished
                _mk(0, 1, 1.5, "dump", reason="watchdog_timeout"),
            ]
        )
        assert validate_trace(doc) == []
        names = [e["name"] for e in doc["traceEvents"]]
        assert "step 9 (unfinished)" in names
        assert "dump" in names

    def test_bucket_provenance_span(self):
        prov = {"name": "ft_bucket0_dp_10leaves_4096B",
                "topo": {"dp": "4,2"}, "codec": "f32", "nbytes": 4096,
                "predicted_us": 123.4,
                "predicted": {"latency_us": 100.0, "bandwidth_us": 23.4}}
        doc = merge_events([_mk(0, 0, 5.0, "bucket_planned", **prov)])
        assert validate_trace(doc) == []
        span = next(
            e for e in doc["traceEvents"] if e.get("cat") == "comm-plan"
        )
        assert span["ph"] == "X" and span["dur"] == pytest.approx(123.4)
        assert span["args"]["topo"] == {"dp": "4,2"}
        assert span["args"]["predicted"]["latency_us"] == 100.0

    def test_request_flow(self):
        doc = merge_events(
            [
                _mk(0, 0, 1.0, "serve_admit", rid=5, slot=0),
                _mk(0, 1, 1.1, "serve_prefill", rid=5, slot=0),
                _mk(1, 0, 2.0, "serve_admit", rid=5, slot=1),  # re-route
                _mk(1, 1, 2.5, "serve_retire", rid=5, n_tokens=4),
            ]
        )
        assert validate_trace(doc) == []
        flows = [e for e in doc["traceEvents"] if e.get("cat") == "request"]
        phs = [e["ph"] for e in flows]
        assert phs[0] == "s" and phs[-1] == "f"
        assert {e["id"] for e in flows} == {5}

    def test_merge_dedups_identical_lines_keeps_restarted_seq(self):
        a = _mk(0, 0, 1.0, "step_start", step=0)
        b = _mk(0, 1, 1.2, "step_end", step=0)
        # same rank, seq restarted by a LATER process (different ts):
        # distinct events, must survive the dedup
        c = _mk(0, 0, 9.0, "step_start", step=5)
        d = _mk(0, 1, 9.1, "step_end", step=5)
        doc = merge_events([a, b, dict(a), dict(b), c, d])  # a/b duplicated
        assert validate_trace(doc) == []
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert sorted(e["name"] for e in xs) == ["step 0", "step 5"]
        assert doc["otherData"]["events"] == 4

    def test_validate_catches_garbage(self):
        assert validate_trace({"traceEvents": "nope"})
        assert validate_trace({"traceEvents": [{"ph": "?"}]})
        bad = validate_trace(
            {"traceEvents": [{"name": "x", "ph": "X", "ts": 0, "pid": 0,
                              "tid": 0, "dur": -1}]}
        )
        assert any("dur" in b for b in bad)
        bad = validate_trace(
            {"traceEvents": [{"name": "f", "ph": "f", "ts": 0, "pid": 0,
                              "tid": 0, "id": 1}]}
        )
        assert any("finish without start" in b for b in bad)

    def test_merge_dir_roundtrip_and_torn_tail(self, tmp_path):
        with flight_recorder(tmp_path, rank=0):
            record_event("step_start", step=0)
            record_event("step_end", step=0)
            dump_current("test")
        with flight_recorder(tmp_path, rank=1, source="peer"):
            record_event("step_start", step=0)
        # torn final line (SIGKILL mid-write): everything before survives
        with open(tmp_path / "flight_00001.jsonl", "a") as f:
            f.write('{"ts": 1.0, "kind": "tru')
        events, dumps = read_dir(str(tmp_path))
        assert {e["rank"] for e in events} == {0, 1}
        assert dumps[0]["reason"] == "test"
        doc = merge_events(events, dumps)
        assert validate_trace(doc) == []
        assert doc["otherData"]["dumps"]["0"]["reason"] == "test"
        out = write_trace(doc, tmp_path / "timeline.json")
        with open(out) as f:
            assert validate_trace(json.load(f)) == []
        assert validate_trace(merge_dir(str(tmp_path))) == []


# -------------------------------------------------------------- provenance


class TestProvenance:
    def test_none_when_no_recorder(self):
        from flextree_tpu.schedule.stages import Topology

        assert (
            bucket_provenance(("dp",), {"dp": Topology.resolve(8, "4,2")}, 1024)
            is None
        )

    def test_payload_with_recorder(self, tmp_path):
        from flextree_tpu.schedule.stages import Topology

        topos = {"dp": Topology.resolve(8, "4,2"), "sp": None}
        with flight_recorder(tmp_path, rank=0):
            prov = bucket_provenance(
                ("dp", "sp"), topos, 1 << 20, n_leaves=12, dtype="float32",
                chunks=2,
            )
        assert prov["topo"] == {"dp": "4,2", "sp": "psum"}
        assert prov["codec"] == "f32" and prov["nbytes"] == 1 << 20
        assert prov["predicted_us"] > 0
        assert set(prov["predicted"]) >= {"latency_us", "bandwidth_us"}
        json.dumps(prov)  # must be event-embeddable

    def test_lonely_and_ring_and_codec(self, tmp_path):
        from flextree_tpu.ops.quantize import get_codec
        from flextree_tpu.schedule.stages import Topology

        with flight_recorder(tmp_path, rank=0):
            ring = bucket_provenance(
                ("dp",), {"dp": Topology.resolve(8, "1")}, 4096
            )
            lonely = bucket_provenance(
                ("dp",), {"dp": Topology.resolve(8, "3,2+2")}, 4096,
                codec=get_codec("int8"),
            )
        assert ring["topo"]["dp"] == "ring" and ring["predicted_us"] > 0
        assert lonely["topo"]["dp"] == "3,2+2"
        assert lonely["codec"] == "int8" and lonely["predicted_us"] > 0


# -------------------------------------------------- fit + serving telemetry


class TestFitTelemetry:
    def _toy(self):
        class D:
            def batch_at(self, step):
                t = np.full((2, 4), float(step + 1))
                return t, t

        def step_fn(state, tokens, targets):
            s = int(np.asarray(state["step"]))
            loss = float("nan") if s == 2 else 0.5
            return (
                {"step": np.int64(s + 1), "w": np.asarray(state["w"]) - 1.0},
                {"loss": loss},
            )

        return D(), step_fn, {"step": np.int64(0), "w": np.zeros(2)}

    def test_fit_events_and_report_view(self, tmp_path):
        from flextree_tpu.parallel.loop import FitConfig, fit

        data, step_fn, state = self._toy()
        with flight_recorder(tmp_path / "obs", rank=0) as rec:
            result = fit(
                state, step_fn, data,
                FitConfig(num_steps=4, log_every=0, prefetch=0),
            )
        events = read_events(rec.event_path)
        kinds = [e["kind"] for e in events]
        assert kinds[0] == "fit_start" and kinds[-1] == "fit_end"
        assert kinds.count("step_start") == 4  # NaN step still started
        assert "nan_skip" in kinds
        # run_report is a view over the same registry
        m = result.report.metrics
        assert m["counters"]["train.anomalies"] == 1
        assert m["counters"]["train.steps"] == 4
        doc = merge_dir(str(tmp_path / "obs"))
        assert validate_trace(doc) == []
        # fit_start/fit_end pair into ONE span despite different step
        # fields (start=0, end=4), and a clean run has no forensic
        # "(unfinished)" markers
        names = [e["name"] for e in doc["traceEvents"]]
        assert names.count("fit 0") == 1
        fit_span = next(e for e in doc["traceEvents"] if e["name"] == "fit 0")
        assert fit_span["ph"] == "X"
        assert not any("(unfinished)" in n for n in names)

    def test_fit_without_recorder_unchanged(self):
        from flextree_tpu.parallel.loop import FitConfig, fit

        data, step_fn, state = self._toy()
        result = fit(
            state, step_fn, data,
            FitConfig(num_steps=4, log_every=0, prefetch=0),
        )
        assert result.report.metrics is None
        assert result.report.anomalies == 1

    def test_watchdog_timeout_dump(self, tmp_path):
        import time as _time

        from flextree_tpu.parallel.loop import FitConfig, Supervision, fit

        data, _, state = self._toy()
        hang = {1}

        def step_fn(state, tokens, targets):
            s = int(np.asarray(state["step"]))
            if s in hang:
                hang.discard(s)
                _time.sleep(1.5)
            return (
                {"step": np.int64(s + 1), "w": np.asarray(state["w"])},
                {"loss": 0.5},
            )

        with flight_recorder(tmp_path, rank=0) as rec:
            result = fit(
                state, step_fn, data,
                FitConfig(num_steps=3, log_every=0, prefetch=0),
                supervision=Supervision(
                    step_timeout_s=0.4, max_step_retries=1
                ),
            )
        assert result.report.step_timeouts == 1
        with open(rec.dump_path) as f:
            dump = json.load(f)
        assert dump["reason"] == "watchdog_timeout"
        kinds = [e["kind"] for e in read_events(rec.event_path)]
        assert "watchdog_timeout" in kinds and "dump" in kinds


# ------------------------------------------------------------- satellites


class TestResultFileDisambiguation:
    def test_same_second_names_differ(self, monkeypatch):
        import flextree_tpu.utils.logging as L

        monkeypatch.setattr(L.time, "time", lambda: 1234567890.0)
        a = L.result_file_name("tag", 8, 100, "4,2")
        b = L.result_file_name("tag", 8, 100, "4,2")
        assert a != b  # the seed-era scheme silently overwrote here
        # scheme positions preserved for field-indexed tooling
        for name in (a, b):
            parts = name.split(".")
            assert parts[:5] == ["tag", "8", "100", "4-2", "ar_test"]
            assert parts[5].startswith("1234567890-")
            assert parts[6] == "json"

    def test_monotonic_across_calls(self):
        from flextree_tpu.utils.logging import result_file_name

        seqs = [
            int(result_file_name("t", 1, 1, "").split(".")[5].split("-")[1])
            for _ in range(3)
        ]
        assert seqs == sorted(seqs) and len(set(seqs)) == 3


class TestSpanLedgerSuffix:
    def test_strict_bytes_suffix(self):
        from flextree_tpu.utils.profiling import SpanLedger, span_bytes

        ledger = SpanLedger()
        for name in (
            "ft_bucket0_dp_3leaves_4096B",   # counts: 4096
            "ft_bucket1_dp_2leaves_100B",    # counts: 100
            "ft_bucket2_dp_fooB",            # last token merely ends in B
            "ft_bucket3_dp_0xB",             # hex-ish garbage
            "ft_bucket4_dp_12B_extra",       # suffix not terminal
            "ft_bucket5_dp_B",               # no digits
        ):
            ledger.record(name)
        assert ledger.total_bytes("ft_bucket") == 4196
        assert span_bytes("x_77B") == 77
        assert span_bytes("x_fooB") is None
        assert span_bytes("x_8B_more") is None


class TestRankAwareLogging:
    def test_rank_field_from_env(self, monkeypatch, capsys):
        from flextree_tpu.utils.logging import get_logger, logger_rank

        monkeypatch.setenv("FT_RANK", "3")
        assert logger_rank() == 3
        log = get_logger("flextree.test_rank_env")
        log.error("hello")
        err = capsys.readouterr().err
        assert "r3" in err and "hello" in err

    def test_explicit_rank_and_absent(self, monkeypatch, capsys):
        from flextree_tpu.utils.logging import get_logger, logger_rank

        monkeypatch.delenv("FT_RANK", raising=False)
        assert logger_rank() is None
        get_logger("flextree.test_rank_exp", rank=7).error("seven")
        assert "r7" in capsys.readouterr().err
        get_logger("flextree.test_rank_none").error("bare")
        assert "r" + "0" not in capsys.readouterr().err.split("]")[0]

    def test_bad_env_value_is_none(self, monkeypatch):
        from flextree_tpu.utils.logging import logger_rank

        monkeypatch.setenv("FT_RANK", "not-a-rank")
        assert logger_rank() is None

    def teardown_method(self):
        # drop the uniquely-named test loggers' handlers
        for name in (
            "flextree.test_rank_env",
            "flextree.test_rank_exp",
            "flextree.test_rank_none",
        ):
            logging.getLogger(name).handlers.clear()


# ---------------------------------------------------- serving registry view


class TestServingTelemetry:
    @pytest.fixture()
    def engine(self):
        import jax

        from flextree_tpu.models.transformer import (
            TransformerConfig,
            init_params,
        )
        from flextree_tpu.serving.batcher import BatcherConfig
        from flextree_tpu.serving.engine import ServingEngine
        from flextree_tpu.serving.kv_cache import PagedCacheConfig

        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=1, d_ff=64
        )
        params = init_params(jax.random.PRNGKey(0), cfg)
        pcfg = PagedCacheConfig(num_blocks=16, block_size=8, blocks_per_seq=4)
        return ServingEngine(params, cfg, pcfg, BatcherConfig(slots=2))

    def test_engine_metrics_and_events(self, engine, tmp_path):
        from flextree_tpu.serving.batcher import Request

        with flight_recorder(tmp_path, rank=0, source="serve") as rec:
            engine.submit(
                Request(rid=1, prompt=np.arange(4), max_new_tokens=3)
            )
            engine.run_until_idle()
        snap = engine.metrics.snapshot()
        assert snap["counters"]["serve.submitted"] == 1
        assert snap["counters"]["serve.finished"] == 1
        assert snap["histograms"]["serve.ttft_ms"]["count"] == 1
        report = engine.report()
        assert report["completed"] == 1
        assert report["counters"] == snap["counters"]  # report IS a view
        kinds = [e["kind"] for e in read_events(rec.event_path)]
        assert "serve_admit" in kinds and "serve_retire" in kinds
        doc = merge_dir(str(tmp_path))
        assert validate_trace(doc) == []
        flows = [
            e for e in doc["traceEvents"] if e.get("cat") == "request"
        ]
        assert [e["ph"] for e in flows] == ["s", "t", "f"]

    def test_pool_report_is_registry_view(self, engine):
        # pool counters are registry-backed; the legacy attributes read
        # the same numbers (pinned here so they can't diverge again)
        from flextree_tpu.serving.pool import PoolConfig, ReplicaPool

        import tempfile

        with tempfile.TemporaryDirectory() as hb:
            pool = ReplicaPool([engine], PoolConfig(heartbeat_dir=hb))
            try:
                from flextree_tpu.serving.batcher import Request

                pool.submit(
                    Request(rid=9, prompt=np.arange(4), max_new_tokens=2)
                )
                for _ in range(200):
                    if pool.idle:
                        break
                    pool.step()
                report = pool.report()
            finally:
                pool.shutdown()
        assert report["submitted"] == 1
        assert report["completed"] == 1
        assert report["metrics"]["counters"]["pool.submitted"] == 1
        assert pool.submitted == 1 and pool.reroutes == 0
        assert 0 in report["replica_metrics"]
        assert (
            report["replica_metrics"][0]["counters"]["serve.finished"] >= 1
        )


# -------------------------------------- per-step span clock (ISSUE 15)


def _prov(nb=4096, pred=None):
    pred = pred or {
        "latency_us": 30.0, "bandwidth_us": 8.0, "reduce_us": 2.0,
        "control_us": 1.0, "codec_us": 0.0,
    }
    return {
        "axes": ["dp"], "topo": {"dp": "8"}, "world": {"dp": 8},
        "nbytes": nb, "codec": "f32", "sharded": False,
        "predicted": pred, "predicted_us": sum(pred.values()),
    }


class TestPlanCapture:
    def test_capture_collects_provenance_spans_only(self):
        from flextree_tpu.utils.profiling import comm_span, plan_capture

        with plan_capture() as cap:
            with comm_span("ft_bucket0_dp_4096B", provenance=_prov()):
                pass
            with comm_span("bare_span_128B"):
                pass
        assert [name for name, _ in cap] == ["ft_bucket0_dp_4096B"]

    def test_nested_captures_both_record(self):
        from flextree_tpu.utils.profiling import comm_span, plan_capture

        with plan_capture() as outer:
            with plan_capture() as inner:
                with comm_span("ft_bucket0_dp_4096B", provenance=_prov()):
                    pass
        assert len(outer) == 1 and len(inner) == 1


class TestStepSpanClock:
    def test_plan_from_capture_groups_phases(self):
        from flextree_tpu.obs.stepclock import plan_from_capture

        plan = plan_from_capture(
            [("b0", _prov(4096)), ("b1", _prov(8192)),
             ("bad", {"predicted_error": True})]
        )
        assert len(plan.buckets) == 2
        assert plan.fixed_us == pytest.approx(2 * 31.0)
        assert plan.bytes_us == pytest.approx(2 * 10.0)
        assert plan.predicted_us == pytest.approx(2 * 41.0)

    def test_plan_sig_distinguishes_bucket_sizes(self):
        from flextree_tpu.obs.stepclock import plan_from_capture

        a = plan_from_capture([("b", _prov(4096))])
        b = plan_from_capture([("b", _prov(8192))])
        assert a.sig != b.sig

    def test_first_step_per_plan_is_dropped_as_compile(self, tmp_path):
        from flextree_tpu.obs.stepclock import StepSpanClock

        clock = StepSpanClock(compute_floor_us=100.0)
        clock.set_plan([("b", _prov())])
        assert clock.observe_step(0, 0.01) is None  # the compiling call
        assert clock.observe_step(1, 0.01) is not None
        clock.set_plan([("b", _prov(8192))])  # re-compile: drop again
        assert clock.observe_step(2, 0.01) is None
        assert clock.dropped_first == 2

    def test_events_carry_pairing_keys_and_breakdowns(self, tmp_path):
        from flextree_tpu.obs.stepclock import StepSpanClock

        with flight_recorder(tmp_path, rank=0):
            clock = StepSpanClock(compute_floor_us=1000.0, fingerprint="fp")
            clock.set_plan([("b0", _prov(4096)), ("b1", _prov(8192))])
            clock.observe_step(0, 0.002)
            clock.observe_step(1, 0.002)  # 2000us: comm = 1000us
        events, _ = read_dir(str(tmp_path))
        step_evs = [e for e in events if e["kind"] == "step_measured"]
        buck_evs = [e for e in events if e["kind"] == "bucket_measured"]
        assert len(step_evs) == 1 and len(buck_evs) == 2
        assert step_evs[0]["comm_us"] == pytest.approx(1000.0, rel=0.01)
        for ev in buck_evs:
            assert ev["per_step"] is True and ev["apportioned"] is True
            assert ev["topo"] == {"dp": "8"} and ev["world"] == {"dp": 8}
            assert isinstance(ev["predicted"], dict)
            assert ev["fingerprint"] == "fp"
        # equal predictions -> equal apportioned shares
        assert buck_evs[0]["measured_us"] == pytest.approx(
            buck_evs[1]["measured_us"]
        )
        assert sum(e["measured_us"] for e in buck_evs) == pytest.approx(
            1000.0, rel=0.01
        )

    def test_provisional_floor_tracks_quietest_step(self):
        from flextree_tpu.obs.stepclock import StepSpanClock

        clock = StepSpanClock()  # no configured floor
        clock.set_plan([("b", _prov())])  # predicted_us = 41
        clock.observe_step(0, 0.001)
        assert clock.floor_us is None  # compile dropped: no evidence yet
        clock.observe_step(1, 0.002)
        clock.observe_step(2, 0.001)
        # floor = min(step_us - predicted) = 1000 - 41
        assert clock.floor_us == pytest.approx(1000.0 - 41.0, rel=0.01)


class TestStepMeasuredTimeline:
    def test_per_step_measured_spans_pair_with_plan_spans(self):
        prov = _prov(4096)
        events = [
            {"ts": 1.0, "rank": 0, "seq": 0, "kind": "bucket_planned",
             "name": "ft_bucket0_dp_4096B", **prov},
            {"ts": 2.0, "rank": 0, "seq": 1, "kind": "bucket_measured",
             "name": "ft_bucket0_dp_4096B", "topo": {"dp": "8"},
             "world": {"dp": 8}, "nbytes": 4096, "codec": "f32",
             "sharded": False, "measured_us": 55.0, "predicted_us": 41.0,
             "predicted": prov["predicted"], "per_step": True,
             "apportioned": True, "step": 3},
            {"ts": 3.0, "rank": 0, "seq": 2, "kind": "step_measured",
             "step": 3, "step_us": 2000.0, "floor_us": 1000.0,
             "comm_us": 1000.0, "predicted_us": 41.0, "plan_sig": "ab",
             "n_buckets": 1},
        ]
        doc = merge_events(events)
        assert validate_trace(doc) == []
        plan = [e for e in doc["traceEvents"] if e.get("cat") == "comm-plan"]
        meas = [e for e in doc["traceEvents"]
                if e.get("cat") == "comm-measured"]
        step = [e for e in doc["traceEvents"]
                if e.get("cat") == "step-measured"]
        assert len(plan) == len(meas) == len(step) == 1
        # the pairing: same name, same rank track, measured span carries
        # the prediction + per-phase breakdown in its args
        assert meas[0]["name"] == plan[0]["name"]
        assert meas[0]["pid"] == plan[0]["pid"] == 0
        assert meas[0]["dur"] == pytest.approx(55.0)
        assert meas[0]["args"]["predicted_us"] == 41.0
        assert isinstance(meas[0]["args"]["predicted"], dict)
        assert step[0]["dur"] == pytest.approx(2000.0)

    def test_serve_round_measured_renders_as_span(self):
        events = [
            {"ts": 1.0, "rank": 0, "seq": 0, "kind": "serve_round_measured",
             "round": 4, "n_active": 3, "max_len": 40,
             "measured_us": 900.0, "predicted_us": 700.0,
             "compute_us": 600.0, "bytes_us": 100.0},
        ]
        doc = merge_events(events)
        assert validate_trace(doc) == []
        spans = [e for e in doc["traceEvents"]
                 if e.get("cat") == "serve-measured"]
        assert len(spans) == 1 and spans[0]["dur"] == pytest.approx(900.0)

    def test_residual_pairs_tags_step_source_and_breakdown(self):
        prov = _prov(4096)
        events = [
            {"ts": 1.0, "rank": 0, "seq": 0, "kind": "bucket_planned",
             "name": "b", **prov},
            {"ts": 2.0, "rank": 0, "seq": 1, "kind": "bucket_measured",
             "topo": {"dp": "8"}, "world": {"dp": 8}, "nbytes": 4096,
             "codec": "f32", "sharded": False, "measured_us": 55.0,
             "predicted_us": 41.0, "predicted": prov["predicted"],
             "per_step": True},
        ]
        from flextree_tpu.obs.timeline import residual_pairs

        samples, _skipped = residual_pairs(events)
        assert len(samples) == 1
        assert samples[0].source == "step"
        assert samples[0].phases == {
            "fixed": pytest.approx(31.0),
            "bytes": pytest.approx(10.0),
            "codec": pytest.approx(0.0),
        }


class TestPrometheusExposition:
    def test_counters_gauges_histograms(self):
        from flextree_tpu.obs.metrics import (
            MetricsRegistry,
            prometheus_exposition,
        )

        reg = MetricsRegistry()
        reg.counter("serve.finished").inc(3)
        reg.gauge("serve.free_blocks").set(17)
        h = reg.windowed_histogram(
            "serve.ttft_ms", buckets=(1.0, 10.0, 100.0), interval_s=1.0,
            intervals=4,
        )
        for v in (0.5, 5.0, 50.0, 50.0):
            h.observe(v, now=100.0)
        text = prometheus_exposition({"0": reg.snapshot()})
        assert "# TYPE flextree_serve_finished counter" in text
        assert 'flextree_serve_finished{rank="0"} 3' in text
        assert 'flextree_serve_free_blocks{rank="0"} 17' in text
        assert "# TYPE flextree_serve_ttft_ms histogram" in text
        # cumulative buckets, not per-bucket counts
        assert 'flextree_serve_ttft_ms_bucket{rank="0",le="1.0"} 1' in text
        assert 'flextree_serve_ttft_ms_bucket{rank="0",le="10.0"} 2' in text
        assert 'flextree_serve_ttft_ms_bucket{rank="0",le="100.0"} 4' in text
        assert 'flextree_serve_ttft_ms_bucket{rank="0",le="+Inf"} 4' in text
        assert 'flextree_serve_ttft_ms_count{rank="0"} 4' in text
        # the windowed SLO view is scrapeable as a gauge
        assert "flextree_serve_ttft_ms_window_count" in text

    def test_name_sanitization(self):
        from flextree_tpu.obs.metrics import _prom_name

        assert _prom_name("serve.ttft_ms") == "flextree_serve_ttft_ms"
        assert _prom_name("a-b/c d") == "flextree_a_b_c_d"

    def test_metrics_cli_prom(self, tmp_path, capsys):
        from flextree_tpu.obs.__main__ import main
        from flextree_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        reg.counter("train.steps").inc(5)
        (tmp_path / "metrics_0.json").write_text(json.dumps(reg.snapshot()))
        assert main(["metrics", str(tmp_path), "--prom"]) == 0
        out = capsys.readouterr().out
        assert 'flextree_train_steps{rank="0"} 5' in out
        assert main(["metrics", str(tmp_path)]) == 0
        assert "train.steps" in capsys.readouterr().out
        empty = tmp_path / "empty"
        empty.mkdir()
        assert main(["metrics", str(empty), "--prom"]) == 1


class TestResidualsCLIFilters:
    def _write_events(self, dir, fingerprint, spec="8", sizes=(4096, 65536)):
        os.makedirs(dir, exist_ok=True)
        with open(os.path.join(dir, "flight_0.jsonl"), "w") as f:
            for i, nb in enumerate(sizes):
                pred = {
                    "latency_us": 30.0, "bandwidth_us": nb / 1000.0,
                    "reduce_us": nb / 4000.0, "control_us": 1.0,
                    "codec_us": 0.0,
                }
                ev = {
                    "ts": float(i), "rank": 0, "seq": i,
                    "kind": "bucket_measured", "topo": {"dp": spec},
                    "world": {"dp": 8}, "nbytes": nb, "codec": "f32",
                    "sharded": False, "measured_us": sum(pred.values()) * 2,
                    "predicted_us": sum(pred.values()), "predicted": pred,
                    "fingerprint": fingerprint,
                }
                f.write(json.dumps(ev) + "\n")

    def test_json_and_fingerprint_filter(self, tmp_path, capsys):
        from flextree_tpu.obs.__main__ import main

        self._write_events(str(tmp_path), "fpA")
        assert main(["residuals", str(tmp_path), "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert len(doc["samples"]) == 2
        assert doc["samples"][0]["phases"]["fixed"] == pytest.approx(31.0)
        assert main(
            ["residuals", str(tmp_path), "--fingerprint", "nope", "--json"]
        ) == 0
        assert json.loads(capsys.readouterr().out)["samples"] == []

    def test_table_has_phase_columns(self, tmp_path, capsys):
        from flextree_tpu.obs.__main__ import main

        self._write_events(str(tmp_path), "fpA")
        assert main(["residuals", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "phases f/b/c" in out and "drift" in out

    def test_fleet_pools_across_dirs(self, tmp_path, capsys):
        from flextree_tpu.obs.__main__ import main

        # each run alone is one shape at two sizes (refuses to fit);
        # pooled across shapes the phase fit answers
        sizes = (4096, 65536, 1 << 20)
        self._write_events(str(tmp_path / "r0"), "fp", spec="8", sizes=sizes)
        self._write_events(str(tmp_path / "r1"), "fp", spec="4,2",
                           sizes=sizes)
        rc = main(["fleet", str(tmp_path / "r0"), str(tmp_path / "r1"),
                   "--json"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert doc["pooled"]["fp"]["condition"] is not None
        assert doc["pooled"]["fp"]["samples"] == 6
        assert doc["pooled"]["fp"]["runs"] == 2

    def test_fleet_fit_out_persists_calibration(self, tmp_path, capsys):
        from flextree_tpu.obs.__main__ import main

        sizes = (4096, 65536, 1 << 20)
        self._write_events(str(tmp_path / "r0"), "fp", spec="8", sizes=sizes)
        self._write_events(str(tmp_path / "r1"), "fp", spec="4,2",
                           sizes=sizes)
        out_path = tmp_path / "CAL.json"
        rc = main([
            "fleet", str(tmp_path / "r0"), str(tmp_path / "r1"),
            "--fit-out", str(out_path), "--backend", "cpu", "--json",
        ])
        assert rc == 0
        capsys.readouterr()
        doc = json.loads(out_path.read_text())
        assert doc["cpu"]["source"] == "feedback"
        assert doc["cpu"]["fingerprint"] == "fp"
        assert doc["cpu"]["meta"]["fleet"]["samples"] == 6
