"""Tier-1 coverage for the elastic device pool (ISSUE 13, docs/ARBITER.md):
windowed SLO percentiles, the chip-lease protocol on the heartbeat dir,
the arbiter's breach/hysteresis/cooldown state machine, ``fit``'s
checkpoint → rebuild → restore lease resizes with the bitwise-resume
proof, and the serving pool's arbiter-controlled add/release membership.

Everything here is deterministic: clocks are injected (``metrics._now``,
``arbiter.core._wall``, the lease client's ``_mono``), SLO readings are
scripted, and the only JAX in the file is the tiny serving model the
pool tests share.  The executed real-wall-clock proof is
``tools/arbiter_spike.py`` → the committed ``ARBITER_SPIKE.json``.
"""

from __future__ import annotations

import json
import math

import numpy as np
import pytest

from flextree_tpu.arbiter import (
    ArbiterConfig,
    DeviceInventory,
    PoolArbiter,
    SloReading,
    pool_slo_reader,
)
from flextree_tpu.obs.metrics import (
    Histogram,
    MetricsRegistry,
    WindowedHistogram,
    merged_window_percentile,
)
from flextree_tpu.obs.timeline import merge_events, validate_trace
from flextree_tpu.parallel.loop import FitConfig, fit
from flextree_tpu.runtime import (
    LeaseGrant,
    LeaseLedger,
    ResizeDirective,
    TrainLeaseClient,
)

# ------------------------------------------------------ windowed histograms


class TestWindowedHistogram:
    def test_window_answers_recent_cumulative_answers_everything(self):
        h = WindowedHistogram(interval_s=1.0, intervals=5)
        for i in range(2000):
            h.observe(5.0, now=100.0 + i * 0.01)  # a long quiet run
        for _ in range(10):
            h.observe(5000.0, now=200.0)  # the fresh breach: 0.5% of total
        # cumulative p99 is diluted by the quiet run; the window is not
        assert h.percentile(99) < 100.0
        assert h.window_percentile(99, now=200.0) > 1_000.0
        assert h.window_count(now=200.0) == 10
        assert h.count == 2010

    def test_old_intervals_expire(self):
        h = WindowedHistogram(interval_s=1.0, intervals=4)
        h.observe(7.0, now=10.0)
        assert h.window_count(now=10.0) == 1
        assert h.window_count(now=13.9) == 1  # still inside the window
        assert h.window_count(now=14.1) == 0  # aged out
        assert math.isnan(h.window_percentile(99, now=14.1))
        assert h.count == 1  # the cumulative view never forgets

    def test_ring_slot_reuse_drops_stale_counts(self):
        h = WindowedHistogram(interval_s=1.0, intervals=3)
        h.observe(1.0, now=0.5)
        # interval index 3 reuses slot 0; the old count must not bleed in
        h.observe(2.0, now=3.5)
        counts, count, _, mn, mx = h.window_counts(now=3.5)
        assert count == 1 and mn == 2.0 and mx == 2.0

    @pytest.mark.parametrize("dist", ["uniform", "lognormal"])
    def test_window_percentile_vs_numpy_oracle(self, dist):
        """The windowed percentile carries the same within-one-bucket
        bound as the cumulative one, measured against NumPy over exactly
        the in-window samples."""
        rng = np.random.default_rng(hash(dist) % (2**32))
        h = WindowedHistogram(interval_s=1.0, intervals=10)
        old = rng.uniform(2_000, 9_000, 500)  # out-of-window noise
        for v in old:
            h.observe(v, now=50.0)
        vals = {
            "uniform": rng.uniform(0, 90, 4000),
            "lognormal": rng.lognormal(1.0, 1.0, 4000),
        }[dist]
        t0 = 100.0
        for i, v in enumerate(vals):
            h.observe(v, now=t0 + (i % 10) * 0.9)
        edges = (0.0,) + h.edges
        for q in (50, 90, 95, 99):
            got = h.window_percentile(q, now=t0 + 9.5)
            want = float(np.percentile(vals, q))
            i = int(np.searchsorted(h.edges, want))
            lo = edges[i]
            hi = h.edges[i] if i < len(h.edges) else float(np.max(vals))
            assert abs(got - want) <= (hi - lo) + 1e-9, (q, got, want)

    def test_merged_window_percentile_pools_replicas(self):
        a = WindowedHistogram(interval_s=1.0, intervals=5)
        b = WindowedHistogram(interval_s=1.0, intervals=5)
        for _ in range(99):
            a.observe(1.0, now=10.0)
        b.observe(90_000.0, now=10.0)  # one replica hides the outlier...
        assert a.window_percentile(99.5, now=10.0) <= 1.0
        p, n = merged_window_percentile([a, b], 99.5, now=10.0)
        assert n == 100
        assert p > 1_000.0  # ...the pooled view does not

    def test_merged_window_requires_matching_edges(self):
        a = WindowedHistogram(buckets=(1.0, 2.0))
        b = WindowedHistogram(buckets=(1.0, 3.0))
        with pytest.raises(ValueError, match="bucket edges"):
            merged_window_percentile([a, b], 99)
        assert math.isnan(merged_window_percentile([], 99)[0])

    def test_payload_carries_window_beside_cumulative(self):
        h = WindowedHistogram(interval_s=1.0, intervals=5)
        h.observe(3.0)
        p = h.to_payload()
        assert p["count"] == 1  # the cumulative schema is unchanged
        assert p["window"]["seconds"] == 5.0
        assert p["window"]["count"] == 1
        json.dumps(p)

    def test_registry_windowed_then_plain_is_one_instrument(self):
        reg = MetricsRegistry()
        w = reg.windowed_histogram("serve.ttft_ms", interval_s=0.5,
                                   intervals=4)
        assert reg.histogram("serve.ttft_ms") is w  # a windowed IS a plain
        # ...but a plain one can never be upgraded in place
        reg.histogram("other")
        with pytest.raises(TypeError, match="already registered"):
            reg.windowed_histogram("other")

    def test_validation(self):
        with pytest.raises(ValueError, match="interval_s"):
            WindowedHistogram(interval_s=0.0)
        with pytest.raises(ValueError):
            WindowedHistogram(intervals=0)


# ------------------------------------------------------------- inventory


class TestDeviceInventory:
    def test_defaults_and_views(self):
        inv = DeviceInventory([0, 1, 2, 3], train=(0, 1, 2))
        assert inv.chips == (0, 1, 2, 3)
        assert inv.held_by("train") == (0, 1, 2)
        assert inv.held_by("serve") == (3,)
        assert inv.grants() == {
            "train": (0, 1, 2), "serve": (3,), "arbiter": ()
        }

    def test_move_is_all_or_nothing(self):
        inv = DeviceInventory([0, 1, 2], train=(0, 1))
        with pytest.raises(ValueError, match="held by"):
            inv.move((1, 2), "train", "arbiter")  # 2 belongs to serve
        assert inv.held_by("train") == (0, 1)  # nothing moved
        inv.move((1,), "train", "arbiter")
        assert inv.holder_of(1) == "arbiter"

    def test_take_honors_the_keep_floor(self):
        inv = DeviceInventory([0, 1, 2])
        assert inv.take("train", 5, keep=1) == (1, 2)
        assert inv.take("train", 1, keep=1) == ()  # already at the floor
        assert inv.held_by("train") == (0,)

    def test_bad_construction_is_loud(self):
        with pytest.raises(ValueError, match="duplicate"):
            DeviceInventory([0, 0])
        with pytest.raises(ValueError, match="unknown chips"):
            DeviceInventory([0, 1], train=(7,))
        with pytest.raises(ValueError, match="at least one"):
            DeviceInventory([])
        with pytest.raises(ValueError, match="not in the inventory"):
            DeviceInventory([0]).holder_of(9)


# ------------------------------------------------------------ lease ledger


class TestLeaseLedger:
    def test_publish_read_roundtrip(self, tmp_path):
        led = LeaseLedger(str(tmp_path))
        assert led.read() is None
        led.publish(0, {"train": (0, 1), "serve": (2,)}, reason="initial")
        grant = led.read()
        assert isinstance(grant, LeaseGrant)
        assert grant.epoch == 0 and grant.chips("train") == (0, 1)
        assert grant.reason == "initial"

    def test_epochs_must_increase(self, tmp_path):
        led = LeaseLedger(str(tmp_path))
        led.publish(3, {"train": (0,)})
        with pytest.raises(ValueError, match="epoch must increase"):
            led.publish(3, {"train": (0,)})

    def test_double_granted_chip_is_loud(self, tmp_path):
        led = LeaseLedger(str(tmp_path))
        with pytest.raises(ValueError, match="granted to both"):
            led.publish(0, {"train": (0, 1), "serve": (1,)})

    def test_acks(self, tmp_path):
        led = LeaseLedger(str(tmp_path))
        assert led.acked_epoch("train") == -1
        led.ack("train", 4)
        assert led.acked_epoch("train") == 4
        assert led.acked_epoch("serve") == -1

    def test_garbage_ledger_reads_as_none(self, tmp_path):
        led = LeaseLedger(str(tmp_path))
        (tmp_path / "lease_ledger.json").write_text("{torn")
        assert led.read() is None


class TestTrainLeaseClient:
    def _client(self, led, **kw):
        clock = {"now": 0.0}
        c = TrainLeaseClient(led, _mono=lambda: clock["now"],
                             poll_interval_s=1.0, **kw)
        return c, clock

    def test_first_poll_adopts_and_acks(self, tmp_path):
        led = LeaseLedger(str(tmp_path))
        led.publish(0, {"train": (0, 1, 2)})
        c, _ = self._client(led)
        assert c.poll(0) is None
        assert c.chips == (0, 1, 2)
        assert led.acked_epoch("train") == 0

    def test_changed_grant_is_a_directive_until_acked(self, tmp_path):
        led = LeaseLedger(str(tmp_path))
        led.publish(0, {"train": (0, 1, 2)})
        c, clock = self._client(led)
        c.poll(0)
        led.publish(1, {"train": (0,), "arbiter": (1, 2)}, reason="breach")
        clock["now"] = 1.0
        d = c.poll(5)
        assert d == ResizeDirective(epoch=1, chips=(0,), reason="breach")
        assert led.acked_epoch("train") == 0  # not acked until applied
        c.ack(d)
        assert led.acked_epoch("train") == 1 and c.chips == (0,)

    def test_unchanged_slice_acks_in_place(self, tmp_path):
        """The epoch that hands OUR former chips to serving does not
        change our slice: no resize, just an ack."""
        led = LeaseLedger(str(tmp_path))
        led.publish(0, {"train": (0,), "arbiter": (1,)})
        c, clock = self._client(led)
        c.poll(0)
        led.publish(1, {"train": (0,), "serve": (1,)})
        clock["now"] = 1.0
        assert c.poll(3) is None
        assert led.acked_epoch("train") == 1

    def test_poll_is_throttled(self, tmp_path):
        led = LeaseLedger(str(tmp_path))
        led.publish(0, {"train": (0, 1)})
        c, clock = self._client(led)
        c.poll(0)
        led.publish(1, {"train": (0,)})
        assert c.poll(1) is None  # inside the poll interval: no file read
        clock["now"] = 1.0
        assert c.poll(2) is not None

    def test_configured_tracks_largest_grant(self, tmp_path):
        led = LeaseLedger(str(tmp_path))
        led.publish(0, {"train": (0, 1, 2)})
        c, _ = self._client(led)
        c.poll(0)
        assert c.configured == 3

    def test_initial_chips_turns_a_first_poll_revocation_into_a_resize(
        self, tmp_path
    ):
        """A client that KNOWS its build world must never silently ack a
        revocation it hasn't applied — the first observation being a
        smaller grant (early breach, restart mid-handoff) is a directive,
        or the arbiter would hand chips to serving while training still
        spans them."""
        led = LeaseLedger(str(tmp_path))
        led.publish(1, {"train": (0,), "arbiter": (1, 2)}, reason="breach")
        c, _ = self._client(led, initial_chips=(0, 1, 2))
        d = c.poll(0)
        assert d == ResizeDirective(epoch=1, chips=(0,), reason="breach")
        assert led.acked_epoch("train") == -1  # nothing acked yet


# ----------------------------------------------------------- the arbiter


def _mk_arbiter(tmp_path, monkeypatch, readings, cfg=None, **hooks):
    """An arbiter over a scripted SLO feed and a fake wall clock; returns
    (arbiter, clock, ledger, log) where log records hook calls."""
    from flextree_tpu.arbiter import core as C

    clock = {"now": 1000.0}
    monkeypatch.setattr(C, "_wall", lambda: clock["now"])
    inv = DeviceInventory([0, 1, 2, 3], train=(0, 1, 2))
    led = LeaseLedger(str(tmp_path))
    calls = {"grant": [], "return": []}
    arb = PoolArbiter(
        inv, led,
        cfg or ArbiterConfig(
            slo_p99_ms=100.0, window_s=5.0, release_frac=0.5,
            breach_ticks=2, clear_ticks=2, cooldown_s=3.0,
            min_train_chips=1, burst_chips=2, min_samples=5,
        ),
        slo_reader=lambda: readings[0],
        on_serve_grant=lambda c: calls["grant"].append(tuple(c)),
        on_serve_return=lambda c: calls["return"].append(tuple(c)),
        **hooks,
    )
    return arb, clock, led, calls


BREACH = SloReading(p99_ms=800.0, samples=20)
CLEAR = SloReading(p99_ms=20.0, samples=20)
IN_BAND = SloReading(p99_ms=80.0, samples=20)  # under SLO, over low-water
THIN = SloReading(p99_ms=9_000.0, samples=2)  # loud but unproven
EMPTY = SloReading(p99_ms=float("nan"), samples=0)


class TestPoolArbiter:
    def test_breach_is_debounced_then_preempts(self, tmp_path, monkeypatch):
        readings = [BREACH]
        arb, clock, led, _ = _mk_arbiter(tmp_path, monkeypatch, readings)
        assert arb.tick()["action"] is None  # one tick is not a trend
        clock["now"] += 1
        d = arb.tick()
        assert d["action"] == "preempt"
        assert arb.pending_handoff == (1, 2)
        assert arb.inventory.held_by("train") == (0,)
        assert led.read().chips("arbiter") == (1, 2)  # parked, not serving

    def test_grant_waits_for_the_train_ack(self, tmp_path, monkeypatch):
        readings = [BREACH]
        arb, clock, led, calls = _mk_arbiter(tmp_path, monkeypatch, readings)
        for _ in range(2):
            clock["now"] += 1
            arb.tick()
        epoch = led.read().epoch
        clock["now"] += 1
        assert arb.tick()["action"] is None  # no ack yet: chips stay parked
        assert not calls["grant"]
        led.ack("train", epoch)
        clock["now"] += 1
        assert arb.tick()["action"] == "grant"
        assert calls["grant"] == [(1, 2)]
        assert arb.loaned == (1, 2)
        assert led.read().chips("serve") == (1, 2, 3)

    def _to_loaned(self, arb, clock, led):
        for _ in range(2):
            clock["now"] += 1
            arb.tick()
        led.ack("train", led.read().epoch)
        clock["now"] += 1
        arb.tick()
        assert arb.loaned == (1, 2)

    def test_return_needs_sustained_clear_past_cooldown(
        self, tmp_path, monkeypatch
    ):
        readings = [BREACH]
        arb, clock, led, calls = _mk_arbiter(tmp_path, monkeypatch, readings)
        self._to_loaned(arb, clock, led)
        readings[0] = CLEAR
        clock["now"] += 0.5
        arb.tick()
        clock["now"] += 0.5
        assert arb.tick()["action"] is None  # clear_ticks met, cooldown not
        clock["now"] += 5.0
        d = arb.tick()
        assert d["action"] == "return"
        assert calls["return"] == [(1, 2)]
        assert arb.inventory.held_by("train") == (0, 1, 2)
        assert arb.loaned == ()
        # training applies the return grant like any other epoch
        assert led.read().chips("train") == (0, 1, 2)

    def test_hysteresis_band_holds_the_allocation(self, tmp_path, monkeypatch):
        """p99 under the SLO but over the low-water: neither streak
        advances, chips stay where they are — the band IS the
        anti-thrash."""
        readings = [BREACH]
        arb, clock, led, calls = _mk_arbiter(tmp_path, monkeypatch, readings)
        self._to_loaned(arb, clock, led)
        readings[0] = IN_BAND
        for _ in range(20):
            clock["now"] += 1
            assert arb.tick()["action"] is None
        assert arb.loaned == (1, 2)
        assert not calls["return"]

    def test_thin_window_is_no_evidence(self, tmp_path, monkeypatch):
        readings = [THIN]
        arb, clock, _, _ = _mk_arbiter(tmp_path, monkeypatch, readings)
        for _ in range(5):
            clock["now"] += 1
            d = arb.tick()
            assert d["action"] is None and not d["breached"]

    def test_empty_window_clears(self, tmp_path, monkeypatch):
        readings = [BREACH]
        arb, clock, led, _ = _mk_arbiter(tmp_path, monkeypatch, readings)
        self._to_loaned(arb, clock, led)
        readings[0] = EMPTY  # traffic stopped entirely
        clock["now"] += 4
        arb.tick()
        clock["now"] += 1
        assert arb.tick()["action"] == "return"

    def test_cooldown_blocks_immediate_re_preempt(self, tmp_path, monkeypatch):
        """A spike right after a return must wait out the cooldown: a
        single oscillation cannot thrash the pool."""
        readings = [BREACH]
        arb, clock, led, calls = _mk_arbiter(tmp_path, monkeypatch, readings)
        self._to_loaned(arb, clock, led)
        readings[0] = CLEAR
        clock["now"] += 4
        arb.tick()
        clock["now"] += 1
        assert arb.tick()["action"] == "return"
        readings[0] = BREACH
        clock["now"] += 1
        arb.tick()
        clock["now"] += 1
        assert arb.tick()["action"] is None  # breach_ticks met, cooldown not
        clock["now"] += 3
        assert arb.tick()["action"] == "preempt"

    def test_min_train_chips_floors_the_revocation(self, tmp_path, monkeypatch):
        readings = [BREACH]
        arb, clock, led, _ = _mk_arbiter(tmp_path, monkeypatch, readings)
        self._to_loaned(arb, clock, led)  # train down to its 1-chip floor
        readings[0] = BREACH
        clock["now"] += 10
        for _ in range(3):
            clock["now"] += 1
            assert arb.tick()["action"] is None  # nothing left to take
        assert arb.inventory.held_by("train") == (0,)

    def test_admit_blocked_growth_is_a_breach(self, tmp_path, monkeypatch):
        readings = [SloReading(p99_ms=10.0, samples=20, admit_blocked=0.0)]
        arb, clock, _, _ = _mk_arbiter(
            tmp_path, monkeypatch, readings,
            cfg=ArbiterConfig(
                slo_p99_ms=100.0, breach_ticks=2, cooldown_s=0.5,
                admit_blocked_delta=5.0, min_samples=5,
            ),
        )
        arb.tick()
        readings[0] = SloReading(p99_ms=10.0, samples=20, admit_blocked=10.0)
        clock["now"] += 1
        assert arb.tick()["breached"]  # p99 fine, admission pressure not
        readings[0] = SloReading(p99_ms=10.0, samples=20, admit_blocked=20.0)
        clock["now"] += 1
        assert arb.tick()["action"] == "preempt"

    def test_grant_restarts_the_cooldown(self, tmp_path, monkeypatch):
        """The grant completes a chip move: a burst that ended while the
        trainer was still checkpointing must not bounce the chips back on
        the very next tick."""
        readings = [BREACH]
        arb, clock, led, _ = _mk_arbiter(tmp_path, monkeypatch, readings)
        for _ in range(2):
            clock["now"] += 1
            arb.tick()
        # the burst drains while training is still rebuilding (no ack):
        # the clear streak fills during the pending handoff
        readings[0] = CLEAR
        for _ in range(3):
            clock["now"] += 1
            assert arb.tick()["action"] is None
        led.ack("train", led.read().epoch)
        clock["now"] += 1
        assert arb.tick()["action"] == "grant"
        grant_wall = clock["now"]
        clock["now"] += 1
        assert arb.tick()["action"] is None  # inside the post-grant cooldown
        clock["now"] = grant_wall + 3.5  # past cooldown_s=3.0
        assert arb.tick()["action"] == "return"

    def test_restart_supersedes_a_prior_ledger(self, tmp_path, monkeypatch):
        readings = [CLEAR]
        arb1, clock, led, _ = _mk_arbiter(tmp_path, monkeypatch, readings)
        assert led.read().epoch == 0
        # a new arbiter against the same heartbeat dir must come up and
        # keep epochs increasing, not refuse until the file is deleted
        inv2 = DeviceInventory([0, 1, 2, 3], train=(0, 1, 2))
        arb2 = PoolArbiter(
            inv2, led,
            ArbiterConfig(slo_p99_ms=100.0),
            slo_reader=lambda: readings[0],
        )
        assert led.read().epoch == 1
        assert led.read().chips("train") == (0, 1, 2)

    def test_pool_slo_reader_enforces_the_window_match(self):
        class _Eng:
            def __init__(self):
                self.metrics = MetricsRegistry()

        class _Rep:
            alive = True
            rank = 0

            def __init__(self):
                self.engine = _Eng()

        class _Pool:
            replicas = [_Rep()]

        pool = _Pool()
        pool.replicas[0].engine.metrics.windowed_histogram(
            "serve.ttft_ms", interval_s=1.0, intervals=10  # spans 10 s
        )
        with pytest.raises(ValueError, match="lease window"):
            pool_slo_reader(pool, window_s=6.0)()
        assert pool_slo_reader(pool, window_s=10.0)().samples == 0

    def test_pool_slo_reader_merges_alive_replicas(self):
        class _Eng:
            def __init__(self):
                self.metrics = MetricsRegistry()

        class _Rep:
            def __init__(self, alive):
                self.alive = alive
                self.engine = _Eng()

        class _Pool:
            replicas = [_Rep(True), _Rep(True), _Rep(False)]

        pool = _Pool()
        for i, r in enumerate(pool.replicas):
            h = r.engine.metrics.windowed_histogram(
                "serve.ttft_ms", interval_s=1.0, intervals=10
            )
            h.observe(10_000.0 if i > 0 else 1.0)
            r.engine.metrics.counter("serve.admit_blocked").inc(3)
        reading = pool_slo_reader(pool)()
        assert reading.samples == 2  # the dead replica's window is gone
        assert reading.p99_ms > 1_000.0
        assert reading.admit_blocked == 6.0


# ------------------------------------------------- fit + the lease client


class _ToyData:
    def batch_at(self, step):
        tok = np.full((2, 4), float(step + 1))
        return tok, tok


def _toy_step(state, tokens, targets):
    s = int(np.asarray(state["step"]))
    return (
        {"step": np.int64(s + 1),
         "w": np.asarray(state["w"]) - 0.01 * float(tokens.mean())},
        {"loss": float(tokens.mean())},
    )


def _w0():
    return {"step": np.int64(0), "w": np.zeros(4, dtype=np.float64)}


class TestFitLeaseResize:
    def _scripted_client(self, led, script):
        """A TrainLeaseClient whose ledger is mutated by `script` keyed on
        the polling step — the in-process stand-in for the arbiter."""
        client = TrainLeaseClient(led, poll_interval_s=0.0)
        orig = client.poll

        def poll(step):
            for at, (epoch, grants) in list(script.items()):
                if step >= at:
                    led.publish(epoch, grants)
                    del script[at]
            return orig(step)

        client.poll = poll
        return client

    def test_shrink_expand_cycle_is_bitwise_and_loses_no_steps(self, tmp_path):
        led = LeaseLedger(str(tmp_path / "hb"))
        led.publish(0, {"train": (0, 1, 2), "serve": (3,)})
        client = self._scripted_client(led, {
            4: (1, {"train": (0,), "arbiter": (1, 2), "serve": (3,)}),
            8: (2, {"train": (0, 1, 2), "serve": (3,)}),
        })
        seen = []
        client.on_resize = (
            lambda chips, plan: seen.append((chips, plan.to_ft_topo())) or None
        )
        ck = str(tmp_path / "ck")
        res = fit(
            _w0(), _toy_step, _ToyData(),
            FitConfig(num_steps=12, ckpt_dir=ck, ckpt_every=100,
                      log_every=0, prefetch=0),
            arbiter=client,
        )
        assert res.steps_run == 12  # zero lost steps
        epochs = res.report.lease_epochs
        assert [e["epoch"] for e in epochs] == [1, 2]
        assert [len(e["chips"]) for e in epochs] == [1, 3]
        assert all(e["bitwise_resume"] for e in epochs)
        assert [c for c, _ in seen] == [(0,), (0, 1, 2)]
        assert led.acked_epoch("train") == 2
        # the arbitrated run ends bitwise equal to an undisturbed one
        oracle = fit(_w0(), _toy_step, _ToyData(),
                     FitConfig(num_steps=12, log_every=0, prefetch=0))
        assert (np.asarray(res.state["w"]).tobytes()
                == np.asarray(oracle.state["w"]).tobytes())

    def test_resize_without_ckpt_dir_converts_the_live_state(self, tmp_path):
        led = LeaseLedger(str(tmp_path / "hb"))
        led.publish(0, {"train": (0, 1)})
        client = self._scripted_client(
            led, {3: (1, {"train": (0,), "arbiter": (1,)})}
        )
        res = fit(
            _w0(), _toy_step, _ToyData(),
            FitConfig(num_steps=6, log_every=0, prefetch=0),
            arbiter=client,
        )
        assert res.steps_run == 6
        assert [e["bitwise_resume"] for e in res.report.lease_epochs] == [True]

    def test_zero_chip_grant_is_refused_loudly(self, tmp_path):
        led = LeaseLedger(str(tmp_path / "hb"))
        led.publish(0, {"train": (0,)})
        client = self._scripted_client(
            led, {2: (1, {"arbiter": (0,)})}
        )
        with pytest.raises(ValueError, match="zero chips"):
            fit(
                _w0(), _toy_step, _ToyData(),
                FitConfig(num_steps=6, log_every=0, prefetch=0),
                arbiter=client,
            )

    def test_run_report_serializes_lease_epochs(self, tmp_path):
        led = LeaseLedger(str(tmp_path / "hb"))
        led.publish(0, {"train": (0, 1)})
        client = self._scripted_client(
            led, {2: (1, {"train": (0,), "arbiter": (1,)})}
        )
        ck = str(tmp_path / "ck")
        fit(
            _w0(), _toy_step, _ToyData(),
            FitConfig(num_steps=5, ckpt_dir=ck, log_every=0, prefetch=0),
            arbiter=client,
        )
        with open(tmp_path / "ck" / "run_report.json") as f:
            persisted = json.load(f)
        assert persisted["lease_epochs"][0]["bitwise_resume"] is True


# ----------------------------------------------- timeline: the arbiter lane


class TestArbiterTimeline:
    def test_arbiter_kinds_render_on_their_own_lane(self):
        evs = [
            {"ts": 1.0, "rank": 0, "seq": 0, "src": "train",
             "kind": "step_start", "step": 0},
            {"ts": 1.1, "rank": 0, "seq": 1, "src": "train",
             "kind": "step_end", "step": 0},
            {"ts": 1.2, "rank": 0, "seq": 2, "src": "train",
             "kind": "slo_breach", "p99_ms": 900.0, "slo_p99_ms": 100.0},
            {"ts": 1.3, "rank": 0, "seq": 3, "src": "train",
             "kind": "lease_preempt", "chips": [1, 2], "epoch": 1},
            {"ts": 1.5, "rank": 0, "seq": 4, "src": "train",
             "kind": "lease_grant", "chips": [1, 2], "epoch": 2},
            {"ts": 1.6, "rank": 0, "seq": 5, "src": "train",
             "kind": "lease_resize", "step": 4, "epoch": 1,
             "bitwise_resume": True},
            {"ts": 2.0, "rank": 0, "seq": 6, "src": "train",
             "kind": "lease_return", "chips": [1, 2], "epoch": 3},
        ]
        doc = merge_events(evs)
        assert validate_trace(doc) == []
        lanes = {
            e["name"]: e["tid"]
            for e in doc["traceEvents"]
            if e.get("ph") == "i" and e.get("cat") == "arbiter"
        }
        assert set(lanes) == {
            "slo_breach", "lease_preempt", "lease_grant", "lease_resize",
            "lease_return",
        }
        assert set(lanes.values()) == {2}  # the dedicated lane
        thread_names = {
            (e["pid"], e["tid"]): e["args"]["name"]
            for e in doc["traceEvents"]
            if e.get("ph") == "M" and e["name"] == "thread_name"
        }
        assert thread_names[(0, 2)] == "arbiter"
        # the SLO reading rides along for the postmortem
        breach = next(e for e in doc["traceEvents"]
                      if e["name"] == "slo_breach")
        assert breach["args"]["p99_ms"] == 900.0


# -------------------------------------------- pool add/release (needs JAX)


@pytest.fixture(scope="module")
def model():
    import jax

    from flextree_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64
    )
    return cfg, init_params(jax.random.PRNGKey(0), cfg)


def _mk_engine(model):
    from flextree_tpu.serving import (
        BatcherConfig,
        PagedCacheConfig,
        ServingEngine,
    )

    cfg, params = model
    pcfg = PagedCacheConfig(num_blocks=32, block_size=8, blocks_per_seq=6)
    return ServingEngine(params, cfg, pcfg, BatcherConfig(slots=2),
                         slo_window_s=4.0)


def _reqs(n, max_new=12):
    from flextree_tpu.serving import Request

    rng = np.random.default_rng(3)
    return [
        Request(rid=i, prompt=rng.integers(0, 64, (4,)).astype(np.int32),
                max_new_tokens=max_new)
        for i in range(n)
    ]


class TestPoolElasticMembership:
    def test_add_then_release_exactly_once(self, tmp_path, model):
        from flextree_tpu.serving import PoolConfig, ReplicaPool

        pool = ReplicaPool(
            [_mk_engine(model)], PoolConfig(heartbeat_dir=str(tmp_path))
        )
        reqs = _reqs(8)
        for r in reqs[:4]:
            pool.submit(r)
        pool.step()
        assert pool.add_replica(_mk_engine(model)) == 1
        for r in reqs[4:]:
            pool.submit(r)
        pool.step()
        pool.step()
        assert pool.replicas[1].assigned  # the new replica took load
        rerouted = pool.release_replica(1)
        assert rerouted  # mid-decode work went back to the queue
        assert pool.replicas[1].released and not pool.replicas[1].alive
        assert not pool.degraded  # a release is not a degradation
        report = pool.run_until_idle()
        assert report["completed"] == 8
        assert report["released"] == 1 and report["alive"] == 1
        assert not report["rejected"]
        assert sorted(pool.completed) == list(range(8))  # exactly once
        assert pool.reroutes == len(rerouted)
        pool.shutdown()

    def test_release_is_idempotent_and_routes_around(self, tmp_path, model):
        from flextree_tpu.serving import PoolConfig, ReplicaPool

        pool = ReplicaPool(
            [_mk_engine(model), _mk_engine(model)],
            PoolConfig(heartbeat_dir=str(tmp_path)),
        )
        assert pool.release_replica(1) == []
        assert pool.release_replica(1) == []  # second release: no-op
        for r in _reqs(3):
            pool.submit(r)
        pool.run_until_idle()
        assert len(pool.completed) == 3
        assert not pool.replicas[1].assigned  # never routed to
        pool.shutdown()

    def test_parallel_rounds_complete_and_survive_a_kill(
        self, tmp_path, model
    ):
        from flextree_tpu.serving import PoolConfig, ReplicaPool

        pool = ReplicaPool(
            [_mk_engine(model), _mk_engine(model)],
            PoolConfig(heartbeat_dir=str(tmp_path), parallel_rounds=True,
                       step_timeout_s=10.0),
        )
        for r in _reqs(6):
            pool.submit(r)
        pool.step()
        pool.kill(1, mode="raise")
        report = pool.run_until_idle()
        assert report["completed"] == 6  # degraded, not failed
        assert report["degraded"] is True
        assert sorted(pool.completed) == list(range(6))
        pool.shutdown()

    def test_parallel_rounds_propagate_unexpected_exceptions(
        self, tmp_path, model
    ):
        """An exception the suspect machinery doesn't model (not a
        timeout, not a ReplicaFailed) must propagate from the parallel
        round exactly as it does from the sequential one — a swallowed
        error would harvest a broken replica as healthy forever."""
        from flextree_tpu.serving import PoolConfig, ReplicaPool

        pool = ReplicaPool(
            [_mk_engine(model), _mk_engine(model)],
            PoolConfig(heartbeat_dir=str(tmp_path), parallel_rounds=True),
        )
        for r in _reqs(4):
            pool.submit(r)

        def broken_step():
            raise ValueError("cache accounting bug")

        pool.replicas[1].engine.step = broken_step
        with pytest.raises(ValueError, match="cache accounting bug"):
            pool.step()
        pool.shutdown()

    def test_engine_report_carries_the_ttft_window(self, tmp_path, model):
        eng = _mk_engine(model)
        eng.submit(_reqs(1, max_new=2)[0])
        while not eng.idle:
            eng.step()
        payload = eng.report()["histograms"]["serve.ttft_ms"]
        assert payload["count"] == 1
        assert payload["window"]["seconds"] == 4.0
