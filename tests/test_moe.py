"""MoE model + expert parallelism vs the single-device oracle.

Routing is deterministic (greedy argmax, first-come-first-served capacity),
so with capacity high enough that no shard drops tokens, an ep-sharded run
must match the all-experts-local single-device run exactly — the same A/B
oracle discipline as the rest of the suite (SURVEY §4).  Capacity dropping
itself is pinned down directly on ``route_topk``.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute train-step tests (fast subset: -m 'not slow')
from jax.sharding import PartitionSpec as P

from flextree_tpu.models.moe import (
    MoEConfig,
    expert_capacity,
    init_moe_params,
    moe_forward,
    moe_param_specs,
    route_topk,
)
from flextree_tpu.parallel.moe_train import (
    factor_devices_moe,
    init_moe_train_state,
    make_mesh_moe,
    make_moe_train_step,
)
from flextree_tpu.parallel.train import TrainConfig


def _cfg(**kw):
    base = dict(
        vocab_size=64,
        d_model=32,
        n_heads=4,
        n_layers=2,
        d_ff=64,
        n_experts=8,
        top_k=2,
        capacity_factor=8.0,  # no drops at test sizes
        router_aux_weight=0.0,
    )
    base.update(kw)
    return MoEConfig(**base)


def _batch(cfg, b=8, t=32, seed=1):
    rng = np.random.default_rng(seed)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (b, t)), jnp.int32)
    targets = jnp.asarray(rng.integers(0, cfg.vocab_size, (b, t)), jnp.int32)
    return tokens, targets


# ----------------------------------------------------------------- routing


def test_route_topk_shapes_and_mass():
    rng = np.random.default_rng(0)
    probs = jax.nn.softmax(
        jnp.asarray(rng.standard_normal((16, 4)).astype(np.float32)), axis=-1
    )
    dispatch, combine = route_topk(probs, k=2, capacity=16)
    assert dispatch.shape == (16, 4, 16)
    # every token dispatched exactly k times (no drops at this capacity)
    np.testing.assert_array_equal(
        np.asarray(dispatch.sum(axis=(1, 2))), np.full(16, 2.0)
    )
    # combine weights normalized over the k picks
    np.testing.assert_allclose(
        np.asarray(combine.sum(axis=(1, 2))), np.ones(16), rtol=1e-6
    )
    # each (expert, slot) holds at most one token
    assert float(dispatch.sum(axis=0).max()) <= 1.0


def test_route_topk_capacity_drops_in_order():
    """All tokens prefer expert 0; only the first C fit."""
    probs = jnp.tile(jnp.asarray([[0.9, 0.1]], jnp.float32), (8, 1))
    dispatch, combine = route_topk(probs, k=1, capacity=3)
    kept = np.asarray(dispatch[:, 0].sum(axis=1))
    np.testing.assert_array_equal(kept, [1, 1, 1, 0, 0, 0, 0, 0])
    # dropped tokens have zero combine mass
    np.testing.assert_array_equal(
        np.asarray(combine.sum(axis=(1, 2)))[3:], np.zeros(5)
    )


def test_route_topk_distinct_experts_per_token():
    rng = np.random.default_rng(1)
    probs = jax.nn.softmax(
        jnp.asarray(rng.standard_normal((32, 8)).astype(np.float32)), axis=-1
    )
    dispatch, _ = route_topk(probs, k=2, capacity=32)
    per_expert = np.asarray(dispatch.sum(axis=2))  # (S, E)
    assert per_expert.max() <= 1.0  # k picks hit k distinct experts


def test_expert_capacity_static():
    cfg = _cfg(capacity_factor=1.0)
    assert expert_capacity(256, cfg) == 256 * 2 // 8
    assert expert_capacity(1, cfg) == 1


# ----------------------------------------------------- forward equivalence


def test_moe_forward_ep_sharded_matches_single_device():
    cfg = _cfg()
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    tokens, _ = _batch(cfg, b=4)
    ref, aux_ref = moe_forward(params, tokens, cfg)

    mesh = jax.make_mesh((4,), ("ep",))
    fn = jax.jit(
        jax.shard_map(
            lambda p, tok: moe_forward(p, tok, cfg, ep_axis="ep")[0],
            mesh=mesh,
            in_specs=(moe_param_specs(cfg, None, "ep"), P("ep", None)),
            out_specs=P("ep", None),
            check_vma=False,
        )
    )
    out = fn(params, tokens)
    np.testing.assert_allclose(
        np.asarray(jax.device_get(out)), np.asarray(ref), atol=2e-4
    )
    assert np.isfinite(float(aux_ref))


def test_moe_forward_full_mesh_matches_single_device():
    """dp x ep x sp x tp all at once, dense layers interleaved (moe_every=2)."""
    cfg = _cfg(n_layers=4, moe_every=2, n_heads=8)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    tokens, _ = _batch(cfg, b=4)
    ref, _ = moe_forward(params, tokens, cfg)

    mesh = jax.make_mesh((2, 2, 2), ("ep", "sp", "tp"))
    fn = jax.jit(
        jax.shard_map(
            lambda p, tok: moe_forward(
                p, tok, cfg, tp_axis="tp", sp_axis="sp", ep_axis="ep"
            )[0],
            mesh=mesh,
            in_specs=(moe_param_specs(cfg, "tp", "ep"), P("ep", "sp")),
            out_specs=P("ep", "sp"),
            check_vma=False,
        )
    )
    out = fn(params, tokens)
    np.testing.assert_allclose(
        np.asarray(jax.device_get(out)), np.asarray(ref), atol=2e-4
    )


def test_moe_layer_rejects_indivisible_experts():
    cfg = _cfg(n_experts=6)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    tokens, _ = _batch(cfg, b=4)
    mesh = jax.make_mesh((4,), ("ep",))
    with pytest.raises(ValueError, match="divisible"):
        jax.shard_map(
            lambda p, tok: moe_forward(p, tok, cfg, ep_axis="ep")[0],
            mesh=mesh,
            in_specs=(moe_param_specs(cfg, None, None), P("ep", None)),
            out_specs=P("ep", None),
            check_vma=False,
        )(params, tokens)


# ---------------------------------------------------------------- training


def _leaves(tree):
    return [np.asarray(x) for x in jax.tree.leaves(jax.device_get(tree))]


def test_moe_train_step_matches_single_device():
    cfg = _cfg()
    tokens, targets = _batch(cfg)
    state = init_moe_train_state(jax.random.PRNGKey(0), cfg)

    s1, m1 = make_moe_train_step(make_mesh_moe(1, (1, 1, 1, 1)), cfg)(
        state, tokens, targets
    )
    s8, m8 = make_moe_train_step(make_mesh_moe(8, (1, 4, 1, 2)), cfg)(
        state, tokens, targets
    )
    np.testing.assert_allclose(float(m8["loss"]), float(m1["loss"]), rtol=1e-4)
    for a, b in zip(_leaves(s8["params"]), _leaves(s1["params"])):
        np.testing.assert_allclose(a, b, atol=5e-5)


@pytest.mark.parametrize("shape", [(2, 2, 2, 1), (1, 2, 2, 2), (2, 4, 1, 1)])
def test_moe_train_step_mesh_shapes(shape):
    cfg = _cfg(n_heads=4 if shape[3] == 1 else 8)
    tokens, targets = _batch(cfg)
    state = init_moe_train_state(jax.random.PRNGKey(0), cfg)
    s1, m1 = make_moe_train_step(make_mesh_moe(1, (1, 1, 1, 1)), cfg)(
        state, tokens, targets
    )
    s, m = make_moe_train_step(make_mesh_moe(8, shape), cfg)(state, tokens, targets)
    np.testing.assert_allclose(float(m["loss"]), float(m1["loss"]), rtol=1e-4)
    for a, b in zip(_leaves(s["params"]), _leaves(s1["params"])):
        np.testing.assert_allclose(a, b, atol=5e-5)


def test_moe_training_loss_decreases_and_aux_reported():
    cfg = _cfg(router_aux_weight=1e-2)
    tokens, targets = _batch(cfg)
    state = init_moe_train_state(jax.random.PRNGKey(0), cfg)
    step = make_moe_train_step(
        make_mesh_moe(8, (1, 4, 1, 2)), cfg, TrainConfig(lr=3e-3)
    )
    losses, auxes = [], []
    for _ in range(5):
        state, metrics = step(state, tokens, targets)
        losses.append(float(metrics["loss"]))
        auxes.append(float(metrics["aux"]))
    assert losses[-1] < losses[0] - 0.2, losses
    assert all(a > 0 for a in auxes), auxes


def test_moe_train_step_with_tree_grad_topo():
    cfg = _cfg()
    tokens, targets = _batch(cfg)
    state = init_moe_train_state(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh_moe(8, (4, 2, 1, 1))
    s_flat, m_flat = make_moe_train_step(mesh, cfg)(state, tokens, targets)
    s_tree, m_tree = make_moe_train_step(mesh, cfg, TrainConfig(grad_topo="2,2"))(
        state, tokens, targets
    )
    np.testing.assert_allclose(float(m_tree["loss"]), float(m_flat["loss"]), rtol=1e-5)
    for a, b in zip(_leaves(s_tree["params"]), _leaves(s_flat["params"])):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_moe_train_step_validation():
    cfg = _cfg(n_experts=6)
    with pytest.raises(ValueError, match="divisible"):
        make_moe_train_step(make_mesh_moe(8, (1, 4, 1, 2)), cfg)
    cfg = _cfg(top_k=9)
    with pytest.raises(ValueError, match="top_k"):
        make_moe_train_step(make_mesh_moe(8, (1, 4, 1, 2)), cfg)


def test_factor_devices_moe():
    assert factor_devices_moe(8) == (1, 2, 2, 2)
    for n in range(1, 33):
        assert int(np.prod(factor_devices_moe(n))) == n
