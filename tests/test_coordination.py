"""Tier-1 coverage for the coordinated elastic control plane (ISSUE 14):
``runtime.ctrlfile`` torn-proof control files, the ``MembershipView``
wall-clock-regression guard, and the ``runtime.coordination``
propose→ack→commit state machine — driven pure-host with injectable
clocks through randomized interleavings (coordinator death at each
phase, duplicate acks, stale-epoch replays) against the protocol
invariants: epochs strictly increase, at most one commit per epoch, no
rank applies uncommitted state.  The same machinery runs against REAL
processes and signals in ``tools/coord_chaos.py`` (committed
``COORD_CHAOS.json``).
"""

from __future__ import annotations

import json
import os
import random

import numpy as np
import pytest

from flextree_tpu.runtime import coordination as coordination_mod
from flextree_tpu.runtime import supervisor as supervisor_mod
from flextree_tpu.runtime.coordination import (
    ControlDecision,
    CoordinationConfig,
    CoordinationHandle,
    CoordLedger,
    EpochFenced,
    ProtocolViolation,
    committed_shrink_plan,
    decision_fingerprint,
)
from flextree_tpu.runtime.ctrlfile import (
    read_control_json,
    write_control_json,
)
from flextree_tpu.runtime.leases import (
    LeaseLedger,
    ResizeDirective,
    TrainLeaseClient,
)
from flextree_tpu.runtime.supervisor import (
    DEAD,
    MembershipView,
    Supervisor,
    SupervisorConfig,
)


# ------------------------------------------------------------- ctrlfile


class TestControlFiles:
    def test_round_trip(self, tmp_path):
        path = str(tmp_path / "x.json")
        write_control_json(str(tmp_path), path, {"a": 1, "b": [2, 3]})
        assert read_control_json(path) == {"a": 1, "b": [2, 3]}

    def test_absent_reads_none(self, tmp_path):
        assert read_control_json(str(tmp_path / "nope.json")) is None

    def test_truncation_at_every_byte_offset_refused(self, tmp_path):
        """The satellite pin: a control file cut at ANY byte offset must
        parse-refuse — including cuts that leave syntactically valid JSON
        (the exact hole a trailer-less format cannot close)."""
        path = str(tmp_path / "x.json")
        write_control_json(
            str(tmp_path), path, {"epoch": 12, "chips": [0, 1], "w": 1.5}
        )
        raw = (tmp_path / "x.json").read_bytes()
        torn = str(tmp_path / "torn.json")
        for cut in range(len(raw)):  # 0..len-1: every strict prefix
            with open(torn, "wb") as f:
                f.write(raw[:cut])
            assert read_control_json(torn, rereads=0) is None, (
                f"truncation at byte {cut}/{len(raw)} was accepted"
            )

    def test_corrupt_payload_byte_refused(self, tmp_path):
        path = str(tmp_path / "x.json")
        write_control_json(str(tmp_path), path, {"epoch": 3})
        raw = bytearray((tmp_path / "x.json").read_bytes())
        raw[2] ^= 0xFF  # flip one payload byte: CRC must catch it
        with open(path, "wb") as f:
            f.write(raw)
        assert read_control_json(path, rereads=0) is None

    def test_trailerless_plain_json_refused(self, tmp_path):
        """A bare JSON file (hand-written, or a truncation that cut the
        trailer off cleanly) is refused — accepting it would re-open the
        clean-cut hole."""
        path = tmp_path / "legacy.json"
        path.write_text('{"epoch": 5}\n')
        assert read_control_json(str(path), rereads=0) is None

    def test_mismatch_rereads_then_reports_torn(self, tmp_path):
        """A persistent mismatch re-reads (transient with atomic writers)
        and then records a ``torn_control_file`` flight event instead of
        raising on the polling thread."""
        from flextree_tpu.obs import flight_recorder

        path = tmp_path / "x.json"
        path.write_text('{"epoch": 5}')  # no trailer: permanently torn
        reads = {"n": 0}

        def counting_sleep(_s):
            reads["n"] += 1

        with flight_recorder(str(tmp_path / "obs"), rank=0) as rec:
            out = read_control_json(
                str(path), rereads=2, _sleep=counting_sleep
            )
            assert out is None
            # static content short-circuits the re-read loop: one sleep,
            # then the identical second read proves nobody is mid-write
            assert reads["n"] == 1
            # and the torn report is EDGE-detected: a second read of the
            # same stuck file must not record a second event
            assert read_control_json(
                str(path), rereads=2, _sleep=counting_sleep
            ) is None
            kinds = [e["kind"] for e in rec.events]
        assert kinds.count("torn_control_file") == 1

    def test_human_readable_first_line(self, tmp_path):
        """`head -1 file` stays the debugging story."""
        path = str(tmp_path / "x.json")
        write_control_json(str(tmp_path), path, {"epoch": 7})
        first = (tmp_path / "x.json").read_text().splitlines()[0]
        assert json.loads(first) == {"epoch": 7}

    def test_no_tmp_leftovers(self, tmp_path):
        write_control_json(str(tmp_path), str(tmp_path / "x.json"), {"a": 1})
        assert [p for p in os.listdir(tmp_path) if p.endswith(".tmp")] == []


# --------------------------------------------- clock-regression guard


def _beat(dir, rank, wall, step=0):
    write_control_json(
        dir,
        os.path.join(dir, f"hb_{rank:05d}.json"),
        {"rank": rank, "pid": 1, "step": step, "ewma_ms": None,
         "wall": wall, "beats": step},
    )


class TestClockRegression:
    def test_backwards_wall_does_not_resurrect_expired_rank(
        self, tmp_path, monkeypatch
    ):
        now = {"t": 1000.0}
        monkeypatch.setattr(supervisor_mod, "_wall", lambda: now["t"])
        d = str(tmp_path)
        view = MembershipView(d, lease_s=3.0)
        _beat(d, 1, wall=1000.0)
        assert view.poll()[1].state != DEAD
        now["t"] = 1010.0  # lease long expired
        assert view.poll()[1].state == DEAD
        # an NTP-stepped beat claims a FUTURE-then-past wall… here a beat
        # stamped before the watermark must not resurrect the rank
        _beat(d, 1, wall=999.0, step=5)
        assert view.poll()[1].state == DEAD

    def test_backwards_wall_does_not_extend_live_lease(
        self, tmp_path, monkeypatch
    ):
        now = {"t": 1000.0}
        monkeypatch.setattr(supervisor_mod, "_wall", lambda: now["t"])
        d = str(tmp_path)
        view = MembershipView(d, lease_s=3.0, straggler_s=1.0)
        _beat(d, 1, wall=1000.0)
        view.poll()
        # the clock steps back 100 s but beats keep coming with the stale
        # stamp: ages are computed against the 1000.0 watermark, so the
        # rank expires on schedule instead of riding a 100 s extension
        _beat(d, 1, wall=900.0, step=3)
        now["t"] = 1004.0
        assert view.poll()[1].state == DEAD

    def test_regression_records_loud_event_once(self, tmp_path, monkeypatch):
        from flextree_tpu.obs import flight_recorder

        now = {"t": 1000.0}
        monkeypatch.setattr(supervisor_mod, "_wall", lambda: now["t"])
        d = str(tmp_path)
        view = MembershipView(d, lease_s=30.0)
        _beat(d, 1, wall=1000.0)
        view.poll()
        with flight_recorder(str(tmp_path / "obs"), rank=0) as rec:
            _beat(d, 1, wall=990.0, step=1)
            view.poll()
            _beat(d, 1, wall=991.0, step=2)  # still behind: same episode
            view.poll()
            events = [e for e in rec.events if e["kind"] == "clock_regression"]
        assert len(events) == 1
        assert events[0]["peer"] == 1
        assert events[0]["regression_s"] == pytest.approx(10.0)

    def test_normal_forward_clock_never_fires_event(
        self, tmp_path, monkeypatch
    ):
        from flextree_tpu.obs import flight_recorder

        now = {"t": 1000.0}
        monkeypatch.setattr(supervisor_mod, "_wall", lambda: now["t"])
        d = str(tmp_path)
        view = MembershipView(d)
        with flight_recorder(str(tmp_path / "obs"), rank=0) as rec:
            for i in range(5):
                _beat(d, 1, wall=1000.0 + i, step=i)
                now["t"] = 1000.0 + i
                view.poll()
            kinds = [e["kind"] for e in rec.events]
        assert "clock_regression" not in kinds


# ---------------------------------------------------------- the ledger


class TestCoordLedger:
    def test_epochs_strictly_increase(self, tmp_path):
        led = CoordLedger(str(tmp_path))
        d0 = ControlDecision(0, "replan", {"topo": "4"}, (0, 1), 0)
        led.publish_proposal(d0, ack_deadline_wall=10.0)
        with pytest.raises(ProtocolViolation, match="must increase"):
            led.publish_proposal(
                ControlDecision(0, "replan", {"topo": "2,2"}, (0, 1), 0),
                ack_deadline_wall=10.0,
            )
        assert led.next_epoch() == 1

    def test_commit_idempotent_same_content_only(self, tmp_path):
        led = CoordLedger(str(tmp_path))
        d0 = ControlDecision(3, "replan", {"topo": "4"}, (0, 1), 0)
        assert led.publish_commit(d0) is True
        assert led.publish_commit(d0) is False  # the failover race: no-op
        with pytest.raises(ProtocolViolation, match="two decisions"):
            led.publish_commit(
                ControlDecision(3, "replan", {"topo": "2,2"}, (0, 1), 0)
            )
        with pytest.raises(ProtocolViolation, match="backwards"):
            led.publish_commit(
                ControlDecision(2, "replan", {"topo": "4"}, (0, 1), 0)
            )

    def test_torn_proposal_reads_absent(self, tmp_path):
        led = CoordLedger(str(tmp_path))
        (tmp_path / "coord_proposal.json").write_text('{"epoch": 9')
        assert led.read_proposal() is None
        assert led.next_epoch() == 0

    def test_acks_scan(self, tmp_path):
        led = CoordLedger(str(tmp_path))
        led.ack(0, 4)
        led.ack(2, 3)
        (tmp_path / "coord_ack_00007.json").write_text("{garbage")
        assert led.read_acks() == {0: 4, 2: 3}

    def test_fingerprint_stable_and_content_sensitive(self):
        a = decision_fingerprint("replan", {"topo": "4", "x": 1})
        b = decision_fingerprint("replan", {"x": 1, "topo": "4"})
        c = decision_fingerprint("replan", {"topo": "2,2", "x": 1})
        assert a == b != c


# ------------------------------------------------- handshake machine


def _handles(dir, members, n=3, cfg=None, sleep=None):
    return [
        CoordinationHandle(
            dir, r, membership=lambda: dict(members), cfg=cfg,
            _sleep=sleep or (lambda s: None),
        )
        for r in range(n)
    ]


class TestHandshake:
    def test_happy_path_apply_at_boundary(self, tmp_path):
        members = {r: "healthy" for r in range(3)}
        hs = _handles(str(tmp_path), members)
        ep = hs[0].propose("replan", {"topo": "3"}, apply_step=5)
        assert ep == 0
        for h in hs[1:]:
            assert h.gate(step=2) is None  # ack, no apply yet
        assert hs[0].gate(step=2) is None  # all acks in -> commit
        for h in hs:
            assert h.gate(step=4) is None  # before the boundary: held
            dec = h.gate(step=5)
            assert dec is not None and dec.epoch == ep
            h.mark_applied(dec)
        assert [h.applied for h in hs] == [[0], [0], [0]]

    def test_followers_never_propose(self, tmp_path):
        members = {r: "healthy" for r in range(3)}
        hs = _handles(str(tmp_path), members)
        assert hs[1].propose("replan", {"topo": "3"}) is None
        assert hs[0].ledger.read_proposal() is None

    def test_one_decision_at_a_time(self, tmp_path):
        members = {r: "healthy" for r in range(2)}
        hs = _handles(str(tmp_path), members, n=2)
        assert hs[0].propose("replan", {"topo": "2"}) == 0
        assert hs[0].propose("replan", {"topo": "ring"}) is None  # slot busy

    def test_duplicate_acks_harmless(self, tmp_path):
        members = {r: "healthy" for r in range(2)}
        hs = _handles(str(tmp_path), members, n=2)
        ep = hs[0].propose("replan", {"topo": "2"})
        for _ in range(4):
            hs[1].gate(step=0)  # re-gating re-acks at most once per epoch
        assert hs[1].ledger.read_acks()[1] == ep
        assert hs[0].gate(step=0) is None  # commit
        d0, d1 = hs[0].gate(step=1), hs[1].gate(step=1)
        hs[0].mark_applied(d0)
        hs[1].mark_applied(d1)
        # replayed commit reads must not re-apply
        assert hs[1].gate(step=2) is None

    def test_double_apply_refused(self, tmp_path):
        members = {0: "healthy"}
        (h,) = _handles(str(tmp_path), members, n=1)
        h.propose("replan", {"topo": "1"})
        h.gate(step=0)
        dec = h.gate(step=1)
        h.mark_applied(dec)
        with pytest.raises(ProtocolViolation, match="double-apply"):
            h.mark_applied(dec)

    def test_coordinator_death_before_any_ack_reproposes(
        self, tmp_path, monkeypatch
    ):
        """Kill at phase=propose: rank 0 writes the proposal and dies
        before anyone acks; past the deadline the successor excludes it
        and re-proposes for the survivors."""
        now = {"t": 100.0}
        monkeypatch.setattr(coordination_mod, "_wall", lambda: now["t"])
        members = {r: "healthy" for r in range(3)}
        cfg = CoordinationConfig(ack_timeout_s=5.0)
        hs = _handles(str(tmp_path), members, cfg=cfg)
        ep = hs[0].propose("replan", {"topo": "3"})
        # wipe rank 0's self-ack: it died before the ack landed
        os.unlink(tmp_path / "coord_ack_00000.json")
        members[0] = "dead"
        assert hs[1].gate(step=0) is None  # acks; rank 0's ack missing
        assert hs[2].gate(step=0) is None
        now["t"] += 10.0  # past the ack deadline
        assert hs[1].gate(step=0) is None  # successor re-proposes (epoch+1)
        prop, _dl = hs[1].ledger.read_proposal()
        assert prop.epoch == ep + 1
        assert prop.coordinator == 1
        assert 0 not in prop.participants
        assert prop.fingerprint == decision_fingerprint(
            "replan", {"topo": "3"}
        )
        assert hs[2].gate(step=0) is None  # ack the re-proposal
        assert hs[1].gate(step=0) is None  # commit
        d1, d2 = hs[1].gate(step=1), hs[2].gate(step=1)
        assert d1.epoch == d2.epoch == ep + 1
        assert d1.fingerprint == d2.fingerprint

    def test_coordinator_death_after_acks_successor_completes(
        self, tmp_path
    ):
        """Kill at phase=ack-collected: every ack (incl. the dead
        coordinator's self-ack) is on disk; the successor COMPLETES the
        in-flight commit at the SAME epoch instead of re-proposing."""
        members = {r: "healthy" for r in range(3)}
        hs = _handles(str(tmp_path), members)
        ep = hs[0].propose("replan", {"topo": "ring"})
        assert hs[1].gate(step=0) is None
        assert hs[2].gate(step=0) is None
        members[0] = "dead"  # dies with all acks in, commit unwritten
        assert hs[1].gate(step=0) is None  # successor completes
        commit = hs[1].ledger.read_commit()
        assert commit is not None and commit.epoch == ep
        d1, d2 = hs[1].gate(step=1), hs[2].gate(step=1)
        hs[1].mark_applied(d1)
        hs[2].mark_applied(d2)
        assert hs[1].applied == hs[2].applied == [ep]

    def test_coordinator_death_after_commit_is_just_applied(self, tmp_path):
        """Kill at phase=commit: the commit is on disk; survivors apply it
        with no successor action needed (and none taken twice)."""
        members = {r: "healthy" for r in range(3)}
        hs = _handles(str(tmp_path), members)
        ep = hs[0].propose("replan", {"topo": "3"})
        assert hs[1].gate(step=0) is None
        assert hs[2].gate(step=0) is None
        assert hs[0].gate(step=0) is None  # commit written
        members[0] = "dead"
        d1, d2 = hs[1].gate(step=1), hs[2].gate(step=1)
        assert d1.epoch == d2.epoch == ep
        hs[1].mark_applied(d1)
        hs[2].mark_applied(d2)
        # the commit slot stays sealed: nothing new in flight
        assert hs[1].gate(step=2) is None

    def test_recovered_coordinator_drives_foreign_proposal(self, tmp_path):
        """A straggling rank 0 recovers to healthy while the successor's
        proposal is mid-handshake: the CURRENT coordinator (rank 0 again)
        must drive the foreign proposal to commit — deferring to the
        live-but-demoted owner (who stopped driving the moment it lost
        coordinatorship) would deadlock the slot forever."""
        members = {0: "straggler", 1: "healthy", 2: "healthy"}
        hs = _handles(str(tmp_path), members)
        ep = hs[1].propose("replan", {"topo": "3"})  # rank 1 coordinates
        assert ep == 0
        members[0] = "healthy"  # rank 0 recovers mid-handshake
        assert hs[2].gate(step=0) is None  # acks
        assert hs[0].gate(step=0) is None  # acks + drives to commit
        commit = hs[0].ledger.read_commit()
        assert commit is not None and commit.epoch == ep
        for h in hs:
            dec = h.gate(step=1)
            assert dec is not None and dec.epoch == ep
            h.mark_applied(dec)

    def test_stalled_follower_excluded_then_fenced(
        self, tmp_path, monkeypatch
    ):
        """SIGSTOP signature: rank 2 misses the ack deadline, the decision
        re-proposes for the ranks that acked, and the resumed rank finds
        itself fenced by the epoch instead of training on a stale plan."""
        now = {"t": 100.0}
        monkeypatch.setattr(coordination_mod, "_wall", lambda: now["t"])
        members = {r: "healthy" for r in range(3)}
        cfg = CoordinationConfig(ack_timeout_s=5.0)
        hs = _handles(str(tmp_path), members, cfg=cfg)
        hs[0].propose("replan", {"topo": "3"})
        assert hs[1].gate(step=0) is None  # acks; rank 2 is frozen
        now["t"] += 6.0  # rank 2 silent past the deadline
        members[2] = "straggler"  # stale beat, lease not expired
        assert hs[0].gate(step=0) is None  # re-propose without rank 2
        assert hs[1].gate(step=0) is None  # ack epoch 1
        assert hs[0].gate(step=0) is None  # commit epoch 1
        d0, d1 = hs[0].gate(step=1), hs[1].gate(step=1)
        hs[0].mark_applied(d0)
        hs[1].mark_applied(d1)
        with pytest.raises(EpochFenced, match="excluded"):
            hs[2].gate(step=1)  # resumed: the epoch moved past it

    def test_fence_fires_guaranteed_dump(self, tmp_path):
        from flextree_tpu.obs import flight_recorder

        members = {0: "healthy", 1: "healthy"}
        hs = _handles(str(tmp_path), members, n=2)
        # a commit that excludes rank 1 entirely
        hs[0].ledger.publish_commit(
            ControlDecision(0, "shrink", {"alive": 1}, (0,), 0)
        )
        with flight_recorder(str(tmp_path / "obs"), rank=1) as rec:
            with pytest.raises(EpochFenced):
                hs[1].gate(step=0)
            assert rec.dumps == 1
        with open(rec.dump_path) as f:
            dump = json.load(f)
        assert dump["reason"] == "coord_fence"

    def test_abandoned_boundary_raises_typed(self, tmp_path, monkeypatch):
        """A rank that acked a boundary whose decision never resolves
        (every peer gone) raises CoordinationAbandoned, not a hang."""
        now = {"t": 100.0}
        monkeypatch.setattr(coordination_mod, "_wall", lambda: now["t"])
        members = {0: "dead", 1: "healthy", 2: "dead"}

        cfg = CoordinationConfig(resolve_timeout_s=10.0, ack_timeout_s=5.0)
        h1 = CoordinationHandle(
            str(tmp_path), 1, membership=lambda: dict(members), cfg=cfg,
            _sleep=lambda s: now.__setitem__("t", now["t"] + 1.0),
        )
        led = CoordLedger(str(tmp_path))
        # a proposal from rank 0 naming ONLY ranks 0 and 2 as still-needed
        # ackers — rank 1 acks, then nobody is left to commit or re-propose
        led.publish_proposal(
            ControlDecision(
                0, "replan", {"topo": "3"}, (0, 1, 2), 0, apply_step=4
            ),
            ack_deadline_wall=now["t"] + 5.0,
        )
        # rank 1 is the only healthy member => IS the coordinator and
        # would normally resolve it itself; disable its driver to model
        # the partition where no rank can resolve the decision
        monkeypatch.setattr(type(h1), "_drive", lambda self, prop: None)
        assert h1.gate(step=0) is None  # acks, boundary at 4
        with pytest.raises(coordination_mod.CoordinationAbandoned):
            h1.gate(step=4)

    def test_stale_epoch_replay_rejected(self, tmp_path):
        """A replayed (duplicate) proposal file at an old epoch cannot
        regress the protocol: the ledger refuses the write."""
        led = CoordLedger(str(tmp_path))
        led.publish_commit(ControlDecision(5, "replan", {"t": 1}, (0,), 0))
        with pytest.raises(ProtocolViolation):
            led.publish_proposal(
                ControlDecision(4, "replan", {"t": 0}, (0,), 0),
                ack_deadline_wall=0.0,
            )


# ----------------------------------------- randomized interleavings


class TestRandomizedInterleavings:
    @pytest.mark.parametrize("seed", range(8))
    def test_invariants_under_random_schedules_and_kills(
        self, tmp_path, monkeypatch, seed
    ):
        """Drive N handles in random order with a random coordinator kill
        at a random point (possibly never) and assert the invariants on
        quiescence: every surviving non-fenced rank applied the SAME
        epoch sequence ending at the final commit, each epoch at most
        once, and the commit fingerprint matches the proposal's."""
        rng = random.Random(seed)
        n = rng.choice([3, 4, 5])
        now = {"t": 1000.0}
        monkeypatch.setattr(coordination_mod, "_wall", lambda: now["t"])
        members = {r: "healthy" for r in range(n)}
        cfg = CoordinationConfig(ack_timeout_s=5.0)
        hs = _handles(str(tmp_path / f"s{seed}"), members, n=n, cfg=cfg)
        payload = {"topo": "3", "seed": seed}
        hs[0].propose("replan", payload)
        kill_at = rng.choice([None, 0, 1, 2, 3, 5, 8])
        fenced: set[int] = set()
        applied: dict[int, list] = {r: [] for r in range(n)}
        for tick in range(60):
            if tick == kill_at:
                members[0] = "dead"
                # a kill can land before the self-ack flushed: drop it
                # half the time to model both interleavings
                ackf = tmp_path / f"s{seed}" / "coord_ack_00000.json"
                if rng.random() < 0.5 and ackf.exists():
                    os.unlink(ackf)
            order = [r for r in range(n) if members[r] == "healthy"]
            rng.shuffle(order)
            for r in order:
                if r in fenced:
                    continue
                try:
                    dec = hs[r].gate(step=tick)
                except EpochFenced:
                    fenced.add(r)
                    continue
                if dec is not None:
                    hs[r].mark_applied(dec)
                    applied[r].append((dec.epoch, dec.fingerprint))
            now["t"] += 1.0
        survivors = [
            r for r in range(n)
            if members[r] == "healthy" and r not in fenced
        ]
        assert survivors, "every rank died or was fenced"
        commit = hs[survivors[0]].ledger.read_commit()
        assert commit is not None, "the decision never committed"
        assert commit.fingerprint == decision_fingerprint("replan", payload)
        seqs = {tuple(applied[r]) for r in survivors}
        assert len(seqs) == 1, f"divergent apply sequences: {seqs}"
        (seq,) = seqs
        assert seq, "survivors never applied the committed decision"
        epochs = [e for e, _ in seq]
        assert epochs == sorted(set(epochs)), "double-applied an epoch"
        assert epochs[-1] == commit.epoch

    def test_torn_control_files_mid_handshake(self, tmp_path):
        """An adversarial scribbler truncating the proposal/commit between
        every tick never wedges or corrupts the protocol — the CRC refuses
        the torn read and the atomic replace restores the truth."""
        rng = random.Random(42)
        members = {r: "healthy" for r in range(3)}
        d = str(tmp_path)
        hs = _handles(d, members)
        hs[0].propose("replan", {"topo": "3"})
        applied = {r: [] for r in range(3)}
        for tick in range(30):
            for name in ("coord_proposal.json", "coord_commit.json"):
                path = os.path.join(d, name)
                if rng.random() < 0.4 and os.path.exists(path):
                    with open(path, "rb") as f:
                        raw = f.read()
                    cut = rng.randrange(1, len(raw))
                    with open(path + ".torn", "wb") as f:
                        f.write(raw[:cut])
                    os.replace(path + ".torn", path)
                    # the torn slot heals on the next publish below; also
                    # model the writer re-publishing (atomic replace)
                    with open(path, "wb") as f:
                        f.write(raw)
            for r in range(3):
                dec = hs[r].gate(step=tick)
                if dec is not None:
                    hs[r].mark_applied(dec)
                    applied[r].append(dec.epoch)
        assert applied[0] == applied[1] == applied[2]
        assert len(applied[0]) == 1


# ------------------------------------------------- coordinated leases


class TestCoordinatedLeases:
    def _sole(self, dir):
        """A single-member handle: always the coordinator."""
        return CoordinationHandle(str(dir), 0, membership=None)

    def test_grant_change_proposes_instead_of_directing(self, tmp_path):
        ledger = LeaseLedger(str(tmp_path))
        ledger.publish(0, {"train": (0, 1, 2, 3)})
        handle = self._sole(tmp_path)
        client = TrainLeaseClient(
            ledger, initial_chips=(0, 1, 2, 3), coordination=handle,
            poll_interval_s=0.0,
        )
        assert client.poll(0) is None  # adopts epoch 0
        ledger.publish(1, {"train": (0, 1), "arbiter": (2, 3)})
        assert client.poll(1) is None  # proposed, NOT directed
        prop, _ = handle.ledger.read_proposal()
        assert prop.kind == "resize"
        assert prop.payload["lease_epoch"] == 1
        assert prop.payload["chips"] == [0, 1]
        # the commit delivers the directive through fit's gate; the
        # client acks with the control epoch stamped
        assert handle.gate(step=2) is None  # self-ack -> commit
        dec = handle.gate(step=2)
        assert dec is not None and dec.kind == "resize"
        directive = ResizeDirective(
            epoch=dec.payload["lease_epoch"],
            chips=tuple(dec.payload["chips"]),
            control_epoch=dec.epoch,
        )
        client.ack(directive)
        handle.mark_applied(dec)
        assert ledger.acked_epoch("train") == 1
        assert ledger.acked_control_epoch("train") == dec.epoch

    def test_ack_without_control_epoch_fenced(self, tmp_path):
        ledger = LeaseLedger(str(tmp_path))
        ledger.publish(0, {"train": (0, 1)})
        client = TrainLeaseClient(
            ledger, initial_chips=(0, 1), coordination=self._sole(tmp_path)
        )
        with pytest.raises(ProtocolViolation, match="control epoch"):
            client.ack(ResizeDirective(epoch=1, chips=(0,)))

    def test_uncoordinated_client_unchanged(self, tmp_path):
        ledger = LeaseLedger(str(tmp_path))
        ledger.publish(0, {"train": (0, 1)})
        client = TrainLeaseClient(
            ledger, initial_chips=(0, 1), poll_interval_s=0.0
        )
        ledger.publish(1, {"train": (0,), "arbiter": (1,)})
        directive = client.poll(0)
        assert directive is not None and directive.chips == (0,)
        client.ack(directive)  # no control epoch required
        assert ledger.acked_epoch("train") == 1
        assert ledger.acked_control_epoch("train") is None


# ---------------------------------------------- coordinated feedback


class TestCoordinatedFeedback:
    def _controller(self, tmp_path, handle, timer):
        from flextree_tpu.planner.cost_model import TpuCostParams, LinkParams
        from flextree_tpu.planner.feedback import (
            FeedbackConfig,
            FeedbackController,
        )

        # deliberately wrong constants so one probe round breaches the band
        skewed = TpuCostParams(
            ici=LinkParams(bandwidth_GBps=1e-3, latency_us=5000.0),
            launch_us=5000.0,
        )
        return FeedbackController(
            4,
            1 << 20,
            FeedbackConfig(
                every_k=1, band=0.5, min_window=2, min_samples=4,
                window=8,
            ),
            params=skewed,
            coordination=handle,
            timer=timer,
        )

    def test_follower_never_probes(self, tmp_path):
        from flextree_tpu.obs import flight_recorder

        members = {0: "healthy", 1: "healthy"}
        follower = CoordinationHandle(
            str(tmp_path), 1, membership=lambda: dict(members)
        )

        def exploding_timer(probes, n):  # pragma: no cover - must not run
            raise AssertionError("follower probed")

        ctl = self._controller(tmp_path, follower, exploding_timer)
        with flight_recorder(str(tmp_path / "obs"), rank=1):
            assert ctl.maybe_tick(10) is None
        assert ctl.ticks == 0

    def test_coordinator_drift_proposes_group_replan(self, tmp_path):
        from flextree_tpu.obs import flight_recorder

        handle = CoordinationHandle(str(tmp_path), 0, membership=None)
        ctl = self._controller(
            tmp_path, handle, lambda probes, n: [1e-4] * len(probes)
        )
        with flight_recorder(str(tmp_path / "obs"), rank=0):
            out = None
            for step in range(1, 6):
                out = ctl.tick(step)
                if handle.ledger.read_proposal() is not None:
                    break
            assert out is None  # propose-only: nothing applied locally
            prop, _ = handle.ledger.read_proposal()
            assert prop.kind == "replan"
            assert "params" in prop.payload and "topo" in prop.payload
            assert ctl.refits == 1

            # the commit round-trips into the identical apply every rank runs
            assert handle.gate(step=10) is None
            dec = handle.gate(step=10)
            assert dec is not None
            applied = ctl.apply_committed(dec.payload, step=10)
        assert applied.plan.to_ft_topo() == dec.payload["topo"]
        assert applied.params.ici.bandwidth_GBps == pytest.approx(
            dec.payload["params"]["ici_bandwidth_GBps"]
        )

    def test_apply_committed_follows_broadcast_spec(self, tmp_path):
        """A rank whose local chooser disagrees with the broadcast winner
        follows the group (the override path), never its own plan."""
        from flextree_tpu.planner.calibrate import _params_to_dict
        from flextree_tpu.planner.cost_model import TpuCostParams
        from flextree_tpu.planner.feedback import (
            FeedbackConfig,
            FeedbackController,
        )

        ctl = FeedbackController(4, 1 << 20, FeedbackConfig())
        payload = {
            "params": _params_to_dict(TpuCostParams()),
            "topo": "ring",  # almost surely not the local argmin for n=4
        }
        out = ctl.apply_committed(payload, step=3)
        assert out.plan.to_ft_topo() == "1"  # the ring sentinel spec


# ----------------------------------------------- fit-level wiring


class _ToyData:
    def batch_at(self, step):
        tok = np.full((2, 4), float(step + 1))
        return tok, tok


def _toy_step():
    def step_fn(state, tokens, targets):
        s = int(np.asarray(state["step"]))
        g = float(tokens.mean())
        return (
            {"step": np.int64(s + 1), "w": np.asarray(state["w"]) - 0.01 * g},
            {"loss": g},
        )

    return step_fn


def _w0():
    return {"step": np.int64(0), "w": np.zeros(4, dtype=np.float64)}


class TestFitCoordination:
    def test_committed_shrink_applies_broadcast_plan(self, tmp_path):
        """The fit seam: a confirmed death becomes a PROPOSAL, and the
        shrink applies from the committed payload — survivor count and
        topo from the broadcast, not recomputed ad hoc."""
        from flextree_tpu.parallel.loop import (
            FitConfig,
            Supervision,
            fit,
        )

        calls = {"n": 0}

        def membership():
            calls["n"] += 1
            st = {r: "healthy" for r in range(4)}
            if calls["n"] > 6:
                st[3] = "dead"
            return st

        # a zero ack budget: the fictional peers (this is a one-process
        # test; ranks 1-3 exist only in the membership view) are excluded
        # on the first drive tick and the decision re-proposes for the
        # ranks actually running the protocol — rank 0 alone
        handle = CoordinationHandle(
            str(tmp_path / "hb"), 0, membership=membership,
            cfg=CoordinationConfig(ack_timeout_s=0.0),
        )
        rebuilt = []
        res = fit(
            _w0(), _toy_step(), _ToyData(),
            FitConfig(
                num_steps=10, ckpt_dir=str(tmp_path / "ck"), ckpt_every=2,
                log_every=0, prefetch=0,
            ),
            supervision=Supervision(
                membership=membership, configured_world=4,
                on_shrink=lambda n, plan: rebuilt.append(
                    (n, plan.to_ft_topo())
                ),
                nbytes_hint=1 << 20,
                coordination=handle,
            ),
        )
        assert res.steps_run == 10
        epochs = res.report.membership_epochs
        assert len(epochs) == 2 and epochs[1]["alive"] == 3
        assert epochs[1]["dead"] == [3]
        # the group decision trail: one applied control epoch, kind shrink
        assert len(res.report.control_epochs) == 1
        entry = res.report.control_epochs[0]
        # epoch 1: epoch 0 named the fictional peers, which never acked
        # and were excluded by the zero ack budget's re-proposal
        assert entry["kind"] == "shrink" and entry["epoch"] == 1
        commit = handle.ledger.read_commit()
        assert commit is not None
        assert commit.payload["alive"] == 3
        assert commit.payload["topo"] == epochs[1]["topo"]
        assert rebuilt == [(3, epochs[1]["topo"])]

    def test_committed_shrink_plan_override(self):
        payload = {"alive": 4, "configured": 8, "topo": "ring", "dead": [4]}
        plan = committed_shrink_plan(payload, 1 << 20)
        assert plan.to_ft_topo() == "1"  # the ring sentinel spec
        payload2 = {"alive": 4, "configured": 8, "topo": "2,2", "dead": [4]}
        assert committed_shrink_plan(payload2, 1 << 20).to_ft_topo() == "2,2"


# ------------------------------------------------- timeline lane


class TestTimelineLane:
    def test_coord_kinds_render_on_dedicated_lane(self):
        from flextree_tpu.obs.timeline import merge_events, validate_trace

        events = [
            {"ts": 1.0, "rank": 0, "seq": 0, "kind": "coord_propose",
             "epoch": 0, "decision": "replan"},
            {"ts": 1.1, "rank": 1, "seq": 0, "kind": "coord_ack", "epoch": 0},
            {"ts": 1.2, "rank": 0, "seq": 1, "kind": "coord_commit",
             "epoch": 0},
            {"ts": 1.3, "rank": 1, "seq": 1, "kind": "coord_apply",
             "epoch": 0},
            {"ts": 1.4, "rank": 1, "seq": 2, "kind": "coord_failover",
             "epoch": 1, "dead_coordinator": 0},
            {"ts": 1.5, "rank": 2, "seq": 0, "kind": "coord_fence",
             "epoch": 1},
            {"ts": 1.6, "rank": 2, "seq": 1, "kind": "torn_control_file",
             "path": "coord_commit.json"},
            {"ts": 1.7, "rank": 0, "seq": 2, "kind": "clock_regression",
             "peer": 2},
        ]
        doc = merge_events(events)
        assert validate_trace(doc) == []
        coord = [
            ev for ev in doc["traceEvents"]
            if ev.get("ph") == "i" and ev.get("tid") == 3
        ]
        assert {ev["name"] for ev in coord} == {
            "coord_propose", "coord_ack", "coord_commit", "coord_apply",
            "coord_failover", "coord_fence", "torn_control_file",
            "clock_regression",
        }
        lanes = [
            ev for ev in doc["traceEvents"]
            if ev.get("ph") == "M" and ev.get("tid") == 3
        ]
        assert lanes and all(
            ev["args"]["name"] == "coordination" for ev in lanes
        )


# ---------------------------------------- follower drift in acks (ISSUE 15)


class TestFollowerDrift:
    def test_ack_ships_drift_provider_summary(self, tmp_path):
        members = {r: "healthy" for r in range(3)}
        hs = _handles(str(tmp_path), members)
        summary = {"fp|8|tree|f32|False": {"median": 1.7, "count": 6}}
        hs[1].drift_provider = lambda: summary
        hs[0].propose("replan", {"topo": "3"}, apply_step=9)
        assert hs[1].gate(step=1) is None  # follower acks
        docs = hs[0].ledger.read_ack_docs()
        assert docs[1]["drift"] == summary
        assert docs[1]["epoch"] == 0
        # rank 0 set no provider: its ack carries no drift field
        assert "drift" not in docs[0]

    def test_peer_drift_excludes_self_and_reads_others(self, tmp_path):
        members = {r: "healthy" for r in range(3)}
        hs = _handles(str(tmp_path), members)
        mine = {"k": {"median": 9.0, "count": 4}}
        theirs = {"k": {"median": 2.0, "count": 8}}
        hs[0].drift_provider = lambda: mine
        hs[2].drift_provider = lambda: theirs
        hs[0].propose("replan", {"topo": "3"})
        for h in hs[1:]:
            h.gate(step=1)
        peer = hs[0].peer_drift()
        assert 0 not in peer  # own windows come from the local detector
        assert peer[2] == theirs
        assert 1 not in peer  # rank 1 shipped no summary

    def test_raising_drift_provider_never_blocks_the_ack(self, tmp_path):
        members = {r: "healthy" for r in range(2)}
        hs = _handles(str(tmp_path), members, n=2)
        hs[1].drift_provider = lambda: (_ for _ in ()).throw(
            RuntimeError("detector broken")
        )
        hs[0].propose("replan", {"topo": "2"})
        assert hs[1].gate(step=1) is None  # ack still lands
        assert hs[0].ledger.read_acks()[1] == 0

    def test_controller_registers_detector_summary(self, tmp_path):
        from flextree_tpu.planner.feedback import (
            FeedbackConfig,
            FeedbackController,
        )

        members = {r: "healthy" for r in range(2)}
        hs = _handles(str(tmp_path), members, n=2)
        ctl = FeedbackController(
            8, 1 << 20, FeedbackConfig(), coordination=hs[1],
            timer=lambda p, n: [0.001] * len(p),
        )
        assert hs[1].drift_provider is not None
        assert hs[1].drift_provider() == ctl._detector.summary()

    def test_peer_drift_min_epoch_drops_pre_refit_summaries(self, tmp_path):
        # an ack is written PRE-apply, so after a replan applies, its
        # epoch's summaries describe the corrected world's past — the
        # controller passes applied_epoch + 1 to drop them
        members = {r: "healthy" for r in range(2)}
        hs = _handles(str(tmp_path), members, n=2)
        hs[1].drift_provider = lambda: {"k": {"median": 3.0, "count": 8}}
        hs[0].propose("replan", {"topo": "2"})
        hs[1].gate(step=1)
        assert hs[0].peer_drift(min_epoch=0) == {
            1: {"k": {"median": 3.0, "count": 8}}
        }
        # as if epoch 0 was applied: its ack's summary no longer pools
        assert hs[0].peer_drift(min_epoch=1) == {}
