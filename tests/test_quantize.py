"""Wire codecs + compressed collectives (ISSUE 5 tentpole).

Three contracts, each machine-checked here and in the bench driver:

1. **Identity**: the ``f32`` codec is bitwise-identical to the
   uncompressed allreduce — by value across every topology family x tail
   x chunking, and structurally (the compiled HLO is the same program).
2. **Bound**: ``int8``/``bf16`` results stay inside
   ``Codec.error_bound`` (the documented contract) on every schedule,
   and every rank holds bit-identical results (replica consistency —
   a quantized sync that lets replicas drift corrupts training).
3. **Error feedback**: with the EF residual carried across steps, the
   running mean of a repeated-constant-gradient sync converges to the
   exact gradient at ~1/N (stochastic rounding is keyed off the step
   counter, so this test is fully deterministic).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from flextree_tpu.ops.quantize import (
    CODECS,
    decode_int8,
    encode_int8,
    get_codec,
)
from flextree_tpu.parallel.allreduce import allreduce
from flextree_tpu.parallel.compressed import compressed_allreduce
from flextree_tpu.parallel.mesh import flat_mesh
from flextree_tpu.schedule.stages import LonelyTopology, Topology

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)

N = 8
TOPOS = ["8", "4,2", "2,2,2", "1", "7+1", "3,2+2"]
SIZES = [4096, 4100, 777]  # divisible / +tail / odd+tail


def _run(fn, x, extra=None):
    mesh = flat_mesh(N, "ft")
    in_specs = (P("ft"),) if extra is None else (P("ft"), P())
    f = lambda row, *a: fn(row[0], *a)[None]
    jf = jax.jit(
        jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=P("ft"), check_vma=False
        )
    )
    args = (x,) if extra is None else (x, extra)
    return np.asarray(jax.block_until_ready(jf(*args)))


def _bound_args(topo_spec):
    t = Topology.resolve(N, topo_spec)
    if isinstance(t, LonelyTopology):
        return t.tree.widths, t.lonely
    return t.widths, 0


# ------------------------------------------------------------ codec units


class TestCodecUnits:
    def test_registry(self):
        assert set(CODECS) == {"f32", "bf16", "int8"}
        assert not get_codec("f32").lossy
        assert get_codec(None).name == "f32"
        assert get_codec(get_codec("int8")).name == "int8"
        with pytest.raises(ValueError, match="unsupported codec"):
            get_codec("fp4")

    def test_int8_roundtrip_error_within_one_step(self):
        rng = np.random.default_rng(0)
        v = jnp.asarray(rng.standard_normal(5000).astype(np.float32) * 7)
        q, s = encode_int8(v, step=3)
        out = decode_int8(q, s, v.shape[0])
        # stochastic rounding: error strictly under one quantization step,
        # per block (scale = block amax / 127)
        blocks = np.asarray(jnp.pad(v, (0, q.shape[0] - v.shape[0]))).reshape(-1, 1024)
        scales = np.abs(blocks).max(axis=1) / 127.0
        err = np.abs(np.asarray(out) - np.asarray(v)).reshape(-1)
        per_elem_bound = np.repeat(scales, 1024)[: v.shape[0]] + 1e-7
        assert (err <= per_elem_bound).all()

    def test_int8_deterministic_in_step(self):
        v = jnp.asarray(np.random.default_rng(1).standard_normal(2048), jnp.float32)
        q1, _ = encode_int8(v, step=5)
        q2, _ = encode_int8(v, step=5)
        q3, _ = encode_int8(v, step=6)
        assert np.array_equal(np.asarray(q1), np.asarray(q2))
        assert not np.array_equal(np.asarray(q1), np.asarray(q3))

    def test_zeros_and_pad_are_exact(self):
        v = jnp.zeros(1500, jnp.float32)  # non-block-aligned, all zero
        q, s = encode_int8(v, step=0)
        assert np.asarray(q).max() == 0
        out = decode_int8(q, s, 1500)
        assert out.shape == (1500,) and not np.asarray(out).any()

    def test_roundtrip_maps(self):
        v = jnp.asarray(np.random.default_rng(2).standard_normal(1000), jnp.float32)
        assert np.array_equal(
            np.asarray(get_codec("f32").roundtrip(v)), np.asarray(v)
        )
        bf = get_codec("bf16").roundtrip(v)
        assert bf.dtype == v.dtype
        assert np.abs(np.asarray(bf) - np.asarray(v)).max() <= np.abs(
            np.asarray(v)
        ).max() * 2**-8

    def test_error_bound_hops(self):
        c = get_codec("int8")
        assert c.hops_for(8, (4, 2)) == 3  # 2 RS stages + 1 AG encode
        assert c.hops_for(8, (1,)) == 8  # ring: 7 folds + 1 AG encode
        assert c.hops_for(8, (7,), lonely=1) == 4  # buddy + RS + AG + restore
        assert get_codec("f32").error_bound(10.0, 8, (4, 2)) == 0.0
        assert c.error_bound(1.0, 8, (4, 2)) == pytest.approx(3 * 8 / 127.0)


# ----------------------------------------------- identity codec == allreduce


class TestIdentityCodec:
    @pytest.mark.parametrize("topo", TOPOS)
    @pytest.mark.parametrize("size", SIZES)
    def test_bitwise_identical(self, topo, size):
        x = jnp.asarray(
            np.random.default_rng(0).standard_normal((N, size)).astype(np.float32)
        )
        a = _run(lambda v: compressed_allreduce(v, "ft", topo=topo, codec="f32"), x)
        b = _run(lambda v: allreduce(v, "ft", topo=topo), x)
        assert a.tobytes() == b.tobytes()

    @pytest.mark.parametrize("chunks", [2, 3])
    def test_bitwise_identical_chunked(self, chunks):
        x = jnp.asarray(
            np.random.default_rng(1).standard_normal((N, 4096)).astype(np.float32)
        )
        a = _run(
            lambda v: compressed_allreduce(
                v, "ft", topo="4,2", codec="f32", chunks=chunks
            ),
            x,
        )
        b = _run(lambda v: allreduce(v, "ft", topo="4,2", chunks=chunks), x)
        assert a.tobytes() == b.tobytes()

    def test_compiles_identically(self):
        """Structural half of the identity contract: with the f32 codec
        the compressed entrypoint compiles to the SAME program as the
        plain allreduce (modulo op-name metadata) — the codec layer adds
        literally nothing to the uncompressed path."""
        import re

        mesh = flat_mesh(N, "ft")

        def lower(fn):
            f = lambda row: fn(row[0])[None]
            jf = jax.jit(
                jax.shard_map(
                    f, mesh=mesh, in_specs=P("ft"), out_specs=P("ft"),
                    check_vma=False,
                )
            )
            return jf.lower(jnp.zeros((N, 4096), jnp.float32)).compile().as_text()

        strip = lambda s: re.sub(r'(metadata=\{[^}]*\}|op_name="[^"]*")', "", s)
        plain = strip(lower(lambda v: allreduce(v, "ft", topo="4,2")))
        compressed = strip(
            lower(lambda v: compressed_allreduce(v, "ft", topo="4,2", codec="f32"))
        )
        assert plain == compressed

    def test_residual_is_zero(self):
        x = jnp.asarray(
            np.random.default_rng(2).standard_normal((N, 512)).astype(np.float32)
        )

        def f(v):
            out, res = compressed_allreduce(
                v, "ft", topo="8", codec="f32", return_residual=True
            )
            return jnp.stack([out, res])

        out = _run(f, x)
        assert not out[:, 1].any()


# ------------------------------------------------------- lossy codec bounds


class TestLossyCodecs:
    @pytest.mark.parametrize("codec", ["int8", "bf16"])
    @pytest.mark.parametrize("topo", TOPOS)
    @pytest.mark.parametrize("size", [4096, 777])
    def test_within_documented_bound(self, codec, topo, size):
        rng = np.random.default_rng(7)
        x = jnp.asarray(rng.standard_normal((N, size)).astype(np.float32) * 3)
        out = _run(
            lambda v: compressed_allreduce(v, "ft", topo=topo, codec=codec, step=5),
            x,
        )
        exact = np.asarray(x).astype(np.float64).sum(axis=0)
        widths, lonely = _bound_args(topo)
        bound = get_codec(codec).error_bound(
            float(np.abs(np.asarray(x)).max()), N, widths, lonely
        )
        err = np.abs(out - exact[None]).max()
        assert err <= bound + 1e-5, f"{codec}/{topo}: {err} > {bound}"

    @pytest.mark.parametrize("topo", ["4,2", "1", "7+1"])
    def test_replica_consistency(self, topo):
        """Every rank must hold bit-identical results — replicas that
        drift under a lossy sync silently fork the model."""
        x = jnp.asarray(
            np.random.default_rng(8).standard_normal((N, 2048)).astype(np.float32)
        )
        out = _run(
            lambda v: compressed_allreduce(v, "ft", topo=topo, codec="int8", step=1),
            x,
        )
        for r in range(1, N):
            assert out[0].tobytes() == out[r].tobytes()

    def test_chunked_int8_within_bound(self):
        x = jnp.asarray(
            np.random.default_rng(9).standard_normal((N, 4096)).astype(np.float32)
        )
        out = _run(
            lambda v: compressed_allreduce(
                v, "ft", topo="4,2", codec="int8", chunks=3, step=2
            ),
            x,
        )
        exact = np.asarray(x).astype(np.float64).sum(axis=0)
        bound = get_codec("int8").error_bound(
            float(np.abs(np.asarray(x)).max()), N, (4, 2)
        )
        assert np.abs(out - exact[None]).max() <= bound + 1e-5

    def test_step_changes_rounding(self):
        """Different step counters must draw different stochastic
        rounding — that decorrelation over time is what makes the
        long-run average converge (and it must come from the step
        counter, not from RNG in the trace)."""
        x = jnp.asarray(
            np.random.default_rng(10).standard_normal((N, 2048)).astype(np.float32)
        )
        f = lambda v, s: compressed_allreduce(
            v, "ft", topo="8", codec="int8", step=s
        )
        a = _run(f, x, extra=jnp.int32(3))
        b = _run(f, x, extra=jnp.int32(3))
        c = _run(f, x, extra=jnp.int32(4))
        assert a.tobytes() == b.tobytes()  # deterministic in step
        assert a.tobytes() != c.tobytes()  # decorrelated across steps


# ------------------------------------------------------------ error feedback


class TestErrorFeedback:
    def test_constant_gradient_running_mean_converges(self):
        """The EF contract: sync ``g + e`` compressed, carry ``e' = input
        - C(input)``; the input quantization telescopes exactly and the
        per-hop requantization is unbiased (stochastic rounding keyed off
        the step), so the running mean of the synced gradient converges
        to the exact ``n * g`` at ~1/N.  Deterministic: same steps, same
        bits, every run."""
        rng = np.random.default_rng(3)
        g = rng.standard_normal(2048).astype(np.float32)
        exact = N * g.astype(np.float64)
        bound = get_codec("int8").error_bound(float(np.abs(g).max()), N, (N,))

        def f(v, s):
            out, res = compressed_allreduce(
                v, "ft", topo="8", codec="int8", step=s, return_residual=True
            )
            return jnp.stack([out, res])

        e = np.zeros_like(g)
        acc = np.zeros_like(exact)
        errs = {}
        for step in range(1, 25):
            x = jnp.asarray(np.tile(g + e, (N, 1)))
            out = _run(f, x, extra=jnp.int32(step))
            acc += out[0, 0].astype(np.float64)
            e = out[0, 1]
            errs[step] = np.abs(acc / step - exact).max()
            # the residual never accumulates beyond one quantization step
            assert np.abs(e).max() <= float(np.abs(g + e).max()) / 127.0 + 1e-6
        # single-shot error is within the bound; the running mean shrinks
        # ~1/N below it (measured 0.23 -> 0.0095 over 24 steps; margins 2x)
        assert errs[1] <= bound + 1e-5
        assert errs[24] < errs[1] / 8
        assert errs[24] < bound / 10

    def test_train_state_carries_ef(self):
        from flextree_tpu.models.transformer import TransformerConfig
        from flextree_tpu.parallel.train import (
            TrainConfig,
            init_train_state,
            make_mesh_nd,
            make_train_step,
            state_specs,
        )

        model_cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64
        )
        mesh = make_mesh_nd(8, (2, 2, 2), ("dp", "sp", "tp"))
        tc = TrainConfig(codec="int8")
        state = init_train_state(jax.random.PRNGKey(0), model_cfg, tc)
        assert "ef" in state and "ef" in state_specs(model_cfg, "tp", tc)
        step = make_train_step(mesh, model_cfg, tc)
        tok = jnp.asarray(
            np.random.default_rng(0).integers(0, 64, (4, 32)), jnp.int32
        )
        s1, m1 = jax.block_until_ready(step(state, tok, tok))
        s2, m2 = jax.block_until_ready(step(s1, tok, tok))
        # the residual is live (nonzero) and the step trains
        assert any(np.asarray(l).any() for l in jax.tree.leaves(s2["ef"]))
        assert float(m2["loss"]) < float(m1["loss"])
        # identity codec keeps the historical state layout
        assert "ef" not in init_train_state(
            jax.random.PRNGKey(0), model_cfg, TrainConfig()
        )


# ------------------------------------------------------- sync integration


class TestCompressedSync:
    def test_bucketed_lossy_sync_within_bound_with_residuals(self):
        from flextree_tpu.parallel.train import resolve_axis_topos, sync_grads

        mesh = flat_mesh(N, "dp")
        topos = resolve_axis_topos(mesh, ("dp",), None)
        rng = np.random.default_rng(4)
        tree = {
            f"leaf{i}": jnp.asarray(
                rng.standard_normal((N, 1000 + 7 * i)).astype(np.float32)
            )
            for i in range(5)
        }
        dev_specs = {k: P() for k in tree}
        io_specs = {k: P("dp") for k in tree}

        def make(codec, bucket_bytes, return_residual=False):
            def f(t):
                rows = {k: v[0] for k, v in t.items()}
                out = sync_grads(
                    rows, dev_specs, ("dp",), topos,
                    bucket_bytes=bucket_bytes, codec=codec, step=3,
                    return_residual=return_residual,
                )
                if return_residual:
                    out = {k: jnp.stack([out[0][k], out[1][k]]) for k in rows}
                    return {k: v[None] for k, v in out.items()}
                return {k: v[None] for k, v in out.items()}

            return jax.jit(
                jax.shard_map(
                    f, mesh=mesh, in_specs=(io_specs,), out_specs=io_specs,
                    check_vma=False,
                )
            )

        exact = jax.block_until_ready(make("f32", 0)(tree))
        for bucket_bytes in (0, None):  # per-leaf and bucketed lossy paths
            got = jax.block_until_ready(
                make("int8", bucket_bytes, return_residual=True)(tree)
            )
            for k in tree:
                amax = float(np.abs(np.asarray(tree[k])).max())
                bound = get_codec("int8").error_bound(amax, N, (N,)) + 1e-5
                err = np.abs(
                    np.asarray(got[k])[0, 0].astype(np.float64)
                    - np.asarray(exact[k])[0].astype(np.float64)
                ).max()
                assert err <= bound, (k, bucket_bytes, err, bound)
                # residuals returned and bounded by one quantization step
                res = np.asarray(got[k])[0, 1]
                assert np.abs(res).max() <= amax / 127.0 + 1e-6

    def test_codec_aware_bucket_sizing(self):
        """choose_bucket_bytes must see the codec: cheaper wire bytes
        shift the launch-vs-bytes argmin toward fewer, larger buckets."""
        from flextree_tpu.planner.choose import choose_bucket_bytes

        t = Topology(8, (4, 2))
        plain = choose_bucket_bytes(64 << 20, t, n_leaves=64)
        compressed = choose_bucket_bytes(64 << 20, t, n_leaves=64, codec="int8")
        assert compressed >= plain
