"""Fused paged-attention decode vs the gather-materialize oracle.

The contract (``ops/paged_attention.py``): the block-streaming paths —
pure-JAX twin and Pallas kernel — attend over exactly the positions the
gather path attends over (pool positions ``< length`` plus the new
token at ``length``), differing only in floating-point summation order
(online softmax folds block by block; the oracle reduces the whole
gathered row at once).  So:

- fused output == gather oracle within the pinned ``FUSED_DECODE_ATOL``,
  across impls x chunk sizes x dtypes x ragged lengths (empty rows,
  mid-block, block-aligned, full table);
- the poisoned-null-block invariance — THE masking property the paged
  cache leans on — holds **bitwise** on the fused paths: whatever a
  masked position holds contributes exactly 0.0;
- ``paged_decode_step(fused=True)`` tracks its gather twin within the
  tolerance on logits while producing **bitwise-identical** pool
  scatters (the scatter is shared code, only attention differs).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flextree_tpu.models.transformer import TransformerConfig, init_params
from flextree_tpu.ops.paged_attention import (
    FUSED_DECODE_ATOL,
    paged_attention,
    paged_attention_gather,
)
from flextree_tpu.serving.kv_cache import (
    NULL_BLOCK,
    BlockAllocator,
    PagedCacheConfig,
    init_pools,
    paged_decode_step,
)

S, H, D, N, BS, P = 5, 4, 16, 32, 8, 7
#: ragged mix: empty row, short, block-aligned, mid-block, near-full
LENGTHS = (0, 3, 8, 17, 41)


def _inputs(dtype=jnp.float32, seed=0, lengths=LENGTHS):
    rng = np.random.default_rng(seed)
    q, kn, vn = (
        jnp.asarray(rng.standard_normal((len(lengths), H, D)), dtype)
        for _ in range(3)
    )
    kp, vp = (
        jnp.asarray(rng.standard_normal((N, BS, H, D)), dtype)
        for _ in range(2)
    )
    tables = np.zeros((len(lengths), P), np.int32)
    free = list(range(1, N))
    for s, L in enumerate(lengths):
        n = int(L) // BS + 1  # blocks written + the one the write lands in
        tables[s, :n] = [free.pop() for _ in range(n)]
    return (q, kn, vn, kp, vp, jnp.asarray(tables),
            jnp.asarray(lengths, jnp.int32))


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize(
    "impl,kwargs",
    [
        ("jnp", {"block_chunk": 1}),
        ("jnp", {"block_chunk": 2}),
        ("jnp", {"block_chunk": 4}),
        ("jnp", {"block_chunk": 64}),  # > P: clamped, single fold
        ("pallas", {}),
    ],
)
def test_fused_matches_gather_oracle(dtype, impl, kwargs):
    args = _inputs(dtype)
    ref = paged_attention_gather(*args).astype(jnp.float32)
    out = paged_attention(*args, impl=impl, **kwargs).astype(jnp.float32)
    tol = FUSED_DECODE_ATOL if dtype == jnp.float32 else 0.05
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=tol,
                               rtol=0)


def test_full_table_and_boundary_lengths():
    """A maximally-full row (length == P*bs - 1, the largest value the
    serving layer can reach — a row AT max_len has no room to decode),
    and a length exactly at a block boundary — the off-by-one classes a
    frontier bound can hide."""
    lengths = (P * BS - 1, BS, 2 * BS)
    rng = np.random.default_rng(1)
    q, kn, vn = (
        jnp.asarray(rng.standard_normal((3, H, D)), jnp.float32)
        for _ in range(3)
    )
    kp, vp = (
        jnp.asarray(rng.standard_normal((N, BS, H, D)), jnp.float32)
        for _ in range(2)
    )
    tables = np.zeros((3, P), np.int32)
    free = list(range(1, N))
    tables[0, :] = [free.pop() for _ in range(P)]  # full row
    tables[1, :2] = [free.pop() for _ in range(2)]
    tables[2, :3] = [free.pop() for _ in range(3)]
    args = (q, kn, vn, kp, vp, jnp.asarray(tables),
            jnp.asarray(lengths, jnp.int32))
    ref = paged_attention_gather(*args)
    for impl in ("jnp", "pallas"):
        out = paged_attention(*args, impl=impl)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=FUSED_DECODE_ATOL, rtol=0)


@pytest.mark.parametrize("impl", ["jnp", "pallas"])
def test_poisoned_null_block_invariance_bitwise(impl):
    """The load-bearing masking property: null-block content (including
    values big enough to overflow the score matmul) changes NOTHING —
    bitwise — because masked probabilities are exactly 0.0 and 0.0 * x
    never reaches the accumulator."""
    q, kn, vn, kp, vp, tables, lengths = _inputs()
    poisoned_k = kp.at[NULL_BLOCK].set(1e30)
    poisoned_v = vp.at[NULL_BLOCK].set(1e30)
    a = paged_attention(q, kn, vn, kp, vp, tables, lengths, impl=impl)
    b = paged_attention(q, kn, vn, poisoned_k, poisoned_v, tables, lengths,
                        impl=impl)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_unwritten_tail_of_current_block_is_invisible():
    """Positions >= length inside the partially-written current block are
    masked too — poison them and the fused output must not move."""
    q, kn, vn, kp, vp, tables, lengths = _inputs()
    row = 3  # length 17: block 2 holds 16..23; 16 written, 17.. unwritten
    blk = int(np.asarray(tables)[row, 2])
    # poison from offset 1 = position 17, the FIRST masked position —
    # the exact cell a `kpos <= length` off-by-one would expose
    kp2 = kp.at[blk, 1:].set(1e30)
    vp2 = vp.at[blk, 1:].set(1e30)
    a = paged_attention(q, kn, vn, kp, vp, tables, lengths)
    b = paged_attention(q, kn, vn, kp2, vp2, tables, lengths)
    np.testing.assert_array_equal(np.asarray(a)[row], np.asarray(b)[row])


def test_jnp_and_pallas_agree():
    args = _inputs(seed=2)
    a = paged_attention(*args, impl="jnp", block_chunk=1)
    b = paged_attention(*args, impl="pallas")
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               atol=FUSED_DECODE_ATOL, rtol=0)


def test_shape_validation_is_loud():
    q, kn, vn, kp, vp, tables, lengths = _inputs()
    with pytest.raises(ValueError, match="queries"):
        paged_attention(q[0], kn, vn, kp, vp, tables, lengths)
    with pytest.raises(ValueError, match="new-token"):
        paged_attention(q, kn[:, :2], vn, kp, vp, tables, lengths)
    with pytest.raises(ValueError, match="lengths"):
        paged_attention(q, kn, vn, kp, vp, tables, lengths[:-1])
    with pytest.raises(ValueError, match="impl"):
        paged_attention(q, kn, vn, kp, vp, tables, lengths, impl="cuda")


# ---------------------------------------------------- whole-decode-step level


@pytest.fixture(scope="module")
def model():
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64
    )
    return cfg, init_params(jax.random.PRNGKey(0), cfg)


def _decode_state(cfg, pcfg, lengths, seed=4):
    rng = np.random.default_rng(seed)
    pools = init_pools(cfg, pcfg)
    pools = {
        kind: [
            jnp.asarray(
                rng.standard_normal(p.shape).astype(np.float32), cfg.dtype
            )
            for p in pools[kind]
        ]
        for kind in ("k", "v")
    }
    alloc = BlockAllocator(pcfg.num_blocks)
    tables = np.zeros((len(lengths), pcfg.blocks_per_seq), np.int32)
    for s, L in enumerate(lengths):
        n = int(L) // pcfg.block_size + 1
        tables[s, :n] = alloc.alloc(n)
    tokens = rng.integers(0, cfg.vocab_size, (len(lengths),)).astype(np.int32)
    return pools, jnp.asarray(tables), jnp.asarray(lengths, jnp.int32), tokens


def test_decode_step_fused_vs_gather(model):
    """Logits within tolerance; layer 0's pool scatter is BITWISE (its
    K/V depend only on the embedding, before any attention differs) and
    deeper layers' scatters inherit the attention tolerance through the
    residual stream."""
    cfg, params = model
    pcfg = PagedCacheConfig(num_blocks=24, block_size=8, blocks_per_seq=6)
    pools, tables, lengths, tokens = _decode_state(
        cfg, pcfg, (5, 12, 24, 33)
    )
    ref_logits, ref_pools = paged_decode_step(
        params, pools, tables, lengths, tokens, cfg, fused=False
    )
    for impl in ("jnp", "pallas"):
        logits, out_pools = paged_decode_step(
            params, pools, tables, lengths, tokens, cfg, fused=True, impl=impl
        )
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(ref_logits),
            atol=FUSED_DECODE_ATOL * 10, rtol=0,
        )  # logits pass through 2 more matmul layers than the attention out
        np.testing.assert_array_equal(
            np.asarray(out_pools["k"][0]), np.asarray(ref_pools["k"][0])
        )
        np.testing.assert_array_equal(
            np.asarray(out_pools["v"][0]), np.asarray(ref_pools["v"][0])
        )
        for l in range(1, cfg.n_layers):
            np.testing.assert_allclose(
                np.asarray(out_pools["k"][l]), np.asarray(ref_pools["k"][l]),
                atol=FUSED_DECODE_ATOL, rtol=0,
            )
            np.testing.assert_allclose(
                np.asarray(out_pools["v"][l]), np.asarray(ref_pools["v"][l]),
                atol=FUSED_DECODE_ATOL, rtol=0,
            )


def test_decode_step_fused_greedy_tokens_match_oracle(model):
    """The serving-level consequence: greedy argmax over fused logits
    equals the gather oracle's on this workload (the bench re-checks this
    on every rep of the real load run)."""
    cfg, params = model
    pcfg = PagedCacheConfig(num_blocks=24, block_size=8, blocks_per_seq=6)
    pools, tables, lengths, tokens = _decode_state(
        cfg, pcfg, (3, 9, 20, 40), seed=5
    )
    ref_logits, _ = paged_decode_step(
        params, pools, tables, lengths, tokens, cfg, fused=False
    )
    logits, _ = paged_decode_step(
        params, pools, tables, lengths, tokens, cfg, fused=True
    )
    np.testing.assert_array_equal(
        np.argmax(np.asarray(logits), axis=-1),
        np.argmax(np.asarray(ref_logits), axis=-1),
    )
