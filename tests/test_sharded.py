"""ZeRO-1 sharded-optimizer path (PR 7): the split-collective seam and
the sharded train step.

Contracts pinned here:

1. **Shard layout** (``schedule.blocks.owned_block``): a permutation of
   ``range(N)`` for tree/ring shapes (buddy-mirrored for lonely), and the
   block the real ``reduce_scatter`` actually leaves on each rank.
2. **The seam**: ``all_gather(reduce_scatter(x)) == allreduce(x)``
   BITWISE for the identity codec across flat/tree/ring/lonely and
   non-divisible counts; within the documented codec bound for bf16/int8
   with bit-identical replicas.
3. **The sharded step**: loss + updated params bitwise-equal to the
   replicated step for f32 across dense/pipeline/MoE (and composed with
   the readiness-ordered overlap), with per-rank moment shards that
   consolidate back to exactly the replicated moments.
4. **Error feedback on the sharded wire**: the running mean of a
   repeated-constant-gradient reduce-scatter∘all-gather round converges
   to exact, same as the fused compressed path.
5. **Plan-cache hygiene**: sharded and replicated autotune plans never
   alias (the cache key grows a sharding component).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from flextree_tpu.ops.quantize import get_codec
from flextree_tpu.parallel.allreduce import all_gather, allreduce, reduce_scatter
from flextree_tpu.parallel.mesh import flat_mesh
from flextree_tpu.schedule.blocks import owned_block, shard_layout
from flextree_tpu.schedule.stages import Topology

N = 8

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)

TOPOS = ["8", "4,2", "2,2,2", "1"]
LONELY = ["3,2+1", "6+1"]


def _run(fn, x, n=N):
    mesh = flat_mesh(n, "ft")
    return np.asarray(
        jax.jit(
            jax.shard_map(
                fn, mesh=mesh, in_specs=P("ft"), out_specs=P("ft"),
                check_vma=False,
            )
        )(x)
    )


def _leaves_bytes(tree):
    return b"".join(np.asarray(l).tobytes() for l in jax.tree.leaves(tree))


# ------------------------------------------------------------ shard layout


class TestShardLayout:
    @pytest.mark.parametrize("spec", TOPOS + ["2,4"])
    def test_partition(self, spec):
        lay = shard_layout(Topology.resolve(N, spec))
        assert sorted(lay) == list(range(N))

    def test_lonely_mirror(self):
        lay = shard_layout(Topology.resolve(7, "3,2+1"))
        assert sorted(lay[:6]) == list(range(6))  # tree ranks partition
        assert lay[6] == lay[0]  # lonely rank mirrors buddy 0

    @pytest.mark.parametrize("spec", TOPOS + ["2,4"])
    def test_matches_real_reduce_scatter(self, spec):
        """The contract is about the REAL collective: rank r's
        reduce_scatter output is block ``owned_block(topo, r)`` of the
        exact sum."""
        rng = np.random.default_rng(1)
        data = rng.standard_normal((N, N * 6)).astype(np.float32)
        out = _run(lambda r: reduce_scatter(r[0], "ft", topo=spec)[None],
                   jnp.asarray(data))
        blocks = data.sum(0).reshape(N, 6)
        topo = Topology.resolve(N, spec)
        for r in range(N):
            np.testing.assert_allclose(
                out[r], blocks[owned_block(topo, r)], rtol=1e-5, atol=1e-5
            )


# ------------------------------------------------------------------ seam


class TestSeam:
    @pytest.mark.parametrize("spec", TOPOS)
    @pytest.mark.parametrize("count", [64, 35, 5])
    def test_bitwise_identity_codec(self, spec, count):
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal((N, count)).astype(np.float32))
        ar = _run(lambda r: allreduce(r[0], "ft", topo=spec)[None], x)
        seam = _run(
            lambda r: all_gather(
                reduce_scatter(r[0], "ft", topo=spec), "ft", topo=spec,
                out_shape=r[0].shape,
            )[None],
            x,
        )
        assert ar.tobytes() == seam.tobytes()

    @pytest.mark.parametrize("spec", LONELY)
    @pytest.mark.parametrize("count", [66, 35])
    def test_bitwise_lonely(self, spec, count):
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.standard_normal((7, count)).astype(np.float32))
        ar = _run(lambda r: allreduce(r[0], "ft", topo=spec)[None], x, n=7)
        seam = _run(
            lambda r: all_gather(
                reduce_scatter(r[0], "ft", topo=spec), "ft", topo=spec,
                out_shape=r[0].shape,
            )[None],
            x, n=7,
        )
        assert ar.tobytes() == seam.tobytes()

    @pytest.mark.parametrize("codec", ["bf16", "int8"])
    @pytest.mark.parametrize("spec", TOPOS + LONELY)
    def test_lossy_bounded_and_replica_consistent(self, codec, spec):
        n = 7 if "+" in spec else N
        rng = np.random.default_rng(4)
        x = jnp.asarray(rng.standard_normal((n, 2048)).astype(np.float32) * 2)
        out = _run(
            lambda r: all_gather(
                reduce_scatter(r[0], "ft", topo=spec, codec=codec, step=3),
                "ft", topo=spec, out_shape=r[0].shape, codec=codec, step=3,
            )[None],
            x, n=n,
        )
        exact = np.asarray(x).astype(np.float64).sum(axis=0)
        if "+" in spec:
            widths = Topology.resolve(n, spec).tree.widths
            lonely = 1
        else:
            widths = Topology.resolve(n, spec).widths
            lonely = 0
        # the split round quantizes both wires plus the lonely ship hop:
        # one allreduce bound plus two extra single-encode events covers it
        amax = float(np.abs(np.asarray(x)).max())
        step = 1.0 / 127.0 if codec == "int8" else 2.0 ** -8
        bound = get_codec(codec).error_bound(amax, n, widths, lonely)
        bound += 2 * n * amax * step
        err = np.abs(out[0].astype(np.float64) - exact).max()
        assert err <= bound + 1e-5, f"{codec}/{spec}: {err} > {bound}"
        for r in range(1, n):
            assert out[r].tobytes() == out[0].tobytes()

    def test_all_gather_rejects_bad_shard(self):
        x = jnp.zeros((N, 10), jnp.float32)
        with pytest.raises(ValueError, match="does not match"):
            _run(
                lambda r: all_gather(
                    r[0], "ft", topo="8", out_shape=(999,)
                )[None],
                x,
            )


# ----------------------------------------------------------- sharded step


def _dense_cfg():
    from flextree_tpu.models.transformer import TransformerConfig

    return TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64
    )


class TestShardedStep:
    @pytest.mark.parametrize("topo", [None, "2,2,2", {"dp": "1"}])
    def test_dense_bitwise_vs_replicated(self, topo):
        from flextree_tpu.parallel.train import (
            TrainConfig,
            init_train_state,
            make_mesh_nd,
            make_train_step,
        )

        mesh = make_mesh_nd(8, (2, 2, 2), ("dp", "sp", "tp"))
        tok = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 64)
        outs = {}
        for name, tc in (
            ("rep", TrainConfig(grad_topo=topo)),
            ("sh", TrainConfig(grad_topo=topo, shard_optimizer=True)),
        ):
            st = init_train_state(jax.random.PRNGKey(0), _dense_cfg(), tc, mesh=mesh)
            step = make_train_step(mesh, _dense_cfg(), tc)
            for _ in range(3):
                st, m = step(st, tok, tok)
            outs[name] = (st, float(m["loss"]))
        assert outs["rep"][1] == outs["sh"][1]
        assert _leaves_bytes(outs["rep"][0]["params"]) == _leaves_bytes(
            outs["sh"][0]["params"]
        )

    def test_dense_overlap_composition_bitwise(self):
        from flextree_tpu.parallel.train import (
            TrainConfig,
            init_train_state,
            make_mesh_nd,
            make_train_step,
        )

        mesh = make_mesh_nd(8, (2, 2, 2), ("dp", "sp", "tp"))
        tok = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 64)
        outs = {}
        for name, kw in (
            ("rep", dict()),
            ("sh_ovl", dict(shard_optimizer=True, overlap=True)),
        ):
            tc = TrainConfig(**kw)
            st = init_train_state(jax.random.PRNGKey(0), _dense_cfg(), tc, mesh=mesh)
            step = make_train_step(mesh, _dense_cfg(), tc)
            for _ in range(2):
                st, _ = step(st, tok, tok)
            outs[name] = st
        assert _leaves_bytes(outs["rep"]["params"]) == _leaves_bytes(
            outs["sh_ovl"]["params"]
        )

    def test_pipeline_bitwise_vs_replicated(self):
        from flextree_tpu.parallel.pipeline import (
            init_pipeline_train_state,
            make_mesh_4d,
            make_pipeline_train_step,
        )
        from flextree_tpu.parallel.train import TrainConfig

        mesh = make_mesh_4d(8, (1, 2, 2, 2))
        tok = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 64)
        outs = {}
        for name, tc in (
            ("rep", TrainConfig()),
            ("sh", TrainConfig(shard_optimizer=True)),
        ):
            st = init_pipeline_train_state(
                jax.random.PRNGKey(0), _dense_cfg(), tc, mesh=mesh
            )
            step = make_pipeline_train_step(mesh, _dense_cfg(), tc, n_microbatches=2)
            for _ in range(2):
                st, m = step(st, tok, tok)
            outs[name] = (st, float(m["loss"]))
        assert outs["rep"][1] == outs["sh"][1]
        assert _leaves_bytes(outs["rep"][0]["params"]) == _leaves_bytes(
            outs["sh"][0]["params"]
        )

    def test_moe_bitwise_vs_replicated(self):
        from flextree_tpu.models.moe import MoEConfig
        from flextree_tpu.parallel.moe_train import (
            init_moe_train_state,
            make_mesh_moe,
            make_moe_train_step,
        )
        from flextree_tpu.parallel.train import TrainConfig

        cfg = MoEConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            n_experts=4, top_k=1, moe_every=2,
        )
        mesh = make_mesh_moe(8, (1, 2, 2, 2))
        tok = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 64)
        outs = {}
        for name, tc in (
            ("rep", TrainConfig()),
            ("sh", TrainConfig(shard_optimizer=True)),
        ):
            st = init_moe_train_state(jax.random.PRNGKey(0), cfg, tc, mesh=mesh)
            step = make_moe_train_step(mesh, cfg, tc)
            for _ in range(2):
                st, m = step(st, tok, tok)
            outs[name] = (st, float(m["loss"]))
        assert outs["rep"][1] == outs["sh"][1]
        assert _leaves_bytes(outs["rep"][0]["params"]) == _leaves_bytes(
            outs["sh"][0]["params"]
        )

    def test_moments_consolidate_to_replicated(self):
        """Per-rank moment shards reassemble to EXACTLY the replicated
        path's mu/nu — the strongest form of "the optimizer state is the
        same state, just not duplicated"."""
        from flextree_tpu.models.transformer import init_params, param_specs
        from flextree_tpu.parallel.train import (
            TrainConfig,
            init_train_state,
            make_mesh_nd,
            make_train_step,
            zero_layout_for,
        )
        from flextree_tpu.parallel.zero import make_consolidate_fn, make_reshard_fn

        mesh = make_mesh_nd(8, (2, 2, 2), ("dp", "sp", "tp"))
        tok = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 64)
        states = {}
        for name, tc in (
            ("rep", TrainConfig()),
            ("sh", TrainConfig(shard_optimizer=True)),
        ):
            st = init_train_state(jax.random.PRNGKey(0), _dense_cfg(), tc, mesh=mesh)
            step = make_train_step(mesh, _dense_cfg(), tc)
            for _ in range(2):
                st, _ = step(st, tok, tok)
            states[name] = st
        pspecs = param_specs(_dense_cfg(), "tp")
        shapes = jax.eval_shape(
            lambda k: init_params(k, _dense_cfg()), jax.random.PRNGKey(0)
        )
        layout = zero_layout_for(mesh, shapes, pspecs, ("dp", "sp", "tp"))
        cons = make_consolidate_fn(mesh, pspecs, layout, None, False)(states["sh"])
        assert _leaves_bytes(cons["mu"]) == _leaves_bytes(states["rep"]["mu"])
        assert _leaves_bytes(cons["nu"]) == _leaves_bytes(states["rep"]["nu"])
        # reshard is the exact inverse: consolidate ∘ reshard is a fixed point
        resh = make_reshard_fn(mesh, pspecs, layout, None, False)(cons)
        cons2 = make_consolidate_fn(mesh, pspecs, layout, None, False)(resh)
        assert _leaves_bytes(cons2) == _leaves_bytes(cons)

    def test_lossy_codec_trains_with_master_and_ef(self):
        from flextree_tpu.parallel.train import (
            TrainConfig,
            init_train_state,
            make_mesh_nd,
            make_train_step,
        )

        mesh = make_mesh_nd(8, (2, 2, 2), ("dp", "sp", "tp"))
        tok = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 64)
        tc = TrainConfig(shard_optimizer=True, codec="int8")
        st = init_train_state(jax.random.PRNGKey(0), _dense_cfg(), tc, mesh=mesh)
        assert "master_shard" in st and "ef" in st
        step = make_train_step(mesh, _dense_cfg(), tc)
        losses = []
        for _ in range(3):
            st, m = jax.block_until_ready(step(st, tok, tok))
            losses.append(float(m["loss"]))
        assert all(np.isfinite(l) for l in losses)
        assert all(
            np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(st["params"])
        )
        # the EF residual is live and the master shard is populated
        assert any(np.asarray(l).any() for l in jax.tree.leaves(st["ef"]))
        assert any(
            np.asarray(l).any() for l in jax.tree.leaves(st["master_shard"])
        )

    def test_clipping_close_to_replicated(self):
        from flextree_tpu.parallel.train import (
            TrainConfig,
            init_train_state,
            make_mesh_nd,
            make_train_step,
        )

        mesh = make_mesh_nd(8, (2, 2, 2), ("dp", "sp", "tp"))
        tok = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 64)
        norms = {}
        for name, tc in (
            ("rep", TrainConfig(grad_clip_norm=0.5)),
            ("sh", TrainConfig(grad_clip_norm=0.5, shard_optimizer=True)),
        ):
            st = init_train_state(jax.random.PRNGKey(0), _dense_cfg(), tc, mesh=mesh)
            step = make_train_step(mesh, _dense_cfg(), tc)
            st, m = step(st, tok, tok)
            norms[name] = float(m["grad_norm"])
        # same norm up to summation order (bitwise holds only with clip off)
        assert norms["sh"] == pytest.approx(norms["rep"], rel=1e-5)


# -------------------------------------------------------- EF on the seam


class TestShardedErrorFeedback:
    def test_constant_gradient_running_mean_converges(self):
        """EF on the SPLIT wire: sync ``g + e`` via reduce_scatter (int8,
        wire-exact residual) + all_gather (int8), carry ``e``; the
        running mean of the gathered result converges toward the exact
        ``N * g`` — the same telescoping contract as the fused path."""
        rng = np.random.default_rng(3)
        g = rng.standard_normal(2048).astype(np.float32)
        exact = N * g.astype(np.float64)

        def f(v, s):
            shard, res = reduce_scatter(
                v[0], "ft", topo="8", codec="int8", step=s,
                return_residual=True,
            )
            out = all_gather(
                shard, "ft", topo="8", out_shape=v[0].shape,
                codec="int8", step=s,
            )
            return jnp.stack([out, res])[None]

        mesh = flat_mesh(N, "ft")
        jf = jax.jit(
            jax.shard_map(
                f, mesh=mesh, in_specs=(P("ft"), P()), out_specs=P("ft"),
                check_vma=False,
            )
        )
        e = np.zeros_like(g)
        acc = np.zeros_like(exact)
        errs = {}
        for step in range(1, 25):
            x = jnp.asarray(np.tile(g + e, (N, 1)))
            out = np.asarray(jf(x, jnp.int32(step)))
            acc += out[0, 0].astype(np.float64)
            e = out[0, 1]
            errs[step] = np.abs(acc / step - exact).max()
        assert errs[24] < errs[1] / 4  # the running mean shrinks
        assert np.abs(e).max() <= float(np.abs(g + e).max()) / 127.0 + 1e-6


# ----------------------------------------------------------- plan cache


class TestAutotuneNoAlias:
    def test_sharded_and_replicated_plans_never_alias(self, tmp_path):
        from flextree_tpu.planner.autotune import autotune_plan

        cache = str(tmp_path / "plans.json")
        calls = []

        def timer(cands, n, nbytes, dtype, repeat):
            calls.append(len(cands))
            return [1.0 + i for i in range(len(cands))]

        a = autotune_plan(
            8, 1 << 16, top_k=2, timer=timer, cache_path=cache, sharded=False
        )
        b = autotune_plan(
            8, 1 << 16, top_k=2, timer=timer, cache_path=cache, sharded=True
        )
        # the second call must MISS (different key component) and re-measure
        assert len(calls) == 2
        assert a.source == "measured" and b.source == "measured"
        # and each replays from its own entry afterwards
        a2 = autotune_plan(
            8, 1 << 16, top_k=2, timer=timer, cache_path=cache, sharded=False
        )
        b2 = autotune_plan(
            8, 1 << 16, top_k=2, timer=timer, cache_path=cache, sharded=True
        )
        assert len(calls) == 2  # pure cache hits
        assert a2.source == "cache" and b2.source == "cache"
        assert (a2.widths, a2.codec) == (a.widths, a.codec)
        assert (b2.widths, b2.codec) == (b.widths, b.codec)


# ------------------------------------------------ elastic re-shard (fit)


class TestLiveReshard:
    def test_shrink_without_checkpoint_reshards_live_state(self):
        """A peer dies before any checkpoint exists: the survivors must
        convert the LIVE old-world sharded state through the consolidated
        layout (old world packs, new world re-shards) instead of handing
        old-world shard shapes to the new step."""
        import dataclasses

        from flextree_tpu.models.transformer import init_params, param_specs
        from flextree_tpu.parallel.loop import FitConfig, Supervision, fit
        from flextree_tpu.parallel.train import (
            TrainConfig,
            init_train_state,
            make_mesh_nd,
            make_state_specs,
            make_train_step,
            zero_layout_for,
        )
        from flextree_tpu.parallel.zero import (
            make_consolidate_fn,
            make_reshard_fn,
        )

        cfg = _dense_cfg()
        tc = TrainConfig(shard_optimizer=True)
        axes = ("dp", "sp", "tp")
        pspecs = param_specs(cfg, "tp")
        shapes = jax.eval_shape(
            lambda k: init_params(k, cfg), jax.random.PRNGKey(0)
        )
        packed_specs = make_state_specs(
            pspecs, dataclasses.replace(tc, shard_optimizer=False)
        )

        def build_world(ndev, grad_topo=None):
            tc2 = dataclasses.replace(tc, grad_topo=grad_topo)
            mesh = make_mesh_nd(ndev, (ndev, 1, 1), axes)
            step = make_train_step(mesh, cfg, tc2)
            layout = zero_layout_for(mesh, shapes, pspecs, axes)
            pack = make_consolidate_fn(mesh, pspecs, layout, grad_topo, False)
            unpack = make_reshard_fn(mesh, pspecs, layout, grad_topo, False)
            return mesh, step, pack, unpack

        mesh, step_fn, pack, unpack = build_world(4)

        class _Data:
            def batch_at(self, step):
                tok = (np.arange(4 * 16, dtype=np.int32).reshape(4, 16) + step) % 64
                return tok, tok

        polls = {"n": 0}

        def membership():
            polls["n"] += 1
            dead = "dead" if polls["n"] > 2 else "healthy"
            return {0: "healthy", 1: "healthy", 2: dead}

        def on_shrink(n_alive, plan):
            mesh2, step2, pack2, unpack2 = build_world(
                n_alive, grad_topo=plan.to_ft_topo()
            )
            return step2, mesh2, packed_specs, pack2, unpack2

        state = init_train_state(jax.random.PRNGKey(0), cfg, tc, mesh=mesh)
        result = fit(
            state, step_fn, _Data(),
            FitConfig(num_steps=5, ckpt_dir=None, log_every=0, prefetch=0),
            mesh=mesh, state_specs=packed_specs,
            supervision=Supervision(
                membership=membership, configured_world=3, on_shrink=on_shrink
            ),
            state_pack=pack, state_unpack=unpack,
        )
        assert result.steps_run == 5
        assert len(result.report.membership_epochs) == 2
        assert result.report.membership_epochs[1]["alive"] == 2
        # the live state was re-carved for the 2-wide world: every shard
        # buffer's global length is now head (n=2 blocks), and finite
        for l in jax.tree.leaves(result.state["mu_shard"]):
            assert np.isfinite(np.asarray(l)).all()
        assert all(
            np.isfinite(np.asarray(l)).all()
            for l in jax.tree.leaves(result.state["params"])
        )


# -------------------------------------------------- split-phase verifier


class TestSplitScheduleVerifier:
    def test_clean_matrix_is_green(self):
        from flextree_tpu.analysis.schedule_check import check_split_schedules

        vs, programs = check_split_schedules()
        assert programs >= 16 and not vs

    def test_tampered_rs_ownership_caught(self):
        from flextree_tpu.analysis.schedule_check import (
            SEND,
            Half,
            build_phase_program,
            check_phase_program,
        )

        topo = Topology(8, (4, 2))
        prog = build_phase_program(topo, "rs", count=64)
        ps = [p for p in prog.posts[0] if p.stage == 1][0]
        for i, h in enumerate(ps.halves):
            if h.kind == SEND:
                ps.halves[i] = Half(SEND, h.peer, ())
                break
        vs = check_phase_program(prog, topo)
        assert any(
            v.kind in ("shard-ownership", "dropped-block", "asymmetric-match")
            for v in vs
        )

    def test_tampered_ag_closure_caught(self):
        from flextree_tpu.analysis.schedule_check import (
            RECV,
            Half,
            build_phase_program,
            check_phase_program,
        )

        topo = Topology(8, (2, 2, 2))
        prog = build_phase_program(topo, "ag", count=64)
        # drop one recv half's blocks: the closure must notice the gap
        for ps in prog.posts[3]:
            for i, h in enumerate(ps.halves):
                if h.kind == RECV:
                    ps.halves[i] = Half(RECV, h.peer, ())
                    break
            break
        vs = check_phase_program(prog, topo)
        assert any(
            v.kind in ("dropped-block", "asymmetric-match") for v in vs
        )


# -------------------------------------------------------- wire accounting


class TestWireBytes:
    def test_sharded_f32_is_exactly_replicated_wire(self):
        from flextree_tpu.analysis.hlo_lint import (
            _lower_sharded_train_step,
            collective_wire_bytes,
        )

        rep = collective_wire_bytes(_lower_sharded_train_step(regather=True))
        sh = collective_wire_bytes(_lower_sharded_train_step())
        assert sh["total"] == pytest.approx(rep["total"])

    def test_sharded_int8_below_ratio_floor(self):
        from flextree_tpu.analysis.hlo_lint import (
            _lower_sharded_train_step,
            collective_wire_bytes,
        )

        rep = collective_wire_bytes(_lower_sharded_train_step(regather=True))
        sh8 = collective_wire_bytes(_lower_sharded_train_step(codec="int8"))
        assert sh8["total"] / rep["total"] <= 0.6
