"""Pipeline parallelism vs the single-device oracle.

The strongest check is end-to-end: one GPipe train step over a 4-axis mesh
must produce the same loss and the same updated parameters as the plain
dp/sp/tp step (and the single-device step) on identical data — the same
A/B-oracle discipline as everywhere else in the suite (SURVEY §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute train-step tests (fast subset: -m 'not slow')

from flextree_tpu.models.transformer import TransformerConfig, init_params
from flextree_tpu.parallel.pipeline import (
    factor_devices_4d,
    init_pipeline_train_state,
    make_mesh_4d,
    make_pipeline_train_step,
    stack_layer_params,
    unstack_layer_params,
)
from flextree_tpu.parallel.train import (
    TrainConfig,
    init_train_state,
    make_mesh_3d,
    make_train_step,
)


def _cfg(**kw):
    base = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64)
    base.update(kw)
    return TransformerConfig(**base)


def _batch(cfg, b=8, t=32, seed=1):
    rng = np.random.default_rng(seed)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (b, t)), jnp.int32)
    targets = jnp.asarray(rng.integers(0, cfg.vocab_size, (b, t)), jnp.int32)
    return tokens, targets


def _leaves(tree):
    return [np.asarray(x) for x in jax.tree.leaves(jax.device_get(tree))]


def _single_device_reference(cfg, state_key, tokens, targets, train_cfg=TrainConfig()):
    state = init_train_state(jax.random.PRNGKey(state_key), cfg)
    step = make_train_step(make_mesh_3d(1, (1, 1, 1)), cfg, train_cfg)
    return step(state, tokens, targets)


def test_stack_unstack_roundtrip():
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    back = unstack_layer_params(stack_layer_params(params))
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize(
    "shape,microbatches",
    [
        ((1, 2, 2, 2), 2),  # pp=2 with sp and tp alongside
        ((2, 4, 1, 1), 4),  # deep pipeline, dp alongside
        ((1, 8, 1, 1), 2),  # pure pipeline, one layer per stage... n_layers=8
        ((2, 2, 2, 1), 2),
    ],
)
def test_pipeline_step_matches_single_device(shape, microbatches):
    n_layers = 8 if shape[1] == 8 else 4
    cfg = _cfg(n_layers=n_layers)
    tokens, targets = _batch(cfg)
    s1, m1 = _single_device_reference(cfg, 0, tokens, targets)

    mesh = make_mesh_4d(8, shape)
    state = init_pipeline_train_state(jax.random.PRNGKey(0), cfg)
    step = make_pipeline_train_step(mesh, cfg, n_microbatches=microbatches)
    sp_, mp = step(state, tokens, targets)

    np.testing.assert_allclose(float(mp["loss"]), float(m1["loss"]), rtol=1e-5)
    got = _leaves(unstack_layer_params(jax.device_get(sp_["params"])))
    want = _leaves(s1["params"])
    for a, b in zip(got, want):
        np.testing.assert_allclose(a, b, atol=1e-5)


def test_pipeline_pp1_is_grad_accumulation():
    """pp=1 degenerates to plain microbatched training — must still match."""
    cfg = _cfg()
    tokens, targets = _batch(cfg)
    s1, m1 = _single_device_reference(cfg, 0, tokens, targets)
    mesh = make_mesh_4d(8, (8, 1, 1, 1))
    state = init_pipeline_train_state(jax.random.PRNGKey(0), cfg)
    step = make_pipeline_train_step(mesh, cfg, n_microbatches=1)
    sp_, mp = step(state, tokens, targets)
    np.testing.assert_allclose(float(mp["loss"]), float(m1["loss"]), rtol=1e-5)
    for a, b in zip(
        _leaves(unstack_layer_params(jax.device_get(sp_["params"]))),
        _leaves(s1["params"]),
    ):
        np.testing.assert_allclose(a, b, atol=1e-5)


def test_pipeline_with_tree_grad_topo():
    cfg = _cfg()
    tokens, targets = _batch(cfg)
    mesh = make_mesh_4d(8, (4, 2, 1, 1))
    state = init_pipeline_train_state(jax.random.PRNGKey(0), cfg)
    flat_s, flat_m = make_pipeline_train_step(mesh, cfg, n_microbatches=2)(
        state, tokens, targets
    )
    tree_s, tree_m = make_pipeline_train_step(
        mesh, cfg, TrainConfig(grad_topo="2,2"), n_microbatches=2
    )(state, tokens, targets)
    np.testing.assert_allclose(
        float(tree_m["loss"]), float(flat_m["loss"]), rtol=1e-6
    )
    for a, b in zip(_leaves(tree_s["params"]), _leaves(flat_s["params"])):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_pipeline_loss_decreases():
    cfg = _cfg()
    tokens, targets = _batch(cfg)
    mesh = make_mesh_4d(8, (1, 2, 2, 2))
    state = init_pipeline_train_state(jax.random.PRNGKey(0), cfg)
    step = make_pipeline_train_step(
        mesh, cfg, TrainConfig(lr=3e-3), n_microbatches=2
    )
    losses = []
    for _ in range(5):
        state, metrics = step(state, tokens, targets)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] - 0.2, losses


def test_pipeline_rejects_indivisible_layers():
    cfg = _cfg(n_layers=3)
    mesh = make_mesh_4d(8, (4, 2, 1, 1))
    with pytest.raises(ValueError, match="divisible"):
        make_pipeline_train_step(mesh, cfg)


def test_pipeline_rejects_indivisible_microbatch():
    cfg = _cfg()
    tokens, targets = _batch(cfg, b=6)
    mesh = make_mesh_4d(8, (1, 2, 2, 2))
    state = init_pipeline_train_state(jax.random.PRNGKey(0), cfg)
    step = make_pipeline_train_step(mesh, cfg, n_microbatches=4)
    with pytest.raises(ValueError, match="microbatch"):
        step(state, tokens, targets)


def test_factor_devices_4d():
    assert factor_devices_4d(1) == (1, 1, 1, 1)
    assert factor_devices_4d(8) == (1, 2, 2, 2)
    assert factor_devices_4d(16) == (2, 2, 2, 2)
    for n in range(1, 33):
        assert int(np.prod(factor_devices_4d(n))) == n
