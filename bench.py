#!/usr/bin/env python
"""Driver benchmark entry point: prints ONE JSON line
``{"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}``.

Two modes, auto-selected:

- **TPU attached** (the normal driver environment): benchmark the hot
  compute path of the allreduce — the Pallas multi-source reduction kernel
  (the rebuild of the reference's OpenMP ``reduce_sum``,
  ``mpi_mod.hpp:246-452``) — against XLA's fused reduction of the same
  stacked array.  Metric is achieved HBM bandwidth; ``vs_baseline`` is
  ours/XLA.  (Only one TPU chip is attached, so the multi-chip allreduce
  itself can't run on real hardware; its A/B lives in the CPU fallback and
  in ``python -m flextree_tpu.bench``.)
- **TPU unavailable / wedged**: the FlexTree allreduce vs ``lax.psum`` A/B
  on an 8-virtual-device CPU mesh (the reference's ``--comm-type`` A/B,
  ``benchmark.cpp:147-174``); metric is bus bandwidth, ``vs_baseline`` is
  FlexTree/psum.

The TPU probe runs in a subprocess with a timeout because a wedged axon
tunnel hangs backend init indefinitely (observed in this container);
``bench.py`` must never hang the driver.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.abspath(__file__))


def tpu_alive(timeout_s: int = 120) -> bool:
    if os.environ.get("FLEXTREE_BENCH_PLATFORM") == "cpu":
        return False
    code = (
        "import jax\n"
        "assert any(d.platform != 'cpu' for d in jax.devices())\n"
        "print('tpu-ok')\n"
    )
    try:
        p = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
        return p.returncode == 0 and "tpu-ok" in p.stdout
    except (subprocess.SubprocessError, OSError):
        return False


def bench_tpu_kernel() -> dict:
    import numpy as np
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, REPO)
    from flextree_tpu.ops.pallas_reduce import reduce_stacked, reduce_stacked_reference
    from flextree_tpu.utils.timing import time_jax_fn

    w, length = 8, 4 * 1024 * 1024  # 8 sources x 16 MB float32
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((w, length)).astype(np.float32))

    ours = time_jax_fn(
        lambda v: reduce_stacked(v, op="sum", interpret=False), x, repeat=20
    )
    baseline = time_jax_fn(
        jax.jit(lambda v: reduce_stacked_reference(v, "sum")), x, repeat=20
    )
    nbytes = (w + 1) * length * 4  # read w copies + write one
    ours_bw = nbytes / ours.min_s / 1e9
    base_bw = nbytes / baseline.min_s / 1e9
    return {
        "metric": "pallas_multisource_reduce_hbm_bw",
        "value": round(ours_bw, 2),
        "unit": "GB/s",
        "vs_baseline": round(ours_bw / base_bw, 3),
    }


def bench_cpu_allreduce() -> dict:
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)
    import numpy as np
    import jax.numpy as jnp

    sys.path.insert(0, REPO)
    from flextree_tpu.bench.harness import BenchConfig, run_allreduce_bench
    from flextree_tpu.planner import choose_topology

    size = 1 << 20  # 4 MB float32 per rank
    plan = choose_topology(8, size * 4)
    # the planner's constants are TPU-calibrated; on the CPU fallback, rank
    # a small candidate set empirically (the planner's top pick included)
    candidates = {plan.to_ft_topo(), "8", "2,2,2", "4,2", "1"}
    ours = None
    for topo in sorted(candidates):
        rep = run_allreduce_bench(
            BenchConfig(size=size, repeat=10, comm_type="flextree", topo=topo)
        )
        if rep.correct and (ours is None or rep.bus_bw_GBps > ours.bus_bw_GBps):
            ours = rep
    base = run_allreduce_bench(BenchConfig(size=size, repeat=10, comm_type="xla"))
    if ours is None or not base.correct:
        raise RuntimeError("correctness check failed in bench")
    return {
        "metric": "allreduce_bus_bw_8vdev_cpu",
        "value": round(ours.bus_bw_GBps, 3),
        "unit": "GB/s",
        "vs_baseline": round(ours.bus_bw_GBps / base.bus_bw_GBps, 3),
    }


def main() -> int:
    try:
        if tpu_alive():
            result = bench_tpu_kernel()
        else:
            result = bench_cpu_allreduce()
    except Exception as e:  # never hang or die silently: emit a valid line
        result = {
            "metric": "bench_error",
            "value": 0.0,
            "unit": f"error:{type(e).__name__}",
            "vs_baseline": 0.0,
        }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
