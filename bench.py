#!/usr/bin/env python
"""Driver benchmark entry point: prints ONE JSON line
``{"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}``.

Two modes, auto-selected:

- **TPU attached** (the normal driver environment): benchmark the model
  layer's hot op — the fused Pallas flash-attention kernel
  (``flextree_tpu.ops.pallas_attention``) — against XLA's full-matrix
  attention on identical bf16 inputs.  Metric is achieved TFLOP/s on the
  causal-attention FLOPs; ``vs_baseline`` is ours/XLA (>1 = faster).
  Timing chains each call's output into the next call's query and ends
  with a host scalar fetch, so the device provably executes every step:
  over the axon tunnel, per-call ``block_until_ready`` measures round-trip
  latency on small work yet can return before long-running work finishes —
  a data-dependency chain is the only timing this backend can't fake.  (Only one TPU chip is
  attached, so the multi-chip allreduce itself can't run on real
  hardware; its A/B lives in the CPU fallback and in
  ``python -m flextree_tpu.bench``.)
- **TPU unavailable / wedged**: the FlexTree allreduce vs ``lax.psum`` A/B
  on an 8-virtual-device CPU mesh (the reference's ``--comm-type`` A/B,
  ``benchmark.cpp:147-174``); metric is bus bandwidth, ``vs_baseline`` is
  FlexTree/psum.

The TPU probe runs in a subprocess with a timeout because a wedged axon
tunnel hangs backend init indefinitely (observed in this container);
``bench.py`` must never hang the driver.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.abspath(__file__))


def tpu_alive(timeout_s: int = 120) -> bool:
    if os.environ.get("FLEXTREE_BENCH_PLATFORM") == "cpu":
        return False
    code = (
        "import jax\n"
        "assert any(d.platform != 'cpu' for d in jax.devices())\n"
        "print('tpu-ok')\n"
    )
    try:
        p = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
        return p.returncode == 0 and "tpu-ok" in p.stdout
    except (subprocess.SubprocessError, OSError):
        return False


def _chained_s(fn, q, k, v, n_calls: int) -> float:
    """Per-call seconds, execution forced by data dependency (shared
    helper: ``flextree_tpu.utils.timing.time_chained``)."""
    sys.path.insert(0, REPO)
    from flextree_tpu.utils.timing import time_chained

    return time_chained(fn, q, k, v, n_calls=n_calls)


def bench_tpu_kernel() -> dict:
    import numpy as np
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, REPO)
    from flextree_tpu.ops.pallas_attention import flash_attention
    from flextree_tpu.parallel.ring_attention import attention_reference

    b, t, h, d = 4, 4096, 16, 128
    rng = np.random.default_rng(0)

    def mk():
        return jnp.asarray(
            rng.standard_normal((b, t, h, d)).astype(np.float32),
            dtype=jnp.bfloat16,
        )

    q, k, v = mk(), mk(), mk()
    flash = jax.jit(
        lambda q, k, v: flash_attention(
            q, k, v, causal=True, block_q=512, block_k=512, interpret=False
        )
    )
    ref = jax.jit(lambda q, k, v: attention_reference(q, k, v, causal=True))

    def flops_for(batch):
        return 4 * batch * h * t * t * d / 2  # causal: half the score matrix

    ours_s = _chained_s(flash, q, k, v, n_calls=30)
    ours_tflops = flops_for(b) / ours_s / 1e12
    # the full-matrix baseline materializes (B*H, T, T) f32 scores (~4 GB
    # at these shapes); prefer the same batch for a like-for-like ratio,
    # fall back to batch 1 on chips where that doesn't fit, comparing by
    # achieved TFLOP/s either way
    try:
        base_s = _chained_s(ref, q, k, v, n_calls=10)
        base_tflops = flops_for(b) / base_s / 1e12
    except Exception:
        base_s = _chained_s(ref, q[:1], k[:1], v[:1], n_calls=10)
        base_tflops = flops_for(1) / base_s / 1e12
    return {
        "metric": "flash_attention_causal_bf16_tflops",
        "value": round(ours_tflops, 2),
        "unit": "TFLOP/s",
        "vs_baseline": round(ours_tflops / base_tflops, 3),
    }


def bench_cpu_allreduce() -> dict:
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)
    import numpy as np
    import jax.numpy as jnp

    sys.path.insert(0, REPO)
    from flextree_tpu.bench.harness import BenchConfig, run_allreduce_bench
    from flextree_tpu.planner import choose_topology

    size = 1 << 20  # 4 MB float32 per rank
    plan = choose_topology(8, size * 4)
    # the planner's constants are TPU-calibrated; on the CPU fallback, rank
    # a small candidate set empirically (the planner's top pick included)
    candidates = {plan.to_ft_topo(), "8", "2,2,2", "4,2", "1"}
    ours = None
    for topo in sorted(candidates):
        rep = run_allreduce_bench(
            BenchConfig(size=size, repeat=10, comm_type="flextree", topo=topo)
        )
        if rep.correct and (ours is None or rep.bus_bw_GBps > ours.bus_bw_GBps):
            ours = rep
    base = run_allreduce_bench(BenchConfig(size=size, repeat=10, comm_type="xla"))
    if ours is None or not base.correct:
        raise RuntimeError("correctness check failed in bench")
    return {
        "metric": "allreduce_bus_bw_8vdev_cpu",
        "value": round(ours.bus_bw_GBps, 3),
        "unit": "GB/s",
        "vs_baseline": round(ours.bus_bw_GBps / base.bus_bw_GBps, 3),
    }


def main() -> int:
    try:
        if tpu_alive():
            result = bench_tpu_kernel()
        else:
            result = bench_cpu_allreduce()
    except Exception as e:  # never hang or die silently: emit a valid line
        result = {
            "metric": "bench_error",
            "value": 0.0,
            "unit": f"error:{type(e).__name__}",
            "vs_baseline": 0.0,
        }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
