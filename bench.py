#!/usr/bin/env python
"""Driver benchmark entry point: prints ONE JSON line
``{"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}``.

Two modes, auto-selected:

- **TPU attached** (the normal driver environment): benchmark the model
  layer's hot op — the fused Pallas flash-attention kernel
  (``flextree_tpu.ops.pallas_attention``) — against XLA's full-matrix
  attention on identical bf16 inputs.  Metric is achieved TFLOP/s on the
  causal-attention FLOPs; ``vs_baseline`` is ours/XLA (>1 = faster).
  Timing chains each call's output into the next call's query and ends
  with a host scalar fetch, so the device provably executes every step:
  over the axon tunnel, per-call ``block_until_ready`` measures round-trip
  latency on small work yet can return before long-running work finishes —
  a data-dependency chain is the only timing this backend can't fake.  (Only one TPU chip is
  attached, so the multi-chip allreduce itself can't run on real
  hardware; its A/B lives in the CPU fallback and in
  ``python -m flextree_tpu.bench``.)
- **TPU unavailable / wedged**: the FlexTree allreduce vs ``lax.psum`` A/B
  on an 8-virtual-device CPU mesh (the reference's ``--comm-type`` A/B,
  ``benchmark.cpp:147-174``); metric is bus bandwidth, ``vs_baseline`` is
  FlexTree/psum.

The TPU probe runs in a subprocess with a timeout because a wedged axon
tunnel hangs backend init indefinitely (observed in this container);
``bench.py`` must never hang the driver.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.abspath(__file__))


def tpu_alive(timeout_s: int = 120) -> bool:
    if os.environ.get("FLEXTREE_BENCH_PLATFORM") == "cpu":
        return False
    code = (
        "import jax\n"
        "assert any(d.platform != 'cpu' for d in jax.devices())\n"
        "print('tpu-ok')\n"
    )
    try:
        p = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
        return p.returncode == 0 and "tpu-ok" in p.stdout
    except (subprocess.SubprocessError, OSError):
        return False


def bench_tpu_kernel() -> dict:
    """Our autotuned Pallas flash attention vs the strongest available
    baseline: the stock Pallas TPU flash kernel, ALSO autotuned and timed
    in its native (B, H, T, D) layout (falling back to XLA full-matrix
    attention if stock fails on this backend).  Both sides use the
    device-loop timing protocol (``time_device_loop``): per-call time is
    the slope of an in-jit chained fori_loop at two iteration counts,
    which cancels the tunneled backend's per-dispatch latency — the r01/r02
    numbers (45/33 TFLOP/s) were dominated by that latency, not by the
    kernel, whose device time is ~95 TFLOP/s (PROFILE_ATTENTION.md).
    Reports MFU against the chip's bf16 peak alongside TFLOP/s."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, REPO)
    from flextree_tpu.bench.harness import (
        AttentionBenchConfig,
        autotune_attention,
        chip_peak_tflops,
    )
    from flextree_tpu.parallel.ring_attention import attention_reference
    from flextree_tpu.utils.timing import time_device_loop

    b, t, h, d = 4, 4096, 16, 128
    cfg = AttentionBenchConfig(batch=b, seq_len=t, heads=h, head_dim=d)
    # shortlisted blocks x both candidate forward schedules (r4): the
    # winner ships, whatever it is
    ours = autotune_attention(
        cfg,
        blocks=((256, 512), (512, 512), (1024, 512)),
        variants=("loop", "pipelined", "kvgrid"),
    )

    baseline_name = "stock_pallas_flash_tuned"
    try:
        base = autotune_attention(
            cfg, impl="stock", blocks=((1024, 512), (512, 512))
        )
        base_tflops = base.tflops
    except Exception:
        baseline_name = "xla_full_matrix"
        rng = np.random.default_rng(0)

        def mk():
            return jnp.asarray(
                rng.standard_normal((b, t, h, d)).astype(np.float32),
                dtype=jnp.bfloat16,
            )

        q, k, v = mk(), mk(), mk()
        ref = jax.jit(lambda q, k, v: attention_reference(q, k, v, causal=True))

        def flops_for(batch):
            return 4 * batch * h * t * t * d / 2  # causal

        try:
            base_s = time_device_loop(ref, q, k, v)
            base_tflops = flops_for(b) / base_s / 1e12
        except Exception:
            base_s = time_device_loop(ref, q[:1], k[:1], v[:1])
            base_tflops = flops_for(1) / base_s / 1e12

    out = {
        "metric": "flash_attention_causal_bf16_tflops",
        "value": round(ours.tflops, 2),
        "unit": "TFLOP/s",
        "vs_baseline": round(ours.tflops / base_tflops, 3),
        # supplementary (beyond the 4-key contract): honesty metrics
        "baseline": baseline_name,
        "baseline_tflops": round(base_tflops, 2),
        "blocks": [ours.config.block_q, ours.config.block_k],
        "variant": ours.config.variant,
        "timing": "device_loop_slope",
    }
    peak = chip_peak_tflops()
    if peak:
        out["mfu"] = round(ours.tflops / peak, 4)
    try:  # end-to-end flagship forward MFU (VERDICT r4 item 8)
        out.update(bench_model_forward(ours.config))
    except Exception as e:  # supplementary row — never sink the main metric
        out["model_fwd_error"] = f"{type(e).__name__}: {e}"[:200]
    return out


def bench_model_forward(attn_cfg=None) -> dict:
    """Single-chip flagship-model forward MFU, device-loop slope timed.

    The kernel A/B above isolates the hot op; this row answers the
    end-to-end question — what fraction of the chip's bf16 peak the whole
    transformer forward (embed + L x (qkvo/flash-attention/mlp) + logits)
    sustains.  The loop chains greedy-sampled tokens back into the next
    forward (same (B, T) int32 shape/dtype), so every iteration is
    data-dependent and the slope cancels tunnel dispatch latency, exactly
    like the kernel rows.  FLOPs are the analytic matmul+attention count
    (causal attention at T_eff = T/2), the standard MFU convention.
    """
    import jax
    import jax.numpy as jnp

    from flextree_tpu.bench.harness import chip_peak_tflops
    from flextree_tpu.models.transformer import (
        TransformerConfig,
        forward,
        init_params,
    )
    from flextree_tpu.utils.timing import time_device_loop

    b, t = 2, 4096
    # run the autotune winner's kernel config inside the model, not the
    # library defaults — attn_cfg is the AttentionBenchConfig that won
    attn_opts = ()
    if attn_cfg is not None:
        attn_opts = (
            ("block_q", attn_cfg.block_q),
            ("block_k", attn_cfg.block_k),
            ("variant", attn_cfg.variant),
        )
    cfg = TransformerConfig(
        vocab_size=32768,
        d_model=2048,
        n_heads=16,  # head_dim 128: the flash kernel's native lane width
        n_layers=4,
        d_ff=8192,
        dtype=jnp.bfloat16,
        attn_impl="flash",
        attn_opts=attn_opts,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (b, t), 0, cfg.vocab_size)

    def step(toks, params):
        logits = forward(params, toks, cfg)
        return jnp.argmax(logits, axis=-1).astype(toks.dtype)

    sec = time_device_loop(step, tokens, params, n_lo=1, n_hi=5)
    d, dff, v = cfg.d_model, cfg.d_ff, cfg.vocab_size
    per_token = cfg.n_layers * (8 * d * d + 4 * d * dff + 2 * t * d) + 2 * d * v
    tflops = per_token * b * t / sec / 1e12
    out = {
        "model_fwd_tflops": round(tflops, 2),
        "model_fwd_config": f"d{d}_ff{dff}_L{cfg.n_layers}_h{cfg.n_heads}"
        f"_b{b}_t{t}_v{v}_bf16_flash",
        "model_fwd_attn_opts": dict(attn_opts) or "library defaults",
    }
    peak = chip_peak_tflops()
    if peak:
        out["model_fwd_mfu"] = round(tflops / peak, 4)
    return out


def bench_cpu_allreduce() -> dict:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from flextree_tpu.utils.compat import request_cpu_devices

    request_cpu_devices(8)
    import numpy as np
    import jax.numpy as jnp

    sys.path.insert(0, REPO)
    from flextree_tpu.bench.harness import BenchConfig, run_allreduce_bench
    from flextree_tpu.planner import choose_topology

    size = 1 << 20  # 4 MB float32 per rank
    # calibrate the cost model on this backend (a few small measured
    # points), then run ONLY the planner's argmin — the planner is trusted,
    # not re-ranked empirically (VERDICT r1 item 2)
    from flextree_tpu.planner import fit_cost_params, measure_points

    points = measure_points(
        ["8", "4,2", "2,2,2", "1"], [1 << 16, 1 << 19], repeat=10, devices=8
    )
    try:
        params = fit_cost_params(points)
        plan = choose_topology(8, size * 4, params=params)
    except RuntimeError:
        # degenerate NNLS fit (measurements too noisy to be consistent with
        # the model): fall back to the default constants rather than dying
        plan = choose_topology(8, size * 4)
    # best-of-2 runs per side, INTERLEAVED (ours, base, ours, base): the
    # headline is min-of-reps, and on this timeshared 1-core host a single
    # run's min swings enough to move vs_baseline ~20% round-to-round
    # (r03 1.478 vs r04 1.203 came from a slow psum BASELINE run, not from
    # our collective changing).  Interleaving bounds a sustained host-
    # contention episode to at most one (ours, base) pair; back-to-back
    # pairs would let one episode inflate both reps of a side.
    ours_cfg = BenchConfig(
        size=size, repeat=10, comm_type="flextree", topo=plan.to_ft_topo()
    )
    base_cfg = BenchConfig(size=size, repeat=10, comm_type="xla")
    ours_reps, base_reps = [], []
    for _ in range(2):
        ours_reps.append(run_allreduce_bench(ours_cfg))
        base_reps.append(run_allreduce_bench(base_cfg))
    if not all(r.correct for r in ours_reps + base_reps):
        raise RuntimeError("correctness check failed in bench")
    ours = max(ours_reps, key=lambda r: r.bus_bw_GBps)
    base = max(base_reps, key=lambda r: r.bus_bw_GBps)
    out = {
        "metric": "allreduce_bus_bw_8vdev_cpu",
        "value": round(ours.bus_bw_GBps, 3),
        "unit": "GB/s",
        "vs_baseline": round(ours.bus_bw_GBps / base.bus_bw_GBps, 3),
    }
    try:  # supplementary: bucketed/fused gradient-sync rows (ISSUE 2)
        out.update(bench_grad_bucketing())
    except Exception as e:  # never sink the main metric
        out["bucketing_error"] = f"{type(e).__name__}: {e}"[:200]
    return out


def bench_grad_bucketing() -> dict:
    """Supplementary rows: fused/chunked gradient sync vs per-leaf on the
    many-small-leaves regime, plus the end-to-end ``train_step_ms`` A/B —
    the in-step metric the bucketing tentpole moves.  Full matrix +
    committed artifact: ``tools/bench_bucketing.py`` -> BENCH_BUCKETING.json.
    """
    from flextree_tpu.bench.harness import (
        GradSyncBenchConfig,
        TrainStepBenchConfig,
        run_grad_sync_bench,
        run_train_step_bench,
    )

    # same shuffled-interleaved min-of-many protocol as
    # tools/bench_bucketing.py, with fewer reps (20/12 vs its 30/16) to keep
    # the driver bench fast: on the timeshared host, min-of-few swings the
    # A/B ratio ~30% (same lesson as the interleaved best-of-2 above)
    sync = run_grad_sync_bench(
        GradSyncBenchConfig(n_leaves=48, leaf_size=4096, repeat=20)
    )
    step = run_train_step_bench(TrainStepBenchConfig(repeat=12))
    out = {
        "grad_sync_48leaf_ms": {
            k: round(v["min_ms"], 3) for k, v in sync["rows"].items()
        },
        "grad_sync_fused_vs_per_leaf": round(
            sync["rows"]["ours_fused"]["vs_per_leaf"], 3
        ),
        "train_step_ms": {
            k: round(v["train_step_ms"], 3) for k, v in step["rows"].items()
        },
        "train_step_fused_vs_per_leaf": round(
            step["rows"]["ours_fused"]["vs_per_leaf"], 3
        ),
    }
    if "ours_fused_supervised" in step["rows"]:
        # ISSUE-4 acceptance tripwire: watchdog + heartbeat on the
        # fault-free path, as a ratio to the unsupervised fused step
        # (1.02 = the 2% budget; WINS.md carries the measured numbers)
        out["watchdog_heartbeat_overhead"] = round(
            step["rows"]["ours_fused_supervised"]["supervision_overhead"], 4
        )
    return out


def bench_tpu_kernel_guarded(timeout_s: int = 3300) -> dict | None:
    # 3300s: r5's autotune sweeps 9 ours configs (3 blocks x 3 variants)
    # + 2 stock, each ~2 slope-loop compiles over the tunnel, plus the
    # 4-layer model-forward MFU row (2 more, larger, compiles)
    """Run the TPU bench in a subprocess with a hard timeout.

    ``tpu_alive`` only proves the tunnel was up at probe time; it has been
    observed to wedge MID-session (backend init or a compile hanging
    indefinitely), and bench.py must never hang the driver.  Returns None
    on timeout/crash so the caller can fall back to the CPU A/B.
    """
    try:
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"), "--tpu-child"],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        print("tpu bench timed out mid-run (tunnel wedged?); falling back "
              "to the CPU A/B", file=sys.stderr)
        return None
    except (subprocess.SubprocessError, OSError) as e:
        print(f"tpu bench child failed to launch: {e}", file=sys.stderr)
        return None
    for line in reversed(p.stdout.strip().splitlines()):
        try:
            d = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(d, dict) and "metric" in d:
            return d
    # crashed (not hung): preserve the diagnostic before the CPU fallback
    print(f"tpu bench child exited rc={p.returncode} with no metric line; "
          f"stderr tail: {p.stderr[-400:]}", file=sys.stderr)
    return None


def run_static_analysis_tripwire(timeout_s: int = 120) -> dict:
    """Supplementary keys ``analysis_violations`` — the static verifier's
    verdict on this exact tree (ISSUE 3 tripwire; 0 = clean) — and
    ``ir_equivalence_violations`` (ISSUE 8): the lowered StableHLO of
    every IR-compiled collective matches its IR stage list
    (count/kind/group-width per stage); any divergence between the
    verified schedule object and the executable is a non-zero count.
    ``control_plane_analysis_violations`` (ISSUE 18): the exhaustive
    protocol model check (coordination/lease/RPC small worlds) plus the
    concurrency lint's whole-tree sweep, combined.

    Runs the full CLI (``flextree_tpu.analysis``) in a subprocess: it
    pins its own 8-vdev CPU mesh (safe regardless of this process's
    backend state) and a wedged run must never hang the driver.  An
    analyzer that fails to run is itself a tripwire condition, reported
    as ``analysis_error`` with the key absent — absent reads as "not
    verified", never as "clean".
    """
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as tf:
        report_path = tf.name
    try:
        p = subprocess.run(
            [
                sys.executable, "-m", "flextree_tpu.analysis",
                "--report", report_path,
            ],
            capture_output=True, text=True, cwd=REPO, timeout=timeout_s,
        )
        with open(report_path, encoding="utf-8") as fh:
            report = json.load(fh)
        out = {
            "analysis_violations": report["analysis_violations"],
            # KeyError (layer missing = pass didn't run) falls through to
            # the except arm: the key stays ABSENT, which reads as "not
            # verified", never as "clean"
            "ir_equivalence_violations": report["layers"]["ir_equivalence"][
                "violations"
            ],
            # ISSUE 18: the control-plane layers' combined verdict — the
            # exhaustive protocol model check plus the concurrency/lock-
            # discipline lint; same absent-is-not-clean contract
            "control_plane_analysis_violations": (
                report["layers"]["protocol_check"]["violations"]
                + report["layers"]["concurrency_lint"]["violations"]
            ),
        }
        if not report["mutation_selftest"]["all_caught"]:
            out["analysis_error"] = "mutation self-test escaped"
        elif p.returncode != 0 and report["analysis_violations"] == 0:
            # rc=1 WITH violations is the analyzer doing its job (the count
            # above carries the verdict); rc!=0 with a clean report means
            # the analyzer itself malfunctioned
            out["analysis_error"] = f"analysis CLI rc={p.returncode}"
        return out
    except (subprocess.SubprocessError, OSError, ValueError, KeyError) as e:
        return {"analysis_error": f"{type(e).__name__}: {e}"[:200]}
    finally:
        try:
            os.unlink(report_path)
        except OSError:
            pass


_RUNTIME_TRIPWIRE_CODE = r'''
import json, os, sys, tempfile
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from flextree_tpu.parallel.loop import FitConfig, fit

class D:
    def batch_at(self, step):
        t = np.full((2, 4), float(step + 1)); return t, t

poison = {{3}}
def step_fn(state, tokens, targets):
    s = int(np.asarray(state["step"])); g = float(tokens.mean())
    if s in poison:
        poison.discard(s); g = float("nan")
    return ({{"step": np.int64(s + 1), "w": np.asarray(state["w"]) - g}},
            {{"loss": g}})

ck = tempfile.mkdtemp()
fit({{"step": np.int64(0), "w": np.zeros(2)}}, step_fn, D(),
    FitConfig(num_steps=6, ckpt_dir=ck, ckpt_every=100, log_every=0))
with open(os.path.join(ck, "run_report.json")) as f:
    print("REPORT_JSON: " + json.dumps(json.load(f)))
'''


_QUANT_TRIPWIRE_CODE = r'''
import json, os, sys, tempfile
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
from flextree_tpu.utils.compat import request_cpu_devices
request_cpu_devices(8)
import numpy as np
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from flextree_tpu.ops.quantize import get_codec
from flextree_tpu.parallel.compressed import compressed_allreduce
from flextree_tpu.parallel.mesh import flat_mesh

mesh = flat_mesh(8, "ft")
rng = np.random.default_rng(7)
x = jnp.asarray(rng.standard_normal((8, 8192)).astype(np.float32) * 2)
exact = np.asarray(x).astype(np.float64).sum(axis=0)
amax = float(np.abs(np.asarray(x)).max())
violations = 0
for codec, topo, widths in (
    ("int8", "4,2", (4, 2)), ("int8", "1", (1,)), ("bf16", "4,2", (4, 2)),
):
    f = lambda row: compressed_allreduce(
        row[0], "ft", topo=topo, codec=codec, step=11)[None]
    out = np.asarray(jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=P("ft"), out_specs=P("ft"), check_vma=False
    ))(x))
    bound = get_codec(codec).error_bound(amax, 8, widths) + 1e-5
    violations += int(np.abs(out[0] - exact).max() > bound)

# autotuner: first run measures + persists, second run must be a pure
# cache hit picking the same plan
from flextree_tpu.planner.autotune import autotune_plan
cache = os.path.join(tempfile.mkdtemp(), "plans.json")
t1 = autotune_plan(8, 1 << 16, top_k=2, repeat=2, codecs=("f32", "int8"),
                   cache_path=cache)
t2 = autotune_plan(8, 1 << 16, top_k=2, repeat=2, codecs=("f32", "int8"),
                   cache_path=cache)
hit = int(t1.source == "measured" and t2.source == "cache"
          and (t1.widths, t1.codec) == (t2.widths, t2.codec))
print("QUANT_JSON: " + json.dumps(
    {{"quant_error_bound_violations": violations, "autotune_cache_hit": hit}}))
'''


_OVERLAP_TRIPWIRE_CODE = r'''
import json, sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
from flextree_tpu.utils.compat import request_cpu_devices
request_cpu_devices(8)
from flextree_tpu.bench.harness import TrainStepBenchConfig, run_train_step_bench

# run_train_step_bench RAISES if any sync variant (incl. ours_overlapped /
# ours_overlap_serialized) diverges bitwise from per-leaf, so reaching the
# print line at all certifies the identity contract on this exact tree
out = run_train_step_bench(
    TrainStepBenchConfig(n_layers=2, repeat=4, supervised=False, overlap=True)
)
rows = out["rows"]
twin = rows["ours_overlap_serialized"]["exposed_comm_ms"]
ovl = rows["ours_overlapped"]["exposed_comm_ms"]
frac = ovl / twin if twin > 0 else 1.0
print("OVERLAP_JSON: " + json.dumps({{
    "overlap_bitwise_violations": 0 if out["identical"] else 1,
    "overlap_exposed_comm_frac": round(frac, 3),
}}))
'''


_SHARDED_TRIPWIRE_CODE = r'''
import json, sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
from flextree_tpu.utils.compat import request_cpu_devices
request_cpu_devices(8)
import numpy as np
from flextree_tpu.analysis.hlo_lint import (
    _lower_sharded_train_step, collective_wire_bytes,
)
from flextree_tpu.models.transformer import TransformerConfig
from flextree_tpu.parallel.train import (
    TrainConfig, init_train_state, make_mesh_nd, make_train_step,
)

# 1) f32 sharded step bitwise == replicated step on this exact tree
cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                        d_ff=64)
mesh = make_mesh_nd(8, (2, 2, 2), ("dp", "sp", "tp"))
tok = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 64)
outs = {{}}
for name, tc in (
    ("rep", TrainConfig()),
    ("sh", TrainConfig(shard_optimizer=True)),
):
    st = init_train_state(jax.random.PRNGKey(0), cfg, tc, mesh=mesh)
    step = make_train_step(mesh, cfg, tc)
    for _ in range(2):
        st, m = step(st, tok, tok)
    outs[name] = st["params"]
violations = 0 if all(
    np.asarray(a).tobytes() == np.asarray(b).tobytes()
    for a, b in zip(jax.tree.leaves(outs["rep"]), jax.tree.leaves(outs["sh"]))
) else 1

# 2) static wire-byte ratio: sharded-int8 step vs replicated fused f32,
# both on the loop-free flat(8) plan (collective operand bytes from the
# lowered StableHLO — same accounting as BENCH_SHARDED.json's floor)
rep_ir = _lower_sharded_train_step(regather=True)  # = the replicated step
sh_ir = _lower_sharded_train_step(codec="int8")
ratio = (
    collective_wire_bytes(sh_ir)["total"]
    / max(collective_wire_bytes(rep_ir)["total"], 1)
)
print("SHARDED_JSON: " + json.dumps({{
    "sharded_bitwise_violations": violations,
    "sharded_wire_bytes_ratio": round(ratio, 3),
}}))
'''


def run_sharded_tripwire(timeout_s: int = 420) -> dict:
    """Supplementary keys ``sharded_bitwise_violations`` (ZeRO-1 f32
    sharded step bitwise-equal to the replicated step on this exact tree;
    0 = identical) and ``sharded_wire_bytes_ratio`` (static collective
    operand bytes of the quantized sharded step over the replicated fused
    f32 step's — the same accounting BENCH_SHARDED.json machine-checks
    at <= 0.6 on the real 2-process wire).  Subprocess-guarded: absent
    keys read as "not verified", never as "clean"."""
    try:
        p = subprocess.run(
            [sys.executable, "-c", _SHARDED_TRIPWIRE_CODE.format(repo=REPO)],
            capture_output=True, text=True, timeout=timeout_s,
        )
        for line in p.stdout.splitlines():
            if line.startswith("SHARDED_JSON: "):
                return json.loads(line[len("SHARDED_JSON: "):])
        return {
            "sharded_error": f"no SHARDED_JSON (rc={p.returncode}); "
            f"stderr tail: {p.stderr[-200:]}"
        }
    except (subprocess.SubprocessError, OSError, ValueError) as e:
        return {"sharded_error": f"{type(e).__name__}: {e}"[:200]}


def run_overlap_tripwire(timeout_s: int = 300) -> dict:
    """Supplementary keys ``overlap_bitwise_violations`` (the overlapped
    and barrier-serialized train steps' updated params bitwise-equal to
    per-leaf on this exact tree; 0 = identical) and
    ``overlap_exposed_comm_frac`` (in-process exposed comm of the
    overlapped step as a fraction of its serialized twin's — informational
    on a single-address-space mesh, where the wire is a memcpy on the
    compute cores; the enforced >=1.3x floor lives on the real 2-process
    wire in tools/bench_overlap.py -> BENCH_OVERLAP.json).
    Subprocess-guarded: absent keys read as "not verified", never "clean".
    """
    try:
        p = subprocess.run(
            [sys.executable, "-c", _OVERLAP_TRIPWIRE_CODE.format(repo=REPO)],
            capture_output=True, text=True, timeout=timeout_s,
        )
        for line in p.stdout.splitlines():
            if line.startswith("OVERLAP_JSON: "):
                return json.loads(line[len("OVERLAP_JSON: "):])
        return {
            "overlap_error": f"no OVERLAP_JSON (rc={p.returncode}); "
            f"stderr tail: {p.stderr[-200:]}"
        }
    except (subprocess.SubprocessError, OSError, ValueError) as e:
        return {"overlap_error": f"{type(e).__name__}: {e}"[:200]}


def run_quantize_tripwire(timeout_s: int = 240) -> dict:
    """Supplementary keys ``quant_error_bound_violations`` (compressed
    allreduce error vs the documented codec bound on this exact tree; 0 =
    inside) and ``autotune_cache_hit`` (first autotune run measures and
    persists, second is a pure cache hit; 1 = yes).  Subprocess-guarded
    like the other tripwires: absent keys read as "not verified", never
    as "clean"."""
    try:
        p = subprocess.run(
            [sys.executable, "-c", _QUANT_TRIPWIRE_CODE.format(repo=REPO)],
            capture_output=True, text=True, timeout=timeout_s,
        )
        for line in p.stdout.splitlines():
            if line.startswith("QUANT_JSON: "):
                return json.loads(line[len("QUANT_JSON: "):])
        return {
            "quant_error": f"no QUANT_JSON (rc={p.returncode}); "
            f"stderr tail: {p.stderr[-200:]}"
        }
    except (subprocess.SubprocessError, OSError, ValueError) as e:
        return {"quant_error": f"{type(e).__name__}: {e}"[:200]}


def run_serving_tripwire(timeout_s: int = 900) -> dict:
    """Supplementary keys ``serving_paged_bitwise_violations`` (requests
    served by the continuous batcher over the paged KV cache produce
    exactly the contiguous-cache ``generate``'s tokens on this exact
    tree; 0 = identical) and ``serving_p99_regression`` (1 if the
    continuous batcher's p99 time-to-first-token exceeds the static
    batch-barrier baseline's at equal offered load — structurally it
    should be well under).  Runs ``tools/bench_serving.py --smoke`` in a
    subprocess (it pins its own CPU backend; a wedged run must never
    hang the driver) and reads the artifact it writes.  Absent keys read
    as "not verified", never as "clean"."""
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as tf:
        report_path = tf.name
    try:
        p = subprocess.run(
            [
                sys.executable, os.path.join(REPO, "tools", "bench_serving.py"),
                "--smoke", "--out", report_path,
            ],
            capture_output=True, text=True, cwd=REPO, timeout=timeout_s,
        )
        with open(report_path, encoding="utf-8") as fh:
            doc = json.load(fh)
        floors = doc["floors"]
        out = {
            "serving_paged_bitwise_violations": floors[
                "paged_bitwise_violations"
            ],
            "serving_p99_regression": floors["p99_regression"],
            # informational: the enforced >=1.3x floor lives in the full
            # (non-smoke) run committed as BENCH_SERVING.json
            "serving_throughput_ratio": floors["throughput_ratio"],
        }
        if not floors["replica_kill"]["ok"]:
            out["serving_error"] = "replica-kill scenario failed"
        elif p.returncode != 0:
            out["serving_error"] = f"bench_serving rc={p.returncode}"
        return out
    except (subprocess.SubprocessError, OSError, ValueError, KeyError) as e:
        return {"serving_error": f"{type(e).__name__}: {e}"[:200]}
    finally:
        try:
            os.unlink(report_path)
        except OSError:
            pass


def run_paged_tripwire(timeout_s: int = 900) -> dict:
    """Supplementary keys ``paged_fused_decode_violations`` (fused paged
    decode vs the gather oracle on this exact tree: per-round tolerance
    misses + poisoned-null-block breaks + any preemption scenario that
    lost, duplicated, or corrupted a request; 0 = clean) and
    ``ondemand_admission_gain`` (mean concurrent resident sequences of
    on-demand admission over reservation at equal pool memory — the
    >= 1.3x floor and the >= 1.15x fused-round timing floor are enforced
    in the full run committed as BENCH_PAGED.json; smoke reports them).
    Runs ``tools/bench_paged.py --smoke`` in a subprocess (it pins its
    own CPU backend) and reads the artifact.  Absent keys read as "not
    verified", never as "clean"."""
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as tf:
        report_path = tf.name
    try:
        p = subprocess.run(
            [
                sys.executable, os.path.join(REPO, "tools", "bench_paged.py"),
                "--smoke", "--out", report_path,
            ],
            capture_output=True, text=True, cwd=REPO, timeout=timeout_s,
        )
        with open(report_path, encoding="utf-8") as fh:
            doc = json.load(fh)
        floors = doc["floors"]
        violations = (
            floors["tolerance_violations"]
            + floors["poison_violations"]
            + int(not floors["preempt_swap_ok"])
            + int(not floors["preempt_recompute_ok"])
            + int(not floors["reserve_baseline_ok"])
        )
        out = {
            "paged_fused_decode_violations": violations,
            "ondemand_admission_gain": floors["ondemand_concurrency_gain"],
            # informational in smoke: the enforced timing floor lives in
            # the committed full-run BENCH_PAGED.json
            "paged_fused_speedup": floors["fused_speedup"],
        }
        if p.returncode != 0:
            out["paged_error"] = f"bench_paged rc={p.returncode}"
        return out
    except (subprocess.SubprocessError, OSError, ValueError, KeyError) as e:
        return {"paged_error": f"{type(e).__name__}: {e}"[:200]}
    finally:
        try:
            os.unlink(report_path)
        except OSError:
            pass


def start_prefix_tripwire():
    """Launch ``tools/bench_prefix.py --smoke`` WITHOUT blocking (it pins
    its own CPU backend).  The prefix smoke is pure CPU work with no
    timing floors of its own, so it runs concurrently with the other
    tripwires and its cost hides inside their sleep windows (chaos kill
    waits, lease windows, hedging timeouts) — on the single-core CI
    runner that is the only way adding a tripwire does not push bench.py
    past the contract test's subprocess budget.  Returns an opaque handle
    for ``collect_prefix_tripwire`` (or an error dict if the launch
    itself failed, which collect passes through)."""
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as tf:
        report_path = tf.name
    try:
        proc = subprocess.Popen(
            [
                sys.executable, os.path.join(REPO, "tools", "bench_prefix.py"),
                "--smoke", "--out", report_path,
            ],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, cwd=REPO,
        )
    except OSError as e:
        try:
            os.unlink(report_path)
        except OSError:
            pass
        return {"prefix_error": f"{type(e).__name__}: {e}"[:200]}
    return (proc, report_path)


def collect_prefix_tripwire(handle, timeout_s: int = 900) -> dict:
    """Supplementary keys ``prefix_cache_bitwise_violations`` (warm-index
    engine output vs the cold engine and contiguous ``generate`` on the
    shared-prompt workload, plus the unique-prompt negative control;
    0 = every hit was byte-for-byte honest) and
    ``prefix_tokens_saved_frac`` (fraction of prompt tokens served from
    cached blocks instead of recomputed — the >= 0.5 floor and the TTFT
    floor are enforced in the full run committed as BENCH_PREFIX.json;
    smoke reports them).  Joins the subprocess ``start_prefix_tripwire``
    launched and reads its artifact.  Absent keys read as "not
    verified", never as "clean"."""
    if isinstance(handle, dict):  # launch already failed
        return handle
    proc, report_path = handle
    try:
        try:
            rc = proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
            return {"prefix_error": f"timeout after {timeout_s}s"}
        floors = json.load(open(report_path, encoding="utf-8"))["floors"]
        violations = (
            floors["prefix_cache_bitwise_violations"]
            + int(not floors["hit_rate_ok"])
            + int(not floors["leak_ok"])
            + int(not floors["negative_control_ok"])
        )
        out = {
            "prefix_cache_bitwise_violations": violations,
            "prefix_tokens_saved_frac": floors["prefix_tokens_saved_frac"],
            # informational in smoke: the enforced TTFT floor lives in
            # the committed full-run BENCH_PREFIX.json
            "prefix_hit_ttft_ratio": floors["hit_ttft_ratio"],
        }
        if rc != 0:
            out["prefix_error"] = f"bench_prefix rc={rc}"
        return out
    except (subprocess.SubprocessError, OSError, ValueError, KeyError) as e:
        return {"prefix_error": f"{type(e).__name__}: {e}"[:200]}
    finally:
        try:
            os.unlink(report_path)
        except OSError:
            pass


def run_prefix_tripwire(timeout_s: int = 900) -> dict:
    """Blocking form of the prefix tripwire (launch + collect)."""
    return collect_prefix_tripwire(start_prefix_tripwire(), timeout_s)


_OBS_TRIPWIRE_CODE = r'''
import json, os, sys, tempfile, time
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from flextree_tpu.obs import flight_recorder, merge_dir, read_dir, validate_trace
from flextree_tpu.parallel.loop import FitConfig, Supervision, fit

class D:
    def batch_at(self, step):
        t = np.full((2, 4), float(step + 1)); return t, t

hang = {{2}}
def step_fn(state, tokens, targets):
    s = int(np.asarray(state["step"]))
    if s in hang:
        hang.discard(s); time.sleep(2.0)  # one watchdogged hang, then retry
    return ({{"step": np.int64(s + 1), "w": np.asarray(state["w"]) - 1.0}},
            {{"loss": 0.5}})

obs = tempfile.mkdtemp()
with flight_recorder(obs, rank=0) as rec:
    fit({{"step": np.int64(0), "w": np.zeros(2)}}, step_fn, D(),
        FitConfig(num_steps=5, log_every=0, prefetch=0),
        supervision=Supervision(step_timeout_s=0.4, max_step_retries=1))
    dump_path = rec.dump_path

# the dump-guarantee floors: the failure path left its marker event, the
# guaranteed sidecar dump, and a record that merges schema-valid
violations = 0
events, dumps = read_dir(obs)
violations += not os.path.exists(dump_path)
violations += dumps.get(0, {{}}).get("reason") != "watchdog_timeout"
violations += not any(e["kind"] == "watchdog_timeout" for e in events)
violations += not any(e["kind"] == "step_end" for e in events)
violations += bool(validate_trace(merge_dir(obs)))

# recorder overhead on the fused train step (same interleaved protocol
# as the supervised row; the enforced <= 2% floor lives in
# tools/obs_chaos.py -> OBS_CHAOS.json)
from flextree_tpu.utils.compat import request_cpu_devices
request_cpu_devices(8)
from flextree_tpu.bench.harness import TrainStepBenchConfig, run_train_step_bench
out = run_train_step_bench(
    TrainStepBenchConfig(n_layers=2, repeat=4, supervised=False, recorder=True)
)
overhead = out["rows"]["ours_fused_recorded"]["recorder_overhead"]
print("OBS_JSON: " + json.dumps({{
    "flight_recorder_dump_violations": violations,
    "obs_overhead_frac": round(max(overhead - 1.0, 0.0), 4),
}}))
'''


def run_obs_tripwire(timeout_s: int = 300) -> dict:
    """Supplementary keys ``flight_recorder_dump_violations`` (a
    watchdog-timeout failure path through the real ``fit`` leaves the
    marker event, the guaranteed sidecar dump, and a record that merges
    into schema-valid Chrome-trace JSON on this exact tree; 0 = all
    held) and ``obs_overhead_frac`` (recorder-on fused train step's
    overhead fraction — informational here; the enforced <= 2% budget
    lives in tools/obs_chaos.py -> OBS_CHAOS.json with the 2-process
    SIGKILL evidence).  Subprocess-guarded: absent keys read as "not
    verified", never as "clean"."""
    try:
        p = subprocess.run(
            [sys.executable, "-c", _OBS_TRIPWIRE_CODE.format(repo=REPO)],
            capture_output=True, text=True, timeout=timeout_s,
        )
        for line in p.stdout.splitlines():
            if line.startswith("OBS_JSON: "):
                return json.loads(line[len("OBS_JSON: "):])
        return {
            "obs_error": f"no OBS_JSON (rc={p.returncode}); "
            f"stderr tail: {p.stderr[-200:]}"
        }
    except (subprocess.SubprocessError, OSError, ValueError) as e:
        return {"obs_error": f"{type(e).__name__}: {e}"[:200]}


def run_feedback_tripwire(timeout_s: int = 600) -> dict:
    """Supplementary keys ``planner_feedback_violations`` — the closed
    planner-feedback loop exercised end-to-end on this exact tree
    (ISSUE 12; 0 = a deliberately mis-calibrated start drift-detects,
    refits, invalidates the stale plan-cache entry and replans in-run) —
    and informational ``feedback_recovery_frac`` (the recovered step's
    fraction of the oracle step time; its >= 0.90 floor is enforced only
    in the committed full-run FEEDBACK.json — a CI container's
    timeshared minute cannot hold a timing floor honestly).

    Runs ``tools/feedback_convergence.py --smoke`` in a subprocess (it
    pins its own 8-vdev CPU mesh); a driver that fails to run reports
    ``feedback_error`` with the keys absent — absent reads as "not
    verified", never as "clean".
    """
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as tf:
        report_path = tf.name
    try:
        p = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "tools", "feedback_convergence.py"),
                "--smoke", "--out", report_path,
            ],
            capture_output=True, text=True, cwd=REPO, timeout=timeout_s,
        )
        with open(report_path, encoding="utf-8") as fh:
            doc = json.load(fh)
        out = {
            "planner_feedback_violations": len(doc["violations"]),
            "feedback_recovery_frac": doc["timing"]["recovery_frac"],
        }
        if p.returncode != 0 and not doc["violations"]:
            # rc=1 WITH violations is the driver doing its job; rc!=0
            # with a clean report means the driver itself malfunctioned
            out["feedback_error"] = f"feedback_convergence rc={p.returncode}"
        return out
    except (subprocess.SubprocessError, OSError, ValueError, KeyError) as e:
        return {"feedback_error": f"{type(e).__name__}: {e}"[:200]}
    finally:
        try:
            os.unlink(report_path)
        except OSError:
            pass


def run_probe_free_tripwire(timeout_s: int = 600) -> dict:
    """Supplementary keys ``probe_free_feedback_violations`` — per-step
    cost attribution exercised end-to-end on this exact tree (ISSUE 15;
    0 = a mis-calibrated start is detected and refit from host-timed
    per-step spans alone, with ZERO dedicated probe collectives, the
    refit carries per-phase scales, fleet pooling beats every
    constituent run's conditioning, and the merged timeline renders
    measured-vs-predicted span pairs) — and informational
    ``probe_free_recovery_frac`` (its >= 0.9x-of-FEEDBACK.json floor is
    enforced only in the committed full-run OBS_ATTRIBUTION.json).

    Runs ``tools/probe_free_feedback.py --smoke`` in a subprocess; a
    driver that fails to run reports ``probe_free_error`` with the keys
    absent — absent reads as "not verified", never as "clean".
    """
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as tf:
        report_path = tf.name
    try:
        p = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "tools", "probe_free_feedback.py"),
                "--smoke", "--out", report_path,
            ],
            capture_output=True, text=True, cwd=REPO, timeout=timeout_s,
        )
        with open(report_path, encoding="utf-8") as fh:
            doc = json.load(fh)
        out = {
            "probe_free_feedback_violations": len(doc["violations"]),
            "probe_free_recovery_frac": doc["timing"]["recovery_frac"],
        }
        if p.returncode != 0 and not doc["violations"]:
            out["probe_free_error"] = (
                f"probe_free_feedback rc={p.returncode}"
            )
        return out
    except (subprocess.SubprocessError, OSError, ValueError, KeyError) as e:
        return {"probe_free_error": f"{type(e).__name__}: {e}"[:200]}
    finally:
        try:
            os.unlink(report_path)
        except OSError:
            pass


def run_arbiter_tripwire(timeout_s: int = 600) -> dict:
    """Supplementary keys ``arbiter_slo_violations`` — the elastic
    device pool exercised end-to-end on this exact tree (ISSUE 13; 0 = a
    Poisson burst breaches the windowed TTFT SLO, the arbiter preempts
    chips from the live sharded training run through the lease ledger,
    training resumes bitwise with zero lost steps, the burst drains, and
    the chips come back) — and informational ``arbiter_recovery_windows``
    (how many lease windows past the spike the p99 needed to recover;
    its <= 1.0 floor is enforced only in the committed full-run
    ARBITER_SPIKE.json — a CI container's timeshared minute cannot hold
    a timing floor honestly).

    Runs ``tools/arbiter_spike.py --smoke`` in a subprocess (it pins its
    own 4-vdev CPU mesh); a driver that fails to run reports
    ``arbiter_error`` with the keys absent — absent reads as "not
    verified", never as "clean".
    """
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as tf:
        report_path = tf.name
    try:
        p = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "tools", "arbiter_spike.py"),
                "--smoke", "--out", report_path,
            ],
            capture_output=True, text=True, cwd=REPO, timeout=timeout_s,
        )
        with open(report_path, encoding="utf-8") as fh:
            doc = json.load(fh)
        out = {
            "arbiter_slo_violations": len(doc["violations"]),
            "arbiter_recovery_windows": doc["recovery"]["recovery_windows"],
        }
        if p.returncode != 0 and not doc["violations"]:
            # rc=1 WITH violations is the driver doing its job; rc!=0
            # with a clean report means the driver itself malfunctioned
            out["arbiter_error"] = f"arbiter_spike rc={p.returncode}"
        return out
    except (subprocess.SubprocessError, OSError, ValueError, KeyError) as e:
        return {"arbiter_error": f"{type(e).__name__}: {e}"[:200]}
    finally:
        try:
            os.unlink(report_path)
        except OSError:
            pass


def run_coordination_tripwire(timeout_s: int = 600) -> dict:
    """Supplementary key ``coordination_violations`` — the coordinated
    elastic control plane exercised end-to-end on this exact tree
    (ISSUE 14; 0 = a coordinator SIGKILL'd mid-handshake fails over and
    the in-flight commit completes at the same epoch, an adversarial
    torn-ledger scribbler never crashes or mis-applies a decision, and a
    group-committed arbiter resize lands bitwise with the lease ack
    fenced on the control epoch).

    Runs ``tools/coord_chaos.py --smoke`` in a subprocess (3 real OS
    processes per scenario, real signals; the full kill-at-every-phase ×
    stall × gloo matrix lives in the committed COORD_CHAOS.json); a
    driver that fails to run reports ``coordination_error`` with the key
    absent — absent reads as "not verified", never as "clean".
    """
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as tf:
        report_path = tf.name
    try:
        p = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "tools", "coord_chaos.py"),
                "--smoke", "--out", report_path,
            ],
            capture_output=True, text=True, cwd=REPO, timeout=timeout_s,
        )
        with open(report_path, encoding="utf-8") as fh:
            doc = json.load(fh)
        violations = sum(
            0 if s.get("ok") else 1 for s in doc["scenarios"].values()
        )
        out = {"coordination_violations": violations}
        if p.returncode != 0 and not violations:
            # rc=1 WITH violations is the driver doing its job; rc!=0
            # with a clean report means the driver itself malfunctioned
            out["coordination_error"] = f"coord_chaos rc={p.returncode}"
        return out
    except (subprocess.SubprocessError, OSError, ValueError, KeyError) as e:
        return {"coordination_error": f"{type(e).__name__}: {e}"[:200]}
    finally:
        try:
            os.unlink(report_path)
        except OSError:
            pass


def run_rpc_chaos_tripwire(timeout_s: int = 600) -> dict:
    """Supplementary key ``rpc_chaos_violations`` — the real-process
    serving front door exercised end-to-end on this exact tree (ISSUE 16;
    0 = a replica SIGKILL'd mid-decode loses no request and forks no
    sequence, every torn response frame is CRC-caught and replayed from
    the idempotency store, and an intake spike sheds loudly with every
    rid accounted).

    Runs ``tools/rpc_chaos.py --smoke`` in a subprocess (real replica
    processes behind real TCP; the full matrix with the SIGTERM drain and
    the hedging A/B lives in the committed RPC_CHAOS.json); a driver that
    fails to run reports ``rpc_chaos_error`` with the key absent — absent
    reads as "not verified", never as "clean".
    """
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as tf:
        report_path = tf.name
    try:
        p = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "tools", "rpc_chaos.py"),
                "--smoke", "--out", report_path,
            ],
            capture_output=True, text=True, cwd=REPO, timeout=timeout_s,
        )
        with open(report_path, encoding="utf-8") as fh:
            doc = json.load(fh)
        violations = sum(
            0 if s.get("ok") else 1 for s in doc["scenarios"].values()
        )
        out = {"rpc_chaos_violations": violations}
        if p.returncode != 0 and not violations:
            out["rpc_chaos_error"] = f"rpc_chaos rc={p.returncode}"
        return out
    except (subprocess.SubprocessError, OSError, ValueError, KeyError) as e:
        return {"rpc_chaos_error": f"{type(e).__name__}: {e}"[:200]}
    finally:
        try:
            os.unlink(report_path)
        except OSError:
            pass


def run_serve_elastic_tripwire(timeout_s: int = 900) -> dict:
    """Supplementary key ``serving_tenancy_violations`` — the serving
    fleet as a lease-ledger tenant, exercised end-to-end on this exact
    tree (ISSUE 19; 0 = a restarted arbiter resumes its parked handoff,
    a drain ack with requests still in flight is refused as a
    ``ProtocolViolation``, and a SIGKILL'd predecessor's successor
    cold-starts loudly with every in-flight rid delivered exactly once).

    Runs ``tools/serve_elastic_chaos.py --smoke`` in a subprocess (the
    full matrix with the autoscale spike and the handoff/shed A/Bs
    lives in the committed SERVE_ELASTIC.json); a driver that fails to
    run reports ``serving_tenancy_error`` with the key absent — absent
    reads as "not verified", never as "clean".
    """
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as tf:
        report_path = tf.name
    try:
        p = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "tools", "serve_elastic_chaos.py"),
                "--smoke", "--out", report_path,
            ],
            capture_output=True, text=True, cwd=REPO, timeout=timeout_s,
        )
        with open(report_path, encoding="utf-8") as fh:
            doc = json.load(fh)
        violations = sum(
            0 if s.get("ok") else 1 for s in doc["scenarios"].values()
        )
        out = {"serving_tenancy_violations": violations}
        if p.returncode != 0 and not violations:
            out["serving_tenancy_error"] = (
                f"serve_elastic_chaos rc={p.returncode}"
            )
        return out
    except (subprocess.SubprocessError, OSError, ValueError, KeyError) as e:
        return {"serving_tenancy_error": f"{type(e).__name__}: {e}"[:200]}
    finally:
        try:
            os.unlink(report_path)
        except OSError:
            pass


def run_disagg_tripwire(timeout_s: int = 900) -> dict:
    """Supplementary keys ``disagg_migration_violations`` — prefill/
    decode disaggregation exercised end-to-end on this exact tree
    (ISSUE 20; 0 = every prompt past the planner's crossover prefills on
    a prefill replica, ships its KV over CRC-trailered frames to a
    decode replica, and completes bitwise vs the single-process
    ``generate`` oracle on BOTH codecs, int8 behind its error-bound +
    token-identity gates) — and ``disagg_decode_p99_ratio``
    (informational: disagg / colocated decode p99 inter-token latency at
    equal chips; the enforced <= 0.9x floor lives in the full run
    committed as BENCH_DISAGG.json, because CI-host latency is noise but
    correctness is not).

    Runs ``tools/bench_disagg.py --smoke`` in a subprocess (real replica
    processes behind real TCP); a driver that fails to run reports
    ``disagg_error`` with the keys absent — absent reads as "not
    verified", never as "clean".
    """
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as tf:
        report_path = tf.name
    try:
        p = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "tools", "bench_disagg.py"),
                "--smoke", "--out", report_path,
            ],
            capture_output=True, text=True, cwd=REPO, timeout=timeout_s,
        )
        with open(report_path, encoding="utf-8") as fh:
            doc = json.load(fh)
        violations = sum(
            0 if s.get("ok") else 1 for s in doc["scenarios"].values()
        )
        out = {"disagg_migration_violations": violations}
        perf = doc["scenarios"].get("disagg_vs_colocated", {})
        ratio = perf.get("checks", {}).get("decode_p99_ratio")
        if ratio is not None:
            out["disagg_decode_p99_ratio"] = ratio
        if p.returncode != 0 and not violations:
            out["disagg_error"] = f"bench_disagg rc={p.returncode}"
        return out
    except (subprocess.SubprocessError, OSError, ValueError, KeyError) as e:
        return {"disagg_error": f"{type(e).__name__}: {e}"[:200]}
    finally:
        try:
            os.unlink(report_path)
        except OSError:
            pass


def run_runtime_report_tripwire(timeout_s: int = 120) -> dict:
    """Supplementary key ``runtime_recovery_violations`` — mirrors
    ``analysis_violations``: a tiny supervised recovery exercise (one
    injected NaN step through the real ``fit``) run in a subprocess, its
    ``run_report.json`` checked against the expected accounting.  0 =
    the recovery machinery works end-to-end on this exact tree; any
    mismatch counts as a violation; a run that fails entirely reports
    ``runtime_report_error`` with the key absent — absent reads as "not
    verified", never as "clean".
    """
    try:
        p = subprocess.run(
            [sys.executable, "-c", _RUNTIME_TRIPWIRE_CODE.format(repo=REPO)],
            capture_output=True, text=True, timeout=timeout_s,
        )
        report = None
        for line in p.stdout.splitlines():
            if line.startswith("REPORT_JSON: "):
                report = json.loads(line[len("REPORT_JSON: "):])
        if report is None:
            return {
                "runtime_report_error": f"no report line (rc={p.returncode}); "
                f"stderr tail: {p.stderr[-200:]}"
            }
        violations = 0
        violations += report.get("anomalies") != 1
        violations += report.get("skipped_steps") != [3]
        # the runtime-supervision keys must exist (machine-readable contract)
        for key in ("step_timeouts", "stragglers", "membership_epochs",
                    "preempted_at", "background_saves"):
            violations += key not in report
        return {"runtime_recovery_violations": violations}
    except (subprocess.SubprocessError, OSError, ValueError) as e:
        return {"runtime_report_error": f"{type(e).__name__}: {e}"[:200]}


def main() -> int:
    if "--tpu-child" in sys.argv:
        # child mode: the actual TPU bench, unguarded (parent holds the
        # timeout); emit the JSON line and exit
        print(json.dumps(bench_tpu_kernel()))
        return 0
    try:
        result = None
        if tpu_alive():
            result = bench_tpu_kernel_guarded()
        if result is None:
            result = bench_cpu_allreduce()
    except Exception as e:  # never hang or die silently: emit a valid line
        result = {
            "metric": "bench_error",
            "value": 0.0,
            "unit": f"error:{type(e).__name__}",
            "vs_baseline": 0.0,
        }
    try:  # provenance stamp (supplementary key, reference CMakeLists:10-31)
        sys.path.insert(0, REPO)
        from flextree_tpu.utils.buildstamp import build_info

        result.setdefault("git", build_info()["git_describe"])
    except Exception:
        pass
    if result.get("metric") != "bench_error":
        # prefix smoke overlaps with everything below; joined at the end
        prefix_handle = start_prefix_tripwire()
        result.update(run_static_analysis_tripwire())
        result.update(run_runtime_report_tripwire())
        result.update(run_quantize_tripwire())
        result.update(run_overlap_tripwire())
        result.update(run_sharded_tripwire())
        result.update(run_serving_tripwire())
        result.update(run_paged_tripwire())
        result.update(run_obs_tripwire())
        result.update(run_feedback_tripwire())
        result.update(run_probe_free_tripwire())
        result.update(run_arbiter_tripwire())
        result.update(run_coordination_tripwire())
        result.update(run_rpc_chaos_tripwire())
        result.update(run_serve_elastic_tripwire())
        result.update(run_disagg_tripwire())
        result.update(collect_prefix_tripwire(prefix_handle))
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
