"""flextree-tpu: a TPU-native topology-parameterized collective framework.

Brand-new implementation of the capabilities of
Youhe-Jiang/AllReduce-Over-MPI ("FlexTree"): hierarchical allreduce with
configurable per-level tree widths, ring / flat / recursive-halving-doubling
special cases, an analytical cost model that picks the tree shape, and an A/B
benchmark harness — re-architected for TPU: schedules lower to
``lax.psum_scatter`` / ``lax.all_gather`` / ``lax.ppermute`` with
``axis_index_groups`` under ``shard_map``, so stages ride ICI/DCN and the
planner factors the device count along physical torus axes.
"""

from .utils import compat as _compat  # noqa: F401  installs jax API shims
from .schedule import (
    BlockLayout,
    Operation,
    LonelyTopology,
    Topology,
    TopologyError,
    get_stages,
    owned_blocks,
    parse_topo,
    recv_plan,
    ring_plan,
    send_plan,
)
from .ops import ReduceOp, SUPPORTED_OPS, get_op

__version__ = "0.1.0"

__all__ = [
    "BlockLayout",
    "Operation",
    "Topology",
    "LonelyTopology",
    "TopologyError",
    "get_stages",
    "owned_blocks",
    "parse_topo",
    "recv_plan",
    "ring_plan",
    "send_plan",
    "ReduceOp",
    "SUPPORTED_OPS",
    "get_op",
    "__version__",
]


def __getattr__(name):
    # Lazy: keep `import flextree_tpu` JAX-free for the pure schedule layer.
    if name in _PARALLEL_EXPORTS:
        from . import parallel

        return getattr(parallel, name)
    if name in _MODEL_EXPORTS:
        from . import models

        return getattr(models, name)
    if name in _INTERPOSE_EXPORTS:
        from . import interpose

        return getattr(interpose, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


# Names re-exported lazily from flextree_tpu.parallel (the JAX backend).
_PARALLEL_EXPORTS = (
    "allreduce",
    "tree_allreduce",
    "ring_allreduce",
    "reduce_scatter",
    "allgather",
    "allreduce_over_mesh",
    "flat_mesh",
    "topology_from_mesh",
    "ring_attention",
    "attention_reference",
    "TrainConfig",
    "factor_devices",
    "init_train_state",
    "make_mesh_3d",
    "make_train_step",
    "state_specs",
)

# Names re-exported lazily from flextree_tpu.models (the model substrate).
_MODEL_EXPORTS = (
    "TransformerConfig",
    "cross_entropy_loss",
    "forward",
    "init_params",
    "param_specs",
)

# The lax.psum interposer (the reference's MPI_Allreduce shadowing analog).
_INTERPOSE_EXPORTS = ("interposed", "install", "uninstall", "is_installed")
