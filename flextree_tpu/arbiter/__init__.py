"""One elastic device pool: a train/serve chip arbiter (docs/ARBITER.md).

Training and serving already share the runtime package (heartbeats,
leases, watchdogs, shrink/replan) but owned their devices statically.
This package unifies them behind a single inventory:

- :mod:`.inventory` — :class:`DeviceInventory`: single-assignment chip
  ownership (``train`` / ``serve`` / ``arbiter``-parked) with loud
  whole-set moves;
- :mod:`.core` — :class:`PoolArbiter`: leases chips to training by
  default, preempts them to serving replicas when the metrics registry's
  windowed TTFT p99 breaches the SLO, and returns them when the burst
  drains (hysteresis band + cooldown, so a single spike cannot thrash).

The cross-process protocol lives in :mod:`flextree_tpu.runtime.leases`
(epoch-numbered grants + acks on the heartbeat dir); training's side is
``parallel.loop.fit(arbiter=TrainLeaseClient(...))``, serving's side is
``ReplicaPool.add_replica`` / ``release_replica``.  The executed proof
is ``tools/arbiter_spike.py`` → ``ARBITER_SPIKE.json``.
"""

from .core import (
    ArbiterConfig,
    PoolArbiter,
    SloReading,
    file_slo_reader,
    pool_slo_reader,
)
from .inventory import DeviceInventory

__all__ = [
    "ArbiterConfig",
    "DeviceInventory",
    "PoolArbiter",
    "SloReading",
    "pool_slo_reader",
    "file_slo_reader",
]
