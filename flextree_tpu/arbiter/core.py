"""The pool arbiter: lease chips to training, preempt them to serving.

One host, one chip inventory, two tenants with opposite economics:
training wants every chip all the time and tolerates interruptions
(checkpoint → shrink → resume is SIGKILL-proven); serving wants chips
*exactly when traffic bursts* and its failure mode — TTFT blowing
through the SLO — is visible in the metrics registry within one rolling
window.  The :class:`PoolArbiter` closes that loop:

- **default**: training holds the leasable chips; serving runs its
  baseline replicas.
- **breach**: when the pool's windowed TTFT p99 (the
  :class:`~flextree_tpu.obs.metrics.WindowedHistogram` view — cumulative
  percentiles dilute a fresh breach after a quiet hour) exceeds
  ``slo_p99_ms`` for ``breach_ticks`` consecutive evaluations, the
  arbiter revokes ``burst_chips`` chips from training through the lease
  ledger (``runtime.leases``).  Training checkpoints NOW and shrinks —
  the arbiter-triggered twin of the SIGTERM-preemption path — then acks;
  only then are the chips granted to serving and the warmed burst
  replicas activated (``on_serve_grant``).
- **drain**: when the windowed p99 stays under ``release_frac *
  slo_p99_ms`` (the hysteresis low-water) for ``clear_ticks``
  evaluations AND ``cooldown_s`` has passed since the last action, the
  burst replicas drain (``on_serve_return`` — in-flight requests
  re-route exactly-once to survivors) and the chips return to training,
  which re-expands through the same re-shard machinery.

The hysteresis band (breach high-water vs ``release_frac`` low-water,
each with its own consecutive-tick debounce) plus the cooldown means a
single latency spike cannot thrash chips back and forth: moving a chip
costs a training checkpoint/restore cycle and a replica drain, so the
arbiter demands *sustained* evidence in both directions.

Every decision lands in the flight record — ``slo_breach`` on the breach
edge, ``lease_preempt`` / ``lease_grant`` / ``lease_return`` on the
moves, each carrying the SLO reading that drove it — and renders as the
arbiter lane of the merged Chrome trace (``obs/timeline.py``), beside
the train/serve spans it caused.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import math
import os
import time
from collections import deque

from ..obs import record_event
from ..obs.metrics import load_window, merged_window_percentile
from ..runtime.ctrlfile import read_control_json, write_control_json
from ..runtime.leases import ARBITER, SERVE, TRAIN, LeaseLedger
from ..utils.logging import get_logger
from .inventory import DeviceInventory

__all__ = [
    "ArbiterConfig",
    "SloReading",
    "PoolArbiter",
    "STATE_FILE",
    "file_slo_reader",
    "pool_slo_reader",
]

log = get_logger("flextree.arbiter")

#: the arbiter's own durable state beside the ledger: which chips are on
#: loan and which handoff is mid-flight — what a restarted arbiter needs
#: beyond the ledger (the ledger says WHERE chips are, not where a parked
#: set was HEADED)
STATE_FILE = "arbiter_state.json"

# injection point for tests (patch this, not time.time): cooldowns and
# ledger stamps are wall time, the heartbeat-dir convention
_wall = time.time


@dataclasses.dataclass(frozen=True)
class SloReading:
    """One evaluation of the serving pool's SLO state: the windowed TTFT
    percentile, how many samples the window holds (few samples = no
    evidence, not a breach), and the pool's cumulative admission-blocked
    count (the secondary pressure signal: requests waiting on cache
    blocks never got a TTFT stamp yet, so a saturated pool can breach on
    admit-pressure before the percentile moves)."""

    p99_ms: float
    samples: int
    admit_blocked: float = 0.0

    def to_payload(self) -> dict:
        return {
            "p99_ms": None if math.isnan(self.p99_ms) else round(self.p99_ms, 3),
            "samples": self.samples,
            "admit_blocked": self.admit_blocked,
        }


@dataclasses.dataclass(frozen=True)
class ArbiterConfig:
    """``slo_p99_ms``: the TTFT p99 target.  ``window_s`` is the lease
    window — the rolling-percentile horizon the breach check reads and
    the budget the spike driver holds recovery to.  The horizon
    physically lives in the serving engines' ``WindowedHistogram``\\ s
    (``ServingEngine(slo_window_s=...)``, same 10 s default); pass
    ``window_s`` to :func:`pool_slo_reader` to ENFORCE the match instead
    of trusting it.  ``release_frac`` sets
    the hysteresis low-water (return chips only once p99 is *well*
    inside the SLO, not hovering at it).  ``breach_ticks`` /
    ``clear_ticks`` debounce each edge in consecutive :meth:`~PoolArbiter.tick`
    evaluations; ``cooldown_s`` is the minimum wall time between chip
    moves.  ``min_train_chips`` floors training's world (a 0-chip
    trainer has no devices to checkpoint from); ``burst_chips`` is the
    handoff granularity.  ``min_samples``: windows thinner than this are
    "no evidence" — never a breach.  ``admit_blocked_delta`` (optional):
    additionally breach when the pool's admit-blocked count grew by at
    least this much since the previous tick.

    ``min_serve_prefill_chips`` / ``min_serve_decode_chips`` floor the
    DISAGGREGATED serving fleet per role: a scale-down never reclaims a
    chip whose departure would strand one role at (or below) its floor —
    a fleet with prefill replicas but zero decode replicas serves
    nothing, and the SLO reader cannot see that until the next breach.
    They only bind when the arbiter is built with ``serve_role_of`` (the
    chip → role map); 0 restores role-blind reclaim."""

    slo_p99_ms: float
    window_s: float = 10.0  # = ServingEngine's slo_window_s default
    release_frac: float = 0.5
    breach_ticks: int = 2
    clear_ticks: int = 3
    cooldown_s: float = 4.0
    min_train_chips: int = 1
    burst_chips: int = 2
    min_samples: int = 5
    admit_blocked_delta: float | None = None
    min_serve_prefill_chips: int = 0
    min_serve_decode_chips: int = 0

    def __post_init__(self):
        if self.slo_p99_ms <= 0:
            raise ValueError(f"slo_p99_ms must be > 0, got {self.slo_p99_ms}")
        if not 0.0 < self.release_frac < 1.0:
            raise ValueError(
                f"release_frac must sit strictly inside (0, 1) — it IS the "
                f"hysteresis band — got {self.release_frac}"
            )
        if self.min_train_chips < 1:
            raise ValueError("min_train_chips must be >= 1")
        if self.burst_chips < 1:
            raise ValueError("burst_chips must be >= 1")
        if self.min_serve_prefill_chips < 0:
            raise ValueError("min_serve_prefill_chips must be >= 0")
        if self.min_serve_decode_chips < 0:
            raise ValueError("min_serve_decode_chips must be >= 0")


def pool_slo_reader(pool, q: float = 99.0, *, window_s: float | None = None):
    """An :class:`SloReading` source over a serving
    :class:`~flextree_tpu.serving.pool.ReplicaPool`: merge the alive
    replicas' windowed ``serve.ttft_ms`` histograms (the SLO is a
    property of the POOL, not any one replica) and sum their
    ``serve.admit_blocked`` counters.  Pass ``window_s`` (=
    ``ArbiterConfig.window_s``) to enforce that every replica's TTFT
    window actually spans the horizon the breach check claims to read —
    a mismatched engine is a loud error, not a silently-wrong lease
    window."""

    def read() -> SloReading:
        hists = []
        blocked = 0.0
        for r in pool.replicas:
            if not r.alive:
                continue
            m = r.engine.metrics
            if "serve.ttft_ms" in m:
                h = m.windowed_histogram("serve.ttft_ms")
                if window_s is not None and abs(h.window_s - window_s) > 1e-9:
                    raise ValueError(
                        f"replica {r.rank}'s TTFT window spans "
                        f"{h.window_s:g}s but the arbiter evaluates a "
                        f"{window_s:g}s lease window — build the engine "
                        f"with slo_window_s={window_s:g}"
                    )
                hists.append(h)
            if "serve.admit_blocked" in m:
                blocked += m.counter("serve.admit_blocked").value
        p99, n = merged_window_percentile(hists, q)
        return SloReading(p99_ms=p99, samples=n, admit_blocked=blocked)

    return read


def file_slo_reader(
    dir: str,
    q: float = 99.0,
    *,
    metric: str = "serve.ttft_ms",
    window_s: float | None = None,
    prefix: str = "metrics_fd_",
):
    """An :class:`SloReading` source over METRICS FILES — the
    cross-process twin of :func:`pool_slo_reader`, for an arbiter whose
    serving tenant is a fleet of real replica processes it cannot reach
    into.

    Reads every ``{prefix}*.json`` snapshot in ``dir`` (the front door's
    :meth:`~flextree_tpu.serving.frontdoor.FrontDoor.write_metrics`
    per-replica files by default), reconstructs each one's windowed
    ``metric`` series (:func:`~flextree_tpu.obs.metrics.load_window` —
    the rolling window survives the file round-trip now; a pre-series
    payload or torn file contributes NO evidence, never a frozen p99),
    and merges them into one pool-level reading, aged against the wall
    clock so a replica that stopped writing decays to empty instead of
    asserting its last breach forever.  ``window_s`` enforcement matches
    :func:`pool_slo_reader`: a snapshot whose window spans a different
    horizon than the breach check claims to read is a loud error."""

    def read() -> SloReading:
        wins = []
        for path in sorted(glob.glob(os.path.join(dir, prefix + "*.json"))):
            try:
                with open(path, encoding="utf-8") as f:
                    snap = json.load(f)
            except (OSError, ValueError):
                continue  # mid-replace / vanished: no evidence this tick
            payload = (snap.get("histograms") or {}).get(metric)
            if payload is None:
                continue
            fw = load_window(payload)
            if fw is None:
                continue  # summary-only payload: absent ≠ clean, skip
            if window_s is not None and abs(fw.window_s - window_s) > 1e-9:
                raise ValueError(
                    f"{os.path.basename(path)}'s {metric} window spans "
                    f"{fw.window_s:g}s but the arbiter evaluates a "
                    f"{window_s:g}s lease window — build the writer with "
                    f"slo_window_s={window_s:g}"
                )
            wins.append(fw)
        p99, n = merged_window_percentile(wins, q, now=time.time())
        return SloReading(p99_ms=p99, samples=n)

    return read


class PoolArbiter:
    """One elastic device pool over a :class:`DeviceInventory` and a
    :class:`~flextree_tpu.runtime.LeaseLedger`.

    The arbiter is a pure decision engine driven by :meth:`tick` (the
    host loop's cadence — the spike driver calls it between pool rounds;
    a daemon-thread wrapper is trivial but the explicit tick keeps tests
    deterministic).  It never touches engines or meshes itself:
    ``on_serve_grant(chips)`` / ``on_serve_return(chips)`` are the
    serving-side hooks (activate warmed replicas / drain them), and
    training reacts through its own :class:`~flextree_tpu.runtime.TrainLeaseClient`
    poll — the arbiter only ever writes the ledger.

    The revoke → ack → grant handoff is two-phase across ticks: chips
    taken from training park on the ``"arbiter"`` holder until training's
    ack lands in the ledger, and only then move to serving.  A chip is
    therefore never promised to two tenants, no matter how slow the
    trainer's checkpoint/rebuild is — the handoff stretches, it never
    races.
    """

    def __init__(
        self,
        inventory: DeviceInventory,
        ledger: LeaseLedger,
        cfg: ArbiterConfig,
        *,
        slo_reader,
        on_serve_grant=None,
        on_serve_return=None,
        serve_is_tenant: bool = False,
        serve_role_of=None,
    ):
        self.inventory = inventory
        self.ledger = ledger
        self.cfg = cfg
        self.slo_reader = slo_reader
        self.on_serve_grant = on_serve_grant
        self.on_serve_return = on_serve_return
        # chip -> serving role ("prefill" / "decode" / "both"): the map
        # the per-role tenancy floors consult on scale-down.  None means
        # a colocated fleet — the floors never bind.
        self.serve_role_of = serve_role_of
        # serving as a LEDGER TENANT: scale-down is a revoke → drain →
        # ack → grant-back handshake through the ledger (the serving
        # fleet's ServeLeaseClient drains real replica processes and acks
        # only after), not a synchronous on_serve_return call — chips
        # leave serving only once serving provably stopped using them,
        # exactly the guarantee training already had.
        self.serve_is_tenant = bool(serve_is_tenant)
        self._pending: dict | None = None  # parked, awaiting src's ack
        self._loaned: list = []  # chips currently on loan to serving
        self._breach_streak = 0
        self._clear_streak = 0
        self._last_action_wall = -math.inf
        self._last_reading: SloReading | None = None  # admit-blocked delta
        # bounded audit tail (the flight recorder carries the durable
        # record; this is the in-memory window drivers/tests read)
        self.decisions: deque = deque(maxlen=4096)
        # the starting assignment goes on the record before any tenant
        # polls (TrainLeaseClient adopts it as its baseline).  A restart
        # against a heartbeat dir that already carries a ledger SUPERSEDES
        # it — the new arbiter's inventory is the fresh truth, and epochs
        # keep increasing so no tenant can mistake the old grant for news.
        prior = self.ledger.read()
        self._epoch = 0 if prior is None else prior.epoch + 1
        self._resume_state(prior)
        self.ledger.publish(self._epoch, inventory.grants(), reason="initial")
        self._save_state()
        record_event(
            "lease_grant",
            holder=TRAIN,
            chips=list(inventory.held_by(TRAIN)),
            epoch=self._epoch,
            reason="initial",
        )

    # ---- durable state (the restart-mid-handoff story) ---------------------

    @property
    def _state_path(self) -> str:
        return os.path.join(self.ledger.dir, STATE_FILE)

    def _save_state(self) -> None:
        write_control_json(
            self.ledger.dir, self._state_path,
            {
                "loaned": list(self._loaned),
                "pending": None if self._pending is None else {
                    "chips": list(self._pending["chips"]),
                    "epoch": self._pending["epoch"],
                    "src": self._pending["src"],
                    "dst": self._pending["dst"],
                },
            },
        )

    def _resume_state(self, prior) -> None:
        """Adopt a predecessor's loan/pending bookkeeping, validated
        against the inventory the caller rebuilt from the ledger — a
        restart mid-handoff must finish the handoff (the parked chips'
        destination is state the ledger alone cannot carry), not strand
        chips on the arbiter holder forever."""
        if prior is None:
            return
        doc = read_control_json(self._state_path)
        if doc is None:
            return  # no predecessor state (or torn): start conservative
        parked = set(self.inventory.held_by(ARBITER))
        serve = set(self.inventory.held_by(SERVE))
        loaned = [c for c in doc.get("loaned") or () if c in serve]
        self._loaned = loaned
        p = doc.get("pending")
        if (
            isinstance(p, dict)
            and p.get("src") in (TRAIN, SERVE)
            and p.get("dst") in (TRAIN, SERVE)
            and p.get("chips")
            and set(p["chips"]) <= parked
        ):
            # the revoke epoch predates our restart; our "initial"
            # publish below re-announces the same shrunken grant at a
            # NEWER epoch, and the source tenant's ack of either epoch
            # proves it applied the revocation — gate on the older one
            self._pending = {
                "chips": tuple(p["chips"]),
                "epoch": int(p["epoch"]),
                "src": p["src"],
                "dst": p["dst"],
            }
            log.warning(
                "arbiter restart: resuming handoff of chips %s "
                "(%s -> %s, revoke epoch %d)",
                list(self._pending["chips"]), self._pending["src"],
                self._pending["dst"], self._pending["epoch"],
            )

    # ---- bookkeeping -------------------------------------------------------

    @property
    def loaned(self) -> tuple:
        """Chips currently preempted from training to serving."""
        return tuple(self._loaned)

    @property
    def pending_handoff(self) -> tuple:
        """Chips revoked from training but not yet granted to serving
        (awaiting training's ack) — empty when no handoff is in flight."""
        return tuple(self._pending["chips"]) if self._pending else ()

    def _publish(self, reason: str) -> int:
        self._epoch += 1
        self.ledger.publish(self._epoch, self.inventory.grants(), reason=reason)
        return self._epoch

    # ---- the decision loop -------------------------------------------------

    def tick(self) -> dict:
        """One SLO evaluation + at most one protocol action.  Returns the
        decision record (also appended to ``self.decisions``)."""
        now = _wall()
        reading = self.slo_reader()
        cfg = self.cfg
        grew = None
        if cfg.admit_blocked_delta is not None and self._last_reading is not None:
            grew = reading.admit_blocked - self._last_reading.admit_blocked
        self._last_reading = reading
        has_evidence = reading.samples >= cfg.min_samples
        over = has_evidence and reading.p99_ms > cfg.slo_p99_ms
        pressured = (
            grew is not None and grew >= cfg.admit_blocked_delta
        )
        breached = over or pressured
        # "clear" needs the window to be POSITIVELY quiet: either no
        # traffic at all, or a well-inside-SLO percentile.  A thin window
        # (few samples) is neither breach nor clear.
        cleared = reading.samples == 0 or (
            has_evidence
            and not math.isnan(reading.p99_ms)
            and reading.p99_ms <= cfg.release_frac * cfg.slo_p99_ms
        )
        if breached:
            if self._breach_streak == 0:
                record_event(
                    "slo_breach",
                    slo_p99_ms=cfg.slo_p99_ms,
                    window_s=cfg.window_s,
                    over=over,
                    admit_pressure=pressured,
                    **reading.to_payload(),
                )
            self._breach_streak += 1
            self._clear_streak = 0
        elif cleared:
            self._clear_streak += 1
            self._breach_streak = 0
        else:  # inside the hysteresis band: hold the current allocation
            self._breach_streak = 0
            self._clear_streak = 0

        cooled = now - self._last_action_wall >= cfg.cooldown_s
        action = None
        if self._pending is not None:
            action = self._maybe_complete_handoff(reading)
        elif (
            breached
            and self._breach_streak >= cfg.breach_ticks
            and cooled
        ):
            action = self._preempt(reading, now)
        elif (
            self._loaned
            and self._clear_streak >= cfg.clear_ticks
            and cooled
        ):
            action = self._return(reading, now)

        decision = {
            "wall": now,
            "reading": reading.to_payload(),
            "breached": breached,
            "cleared": cleared,
            "breach_streak": self._breach_streak,
            "clear_streak": self._clear_streak,
            "action": action,
            "epoch": self._epoch,
            "train_chips": list(self.inventory.held_by(TRAIN)),
            "serve_chips": list(self.inventory.held_by(SERVE)),
            "loaned": list(self._loaned),
            "pending": None if self._pending is None
            else list(self._pending["chips"]),
        }
        self.decisions.append(decision)
        return decision

    # ---- actions -----------------------------------------------------------

    def _preempt(self, reading: SloReading, now: float):
        """Phase 1 of the scale-up handoff: revoke chips from training
        (park on the arbiter holder) and wait for training's ack."""
        chips = self.inventory.take(
            TRAIN, self.cfg.burst_chips, keep=self.cfg.min_train_chips
        )
        if not chips:
            return None  # training already at its floor: nothing to move
        epoch = self._publish(
            f"slo breach: p99 {reading.p99_ms:.1f}ms > "
            f"{self.cfg.slo_p99_ms:.1f}ms"
        )
        self._pending = {
            "chips": chips, "epoch": epoch, "src": TRAIN, "dst": SERVE,
        }
        self._last_action_wall = now
        self._save_state()
        record_event(
            "lease_preempt",
            chips=list(chips),
            holder_from=TRAIN,
            epoch=epoch,
            **reading.to_payload(),
        )
        log.warning(
            "arbiter: SLO breach (p99 %.1fms > %.1fms, %d samples) — "
            "revoking chips %s from training (epoch %d)",
            reading.p99_ms, self.cfg.slo_p99_ms, reading.samples,
            list(chips), epoch,
        )
        return "preempt"

    def _maybe_complete_handoff(self, reading: SloReading):
        """Phase 2 of either handoff direction: once the SOURCE tenant
        acked the revocation epoch (training: checkpointed + shrunk;
        serving: replicas drained — its client refuses to ack sooner),
        hand the parked chips to the destination."""
        pending = self._pending
        # ONE ack read serves both fields — two reads could pair the
        # epoch from one ack version with the control stamp of another
        ack = self.ledger.read_ack(pending["src"]) or {}
        try:
            acked = int(ack["epoch"])
        except (KeyError, ValueError, TypeError):
            acked = -1
        if acked < pending["epoch"]:
            return None  # source still checkpointing/draining: wait
        # a coordinated (multi-process) tenant stamps the control epoch it
        # group-applied the revocation under (runtime.coordination's
        # fencing: the ack provably post-dates the apply); single-process
        # tenants leave it None — record whichever the ack carries
        control_epoch = ack.get("control_epoch")
        dst = pending["dst"]
        chips = self.inventory.move(pending["chips"], ARBITER, dst)
        epoch = self._publish(f"granting {list(chips)} to {dst}")
        if dst == SERVE:
            self._loaned.extend(chips)
        else:
            self._loaned = [c for c in self._loaned if c not in chips]
        self._pending = None
        # the grant IS a chip move: the cooldown restarts here, so a
        # burst that ends while the trainer was still checkpointing
        # cannot bounce the chips straight back on the next tick
        self._last_action_wall = _wall()
        self._save_state()
        record_event(
            "lease_grant" if dst == SERVE else "lease_return",
            chips=list(chips),
            holder=dst,
            epoch=epoch,
            control_epoch=control_epoch,
            **reading.to_payload(),
        )
        if dst == SERVE and self.on_serve_grant is not None:
            self.on_serve_grant(chips)
        if dst == TRAIN and self.on_serve_return is not None:
            # tenant mode: the fleet already drained before serving's ack
            # — this hook is notification, not the drain itself
            self.on_serve_return(chips)
        log.warning(
            "arbiter: chips %s granted to %s (epoch %d)",
            list(chips), dst, epoch,
        )
        return "grant" if dst == SERVE else "return"

    def _reclaimable(self) -> tuple:
        """Split the loaned chips into (take, withheld) under the
        per-role tenancy floors: a chip stays with serving when
        reclaiming it would drop its role's serve-chip count below
        ``min_serve_{prefill,decode}_chips``.  Chips mapping to
        ``"both"`` (or any role without a floor) reclaim freely; with no
        ``serve_role_of`` map or all-zero floors the split is the old
        role-blind take-everything."""
        chips = tuple(self._loaned)
        floors = {
            "prefill": self.cfg.min_serve_prefill_chips,
            "decode": self.cfg.min_serve_decode_chips,
        }
        if self.serve_role_of is None or not any(floors.values()):
            return chips, ()
        counts: dict = {}
        for c in self.inventory.held_by(SERVE):
            role = self.serve_role_of(c)
            counts[role] = counts.get(role, 0) + 1
        take, withheld = [], []
        for c in chips:
            role = self.serve_role_of(c)
            if counts.get(role, 0) - 1 < floors.get(role, 0):
                withheld.append(c)
                continue
            counts[role] = counts.get(role, 0) - 1
            take.append(c)
        return tuple(take), tuple(withheld)

    def _return(self, reading: SloReading, now: float):
        """Scale-down.  Tenant mode: phase 1 of the reverse handoff —
        revoke the loaned chips from serving (park them), publish, and
        wait for serving's ack (its lease client SIGTERM-drains the
        replica processes and refuses to ack while requests are in
        flight).  Legacy in-process mode: drain synchronously via
        ``on_serve_return`` and move the chips in one tick.  Either way
        the per-role tenancy floors filter the reclaim first: a chip
        whose departure would strand prefill or decode below its floor
        stays loaned (loudly — ``lease_withheld``), so a burst that
        scaled up one role can never drain the other to zero."""
        chips, withheld = self._reclaimable()
        if withheld:
            record_event(
                "lease_withheld",
                chips=list(withheld),
                reason="role_floor",
                **reading.to_payload(),
            )
            log.warning(
                "arbiter: scale-down withholds chips %s — reclaiming "
                "them would strand a serving role below its tenancy "
                "floor", list(withheld),
            )
        if not chips:
            return None  # everything loaned is floor-pinned: hold
        p99_txt = (
            "-" if math.isnan(reading.p99_ms) else round(reading.p99_ms, 1)
        )
        if self.serve_is_tenant:
            self.inventory.move(chips, SERVE, ARBITER)
            epoch = self._publish(
                f"burst drained: reclaiming {list(chips)} from serving "
                f"(p99 {p99_txt}ms inside "
                f"{self.cfg.release_frac:.0%} of SLO)"
            )
            self._pending = {
                "chips": chips, "epoch": epoch, "src": SERVE, "dst": TRAIN,
            }
            self._last_action_wall = now
            self._save_state()
            record_event(
                "lease_preempt",
                chips=list(chips),
                holder_from=SERVE,
                epoch=epoch,
                **reading.to_payload(),
            )
            log.warning(
                "arbiter: burst drained — revoking chips %s from serving "
                "(epoch %d), awaiting drain ack", list(chips), epoch,
            )
            return "preempt"
        if self.on_serve_return is not None:
            self.on_serve_return(chips)
        self.inventory.move(chips, SERVE, TRAIN)
        self._loaned = [c for c in self._loaned if c not in chips]
        epoch = self._publish(
            f"burst drained: p99 {p99_txt}"
            f"ms inside {self.cfg.release_frac:.0%} of SLO"
        )
        self._last_action_wall = now
        self._save_state()
        record_event(
            "lease_return",
            chips=list(chips),
            holder=TRAIN,
            epoch=epoch,
            **reading.to_payload(),
        )
        log.warning(
            "arbiter: burst drained — chips %s returned to training "
            "(epoch %d)", list(chips), epoch,
        )
        return "return"
