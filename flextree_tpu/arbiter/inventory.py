"""Device inventory: which chips exist, who holds each one.

The inventory is the arbiter's in-memory model of the machine's chips —
a chip is an opaque id (an index into ``jax.devices()`` on this host;
any stable token works) with exactly one holder at a time: ``"train"``,
``"serve"``, or ``"arbiter"`` (parked mid-handoff).  Every mutation is a
whole-set move with loud failure on a chip that is not where the caller
thinks it is — the single-assignment invariant is what the lease ledger
publishes, so it must be impossible to corrupt here first.
"""

from __future__ import annotations

from ..runtime.leases import ARBITER, SERVE, TRAIN

__all__ = ["DeviceInventory"]

_HOLDERS = (TRAIN, SERVE, ARBITER)


class DeviceInventory:
    """Single-assignment chip ownership with whole-set moves.

    ``grants()`` is the ledger-shaped view (holder → sorted chip tuple);
    :meth:`move` relocates a specific chip set and refuses partial or
    misattributed moves, so a bookkeeping bug surfaces as a raise, never
    as a chip silently counted twice.
    """

    def __init__(self, chips, *, train=None):
        chips = tuple(chips)
        if len(set(chips)) != len(chips):
            raise ValueError(f"duplicate chip ids in inventory: {chips}")
        if not chips:
            raise ValueError("an inventory needs at least one chip")
        train = tuple(chips if train is None else train)
        unknown = [c for c in train if c not in chips]
        if unknown:
            raise ValueError(f"train grant names unknown chips: {unknown}")
        self._holder = {c: (TRAIN if c in train else SERVE) for c in chips}

    @classmethod
    def from_grants(cls, grants: dict) -> "DeviceInventory":
        """Rebuild an inventory from a ledger-shaped grants dict (holder
        → chip iterable) — the arbiter-restart path: the last published
        ledger IS the surviving truth about who holds what, parked
        (``"arbiter"``) chips included, which the ``train=`` constructor
        cannot express."""
        holder: dict = {}
        for h, chips in grants.items():
            if h not in _HOLDERS:
                raise ValueError(f"unknown holder {h!r} in grants")
            for c in chips:
                if c in holder:
                    raise ValueError(
                        f"chip {c!r} granted to both {holder[c]!r} and {h!r}"
                    )
                holder[c] = h
        if not holder:
            raise ValueError("an inventory needs at least one chip")
        inv = cls.__new__(cls)
        inv._holder = holder
        return inv

    @property
    def chips(self) -> tuple:
        return tuple(sorted(self._holder))

    def held_by(self, holder: str) -> tuple:
        if holder not in _HOLDERS:
            raise ValueError(f"unknown holder {holder!r}")
        return tuple(sorted(c for c, h in self._holder.items() if h == holder))

    def holder_of(self, chip) -> str:
        try:
            return self._holder[chip]
        except KeyError:
            raise ValueError(f"chip {chip!r} is not in the inventory") from None

    def move(self, chips, src: str, dst: str) -> tuple:
        """Move ``chips`` from ``src`` to ``dst`` — all or nothing."""
        if src not in _HOLDERS or dst not in _HOLDERS:
            raise ValueError(f"unknown holder in move {src!r} -> {dst!r}")
        chips = tuple(chips)
        for c in chips:
            h = self.holder_of(c)
            if h != src:
                raise ValueError(
                    f"chip {c!r} is held by {h!r}, not {src!r} — refusing "
                    "the whole move"
                )
        for c in chips:
            self._holder[c] = dst
        return tuple(sorted(chips))

    def take(self, holder: str, k: int, *, keep: int = 0) -> tuple:
        """Park up to ``k`` of ``holder``'s chips on the arbiter (the
        revocation half of a handoff), never leaving fewer than ``keep``.
        Returns the chips actually taken (possibly empty)."""
        held = self.held_by(holder)
        k = max(0, min(k, len(held) - keep))
        taken = held[len(held) - k:]  # take from the tail: stable ids keep
        return self.move(taken, holder, ARBITER) if taken else ()

    def grants(self) -> dict:
        """The ledger-shaped view: holder → sorted chip tuple (holders
        with no chips included, so a reader sees explicit emptiness)."""
        return {h: self.held_by(h) for h in _HOLDERS}
