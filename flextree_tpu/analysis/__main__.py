"""CLI: run the full static-analysis suite and emit a JSON report.

    python -m flextree_tpu.analysis --report ANALYSIS.json

Exit status is the CI contract: 0 iff the clean tree reports zero
violations AND every seeded corruption class is caught by its layer.
``--skip-hlo`` runs only the JAX-less layers (schedule model checker,
jit hygiene, control-plane protocol checker, concurrency lint) for
environments without a usable backend; the committed report is always
produced by a full run.

``--programs SUBSTR [SUBSTR ...]`` filters the schedule / split-phase /
IR-family / ir-equivalence matrices to rows whose name contains any of
the substrings — the growing matrix stays debuggable one program at a
time.  The report carries per-program wall-times (``program_times``) so
a row creeping toward the 60 s budget is visible in the artifact, not
just in CI duration graphs.  (Both the filter flag and the timing block
are excluded from the CI staleness comparison —
``tools/run_static_checks.py`` strips the volatile keys.)
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _configure_cpu_mesh() -> None:
    """Pin 8 virtual CPU devices before any backend initializes — same
    gotchas as ``tests/conftest.py`` (the axon TPU plugin can wedge
    backend init; ``jax_platforms=cpu`` is the only reliable lever)."""
    import jax

    from ..utils.compat import request_cpu_devices

    jax.config.update("jax_platforms", "cpu")
    try:
        request_cpu_devices(8)
    except RuntimeError:
        pass  # backends already up (e.g. under pytest): use what exists


def build_report(include_hlo: bool = True, programs=None) -> dict:
    """One report from the SAME library loops the tests and gates call —
    ``programs``/``times`` are hooks on those functions, never a second
    copy of their matrix logic (the drift class this PR exists to kill)."""
    from ..schedule.analysis import traffic_summary
    from ..schedule.stages import Topology
    from .base import violations_to_json
    from .concurrency_lint import run_concurrency_lint
    from .jit_hygiene import run_jit_hygiene
    from .mutation import run_mutation_selftest
    from .protocol_check import run_protocol_check
    from .schedule_check import (
        check_ir_families,
        check_split_schedules,
        check_standard_schedules,
    )

    t0 = time.perf_counter()
    report: dict = {"layers": {}}
    times: dict = {}
    violations = []

    for layer, fn in (
        ("schedule_check", check_standard_schedules),
        ("split_schedule_check", check_split_schedules),
        ("ir_check", check_ir_families),
    ):
        layer_times: dict = {}
        vs, checked = fn(programs=programs, times=layer_times)
        violations += vs
        report["layers"][layer] = {
            "programs_checked": checked,
            "violations": len(vs),
        }
        times[layer] = layer_times

    if include_hlo:
        from .hlo_lint import run_hlo_lint
        from .ir_equivalence import run_ir_equivalence

        hlo_v, hlo_detail = run_hlo_lint(full=True)
        violations += hlo_v
        report["layers"]["hlo_lint"] = {
            "entrypoints": hlo_detail,
            "violations": len(hlo_v),
        }

        # ir_equivalence: the lowered StableHLO's collective sequence
        # must match the IR stage list (count/kind/width/pairs/bytes)
        eq_times: dict = {}
        eq_v, eq_detail = run_ir_equivalence(programs=programs, times=eq_times)
        violations += eq_v
        report["layers"]["ir_equivalence"] = {
            "entrypoints": eq_detail,
            "violations": len(eq_v),
        }
        times["ir_equivalence"] = eq_times

    jit_v, jit_detail = run_jit_hygiene()
    violations += jit_v
    report["layers"]["jit_hygiene"] = {**jit_detail, "violations": len(jit_v)}

    # layer 4: exhaustive small-world exploration of the control-plane
    # protocol models (JAX-less — runs in --skip-hlo environments too)
    proto_times: dict = {}
    proto_v, proto_detail = run_protocol_check(
        programs=programs, times=proto_times
    )
    violations += proto_v
    report["layers"]["protocol_check"] = {
        **proto_detail, "violations": len(proto_v),
    }
    times["protocol_check"] = proto_times

    # layer 5: concurrency / lock-discipline lint over the threaded
    # host code (also JAX-less)
    conc_times: dict = {}
    conc_v, conc_detail = run_concurrency_lint(
        programs=programs, times=conc_times
    )
    violations += conc_v
    report["layers"]["concurrency_lint"] = {
        **conc_detail, "violations": len(conc_v),
    }
    times["concurrency_lint"] = conc_times

    report["mutation_selftest"] = run_mutation_selftest(include_hlo=include_hlo)
    report["violations"] = violations_to_json(violations)
    report["analysis_violations"] = len(violations)
    # traffic accounting for the report's headline shapes (schedule/analysis)
    report["traffic"] = {
        "4,2@8x64xf32": traffic_summary(Topology(8, (4, 2)), 64, 4),
        "2,2,2@8x64xf32": traffic_summary(Topology(8, (2, 2, 2)), 64, 4),
    }
    report["program_times"] = times
    report["elapsed_s"] = round(time.perf_counter() - t0, 2)
    report["ok"] = (
        not violations and report["mutation_selftest"]["all_caught"]
    )
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m flextree_tpu.analysis")
    ap.add_argument("--report", metavar="PATH", help="write the JSON report here")
    ap.add_argument(
        "--skip-hlo",
        action="store_true",
        help="skip the HLO lint layer (no JAX backend required)",
    )
    ap.add_argument(
        "--programs",
        nargs="+",
        metavar="SUBSTR",
        help="only check matrix programs whose name contains a substring "
        "(e.g. --programs swing '4,2@8')",
    )
    args = ap.parse_args(argv)

    if not args.skip_hlo:
        _configure_cpu_mesh()
    report = build_report(
        include_hlo=not args.skip_hlo, programs=args.programs
    )

    if args.report:
        with open(args.report, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2, sort_keys=False)
            fh.write("\n")

    n_v = report["analysis_violations"]
    mut = report["mutation_selftest"]
    caught = sum(1 for c in mut["classes"].values() if c["caught"])
    print(
        f"flextree static analysis: {n_v} violations; mutation self-test "
        f"{caught}/{len(mut['classes'])} classes caught; "
        f"{report['elapsed_s']}s"
    )
    slowest = sorted(
        (
            (ms, f"{layer}:{name}")
            for layer, rows in report["program_times"].items()
            for name, ms in rows.items()
        ),
        reverse=True,
    )[:3]
    for ms, name in slowest:
        print(f"  slowest: {name} {ms}ms")
    for row in report["violations"]:
        print(f"  {row['layer']}/{row['kind']} @ {row['where']}: {row['detail']}")
    for name, row in mut["classes"].items():
        if not row["caught"]:
            print(f"  MUTATION ESCAPED: {name} (expected {row['expected']})")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
