"""CLI: run the full static-analysis suite and emit a JSON report.

    python -m flextree_tpu.analysis --report ANALYSIS.json

Exit status is the CI contract: 0 iff the clean tree reports zero
violations AND every seeded corruption class is caught by its layer.
``--skip-hlo`` runs only the JAX-less layers (schedule model checker +
jit hygiene) for environments without a usable backend; the committed
report is always produced by a full run.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _configure_cpu_mesh() -> None:
    """Pin 8 virtual CPU devices before any backend initializes — same
    gotchas as ``tests/conftest.py`` (the axon TPU plugin can wedge
    backend init; ``jax_platforms=cpu`` is the only reliable lever)."""
    import jax

    from ..utils.compat import request_cpu_devices

    jax.config.update("jax_platforms", "cpu")
    try:
        request_cpu_devices(8)
    except RuntimeError:
        pass  # backends already up (e.g. under pytest): use what exists


def build_report(include_hlo: bool = True) -> dict:
    from ..schedule.analysis import traffic_summary
    from ..schedule.stages import Topology
    from .base import violations_to_json
    from .jit_hygiene import run_jit_hygiene
    from .mutation import run_mutation_selftest
    from .schedule_check import check_split_schedules, check_standard_schedules

    t0 = time.perf_counter()
    report: dict = {"layers": {}}
    violations = []

    sched_v, programs = check_standard_schedules()
    violations += sched_v
    report["layers"]["schedule_check"] = {
        "programs_checked": programs,
        "violations": len(sched_v),
    }

    # standalone reduce-scatter / all-gather programs (PR 7): conservation
    # proves each rank ends with exactly its owned block / the full vector
    split_v, split_programs = check_split_schedules()
    violations += split_v
    report["layers"]["split_schedule_check"] = {
        "programs_checked": split_programs,
        "violations": len(split_v),
    }

    if include_hlo:
        from .hlo_lint import run_hlo_lint

        hlo_v, hlo_detail = run_hlo_lint(full=True)
        violations += hlo_v
        report["layers"]["hlo_lint"] = {
            "entrypoints": hlo_detail,
            "violations": len(hlo_v),
        }

    jit_v, jit_detail = run_jit_hygiene()
    violations += jit_v
    report["layers"]["jit_hygiene"] = {**jit_detail, "violations": len(jit_v)}

    report["mutation_selftest"] = run_mutation_selftest(include_hlo=include_hlo)
    report["violations"] = violations_to_json(violations)
    report["analysis_violations"] = len(violations)
    # traffic accounting for the report's headline shapes (schedule/analysis)
    report["traffic"] = {
        "4,2@8x64xf32": traffic_summary(Topology(8, (4, 2)), 64, 4),
        "2,2,2@8x64xf32": traffic_summary(Topology(8, (2, 2, 2)), 64, 4),
    }
    report["elapsed_s"] = round(time.perf_counter() - t0, 2)
    report["ok"] = (
        not violations and report["mutation_selftest"]["all_caught"]
    )
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m flextree_tpu.analysis")
    ap.add_argument("--report", metavar="PATH", help="write the JSON report here")
    ap.add_argument(
        "--skip-hlo",
        action="store_true",
        help="skip the HLO lint layer (no JAX backend required)",
    )
    args = ap.parse_args(argv)

    if not args.skip_hlo:
        _configure_cpu_mesh()
    report = build_report(include_hlo=not args.skip_hlo)

    if args.report:
        with open(args.report, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2, sort_keys=False)
            fh.write("\n")

    n_v = report["analysis_violations"]
    mut = report["mutation_selftest"]
    caught = sum(1 for c in mut["classes"].values() if c["caught"])
    print(
        f"flextree static analysis: {n_v} violations; mutation self-test "
        f"{caught}/{len(mut['classes'])} classes caught; "
        f"{report['elapsed_s']}s"
    )
    for row in report["violations"]:
        print(f"  {row['layer']}/{row['kind']} @ {row['where']}: {row['detail']}")
    for name, row in mut["classes"].items():
        if not row["caught"]:
            print(f"  MUTATION ESCAPED: {name} (expected {row['expected']})")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
