"""Layer 4: explicit-state model checking of the control-plane protocols.

The chaos drivers (COORD_CHAOS.json, RPC_CHAOS.json, ARBITER_SPIKE.json)
*sample* interleavings of the host-side control plane; this layer
*enumerates* them.  Three extracted transition models — each living
beside its implementation and pinned to it by shared constants plus the
conformance tests in ``tests/test_control_plane_analysis.py`` — are
explored exhaustively over small worlds with faults injectable at every
transition:

- :class:`~flextree_tpu.runtime.coord_model.CoordModel` — the
  propose→ack→commit handshake at 2/3/4 ranks, coordinator crash at
  every transition, stalled followers, duplicate acks, lost races;
- :class:`~flextree_tpu.runtime.lease_model.LeaseModel` — the
  revoke→ack→grant chip handoff with tenant restart mid-handoff;
- :class:`~flextree_tpu.serving.rpc_model.RpcModel` — one rid's
  retry/hedge/re-route lifecycle against the replica idempotency store;
- :class:`~flextree_tpu.serving.rpc_model.MigrationModel` — the
  disaggregated KV-migration handshake (export → ship → admit-or-refuse
  → release) with the decode replica crashing at every phase: a crash
  mid-migration never loses the request or leaks the prefill-side
  export.

Invariants checked in EVERY reachable state (write-time rules, per-state
predicates, and quiescence checks): at most one commit per control
epoch, commits byte-identical to their proposals, control and lease
epochs strictly increasing, fenced ranks never applying, no chip
granted to (or in active use by) two holders, and every rid landing in
exactly one of {completed-once, shed, failed} with no re-execution of a
completed rid.

The search is bounded EXPLICITLY: each model carries fault/decision
budgets (reported per model), memoization is the visited-state set, and
a ``max_states`` overflow or a budget-limited quiescent frontier is
reported as ``truncated`` — never silently absorbed into "clean".
A violation's report line carries a minimal witness trace (the label
path from the initial state), which is also how the mutation self-test
proves the seeded protocol corruptions produce *reachable* violations.
"""

from __future__ import annotations

import time
from collections import deque

from .base import Violation

__all__ = ["explore", "run_protocol_check", "default_models"]

# hard cap on any single model's visited set — the coordination model at
# 4 ranks explores ~10^4-10^5 states; anything past this cap is a model
# regression, reported as truncation (a violation of the CLI's budget,
# not silently dropped)
MAX_STATES = 400_000


class ExploreResult:
    def __init__(self, name):
        self.name = name
        self.states = 0
        self.transitions = 0
        self.fault_transitions = 0
        self.truncated = False  # hard cap hit: the search is NOT exhaustive
        # quiescent states whose only blocked successor was a documented
        # model budget (reported, distinct from truncation: the budgets
        # are the explicit small-world bound, not a search failure)
        self.bounded_frontier = 0
        self.elapsed_ms = 0.0
        # kind -> (count, witness, first_detail)
        self.violations: dict[str, tuple[int, str, str]] = {}

    def add_violation(self, kind, detail, witness):
        count, w, d = self.violations.get(kind, (0, witness, detail))
        self.violations[kind] = (count + 1, w, d)

    def to_detail(self) -> dict:
        return {
            "states": self.states,
            "transitions": self.transitions,
            "fault_transitions": self.fault_transitions,
            "truncated": self.truncated,
            "bounded_frontier": self.bounded_frontier,
            "violations": sum(c for c, _, _ in self.violations.values()),
        }


def explore(model, max_states: int = MAX_STATES) -> ExploreResult:
    """Exhaustive BFS over ``model``'s reachable states.

    The model contract: ``initial()``, ``transitions(state) ->
    [(label, next_state, [(kind, detail), ...])]``, optional
    ``state_violations(state)`` (per-state predicates) and
    ``quiescent_violations(state) -> ([(kind, detail)], truncated)``
    (terminal-state checks, with budget-truncation reported
    separately), plus ``is_fault_label(label)`` for the fault-injection
    accounting.  BFS keeps witness traces minimal (first hit = shortest
    path in transitions).
    """
    t0 = time.perf_counter()
    res = ExploreResult(model.name)
    init = model.initial()
    parent: dict = {init: None}  # state -> (prev_state, label)
    queue = deque([init])
    res.states = 1
    check_state = getattr(model, "state_violations", None)
    if check_state is not None:
        for kind, detail in check_state(init):
            res.add_violation(kind, detail, "<initial>")
    while queue:
        s = queue.popleft()
        succs = model.transitions(s)
        if not succs:
            viols, bounded = model.quiescent_violations(s)
            if bounded:
                res.bounded_frontier += 1
            for kind, detail in viols:
                res.add_violation(kind, detail, _witness(parent, s))
            continue
        for label, ns, viols in succs:
            res.transitions += 1
            if model.is_fault_label(label):
                res.fault_transitions += 1
            for kind, detail in viols:
                res.add_violation(kind, detail,
                                  _witness(parent, s, extra=label))
            if ns in parent:
                continue
            if res.states >= max_states:
                res.truncated = True
                continue
            parent[ns] = (s, label)
            res.states += 1
            if check_state is not None:
                for kind, detail in check_state(ns):
                    res.add_violation(kind, detail,
                                      _witness(parent, s, extra=label))
            queue.append(ns)
    res.elapsed_ms = round((time.perf_counter() - t0) * 1e3, 1)
    return res


def _witness(parent, state, extra=None, cap: int = 24) -> str:
    labels = [] if extra is None else [extra]
    while parent.get(state) is not None:
        state, label = parent[state]
        labels.append(label)
    labels.reverse()
    if len(labels) > cap:
        labels = ["..."] + labels[-cap:]
    return " -> ".join(labels)


def default_models():
    """The committed matrix: coordination at every small-world width
    (crash injected at every transition of each), one lease world, one
    RPC world, one KV-migration world."""
    from ..runtime.coord_model import CoordModel
    from ..runtime.lease_model import LeaseModel
    from ..serving.rpc_model import MigrationModel, RpcModel

    return [
        CoordModel(2),
        CoordModel(3),
        CoordModel(4),
        LeaseModel(),
        RpcModel(),
        MigrationModel(),
    ]


def run_protocol_check(
    programs=None, times: dict | None = None, models=None
):
    """Explore every model; return ``(violations, detail)``.

    ``programs`` filters by model-name substring (the CLI's
    ``--programs`` hook); ``times`` collects per-model wall-times in ms
    keyed by model name, like every other layer.  A clean tree reports
    zero violations and zero truncation; EITHER is a red report (a
    truncated search is not a verified search).
    """
    if models is None:
        models = default_models()
    violations: list[Violation] = []
    detail: dict = {"models": {}}
    for model in models:
        if programs and not any(p in model.name for p in programs):
            continue
        res = explore(model)
        detail["models"][model.name] = res.to_detail()
        if times is not None:
            times[model.name] = res.elapsed_ms
        for kind, (count, witness, vdetail) in sorted(res.violations.items()):
            violations.append(Violation(
                layer="protocol",
                kind=kind,
                where=model.name,
                detail=f"{vdetail} [{count} reachable; witness: {witness}]",
            ))
        if res.truncated:
            violations.append(Violation(
                layer="protocol",
                kind="search-truncated",
                where=model.name,
                detail=(
                    f"state-space search truncated at {res.states} states "
                    "— a truncated search is not a verified search; raise "
                    "MAX_STATES or shrink the model's budgets"
                ),
            ))
    detail["states"] = sum(
        m["states"] for m in detail["models"].values()
    )
    detail["transitions"] = sum(
        m["transitions"] for m in detail["models"].values()
    )
    return violations, detail
