"""Layer 1: schedule model checker — prove a generated message program
deadlock-free and conservation-correct before it touches a mesh.

``schedule/validate.py`` proves invariants of the *plans* (partition,
send/recv agreement, convergence).  This layer goes one level down: it
builds the explicit per-rank **message program** a schedule executes —
every send/recv half, in issue order, for every schedule family (tree /
ring / lonely / swing / generalized) and for the chunk-pipelined mode
(``chunks=C``) — and model-checks the program itself.  The distinction
matters for the mutation self-test: a corruption is seeded into the
*program* (the thing a backend would actually run), so a checker that
silently re-derives everything from the pristine plans would prove
nothing.

Since ISSUE 8 the expansion is no longer hand-written per family: every
schedule is emitted as a declarative IR program (``schedule/ir.py``) and
:func:`program_from_ir` is the ONE mechanical conversion from IR stages
to the per-rank message program — the checker and the executable
(``schedule.ir.compile_ir``) derive from the same object, eliminating
the drift surface the old second expansion carried.  A new family gets
deadlock/conservation proofs by writing an emitter, nothing else.

Checks (every violation names ``(stage, src, dst, block)``):

1. **Peer symmetry** — every send half has exactly one matching recv half
   in the same round and vice versa, with equal block sets (the
   program-level twin of ``validate.stage_matches``).
2. **Deadlock-freedom** — the program is executed under *blocking
   rendezvous* semantics (each rank issues its post-sets strictly in
   order; a post-set completes only when every half finds its counterpart
   concurrently pending).  The checker runs that abstract machine to
   quiescence: termination proves no cycle among blocking matches exists
   under even the most pessimistic transport (no buffering); a stuck
   frontier is reported as a deadlock cycle.  XLA's collectives are more
   forgiving — this is deliberately the strongest transport model.
3. **Chunk conservation** — per chunk, replayed from the program's own
   halves: every reduce-scatter stage's sends partition the sender's
   owned set (no block reduced twice, none dropped), final scatter
   ownership tiles ``[0, N)``, and the allgather phase's closure leaves
   every rank holding the full reduced vector.
4. **Chunk-buffer overlap** — the chunk-pipelined mode slices one buffer;
   the per-chunk element spans must be pairwise disjoint and tile the
   divisible head exactly, so interleaved phases can never alias.
5. **Watchdog contract** — every executed schedule carries the
   runtime-deadline wrapper (``Program.watchdogged``): a timeout-wrapped
   rendezvous cannot deadlock *forever* (the runtime converts the block
   into a typed ``FT_STEP_TIMEOUT``), so a program that loses the wrapper
   is an ``unbounded-wait`` violation regardless of its message pattern.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..schedule import ir as sir
from ..schedule.plan import ring_plan
from ..schedule.stages import LonelyTopology, Topology, TopologyError
from ..schedule.validate import ScheduleError
from .base import Violation

__all__ = [
    "Half",
    "PostSet",
    "Program",
    "program_from_ir",
    "check_ir",
    "build_program",
    "build_phase_program",
    "check_program",
    "check_phase_program",
    "check_schedule",
    "default_schedule_matrix",
    "check_standard_schedules",
    "default_phase_matrix",
    "check_split_schedules",
    "default_ir_matrix",
    "check_ir_families",
]

SEND, RECV = "send", "recv"


@dataclass(frozen=True)
class Half:
    """One direction of one point-to-point transfer, as issued by ``rank``:
    ``(kind=send, peer)`` means rank -> peer, ``(kind=recv, peer)`` means
    peer -> rank.  ``blocks`` are chunk-local block indices in ``[0, N)``."""

    kind: str
    peer: int
    blocks: tuple[int, ...]


@dataclass
class PostSet:
    """Halves one rank posts *together* (nonblocking post + wait-all), the
    unit of progress in the rendezvous machine.  A tree-stage pairwise
    exchange is a 2-half post-set (send+recv, same peer); a ring step is a
    2-half post-set (send right, recv left); a lonely fold/restore hop is a
    single half."""

    rank: int
    halves: list[Half]
    # coordinates for violation reports
    chunk: int
    phase: str  # "rs" | "ag" | "fold" | "restore"
    stage: int


@dataclass
class Program:
    """The full message program of one schedule execution."""

    num_nodes: int
    kind: str  # "tree" | "ring" | "lonely"
    # per-rank ordered post-sets (issue order == trace order)
    posts: dict[int, list[PostSet]] = field(default_factory=dict)
    num_stages: int = 1
    chunks: int = 1
    # per-chunk element spans (offset, size) into the flat divisible head
    chunk_spans: list[tuple[int, int]] = field(default_factory=list)
    head_elems: int = 0
    # the watchdog contract: every executed schedule runs under a recv/step
    # deadline (fit's StepWatchdog, the simulator's FaultPlan.recv_timeout),
    # so a blocking rendezvous is BOUNDED — a deadlock is surfaced as a
    # typed FT_STEP_TIMEOUT at runtime, never an infinite hang.  A program
    # that loses this wrapper is itself a violation ("unbounded-wait"),
    # independent of its message pattern being correct.
    watchdogged: bool = True
    # split collectives (PR 7): "rs" / "ag" marks a standalone
    # reduce-scatter or all-gather program (one phase of the seam);
    # conservation is then phase-specific — see check_phase_program.
    phase_only: str | None = None

    def postsets(self):
        for rank in sorted(self.posts):
            yield from self.posts[rank]


# --------------------------------------------------------------------- build


def _append_ir_stage(prog: Program, st: "sir.IRStage", scheduled: int) -> None:
    """Convert ONE IR stage into per-rank post-sets.

    Grouped stages post one set per participating rank with per-peer
    (send, recv) half pairs in group order — the nonblocking post +
    wait-all unit a grouped XLA collective is.  Pair stages post each
    rank's sends then its recvs (a ring/swing step is send-right +
    recv-left together; a one-sided fold/restore hop is a single half).
    Whole-buffer hops (``blocks=()``) carry every scheduled block.
    """
    all_blocks = tuple(range(scheduled))
    if st.lowering == "grouped":
        send_map = {(x.src, x.dst): x.blocks for x in st.xfers}
        for grp in st.groups:
            for r in grp:
                halves = []
                for peer in grp:
                    if peer == r:
                        continue
                    halves.append(Half(SEND, peer, send_map[(r, peer)]))
                    halves.append(Half(RECV, peer, send_map[(peer, r)]))
                prog.posts.setdefault(r, []).append(
                    PostSet(r, halves, st.chunk, st.phase, st.index)
                )
        return
    sends: dict[int, list[Half]] = {}
    recvs: dict[int, list[Half]] = {}
    for x in st.xfers:
        blocks = x.blocks if x.blocks else all_blocks
        sends.setdefault(x.src, []).append(Half(SEND, x.dst, blocks))
        recvs.setdefault(x.dst, []).append(Half(RECV, x.src, blocks))
    for r in sorted(set(sends) | set(recvs)):
        halves = sends.get(r, []) + recvs.get(r, [])
        prog.posts.setdefault(r, []).append(
            PostSet(r, halves, st.chunk, st.phase, st.index)
        )


def program_from_ir(ir_prog: "sir.IRProgram") -> Program:
    """The ONE expansion: IR stages -> the per-rank message program.

    This is what makes the IR the single source of truth — the compiler
    lowers ``ir_prog.stages`` and this function expands the same stages
    for the model checker, so a schedule bug cannot hide in a divergence
    between two hand-written expansions (the pre-ISSUE-8 architecture).
    """
    prog = Program(
        ir_prog.num_nodes,
        ir_prog.family,
        num_stages=ir_prog.num_stages,
        chunks=ir_prog.chunks,
    )
    prog.head_elems = ir_prog.head_elems
    prog.chunk_spans = list(ir_prog.chunk_spans)
    for st in ir_prog.stages:
        _append_ir_stage(prog, st, ir_prog.scheduled)
    return prog


def check_ir(ir_prog: "sir.IRProgram") -> list[Violation]:
    """Model-check an IR program: expand via :func:`program_from_ir`, run
    every program check.  ``schedule.ir.compile_ir`` calls this before
    lowering and refuses the program on any violation."""
    return check_program(program_from_ir(ir_prog))


def _append_tree_chunk(prog: Program, topo: Topology, chunk: int, phase: str):
    """One tree phase appended from the IR emitter (shared with the
    split-phase programs below)."""
    for st in sir.tree_phase_stages(topo, phase, chunk=chunk):
        _append_ir_stage(prog, st, topo.num_nodes)


def build_program(topo, count: int | None = None, chunks: int = 1) -> Program:
    """Build the message program for one schedule execution.

    ``topo``: a resolved ``Topology``/``LonelyTopology`` or an
    ``IRProgram`` (swing/generalized arrive only as IR).  ``count``:
    elements per rank (defaults to one block per rank times N); only the
    divisible head is scheduled, exactly as the executors slice it.
    ``chunks``: the chunk-pipelined mode — chunk ``c``'s allgather is
    issued between chunk ``c+1``'s reduce-scatter and its own, the same
    interleaving the jitted program traces.  Everything is emitted as IR
    (``schedule/ir.py``) and expanded by :func:`program_from_ir`.
    """
    if isinstance(topo, sir.IRProgram):
        return program_from_ir(topo)
    if not isinstance(topo, (Topology, LonelyTopology)):
        raise TypeError(f"resolve the topology first, got {type(topo)}")
    return program_from_ir(sir.emit_ir(topo, count=count, chunks=chunks))


# --------------------------------------------------------------------- check


def _check_symmetry(prog: Program) -> list[Violation]:
    """Every send half must pair with exactly one recv half at the peer, in
    the same (chunk, phase, stage), with the identical block set."""
    out: list[Violation] = []
    index: dict[tuple, list[Half]] = {}
    for ps in prog.postsets():
        for h in ps.halves:
            index.setdefault(
                (ps.chunk, ps.phase, ps.stage, ps.rank, h.kind), []
            ).append(h)

    def name(ps):
        return f"{prog.kind} chunk{ps.chunk}/{ps.phase}"

    for ps in prog.postsets():
        for h in ps.halves:
            want = RECV if h.kind == SEND else SEND
            mates = [
                m
                for m in index.get(
                    (ps.chunk, ps.phase, ps.stage, h.peer, want), []
                )
                if m.peer == ps.rank
            ]
            src, dst = (
                (ps.rank, h.peer) if h.kind == SEND else (h.peer, ps.rank)
            )
            if len(mates) != 1:
                out.append(
                    Violation(
                        "schedule",
                        "asymmetric-match",
                        name(ps),
                        f"{h.kind} half {src}->{dst} has {len(mates)} "
                        f"counterpart halves (want exactly 1)",
                        stage=ps.stage,
                        src=src,
                        dst=dst,
                        block=h.blocks[0] if h.blocks else None,
                    )
                )
            elif set(mates[0].blocks) != set(h.blocks):
                diff = set(mates[0].blocks) ^ set(h.blocks)
                out.append(
                    Violation(
                        "schedule",
                        "asymmetric-match",
                        name(ps),
                        f"{src}->{dst} disagrees on blocks: one side "
                        f"{sorted(h.blocks)}, other {sorted(mates[0].blocks)}",
                        stage=ps.stage,
                        src=src,
                        dst=dst,
                        block=min(diff) if diff else None,
                    )
                )
    return out


def _check_deadlock(prog: Program) -> list[Violation]:
    """Run the blocking-rendezvous abstract machine to quiescence.

    Each rank's post-sets issue strictly in order.  A pending half matches
    when its counterpart half (same chunk/phase/stage coordinates, mirrored
    direction, equal blocks) is pending at the peer; a post-set completes
    when all its halves match; completion is simultaneous across ranks.
    If the machine quiesces before every post-set completed, the frontier
    is a genuine wait-for cycle (or an unmatched blocking op) — reported
    per stuck rank with the exchange it is blocked on.
    """
    ptr = {r: 0 for r in prog.posts}
    queues = {r: prog.posts[r] for r in prog.posts}

    def frontier(r):
        q = queues[r]
        return q[ptr[r]] if ptr[r] < len(q) else None

    def half_matches(ps: PostSet, h: Half) -> bool:
        mate = frontier(h.peer)
        if mate is None:
            return False
        if (mate.chunk, mate.phase, mate.stage) != (
            ps.chunk,
            ps.phase,
            ps.stage,
        ):
            return False
        want = RECV if h.kind == SEND else SEND
        return any(
            m.kind == want
            and m.peer == ps.rank
            and set(m.blocks) == set(h.blocks)
            for m in mate.halves
        )

    while True:
        completable = [
            r
            for r in queues
            if (ps := frontier(r)) is not None
            and all(half_matches(ps, h) for h in ps.halves)
        ]
        if not completable:
            break
        for r in completable:
            ptr[r] += 1

    out: list[Violation] = []
    stuck = [r for r in queues if ptr[r] < len(queues[r])]
    # a watchdog-wrapped rendezvous cannot deadlock *forever*: the runtime
    # converts the block into a typed FT_STEP_TIMEOUT — the deadlock is
    # still a schedule bug (the step never completes), but the failure mode
    # is a diagnostic, not a hang; say so in the report
    bound = (
        " (bounded at runtime: the watchdog converts this into "
        "FT_STEP_TIMEOUT — still a schedule bug)"
        if prog.watchdogged
        else " (UNBOUNDED: no watchdog — this hangs forever)"
    )
    for r in sorted(stuck):
        ps = frontier(r)
        blocked = [h for h in ps.halves if not half_matches(ps, h)]
        h = blocked[0] if blocked else ps.halves[0]
        src, dst = (r, h.peer) if h.kind == SEND else (h.peer, r)
        out.append(
            Violation(
                "schedule",
                "deadlock",
                f"{prog.kind} chunk{ps.chunk}/{ps.phase}",
                f"rank {r} blocks on {h.kind} {src}->{dst} "
                f"(cycle among {len(stuck)} stuck ranks: {sorted(stuck)})"
                + bound,
                stage=ps.stage,
                src=src,
                dst=dst,
                block=h.blocks[0] if h.blocks else None,
            )
        )
    return out


def _check_watchdog(prog: Program) -> list[Violation]:
    """Every executed schedule must keep its watchdog wrapper: without a
    recv/step deadline a blocking rendezvous whose peer died or stalled
    hangs forever instead of surfacing ``FT_STEP_TIMEOUT``."""
    if prog.watchdogged:
        return []
    return [
        Violation(
            "schedule",
            "unbounded-wait",
            prog.kind,
            "program lost its watchdog wrapper (watchdogged=False): a "
            "blocking rendezvous with no recv deadline can hang forever on "
            "a dead or stalled peer instead of raising FT_STEP_TIMEOUT — "
            "every executed schedule must run deadline-wrapped",
        )
    ]


def _check_conservation(prog: Program) -> list[Violation]:
    """Replay ownership per chunk from the program's own halves."""
    out: list[Violation] = []
    n = prog.num_nodes
    if prog.kind == "ring":
        return _check_ring_conservation(prog)
    # ranks that only fold through a buddy (lonely shapes, non-power-of-two
    # swing extras) own no blocks: the replay runs over the scheduled ranks
    n = n - sum(
        1
        for r, q in prog.posts.items()
        if any(ps.phase == "fold" and ps.halves[0].kind == SEND for ps in q)
    )

    for c in range(prog.chunks):
        # ---- reduce-scatter: sends partition owned; recvs define new owned
        owned = {r: set(range(n)) for r in range(n)}
        by_rs: dict[tuple[int, int], list[tuple[Half, PostSet]]] = {}
        by_ag: dict[tuple[int, int], list[tuple[Half, PostSet]]] = {}
        for ps in prog.postsets():
            if ps.chunk != c or ps.rank >= n:
                continue
            for h in ps.halves:
                if ps.phase == "rs":
                    by_rs.setdefault((ps.rank, ps.stage), []).append((h, ps))
                elif ps.phase == "ag":
                    by_ag.setdefault((ps.rank, ps.stage), []).append((h, ps))
        n_stages = prog.num_stages
        where = f"{prog.kind} chunk{c}/rs"
        for i in range(n_stages):
            for r in range(n):
                sent: dict[int, int] = {}
                kept: set[int] = set()
                for h, ps in by_rs.get((r, i), []):
                    if h.kind == SEND:
                        for b in h.blocks:
                            if b in sent:
                                out.append(
                                    Violation(
                                        "schedule",
                                        "double-count",
                                        where,
                                        f"rank {r} sends block {b} to both "
                                        f"{sent[b]} and {h.peer}: reduced twice",
                                        stage=i, src=r, dst=h.peer, block=b,
                                    )
                                )
                            sent[b] = h.peer
                    else:
                        kept |= set(h.blocks)
                # a rank also keeps its own residue chain without sending it
                # to itself (self-ops are skipped); its kept set IS the recv
                # halves' union — sends must cover owned minus kept exactly
                missing = owned[r] - set(sent) - kept
                extra = set(sent) - owned[r]
                for b in sorted(missing):
                    out.append(
                        Violation(
                            "schedule",
                            "dropped-block",
                            where,
                            f"rank {r} owns block {b} but neither sends nor "
                            f"keeps it at stage {i}: its contribution is lost",
                            stage=i, src=r, dst=None, block=b,
                        )
                    )
                for b in sorted(extra):
                    out.append(
                        Violation(
                            "schedule",
                            "double-count",
                            where,
                            f"rank {r} sends block {b} it does not own at "
                            f"stage {i} (already contributed upstream)",
                            stage=i, src=r, dst=sent[b], block=b,
                        )
                    )
                if not kept <= owned[r]:
                    bad = min(kept - owned[r])
                    out.append(
                        Violation(
                            "schedule",
                            "double-count",
                            where,
                            f"rank {r} stage {i} receives partials for block "
                            f"{bad} it no longer owns",
                            stage=i, src=None, dst=r, block=bad,
                        )
                    )
                owned[r] = kept
        seen: set[int] = set()
        for r in range(n):
            dup = seen & owned[r]
            if dup:
                out.append(
                    Violation(
                        "schedule",
                        "double-count",
                        f"{prog.kind} chunk{c}",
                        f"final scatter ownership overlaps on block "
                        f"{min(dup)} (rank {r})",
                        stage=n_stages - 1, src=None, dst=r, block=min(dup),
                    )
                )
            seen |= owned[r]
        for b in sorted(set(range(n)) - seen):
            out.append(
                Violation(
                    "schedule",
                    "dropped-block",
                    f"{prog.kind} chunk{c}",
                    f"no rank owns block {b} after reduce-scatter: it was "
                    f"never fully reduced",
                    stage=n_stages - 1, src=None, dst=None, block=b,
                )
            )

        # ---- allgather closure: replay forwarding in issue order
        holdings = {r: set(owned[r]) for r in range(n)}
        for i in reversed(range(n_stages)):
            new_holdings = {r: set(h) for r, h in holdings.items()}
            for r in range(n):
                for h, ps in by_ag.get((r, i), []):
                    if h.kind != RECV:
                        continue
                    inbound = set(h.blocks)
                    if not inbound <= holdings.get(h.peer, set()):
                        bad = min(inbound - holdings.get(h.peer, set()))
                        out.append(
                            Violation(
                                "schedule",
                                "dropped-block",
                                f"{prog.kind} chunk{c}/ag",
                                f"rank {h.peer} forwards block {bad} it does "
                                f"not hold at stage {i}",
                                stage=i, src=h.peer, dst=r, block=bad,
                            )
                        )
                    new_holdings[r] |= inbound
            holdings = new_holdings
        for r in range(n):
            gaps = set(range(n)) - holdings[r]
            if gaps:
                out.append(
                    Violation(
                        "schedule",
                        "dropped-block",
                        f"{prog.kind} chunk{c}/ag",
                        f"allgather closure fails: rank {r} ends without "
                        f"blocks {sorted(gaps)}",
                        stage=0, src=None, dst=r, block=min(gaps),
                    )
                )
    return out


def _check_ring_conservation(prog: Program) -> list[Violation]:
    out: list[Violation] = []
    n = prog.num_nodes
    for r in range(n):
        steps = [ps for ps in prog.posts.get(r, [])]
        reduce_steps = [ps for ps in steps if ps.phase == "rs"]
        gather_steps = [ps for ps in steps if ps.phase == "ag"]
        folded = {r}
        for ps in reduce_steps:
            for h in ps.halves:
                if h.kind == RECV:
                    folded.update(h.blocks)
        missing = set(range(n)) - folded
        for b in sorted(missing):
            out.append(
                Violation(
                    "schedule",
                    "dropped-block",
                    "ring/rs",
                    f"rank {r} never folds a partial for block {b} in the "
                    f"reduce phase",
                    stage=len(reduce_steps), src=None, dst=r, block=b,
                )
            )
        have = {(r + 1) % n}
        for ps in gather_steps:
            for h in ps.halves:
                if h.kind == RECV:
                    have.update(h.blocks)
        for b in sorted(set(range(n)) - have):
            out.append(
                Violation(
                    "schedule",
                    "dropped-block",
                    "ring/ag",
                    f"rank {r} ends the allgather without block {b}",
                    stage=len(gather_steps), src=None, dst=r, block=b,
                )
            )
    return out


def _check_chunk_spans(prog: Program) -> list[Violation]:
    """Chunk buffer spans must be pairwise disjoint and tile the head."""
    out: list[Violation] = []
    spans = sorted(
        range(len(prog.chunk_spans)), key=lambda i: prog.chunk_spans[i][0]
    )
    covered = 0
    for idx in spans:
        off, size = prog.chunk_spans[idx]
        if off < covered:
            out.append(
                Violation(
                    "schedule",
                    "chunk-overlap",
                    f"{prog.kind} chunk{idx}",
                    f"chunk {idx} buffer [{off}, {off + size}) overlaps the "
                    f"previous chunk's span ending at {covered}: interleaved "
                    f"phases would alias",
                    stage=None, src=None, dst=None, block=idx,
                )
            )
        elif off > covered:
            out.append(
                Violation(
                    "schedule",
                    "chunk-overlap",
                    f"{prog.kind} chunk{idx}",
                    f"gap [{covered}, {off}) before chunk {idx}'s buffer: "
                    f"those elements belong to no chunk and are never "
                    f"reduced",
                    stage=None, src=None, dst=None, block=idx,
                )
            )
        covered = max(covered, off + size)
    if prog.chunk_spans and covered != prog.head_elems:
        out.append(
            Violation(
                "schedule",
                "chunk-overlap",
                f"{prog.kind}",
                f"chunk spans cover [0, {covered}) but the divisible head is "
                f"{prog.head_elems} elements",
                stage=None, src=None, dst=None, block=None,
            )
        )
    return out


def check_program(prog: Program) -> list[Violation]:
    """All program-level checks; order: watchdog contract, symmetry,
    deadlock, conservation, buffer spans (cheapest-to-localize first)."""
    out = _check_watchdog(prog)
    out += _check_symmetry(prog)
    out += _check_deadlock(prog)
    out += _check_conservation(prog)
    out += _check_chunk_spans(prog)
    return out


def check_schedule(
    topo_like, num_nodes: int | None = None, count: int | None = None,
    chunks: int = 1,
) -> list[Violation]:
    """Resolve, build, and model-check one schedule.

    A structurally-invalid topology (``Topology.resolve`` or plan
    construction raising) is itself reported as a violation rather than an
    analyzer crash — the CI gate must not confuse "schedule is wrong" with
    "analyzer is broken".
    """
    try:
        if isinstance(topo_like, (Topology, LonelyTopology)):
            topo = topo_like
        else:
            if num_nodes is None:
                raise ValueError("num_nodes required for unresolved specs")
            topo = Topology.resolve(num_nodes, topo_like)
        prog = build_program(topo, count=count, chunks=chunks)
    except (ScheduleError, ValueError, TypeError) as e:
        return [
            Violation(
                "schedule",
                "invalid-topology",
                str(topo_like),
                f"{type(e).__name__}: {e}",
            )
        ]
    return check_program(prog)


def default_schedule_matrix(max_n: int = 16) -> list[tuple]:
    """(spec, num_nodes, count, chunks) rows covering the shape families the
    backends execute: flat / two-level / halving-doubling trees, the ring,
    lonely shapes, non-divisible counts, and the chunk-pipelined mode."""
    rows = [
        ("8", 8, 64, 1),
        ("4,2", 8, 64, 1),
        ("2,2,2", 8, 64, 1),
        ("2,4", 8, 96, 1),
        ("1", 8, 64, 1),          # ring
        ("3,2+1", 7, 84, 1),      # lonely
        ("6+1", 7, 66, 1),
        ("4,2", 8, 64, 4),        # chunk-pipelined
        ("2,2,2", 8, 128, 3),
        ("4,2", 8, 100, 2),       # non-divisible count, chunked
        ("12", 12, 144, 1),
        ("4,4", 16, 256, 2),
    ]
    return [r for r in rows if r[1] <= max_n]


def _row_selected(name: str, programs) -> bool:
    """``programs``: optional substring filters (the CLI's ``--programs``)
    — ``None``/empty selects everything."""
    return not programs or any(p in name for p in programs)


def check_standard_schedules(
    max_n: int = 16, programs=None, times: dict | None = None
) -> tuple[list[Violation], int]:
    """Model-check the default matrix; returns (violations,
    programs_checked).  ``programs`` filters rows by name substring;
    ``times`` (when given) collects per-program wall-ms — both hooks
    exist so the CLI report and this gate are the SAME loop, never two
    drifting copies."""
    violations: list[Violation] = []
    checked = 0
    for spec, n, count, chunks in default_schedule_matrix(max_n):
        name = f"{spec}@{n}x{count}c{chunks}"
        if not _row_selected(name, programs):
            continue
        t0 = time.perf_counter()
        violations += check_schedule(spec, num_nodes=n, count=count, chunks=chunks)
        if times is not None:
            times[name] = round((time.perf_counter() - t0) * 1e3, 2)
        checked += 1
    return violations, checked


# ------------------------------------------------- IR families (ISSUE 8)


def default_ir_matrix(max_n: int = 16) -> list[tuple]:
    """(spec, num_nodes, count) rows for the IR-only families: Swing at
    power-of-two AND non-power-of-two N (the latter runs the buddy-folded
    core), and the generalized construction at its corners (flat-tree
    message pattern, recursive halving-doubling) plus interior ports."""
    rows = [
        ("swing", 4, 32),
        ("swing", 6, 48),       # non-power-of-two: 4-core + 2 folded extras
        ("swing", 8, 64),
        ("swing", 12, 96),
        ("swing", 16, 256),
        ("gen:8@7", 8, 64),     # flat-tree corner, one round
        ("gen:2,2,2@1", 8, 64),  # recursive halving-doubling corner
        ("gen:4,2@2", 8, 96),
        ("gen:4,2@1", 8, 64),
        ("gen:3,2@1", 6, 36),
        ("gen:4,4@3", 16, 256),
    ]
    return [r for r in rows if r[1] <= max_n]


def check_ir_families(
    max_n: int = 16, programs=None, times: dict | None = None
) -> tuple[list[Violation], int]:
    """Emit and model-check every IR-family row; returns (violations,
    programs_checked).  An emitter that raises is reported as an
    ``invalid-topology`` violation, never an analyzer crash.
    ``programs``/``times`` as in :func:`check_standard_schedules`."""
    violations: list[Violation] = []
    checked = 0
    for spec, n, count in default_ir_matrix(max_n):
        name = f"{spec}@{n}"
        if not _row_selected(name, programs):
            continue
        t0 = time.perf_counter()
        try:
            prog = sir.emit_ir(spec, num_nodes=n, count=count)
        except (TopologyError, ScheduleError, ValueError, TypeError) as e:
            violations.append(
                Violation(
                    "schedule", "invalid-topology", name,
                    f"{type(e).__name__}: {e}",
                )
            )
            continue
        violations += check_ir(prog)
        if times is not None:
            times[name] = round((time.perf_counter() - t0) * 1e3, 2)
        checked += 1
    return violations, checked


# ----------------------------------------------------- split phases (PR 7)


def build_phase_program(topo, phase: str, count: int | None = None) -> Program:
    """The message program of ONE standalone phase: ``phase="rs"`` is the
    reduce-scatter collective (every rank ends owning exactly its
    ``schedule.blocks.owned_block``; lonely ranks additionally receive a
    mirror copy of their buddy's block over one extra ship hop),
    ``phase="ag"`` the all-gather (owned blocks in, the full vector out
    on every rank; lonely ranks get it over the restore hop)."""
    if phase not in ("rs", "ag"):
        raise ValueError(f"phase must be 'rs' or 'ag', got {phase!r}")
    if not isinstance(topo, (Topology, LonelyTopology)):
        raise TypeError(f"resolve the topology first, got {type(topo)}")
    n = topo.num_nodes

    if isinstance(topo, LonelyTopology):
        tree, m, l = topo.tree, topo.tree.num_nodes, topo.lonely
        prog = Program(
            n, "lonely", num_stages=tree.num_stages, phase_only=phase
        )
        count = count if count is not None else m * m
        prog.head_elems = (count // m) * m
        prog.chunk_spans = [(0, prog.head_elems)]
        all_blocks = tuple(range(m))
        if phase == "rs":
            for i in range(l):
                prog.posts.setdefault(m + i, []).append(
                    PostSet(m + i, [Half(SEND, i, all_blocks)], 0, "fold", 0)
                )
                prog.posts.setdefault(i, []).append(
                    PostSet(i, [Half(RECV, m + i, all_blocks)], 0, "fold", 0)
                )
            _append_tree_chunk(prog, tree, 0, "rs")
            for i in range(l):
                blocks = (_program_owned_block(tree, i),)
                prog.posts.setdefault(i, []).append(
                    PostSet(i, [Half(SEND, m + i, blocks)], 0, "ship", 0)
                )
                prog.posts.setdefault(m + i, []).append(
                    PostSet(m + i, [Half(RECV, i, blocks)], 0, "ship", 0)
                )
        else:
            _append_tree_chunk(prog, tree, 0, "ag")
            for i in range(l):
                prog.posts.setdefault(i, []).append(
                    PostSet(i, [Half(SEND, m + i, all_blocks)], 0, "restore", 0)
                )
                prog.posts.setdefault(m + i, []).append(
                    PostSet(m + i, [Half(RECV, i, all_blocks)], 0, "restore", 0)
                )
        return prog

    count = count if count is not None else n * n
    head = (count // n) * n
    if topo.is_ring:
        prog = Program(n, "ring", num_stages=1, phase_only=phase)
        prog.head_elems = head
        prog.chunk_spans = [(0, head)]
        plans = [ring_plan(n, r) for r in range(n)]
        steps = range(n - 1) if phase == "rs" else range(n - 1, 2 * (n - 1))
        for step in steps:
            for r in range(n):
                snd, rcv = plans[r][step]
                prog.posts.setdefault(r, []).append(
                    PostSet(
                        r,
                        [
                            Half(SEND, snd.peer, snd.blocks),
                            Half(RECV, rcv.peer, rcv.blocks),
                        ],
                        0,
                        phase,
                        step,
                    )
                )
        return prog

    prog = Program(n, "tree", num_stages=topo.num_stages, phase_only=phase)
    prog.head_elems = head
    prog.chunk_spans = [(0, head)]
    _append_tree_chunk(prog, topo, 0, phase)
    return prog


def _program_owned_block(topo, rank: int) -> int:
    """Contract block per rank in PROGRAM coordinates: the message model
    names blocks by residue chain, so rank ``r`` owns block ``r`` in a
    tree and block ``(r+1) % N`` on the ring; lonely rank ``m+i`` mirrors
    buddy ``i``.  The XLA lowering realizes program block ``b`` at
    contiguous tile offset ``schedule.blocks.owned_block(topo, b)`` —
    two names for the same residue chain, and the cross-check between
    them is property-tested against the real collectives in
    ``tests/test_sharded.py``."""
    if hasattr(topo, "tree"):
        m = topo.tree.num_nodes
        return _program_owned_block(topo.tree, rank if rank < m else rank - m)
    if topo.is_ring:
        return (rank + 1) % topo.num_nodes
    return rank


def _check_phase_conservation(prog: Program, topo) -> list[Violation]:
    """Phase-specific conservation: the rs program must leave every rank
    owning exactly its contract block (the shard layout the ZeRO
    optimizer state is carved by, in program coordinates —
    :func:`_program_owned_block`); the ag program, started from that
    ownership, must close to every rank holding the full vector."""
    owned_block = _program_owned_block
    out: list[Violation] = []
    tree = topo.tree if isinstance(topo, LonelyTopology) else topo
    lonely = topo.lonely if isinstance(topo, LonelyTopology) else 0
    m = tree.num_nodes
    name = f"{prog.kind}/{prog.phase_only}"

    if prog.phase_only == "rs":
        if prog.kind == "ring":
            # the fold walk: rank r's final fold lands on its owned block
            for r in range(m):
                recvd: list[int] = []
                for ps in prog.posts.get(r, []):
                    if ps.phase != "rs":
                        continue
                    for h in ps.halves:
                        if h.kind == RECV:
                            recvd.extend(h.blocks)
                want = owned_block(topo, r)
                if not recvd or recvd[-1] != want:
                    out.append(
                        Violation(
                            "schedule", "shard-ownership", name,
                            f"ring rank {r}'s final fold lands on block "
                            f"{recvd[-1] if recvd else None}, but the shard "
                            f"layout says it owns block {want}",
                            stage=len(recvd), src=None, dst=r,
                            block=want,
                        )
                    )
                missing = set(range(m)) - {r} - set(recvd)
                for b in sorted(missing):
                    out.append(
                        Violation(
                            "schedule", "dropped-block", name,
                            f"ring rank {r} never folds a partial for block {b}",
                            stage=None, src=None, dst=r, block=b,
                        )
                    )
            return out
        # tree (and the lonely prefix tree): replay per-stage ownership
        owned = {r: set(range(m)) for r in range(m)}
        for i in range(tree.num_stages):
            for r in range(m):
                sent: dict[int, int] = {}
                kept: set[int] = set()
                for ps in prog.posts.get(r, []):
                    if ps.phase != "rs" or ps.stage != i:
                        continue
                    for h in ps.halves:
                        if h.kind == SEND:
                            for b in h.blocks:
                                sent[b] = h.peer
                        else:
                            kept |= set(h.blocks)
                missing = owned[r] - set(sent) - kept
                for b in sorted(missing):
                    out.append(
                        Violation(
                            "schedule", "dropped-block", name,
                            f"rank {r} owns block {b} but neither sends nor "
                            f"keeps it at stage {i}",
                            stage=i, src=r, dst=None, block=b,
                        )
                    )
                extra = set(sent) - owned[r]
                for b in sorted(extra):
                    out.append(
                        Violation(
                            "schedule", "double-count", name,
                            f"rank {r} sends block {b} it does not own at "
                            f"stage {i}",
                            stage=i, src=r, dst=sent[b], block=b,
                        )
                    )
                owned[r] = kept
        for r in range(m):
            want = {owned_block(tree, r)}
            if owned[r] != want:
                out.append(
                    Violation(
                        "schedule", "shard-ownership", name,
                        f"rank {r} ends the reduce-scatter owning "
                        f"{sorted(owned[r])}, but the shard layout says "
                        f"exactly {sorted(want)}",
                        stage=tree.num_stages - 1, src=None, dst=r,
                        block=min(want),
                    )
                )
        if lonely:
            # the ship hop must hand each lonely rank its buddy's block
            for i in range(lonely):
                got: set[int] = set()
                for ps in prog.posts.get(m + i, []):
                    if ps.phase == "ship":
                        for h in ps.halves:
                            if h.kind == RECV:
                                got |= set(h.blocks)
                want = {owned_block(tree, i)}
                if got != want:
                    out.append(
                        Violation(
                            "schedule", "shard-ownership", name,
                            f"lonely rank {m + i} ends with mirror blocks "
                            f"{sorted(got)}, want buddy {i}'s {sorted(want)}",
                            stage=None, src=i, dst=m + i,
                            block=min(want),
                        )
                    )
        return out

    # ---- ag: closure from the contract ownership
    holdings = {r: {owned_block(topo, r)} for r in range(prog.num_nodes)}
    if prog.kind == "ring":
        for r in range(m):
            for ps in prog.posts.get(r, []):
                for h in ps.halves:
                    if h.kind == RECV:
                        holdings[r] |= set(h.blocks)
    else:
        for i in reversed(range(tree.num_stages)):
            new_h = {r: set(h) for r, h in holdings.items()}
            for r in range(m):
                for ps in prog.posts.get(r, []):
                    if ps.phase != "ag" or ps.stage != i:
                        continue
                    for h in ps.halves:
                        if h.kind != RECV:
                            continue
                        inbound = set(h.blocks)
                        if not inbound <= holdings.get(h.peer, set()):
                            bad = min(inbound - holdings.get(h.peer, set()))
                            out.append(
                                Violation(
                                    "schedule", "dropped-block", name,
                                    f"rank {h.peer} forwards block {bad} it "
                                    f"does not hold at stage {i}",
                                    stage=i, src=h.peer, dst=r, block=bad,
                                )
                            )
                        new_h[r] |= inbound
            holdings = new_h
        if lonely:
            for i in range(lonely):
                for ps in prog.posts.get(m + i, []):
                    if ps.phase == "restore":
                        for h in ps.halves:
                            if h.kind == RECV:
                                holdings[m + i] = set(h.blocks)
    check_ranks = range(prog.num_nodes) if not lonely else range(m + lonely)
    for r in check_ranks:
        gaps = set(range(m)) - holdings[r]
        if gaps:
            out.append(
                Violation(
                    "schedule", "dropped-block", name,
                    f"all-gather closure fails: rank {r} ends without "
                    f"blocks {sorted(gaps)}",
                    stage=0, src=None, dst=r, block=min(gaps),
                )
            )
    return out


def check_phase_program(prog: Program, topo) -> list[Violation]:
    """All checks for one standalone-phase program: watchdog contract,
    peer symmetry, deadlock-freedom under blocking rendezvous, and the
    phase-specific ownership/closure conservation."""
    out = _check_watchdog(prog)
    out += _check_symmetry(prog)
    out += _check_deadlock(prog)
    out += _check_phase_conservation(prog, topo)
    return out


def default_phase_matrix(max_n: int = 16) -> list[tuple]:
    """(spec, num_nodes, count) rows for the split collectives: the shapes
    the sharded train path actually rides (flat/two-level/halving trees,
    ring) plus the lonely mirror contract."""
    rows = [
        ("8", 8, 64),
        ("4,2", 8, 64),
        ("2,2,2", 8, 64),
        ("2,4", 8, 96),
        ("1", 8, 64),
        ("2", 2, 16),
        ("3,2+1", 7, 84),
        ("6+1", 7, 66),
        ("4,4", 16, 256),
    ]
    return [r for r in rows if r[1] <= max_n]


def check_split_schedules(
    max_n: int = 16, programs=None, times: dict | None = None
) -> tuple[list[Violation], int]:
    """Model-check the standalone reduce-scatter AND all-gather programs
    over the default phase matrix; returns (violations, programs).
    ``programs``/``times`` as in :func:`check_standard_schedules`."""
    violations: list[Violation] = []
    checked = 0
    for spec, n, count in default_phase_matrix(max_n):
        try:
            topo = Topology.resolve(n, spec)
        except (ScheduleError, ValueError) as e:
            violations.append(
                Violation("schedule", "invalid-topology", spec, str(e))
            )
            continue
        for phase in ("rs", "ag"):
            name = f"{spec}@{n}/{phase}"
            if not _row_selected(name, programs):
                continue
            t0 = time.perf_counter()
            try:
                prog = build_phase_program(topo, phase, count=count)
            except (ScheduleError, ValueError, TypeError) as e:
                violations.append(
                    Violation(
                        "schedule", "invalid-topology", name,
                        f"{type(e).__name__}: {e}",
                    )
                )
                continue
            violations += check_phase_program(prog, topo)
            if times is not None:
                times[name] = round((time.perf_counter() - t0) * 1e3, 2)
            checked += 1
    return violations, checked
