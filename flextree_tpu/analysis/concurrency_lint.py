"""Layer 5: concurrency / lock-discipline lint over the threaded host code.

The runtime and serving layers are genuinely multi-threaded: dispatcher
pools, heartbeat daemons, RPC reader threads, background checkpointers,
and signal handlers all share state with the step loop.  The protocol
checker (layer 4) verifies the *cross-process* handshakes; this layer
lints the *in-process* discipline those components rely on.  Four checks,
each an AST/call-graph pass over one file at a time:

- **lock-order** — the lock-acquisition graph: every ``with <lock>:``
  region contributes an edge to each lock acquired inside it (directly
  or through a call to a same-file function that acquires one).  Any
  cycle in the graph is a potential ABBA deadlock, flagged whether or
  not today's thread schedule can hit it.
- **lock-blocking** — a blocking call (sleep, thread join, socket
  accept/recv/sendall/connect, ``Event.wait``, queue get/put, blocking
  lock acquire, ``open``/``os.fsync``, subprocess, jit materialization
  via ``block_until_ready``/``device_get``) made while holding a lock.
  Every waiter on that lock inherits the block; on the hot paths
  (recorder, metrics, front door) that is a latency cliff or a wedge.
- **guard** — write-side lock discipline, made auditable: a field whose
  ``__init__`` assignment carries ``# guarded-by: <lock>`` must only be
  written inside ``with self.<lock>:``, in a method whose name ends in
  ``_locked`` (the callee-holds-the-lock convention), on a line carrying
  ``# holds: <lock>``, or in ``__init__`` itself (no concurrency before
  construction completes).  Unannotated fields are not checked — the
  annotation is the opt-in that makes the discipline reviewable.
- **signal-blocking** — a blocking primitive (the narrow set: lock
  acquire, ``wait``, ``join``, sleep, queue ops — NOT buffered file
  I/O, which Python-level handlers may use) reachable from a function
  registered via ``signal.signal``.  A handler runs ON the thread it
  interrupted; blocking on a lock that frame may hold is a permanent
  deadlock — the exact class the recorder's ``dump_nonblocking`` (try-
  lock, skip on contention) exists to avoid.

Honest limits, by construction: resolution is per-file and name-based
(a bare call, or an attribute call whose receiver is ``self``/``cls`` or
plausibly names a same-file class, is matched against every same-file
``def`` sharing its name — ``json.dump()`` does NOT resolve to a local
``dump`` method), so cross-module blocking — ``record_event`` into the recorder, a metrics
``inc`` under a caller's lock — is invisible here; single-writer fields
need no annotation and get no check; lock identity is lexical
(``ClassName.attr``), so two instances of one class sharing the lint's
node is deliberate (the ABBA *shape* is per-class, not per-object).
False positives are waived in place with an auditable pragma::

    with self._wlock:
        send_frame(...)  # concurrency: ok — the write lock IS the serializer

The pragma must carry a reason and suppresses only its own line (or the
whole function when placed on the ``def`` line).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

from .base import Violation

__all__ = [
    "scan_source",
    "scan_file",
    "run_concurrency_lint",
    "PRAGMA",
    "GUARDED_BY",
    "HOLDS",
]

PRAGMA = "concurrency: ok"
#: ``self.x = ...  # guarded-by: _lock`` in ``__init__`` opts the field in.
GUARDED_BY = "guarded-by:"
#: ``# holds: _lock`` on a write line asserts the caller holds the lock.
HOLDS = "holds:"

#: Receiver names treated as locks in ``with`` statements / ``.acquire``.
_LOCKISH = re.compile(r"lock|mutex|cond\b|condition|sem\b|semaphore", re.I)
#: Receiver names treated as queues for ``.get`` / ``.put``.
_QUEUEISH = re.compile(r"queue|jobs|results|resq|work\b|_work|intake|inbox")
#: Receiver names treated as joinable threads/processes for ``.join``.
_THREADISH = re.compile(r"thread|proc|worker|reader|writer|^_?[tp]$")
#: Receiver names treated as sockets for ``.connect``.
_SOCKISH = re.compile(r"sock|conn", re.I)

#: Attribute calls that mutate their receiver in place (for guard checks).
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "discard", "remove",
    "pop", "popleft", "popitem", "clear", "update", "setdefault",
})

#: Constructors that make an attribute a lock (collected per class).
_LOCK_CTORS = frozenset({
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore", "Lock", "RLock",
    "Condition",
})


def _qualname(node) -> str | None:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _receiver_name(func_node) -> str | None:
    """Final receiver identifier of ``a.b.c.meth`` → ``c`` (or ``a`` for
    ``a.meth``); None for non-attribute calls."""
    if not isinstance(func_node, ast.Attribute):
        return None
    value = func_node.value
    if isinstance(value, ast.Attribute):
        return value.attr
    if isinstance(value, ast.Name):
        return value.id
    return None


def _blocking_call(node: ast.Call):
    """Classify a direct call: ``(reason, signal_unsafe)`` or None.

    ``signal_unsafe`` marks the narrow set that is also forbidden on
    signal-handler paths (buffered file I/O is allowed there — Python
    delivers signals between bytecodes, not inside C I/O — so ``open``
    and ``fsync`` are lock-hold problems only).
    """
    q = _qualname(node.func)
    last = q.rsplit(".", 1)[-1] if q else None
    recv = _receiver_name(node.func)
    if q == "open":
        return ("open() file I/O", False)
    if q in {"os.fsync", "os.fdatasync"}:
        return (f"{q}() disk barrier", False)
    if q and q.startswith("subprocess."):
        return (f"{q}() subprocess", True)
    if q == "select.select":
        return ("select.select()", True)
    if last == "sleep" or q == "_sleep":
        return ("sleep", True)
    if last in {"block_until_ready", "device_get"}:
        return (f".{last}() device sync", True)
    if last == "acquire" and recv and _LOCKISH.search(recv):
        for kw in node.keywords:
            if kw.arg == "blocking" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is False:
                return None  # try-lock: the signal-safe idiom
        if node.args and isinstance(node.args[0], ast.Constant) \
                and node.args[0].value is False:
            return None
        return (f"blocking {recv}.acquire()", True)
    if last == "wait" and recv is not None:
        return (f"{recv}.wait()", True)
    if last == "join":
        if isinstance(getattr(node.func, "value", None), ast.Constant):
            return None  # "sep".join(...)
        if q and q.startswith(("os.path", "posixpath", "ntpath")):
            return None
        if recv and _THREADISH.search(recv):
            return (f"{recv}.join()", True)
        return None
    if last in {"get", "put"} and recv and _QUEUEISH.search(recv):
        return (f"{recv}.{last}()", True)
    if last in {"accept", "recv", "recv_into", "sendall", "makefile"}:
        return (f"socket .{last}()", True)
    if last in {"connect", "create_connection"} and (
            q == "socket.create_connection"
            or (recv and _SOCKISH.search(recv))):
        return (f"{last}() dial", True)
    return None


@dataclass
class _Finding:
    kind: str
    lineno: int
    func: str
    detail: str


@dataclass
class _FnSummary:
    """Per-function bottom-up facts, closed under same-file calls."""

    blocks: str | None = None  # broad-set witness ("why"), or None
    signal_blocks: str | None = None  # narrow-set witness, or None
    acquires: dict = field(default_factory=dict)  # lock id -> lineno
    calls: set = field(default_factory=set)  # callee last-component names


class _FileScan:
    def __init__(self, src: str, filename: str):
        self.src_lines = src.splitlines()
        self.filename = filename
        self.tree = ast.parse(src, filename=filename)
        self.findings: list[_Finding] = []
        self.waived = 0
        self.guarded_fields = 0
        self.lock_edges: dict = {}  # (lockA, lockB) -> (lineno, func)
        # every def in the file (incl. methods and nested), name -> [nodes]
        self.defs_by_name: dict[str, list] = {}
        self.class_of_def: dict[int, str | None] = {}
        self.summaries: dict[int, _FnSummary] = {}
        self.class_names: list[str] = []
        self._collect_defs()

    # ------------------------------------------------------------- setup

    def _collect_defs(self):
        def walk(node, cls):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    self.class_names.append(child.name)
                    walk(child, child.name)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    self.defs_by_name.setdefault(child.name, []).append(child)
                    self.class_of_def[id(child)] = cls
                    walk(child, cls)
                else:
                    walk(child, cls)

        walk(self.tree, None)

    def _line_has(self, lineno: int, marker: str) -> bool:
        if 1 <= lineno <= len(self.src_lines):
            return marker in self.src_lines[lineno - 1]
        return False

    def _record(self, kind, lineno, func, detail, fn_waived=False):
        if self._line_has(lineno, PRAGMA) or fn_waived:
            self.waived += 1
            return
        self.findings.append(_Finding(kind, lineno, func, detail))

    # ---------------------------------------------------- lock identity

    def _lock_id(self, expr, cls: str | None) -> str | None:
        """Class-qualified name of a lock expression, or None if the
        expression doesn't look like a lock.  ``self._lock`` in class C
        → ``C._lock``; ``client._lock`` matches a same-file class by
        receiver-name containment (``client`` → ``ReplicaClient``)."""
        if isinstance(expr, ast.Name):
            return expr.id if _LOCKISH.search(expr.id) else None
        if not isinstance(expr, ast.Attribute):
            return None
        if not _LOCKISH.search(expr.attr):
            return None
        base = expr.value
        if isinstance(base, ast.Name):
            if base.id == "self" and cls is not None:
                return f"{cls}.{expr.attr}"
            for name in self.class_names:
                if base.id.lower().replace("_", "") in name.lower():
                    return f"{name}.{expr.attr}"
            return f"{base.id}.{expr.attr}"
        q = _qualname(expr)
        return q

    def _callee_name(self, call: ast.Call) -> str | None:
        """Name a call resolves to among same-file defs, or None.

        Bare-name calls resolve by name.  Attribute calls resolve only
        when the receiver plausibly IS an instance of a same-file class:
        ``self.x()`` / ``cls.x()`` always, ``recorder.dump()`` when some
        class name contains the receiver (``recorder`` →
        ``FlightRecorder``).  ``json.dump()`` must NOT resolve to a
        local ``dump`` method — module receivers match no class."""
        q = _qualname(call.func)
        if q is None:
            return None
        if isinstance(call.func, ast.Name):
            return q
        last = q.rsplit(".", 1)[-1]
        recv = _receiver_name(call.func)
        if recv in {"self", "cls"}:
            return last
        if recv is not None and len(recv) >= 3:
            probe = recv.lower().lstrip("_")
            for name in self.class_names:
                if probe in name.lower():
                    return last
        return None

    # ------------------------------------------------- function summaries

    def _direct_summary(self, fn) -> _FnSummary:
        s = _FnSummary()
        cls = self.class_of_def.get(id(fn))
        for node in _walk_own(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lid = self._lock_id(item.context_expr, cls)
                    if lid is not None:
                        s.acquires.setdefault(lid, node.lineno)
                        # entering `with <lock>` IS a blocking acquire on
                        # the narrow (signal-path) set; it is NOT a broad
                        # lock-blocking primitive — nested acquisition is
                        # the lock-order check's job, not this one's
                        if s.signal_blocks is None:
                            s.signal_blocks = f"blocking acquire of {lid}"
            if not isinstance(node, ast.Call):
                continue
            hit = _blocking_call(node)
            if hit is not None:
                reason, narrow = hit
                if s.blocks is None:
                    s.blocks = reason
                if narrow and s.signal_blocks is None:
                    s.signal_blocks = reason
                if reason.startswith("blocking ") and \
                        isinstance(node.func, ast.Attribute):
                    lid = self._lock_id(node.func.value, cls)
                    if lid is not None:
                        s.acquires.setdefault(lid, node.lineno)
            callee = self._callee_name(node)
            if callee is not None:
                s.calls.add(callee)
        return s

    def _compute_summaries(self):
        fns = [f for fl in self.defs_by_name.values() for f in fl]
        for fn in fns:
            self.summaries[id(fn)] = self._direct_summary(fn)
        # fixpoint: propagate through same-file, name-matched calls
        changed = True
        while changed:
            changed = False
            for fn in fns:
                s = self.summaries[id(fn)]
                for callee_name in s.calls:
                    for callee in self.defs_by_name.get(callee_name, ()):
                        if callee is fn:
                            continue
                        cs = self.summaries[id(callee)]
                        if cs.blocks is not None and s.blocks is None:
                            s.blocks = f"{callee_name}() → {cs.blocks}"
                            changed = True
                        if cs.signal_blocks is not None \
                                and s.signal_blocks is None:
                            s.signal_blocks = (
                                f"{callee_name}() → {cs.signal_blocks}"
                            )
                            changed = True
                        for lid, ln in cs.acquires.items():
                            if lid not in s.acquires:
                                s.acquires[lid] = ln
                                changed = True

    # ------------------------------------------------------- main passes

    def scan(self) -> list[_Finding]:
        self._compute_summaries()
        for name, fns in self.defs_by_name.items():
            for fn in fns:
                self._scan_fn(fn)
        self._scan_guards()
        self._scan_signal_handlers()
        return self.findings

    def _scan_fn(self, fn):
        """Lexical walk with the held-lock stack: blocking-under-lock
        findings and lock-graph edges."""
        cls = self.class_of_def.get(id(fn))
        fn_waived = self._line_has(fn.lineno, PRAGMA)
        held: list[str] = []

        def visit(node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                return  # nested defs run later, not under these locks
            if isinstance(node, (ast.With, ast.AsyncWith)):
                pushed = []
                for item in node.items:
                    lid = self._lock_id(item.context_expr, cls)
                    if lid is not None:
                        self._note_acquire(lid, node.lineno, fn, held)
                        held.append(lid)
                        pushed.append(lid)
                for child in node.body:
                    visit(child)
                for _ in pushed:
                    held.pop()
                return
            if isinstance(node, ast.Call) and held:
                self._check_call_under_lock(node, fn, cls, held, fn_waived)
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in fn.body:
            visit(stmt)

    def _note_acquire(self, lid, lineno, fn, held):
        for h in held:
            if h != lid:
                self.lock_edges.setdefault(
                    (h, lid), (lineno, getattr(fn, "name", "<lambda>"))
                )

    def _check_call_under_lock(self, node, fn, cls, held, fn_waived):
        name = getattr(fn, "name", "<lambda>")
        hit = _blocking_call(node)
        if hit is not None:
            reason, _narrow = hit
            if reason.startswith("blocking ") and \
                    isinstance(node.func, ast.Attribute):
                lid = self._lock_id(node.func.value, cls)
                if lid is not None:
                    self._note_acquire(lid, node.lineno, fn, held)
            self._record(
                "lock-blocking", node.lineno, name,
                f"{reason} while holding {held[-1]} in `{name}` — every "
                f"waiter on that lock inherits the block",
                fn_waived=fn_waived,
            )
            return
        callee_name = self._callee_name(node)
        if callee_name is None:
            return
        for callee in self.defs_by_name.get(callee_name, ()):
            cs = self.summaries[id(callee)]
            if cs.blocks is not None:
                self._record(
                    "lock-blocking", node.lineno, name,
                    f"call to `{callee_name}` (which blocks: {cs.blocks}) "
                    f"while holding {held[-1]} in `{name}`",
                    fn_waived=fn_waived,
                )
                break
        else:
            return
        for callee in self.defs_by_name.get(callee_name, ()):
            for lid, ln in self.summaries[id(callee)].acquires.items():
                self._note_acquire(lid, node.lineno, fn, held)

    # --------------------------------------------------------- lock order

    def lock_order_findings(self) -> list[_Finding]:
        """Cycles in the per-file lock graph (ABBA shapes)."""
        graph: dict[str, set] = {}
        for (a, b) in self.lock_edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        out = []
        seen_cycles = set()
        for start in sorted(graph):
            path, stack = [], [(start, iter(sorted(graph[start])))]
            on_path = {start}
            path.append(start)
            while stack:
                node, it = stack[-1]
                nxt = next(it, None)
                if nxt is None:
                    stack.pop()
                    on_path.discard(path.pop())
                    continue
                if nxt in on_path:
                    cyc = tuple(path[path.index(nxt):]) + (nxt,)
                    key = frozenset(cyc)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        lineno, func = self.lock_edges.get(
                            (cyc[0], cyc[1]), (0, "?")
                        )
                        out.append(_Finding(
                            "lock-order", lineno, func,
                            "lock-order cycle "
                            + " → ".join(cyc)
                            + " — two threads taking these in opposite "
                            "order deadlock; pick one global order",
                        ))
                    continue
                if nxt in graph and nxt not in on_path:
                    on_path.add(nxt)
                    path.append(nxt)
                    stack.append((nxt, iter(sorted(graph[nxt]))))
        return out

    # ------------------------------------------------------- guard checks

    def _guarded_map(self, cls_node) -> dict:
        """``field -> lock attr`` from annotated ``__init__`` lines."""
        out = {}
        for fn in cls_node.body:
            if not (isinstance(fn, ast.FunctionDef)
                    and fn.name == "__init__"):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                line = self.src_lines[node.lineno - 1] \
                    if node.lineno <= len(self.src_lines) else ""
                if GUARDED_BY not in line:
                    continue
                lock = line.split(GUARDED_BY, 1)[1].strip().split()[0]
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        out[t.attr] = lock
        return out

    def _scan_guards(self):
        for cls_node in ast.walk(self.tree):
            if not isinstance(cls_node, ast.ClassDef):
                continue
            guarded = self._guarded_map(cls_node)
            if not guarded:
                continue
            self.guarded_fields += len(guarded)
            for fn in cls_node.body:
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                if fn.name == "__init__" or fn.name.endswith("_locked"):
                    continue
                self._scan_guarded_writes(cls_node.name, fn, guarded)

    def _scan_guarded_writes(self, cls, fn, guarded):
        fn_waived = self._line_has(fn.lineno, PRAGMA)
        held: list[str] = []

        def self_field(expr) -> str | None:
            """``self.<field>`` or ``self.<field>[...]`` → field name."""
            if isinstance(expr, ast.Subscript):
                expr = expr.value
            if isinstance(expr, ast.Attribute) and \
                    isinstance(expr.value, ast.Name) and \
                    expr.value.id == "self" and expr.attr in guarded:
                return expr.attr
            return None

        def check_write(fieldname, lineno):
            lock = guarded[fieldname]
            if f"{cls}.{lock}" in held:
                return
            if self._line_has(lineno, f"{HOLDS} {lock}"):
                return
            self._record(
                "guard", lineno, fn.name,
                f"`self.{fieldname}` (guarded-by: {lock}) written in "
                f"`{fn.name}` without holding {cls}.{lock} — annotate the "
                f"line `# holds: {lock}` if the caller provably holds it",
                fn_waived=fn_waived,
            )

        def visit(node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                pushed = []
                for item in node.items:
                    lid = self._lock_id(item.context_expr, cls)
                    if lid is not None:
                        held.append(lid)
                        pushed.append(lid)
                for child in node.body:
                    visit(child)
                for _ in pushed:
                    held.pop()
                return
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    f = self_field(t)
                    if f is not None:
                        check_write(f, node.lineno)
            elif isinstance(node, ast.AugAssign):
                f = self_field(node.target)
                if f is not None:
                    check_write(f, node.lineno)
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS:
                f = self_field(node.func.value)
                if f is not None:
                    check_write(f, node.lineno)
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in fn.body:
            visit(stmt)

    # ----------------------------------------------- signal-handler paths

    def _signal_handlers(self):
        """Defs registered via ``signal.signal(sig, handler)``."""
        out = []
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Call)
                    and _qualname(node.func) == "signal.signal"
                    and len(node.args) >= 2):
                continue
            h = node.args[1]
            name = None
            if isinstance(h, ast.Name):
                name = h.id
            elif isinstance(h, ast.Attribute):
                name = h.attr  # self._handler → method name
            if name is None:
                continue
            for fn in self.defs_by_name.get(name, ()):
                out.append(fn)
        return out

    def _scan_signal_handlers(self):
        for fn in self._signal_handlers():
            fn_waived = self._line_has(fn.lineno, PRAGMA)
            s = self.summaries.get(id(fn))
            if s is None or s.signal_blocks is None:
                continue
            self._record(
                "signal-blocking", fn.lineno, fn.name,
                f"signal handler `{fn.name}` can block: {s.signal_blocks} "
                f"— a handler runs ON the interrupted thread, which may "
                f"hold the very lock/queue it would wait on (permanent "
                f"deadlock); use try-lock (`acquire(blocking=False)`) or "
                f"set-a-flag-and-return",
                fn_waived=fn_waived,
            )


def _walk_own(fn):
    """Walk ``fn``'s body without descending into nested defs."""
    stack = list(
        ast.iter_child_nodes(fn)
        if not isinstance(fn, ast.Lambda)
        else [fn.body]
    )
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def scan_source(src: str, filename: str = "<string>"):
    """Lint one source blob; returns ``(violations, detail)`` where
    detail carries the waived count, guarded-field count, and the file's
    lock edges (for the whole-tree graph report)."""
    scan = _FileScan(src, filename)
    findings = scan.scan()
    findings += scan.lock_order_findings()
    out = [
        Violation(
            "concurrency", f.kind, f"{filename}:{f.lineno}", f.detail,
            src=f.lineno,
        )
        for f in findings
    ]
    return out, {
        "waived": scan.waived,
        "guarded_fields": scan.guarded_fields,
        "lock_edges": sorted(
            f"{a} → {b}" for a, b in scan.lock_edges
        ),
    }


def scan_file(path: str, rel: str | None = None):
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    return scan_source(src, rel or path)


def run_concurrency_lint(
    root: str | None = None, programs=None, times: dict | None = None
):
    """Lint every ``.py`` file under the package root; ``programs``
    filters by path substring, ``times`` collects per-package wall-times
    (grouped by top-level subpackage) like every other layer."""
    import time as _time

    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base = os.path.dirname(os.path.abspath(root))
    violations: list[Violation] = []
    detail: dict = {
        "files_scanned": 0, "waived": 0, "guarded_fields": 0,
        "lock_edges": [],
    }
    edges: set = set()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, base)
            if programs and not any(p in rel for p in programs):
                continue
            t0 = _time.perf_counter()
            vs, d = scan_file(path, rel)
            violations += vs
            detail["files_scanned"] += 1
            detail["waived"] += d["waived"]
            detail["guarded_fields"] += d["guarded_fields"]
            edges.update(d["lock_edges"])
            if times is not None:
                pkg = os.path.dirname(rel) or rel
                times[pkg] = round(
                    times.get(pkg, 0.0)
                    + (_time.perf_counter() - t0) * 1e3, 1
                )
    detail["lock_edges"] = sorted(edges)
    return violations, detail
