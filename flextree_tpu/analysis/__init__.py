"""FlexTree static verifier: ahead-of-time analysis of generated programs.

Five layers (plus the IR-equivalence pass), one report:

1. :mod:`.schedule_check` — model-check generated message programs for
   every schedule family (tree/ring/lonely/swing/generalized × chunked):
   deadlock-freedom under blocking rendezvous, chunk conservation, peer
   symmetry, chunk-buffer overlap.  Every program is expanded from the
   declarative schedule IR (``schedule/ir.py``) by
   :func:`~.schedule_check.program_from_ir` — the same object
   ``compile_ir`` lowers, so checker and executable cannot drift.
2. :mod:`.hlo_lint` — lower the jitted entrypoints and lint the StableHLO
   against declared collective budgets, dtype, host-transfer, and
   donation contracts; :mod:`.ir_equivalence` additionally certifies each
   IR-compiled collective's StableHLO sequence matches its IR stage list.
3. :mod:`.jit_hygiene` — AST lint over the library source for
   wall-clock/RNG calls inside jitted code, Python branching on traced
   values, and missing ``static_argnames``.
4. :mod:`.protocol_check` — explicit-state model checking of the
   control-plane protocols: exhaustive small-world exploration of the
   extracted coordination/lease/RPC transition models (each living
   beside its implementation, pinned by shared constants +
   ``tests/test_control_plane_analysis.py``) with faults injected at
   every transition.
5. :mod:`.concurrency_lint` — AST/call-graph lint of the threaded host
   code: lock-order cycles, blocking calls under a lock, writes to
   ``# guarded-by:``-annotated fields without the lock, and blocking
   primitives reachable from signal handlers.

The suite is self-distrusting: :mod:`.mutation` seeds known corruption
classes and asserts each is caught — a checker that passes everything is
a failing test.  CLI: ``python -m flextree_tpu.analysis --report
ANALYSIS.json`` (``--programs`` filters the matrices); CI gate:
``tools/run_static_checks.py --staleness-gate``.
"""

from .base import Violation, violations_to_json
from .schedule_check import (
    build_program,
    check_ir,
    check_ir_families,
    check_program,
    check_schedule,
    check_standard_schedules,
    program_from_ir,
)

__all__ = [
    "Violation",
    "violations_to_json",
    "build_program",
    "check_ir",
    "check_ir_families",
    "check_program",
    "check_schedule",
    "check_standard_schedules",
    "program_from_ir",
]
