"""FlexTree static verifier: ahead-of-time analysis of generated programs.

Three layers, one report:

1. :mod:`.schedule_check` — model-check generated message programs
   (tree/ring/lonely × chunked): deadlock-freedom under blocking
   rendezvous, chunk conservation, peer symmetry, chunk-buffer overlap.
2. :mod:`.hlo_lint` — lower the jitted entrypoints and lint the StableHLO
   against declared collective budgets, dtype, host-transfer, and
   donation contracts.
3. :mod:`.jit_hygiene` — AST lint over the library source for
   wall-clock/RNG calls inside jitted code, Python branching on traced
   values, and missing ``static_argnames``.

The suite is self-distrusting: :mod:`.mutation` seeds known corruption
classes and asserts each is caught — a checker that passes everything is
a failing test.  CLI: ``python -m flextree_tpu.analysis --report
ANALYSIS.json``; CI gate: ``tools/run_static_checks.py``.
"""

from .base import Violation, violations_to_json
from .schedule_check import (
    build_program,
    check_program,
    check_schedule,
    check_standard_schedules,
)

__all__ = [
    "Violation",
    "violations_to_json",
    "build_program",
    "check_program",
    "check_schedule",
    "check_standard_schedules",
]
