"""Shared vocabulary of the static-analysis suite: the Violation record.

Every layer (schedule model checker, HLO linter, jit-hygiene lint) reports
findings as :class:`Violation` rows so the CLI, the CI gate, and the
mutation self-test can treat them uniformly.  A violation is *located*:
schedule violations name ``(stage, src, dst, block)``, HLO violations name
the entrypoint and the offending op line, jit-hygiene violations name
``file:line``.  ``detail`` is always a full human sentence — the analyzer
is a reviewer, not a boolean.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass

__all__ = ["Violation", "violations_to_json"]


@dataclass(frozen=True)
class Violation:
    """One analyzer finding.

    ``layer``: ``"schedule"`` | ``"hlo"`` | ``"jit"`` | ``"protocol"`` |
    ``"concurrency"``.
    ``kind``: a stable machine-readable class (``"deadlock"``,
    ``"double-count"``, ``"dropped-block"``, ``"asymmetric-match"``,
    ``"chunk-overlap"``, ``"unbounded-wait"``, ``"budget"``,
    ``"dtype-drift"``, ``"host-transfer"``, ``"donation"``,
    ``"wall-clock"``, ``"rng"``, ``"traced-branch"``,
    ``"static-argnames"``; protocol kinds like ``"epoch-double-commit"``,
    ``"double-grant"``, ``"completed-rid-reexecuted"``,
    ``"clean-rank-fenced"``; concurrency kinds ``"lock-order"``,
    ``"lock-blocking"``, ``"guard"``, ``"signal-blocking"``) — the
    mutation self-test asserts on these.
    ``where``: entrypoint / schedule / file the finding is in.
    ``stage``/``src``/``dst``/``block``: schedule coordinates (None for the
    other layers; ``src``/``dst`` double as line numbers for jit findings).
    """

    layer: str
    kind: str
    where: str
    detail: str
    stage: int | None = None
    src: int | None = None
    dst: int | None = None
    block: int | None = None

    def __str__(self) -> str:
        loc = ""
        if self.stage is not None or self.src is not None:
            coords = ", ".join(
                f"{k}={v}"
                for k, v in (
                    ("stage", self.stage),
                    ("src", self.src),
                    ("dst", self.dst),
                    ("block", self.block),
                )
                if v is not None
            )
            loc = f" [{coords}]"
        return f"{self.layer}/{self.kind} @ {self.where}{loc}: {self.detail}"


def violations_to_json(violations) -> list[dict]:
    """JSON-ready rows (stable key order, no Nones dropped — the report is
    a committed artifact and diffs should be meaningful)."""
    return [asdict(v) for v in violations]
