"""Layer 2: HLO linter — lower the jitted entrypoints and hold the
StableHLO to declared budgets.

``tests/test_hlo_lowering.py`` pins a handful of lowering facts with
one-off asserts; this layer generalizes them into a declarative contract:
every entrypoint (allreduce variants, the bucketed train step, the MoE and
pipeline steps) carries an :class:`HloBudget` stating what its compiled
program may contain —

- **collective counts**: scheduled collectives scale with buckets and
  stages, never with gradient leaves; chunked schedules multiply by the
  chunk count, never more;
- **op classes**: no ``all_to_all`` outside the entrypoints that earn it
  (Ulysses, MoE dispatch), no host transfers
  (``send``/``recv``/``infeed``/``outfeed``) anywhere;
- **dtype**: collectives on the bf16 path carry bf16 operands — a silent
  f32 upcast doubles wire bytes and is exactly the kind of regression a
  refactor introduces without failing any numeric test;
- **donation**: entrypoints jitted with donated buffers actually lower
  with ``jax.buffer_donor`` so XLA may alias (a dropped donation doubles
  peak memory, again numerically invisible).

Everything works on ``jax.jit(...).lower().as_text()`` — tracing plus
StableHLO emission, no XLA compile — so the whole layer runs in seconds
on the CPU host.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from .base import Violation

__all__ = [
    "HloBudget",
    "collective_counts",
    "collective_operand_dtypes",
    "collective_wire_bytes",
    "lint_ir",
    "lower_entrypoints",
    "overlap_sync_budget",
    "sharded_sync_budget",
    "run_hlo_lint",
]

#: StableHLO ops that move data between host and device — never expected
#: in any FlexTree program (the whole point is staying on-fabric).
HOST_TRANSFER_OPS = (
    "stablehlo.send",
    "stablehlo.recv",
    "stablehlo.infeed",
    "stablehlo.outfeed",
)

COLLECTIVE_OPS = (
    "reduce_scatter",
    "all_gather",
    "all_reduce",
    "collective_permute",
    "all_to_all",
)


@dataclass(frozen=True)
class HloBudget:
    """Declared contract for one lowered entrypoint.  ``None`` = unchecked;
    counts are exact-or-max depending on ``exact`` (exact catches both
    regressions *and* silently-vanished collectives)."""

    reduce_scatter: int | None = None
    all_gather: int | None = None
    all_reduce: int | None = None
    collective_permute: int | None = None
    all_to_all: int | None = 0
    exact: bool = True
    #: allowed element types on collective operands (None = unchecked)
    collective_dtypes: tuple[str, ...] | None = None
    #: require at least one donated input to survive lowering
    require_donation: bool = False
    #: compressed entrypoints: at least one collective must carry this
    #: element type on the wire (e.g. "i8") — a refactor that decodes
    #: before the collective keeps the numerics quantized but silently
    #: multiplies the wire bytes back up (violation kind "codec-upcast")
    require_wire_dtype: str | None = None
    #: overlapped entrypoints: backward compute (dot_general) must appear
    #: AFTER the first scheduled sync collective in program order — the
    #: readiness-ordered step issues each bucket's collective mid-backward,
    #: so a program whose collectives all trail the last matmul has
    #: reintroduced the full-backward barrier (violation kind
    #: "overlap-serialization"; StableHLO emission preserves trace order,
    #: so the check is a pure text-order one).  Only meaningful on
    #: entrypoints whose forward has no collectives (dp-only meshes).
    require_compute_after_collective: bool = False
    #: sharded (ZeRO) entrypoints: at least one all_gather must FOLLOW the
    #: first optimizer sqrt (AdamW's sqrt(nu)) in program order — the
    #: sharded step gathers updated PARAMETERS, which exist only after the
    #: shard update; a step whose gathers all precede the optimizer math
    #: has regathered the GRADIENTS instead (the replicated schedule in
    #: disguise: numerically identical for f32, but the optimizer state is
    #: fully replicated again and the wire savings the sharding exists for
    #: are gone).  Violation kind "shard-regather"; only meaningful on
    #: entrypoints whose forward emits no all_gather (dp-only meshes) and
    #: whose only sqrt is AdamW's (rms_norm uses rsqrt, a different op).
    require_gather_after_update: bool = False
    note: str = ""


def collective_counts(ir: str) -> dict[str, int]:
    return {op: ir.count(f'"stablehlo.{op}"') for op in COLLECTIVE_OPS}


def collective_operand_dtypes(ir: str) -> dict[str, list[str]]:
    """Element type of each collective op's operand, parsed from the
    ``: (tensor<...xTY>, ...) -> ...`` suffix of its line.  The attribute
    dict mid-line contains nested ``<...>`` (channel handles), so only the
    trailing operand-type list is parsed — same lesson as
    ``tests/test_hlo_lowering.py``."""
    out: dict[str, list[str]] = {op: [] for op in COLLECTIVE_OPS}
    for line in ir.splitlines():
        for op in COLLECTIVE_OPS:
            if f'"stablehlo.{op}"' not in line:
                continue
            m = re.search(r":\s*\(tensor<([^>]*?)>", line)
            if m:
                elem = m.group(1).split("x")[-1]
                out[op].append(elem)
    return out


_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "i64": 8, "i32": 4, "i16": 2, "i8": 1, "i1": 1,
    "ui64": 8, "ui32": 4, "ui16": 2, "ui8": 1,
}


_COLL_RE = re.compile(
    r'"stablehlo\.(reduce_scatter|all_reduce|all_gather|all_to_all|'
    r'collective_permute)"'
)
_SIG_RE = re.compile(r":\s*\(([^()]*)\)\s*->")
_GRP_RE = re.compile(r"replica_groups\s*=\s*dense<[^>]*>\s*:\s*tensor<\d+x(\d+)xi64>")
_TENSOR_RE = re.compile(r"tensor<([0-9x]*)([a-z][a-z0-9]*)>")


def collective_wire_bytes(ir: str) -> dict[str, float]:
    """Per-chip wire bytes of every collective in ``ir``, from the lowered
    StableHLO — the static accounting BENCH_SHARDED.json's floor is
    checked against.

    Per op the operand bytes (every tensor in its ``: (...) ->``
    signature; region ops close with ``}) : (tensor<..>)``, and their
    reducer-body ops carry no parenthesized signature, so the first match
    after the op IS its own) are scaled by the op's wire factor over its
    replica-group width ``w``: ``(w-1)/w`` for reduce_scatter/all_to_all
    (each chip keeps 1/w), ``2(w-1)/w`` for all_reduce, ``w-1`` for
    all_gather (the operand is the 1/w tile; each chip receives ``w-1``
    more), ``1`` for collective_permute.  Only valid for programs whose
    collectives are not inside ``fori_loop`` bodies (loop trip counts are
    invisible to a text scan) — the flat tree lowers loop-free, which is
    why the sharded bench pins ``grad_topo`` flat.
    """
    out: dict[str, float] = {op: 0.0 for op in COLLECTIVE_OPS}
    for m in _COLL_RE.finditer(ir):
        op = m.group(1)
        window = ir[m.start() : m.start() + 8000]
        sig = _SIG_RE.search(window)
        if not sig:
            continue
        grp = _GRP_RE.search(window[: sig.end()])
        w = int(grp.group(1)) if grp else 1
        nbytes = 0
        for dims, ty in _TENSOR_RE.findall(sig.group(1)):
            n = 1
            for d in dims.split("x"):
                if d:
                    n *= int(d)
            nbytes += n * _DTYPE_BYTES.get(ty, 4)
        if op in ("reduce_scatter", "all_to_all"):
            factor = (w - 1) / w if w > 1 else 0.0
        elif op == "all_reduce":
            factor = 2 * (w - 1) / w if w > 1 else 0.0
        elif op == "all_gather":
            factor = float(w - 1)
        else:
            factor = 1.0
        out[op] += nbytes * factor
    out["total"] = sum(out[op] for op in COLLECTIVE_OPS)
    return out


def lint_ir(name: str, ir: str, budget: HloBudget) -> list[Violation]:
    out: list[Violation] = []
    counts = collective_counts(ir)
    for op in COLLECTIVE_OPS:
        want = getattr(budget, op)
        if want is None:
            continue
        got = counts[op]
        bad = got != want if budget.exact else got > want
        if bad:
            rel = "!=" if budget.exact else ">"
            out.append(
                Violation(
                    "hlo",
                    "budget",
                    name,
                    f"{got} stablehlo.{op} ops {rel} budget {want}"
                    + (f" ({budget.note})" if budget.note else ""),
                )
            )
    for op in HOST_TRANSFER_OPS:
        if f'"{op}"' in ir:
            out.append(
                Violation(
                    "hlo",
                    "host-transfer",
                    name,
                    f"unexpected {op}: program round-trips through the host",
                )
            )
    if budget.collective_dtypes is not None:
        for op, dtypes in collective_operand_dtypes(ir).items():
            for dt in dtypes:
                if dt not in budget.collective_dtypes:
                    out.append(
                        Violation(
                            "hlo",
                            "dtype-drift",
                            name,
                            f"stablehlo.{op} operates on {dt}, allowed "
                            f"{budget.collective_dtypes}: a silent upcast "
                            f"multiplies wire bytes",
                        )
                    )
                    break
    if budget.require_wire_dtype is not None:
        seen = {dt for dts in collective_operand_dtypes(ir).values() for dt in dts}
        if budget.require_wire_dtype not in seen:
            out.append(
                Violation(
                    "hlo",
                    "codec-upcast",
                    name,
                    f"no collective carries {budget.require_wire_dtype} on "
                    f"the wire (saw {sorted(seen)}): the codec was decoded "
                    f"before the collective — numerics stay quantized while "
                    f"the wire bytes silently multiply back up",
                )
            )
    if budget.require_compute_after_collective:
        lines = ir.splitlines()
        first_coll = None
        last_dot = None
        for i, line in enumerate(lines):
            if first_coll is None and (
                '"stablehlo.reduce_scatter"' in line
                or '"stablehlo.all_to_all"' in line
            ):
                first_coll = i
            if "stablehlo.dot_general" in line:
                last_dot = i
        if first_coll is None or last_dot is None or last_dot < first_coll:
            out.append(
                Violation(
                    "hlo",
                    "overlap-serialization",
                    name,
                    "no backward compute (dot_general) follows the first "
                    "sync collective: every collective trails the full "
                    "backward — the readiness-ordered overlap has been "
                    "serialized behind a full-backward barrier",
                )
            )
    if budget.require_gather_after_update:
        # anchor AFTER the first sync collective (reduce_scatter /
        # all_to_all): the forward emits its own sqrt ops, but only the
        # optimizer's sqrt(nu) can appear after the gradient sync starts
        # — in the correct sharded step that sqrt precedes the parameter
        # all_gather; in the grad-regathering corruption every gather
        # lands before it
        lines = ir.splitlines()
        first_coll = None
        first_sqrt_after = None
        last_gather = None
        for i, line in enumerate(lines):
            if first_coll is None and (
                '"stablehlo.reduce_scatter"' in line
                or '"stablehlo.all_to_all"' in line
            ):
                first_coll = i
            if (
                first_coll is not None
                and first_sqrt_after is None
                and i > first_coll
                and "stablehlo.sqrt " in line
            ):
                first_sqrt_after = i
            if '"stablehlo.all_gather"' in line:
                last_gather = i
        if (
            first_coll is None
            or first_sqrt_after is None
            or last_gather is None
            or last_gather < first_sqrt_after
        ):
            out.append(
                Violation(
                    "hlo",
                    "shard-regather",
                    name,
                    "no all_gather follows the optimizer update (first "
                    "sqrt) in program order: the step gathers GRADIENTS "
                    "instead of updated parameter shards — the replicated "
                    "schedule in disguise, with the optimizer state fully "
                    "replicated again and the sharded wire savings gone",
                )
            )
    if budget.require_donation and "jax.buffer_donor" not in ir:
        out.append(
            Violation(
                "hlo",
                "donation",
                name,
                "no jax.buffer_donor attribute survived lowering: the "
                "donated input is being copied, doubling peak memory",
            )
        )
    return out


# ----------------------------------------------------------- entrypoints


def _require_devices(n: int = 8) -> None:
    import jax

    if len(jax.devices()) < n:
        raise RuntimeError(
            f"hlo lint needs {n} (virtual) devices, found "
            f"{len(jax.devices())} — run under the analysis CLI or the "
            f"test harness, which pin 8 virtual CPU devices"
        )


def _lower_allreduce(topo, op="sum", dtype=None, chunks=1, donate=False) -> str:
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..parallel import tree_allreduce
    from ..parallel.mesh import flat_mesh

    if dtype is None:
        dtype = jnp.float32 if op == "sum" else jnp.int32
    mesh = flat_mesh(8, "ft")

    def f(row):
        return tree_allreduce(row[0], "ft", topo, op=op, chunks=chunks)[None]

    fn = jax.shard_map(f, mesh=mesh, in_specs=P("ft"), out_specs=P("ft"))
    jitted = jax.jit(fn, donate_argnums=(0,) if donate else ())
    return jitted.lower(jnp.zeros((8, 64), dtype)).as_text()


def _lower_compressed_allreduce(topo, codec, size: int = 2048, upcast: bool = False) -> str:
    """Lower ``compressed_allreduce`` with ``codec`` over an 8-device mesh.

    ``upcast=True`` builds the *corrupted* variant for the mutation
    self-test: quantize/dequantize locally, then run the plain f32
    collective — the classic silent wire upcast (numerically almost
    indistinguishable from the compressed path, 4x the wire bytes).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..ops.quantize import get_codec
    from ..parallel import tree_allreduce
    from ..parallel.compressed import compressed_allreduce
    from ..parallel.mesh import flat_mesh

    mesh = flat_mesh(8, "ft")

    def f(row):
        if upcast:
            c = get_codec(codec)
            return tree_allreduce(c.roundtrip(row[0], 0), "ft", topo)[None]
        return compressed_allreduce(row[0], "ft", topo=topo, codec=codec, step=0)[None]

    fn = jax.shard_map(f, mesh=mesh, in_specs=P("ft"), out_specs=P("ft"))
    return jax.jit(fn).lower(jnp.zeros((8, size), jnp.float32)).as_text()


def _lower_ring(dtype=None) -> str:
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..parallel import ring_allreduce
    from ..parallel.mesh import flat_mesh

    mesh = flat_mesh(8, "ft")

    def f(row):
        return ring_allreduce(row[0], "ft")[None]

    fn = jax.shard_map(f, mesh=mesh, in_specs=P("ft"), out_specs=P("ft"))
    return jax.jit(fn).lower(jnp.zeros((8, 64), dtype or jnp.float32)).as_text()


def _small_model_cfg():
    import jax.numpy as jnp

    from ..models.transformer import TransformerConfig

    return TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64
    )


def _lower_train_step(bucket_bytes) -> str:
    import jax
    import jax.numpy as jnp

    from ..parallel.train import (
        TrainConfig,
        init_train_state,
        make_mesh_nd,
        make_train_step,
    )

    model_cfg = _small_model_cfg()
    mesh = make_mesh_nd(8, (2, 2, 2), ("dp", "sp", "tp"))
    state_sds = jax.eval_shape(
        lambda k: init_train_state(k, model_cfg), jax.random.PRNGKey(0)
    )
    tok = jax.ShapeDtypeStruct((4, 32), jnp.int32)
    step = make_train_step(
        mesh, model_cfg, TrainConfig(bucket_bytes=bucket_bytes)
    )
    return step.lower(state_sds, tok, tok).as_text()


def _lower_native_train_step() -> str:
    import jax
    import jax.numpy as jnp

    from ..parallel.train import (
        TrainConfig,
        init_train_state,
        make_mesh_nd,
        make_train_step,
    )

    model_cfg = _small_model_cfg()
    mesh = make_mesh_nd(8, (2, 2, 2), ("dp", "sp", "tp"))
    state_sds = jax.eval_shape(
        lambda k: init_train_state(k, model_cfg), jax.random.PRNGKey(0)
    )
    tok = jax.ShapeDtypeStruct((4, 32), jnp.int32)
    step = make_train_step(mesh, model_cfg, TrainConfig(grad_topo="psum"))
    return step.lower(state_sds, tok, tok).as_text()


def bucketed_sync_budget() -> tuple[int, int]:
    """(expected fused-sync reduce_scatter/all_gather count, synced leaf
    count) from the very bucket plan the sync executes — the generalized
    form of the one-off guard in ``tests/test_hlo_lowering.py``."""
    import jax
    import jax.numpy as jnp

    from ..parallel.bucketing import plan_buckets, replication_key
    from ..parallel.train import init_train_state, state_specs

    model_cfg = _small_model_cfg()
    state_sds = jax.eval_shape(
        lambda k: init_train_state(k, model_cfg), jax.random.PRNGKey(0)
    )
    pspecs = state_specs(model_cfg, "tp")["params"]
    flat_g, treedef = jax.tree.flatten(state_sds["params"])
    flat_s = treedef.flatten_up_to(pspecs)
    axis_sizes = {"dp": 2, "sp": 2, "tp": 2}
    buckets = plan_buckets(
        flat_g, flat_s, ("dp", "sp", "tp"),
        axis_sizes=axis_sizes, bucket_bytes=1 << 30,
    )
    expected = sum(len(b.axes) for b in buckets)
    n_synced = sum(1 for s in flat_s if replication_key(s, ("dp", "sp", "tp")))
    return expected, n_synced


def _lower_overlap_train_step(
    serialize: bool = False, codec: str = "f32"
) -> str:
    """Lower the readiness-ordered overlapped dense step (or, with
    ``serialize=True``, its full-backward-barrier twin) on a dp-only
    8-device mesh — tp=sp=1, so the forward emits NO collectives and
    every scheduled collective in the program belongs to the gradient
    sync (the precondition for ``require_compute_after_collective``)."""
    import jax
    import jax.numpy as jnp

    from ..parallel.train import (
        TrainConfig,
        init_train_state,
        make_mesh_nd,
        make_train_step,
    )

    model_cfg = _small_model_cfg()
    mesh = make_mesh_nd(8, (8, 1, 1), ("dp", "sp", "tp"))
    # explicit inner cap AND explicit flat topology so the budget is
    # environment-independent: one collective per fired boundary bucket,
    # immune to an ambient FT_TOPO (grad_topo=None would resolve through
    # the env var and diverge from overlap_sync_budget's flat(8) plan)
    train_cfg = TrainConfig(
        overlap=True, codec=codec, bucket_bytes=1 << 30, grad_topo="8"
    )
    state_sds = jax.eval_shape(
        lambda k: init_train_state(k, model_cfg, train_cfg),
        jax.random.PRNGKey(0),
    )
    tok = jax.ShapeDtypeStruct((8, 32), jnp.int32)
    step = make_train_step(
        mesh, model_cfg, train_cfg, serialize_overlap=serialize
    )
    return step.lower(state_sds, tok, tok).as_text()


def overlap_sync_budget(codec: str = "f32") -> tuple[int, int]:
    """(number of fired overlap buckets, number of readiness segments)
    for the overlapped dense entrypoint above, from the very plan the
    step executes at trace time (``parallel.overlap.plan_overlap``) — so
    the collective-count budget tracks the planner, not a hand-kept
    constant.  On the dp-only mesh every bucket is one (dp, f32) group:
    one scheduled tree collective per bucket (rs+ag pair for the identity
    codec; grouped a2a/ag pairs for int8)."""
    import jax
    import jax.numpy as jnp

    from ..ops.quantize import get_codec
    from ..parallel.overlap import plan_overlap
    from ..parallel.train import TrainConfig, init_train_state, state_specs
    from ..schedule.stages import Topology

    model_cfg = _small_model_cfg()
    train_cfg = TrainConfig(overlap=True, codec=codec, bucket_bytes=1 << 30)
    state_sds = jax.eval_shape(
        lambda k: init_train_state(k, model_cfg, train_cfg),
        jax.random.PRNGKey(0),
    )
    pspecs = state_specs(model_cfg, "tp")["params"]
    c = get_codec(codec)
    # n_tokens/t_local are PER-DEVICE (inside shard_map the (8, 32) batch
    # shards to (1, 32) on the dp-8 mesh) — must match the traced values
    plan = plan_overlap(
        state_sds["params"], pspecs, ("dp", "sp", "tp"),
        {"dp": Topology.flat(8), "sp": None, "tp": None},
        {"dp": 8, "sp": 1, "tp": 1},
        n_tokens=32, t_local=32, d_model=model_cfg.d_model,
        codec=c if c.lossy else None,
    )
    return plan.n_buckets, len(plan.labels)


def _lower_split_collective(topo, phase: str, codec: str = "f32") -> str:
    """Lower a standalone reduce_scatter or all_gather over the 8-device
    mesh (divisible count, so the shard is a pure 1/N block)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..parallel.allreduce import all_gather, reduce_scatter
    from ..parallel.mesh import flat_mesh

    mesh = flat_mesh(8, "ft")
    size = 2048

    def f(row):
        if phase == "rs":
            return reduce_scatter(row[0], "ft", topo, codec=codec)[None]
        return all_gather(row[0], "ft", topo, codec=codec)[None]

    n_in = size if phase == "rs" else size // 8
    fn = jax.shard_map(f, mesh=mesh, in_specs=P("ft"), out_specs=P("ft"))
    return jax.jit(fn).lower(jnp.zeros((8, n_in), jnp.float32)).as_text()


def _lower_sharded_train_step(codec: str = "f32", regather: bool = False) -> str:
    """Lower the ZeRO-1 sharded dense step on a dp-only 8-device mesh —
    tp=sp=1, so the forward emits NO collectives and every reduce-scatter
    / all_gather in the program belongs to the sharded sync (the
    precondition for ``require_gather_after_update``).

    ``regather=True`` builds the *corrupted* variant for the mutation
    self-test: the replicated step over the same explicit flat(8) plan —
    literally "a sharded step that secretly all-gathers gradients instead
    of parameters" (identical collective counts: one rs + one ag per
    bucket; bitwise-identical f32 numerics; the ONLY observable
    difference is that its gathers precede the optimizer sqrt)."""
    import jax
    import jax.numpy as jnp

    from ..parallel.train import (
        TrainConfig,
        init_train_state,
        make_mesh_nd,
        make_train_step,
    )

    model_cfg = _small_model_cfg()
    mesh = make_mesh_nd(8, (8, 1, 1), ("dp", "sp", "tp"))
    train_cfg = TrainConfig(
        shard_optimizer=not regather, codec=codec,
        bucket_bytes=1 << 30, grad_topo="8",
    )
    state_sds = jax.eval_shape(
        lambda k: init_train_state(k, model_cfg, train_cfg, mesh=mesh),
        jax.random.PRNGKey(0),
    )
    tok = jax.ShapeDtypeStruct((8, 32), jnp.int32)
    step = make_train_step(mesh, model_cfg, train_cfg)
    return step.lower(state_sds, tok, tok).as_text()


def sharded_sync_budget(codec: str = "f32") -> tuple[int, int]:
    """(number of ZeRO buckets, number of synced leaves) for the sharded
    dense entrypoint above, from the very bucket plan the step executes —
    one grad reduce-scatter AND one param all-gather per bucket on the
    dp-only flat(8) plan (for int8: 2 grouped all_to_alls per bucket for
    the grads — i8 payload + f32 scales — and 2 all_gathers for the
    params)."""
    import jax
    import jax.numpy as jnp

    from ..ops.quantize import get_codec
    from ..parallel.bucketing import plan_buckets, replication_key
    from ..parallel.train import init_train_state, state_specs, TrainConfig

    model_cfg = _small_model_cfg()
    state_sds = jax.eval_shape(
        lambda k: init_train_state(k, model_cfg), jax.random.PRNGKey(0)
    )
    pspecs = state_specs(model_cfg, "tp")["params"]
    flat_g, treedef = jax.tree.flatten(state_sds["params"])
    flat_s = treedef.flatten_up_to(pspecs)
    axis_sizes = {"dp": 8, "sp": 1, "tp": 1}
    c = get_codec(codec)
    buckets = plan_buckets(
        flat_g, flat_s, ("dp", "sp", "tp"),
        axis_sizes=axis_sizes, bucket_bytes=1 << 30,
        codec=c if c.lossy else None, sharded=True,
    )
    n_synced = sum(
        1
        for s in flat_s
        if any(axis_sizes[a] > 1 for a in replication_key(s, ("dp", "sp", "tp")))
    )
    return len(buckets), n_synced


def _lower_moe_step() -> str:
    import jax
    import jax.numpy as jnp

    from ..models.moe import MoEConfig
    from ..parallel.moe_train import (
        init_moe_train_state,
        make_mesh_moe,
        make_moe_train_step,
    )
    from ..parallel.train import TrainConfig

    cfg = MoEConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        n_experts=4, top_k=1, moe_every=2,
    )
    mesh = make_mesh_moe(8, (1, 2, 2, 2))
    state_sds = jax.eval_shape(
        lambda k: init_moe_train_state(k, cfg), jax.random.PRNGKey(0)
    )
    tok = jax.ShapeDtypeStruct((4, 32), jnp.int32)
    step = make_moe_train_step(mesh, cfg, TrainConfig(bucket_bytes=1 << 30))
    return step.lower(state_sds, tok, tok).as_text()


def _lower_pipeline_step() -> str:
    import jax
    import jax.numpy as jnp

    from ..parallel.pipeline import (
        init_pipeline_train_state,
        make_mesh_4d,
        make_pipeline_train_step,
    )
    from ..parallel.train import TrainConfig

    cfg = _small_model_cfg()
    mesh = make_mesh_4d(8, (1, 2, 2, 2))
    state_sds = jax.eval_shape(
        lambda k: init_pipeline_train_state(k, cfg), jax.random.PRNGKey(0)
    )
    tok = jax.ShapeDtypeStruct((4, 32), jnp.int32)
    step = make_pipeline_train_step(
        mesh, cfg, train_cfg=TrainConfig(bucket_bytes=1 << 30),
        n_microbatches=2,
    )
    return step.lower(state_sds, tok, tok).as_text()


def lower_entrypoints(full: bool = True) -> list[tuple[str, str, HloBudget]]:
    """(name, stablehlo text, budget) for every linted entrypoint.

    ``full=False`` lowers only the allreduce-family entrypoints (no model
    steps) — the fast subset ``bench.py``'s tripwire uses.
    """
    _require_devices(8)
    rows: list[tuple[str, str, HloBudget]] = [
        (
            "tree_allreduce_sum_4x2_f32",
            _lower_allreduce((4, 2)),
            HloBudget(
                reduce_scatter=2, all_gather=2, all_reduce=0,
                collective_permute=0,
                collective_dtypes=("f32",),
                note="one grouped rs+ag pair per stage",
            ),
        ),
        (
            "tree_allreduce_sum_4x2_bf16",
            _lower_allreduce((4, 2), dtype="bfloat16"),
            HloBudget(
                reduce_scatter=2, all_gather=2, all_reduce=0,
                collective_permute=0,
                collective_dtypes=("bf16",),
                note="bf16 path must not upcast collectives to f32",
            ),
        ),
        (
            "tree_allreduce_bor_4x2_i32",
            _lower_allreduce((4, 2), op="bor"),
            HloBudget(
                reduce_scatter=0, all_gather=2, all_reduce=0,
                collective_permute=2,
                note="non-sum stages are the ppermute ring, one per stage",
            ),
        ),
        (
            "tree_allreduce_sum_4x2_chunks4",
            _lower_allreduce((4, 2), chunks=4),
            HloBudget(
                reduce_scatter=8, all_gather=8, all_reduce=0,
                collective_permute=0,
                note="chunks=C multiplies scheduled collectives by exactly C",
            ),
        ),
        (
            "ring_allreduce_f32",
            _lower_ring(),
            HloBudget(
                reduce_scatter=0, all_gather=0, all_reduce=0,
                collective_permute=2,
                note="two fori_loop neighbor permutes, O(1) in N",
            ),
        ),
        (
            "compressed_allreduce_bf16_4x2",
            _lower_compressed_allreduce((4, 2), "bf16"),
            HloBudget(
                reduce_scatter=2, all_gather=2, all_reduce=0,
                collective_permute=0,
                collective_dtypes=("bf16",),
                require_wire_dtype="bf16",
                note="bf16 codec: the scheduled collectives must carry "
                     "bf16 on the wire, never a silent f32 upcast",
            ),
        ),
        (
            "compressed_allreduce_int8_4x2",
            _lower_compressed_allreduce((4, 2), "int8"),
            HloBudget(
                reduce_scatter=0, all_gather=4, all_reduce=0,
                collective_permute=0, all_to_all=4,
                collective_dtypes=("i8", "f32"),
                require_wire_dtype="i8",
                note="int8 codec: per-stage grouped all_to_all of (i8 "
                     "payload, f32 scales) + encoded-forwarding gathers; "
                     "the bulk payload must be i8 on the wire",
            ),
        ),
        (
            "tree_allreduce_donated",
            _lower_allreduce((4, 2), donate=True),
            HloBudget(
                reduce_scatter=2, all_gather=2,
                require_donation=True,
                note="donated input must lower with jax.buffer_donor",
            ),
        ),
        (
            "reduce_scatter_f32_4x2",
            _lower_split_collective((4, 2), "rs"),
            HloBudget(
                reduce_scatter=2, all_gather=0, all_reduce=0,
                collective_permute=0,
                collective_dtypes=("f32",),
                note="phase 1 alone: one grouped reduce-scatter per stage, "
                     "NO allgather — the split seam (PR 7)",
            ),
        ),
        (
            "all_gather_f32_4x2",
            _lower_split_collective((4, 2), "ag"),
            HloBudget(
                reduce_scatter=0, all_gather=2, all_reduce=0,
                collective_permute=0,
                collective_dtypes=("f32",),
                note="phase 2 alone: one grouped allgather per stage, NO "
                     "reduce-scatter",
            ),
        ),
        (
            "reduce_scatter_int8_4x2",
            _lower_split_collective((4, 2), "rs", codec="int8"),
            HloBudget(
                reduce_scatter=0, all_gather=0, all_reduce=0,
                collective_permute=0, all_to_all=4,
                collective_dtypes=("i8", "f32"),
                require_wire_dtype="i8",
                note="compressed phase 1: per-stage grouped (i8 payload, "
                     "f32 scales) all_to_alls; int8 stays i8 on the wire",
            ),
        ),
    ]
    if not full:
        return rows

    native = collective_counts(_lower_native_train_step())
    expected_sync, n_synced_leaves = bucketed_sync_budget()
    bucketed_ir = _lower_train_step(bucket_bytes=1 << 30)
    rows.append(
        (
            "train_step_bucketed",
            bucketed_ir,
            HloBudget(
                reduce_scatter=native["reduce_scatter"] + expected_sync,
                all_gather=native["all_gather"] + expected_sync,
                # fused tails: at most one dense collective per bucket-axis
                # on top of the step's own psums
                all_reduce=native["all_reduce"] + expected_sync,
                exact=False,
                note=(
                    f"sync collectives scale with buckets "
                    f"({expected_sync} bucket-axes), never with the "
                    f"{n_synced_leaves} gradient leaves"
                ),
            ),
        )
    )
    rows.append(
        (
            "moe_train_step_bucketed",
            _lower_moe_step(),
            HloBudget(
                # MoE earns its all_to_alls (dispatch+combine per MoE layer,
                # forward and backward) but they must stay bounded and
                # static: 1 MoE layer x 2 exchanges x (fwd + bwd) = 4
                all_to_all=4,
                exact=False,
                note="MoE dispatch/combine only; no per-leaf sync blowup",
            ),
        )
    )
    rows.append(
        (
            "pipeline_train_step_bucketed",
            _lower_pipeline_step(),
            HloBudget(
                all_to_all=0,
                note="GPipe moves activations on collective_permute only",
            ),
        )
    )

    # readiness-ordered overlap (ISSUE 6): the overlapped step and its
    # full-backward-barrier twin carry the SAME collective-count budget —
    # overlap must relocate collectives, never add or drop them — and the
    # overlapped one must actually interleave them with backward compute
    n_buckets, n_segments = overlap_sync_budget()
    overlap_budget = dict(
        reduce_scatter=n_buckets, all_gather=n_buckets,
        collective_permute=0,
        note=(
            f"sync collectives scale with the {n_buckets} planned overlap "
            f"buckets over {n_segments} readiness segments; counts must "
            f"equal the serialized twin's"
        ),
    )
    rows.append(
        (
            "train_step_overlapped",
            _lower_overlap_train_step(serialize=False),
            HloBudget(require_compute_after_collective=True, **overlap_budget),
        )
    )
    rows.append(
        (
            "train_step_overlap_serialized",
            _lower_overlap_train_step(serialize=True),
            HloBudget(**overlap_budget),
        )
    )
    n_buckets_i8, _ = overlap_sync_budget("int8")
    rows.append(
        (
            "train_step_overlapped_int8",
            _lower_overlap_train_step(codec="int8"),
            HloBudget(
                reduce_scatter=0, all_to_all=2 * n_buckets_i8,
                collective_dtypes=None,
                require_wire_dtype="i8",
                require_compute_after_collective=True,
                note=(
                    "overlapped int8 sync keeps the wire dtype: grouped "
                    "(i8 payload, f32 scales) all_to_alls fired "
                    "mid-backward, never a decoded f32 collective"
                ),
            ),
        )
    )

    # ZeRO-1 sharded entrypoints (PR 7): one grad reduce-scatter + one
    # param all-gather per bucket, and the gather must FOLLOW the
    # optimizer update — a step that gathers grads instead is the
    # replicated schedule in disguise (the shard-regather mutant)
    nz, nz_leaves = sharded_sync_budget()
    rows.append(
        (
            "train_step_sharded",
            _lower_sharded_train_step(),
            HloBudget(
                reduce_scatter=nz, all_gather=nz, collective_permute=0,
                require_gather_after_update=True,
                note=(
                    f"sharded sync: {nz} buckets over {nz_leaves} synced "
                    f"leaves — one grad rs + one PARAM ag per bucket, "
                    f"gather after the shard update"
                ),
            ),
        )
    )
    nz_i8, _ = sharded_sync_budget("int8")
    rows.append(
        (
            "train_step_sharded_int8",
            _lower_sharded_train_step(codec="int8"),
            HloBudget(
                reduce_scatter=0, all_gather=2 * nz_i8,
                all_to_all=2 * nz_i8, collective_permute=0,
                require_wire_dtype="i8",
                require_gather_after_update=True,
                note=(
                    "sharded int8: grads ride grouped (i8, scales) "
                    "all_to_alls, params ride encoded-forwarding gathers "
                    "— int8 stays i8 on the reduce-scatter wire"
                ),
            ),
        )
    )
    return rows


def run_hlo_lint(full: bool = True) -> tuple[list[Violation], dict]:
    """Lint every entrypoint; returns (violations, per-entrypoint detail)."""
    violations: list[Violation] = []
    detail: dict = {}
    for name, ir, budget in lower_entrypoints(full=full):
        vs = lint_ir(name, ir, budget)
        violations += vs
        detail[name] = {
            "counts": collective_counts(ir),
            "violations": len(vs),
            "note": budget.note,
        }
    return violations, detail


# ------------------------------------------------- mutation entrypoints


def lower_leaf_unrolled_train_step() -> tuple[str, HloBudget]:
    """The 'leaf-unrolled collectives' corruption: the per-leaf train step
    (``bucket_bytes=0``) lowered against the *bucketed* budget.  The
    mutation self-test asserts the linter rejects it — this is the
    regression the bucketing tentpole exists to prevent."""
    native = collective_counts(_lower_native_train_step())
    expected_sync, n_synced = bucketed_sync_budget()
    ir = _lower_train_step(bucket_bytes=0)
    budget = HloBudget(
        reduce_scatter=native["reduce_scatter"] + expected_sync,
        all_gather=native["all_gather"] + expected_sync,
        all_reduce=native["all_reduce"] + expected_sync,
        exact=False,
        note=f"bucketed budget applied to a per-leaf ({n_synced}-leaf) sync",
    )
    return ir, budget


def lower_overlap_serialized_train_step() -> tuple[str, HloBudget]:
    """The 'overlap-serialization' corruption: the overlapped train step
    with the full-backward barrier reintroduced before the first
    collective (``make_train_step(serialize_overlap=True)``) lowered
    against the *overlapped* budget.  Numerically bitwise-identical to
    the overlapped step — only the linter's program-order check can see
    that every collective now trails the backward, un-hiding all the wire
    time the overlap tentpole exists to hide."""
    _require_devices(8)
    n_buckets, n_segments = overlap_sync_budget()
    ir = _lower_overlap_train_step(serialize=True)
    budget = HloBudget(
        reduce_scatter=n_buckets, all_gather=n_buckets,
        collective_permute=0,
        require_compute_after_collective=True,
        note=f"overlapped budget applied to the {n_segments}-segment "
             f"barrier twin",
    )
    return ir, budget


def lower_shard_regather_train_step() -> tuple[str, HloBudget]:
    """The 'shard-regather' corruption: a "sharded" step that secretly
    all-gathers GRADIENTS instead of updated parameters — which is
    exactly the replicated step over the same flat(8) bucket plan
    (identical collective counts: one rs + one ag per bucket;
    bitwise-identical f32 numerics; optimizer state silently fully
    replicated again).  Only the program-ORDER check can see it: every
    all_gather precedes the optimizer sqrt."""
    _require_devices(8)
    nz, nz_leaves = sharded_sync_budget()
    ir = _lower_sharded_train_step(regather=True)
    budget = HloBudget(
        reduce_scatter=nz, all_gather=nz, collective_permute=0,
        require_gather_after_update=True,
        note=f"sharded budget applied to the grad-regathering "
             f"({nz_leaves}-leaf replicated) step",
    )
    return ir, budget


def lower_codec_upcast_allreduce() -> tuple[str, HloBudget]:
    """The 'codec-upcast' corruption: an int8-codec entrypoint refactored
    to decode *before* the collective — quantized numerics (so every
    numeric test still passes), f32 on the wire (4x the bytes).  The
    linter must flag the missing i8 wire dtype."""
    _require_devices(8)
    ir = _lower_compressed_allreduce((4, 2), "int8", upcast=True)
    budget = HloBudget(
        reduce_scatter=0, all_gather=4, all_reduce=0,
        collective_permute=0, all_to_all=4,
        collective_dtypes=("i8", "f32"),
        require_wire_dtype="i8",
        note="int8-codec budget applied to a decode-before-wire program",
    )
    return ir, budget


def lower_dtype_drifted_allreduce() -> tuple[str, HloBudget]:
    """The 'dtype drift' corruption: a bf16 allreduce that silently
    upcasts to f32 around the collective — numerically near-identical,
    2x the wire bytes.  The linter must flag the f32 collectives."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..parallel import tree_allreduce
    from ..parallel.mesh import flat_mesh

    _require_devices(8)
    mesh = flat_mesh(8, "ft")

    def f(row):
        drifted = tree_allreduce(
            row[0].astype(jnp.float32), "ft", (4, 2)
        )
        return drifted.astype(jnp.bfloat16)[None]

    fn = jax.shard_map(f, mesh=mesh, in_specs=P("ft"), out_specs=P("ft"))
    ir = jax.jit(fn).lower(jnp.zeros((8, 64), jnp.bfloat16)).as_text()
    budget = HloBudget(
        reduce_scatter=2, all_gather=2,
        collective_dtypes=("bf16",),
        note="bf16 entrypoint: collectives must stay bf16",
    )
    return ir, budget
