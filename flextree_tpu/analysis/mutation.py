"""Mutation self-test: the analyzer must distrust itself.

A static checker that reports zero violations is indistinguishable from a
static checker that checks nothing.  This harness seeds one corruption
per known bug class into the artifact the corresponding layer consumes —
the message *program* for layer 1, the lowered *StableHLO* for layer 2,
*source text* for layer 3 — and asserts the layer reports the expected
violation kind.  Any mutation that sails through means the analyzer lost
a check, and the suite (CLI, CI gate, tier-1 test) fails.

Classes (the acceptance matrix of ISSUE 3):

====================  ======  ==========================================
mutation              layer   expected violation kind
====================  ======  ==========================================
peer-swap             1       ``asymmetric-match`` (and ``deadlock``)
dropped-block         1       ``dropped-block``
double-count          1       ``double-count``
chunk-overlap         1       ``chunk-overlap``
crossed-order         1       ``deadlock`` (a real wait-for cycle)
watchdog-removal      1       ``unbounded-wait`` (lost recv deadline)
swing-stride          1       ``dropped-block`` (corrupted swing peers)
genblock-truncate     1       ``dropped-block`` (truncated block-map)
leaf-unrolled         2       ``budget``
dtype-drift           2       ``dtype-drift``
codec-upcast          2       ``codec-upcast``
overlap-serialization 2       ``overlap-serialization``
shard-regather        2       ``shard-regather`` (grads regathered)
ir-divergence         2       ``ir-equivalence`` (executable != IR)
wall-clock            3       ``wall-clock``
host-rng              3       ``rng``
traced-branch         3       ``traced-branch``
missing-static        3       ``static-argnames``
====================  ======  ==========================================

Extended by ISSUE 18 with the control-plane classes — layer 4 seeds a
semantic corruption into a protocol transition *model* (the checker must
prove the resulting violation REACHABLE, witness trace included), layer
5 seeds a discipline corruption into source text:

======================  ======  ========================================
commit-without-all-acks 4       ``commit-quorum`` (commit before quorum)
double-grant            4       ``double-grant`` (publish skips the
                                one-holder-per-chip validation)
serve-ack-before-drain  4       ``dual-holder-use`` (serving acks a
                                revocation with requests still in
                                flight — the grant hands training chips
                                serving is actively using)
replay-miss             4       ``completed-rid-reexecuted`` (idempotency
                                store misses on replay)
migration-skip-release  4       ``migration-block-leak`` (failed KV
                                handoffs skip ``release_exported`` —
                                every abort leaks the prefill-side
                                blocks)
lock-order-inversion    5       ``lock-order`` (ABBA cycle)
dropped-guard           5       ``guard`` (guarded field written bare)
signal-path-blocking    5       ``signal-blocking`` (handler reaches a
                                blocking lock acquire)
======================  ======  ========================================
"""

from __future__ import annotations

import dataclasses

from ..schedule import ir as sir
from ..schedule.stages import Topology
from .schedule_check import (
    RECV,
    SEND,
    Half,
    PostSet,
    build_program,
    check_ir,
    check_program,
)

__all__ = ["MUTATIONS", "run_mutation_selftest"]


# ----------------------------------------------------- layer 1 mutations


def _mutate_peer_swap():
    """Redirect one send half to the wrong peer — the receiver never hears
    from the true sender."""
    prog = build_program(Topology(8, (4, 2)), count=64)
    ps = prog.posts[0][0]
    for i, h in enumerate(ps.halves):
        if h.kind == SEND:
            wrong = (h.peer + 1) % 8 or 2
            ps.halves[i] = Half(SEND, wrong, h.blocks)
            break
    return check_program(prog)


def _mutate_dropped_block():
    """Symmetrically drop one block from a matched send/recv pair — both
    sides agree, so only conservation can catch it."""
    prog = build_program(Topology(8, (4, 2)), count=64)
    ps = prog.posts[0][0]
    send = next(h for h in ps.halves if h.kind == SEND and len(h.blocks) > 1)
    victim = send.blocks[0]

    def drop(half):
        return Half(half.kind, half.peer, tuple(b for b in half.blocks if b != victim))

    ps.halves[ps.halves.index(send)] = drop(send)
    peer_ps = prog.posts[send.peer][0]
    for i, h in enumerate(peer_ps.halves):
        if h.kind == RECV and h.peer == 0 and victim in h.blocks:
            peer_ps.halves[i] = drop(h)
    return check_program(prog)


def _mutate_double_count():
    """Send the same block to two peers — it gets reduced twice."""
    prog = build_program(Topology(8, (4, 2)), count=64)
    ps = prog.posts[0][0]
    sends = [h for h in ps.halves if h.kind == SEND]
    dup_block = sends[0].blocks[0]
    i = ps.halves.index(sends[1])
    ps.halves[i] = Half(SEND, sends[1].peer, sends[1].blocks + (dup_block,))
    # keep the pair symmetric so only conservation fires
    peer_ps = prog.posts[sends[1].peer][0]
    for j, h in enumerate(peer_ps.halves):
        if h.kind == RECV and h.peer == 0:
            peer_ps.halves[j] = Half(RECV, 0, h.blocks + (dup_block,))
    return check_program(prog)


def _mutate_chunk_overlap():
    """Shift a chunk's buffer span onto its neighbor — the interleaved
    phase-2/phase-1 windows would alias."""
    prog = build_program(Topology(8, (4, 2)), count=128, chunks=2)
    off, size = prog.chunk_spans[1]
    prog.chunk_spans[1] = (off - 8, size)
    return check_program(prog)


def _mutate_watchdog_removal():
    """Strip the watchdog contract from an otherwise-clean program — the
    static twin of deleting the step deadline from the runtime: a schedule
    that can block forever on a dead peer must be rejected even though its
    message pattern is perfectly correct (ISSUE 4's runtime-supervision
    invariant: a timeout-wrapped rendezvous cannot deadlock-forever)."""
    prog = build_program(Topology(8, (4, 2)), count=64)
    prog.watchdogged = False
    return check_program(prog)


def _mutate_crossed_order():
    """Serialize one stage's exchanges per rank in rotated (crossed) order
    — a genuine wait-for cycle under blocking rendezvous."""
    topo = Topology(3, (3,))
    prog = build_program(topo, count=9)

    def serialize(rank, peer_order):
        ps = prog.posts[rank][0]
        by_peer: dict[int, list[Half]] = {}
        for h in ps.halves:
            by_peer.setdefault(h.peer, []).append(h)
        prog.posts[rank][0:1] = [
            PostSet(rank, by_peer[p], ps.chunk, ps.phase, ps.stage)
            for p in peer_order
        ]

    serialize(0, [2, 1])
    serialize(1, [0, 2])
    serialize(2, [1, 0])
    return check_program(prog)


def _mutate_swing_stride():
    """Corrupt the swing peer stride CONSISTENTLY (every stage-1 transfer
    redirected two ranks over — both ends agree, so peer symmetry holds
    and only conservation can see that block partials now land on ranks
    that never fold them)."""
    prog = sir.swing_ir(8, count=64)
    st = prog.stages[1]
    bad_xfers = tuple(
        dataclasses.replace(x, dst=(x.dst + 2) % 8) for x in st.xfers
    )
    bad = dataclasses.replace(
        prog,
        stages=prog.stages[:1]
        + (dataclasses.replace(st, xfers=bad_xfers),)
        + prog.stages[2:],
    )
    return check_ir(bad)


def _mutate_genblock_truncate():
    """Truncate the generalized family's block-map symmetrically (drop the
    last block of every stage-0 transfer on BOTH halves) — the residue
    chains stop partitioning the owned set and those blocks' partial sums
    are silently lost."""
    prog = sir.generalized_ir((4, 2), 1, count=64)
    st = prog.stages[0]
    bad_xfers = tuple(
        dataclasses.replace(x, blocks=x.blocks[:-1]) for x in st.xfers
    )
    bad = dataclasses.replace(
        prog,
        stages=(dataclasses.replace(st, xfers=bad_xfers),) + prog.stages[1:],
    )
    return check_ir(bad)


# ----------------------------------------------------- layer 2 mutations


def _mutate_leaf_unrolled():
    from .hlo_lint import lint_ir, lower_leaf_unrolled_train_step

    ir, budget = lower_leaf_unrolled_train_step()
    return lint_ir("mutated:leaf_unrolled_train_step", ir, budget)


def _mutate_dtype_drift():
    from .hlo_lint import lint_ir, lower_dtype_drifted_allreduce

    ir, budget = lower_dtype_drifted_allreduce()
    return lint_ir("mutated:dtype_drifted_allreduce", ir, budget)


def _mutate_codec_upcast():
    from .hlo_lint import lint_ir, lower_codec_upcast_allreduce

    ir, budget = lower_codec_upcast_allreduce()
    return lint_ir("mutated:codec_upcast_allreduce", ir, budget)


def _mutate_overlap_serialization():
    from .hlo_lint import lint_ir, lower_overlap_serialized_train_step

    ir, budget = lower_overlap_serialized_train_step()
    return lint_ir("mutated:overlap_serialized_train_step", ir, budget)


def _mutate_shard_regather():
    from .hlo_lint import lint_ir, lower_shard_regather_train_step

    ir, budget = lower_shard_regather_train_step()
    return lint_ir("mutated:shard_regather_train_step", ir, budget)


def _mutate_ir_divergence():
    """IR/executable divergence: a lowered collective checked against a
    DIFFERENT IR's stage list — bitwise-exact numerics on both sides, so
    only the ``ir_equivalence`` pass can see the certified object is not
    the object that runs."""
    from .ir_equivalence import lower_ir_divergent

    return lower_ir_divergent()


# ----------------------------------------------------- layer 3 mutations

_HYGIENE_MUTANT = '''
import time, random
import numpy as np
import jax


def make_step(cfg):
    def step(x, topo):
        t = time.perf_counter()
        noise = np.random.standard_normal(4)
        jitter = random.random()
        if x > 0:
            x = x + noise.sum() * jitter * t
        return x
    return jax.jit(step)
'''


def _mutate_hygiene(kind):
    from .jit_hygiene import scan_source

    def run():
        vs, _ = scan_source(_HYGIENE_MUTANT, "mutated_source.py")
        return vs

    return run


# ----------------------------------------------------- layer 4 mutations
#
# Each seeds one semantic corruption into a protocol transition model and
# runs the exhaustive explorer over it: "caught" means the expected
# violation kind is REACHABLE (the checker carries a witness trace), not
# merely that some assertion somewhere tripped.


def _mutate_commit_without_all_acks():
    from ..runtime.coord_model import CoordModel
    from .protocol_check import run_protocol_check

    vs, _ = run_protocol_check(
        models=[CoordModel(3, mutation="commit_without_all_acks")]
    )
    return vs


def _mutate_double_grant():
    from ..runtime.lease_model import LeaseModel
    from .protocol_check import run_protocol_check

    vs, _ = run_protocol_check(models=[LeaseModel(mutation="double_grant")])
    return vs


def _mutate_serve_ack_before_drain():
    from ..runtime.lease_model import LeaseModel
    from .protocol_check import run_protocol_check

    vs, _ = run_protocol_check(
        models=[LeaseModel(mutation="serve_ack_before_drain")]
    )
    return vs


def _mutate_replay_miss():
    from ..serving.rpc_model import RpcModel
    from .protocol_check import run_protocol_check

    vs, _ = run_protocol_check(models=[RpcModel(mutation="replay_miss")])
    return vs


def _mutate_skip_release():
    from ..serving.rpc_model import MigrationModel
    from .protocol_check import run_protocol_check

    vs, _ = run_protocol_check(
        models=[MigrationModel(mutation="skip_release")]
    )
    return vs


# ----------------------------------------------------- layer 5 mutations

_LOCK_ORDER_MUTANT = '''
import threading


class Broker:
    def __init__(self):
        self._xlock = threading.Lock()
        self._ylock = threading.Lock()

    def forward(self):
        with self._xlock:
            with self._ylock:
                pass

    def backward(self):
        with self._ylock:
            with self._xlock:
                pass
'''

_DROPPED_GUARD_MUTANT = '''
import threading


class Tally:
    def __init__(self):
        self.counts = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        while True:
            self.bump("beat")

    def bump(self, key):
        self.counts[key] = self.counts.get(key, 0) + 1
'''

_SIGNAL_BLOCKING_MUTANT = '''
import signal
import threading


class Dumper:
    def __init__(self):
        self._lock = threading.Lock()
        self._ring = []

    def dump(self):
        with self._lock:
            return list(self._ring)

    def install(self):
        signal.signal(signal.SIGTERM, self._on_signal)

    def _on_signal(self, signum, frame):
        self.dump()
'''


def _mutate_concurrency(src):
    def run():
        from .concurrency_lint import scan_source

        vs, _ = scan_source(src, "mutated_source.py")
        return vs

    return run


# ------------------------------------------------------------- harness

#: name -> (expected_kind, expected_layer, thunk)
MUTATIONS = {
    "peer-swap": ("asymmetric-match", "schedule", _mutate_peer_swap),
    "dropped-block": ("dropped-block", "schedule", _mutate_dropped_block),
    "double-count": ("double-count", "schedule", _mutate_double_count),
    "chunk-overlap": ("chunk-overlap", "schedule", _mutate_chunk_overlap),
    "crossed-order": ("deadlock", "schedule", _mutate_crossed_order),
    "watchdog-removal": ("unbounded-wait", "schedule", _mutate_watchdog_removal),
    "swing-stride": ("dropped-block", "schedule", _mutate_swing_stride),
    "genblock-truncate": ("dropped-block", "schedule", _mutate_genblock_truncate),
    "leaf-unrolled": ("budget", "hlo", _mutate_leaf_unrolled),
    "dtype-drift": ("dtype-drift", "hlo", _mutate_dtype_drift),
    "codec-upcast": ("codec-upcast", "hlo", _mutate_codec_upcast),
    "overlap-serialization": (
        "overlap-serialization", "hlo", _mutate_overlap_serialization,
    ),
    "shard-regather": ("shard-regather", "hlo", _mutate_shard_regather),
    "ir-divergence": ("ir-equivalence", "hlo", _mutate_ir_divergence),
    "wall-clock": ("wall-clock", "jit", _mutate_hygiene("wall-clock")),
    "host-rng": ("rng", "jit", _mutate_hygiene("rng")),
    "traced-branch": ("traced-branch", "jit", _mutate_hygiene("traced-branch")),
    "missing-static": ("static-argnames", "jit", _mutate_hygiene("static-argnames")),
    "commit-without-all-acks": (
        "commit-quorum", "protocol", _mutate_commit_without_all_acks,
    ),
    "double-grant": ("double-grant", "protocol", _mutate_double_grant),
    "serve-ack-before-drain": (
        "dual-holder-use", "protocol", _mutate_serve_ack_before_drain,
    ),
    "replay-miss": (
        "completed-rid-reexecuted", "protocol", _mutate_replay_miss,
    ),
    "migration-skip-release": (
        "migration-block-leak", "protocol", _mutate_skip_release,
    ),
    "lock-order-inversion": (
        "lock-order", "concurrency", _mutate_concurrency(_LOCK_ORDER_MUTANT),
    ),
    "dropped-guard": (
        "guard", "concurrency", _mutate_concurrency(_DROPPED_GUARD_MUTANT),
    ),
    "signal-path-blocking": (
        "signal-blocking", "concurrency",
        _mutate_concurrency(_SIGNAL_BLOCKING_MUTANT),
    ),
}


def run_mutation_selftest(include_hlo: bool = True) -> dict:
    """Run every seeded corruption; returns a per-class report.

    ``caught`` is True iff the expected (layer, kind) appears among the
    violations the mutated artifact produced.  ``all_caught`` is the gate
    the CLI and CI fail on.  ``include_hlo=False`` skips the two
    lowering-based mutations (for JAX-less or device-less hosts).
    """
    report: dict = {"classes": {}, "all_caught": True}
    for mut_name, (kind, layer, thunk) in MUTATIONS.items():
        if not include_hlo and layer == "hlo":
            continue
        violations = thunk()
        caught = any(v.layer == layer and v.kind == kind for v in violations)
        report["classes"][mut_name] = {
            "expected": f"{layer}/{kind}",
            "caught": caught,
            "violations_raised": len(violations),
        }
        if not caught:
            report["all_caught"] = False
    return report
