"""``ir_equivalence`` pass: certify that what XLA will run is what the IR
declares.

The schedule IR (``schedule/ir.py``) is the verified object — the model
checker proves peer symmetry / deadlock-freedom / conservation on the
stage list, and ``compile_ir`` refuses a non-canonical program.  This
pass closes the remaining gap: it lowers the COMPILED collective to
StableHLO and checks the emitted collective op sequence against the IR
stage list — count, kind, group width, permute pair count, and (for
unrolled stages) operand wire bytes per op, extending ``hlo_lint``'s
wire-byte parsing to a per-op positional contract.

What each stage kind must lower to (``parallel/ir_lower.py``):

- grouped sum ``rs``  -> one ``stablehlo.reduce_scatter`` whose
  replica-group width equals the stage width;
- grouped ``ag``      -> one ``stablehlo.all_gather`` (same width rule);
- pair stages         -> one ``stablehlo.collective_permute`` per send
  slot, with exactly ``len(stage.xfers)`` source-target pairs;
- ring-step stages    -> ROLLED: one permute per ``fori_loop`` (two for
  the full ring), matched by kind only (trip counts are invisible to a
  text scan — the wire-byte caveat of ``collective_wire_bytes``);
- lonely/non-sum grouped stages -> one rolled permute per stage.

A divergence ("the executable does something the IR does not say") is
violation kind ``ir-equivalence`` — and the mutation class
``ir-divergence`` asserts this pass actually catches one.
"""

from __future__ import annotations

import re

from ..schedule import ir as sir
from .base import Violation
from .hlo_lint import _COLL_RE, _DTYPE_BYTES, _GRP_RE, _SIG_RE, _TENSOR_RE

__all__ = [
    "expected_hlo_sequence",
    "actual_hlo_sequence",
    "compare_sequences",
    "ir_equivalence_entrypoints",
    "run_ir_equivalence",
    "lower_ir_divergent",
]

_PAIRS_RE = re.compile(
    r"source_target_pairs\s*=\s*dense<[^>]*>\s*:\s*tensor<(\d+)x2xi64>"
)


def _chunk_sizes(total: int, n: int, chunks: int) -> list[int]:
    blocks = total // n
    c = max(1, min(chunks, blocks))
    base, rem = divmod(blocks, c)
    return [(base + (1 if i < rem else 0)) * n for i in range(c)]


def expected_hlo_sequence(
    prog: "sir.IRProgram", elems_per_rank: int, itemsize: int = 4,
    op: str = "sum",
) -> list[dict]:
    """The collective op sequence the lowering of ``prog`` must emit, in
    trace order, for a flat ``elems_per_rank``-element per-rank buffer.
    Rows are dicts with ``op`` and optionally ``width`` (replica-group
    width), ``pairs`` (permute pair count), ``bytes`` (operand bytes) and
    ``rolled`` (kind-only match)."""
    m = prog.scheduled
    head = (elems_per_rank // m) * m
    tile = head // m if m else 0
    rows: list[dict] = []

    if prog.family == "ring":
        rows.append({"op": "collective_permute", "rolled": True})
        rows.append({"op": "collective_permute", "rolled": True})
    elif prog.family == "tree" and op == "sum":
        sizes = _chunk_sizes(head, m, prog.chunks) if head else []
        cur = {c: s for c, s in enumerate(sizes)}
        for st in prog.stages:
            w = prog.topo.widths[st.index]
            size = cur.get(st.chunk, 0)
            if st.phase == "rs":
                rows.append(
                    {"op": "reduce_scatter", "width": w, "bytes": size * itemsize}
                )
                cur[st.chunk] = size // w
            else:
                rows.append(
                    {"op": "all_gather", "width": w, "bytes": size * itemsize}
                )
                cur[st.chunk] = size * w
    elif prog.family in ("tree", "lonely"):
        # non-sum trees and lonely prefix trees ride the ppermute-ring
        # helpers: one rolled permute per grouped stage; fold/restore
        # hops are unrolled whole-buffer permutes
        for st in prog.stages:
            if st.phase in ("fold", "restore"):
                rows.append(
                    {
                        "op": "collective_permute",
                        "pairs": len(st.xfers),
                        "bytes": head * itemsize,
                    }
                )
            else:
                rows.append({"op": "collective_permute", "rolled": True})
    else:  # swing / generalized: unrolled pair stages
        for st in prog.stages:
            if st.phase in ("fold", "restore"):
                rows.append(
                    {
                        "op": "collective_permute",
                        "pairs": len(st.xfers),
                        "bytes": head * itemsize,
                    }
                )
                continue
            per_src: dict[int, int] = {}
            for x in st.xfers:
                per_src[x.src] = per_src.get(x.src, 0) + 1
            n_slots = max(per_src.values())
            k = len(st.xfers[0].blocks)
            for j in range(n_slots):
                pairs = sum(1 for v in per_src.values() if v > j)
                rows.append(
                    {
                        "op": "collective_permute",
                        "pairs": pairs,
                        "bytes": k * tile * itemsize,
                    }
                )
    if head < elems_per_rank:
        rows.append({"op": "all_reduce"})  # the dense sub-N tail
    return rows


def actual_hlo_sequence(ir_text: str) -> list[dict]:
    """Parse the collective ops out of lowered StableHLO, in emission
    order, with replica-group width / permute pair count / operand bytes
    — the per-op form of ``hlo_lint.collective_wire_bytes``'s scan."""
    rows: list[dict] = []
    for mt in _COLL_RE.finditer(ir_text):
        op = mt.group(1)
        window = ir_text[mt.start() : mt.start() + 8000]
        sig = _SIG_RE.search(window)
        row: dict = {"op": op}
        if sig:
            grp = _GRP_RE.search(window[: sig.end()])
            if grp:
                row["width"] = int(grp.group(1))
            pr = _PAIRS_RE.search(window[: sig.end()])
            if pr:
                row["pairs"] = int(pr.group(1))
            nbytes = 0
            for dims, ty in _TENSOR_RE.findall(sig.group(1)):
                n = 1
                for d in dims.split("x"):
                    if d:
                        n *= int(d)
                nbytes += n * _DTYPE_BYTES.get(ty, 4)
            row["bytes"] = nbytes
        rows.append(row)
    return rows


def compare_sequences(
    name: str, expected: list[dict], actual: list[dict]
) -> list[Violation]:
    """Positional comparison; every mismatch names the op index and the
    IR-side expectation so the drift is localizable.

    Programs with ROLLED stages (ring, lonely/non-sum trees) mix inline
    ops with ``fori_loop`` bodies, which StableHLO text outlines into
    separate functions — text position no longer equals trace order
    across the boundary, so those programs fall back to a multiset
    match: every unrolled IR stage must claim a distinct emitted op
    (kind + pairs + bytes), and the leftover ops must be exactly the
    rolled permutes."""
    if any(e.get("rolled") for e in expected):
        return _compare_multiset(name, expected, actual)
    out: list[Violation] = []
    if len(expected) != len(actual):
        kinds_e = [r["op"] for r in expected]
        kinds_a = [r["op"] for r in actual]
        out.append(
            Violation(
                "hlo",
                "ir-equivalence",
                name,
                f"IR declares {len(expected)} collectives "
                f"({kinds_e}), the lowered program emits {len(actual)} "
                f"({kinds_a}): the executable diverged from the IR stage "
                f"list",
            )
        )
        return out
    for i, (e, a) in enumerate(zip(expected, actual)):
        if e["op"] != a["op"]:
            out.append(
                Violation(
                    "hlo", "ir-equivalence", name,
                    f"collective #{i}: IR stage lowers to {e['op']}, "
                    f"program emits {a['op']}",
                )
            )
            continue
        if e.get("rolled"):
            continue  # kind-only match (loop trip counts invisible)
        for key, what in (
            ("width", "replica-group width"),
            ("pairs", "source-target pair count"),
            ("bytes", "operand wire bytes"),
        ):
            if key in e and key in a and e[key] != a[key]:
                out.append(
                    Violation(
                        "hlo", "ir-equivalence", name,
                        f"collective #{i} ({e['op']}): IR declares {what} "
                        f"{e[key]}, program emits {a[key]}",
                    )
                )
    return out


def _compare_multiset(
    name: str, expected: list[dict], actual: list[dict]
) -> list[Violation]:
    out: list[Violation] = []
    if len(expected) != len(actual):
        out.append(
            Violation(
                "hlo", "ir-equivalence", name,
                f"IR declares {len(expected)} collectives, the lowered "
                f"program emits {len(actual)}: the executable diverged "
                f"from the IR stage list",
            )
        )
        return out
    remaining = list(actual)
    rolled = 0
    for e in expected:
        if e.get("rolled"):
            rolled += 1
            continue
        hit = next(
            (
                i
                for i, a in enumerate(remaining)
                if a["op"] == e["op"]
                and all(a.get(k) == e[k] for k in ("width", "pairs", "bytes") if k in e)
            ),
            None,
        )
        if hit is None:
            out.append(
                Violation(
                    "hlo", "ir-equivalence", name,
                    f"no emitted collective matches IR stage row {e} "
                    f"(remaining ops: {remaining})",
                )
            )
        else:
            remaining.pop(hit)
    bad = [a for a in remaining if a["op"] != "collective_permute"]
    if len(remaining) != rolled or bad:
        out.append(
            Violation(
                "hlo", "ir-equivalence", name,
                f"rolled stages should leave exactly {rolled} "
                f"collective_permute loop bodies, found {remaining}",
            )
        )
    return out


# ----------------------------------------------------------- entrypoints


def _lower_ir_collective(prog: "sir.IRProgram", elems: int, op: str = "sum") -> str:
    """Lower ``compile_ir(prog)`` over a ``prog.num_nodes``-device mesh
    (virtual CPU devices, pinned by the analysis CLI / test harness)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import flat_mesh
    from ..schedule.ir import compile_ir

    n = prog.num_nodes
    fn = compile_ir(prog, op=op)
    mesh = flat_mesh(n, "ft")

    def f(row):
        return fn(row[0], "ft")[None]

    sm = jax.shard_map(f, mesh=mesh, in_specs=P("ft"), out_specs=P("ft"))
    return jax.jit(sm).lower(jnp.zeros((n, elems), jnp.float32)).as_text()


def ir_equivalence_entrypoints() -> list[tuple[str, "sir.IRProgram", int]]:
    """(name, program, per-rank elems) rows — every family, chunked mode
    included; counts divisible by the block owners so the expected
    sequence carries no tail op (the tail path is covered separately in
    the hlo_lint budgets)."""
    from ..schedule.stages import LonelyTopology, Topology

    return [
        ("tree_4x2", sir.tree_ir(Topology(8, (4, 2)), count=256), 256),
        (
            "tree_4x2_chunks2",
            sir.tree_ir(Topology(8, (4, 2)), count=256, chunks=2),
            256,
        ),
        ("ring_8", sir.ring_ir(8, count=256), 256),
        (
            "lonely_3x2p2",
            sir.lonely_ir(
                LonelyTopology(8, Topology(6, (3, 2)), 2), count=252
            ),
            252,
        ),
        ("swing_8", sir.swing_ir(8, count=256), 256),
        ("swing_6", sir.swing_ir(6, count=256), 256),
        ("gen_4x2_p2", sir.generalized_ir((4, 2), 2, count=256), 256),
        ("gen_2x2x2_p1", sir.generalized_ir((2, 2, 2), 1, count=256), 256),
    ]


def run_ir_equivalence(
    programs=None, times: dict | None = None
) -> tuple[list[Violation], dict]:
    """Lower and check every entrypoint; returns (violations, detail).
    ``programs`` filters entrypoints by name substring; ``times`` (when
    given) collects per-entrypoint wall-ms — the hooks the CLI report
    uses, so the gate and the report are one loop."""
    import time

    violations: list[Violation] = []
    detail: dict = {}
    for name, prog, elems in ir_equivalence_entrypoints():
        if programs and not any(p in name for p in programs):
            continue
        t0 = time.perf_counter()
        expected = expected_hlo_sequence(prog, elems)
        ir_text = _lower_ir_collective(prog, elems)
        actual = actual_hlo_sequence(ir_text)
        vs = compare_sequences(name, expected, actual)
        violations += vs
        if times is not None:
            times[name] = round((time.perf_counter() - t0) * 1e3, 2)
        detail[name] = {
            "stages": len(prog.stages),
            "collectives": len(actual),
            "violations": len(vs),
        }
    return violations, detail


# ------------------------------------------------- mutation entrypoint


def lower_ir_divergent() -> list[Violation]:
    """The 'ir-divergence' corruption: the LOWERED program of one IR
    checked against the stage list of ANOTHER — the static twin of an
    executor that silently runs a different schedule than the object the
    model checker certified.  Numerically both are exact allreduces, so
    only this pass can see the divergence."""
    from ..schedule.stages import Topology

    real = sir.tree_ir(Topology(8, (4, 2)), count=256)
    claimed = sir.tree_ir(Topology(8, (2, 2, 2)), count=256)
    ir_text = _lower_ir_collective(real, 256)
    return compare_sequences(
        "mutated:ir_divergent_tree",
        expected_hlo_sequence(claimed, 256),
        actual_hlo_sequence(ir_text),
    )
