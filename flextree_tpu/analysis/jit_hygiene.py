"""Layer 3: jit-hygiene lint — AST analysis of the library source for
patterns that are legal Python but wrong inside a traced function.

A jitted function executes its Python body ONCE, at trace time.  Three
bug families follow, none of which any runtime test reliably catches:

- **wall-clock / host RNG** (``time.*``, ``datetime.now``, ``random.*``,
  ``np.random.*``): the value is baked into the compiled program as a
  constant — timings measure tracing, "randomness" repeats forever.
  (``jax.random`` is explicitly fine: it is functional and traced.)
- **Python branching on traced values** (``if``/``while`` on something
  derived from a traced argument): either a tracer-boolean error at trace
  time in the lucky case, or — when the value happens to be concrete at
  trace time — a silently specialized program.
- **missing ``static_argnames``**: jitting a function whose config-like
  parameters are passed dynamically retraces per call or fails on
  unhashable types.

Scope: the lint considers *traced* every function that lexically flows
into a tracing entry point in its own module — decorated with / passed to
``jax.jit`` / ``jax.shard_map`` / ``jax.vmap`` / ``jax.grad`` /
``lax.scan`` / ``lax.fori_loop`` / ``lax.while_loop`` / ``lax.cond`` /
``lax.switch`` / ``jax.checkpoint`` — plus everything lexically nested
inside one.  Cross-module call graphs are deliberately out of scope (the
direct jit surface is where the historical bugs live); anything the
heuristics get wrong is waived in place with an auditable pragma::

    x = time.perf_counter()  # jit-hygiene: ok — host-side timing helper

The pragma must carry a reason and suppresses only its own line (or the
whole function when placed on the ``def`` line).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass

from .base import Violation

__all__ = ["scan_source", "scan_file", "run_jit_hygiene", "PRAGMA"]

PRAGMA = "jit-hygiene: ok"

#: Calls that trace their function argument(s).
TRACING_FNS = frozenset(
    {
        "jax.jit",
        "jit",
        "jax.shard_map",
        "shard_map",
        "jax.vmap",
        "jax.pmap",
        "jax.grad",
        "jax.value_and_grad",
        "jax.checkpoint",
        "jax.remat",
        "jax.eval_shape",
        "jax.linear_transpose",
        "lax.scan",
        "jax.lax.scan",
        "lax.fori_loop",
        "jax.lax.fori_loop",
        "lax.while_loop",
        "jax.lax.while_loop",
        "lax.cond",
        "jax.lax.cond",
        "lax.switch",
        "jax.lax.switch",
        "lax.associative_scan",
        "jax.lax.associative_scan",
    }
)

#: Wall-clock sources: any of these called inside a traced function bakes
#: trace-time state into the compiled program.
WALL_CLOCK_PREFIXES = ("time.",)
WALL_CLOCK_CALLS = frozenset(
    {
        "datetime.datetime.now",
        "datetime.datetime.utcnow",
        "datetime.date.today",
        "datetime.now",
        "perf_counter",
        "monotonic",
    }
)

#: Host RNG namespaces (jax.random is functional and fine).
RNG_PREFIXES = ("random.", "np.random.", "numpy.random.")
RNG_OK_PREFIXES = ("jax.random.",)

#: Attribute reads on a traced value that are static at trace time.
STATIC_ATTRS = frozenset(
    {"shape", "dtype", "size", "ndim", "sharding", "aval", "itemsize"}
)

#: Calls whose result is static (a Python value) even on traced operands.
STATIC_CALLS = frozenset(
    {
        "lax.axis_size",
        "jax.lax.axis_size",
        "len",
        "isinstance",
        "issubclass",
        "type",
        "getattr",
        "hasattr",
        "callable",
        "int",
        "float",
        "bool",
        "str",
        "tuple",
        "list",
        "dict",
        "set",
        "sorted",
        "enumerate",
        "zip",
        "range",
        "math.prod",
        "Topology.resolve",
        "get_op",
    }
)

#: Parameter names that almost always want static_argnames when jitted.
CONFIG_PARAM_NAMES = frozenset(
    {"cfg", "config", "topo", "topology", "mesh", "axis_name", "spec", "op"}
)


def _qualname(node) -> str | None:
    """Dotted name of a Name/Attribute chain (``jax.lax.scan``), else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class _Finding:
    kind: str
    lineno: int
    func: str
    detail: str


class _FileScan:
    def __init__(self, src: str, filename: str):
        self.src_lines = src.splitlines()
        self.filename = filename
        self.tree = ast.parse(src, filename=filename)
        self.findings: list[_Finding] = []
        self.waived = 0

    # ---------------------------------------------------- traced-fn set

    def traced_functions(self) -> list[ast.AST]:
        """FunctionDefs that flow into a tracing call, plus their lexically
        nested defs."""
        defs_by_name: dict[str, list[ast.AST]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(node.name, []).append(node)

        roots: list[ast.AST] = []
        seen: set[int] = set()

        def add(fn):
            if id(fn) not in seen:
                seen.add(id(fn))
                roots.append(fn)

        # decorated defs
        for fns in defs_by_name.values():
            for fn in fns:
                for dec in fn.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    q = _qualname(target)
                    if q in TRACING_FNS or (
                        q in {"partial", "functools.partial"}
                        and isinstance(dec, ast.Call)
                        and dec.args
                        and _qualname(dec.args[0]) in TRACING_FNS
                    ):
                        add(fn)
        # defs referenced in tracing calls
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            q = _qualname(node.func)
            if q not in TRACING_FNS:
                continue
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id in defs_by_name:
                    for fn in defs_by_name[arg.id]:
                        add(fn)
                elif isinstance(arg, ast.Lambda):
                    add(arg)
        # lexically nested defs inside a traced def are traced too
        out = list(roots)
        for fn in roots:
            for sub in ast.walk(fn):
                if (
                    isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and sub is not fn
                ):
                    if id(sub) not in seen:
                        seen.add(id(sub))
                        out.append(sub)
        return out

    # ----------------------------------------------------------- checks

    def _record(self, kind, node, func_name, detail, fn_waived=False):
        lineno = getattr(node, "lineno", 0)
        if self._waived(lineno) or fn_waived:
            self.waived += 1
            return
        self.findings.append(_Finding(kind, lineno, func_name, detail))

    def _waived(self, lineno: int) -> bool:
        if 1 <= lineno <= len(self.src_lines):
            return PRAGMA in self.src_lines[lineno - 1]
        return False

    def scan(self) -> list[_Finding]:
        for fn in self.traced_functions():
            self._scan_traced_fn(fn)
        self._scan_jit_static_argnames()
        return self.findings

    @staticmethod
    def _walk_own(fn):
        """Walk ``fn``'s body without descending into nested function
        defs — those are traced units of their own and scanned separately
        (descending here would double-report their findings)."""
        stack = list(
            ast.iter_child_nodes(fn)
            if not isinstance(fn, ast.Lambda)
            else [fn.body]
        )
        while stack:
            node = stack.pop()
            yield node
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            stack.extend(ast.iter_child_nodes(node))

    def _scan_traced_fn(self, fn):
        name = getattr(fn, "name", "<lambda>")
        # a pragma on the def line waives THIS node only (keyed by node,
        # not by name — same-named defs and lambdas must not collide)
        fn_waived = self._waived(getattr(fn, "lineno", 0))
        # wall-clock / RNG calls anywhere in the traced body
        for node in self._walk_own(fn):
            if not isinstance(node, ast.Call):
                continue
            q = _qualname(node.func)
            if q is None:
                continue
            if q.startswith(RNG_OK_PREFIXES):
                continue
            if q.startswith(WALL_CLOCK_PREFIXES) or q in WALL_CLOCK_CALLS:
                self._record(
                    "wall-clock",
                    node,
                    name,
                    f"`{q}()` inside traced `{name}` runs once at trace "
                    f"time; the compiled program reuses that instant forever",
                    fn_waived=fn_waived,
                )
            elif q.startswith(RNG_PREFIXES):
                self._record(
                    "rng",
                    node,
                    name,
                    f"`{q}()` inside traced `{name}` bakes one host-RNG "
                    f"draw into the program; use jax.random with a key",
                    fn_waived=fn_waived,
                )
        # Python branches on traced values
        self._scan_branches(fn, name, fn_waived)

    def _static_argnames_of(self, fn) -> set[str]:
        """Parameters declared static at the jit boundary — excluded from
        the taint set (branching on them is exactly what static args are
        for).  Reads ``static_argnames``/``static_argnums`` from
        ``@partial(jax.jit, ...)``-style decorators and from
        ``jax.jit(f, static_argnames=...)`` call sites naming ``f``."""
        ordered = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        static: set[str] = set()

        def harvest(call: ast.Call):
            for kw in call.keywords:
                if kw.arg == "static_argnames":
                    for n in ast.walk(kw.value):
                        if isinstance(n, ast.Constant) and isinstance(
                            n.value, str
                        ):
                            static.add(n.value)
                elif kw.arg == "static_argnums":
                    for n in ast.walk(kw.value):
                        if isinstance(n, ast.Constant) and isinstance(
                            n.value, int
                        ):
                            if 0 <= n.value < len(ordered):
                                static.add(ordered[n.value])

        for dec in getattr(fn, "decorator_list", []):
            if not isinstance(dec, ast.Call):
                continue
            q = _qualname(dec.func)
            if q in {"jax.jit", "jit"}:
                harvest(dec)
            elif (
                q in {"partial", "functools.partial"}
                and dec.args
                and _qualname(dec.args[0]) in TRACING_FNS
            ):
                harvest(dec)
        fn_name = getattr(fn, "name", None)
        for node in ast.walk(self.tree):
            if (
                isinstance(node, ast.Call)
                and _qualname(node.func) in {"jax.jit", "jit"}
                and node.args
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id == fn_name
            ):
                harvest(node)
        return static

    def _scan_branches(self, fn, name, fn_waived=False):
        params = set()
        args = fn.args
        for a in (
            list(args.posonlyargs)
            + list(args.args)
            + list(args.kwonlyargs)
            + ([args.vararg] if args.vararg else [])
            + ([args.kwarg] if args.kwarg else [])
        ):
            params.add(a.arg)
        tainted = params - self._static_argnames_of(fn)

        def dyn(node) -> str | None:
            """Name of an unprotected tainted use inside ``node``, or None."""
            if isinstance(node, ast.Name):
                return node.id if node.id in tainted else None
            if isinstance(node, ast.Attribute):
                if node.attr in STATIC_ATTRS:
                    return None
                return dyn(node.value)
            if isinstance(node, ast.Call):
                q = _qualname(node.func)
                if q is not None and (
                    q in STATIC_CALLS or q.rsplit(".", 1)[-1] in STATIC_CALLS
                ):
                    return None
                for child in (
                    [node.func] + node.args + [k.value for k in node.keywords]
                ):
                    hit = dyn(child)
                    if hit:
                        return hit
                return None
            if isinstance(node, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
            ):
                return None  # `x is (not) None`: a static sentinel test
            for child in ast.iter_child_nodes(node):
                hit = dyn(child)
                if hit:
                    return hit
            return None

        class V(ast.NodeVisitor):
            def __init__(self, outer):
                self.outer = outer

            def visit_FunctionDef(self, node):
                if node is not fn:
                    return  # nested defs are scanned as their own unit
                self.generic_visit(node)

            visit_AsyncFunctionDef = visit_FunctionDef
            visit_Lambda = visit_FunctionDef

            def visit_Assign(self, node):
                if dyn(node.value):
                    for t in node.targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                tainted.add(n.id)
                self.generic_visit(node)

            def visit_AugAssign(self, node):
                if dyn(node.value) and isinstance(node.target, ast.Name):
                    tainted.add(node.target.id)
                self.generic_visit(node)

            def _check(self, node, label):
                hit = dyn(node.test)
                if hit:
                    self.outer._record(
                        "traced-branch",
                        node,
                        name,
                        f"`{label}` in traced `{name}` tests `{hit}`, which "
                        f"derives from a traced argument — use lax.cond/"
                        f"jnp.where, or mark the argument static",
                        fn_waived=fn_waived,
                    )
                self.generic_visit(node)

            def visit_If(self, node):
                self._check(node, "if")

            def visit_While(self, node):
                self._check(node, "while")

            def visit_IfExp(self, node):
                self._check(node, "conditional expression")

        V(self).visit(fn)

    def _scan_jit_static_argnames(self):
        defs_by_name = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.FunctionDef):
                defs_by_name.setdefault(node.name, node)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            if _qualname(node.func) not in {"jax.jit", "jit"}:
                continue
            if any(
                k.arg in {"static_argnames", "static_argnums"}
                for k in node.keywords
            ):
                continue
            if not node.args or not isinstance(node.args[0], ast.Name):
                continue
            target = defs_by_name.get(node.args[0].id)
            if target is None:
                continue
            suspects = [
                a.arg
                for a in target.args.args + target.args.kwonlyargs
                if a.arg in CONFIG_PARAM_NAMES
            ]
            if suspects:
                self._record(
                    "static-argnames",
                    node,
                    target.name,
                    f"jax.jit({target.name}) without static_argnames, but "
                    f"`{target.name}` takes config-like parameter(s) "
                    f"{suspects}: every distinct value retraces (or fails "
                    f"to hash)",
                    fn_waived=self._waived(target.lineno),
                )


def scan_source(src: str, filename: str = "<string>") -> tuple[list[Violation], int]:
    """Lint one source blob; returns (violations, waived_count)."""
    scan = _FileScan(src, filename)
    findings = scan.scan()
    out = [
        Violation(
            "jit",
            f.kind,
            f"{filename}:{f.lineno}",
            f.detail,
            src=f.lineno,
        )
        for f in findings
    ]
    return out, scan.waived


def scan_file(path: str, rel: str | None = None) -> tuple[list[Violation], int]:
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    return scan_source(src, rel or path)


def run_jit_hygiene(root: str | None = None) -> tuple[list[Violation], dict]:
    """Lint every ``.py`` file under the package root (default: the
    installed ``flextree_tpu`` package itself)."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base = os.path.dirname(os.path.abspath(root))
    violations: list[Violation] = []
    files = waived = 0
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            vs, w = scan_file(path, os.path.relpath(path, base))
            violations += vs
            waived += w
            files += 1
    return violations, {"files_scanned": files, "waived": waived}
