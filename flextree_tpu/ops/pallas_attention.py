"""Pallas TPU kernel: fused causal flash attention (forward).

The attention analog of ``pallas_reduce``: where that kernel pins the
allreduce's local-reduce layout, this one fuses the model layer's hot op —
the (Tq x Tk) score/softmax/value contraction — into a single VMEM-resident
pass, so the T x T score matrix never touches HBM.  One grid step owns one
(batch*head, q-block) tile; an inner ``fori_loop`` walks k/v blocks with
the online-softmax running max / normalizer (the same accumulation scheme
as ``flextree_tpu.parallel.ring_attention.local_attention_block``, but per
128-row tile on the MXU instead of per ring hop).

Causality is positional (``q_offset``/``k_offset`` give the blocks' global
coordinates), so the kernel drops straight into the Ulysses path — after
its all-to-all the full sequence is local — and into plain single-device
attention; the causal upper bound also *shortens the k loop* per q tile,
halving the work vs a masked dense matmul.

Differentiable via ``jax.custom_vjp`` with a **blockwise flash backward**:
the forward additionally emits the per-row logsumexp, and two backward
kernels recompute probabilities tile-by-tile from (q, k, v, lse) — one
gridded over q tiles producing dq, one over k tiles producing dk/dv — so
the backward, like the forward, never materializes the (Tq, Tk) score
matrix.  Total residual memory is O(T) beyond the inputs (out + lse +
delta rows).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

__all__ = ["flash_attention", "attention_with_offsets"]

_NEG_INF = -1e30
_LANE = 128  # lse is lane-replicated to satisfy Mosaic's (8, 128) block rule
_LOG2E = 1.4426950408889634

# forward k-loop unroll factor (env-overridable for tuning experiments);
# measured neutral-to-slightly-negative on v5e at the benchmark shape, so
# the default stays 1 — the knob exists for other chips/shapes
import os as _os

_FWD_UNROLL = int(_os.environ.get("FLEXTREE_FLASH_UNROLL", "1"))

# Default forward k-walk schedule.  "loop" is the r03 kernel with measured
# TPU numbers (93.3 TFLOP/s, BENCH_ATTENTION.json); "pipelined"/"kvgrid"
# are CPU-parity-pinned but flip to default only once the on-chip variant
# ablation (tools/run_tpu_artifacts.sh) shows one of them winning.
# Env-overridable so the bench can sweep without editing call sites.
DEFAULT_FWD_VARIANT = _os.environ.get("FLEXTREE_FLASH_VARIANT", "loop")


def attention_with_offsets(
    q, k, v, *, causal: bool, scale: float, q_offset=0, k_offset=0
):
    """Pure-jnp oracle on (BH, Tq, D)/(BH, Tk, D): full score matrix with
    positional causal masking — the A/B reference and the VJP recompute."""
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32), k.astype(jnp.float32))
    s = s * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None], s, _NEG_INF)
    m = s.max(axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    if causal:
        p = jnp.where(mask[None], p, 0.0)
    l = p.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32))
    out = jnp.where(l > 0, out / jnp.where(l > 0, l, 1.0), 0.0)
    return out.astype(q.dtype)


def _flash_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    *maybe_lse_ref,
    block_q: int,
    block_k: int,
    t_kv: int,
    t_kv_valid: int,
    causal: bool,
    scale: float,
    q_offset: int,
    k_offset: int,
    unroll: int = 1,
    pipeline: bool = False,
):  # variant="loop"/"pipelined" kernel; the "kvgrid" variant is below
    i = pl.program_id(1)
    # fold scale*log2(e) into q once (bq x D) instead of scaling each
    # (bq x bk) score tile, and run the online softmax in the exp2 domain —
    # softmax is base-invariant when max/normalizer use the same base.
    # Together with the full/masked loop split below this lifted the v5e
    # benchmark shape from 83 to ~95 TFLOP/s (see PROFILE_ATTENTION.md).
    q = q_ref[0] * (scale * _LOG2E)  # native dtype — bf16 q/k feed the MXU
    d = q.shape[-1]
    n_kb = t_kv // block_k

    if causal:
        # highest visible k position for this q tile (exclusive)
        hi = q_offset + (i + 1) * block_q - k_offset
        kb_hi = jnp.clip((hi + block_k - 1) // block_k, 0, n_kb)
        # tiles fully visible to every row of this q tile need no mask:
        # the first row (qpos = q_offset + i*block_q) sees `lo_vis` leading
        # k positions, so tiles strictly inside that prefix skip the
        # iota/compare/select entirely
        lo_vis = q_offset + i * block_q - k_offset + 1
        kb_full = jnp.clip(lo_vis // block_k, 0, n_kb)
    else:
        kb_hi = n_kb
        kb_full = n_kb
    if t_kv_valid < t_kv:  # static: only tiles before the pad are mask-free
        kb_full = jnp.minimum(kb_full, t_kv_valid // block_k)

    def tile(j):
        kb = k_ref[0, pl.ds(j * block_k, block_k), :]
        vb = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (bq, bk) f32 log2-domain scores from native-dtype operands
        return s, vb

    def update(carry, s, vb, valid=None):
        return _kv_update(*carry, s, vb, valid)

    def step_full(j, carry):
        s, vb = tile(j)
        return update(carry, s, vb)

    def step_masked(j, carry):
        s, vb = tile(j)
        kpos = (
            k_offset
            + j * block_k
            + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        )
        valid = kpos - k_offset < t_kv_valid
        if causal:
            qpos = (
                q_offset
                + i * block_q
                + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            )
            valid = valid & (qpos >= kpos)
        return update(carry, s, vb, valid=valid)

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    if pipeline:
        # Software-pipelined full loop: iteration j's body computes tile
        # j's scores (MXU, independent of the softmax carry) *and* folds
        # tile j-1's already-computed scores into the online softmax (VPU +
        # the p@v MXU op).  Inside one loop body the two are explicitly
        # independent, so Mosaic can overlap them — the cross-iteration
        # scheduling a carry-serialized ``fori_loop`` body denies it
        # (PROFILE_ATTENTION.md §2: the ~52% ceiling assumed no MXU/VPU
        # overlap; this is the lever that escapes it).
        s0, vb0 = tile(0)  # safe: t_kv >= block_k always (padded geometry)

        def step_pipe(j, carry):
            m, l, acc, s_prev, vb_prev = carry
            s_next, vb_next = tile(j)
            m, l, acc = update((m, l, acc), s_prev, vb_prev)
            return m, l, acc, s_next, vb_next

        m, l, acc, s_last, vb_last = lax.fori_loop(
            1, kb_full, step_pipe, (m0, l0, acc0, s0, vb0)
        )
        # epilogue: tile kb_full-1's scores are computed but unconsumed;
        # fold them in — unless the full loop was empty (kb_full == 0),
        # where the prefetched tile 0 must be discarded
        fed = update((m, l, acc), s_last, vb_last)
        m, l, acc = jax.tree.map(
            lambda a, b: jnp.where(kb_full > 0, a, b), fed, (m, l, acc)
        )
        carry = (m, l, acc)
    else:
        try:
            carry = lax.fori_loop(
                0, kb_full, step_full, (m0, l0, acc0), unroll=unroll
            )
        except ValueError:
            # older JAX rejects unroll with the dynamic (causal) bound;
            # unroll is a tuning knob, never a semantics change — fall back
            carry = lax.fori_loop(0, kb_full, step_full, (m0, l0, acc0))
    m, l, acc = lax.fori_loop(kb_full, kb_hi, step_masked, carry)
    out = jnp.where(l > 0, acc / jnp.where(l > 0, l, 1.0), 0.0)
    o_ref[0] = out.astype(o_ref.dtype)
    if maybe_lse_ref:  # only the differentiated path pays for the lse store
        # lse is stored in NATURAL-log units (m is log2-domain: divide the
        # whole thing by log2(e)); fully-masked rows get a +inf-like
        # sentinel so the backward's exp(s - lse) is exactly zero for them;
        # the value is replicated across the 128-lane minor dim (Mosaic
        # block constraint)
        lse = jnp.where(
            l > 0,
            (m + jnp.log2(jnp.maximum(l, 1e-38))) * (1.0 / _LOG2E),
            -_NEG_INF,
        )
        maybe_lse_ref[0][0] = jnp.broadcast_to(lse, (block_q, _LANE))


def _flash_kernel_kvgrid(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    *rest_refs,
    block_q: int,
    block_k: int,
    block_k_major: int,
    t_kv: int,
    t_kv_valid: int,
    causal: bool,
    scale: float,
    q_offset: int,
    k_offset: int,
):
    """The "kvgrid" forward: k/v-major tiles are a GRID dimension, not a
    ``fori_loop``.

    The softmax carry (m, l, acc) lives in VMEM scratch across the
    ``arbitrary``-semantics kv axis, each grid step's inner walk over
    ``block_k`` minor tiles is a *statically unrolled* Python loop, and
    k/v blocks arrive by BlockSpec DMA — so Mosaic sees straight-line code
    per step, double-buffers the k/v fetches across steps, and can overlap
    tile t+1's DMA/matmul with tile t's softmax.  This is the structure
    the stock Pallas TPU flash kernel uses; the ``loop`` variant's dynamic
    trip count denies Mosaic all of it (PROFILE_ATTENTION.md §2/§4).
    Causally-invisible (i, j) grid steps skip compute under ``pl.when``
    (their k/v DMA still happens — same total traffic as the loop
    variant's whole-k/v residency).
    """
    has_lse = len(rest_refs) == 4
    if has_lse:
        lse_ref, acc_ref, m_ref, l_ref = rest_refs
    else:
        acc_ref, m_ref, l_ref = rest_refs
    i = pl.program_id(1)
    j = pl.program_id(2)
    n_j = t_kv // block_k_major

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full(m_ref.shape, _NEG_INF, jnp.float32)
        l_ref[...] = jnp.zeros(l_ref.shape, jnp.float32)
        acc_ref[...] = jnp.zeros(acc_ref.shape, jnp.float32)

    if causal:
        # exclusive bound of visible local k positions for this q tile
        hi = q_offset + (i + 1) * block_q - k_offset
        run = (j * block_k_major) < hi
        # last kv-major tile with any visible position — where the output
        # is finalized (0 when nothing is visible: zero acc, l=0 path)
        j_last = jnp.clip(-(-hi // block_k_major) - 1, 0, n_j - 1)
        # fully-visible prefix (min over the tile's rows), for mask skipping
        lo_vis = q_offset + i * block_q - k_offset + 1
    else:
        run = True
        j_last = n_j - 1
        lo_vis = t_kv

    def _body():
        q = q_ref[0] * (scale * _LOG2E)
        m = m_ref[:, 0:1]
        l = l_ref[:, 0:1]
        acc = acc_ref[...]
        for jj in range(block_k_major // block_k):
            base = j * block_k_major + jj * block_k  # local k index (traced)
            kb = k_ref[0, jj * block_k:(jj + 1) * block_k, :]
            vb = v_ref[0, jj * block_k:(jj + 1) * block_k, :]
            # the score matmul lives INSIDE the branches so a skipped minor
            # tile (fully invisible: beyond the causal bound or entirely in
            # the pad) costs neither MXU nor VPU work — with
            # block_k_major > block_k the last visible major tile otherwise
            # computes up to (bkM - bk) columns of zeros per q tile
            visible = base < t_kv_valid
            if causal:
                visible = visible & (base < hi)
            needs_mask = base + block_k > t_kv_valid
            if causal:
                needs_mask = needs_mask | (base + block_k > lo_vis)

            def scores(q):
                return jax.lax.dot_general(
                    q, kb, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )

            def masked(op):
                m, l, acc, q = op
                s = scores(q)
                kpos = base + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1
                )
                valid = kpos < t_kv_valid
                if causal:
                    qpos = (
                        q_offset - k_offset + i * block_q
                        + lax.broadcasted_iota(
                            jnp.int32, (block_q, block_k), 0
                        )
                    )
                    valid = valid & (qpos >= kpos)
                return _kv_update(m, l, acc, s, vb, valid)

            def unmasked(op):
                m, l, acc, q = op
                return _kv_update(m, l, acc, scores(q), vb, None)

            def folded(op):
                return lax.cond(needs_mask, masked, unmasked, op)

            m, l, acc = lax.cond(
                visible, folded, lambda op: op[:3], (m, l, acc, q)
            )
        m_ref[...] = jnp.broadcast_to(m, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l, l_ref.shape)
        acc_ref[...] = acc

    if causal:
        pl.when(run)(_body)
    else:
        _body()

    @pl.when(j == j_last)
    def _finalize():
        m = m_ref[:, 0:1]
        l = l_ref[:, 0:1]
        acc = acc_ref[...]
        out = jnp.where(l > 0, acc / jnp.where(l > 0, l, 1.0), 0.0)
        o_ref[0] = out.astype(o_ref.dtype)
        if has_lse:
            lse = jnp.where(
                l > 0,
                (m + jnp.log2(jnp.maximum(l, 1e-38))) * (1.0 / _LOG2E),
                -_NEG_INF,
            )
            lse_ref[0] = jnp.broadcast_to(lse, (block_q, _LANE))


def _kv_update(m, l, acc, s, vb, valid):
    """One online-softmax fold — THE implementation, shared by every
    forward variant (``_flash_kernel`` wraps it as ``update``); a numerics
    change here changes all three schedules identically.  Probabilities
    drop to v's dtype for the MXU (standard flash practice; exact when v
    is f32, ~1e-2 abs err in bf16)."""
    if valid is not None:
        s = jnp.where(valid, s, _NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
    p = jnp.exp2(s - m_new)
    if valid is not None:
        p = jnp.where(valid, p, 0.0)
    corr = jnp.exp2(m - m_new)
    l_new = l * corr + p.sum(axis=-1, keepdims=True)
    acc_new = acc * corr + jax.lax.dot_general(
        p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, acc_new


def _blocks(q, k, block_q, block_k):
    """Resolved (bq, bk, tq_pad, tk_pad, interpret-independent) geometry.

    Clamped block sizes are rounded up to a multiple of 8 (Mosaic's
    second-minor tiling unit for f32): tq=100 must yield bq=104, not 100 —
    a non-multiple-of-8 block would tile poorly or be rejected on real TPU.
    The sequence padding below already absorbs the overshoot.
    """
    tq, tk = q.shape[1], k.shape[1]
    bq = -(-min(block_q, max(tq, 8)) // 8) * 8
    bk = -(-min(block_k, max(tk, 8)) // 8) * 8
    return bq, bk, -(-tq // bq) * bq, -(-tk // bk) * bk


def _to_bhd(x, t_pad):
    """(B, T, H, D) -> (B*H, T_pad, D)."""
    b, t, h, d = x.shape
    x = x.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    if t_pad != t:
        x = jnp.pad(x, ((0, 0), (0, t_pad - t), (0, 0)))
    return x


def _from_bhd(x, b, h, t):
    return x[:, :t].reshape(b, h, t, x.shape[-1]).transpose(0, 2, 1, 3)


def _flash_fwd_impl(
    q, k, v, causal, scale, q_offset, k_offset, block_q, block_k, interpret,
    emit_lse: bool = False,
    variant: str | None = None,
):
    """(B, Tq, H, D) x (B, Tk, H, D)^2 -> fused attention out, plus the
    per-row logsumexp (B*H, Tq_pad) when ``emit_lse`` (else None) — the
    primal/inference path skips that extra HBM store entirely.

    ``variant``: "loop" (carry-serialized fori_loop), "pipelined"
    (software-pipelined fori_loop), or "kvgrid" (k/v walk as a grid axis
    with VMEM scratch carry — see ``_flash_kernel_kvgrid``).
    """
    if variant is None:
        variant = DEFAULT_FWD_VARIANT
    if variant not in ("loop", "pipelined", "kvgrid"):
        raise ValueError(f"unknown flash variant {variant!r}")
    b, tq, h, d = q.shape
    tk = k.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bq, bk, tq_pad, tk_pad = _blocks(q, k, block_q, block_k)
    q3, k3, v3 = _to_bhd(q, tq_pad), _to_bhd(k, tk_pad), _to_bhd(v, tk_pad)

    out_shape = [jax.ShapeDtypeStruct((b * h, tq_pad, d), q.dtype)]
    if variant == "kvgrid":
        out_specs = [pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0))]
        if emit_lse:
            out_shape.append(
                jax.ShapeDtypeStruct((b * h, tq_pad, _LANE), jnp.float32)
            )
            out_specs.append(
                pl.BlockSpec((1, bq, _LANE), lambda bh, i, j: (bh, i, 0))
            )
        from jax.experimental.pallas import tpu as pltpu

        try:
            compiler_params = pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")
            )
        except AttributeError:  # pragma: no cover - older naming
            compiler_params = pltpu.TPUCompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")
            )
        # k/v-major DMA granule: up to 4 minor tiles (<= 2048 rows) per
        # grid step, statically unrolled in the kernel — bigger transfers
        # for the pipeline to double-buffer, with per-minor-tile compute
        # skip keeping the causal diagonal cheap
        n_minor = tk_pad // bk
        # default 1: the 2048 cap bounds the UPSIZING only — a single
        # larger-than-2048 minor tile (big block_k) still runs unchanged
        u = next(
            (u for u in (4, 2, 1) if n_minor % u == 0 and bk * u <= 2048), 1
        )
        bkM = bk * u
        res = pl.pallas_call(
            functools.partial(
                _flash_kernel_kvgrid,
                block_q=bq,
                block_k=bk,
                block_k_major=bkM,
                t_kv=tk_pad,
                t_kv_valid=tk,
                causal=causal,
                scale=scale,
                q_offset=q_offset,
                k_offset=k_offset,
            ),
            out_shape=tuple(out_shape),
            grid=(b * h, tq_pad // bq, tk_pad // bkM),
            in_specs=[
                pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
                pl.BlockSpec((1, bkM, d), lambda bh, i, j: (bh, j, 0)),
                pl.BlockSpec((1, bkM, d), lambda bh, i, j: (bh, j, 0)),
            ],
            out_specs=tuple(out_specs),
            scratch_shapes=[
                pltpu.VMEM((bq, d), jnp.float32),      # acc
                pltpu.VMEM((bq, _LANE), jnp.float32),  # m
                pltpu.VMEM((bq, _LANE), jnp.float32),  # l
            ],
            compiler_params=compiler_params,
            interpret=interpret,
        )(q3, k3, v3)
    else:
        out_specs = [pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0))]
        if emit_lse:
            out_shape.append(
                jax.ShapeDtypeStruct((b * h, tq_pad, _LANE), jnp.float32)
            )
            out_specs.append(
                pl.BlockSpec((1, bq, _LANE), lambda bh, i: (bh, i, 0))
            )
        res = pl.pallas_call(
            functools.partial(
                _flash_kernel,
                block_q=bq,
                block_k=bk,
                t_kv=tk_pad,
                t_kv_valid=tk,
                causal=causal,
                scale=scale,
                q_offset=q_offset,
                k_offset=k_offset,
                unroll=_FWD_UNROLL,
                pipeline=variant == "pipelined",
            ),
            out_shape=tuple(out_shape),
            grid=(b * h, tq_pad // bq),
            in_specs=[
                pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
                pl.BlockSpec((1, tk_pad, d), lambda bh, i: (bh, 0, 0)),
                pl.BlockSpec((1, tk_pad, d), lambda bh, i: (bh, 0, 0)),
            ],
            out_specs=tuple(out_specs),
            interpret=interpret,
        )(q3, k3, v3)
    if emit_lse:
        out, lse = res
        # store only one lane's row as the residual (128x smaller); the
        # backward re-broadcasts to the block layout on entry
        return _from_bhd(out, b, h, tq), lse[..., 0]
    return _from_bhd(res[0], b, h, tq), None


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, *rest,
    has_glse, block_q, block_k, t_kv, t_kv_valid, causal, scale,
    q_offset, k_offset,
):
    dq_ref = rest[-1]
    i = pl.program_id(1)
    # prescale q into the log2 domain (see _flash_kernel); the raw k tile
    # still feeds the final ds @ k matmul, so dq's chain-rule `* scale`
    # at the end is unchanged
    qs = q_ref[0] * (scale * _LOG2E)
    do = do_ref[0].astype(jnp.float32)
    # residual lse is natural-log; shift it into the log2 domain once
    lse2 = lse_ref[0][:, 0:1] * _LOG2E  # (bq, 1) — lane-replicated storage
    # cotangent of the lse output; operand only exists when it was consumed
    glse = rest[0][0][:, 0:1] if has_glse else 0.0
    # delta_i = dout_i . out_i (the softmax-normalizer term)
    delta = jnp.sum(do * o_ref[0].astype(jnp.float32), axis=-1, keepdims=True)
    d = qs.shape[-1]
    n_kb = t_kv // block_k
    if causal:
        hi = q_offset + (i + 1) * block_q - k_offset
        kb_hi = jnp.clip((hi + block_k - 1) // block_k, 0, n_kb)
        lo_vis = q_offset + i * block_q - k_offset + 1
        kb_full = jnp.clip(lo_vis // block_k, 0, n_kb)
    else:
        kb_hi = n_kb
        kb_full = n_kb
    if t_kv_valid < t_kv:
        kb_full = jnp.minimum(kb_full, t_kv_valid // block_k)

    def tile_dq(j, dq, p, kb, vb):
        dp = jax.lax.dot_general(
            do, vb.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # d(lse_i)/d(s_ij) = p_ij, so the lse cotangent adds glse_i * p_ij
        ds = p * (dp - delta + glse)
        return dq + jax.lax.dot_general(
            ds.astype(kb.dtype), kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    def loads(j):
        kb = k_ref[0, pl.ds(j * block_k, block_k), :]
        vb = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            qs, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # log2-domain scores
        return s, kb, vb

    def body_full(j, dq):
        s, kb, vb = loads(j)
        return tile_dq(j, dq, jnp.exp2(s - lse2), kb, vb)

    def body_masked(j, dq):
        s, kb, vb = loads(j)
        kpos = (
            k_offset + j * block_k
            + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        )
        valid = kpos - k_offset < t_kv_valid
        if causal:
            qpos = (
                q_offset + i * block_q
                + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            )
            valid = valid & (qpos >= kpos)
        p = jnp.where(valid, jnp.exp2(s - lse2), 0.0)
        return tile_dq(j, dq, p, kb, vb)

    dq = lax.fori_loop(0, kb_full, body_full, jnp.zeros((block_q, d), jnp.float32))
    dq = lax.fori_loop(kb_full, kb_hi, body_masked, dq)
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, *rest,
    has_glse, block_q, block_k, t_q, t_kv, t_kv_valid, causal, scale,
    q_offset, k_offset,
):
    glse_ref = rest[0] if has_glse else None
    dk_ref, dv_ref = rest[-2], rest[-1]
    j = pl.program_id(1)
    kb = k_ref[0]
    # log2-domain prescale lives on the k tile here (q appears raw in the
    # final ds^T @ q matmul, so prescaling q would corrupt dk); one
    # (bk x D) multiply per grid step replaces a (bq x bk) score scale per
    # q tile
    kbs = kb * (scale * _LOG2E)
    vb = v_ref[0]
    d = kb.shape[-1]
    n_qb = t_q // block_q
    if causal:
        # first q tile whose last row can see this k tile
        lo = (k_offset + j * block_k - q_offset) // block_q
        qb_lo = jnp.clip(lo, 0, n_qb)
        # first q tile whose FIRST row sees the whole k tile — from there
        # on no causal mask is needed
        full_lo = -(-(k_offset + (j + 1) * block_k - 1 - q_offset) // block_q)
        qb_full_lo = jnp.clip(full_lo, 0, n_qb)
    else:
        qb_lo = 0
        qb_full_lo = 0

    kpos = (
        k_offset + j * block_k
        + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    )
    k_valid = kpos - k_offset < t_kv_valid

    def tiles(i):
        qb = q_ref[0, pl.ds(i * block_q, block_q), :]
        do = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        ob = o_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse2 = lse_ref[0, pl.ds(i * block_q, block_q), 0:1] * _LOG2E
        glse = (
            glse_ref[0, pl.ds(i * block_q, block_q), 0:1] if has_glse else 0.0
        )
        delta = jnp.sum(do * ob, axis=-1, keepdims=True)
        s = jax.lax.dot_general(
            qb, kbs, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # log2-domain scores
        return qb, do, lse2, glse, delta, s

    def accumulate(carry, qb, do, glse, delta, p):
        dk, dv = carry
        dv = dv + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, vb.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta + glse)
        dk = dk + jax.lax.dot_general(
            ds.astype(qb.dtype), qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk, dv

    def body_masked(i, carry):
        qb, do, lse2, glse, delta, s = tiles(i)
        valid = k_valid
        if causal:
            qpos = (
                q_offset + i * block_q
                + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            )
            valid = valid & (qpos >= kpos)
        p = jnp.where(valid, jnp.exp2(s - lse2), 0.0)
        return accumulate(carry, qb, do, glse, delta, p)

    def body_full(i, carry):
        qb, do, lse2, glse, delta, s = tiles(i)
        return accumulate(carry, qb, do, glse, delta, jnp.exp2(s - lse2))

    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    if t_kv_valid < t_kv:
        # k padding present: every q tile needs the k-validity mask
        dk, dv = lax.fori_loop(qb_lo, n_qb, body_masked, (dk0, dv0))
    else:
        carry = lax.fori_loop(qb_lo, qb_full_lo, body_masked, (dk0, dv0))
        dk, dv = lax.fori_loop(qb_full_lo, n_qb, body_full, carry)
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_impl(
    q, k, v, out, lse, g, g_lse, causal, scale, q_offset, k_offset,
    block_q, block_k, interpret,
):
    """``g``: cotangent of the attention output; ``g_lse``: cotangent of
    the lse output ((B*H, Tq_pad) or None when lse was not consumed)."""
    b, tq, h, d = q.shape
    tk = k.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bq, bk, tq_pad, tk_pad = _blocks(q, k, block_q, block_k)
    q3, k3, v3 = _to_bhd(q, tq_pad), _to_bhd(k, tk_pad), _to_bhd(v, tk_pad)
    do3 = _to_bhd(g, tq_pad)
    o3 = _to_bhd(out, tq_pad)
    # residual lse is one row per query; rebuild the lane-replicated block
    # layout the kernels read ([:, 0:1])
    lse = jnp.broadcast_to(lse[..., None], (*lse.shape, _LANE))
    has_glse = g_lse is not None
    dq_inputs = [q3, k3, v3, do3, o3, lse]
    if has_glse:
        g_lse = jnp.broadcast_to(
            g_lse.astype(jnp.float32)[..., None], lse.shape
        )
        dq_inputs.append(g_lse)

    common = dict(
        has_glse=has_glse, block_q=bq, block_k=bk, causal=causal,
        scale=scale, q_offset=q_offset, k_offset=k_offset,
    )
    dq_tile_specs = [
        pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
        pl.BlockSpec((1, tk_pad, d), lambda bh, i: (bh, 0, 0)),
        pl.BlockSpec((1, tk_pad, d), lambda bh, i: (bh, 0, 0)),
        pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
        pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
        pl.BlockSpec((1, bq, _LANE), lambda bh, i: (bh, i, 0)),
    ]
    if has_glse:
        dq_tile_specs.append(pl.BlockSpec((1, bq, _LANE), lambda bh, i: (bh, i, 0)))
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, t_kv=tk_pad, t_kv_valid=tk, **common
        ),
        out_shape=jax.ShapeDtypeStruct((b * h, tq_pad, d), q.dtype),
        grid=(b * h, tq_pad // bq),
        in_specs=dq_tile_specs,
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
        interpret=interpret,
    )(*dq_inputs)

    dkv_specs = [
        pl.BlockSpec((1, tq_pad, d), lambda bh, j: (bh, 0, 0)),
        pl.BlockSpec((1, bk, d), lambda bh, j: (bh, j, 0)),
        pl.BlockSpec((1, bk, d), lambda bh, j: (bh, j, 0)),
        pl.BlockSpec((1, tq_pad, d), lambda bh, j: (bh, 0, 0)),
        pl.BlockSpec((1, tq_pad, d), lambda bh, j: (bh, 0, 0)),
        pl.BlockSpec((1, tq_pad, _LANE), lambda bh, j: (bh, 0, 0)),
    ]
    if has_glse:
        dkv_specs.append(
            pl.BlockSpec((1, tq_pad, _LANE), lambda bh, j: (bh, 0, 0))
        )
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, t_q=tq_pad, t_kv=tk_pad, t_kv_valid=tk,
            **common,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b * h, tk_pad, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, tk_pad, d), v.dtype),
        ),
        grid=(b * h, tk_pad // bk),
        in_specs=dkv_specs,
        out_specs=(
            pl.BlockSpec((1, bk, d), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, j: (bh, j, 0)),
        ),
        interpret=interpret,
    )(*dq_inputs)

    return (
        _from_bhd(dq, b, h, tq),
        _from_bhd(dk, b, h, tk),
        _from_bhd(dv, b, h, tk),
    )


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10)
)
def _flash_attention_core(
    q, k, v, causal, scale, q_offset, k_offset, block_q, block_k, interpret,
    variant,
):
    out, _ = _flash_fwd_impl(
        q, k, v, causal, scale, q_offset, k_offset, block_q, block_k, interpret,
        variant=variant,
    )
    return out


def _core_fwd(
    q, k, v, causal, scale, q_offset, k_offset, block_q, block_k, interpret,
    variant,
):
    out, lse = _flash_fwd_impl(
        q, k, v, causal, scale, q_offset, k_offset, block_q, block_k, interpret,
        emit_lse=True, variant=variant,
    )
    return out, (q, k, v, out, lse)


def _core_bwd(
    causal, scale, q_offset, k_offset, block_q, block_k, interpret, variant,
    res, g,
):
    q, k, v, out, lse = res
    return _flash_bwd_impl(
        q, k, v, out, lse, g, None, causal, scale, q_offset, k_offset,
        block_q, block_k, interpret,
    )


_flash_attention_core.defvjp(_core_fwd, _core_bwd)


# -- variant exposing a differentiable logsumexp output (ring-merge input) --


def _lse_to_btH(lse, b, h, t):
    """(B*H, Tq_pad) row layout -> (B, Tq, H), sentinel -> -inf-like."""
    out = lse[:, :t].reshape(b, h, t).transpose(0, 2, 1)
    # in-kernel sentinel for fully-masked rows is +1e30 (so the backward's
    # exp(s - lse) vanishes); the public meaning is "no mass" = -inf-like
    return jnp.where(out >= -_NEG_INF, _NEG_INF, out)


def _lse_from_btH(g_lse, tq_pad):
    """(B, Tq, H) cotangent -> (B*H, Tq_pad) row layout."""
    b, t, h = g_lse.shape
    g = g_lse.transpose(0, 2, 1).reshape(b * h, t)
    if tq_pad != t:
        g = jnp.pad(g, ((0, 0), (0, tq_pad - t)))
    return g


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10)
)
def _flash_attention_lse_core(
    q, k, v, causal, scale, q_offset, k_offset, block_q, block_k, interpret,
    variant,
):
    out, lse = _flash_fwd_impl(
        q, k, v, causal, scale, q_offset, k_offset, block_q, block_k, interpret,
        emit_lse=True, variant=variant,
    )
    b, tq, h, _ = q.shape
    return out, _lse_to_btH(lse, b, h, tq)


def _lse_core_fwd(
    q, k, v, causal, scale, q_offset, k_offset, block_q, block_k, interpret,
    variant,
):
    out, lse = _flash_fwd_impl(
        q, k, v, causal, scale, q_offset, k_offset, block_q, block_k, interpret,
        emit_lse=True, variant=variant,
    )
    b, tq, h, _ = q.shape
    return (out, _lse_to_btH(lse, b, h, tq)), (q, k, v, out, lse)


def _lse_core_bwd(
    causal, scale, q_offset, k_offset, block_q, block_k, interpret, variant,
    res, g,
):
    q, k, v, out, lse = res
    g_out, g_lse = g
    tq_pad = lse.shape[1]
    return _flash_bwd_impl(
        q, k, v, out, lse, g_out, _lse_from_btH(g_lse, tq_pad),
        causal, scale, q_offset, k_offset, block_q, block_k, interpret,
    )


_flash_attention_lse_core.defvjp(_lse_core_fwd, _lse_core_bwd)


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    scale: float | None = None,
    q_offset: int = 0,
    k_offset: int = 0,
    block_q: int = 256,
    block_k: int = 512,
    interpret: bool | None = None,
    return_lse: bool = False,
    variant: str | None = None,
):
    """Fused attention on (B, Tq, H, D) queries / (B, Tk, H, D) keys-values.

    Same contract as ``attention_reference`` (output for the local queries
    in ``q``'s dtype) plus global ``q_offset``/``k_offset`` positions for
    causal masking of shifted blocks.  ``interpret=None`` auto-selects the
    Pallas interpreter off-TPU so tests run on CPU.

    With ``return_lse=True`` also returns the per-row logsumexp of the
    masked scores, shape (B, Tq, H) float32 (fully-masked rows: -1e30) —
    differentiable, which is what lets blockwise consumers (the flash ring
    attention) merge partial attentions exactly.

    ``variant`` selects the forward k-walk structure — identical numerics:
    "loop" (carry-serialized fori_loop, the r03 kernel; the default via
    ``DEFAULT_FWD_VARIANT`` until the on-chip ablation crowns a winner),
    "pipelined" (software-pipelined fori_loop: tile j's MXU score matmul
    issued alongside tile j-1's VPU softmax), "kvgrid" (k/v tiles as a
    grid axis with VMEM scratch carry and BlockSpec-DMA'd k/v — Mosaic
    pipelines grid steps).  The backward kernels are shared.
    """
    if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
        raise ValueError(f"expected (B, T, H, D) inputs, got {q.shape}")
    if k.shape != v.shape:
        raise ValueError(f"k/v shapes differ: {k.shape} vs {v.shape}")
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    core = _flash_attention_lse_core if return_lse else _flash_attention_core
    return core(
        q, k, v, causal, float(scale), int(q_offset), int(k_offset),
        int(block_q), int(block_k), interpret,
        str(DEFAULT_FWD_VARIANT if variant is None else variant),
    )
