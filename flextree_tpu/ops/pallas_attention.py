"""Pallas TPU kernel: fused causal flash attention (forward).

The attention analog of ``pallas_reduce``: where that kernel pins the
allreduce's local-reduce layout, this one fuses the model layer's hot op —
the (Tq x Tk) score/softmax/value contraction — into a single VMEM-resident
pass, so the T x T score matrix never touches HBM.  One grid step owns one
(batch*head, q-block) tile; an inner ``fori_loop`` walks k/v blocks with
the online-softmax running max / normalizer (the same accumulation scheme
as ``flextree_tpu.parallel.ring_attention.local_attention_block``, but per
128-row tile on the MXU instead of per ring hop).

Causality is positional (``q_offset``/``k_offset`` give the blocks' global
coordinates), so the kernel drops straight into the Ulysses path — after
its all-to-all the full sequence is local — and into plain single-device
attention; the causal upper bound also *shortens the k loop* per q tile,
halving the work vs a masked dense matmul.

Differentiable via ``jax.custom_vjp``: the backward recomputes attention
with the pure-jnp oracle under ``jax.vjp``, so gradients are exact and the
*forward* stores only (q, k, v) — but the recompute materializes the full
(B*H, Tq, Tk) f32 score matrix, so **backward memory is O(T^2)** like the
reference; the fused-forward memory win applies to inference and to
sequence lengths whose score matrix still fits during training.  A
blockwise flash backward kernel is the known next step.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

__all__ = ["flash_attention", "attention_with_offsets"]

_NEG_INF = -1e30


def attention_with_offsets(
    q, k, v, *, causal: bool, scale: float, q_offset=0, k_offset=0
):
    """Pure-jnp oracle on (BH, Tq, D)/(BH, Tk, D): full score matrix with
    positional causal masking — the A/B reference and the VJP recompute."""
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32), k.astype(jnp.float32))
    s = s * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None], s, _NEG_INF)
    m = s.max(axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    if causal:
        p = jnp.where(mask[None], p, 0.0)
    l = p.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32))
    out = jnp.where(l > 0, out / jnp.where(l > 0, l, 1.0), 0.0)
    return out.astype(q.dtype)


def _flash_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    *,
    block_q: int,
    block_k: int,
    t_kv: int,
    t_kv_valid: int,
    causal: bool,
    scale: float,
    q_offset: int,
    k_offset: int,
):
    i = pl.program_id(1)
    q = q_ref[0]  # (bq, D), native dtype — bf16 q/k feed the MXU directly
    d = q.shape[-1]
    n_kb = t_kv // block_k

    if causal:
        # highest visible k position for this q tile (exclusive)
        hi = q_offset + (i + 1) * block_q - k_offset
        kb_hi = jnp.clip((hi + block_k - 1) // block_k, 0, n_kb)
    else:
        kb_hi = n_kb

    def body(j, carry):
        m, l, acc = carry
        kb = k_ref[0, pl.ds(j * block_k, block_k), :]
        vb = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (bq, bk) f32 scores from native-dtype operands
        kpos = (
            k_offset
            + j * block_k
            + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        )
        valid = kpos - k_offset < t_kv_valid
        if causal:
            qpos = (
                q_offset
                + i * block_q
                + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            )
            valid = valid & (qpos >= kpos)
        s = jnp.where(valid, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(valid, p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1, keepdims=True)
        # probabilities drop to v's dtype for the MXU (standard flash
        # practice; exact when v is f32, ~1e-2 abs err in bf16)
        acc_new = acc * corr + jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = lax.fori_loop(0, kb_hi, body, (m0, l0, acc0))
    out = jnp.where(l > 0, acc / jnp.where(l > 0, l, 1.0), 0.0)
    o_ref[0] = out.astype(o_ref.dtype)


def _flash_fwd_impl(
    q, k, v, causal, scale, q_offset, k_offset, block_q, block_k, interpret
):
    """(B, Tq, H, D) x (B, Tk, H, D)^2 -> (B, Tq, H, D) fused attention."""
    b, tq, h, d = q.shape
    tk = k.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    bq = min(block_q, max(tq, 8))
    bk = min(block_k, max(tk, 8))
    tq_pad = -(-tq // bq) * bq
    tk_pad = -(-tk // bk) * bk

    # (B, T, H, D) -> (B*H, T, D)
    def to_bhd(x, t_pad):
        x = x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)
        if t_pad != x.shape[1]:
            x = jnp.pad(x, ((0, 0), (0, t_pad - x.shape[1]), (0, 0)))
        return x

    q3, k3, v3 = to_bhd(q, tq_pad), to_bhd(k, tk_pad), to_bhd(v, tk_pad)

    out = pl.pallas_call(
        functools.partial(
            _flash_kernel,
            block_q=bq,
            block_k=bk,
            t_kv=tk_pad,
            t_kv_valid=tk,
            causal=causal,
            scale=scale,
            q_offset=q_offset,
            k_offset=k_offset,
        ),
        out_shape=jax.ShapeDtypeStruct((b * h, tq_pad, d), q.dtype),
        grid=(b * h, tq_pad // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, tk_pad, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, tk_pad, d), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
        interpret=interpret,
    )(q3, k3, v3)
    out = out[:, :tq].reshape(b, h, tq, d).transpose(0, 2, 1, 3)
    return out


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9)
)
def _flash_attention_core(
    q, k, v, causal, scale, q_offset, k_offset, block_q, block_k, interpret
):
    return _flash_fwd_impl(
        q, k, v, causal, scale, q_offset, k_offset, block_q, block_k, interpret
    )


def _core_fwd(q, k, v, causal, scale, q_offset, k_offset, block_q, block_k, interpret):
    out = _flash_fwd_impl(
        q, k, v, causal, scale, q_offset, k_offset, block_q, block_k, interpret
    )
    return out, (q, k, v)


def _core_bwd(causal, scale, q_offset, k_offset, block_q, block_k, interpret, res, g):
    q, k, v = res
    b, tq, h, d = q.shape

    def ref(q, k, v):
        def bhd(x):
            return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

        out = attention_with_offsets(
            bhd(q), bhd(k), bhd(v),
            causal=causal, scale=scale,
            q_offset=q_offset, k_offset=k_offset,
        )
        return out.reshape(b, h, tq, d).transpose(0, 2, 1, 3)

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


_flash_attention_core.defvjp(_core_fwd, _core_bwd)


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    scale: float | None = None,
    q_offset: int = 0,
    k_offset: int = 0,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
):
    """Fused attention on (B, Tq, H, D) queries / (B, Tk, H, D) keys-values.

    Same contract as ``attention_reference`` (output for the local queries
    in ``q``'s dtype) plus global ``q_offset``/``k_offset`` positions for
    causal masking of shifted blocks.  ``interpret=None`` auto-selects the
    Pallas interpreter off-TPU so tests run on CPU.
    """
    if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
        raise ValueError(f"expected (B, T, H, D) inputs, got {q.shape}")
    if k.shape != v.shape:
        raise ValueError(f"k/v shapes differ: {k.shape} vs {v.shape}")
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    return _flash_attention_core(
        q, k, v, causal, float(scale), int(q_offset), int(k_offset),
        int(block_q), int(block_k), interpret,
    )
