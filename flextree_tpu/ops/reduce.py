"""Reduction-op registry: which elementwise reductions the framework supports,
over which dtypes, with NumPy and JAX implementations.

The reference supports MPI_SUM over 11 dtypes and MPI_BAND over 8 integer
dtypes, aborting on anything else (``allreduce_over_mpi/mpi_mod.hpp:825-874``,
``handle_reduce``).  We mirror that matrix — translated to TPU-native dtypes
(float64 exists on CPU backends; bfloat16 replaces long double) — and add the
other lattice ops (band/bor/bxor/max/min/prod) that fall out for free, since
our generic reduce path is op-parametric rather than a hand-unrolled switch
per source count (the reference's ``reduce_sum``/``reduce_band`` kernels,
``mpi_mod.hpp:246-660``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import numpy as np

__all__ = ["ReduceOp", "get_op", "SUPPORTED_OPS", "check_dtype"]

_FLOAT_DTYPES = ("float16", "bfloat16", "float32", "float64")
_INT_DTYPES = ("int8", "int16", "int32", "int64", "uint8", "uint16", "uint32", "uint64")
_BOOL_DTYPES = ("bool",)

# The reference's MPI_SUM dtype set (mpi_mod.hpp:827-837) translated to TPU
# dtypes; MPI_BAND's integer set (mpi_mod.hpp:851-858) plus bool.
_SUM_DTYPES = _FLOAT_DTYPES + _INT_DTYPES
_BITWISE_DTYPES = _INT_DTYPES + _BOOL_DTYPES
_ORDER_DTYPES = _FLOAT_DTYPES + _INT_DTYPES


@dataclass(frozen=True)
class ReduceOp:
    """An associative+commutative elementwise reduction.

    ``np_fn``/``jnp_name`` are binary; collectives fold them over peer copies.
    ``identity`` is the neutral element used when padding buffers so that the
    padded tail never corrupts real data.
    """

    name: str
    np_fn: Callable[[np.ndarray, np.ndarray], np.ndarray]
    jnp_name: str  # attribute on jax.numpy, resolved lazily (keep this module JAX-free)
    dtypes: tuple[str, ...]
    identity: Callable[[np.dtype], object]

    def check_dtype(self, dtype) -> None:
        check_dtype(self, dtype)

    def identity_for(self, dtype) -> object:
        return self.identity(np.dtype(dtype))


def _all_ones(dt: np.dtype):
    if dt == np.bool_:
        return True
    return dt.type(~dt.type(0))  # all bits set


def _min_value(dt: np.dtype):
    # np.issubdtype is False for ml_dtypes floats (bfloat16 has kind 'V'),
    # so classify by "not integer/bool" rather than "is np.floating".
    if np.issubdtype(dt, np.integer):
        return np.iinfo(dt).min
    return dt.type(-np.inf)


def _max_value(dt: np.dtype):
    if np.issubdtype(dt, np.integer):
        return np.iinfo(dt).max
    return dt.type(np.inf)


SUPPORTED_OPS: dict[str, ReduceOp] = {
    op.name: op
    for op in [
        ReduceOp("sum", np.add, "add", _SUM_DTYPES, lambda dt: dt.type(0)),
        ReduceOp("prod", np.multiply, "multiply", _SUM_DTYPES, lambda dt: dt.type(1)),
        ReduceOp("max", np.maximum, "maximum", _ORDER_DTYPES, _min_value),
        ReduceOp("min", np.minimum, "minimum", _ORDER_DTYPES, _max_value),
        ReduceOp("band", np.bitwise_and, "bitwise_and", _BITWISE_DTYPES, _all_ones),
        ReduceOp("bor", np.bitwise_or, "bitwise_or", _BITWISE_DTYPES, lambda dt: dt.type(0)),
        ReduceOp("bxor", np.bitwise_xor, "bitwise_xor", _BITWISE_DTYPES, lambda dt: dt.type(0)),
    ]
}


def get_op(op: "str | ReduceOp") -> ReduceOp:
    """Resolve an op name (or pass through a ReduceOp).  Unknown ops raise,
    mirroring the reference's abort on unsupported MPI ops
    (``mpi_mod.hpp:875-877``)."""
    if isinstance(op, ReduceOp):
        return op
    try:
        return SUPPORTED_OPS[op]
    except KeyError:
        raise ValueError(
            f"unsupported reduce op {op!r}; supported: {sorted(SUPPORTED_OPS)}"
        ) from None


def check_dtype(op: ReduceOp, dtype) -> None:
    """Raise if ``dtype`` is outside the op's supported matrix (the analog of
    the reference's per-dtype dispatch aborting, ``mpi_mod.hpp:838-841``)."""
    name = "bfloat16" if "bfloat16" in str(dtype) else np.dtype(dtype).name
    if name not in op.dtypes:
        raise TypeError(f"op {op.name!r} does not support dtype {name}")
