"""Reduction ops: the dtype/op support matrix and kernels."""

from .reduce import ReduceOp, SUPPORTED_OPS, check_dtype, get_op

__all__ = [
    "ReduceOp",
    "SUPPORTED_OPS",
    "check_dtype",
    "get_op",
    "reduce_stacked",
    "reduce_stacked_reference",
    "flash_attention",
    "attention_with_offsets",
]


def __getattr__(name):
    # Lazy: the Pallas kernels pull in JAX; keep the base op registry
    # importable without it (the schedule layer stays JAX-free).
    if name in ("reduce_stacked", "reduce_stacked_reference"):
        from . import pallas_reduce

        return getattr(pallas_reduce, name)
    if name in ("flash_attention", "attention_with_offsets"):
        from . import pallas_attention

        return getattr(pallas_attention, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
