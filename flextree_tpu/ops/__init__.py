"""Reduction ops: the dtype/op support matrix and kernels, plus the wire
codecs for compressed collectives.

The fused paged-decode attention lives in :mod:`.paged_attention` and is
imported by its MODULE path (``from flextree_tpu.ops.paged_attention
import paged_attention, paged_attention_gather, FUSED_DECODE_ATOL``) —
the function shares the submodule's name, so a package-level re-export
would be whichever of the two bound last (import-order dependent, the
``os.path`` problem); the submodule path is the one canonical spelling.
"""

from .quantize import CODECS, Codec, decode_int8, encode_int8, get_codec
from .reduce import ReduceOp, SUPPORTED_OPS, check_dtype, get_op

__all__ = [
    "ReduceOp",
    "SUPPORTED_OPS",
    "check_dtype",
    "get_op",
    "Codec",
    "CODECS",
    "get_codec",
    "encode_int8",
    "decode_int8",
    "reduce_stacked",
    "reduce_stacked_reference",
    "flash_attention",
    "attention_with_offsets",
]


def __getattr__(name):
    # Lazy: the Pallas kernels pull in JAX; keep the base op registry
    # importable without it (the schedule layer stays JAX-free).
    if name in ("reduce_stacked", "reduce_stacked_reference"):
        from . import pallas_reduce

        return getattr(pallas_reduce, name)
    if name in ("flash_attention", "attention_with_offsets"):
        from . import pallas_attention

        return getattr(pallas_attention, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
