"""Reduction ops: the dtype/op support matrix and kernels."""

from .reduce import ReduceOp, SUPPORTED_OPS, check_dtype, get_op

__all__ = ["ReduceOp", "SUPPORTED_OPS", "check_dtype", "get_op"]
