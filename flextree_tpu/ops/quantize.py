"""Wire codecs for compressed collectives: what the bytes on the fabric are.

The reference (and our port, until this module) varies only the tree
*shape*; the payload dtype is whatever the gradient is.  EQuARX
(PAPERS.md, arXiv:2506.17615) shows that quantizing the allreduce payload
inside the collective recovers large wall-clock wins at equal model
quality — the bytes on the wire become a *chosen* quantity, exactly like
the stage widths.  This module defines the codecs; the per-hop application
inside the tree/ring schedules lives in ``parallel/compressed.py``.

Codecs:

- ``f32`` — identity.  ``compressed_allreduce`` routes straight to the
  uncompressed ``allreduce``; bitwise-identical by construction (and by
  property test + compiled-HLO guard in ``tests/test_quantize.py``).
- ``bf16`` — payload cast to bfloat16; the scheduled collectives carry
  (and accumulate in) bf16 on the wire.  Ratio 0.5.
- ``int8`` — block-scaled 8-bit quantization: each ``block_size`` run of
  elements shares one f32 scale ``amax/127``; values are quantized with
  **deterministic stochastic rounding** keyed off the training step
  counter (an integer hash of (element index, step, salt) — no RNG keys,
  no host entropy, nothing the jit-hygiene layer would flag; the same
  step re-traces to the same bits).  Wire payload is int8 plus one f32
  scale per block: ratio ``0.25 + 4/(4*block_size)``.

Stochastic rounding is what makes the quantizer *unbiased*
(``E[decode(encode(x))] = x``), which error feedback turns into exact
long-run gradients (see ``docs/QUANTIZED_COLLECTIVES.md``); keying it off
the step counter keeps the trace pure — the reference point is EF21/EF14
style error feedback, carried in the train state by ``parallel/train.py``.

Error bound (the documented contract the bench driver machine-checks):
one encode of a buffer whose partial sums are bounded by ``A`` has
per-element error ``<= A / 127`` (stochastic rounding error is strictly
less than one quantization step).  A full allreduce over ``n`` ranks
quantizes partial sums bounded by ``n * amax`` once per hop on the
accumulation path, so

    |result - exact| <= hops * n * amax / 127        (int8)
    |result - exact| <= hops * n * amax * 2**-8      (bf16)

with ``hops = num_stages + 1`` for a tree (each phase-1 stage re-encodes
the partial sums; phase 2 encodes the final tile once and forwards it
still-encoded) and ``hops = n`` for the ring — see
:meth:`Codec.error_bound`.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = [
    "Codec",
    "CODECS",
    "get_codec",
    "encode_int8",
    "decode_int8",
    "DEFAULT_BLOCK",
]

#: Elements sharing one int8 scale.  1024 keeps the scale overhead at
#: ~0.4% of payload while the per-block amax stays tight enough that the
#: documented bound is far from the f32 noise floor.
DEFAULT_BLOCK = 1024


def _uniform01(n: int, step, salt: int):
    """Deterministic per-element uniforms in [0, 1): an integer bit-mix of
    (element index, step, salt).  ``step`` may be a traced int scalar (the
    train-state step counter) — everything here is pure jnp, so the same
    (shape, step, salt) re-traces to the same bits on any backend, and
    there is no RNG key threading and no host entropy in the trace."""
    import jax.numpy as jnp
    from jax import lax

    i = lax.iota(jnp.uint32, n)
    s = jnp.asarray(step, jnp.int32).astype(jnp.uint32)
    k = i * np.uint32(0x9E3779B9)
    k = k ^ (s * np.uint32(0x85EBCA6B) + np.uint32((salt * 0xC2B2AE35) & 0xFFFFFFFF))
    # xorshift-multiply finalizer (splitmix-style avalanche)
    k = k ^ (k >> 15)
    k = k * np.uint32(0x2C1B3C6D)
    k = k ^ (k >> 12)
    k = k * np.uint32(0x297A2D39)
    k = k ^ (k >> 15)
    return (k >> 8).astype(jnp.float32) * np.float32(2.0**-24)


def _pad_to_block(v, block: int):
    import jax.numpy as jnp

    pad = (-v.shape[-1]) % block
    if pad:
        width = [(0, 0)] * (v.ndim - 1) + [(0, pad)]
        v = jnp.pad(v, width)
    return v


def encode_int8(v, step=0, *, salt: int = 0, block: int = DEFAULT_BLOCK):
    """Block-scaled int8 encode of ``v`` (..., L) along the last axis.

    Returns ``(q, scales)``: ``q`` int8 of shape (..., ceil(L/B)*B) and
    ``scales`` f32 of shape (..., ceil(L/B)).  The trailing pad (zeros)
    quantizes to 0 exactly, so decode+slice is lossless about the pad.
    Stochastic rounding: ``q = floor(x/scale + u)`` with ``u`` from
    :func:`_uniform01` — unbiased, deterministic in (step, salt).
    """
    import jax.numpy as jnp

    v = _pad_to_block(v, block)
    b = v.reshape(v.shape[:-1] + (-1, block))
    amax = jnp.max(jnp.abs(b), axis=-1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    u = _uniform01(int(np.prod(b.shape)), step, salt).reshape(b.shape)
    q = jnp.floor(b / scale[..., None] + u)
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    return q.reshape(v.shape), scale


def decode_int8(q, scales, length: int | None = None, *, block: int = DEFAULT_BLOCK):
    """Inverse of :func:`encode_int8`; ``length`` slices the block pad off
    the last axis (None keeps the padded length)."""
    import jax.numpy as jnp

    b = q.reshape(q.shape[:-1] + (-1, block)).astype(jnp.float32)
    out = (b * scales[..., None]).reshape(q.shape)
    if length is not None and length != out.shape[-1]:
        out = out[..., :length]
    return out


@dataclass(frozen=True)
class Codec:
    """One wire format for the compressed collectives.

    ``wire_ratio`` is payload wire bytes per f32 input byte (scales
    included for int8) — the factor the cost model multiplies the
    bandwidth term by.  ``hop_cost`` marks codecs that pay a per-hop
    encode/decode pass (priced by ``TpuCostParams.codec_bw_GBps``).
    """

    name: str
    wire_ratio: float
    lossy: bool
    hop_cost: bool  # per-hop encode/decode work on the accumulation path
    block: int = DEFAULT_BLOCK

    def roundtrip(self, x, step=0, *, salt: int = 0):
        """The canonical *local* lossy map ``C(x)`` — decode(encode(x)) on
        the flat buffer.  This is the residual reference for error
        feedback: ``e' = v - C(v)`` (``parallel/train.py``).  For tree
        schedules whose stage-0 tiles are block-aligned, the wire's first
        encode is literally this map (``parallel/compressed.py`` reuses
        salt 0 for the input encode), so the EF telescoping is exact."""
        import jax.numpy as jnp

        if not self.lossy:
            return x
        if self.name == "bf16":
            return x.astype(jnp.bfloat16).astype(x.dtype)
        shape = x.shape
        v = x.reshape(-1).astype(jnp.float32)
        q, s = encode_int8(v, step, salt=salt, block=self.block)
        return decode_int8(q, s, v.shape[0], block=self.block).reshape(shape).astype(x.dtype)

    def wire_bytes(self, n_elems: int) -> int:
        """Exact payload bytes this codec puts on the wire for ``n_elems``
        f32 input elements — int8 pads to the codec block and ships one
        f32 scale per block, so this is what ``wire_ratio`` approximates
        (they converge as ``n_elems`` grows).  The serving migration
        planner prices ship-vs-recompute from this."""
        n = max(int(n_elems), 0)
        if self.name == "f32":
            return 4 * n
        if self.name == "bf16":
            return 2 * n
        blocks = -(-n // self.block) if n else 0
        return blocks * self.block + 4 * blocks

    def hops_for(self, n: int, widths, lonely: int = 0) -> int:
        """Encode events on the accumulation path of one allreduce: each
        phase-1 stage re-encodes partial sums, phase 2 encodes once and
        forwards; the ring re-encodes per fold step; lonely shapes pay the
        buddy fold/restore encodes plus per-stage encodes both phases
        (their prefix-tree stages ride ppermute rings that cannot forward
        encoded data across stage boundaries)."""
        if widths is not None and tuple(widths) == (1,):
            return max(n, 1)  # (n-1) fold hops + 1 phase-2 encode
        k = len(tuple(widths)) if widths is not None else 1
        if lonely:
            return 2 * k + 2  # buddy fold + k RS + k AG + restore
        return k + 1

    def error_bound(self, amax: float, n: int, widths=None, lonely: int = 0) -> float:
        """Documented per-element absolute error bound of one allreduce of
        data with per-rank max |x| <= ``amax`` over ``n`` ranks (see the
        module docstring for the derivation).  0 for the identity codec."""
        if not self.lossy:
            return 0.0
        hops = self.hops_for(n, widths, lonely)
        step_size = 1.0 / 127.0 if self.name == "int8" else 2.0**-8
        return hops * n * float(amax) * step_size


CODECS: dict[str, Codec] = {
    "f32": Codec("f32", wire_ratio=1.0, lossy=False, hop_cost=False),
    "bf16": Codec("bf16", wire_ratio=0.5, lossy=True, hop_cost=False),
    "int8": Codec(
        "int8",
        wire_ratio=0.25 + 4.0 / (4.0 * DEFAULT_BLOCK),
        lossy=True,
        hop_cost=True,
    ),
}


def get_codec(codec) -> Codec:
    """Resolve a codec name (or pass through a Codec).  Unknown names
    raise, mirroring ``ops.reduce.get_op``."""
    if isinstance(codec, Codec):
        return codec
    if codec is None:
        return CODECS["f32"]
    try:
        return CODECS[codec]
    except KeyError:
        raise ValueError(
            f"unsupported codec {codec!r}; supported: {sorted(CODECS)}"
        ) from None
