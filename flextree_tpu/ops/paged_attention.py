"""Fused paged-attention decode: stream K/V blocks, never gather the row.

The serving decode round (``serving/kv_cache.py``) historically ran
gather → ragged decode → scatter: every step materialized each slot's
block table into a contiguous ``(S, P·bs, H, Dh)`` K/V view (~5 MB of
copies per round at the bench config — named in docs/SERVING.md as the
single biggest paged overhead), spliced the new token's K/V into it, and
only then ran attention over the full padded width.  This module removes
the materialization: attention walks the block table directly with an
online-softmax accumulator (the same running max / normalizer scheme as
``ops/pallas_attention._kv_update`` and the ring-attention fold), reading
each K/V block from the pool exactly once and stopping at the batch's
causal frontier — blocks past ``max(lengths)`` are never touched, where
the gather path always paid for the full table width.

Two implementations, one contract:

- ``impl="jnp"`` — a pure-JAX block-streaming twin: a ``fori_loop`` whose
  trip count is the *runtime* block frontier walks ``block_chunk`` table
  columns per step, batched over all S slots.  This is the production
  path on the CPU backend.  ``block_chunk=1`` measured fastest there
  (1.5x over the gather round at the bench config's mid-run lengths —
  wider chunks gather more masked positions back in and lost the win);
  the knob exists because the trade flips on hardware where fewer,
  larger contractions beat tighter masking.
- ``impl="pallas"`` — a Pallas kernel, one grid step per slot, same
  accumulation order; ``interpret=None`` auto-selects the interpreter
  off-TPU exactly like ``flash_attention`` does.  On CPU it validates the
  kernel's numerics (the interpreter emulates, so its *timings* are a
  floor, not the TPU win).

``paged_attention_gather`` is the retained gather-materialize oracle —
the exact computation the historical decode step ran, and the thing
proven **bitwise** against the contiguous-cache ``generate``.  The fused
paths change only floating-point summation order (online softmax folds
block by block; the oracle reduces the whole row at once), so they are
gated against the oracle within a pinned tolerance
(``FUSED_DECODE_ATOL`` — enforced per rep in ``tools/bench_paged.py``
and pinned in ``tests/test_paged_attention.py``), not bitwise.

Masking mirrors ``models.generate.cached_attention``: pool positions at
or past a row's ``length`` are driven to ``-1e30`` *before* the running
max and their probabilities zeroed after it, so whatever an unwritten or
null-block position holds — including deliberately poisoned values —
contributes exactly ``0.0`` to the f32 accumulator.  The new token's K/V
(position ``length``, which the gather path spliced into the view) is
folded as a final always-visible online-softmax step instead.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

__all__ = [
    "FUSED_DECODE_ATOL",
    "paged_attention",
    "paged_attention_gather",
]

_NEG_INF = -1e30

#: Pinned fused-vs-gather tolerance on the attention output (f32 compute):
#: the two paths differ only in summation order, and the observed gap on
#: the bench config is ~1e-7; the pin leaves two orders of headroom while
#: still catching any real masking/indexing defect (which shows up as
#: O(1) differences, not O(1e-5)).
FUSED_DECODE_ATOL = 2e-5


def _check_shapes(q, k_new, v_new, k_pool, v_pool, tables, lengths):
    if q.ndim != 3:
        raise ValueError(f"expected (S, H, D) queries, got {q.shape}")
    if k_new.shape != q.shape or v_new.shape != q.shape:
        raise ValueError(
            f"new-token K/V must match q's shape {q.shape}, got "
            f"{k_new.shape} / {v_new.shape}"
        )
    if k_pool.ndim != 4 or k_pool.shape != v_pool.shape:
        raise ValueError(
            f"expected matching (N, bs, H, D) pools, got {k_pool.shape} "
            f"vs {v_pool.shape}"
        )
    if k_pool.shape[2:] != q.shape[1:]:
        raise ValueError(
            f"pool head/dim {k_pool.shape[2:]} != query {q.shape[1:]}"
        )
    if tables.ndim != 2 or tables.shape[0] != q.shape[0]:
        raise ValueError(f"expected (S, P) tables, got {tables.shape}")
    if lengths.shape != (q.shape[0],):
        raise ValueError(f"expected (S,) lengths, got {lengths.shape}")


def paged_attention_gather(q, k_new, v_new, k_pool, v_pool, tables, lengths):
    """The gather-materialize oracle: gather every table block into a
    contiguous ``(S, P·bs, H, D)`` view, splice the new token's K/V at
    each row's ``length``, and attend with the full-row softmax — exactly
    the historical decode-step computation (``cached_attention`` on the
    gathered view), kept as THE correctness reference: this path is the
    one proven bitwise against the contiguous-cache ``generate``."""
    from ..models.generate import cached_attention

    _check_shapes(q, k_new, v_new, k_pool, v_pool, tables, lengths)
    s = q.shape[0]
    upd = jax.vmap(
        lambda c, u, p: lax.dynamic_update_slice_in_dim(c, u, p, axis=0)
    )
    kc = upd(k_pool[tables].reshape(s, -1, *k_pool.shape[2:]),
             k_new[:, None], lengths)
    vc = upd(v_pool[tables].reshape(s, -1, *v_pool.shape[2:]),
             v_new[:, None], lengths)
    positions = lengths[:, None].astype(jnp.int32)
    return cached_attention(q[:, None], kc, vc, positions)[:, 0]


# ------------------------------------------------------------ jnp streaming


def _stream_jnp(q, k_new, v_new, k_pool, v_pool, tables, lengths, scale,
                block_chunk):
    s, h, d = q.shape
    bs = k_pool.shape[1]
    p = tables.shape[1]
    cb = max(1, min(int(block_chunk), p))
    # pad the table width to a chunk multiple with null blocks: the pad
    # columns gather block 0, whose positions sit past every row's causal
    # bound and mask to exactly zero weight
    p_pad = -(-p // cb) * cb
    if p_pad != p:
        tables = jnp.pad(tables, ((0, 0), (0, p_pad - p)))
    # runtime frontier: blocks holding positions < max(lengths); the loop
    # never touches table columns past it (the gather oracle always pays
    # for all P — this bound is the streamed path's algorithmic win)
    frontier = (jnp.max(lengths) + bs - 1) // bs
    n_steps = (frontier + cb - 1) // cb

    lengths_b = lengths[:, None]  # (S, 1)
    m0 = jnp.full((s, h), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((s, h), jnp.float32)
    acc0 = jnp.zeros((s, h, d), jnp.float32)

    def body(i, carry):
        m, l, acc = carry
        tb = lax.dynamic_slice_in_dim(tables, i * cb, cb, axis=1)  # (S, cb)
        kb = k_pool[tb].reshape(s, cb * bs, h, d)
        vb = v_pool[tb].reshape(s, cb * bs, h, d)
        # einsum in the compute dtype then f32, mirroring cached_attention
        sc = jnp.einsum("shd,sbhd->shb", q, kb).astype(jnp.float32) * scale
        kpos = i * cb * bs + jnp.arange(cb * bs)
        valid = kpos[None, :] < lengths_b  # (S, cb*bs)
        sc = jnp.where(valid[:, None, :], sc, _NEG_INF)
        m_new = jnp.maximum(m, sc.max(axis=-1))
        pr = jnp.exp(sc - m_new[..., None])
        # explicit zero: when a row's m is still the -1e30 sentinel (no
        # visible position yet) exp(0)=1 would leak masked content
        pr = jnp.where(valid[:, None, :], pr, 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + pr.sum(axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "shb,sbhd->shd", pr, vb.astype(jnp.float32)
        )
        return m_new, l, acc

    m, l, acc = lax.fori_loop(0, n_steps, body, (m0, l0, acc0))

    # the new token's K/V — position `length`, always visible to itself
    s_new = jnp.einsum("shd,shd->sh", q, k_new).astype(jnp.float32) * scale
    m_fin = jnp.maximum(m, s_new)
    p_new = jnp.exp(s_new - m_fin)
    corr = jnp.exp(m - m_fin)
    l = l * corr + p_new
    acc = acc * corr[..., None] + p_new[..., None] * v_new.astype(jnp.float32)
    return (acc / l[..., None]).astype(q.dtype)


# ------------------------------------------------------------ pallas kernel


def _paged_kernel(q_ref, kn_ref, vn_ref, tab_ref, len_ref, kp_ref, vp_ref,
                  o_ref, *, bs: int, scale: float):
    """One grid step = one slot: walk the row's block table with the
    online-softmax accumulator, then fold the new token's K/V.  Same
    accumulation order as ``_stream_jnp`` at ``block_chunk=1``."""
    q = q_ref[0]  # (H, D) native dtype — the score matmul stays native
    h, d = q.shape
    length = len_ref[0]
    nb = (length + bs - 1) // bs  # blocks holding positions < length

    m0 = jnp.full((h, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((h, 1), jnp.float32)
    acc0 = jnp.zeros((h, d), jnp.float32)

    def body(p_i, carry):
        m, l, acc = carry
        blk = tab_ref[0, p_i]
        kb = kp_ref[blk]  # (bs, H, D)
        vb = vp_ref[blk]
        sc = jnp.einsum("hd,bhd->hb", q, kb).astype(jnp.float32) * scale
        kpos = p_i * bs + jnp.arange(bs)
        valid = (kpos < length)[None, :]  # (1, bs)
        sc = jnp.where(valid, sc, _NEG_INF)
        m_new = jnp.maximum(m, sc.max(axis=-1, keepdims=True))
        pr = jnp.where(valid, jnp.exp(sc - m_new), 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + pr.sum(axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum(
            "hb,bhd->hd", pr, vb.astype(jnp.float32)
        )
        return m_new, l, acc

    m, l, acc = lax.fori_loop(0, nb, body, (m0, l0, acc0))

    kn = kn_ref[0]
    vn = vn_ref[0]
    s_new = jnp.einsum("hd,hd->h", q, kn)[:, None].astype(jnp.float32) * scale
    m_fin = jnp.maximum(m, s_new)
    p_new = jnp.exp(s_new - m_fin)
    corr = jnp.exp(m - m_fin)
    l = l * corr + p_new
    acc = acc * corr + p_new * vn.astype(jnp.float32)
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def _stream_pallas(q, k_new, v_new, k_pool, v_pool, tables, lengths, scale,
                   interpret):
    s, h, d = q.shape
    n, bs = k_pool.shape[:2]
    p = tables.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return pl.pallas_call(
        functools.partial(_paged_kernel, bs=bs, scale=scale),
        out_shape=jax.ShapeDtypeStruct((s, h, d), q.dtype),
        grid=(s,),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda i: (i, 0, 0)),   # q row
            pl.BlockSpec((1, h, d), lambda i: (i, 0, 0)),   # new k
            pl.BlockSpec((1, h, d), lambda i: (i, 0, 0)),   # new v
            pl.BlockSpec((1, p), lambda i: (i, 0)),         # table row
            pl.BlockSpec((1,), lambda i: (i,)),             # length
            pl.BlockSpec((n, bs, h, d), lambda i: (0, 0, 0, 0)),  # k pool
            pl.BlockSpec((n, bs, h, d), lambda i: (0, 0, 0, 0)),  # v pool
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda i: (i, 0, 0)),
        interpret=interpret,
    )(q, k_new, v_new, tables.astype(jnp.int32), lengths.astype(jnp.int32),
      k_pool, v_pool)


def paged_attention(
    q,
    k_new,
    v_new,
    k_pool,
    v_pool,
    tables,
    lengths,
    *,
    scale: float | None = None,
    impl: str = "jnp",
    interpret: bool | None = None,
    block_chunk: int = 1,
):
    """Fused paged decode attention for one token per slot.

    ``q`` / ``k_new`` / ``v_new``: (S, H, D) — the decode step's query and
    the new token's K/V, already RoPE'd at each row's position.
    ``k_pool`` / ``v_pool``: (N, bs, H, D) per-layer pools; ``tables``:
    (S, P) int32 block ids; ``lengths``: (S,) int32 cache positions
    already written per row, each ``< P*bs`` (a row AT the table's
    capacity has no position left to decode into — the serving layer
    never reaches it, and the gather oracle's splice clamps there).
    Returns (S, H, D) in ``q``'s dtype —
    attention over pool positions ``< length`` plus the new token at
    position ``length``, equal to :func:`paged_attention_gather` within
    :data:`FUSED_DECODE_ATOL` (summation order is the only difference).

    ``impl="jnp"`` is the batched block-streaming path (``block_chunk``
    table columns per loop step); ``impl="pallas"`` runs the kernel
    (interpreted off-TPU, like ``flash_attention``'s ``interpret=``
    plumbing).
    """
    _check_shapes(q, k_new, v_new, k_pool, v_pool, tables, lengths)
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    tables = jnp.asarray(tables, jnp.int32)
    lengths = jnp.asarray(lengths, jnp.int32)
    if impl == "jnp":
        return _stream_jnp(q, k_new, v_new, k_pool, v_pool, tables, lengths,
                           float(scale), block_chunk)
    if impl == "pallas":
        return _stream_pallas(q, k_new, v_new, k_pool, v_pool, tables,
                              lengths, float(scale), interpret)
    raise ValueError(f"unknown paged-attention impl {impl!r}")
