"""Pallas TPU kernel: fold W source buffers into one, elementwise.

The TPU rebuild of the reference's local reduction kernels ``reduce_sum`` /
``reduce_band`` (``allreduce_over_mpi/mpi_mod.hpp:246-660``): there, an
OpenMP ``parallel for simd`` over up to 20 sources with a hand-unrolled
switch per source count; here, a single VPU kernel tiled over the payload,
streaming ``(W, rows_tile, 128)`` blocks HBM->VMEM and writing the reduced
``(rows_tile, 128)`` tile back.  XLA fuses this pattern well on its own —
the kernel exists because the local reduce is the allreduce's only compute
(SURVEY §3.2 "HOT LOOP") and a hand-tiled kernel both pins the layout and
gives the benchmark a deterministic HBM-bandwidth probe on one chip.

The op set mirrors the ``handle_reduce`` dispatch (``mpi_mod.hpp:825-874``):
sum + the bitwise/lattice family, validated against the same dtype matrix.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .reduce import get_op

__all__ = ["reduce_stacked", "reduce_stacked_reference"]

_LANE = 128


def _kernel(x_ref, o_ref, *, w: int, jnp_name: str):
    if jnp_name == "add":
        # jnp.sum over the leading (source) axis vectorizes cleanly
        o_ref[:] = jnp.sum(x_ref[:], axis=0)
    else:
        fn = getattr(jnp, jnp_name)
        acc = x_ref[0]
        for j in range(1, w):
            acc = fn(acc, x_ref[j])
        o_ref[:] = acc


def reduce_stacked_reference(x: jax.Array, op="sum") -> jax.Array:
    """Pure-jnp oracle: fold ``x[(W, L)]`` over axis 0 with ``op``."""
    rop = get_op(op)
    fn = getattr(jnp, rop.jnp_name)
    acc = x[0]
    for j in range(1, x.shape[0]):
        acc = fn(acc, x[j])
    return acc


@functools.partial(jax.jit, static_argnames=("op", "rows_tile", "interpret"))
def reduce_stacked(
    x: jax.Array,
    op: str = "sum",
    rows_tile: int = 512,
    interpret: bool | None = None,
) -> jax.Array:
    """Reduce ``x`` of shape ``(W, L)`` over axis 0 -> ``(L,)`` on the VPU.

    ``L`` is padded internally to a multiple of ``rows_tile * 128`` with the
    op identity (like the schedule layer pads to ``data_size_aligned``,
    ``mpi_mod.hpp:232``).  ``interpret=None`` auto-selects the Pallas
    interpreter off-TPU so tests run on CPU.
    """
    from jax.experimental import pallas as pl

    rop = get_op(op)
    rop.check_dtype(x.dtype)
    if x.ndim != 2:
        raise ValueError(f"expected (num_sources, length), got {x.shape}")
    w, length = x.shape
    if w == 1:
        return x[0]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    chunk = rows_tile * _LANE
    padded = -(-length // chunk) * chunk
    if padded != length:
        pad_val = rop.identity_for(x.dtype)
        x = jnp.pad(x, ((0, 0), (0, padded - length)), constant_values=pad_val)
    rows = padded // _LANE
    x3 = x.reshape(w, rows, _LANE)

    out = pl.pallas_call(
        functools.partial(_kernel, w=w, jnp_name=rop.jnp_name),
        out_shape=jax.ShapeDtypeStruct((rows, _LANE), x.dtype),
        grid=(rows // rows_tile,),
        in_specs=[
            pl.BlockSpec((w, rows_tile, _LANE), lambda i: (0, i, 0)),
        ],
        out_specs=pl.BlockSpec((rows_tile, _LANE), lambda i: (i, 0)),
        interpret=interpret,
    )(x3)
    return out.reshape(padded)[:length]
