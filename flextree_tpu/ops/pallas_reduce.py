"""Pallas TPU kernel: fold W source buffers into one, elementwise.

The TPU rebuild of the reference's local reduction kernels ``reduce_sum`` /
``reduce_band`` (``allreduce_over_mpi/mpi_mod.hpp:246-660``): there, an
OpenMP ``parallel for simd`` over up to 20 sources with a hand-unrolled
switch per source count; here, a single VPU kernel tiled over the payload,
streaming one native 2D ``(rows_tile, 128)`` tile per source HBM->VMEM and
folding it into a VMEM-resident accumulator that is written back once per
output tile.  XLA fuses this pattern well on its own —
the kernel exists because the local reduce is the allreduce's only compute
(SURVEY §3.2 "HOT LOOP") and a hand-tiled kernel both pins the layout and
gives the benchmark a deterministic HBM-bandwidth probe on one chip.

The op set mirrors the ``handle_reduce`` dispatch (``mpi_mod.hpp:825-874``):
sum + the bitwise/lattice family, validated against the same dtype matrix.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .reduce import get_op

__all__ = ["reduce_stacked", "reduce_stacked_reference"]

_LANE = 128


def _kernel(x_ref, o_ref, *, jnp_name: str, sources_tile: int):
    # Grid is (row_tiles, source_groups) with the source axis fastest; the
    # output block's index map ignores the source axis, so Pallas keeps the
    # tile resident in VMEM across all accumulation steps and writes it
    # back to HBM once.  Each step streams ``sources_tile`` native 2D
    # (rows_tile, 128) tiles (one 3D block) and folds them with a statically
    # unrolled tree before touching the accumulator — fewer grid steps and
    # larger DMAs per step than the sources_tile=1 layout, same (W+1)·L
    # traffic.
    from jax.experimental import pallas as pl

    fn = getattr(jnp, jnp_name)
    j = pl.program_id(1)
    vals = [x_ref[t] for t in range(sources_tile)]
    while len(vals) > 1:  # pairwise: dependency depth log2(st), not st-1
        vals = [
            fn(vals[t], vals[t + 1]) if t + 1 < len(vals) else vals[t]
            for t in range(0, len(vals), 2)
        ]
    acc = vals[0]

    @pl.when(j == 0)
    def _init():
        o_ref[:] = acc

    @pl.when(j != 0)
    def _fold():
        o_ref[:] = fn(o_ref[:], acc)


def reduce_stacked_reference(x: jax.Array, op="sum") -> jax.Array:
    """Pure-jnp oracle: fold ``x[(W, L)]`` over axis 0 with ``op``."""
    rop = get_op(op)
    fn = getattr(jnp, rop.jnp_name)
    acc = x[0]
    for j in range(1, x.shape[0]):
        acc = fn(acc, x[j])
    return acc


@functools.partial(
    jax.jit, static_argnames=("op", "rows_tile", "sources_tile", "interpret")
)
def reduce_stacked(
    x: jax.Array,
    op: str = "sum",
    rows_tile: int = 512,
    sources_tile: int = 1,
    interpret: bool | None = None,
) -> jax.Array:
    """Reduce ``x`` of shape ``(W, L)`` over axis 0 -> ``(L,)`` on the VPU.

    ``L`` is padded internally to a multiple of ``rows_tile * 128`` with the
    op identity (like the schedule layer pads to ``data_size_aligned``,
    ``mpi_mod.hpp:232``).  ``interpret=None`` auto-selects the Pallas
    interpreter off-TPU so tests run on CPU.

    ``sources_tile`` folds that many sources per grid step (a 3D input
    block) — a DMA-granularity/step-count tuning knob with identical
    traffic and results equal up to f32 reassociation (the grouped fold
    changes the reduction order; exact for the bitwise/lattice ops);
    silently clamped to ``gcd(sources_tile, W)`` so any W stays valid.
    """
    from jax.experimental import pallas as pl

    rop = get_op(op)
    rop.check_dtype(x.dtype)
    if x.ndim != 2:
        raise ValueError(f"expected (num_sources, length), got {x.shape}")
    w, length = x.shape
    if w == 1:
        return x[0]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    st = np.gcd(int(sources_tile), w) if sources_tile else 1

    chunk = rows_tile * _LANE
    padded = -(-length // chunk) * chunk
    if padded != length:
        pad_val = rop.identity_for(x.dtype)
        x = jnp.pad(x, ((0, 0), (0, padded - length)), constant_values=pad_val)
    rows = padded // _LANE
    x3 = x.reshape(w, rows, _LANE)

    out = pl.pallas_call(
        functools.partial(_kernel, jnp_name=rop.jnp_name, sources_tile=st),
        out_shape=jax.ShapeDtypeStruct((rows, _LANE), x.dtype),
        grid=(rows // rows_tile, w // st),
        in_specs=[
            pl.BlockSpec((st, rows_tile, _LANE), lambda i, j: (j, i, 0)),
        ],
        out_specs=pl.BlockSpec((rows_tile, _LANE), lambda i, j: (i, 0)),
        interpret=interpret,
    )(x3)
    return out.reshape(padded)[:length]
