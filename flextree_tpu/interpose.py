"""Drop-in interposition: route ``jax.lax.psum`` through FlexTree.

The reference's integration API is symbol shadowing: without
``STANDALONE_TEST``, ``mpi_mod.hpp:1167-1171`` defines a file-static
``MPI_Allreduce`` so any translation unit that includes the header silently
runs FlexTree instead of libmpi — zero host-code changes.  The TPU-native
analog shadows the public ``jax.lax.psum`` wrapper: inside the interposition
scope, user code (or a host framework's gradient sync) calling
``lax.psum(x, axis)`` gets the topology-parameterized hierarchical allreduce,
with the stage widths read from the ``FT_TOPO`` environment variable exactly
like the reference runtime (``mpi_mod.hpp:882-929``) unless given explicitly.

Scope and fallbacks (mirroring the reference's entry-point routing,
``mpi_mod.hpp:1181-1215``):

- single named axis, sum over arrays -> FlexTree tree/ring per topology;
- ``axis_index_groups``, multi-axis tuples, or anything else we don't
  implement -> the original ``psum`` (the reference similarly leaves
  non-SUM/BAND ops to the real MPI);
- world size 1 -> identity fast path (handled inside ``allreduce``).

Coverage (the analog of the reference's whole-TU shadowing): beyond the
``jax.lax.psum`` attribute, ``install`` rewrites *aliases* — any non-JAX
module whose namespace holds the original ``psum`` function object (i.e.
code that did ``from jax.lax import psum`` before install) gets the shim
too, and ``uninstall`` restores every site.  That closes the
early-import miss; what remains out of scope is code that bound the
``psum_p`` primitive directly — exactly as the reference's TU shadowing
never caught callers invoking the PMPI_ layer.  JAX-internal modules are
deliberately not alias-patched (grad/batching machinery must keep native
semantics), so interposition cannot recurse or corrupt unrelated tracing.
The patch is process-global while installed (like the reference's
link-time shadowing is TU-global); ``interposed()`` gives a scoped
context manager, and ``install()``/``uninstall()`` the explicit global
switch.
"""

from __future__ import annotations

import contextlib
import sys
import threading

import jax

from .parallel.allreduce import allreduce

__all__ = ["interposed", "install", "uninstall", "is_installed"]

_lock = threading.Lock()
_original_psum = None  # non-None iff installed
_patched_sites: list = []  # [(module, attr_name)] alias sites rewritten


def _make_psum(topo, min_size: int):
    import jax.lax as _lax  # resolve the original once, at install time

    orig = _lax.psum

    def flextree_psum(x, axis_name, *, axis_index_groups=None):
        if axis_index_groups is not None or not isinstance(axis_name, str):
            return orig(x, axis_name, axis_index_groups=axis_index_groups)

        def one(leaf):
            leaf = jax.numpy.asarray(leaf)
            if leaf.size < min_size:
                return orig(leaf, axis_name)
            return allreduce(leaf, axis_name, topo=topo, op="sum")

        return jax.tree.map(one, x)

    flextree_psum._flextree_interposer = True  # noqa: SLF001 (introspection tag)
    flextree_psum._flextree_original = orig
    return flextree_psum


def _alias_sites(orig) -> list:
    """(module, attr) pairs outside jax/flextree holding ``orig`` itself —
    the ``from jax.lax import psum`` aliases the attribute patch would miss."""
    sites = []
    for name, mod in list(sys.modules.items()):
        if mod is None:
            continue
        if name == "jax" or name.startswith("jax.") or name.startswith("flextree_tpu"):
            continue  # JAX internals keep native semantics; we never self-patch
        try:
            ns = vars(mod)
        except TypeError:
            continue
        for attr, val in list(ns.items()):
            if val is orig:
                sites.append((mod, attr))
    return sites


def install(topo=None, *, min_size: int = 0, patch_aliases: bool = True) -> None:
    """Globally shadow ``jax.lax.psum`` with the FlexTree allreduce.

    ``topo``: anything ``Topology.resolve`` accepts (None -> ``FT_TOPO`` env
    at call time, else flat).  ``min_size``: leaves smaller than this many
    elements keep the native psum (scalars like loss aggregation gain
    nothing from a hierarchical schedule).  ``patch_aliases``: also rewrite
    ``from jax.lax import psum`` aliases in already-imported user modules
    (see module docstring).
    """
    global _original_psum
    with _lock:
        if _original_psum is not None:
            raise RuntimeError("FlexTree interposer is already installed")
        shim = _make_psum(topo, min_size)
        _original_psum = shim._flextree_original
        jax.lax.psum = shim
        if patch_aliases:
            for mod, attr in _alias_sites(_original_psum):
                setattr(mod, attr, shim)
                _patched_sites.append((mod, attr))


def uninstall() -> None:
    """Restore the native ``jax.lax.psum`` (and every patched alias site)."""
    global _original_psum
    with _lock:
        if _original_psum is None:
            raise RuntimeError("FlexTree interposer is not installed")
        jax.lax.psum = _original_psum
        while _patched_sites:
            mod, attr = _patched_sites.pop()
            setattr(mod, attr, _original_psum)
        _original_psum = None


def is_installed() -> bool:
    return _original_psum is not None


@contextlib.contextmanager
def interposed(topo=None, *, min_size: int = 0):
    """Scoped interposition: ``with interposed(topo="4,2"): ...``.

    Functions *traced* inside the scope bake in the FlexTree lowering (XLA
    compiles what was traced), so a jitted function first called inside the
    scope keeps FlexTree semantics for its cached executable — the same
    "whoever included the header got FlexTree forever" persistence as the
    reference's shadowing, made explicit.
    """
    install(topo, min_size=min_size)
    try:
        yield
    finally:
        uninstall()
