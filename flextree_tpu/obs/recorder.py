"""Per-rank flight recorder: bounded ring buffer + JSONL spill + dumps.

Design constraints, in order:

1. **Cheap when off.**  :func:`record_event` is the library-wide
   instrumentation call; with no recorder installed it is one module
   attribute read and a ``None`` check — the train step, the serving
   round, and the collectives pay nothing until a run opts in.
2. **Cheap when on.**  ``record`` appends a small dict to a
   ``deque(maxlen=capacity)`` (bounded memory, O(1), GIL-atomic) and
   stages the serialized line into a write buffer.  The file is touched
   only when the buffer reaches ``spill_every`` events or a *flush kind*
   (``step_end``, ``dump`` …) arrives — a flush is a buffered write +
   ``flush()`` to the OS page cache, never an fsync.
3. **Forensics survive the process.**  Every event is eventually spilled
   to the rank's append-only JSONL file in ``seq`` order, so a
   SIGKILL'd rank leaves its record up to its last flush (per-step,
   since ``step_end`` flushes).  The soft failure paths — watchdog
   timeout, NaN rewind, shrink-on-peer-death, SIGTERM preemption,
   serving strike-out — additionally write an explicit **dump**: a
   ``dump`` marker event plus a sidecar ``*.dump.json`` carrying the
   reason and the ring's last events, the "what happened in the 300 ms
   before" record the postmortem opens first.

The module-level *current recorder* (install with
:func:`flight_recorder`) is what instrumentation sites talk to; the
companion :class:`~flextree_tpu.obs.metrics.MetricsRegistry` rides the
same installation so counters/histograms land next to the events.
Timestamps are wall time (``_wall``, injectable like
``runtime.supervisor._wall``) because the merger correlates events
*across processes* — a monotonic clock has no cross-process epoch.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import signal
import threading
import time
from collections import deque

from .metrics import MetricsRegistry

__all__ = [
    "EVENT_FILE_FMT",
    "DUMP_FILE_FMT",
    "FLUSH_KINDS",
    "FlightRecorder",
    "flight_recorder",
    "current_recorder",
    "record_event",
    "dump_current",
    "get_registry",
    "install_signal_dump",
]

# injection point for tests (patch this, not time.time)
_wall = time.time

EVENT_FILE_FMT = "flight_{rank:05d}.jsonl"
DUMP_FILE_FMT = "flight_{rank:05d}.dump.json"

#: Event kinds that force the write buffer to disk when recorded: the
#: step boundary (per-step durability — a SIGKILL loses at most the
#: current step) and every failure-path marker.
FLUSH_KINDS = frozenset(
    {
        "step_end",
        "dump",
        "shrink",
        "watchdog_timeout",
        "nan_rewind",
        "preempt",
        "fit_end",
        "drain",
    }
)


class FlightRecorder:
    """One rank's event record.  ``dir=None`` keeps it memory-only (the
    ring still serves ``dump``-style introspection in tests)."""

    def __init__(
        self,
        dir: str | os.PathLike | None = None,
        rank: int = 0,
        *,
        capacity: int = 4096,
        spill_every: int = 64,
        source: str = "train",
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.dir = os.fspath(dir) if dir is not None else None
        self.rank = int(rank)
        self.capacity = int(capacity)
        self.spill_every = max(1, int(spill_every))
        self.source = source
        self.events: deque = deque(maxlen=capacity)  # guarded-by: _lock
        self.recorded = 0  # guarded-by: _lock
        self.dumps = 0  # guarded-by: _lock
        # batches dropped on write/flush failure
        self.spill_errors = 0  # guarded-by: _lock
        self._seq = itertools.count()
        self._pending: list[str] = []  # guarded-by: _lock
        self._lock = threading.Lock()
        self._fh = None  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        if self.dir is not None:
            os.makedirs(self.dir, exist_ok=True)
            self._fh = open(  # noqa: SIM115 — held for the recorder's life
                self.event_path, "a", encoding="utf-8"
            )

    # ---- paths -------------------------------------------------------------

    @property
    def event_path(self) -> str | None:
        if self.dir is None:
            return None
        return os.path.join(self.dir, EVENT_FILE_FMT.format(rank=self.rank))

    @property
    def dump_path(self) -> str | None:
        if self.dir is None:
            return None
        return os.path.join(self.dir, DUMP_FILE_FMT.format(rank=self.rank))

    # ---- the hot path ------------------------------------------------------

    def record(self, kind: str, **fields) -> dict:
        """Record one structured event; returns it (tests read it back).

        Thread-safe: instrumentation sites include daemon threads (the
        heartbeat loop) next to the step loop.  ``fields`` must be
        JSON-serializable — the recorder serializes eagerly so a
        mutated-later dict can't rewrite history.
        """
        with self._lock:
            return self._record_locked(kind, fields)

    def _record_locked(self, kind: str, fields: dict) -> dict:
        # seq assignment, ring append and spill staging share the lock
        # so the file's line order IS seq order even with the heartbeat
        # daemon racing the step loop
        ev = {
            "ts": _wall(),
            "rank": self.rank,
            "src": self.source,
            "seq": next(self._seq),
            "kind": kind,
        }
        ev.update(fields)
        self.events.append(ev)
        self.recorded += 1
        if self._fh is not None and not self._closed:
            self._pending.append(json.dumps(ev, sort_keys=True, default=str))
            if len(self._pending) >= self.spill_every or kind in FLUSH_KINDS:
                self._spill_locked()
        return ev

    def _spill_locked(self) -> None:
        if not self._pending or self._fh is None:
            return
        try:
            self._fh.write("\n".join(self._pending) + "\n")
            self._fh.flush()
        except OSError:
            # obs must never take down the run it observes.  The batch
            # may have PARTIALLY landed (buffered write succeeded, flush
            # failed) — retrying it would duplicate lines in the record,
            # which corrupts the forensic stream worse than a counted
            # gap: drop the batch (the events stay in the ring for a
            # later dump) and account for it.
            self.spill_errors += 1
        self._pending.clear()

    def flush(self) -> None:
        with self._lock:
            self._spill_locked()

    # ---- failure paths -----------------------------------------------------

    def dump(self, reason: str, **fields) -> str | None:
        """The guaranteed-on-failure record: a ``dump`` marker event
        (flushed with everything before it) plus a sidecar JSON carrying
        the ring's last events.  Returns the sidecar path (None when
        memory-only).  Idempotent-safe: later dumps overwrite the
        sidecar — the newest failure context wins — while every marker
        event stays in the JSONL stream."""
        with self._lock:
            payload = self._dump_payload_locked(reason, fields)
        return self._write_dump(payload)

    def dump_nonblocking(self, reason: str, **fields) -> str | None:
        """Signal-handler-safe dump: a handler runs ON the thread it
        interrupted, so blocking on the recorder lock when that frame
        already holds it is a permanent deadlock.  Try the lock; if the
        interrupted frame holds it (a microseconds-wide window around
        each record), skip the dump rather than wedge the process the
        handler exists to evidence.  Returns None on skip/memory-only."""
        if not self._lock.acquire(blocking=False):
            return None
        try:
            payload = self._dump_payload_locked(reason, fields)
        finally:
            self._lock.release()
        return self._write_dump(payload)

    def _dump_payload_locked(self, reason: str, fields: dict) -> dict:
        self._record_locked("dump", {"reason": reason, **fields})
        self._spill_locked()  # the marker and everything before it
        self.dumps += 1
        payload = {
            "rank": self.rank,
            "src": self.source,
            "reason": reason,
            "ts": _wall(),
            "recorded": self.recorded,
            "events": list(self.events),
        }
        payload.update(fields)
        return payload

    def _write_dump(self, payload: dict) -> str | None:
        if self.dir is None:
            return None
        tmp = self.dump_path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f, sort_keys=True, default=str)
            os.replace(tmp, self.dump_path)
        except OSError:
            with contextlib.suppress(OSError):
                os.unlink(tmp)
            return None
        return self.dump_path

    def close(self) -> None:
        with self._lock:
            self._spill_locked()
            if self._fh is not None:
                with contextlib.suppress(OSError):
                    self._fh.close()
            self._closed = True
            self._fh = None

    def __enter__(self) -> "FlightRecorder":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---- the ambient (module-level) recorder ---------------------------------
#
# Instrumentation sites call record_event()/get_registry() against these;
# both are None until a run installs a recorder, so the check is one
# global read.  Installation nests (the inner recorder wins, the outer is
# restored on exit) — the same shape as profiling.span_ledger.

_CURRENT: FlightRecorder | None = None
_CURRENT_REGISTRY: MetricsRegistry | None = None


def current_recorder() -> FlightRecorder | None:
    return _CURRENT


def get_registry() -> MetricsRegistry | None:
    """The ambient metrics registry (installed with the recorder)."""
    return _CURRENT_REGISTRY


def record_event(kind: str, **fields) -> None:
    """Record into the ambient recorder; no-op (one ``None`` check) when
    no recorder is installed."""
    rec = _CURRENT
    if rec is not None:
        rec.record(kind, **fields)


def dump_current(reason: str, **fields) -> str | None:
    """Dump the ambient recorder (no-op when none installed)."""
    rec = _CURRENT
    if rec is not None:
        return rec.dump(reason, **fields)
    return None


@contextlib.contextmanager
def flight_recorder(
    dir: str | os.PathLike | None = None,
    rank: int = 0,
    *,
    capacity: int = 4096,
    spill_every: int = 64,
    source: str = "train",
    registry: MetricsRegistry | None = None,
):
    """Install a :class:`FlightRecorder` (and a metrics registry) as the
    ambient telemetry sinks for the enclosed block.

    On exit the recorder is flushed and closed and, when ``dir`` is set,
    the registry snapshot is written next to the event file as
    ``metrics_{rank:05d}.json`` — the stable JSON export the reports
    view."""
    global _CURRENT, _CURRENT_REGISTRY
    rec = FlightRecorder(
        dir, rank, capacity=capacity, spill_every=spill_every, source=source
    )
    reg = registry if registry is not None else MetricsRegistry()
    prev, prev_reg = _CURRENT, _CURRENT_REGISTRY
    _CURRENT, _CURRENT_REGISTRY = rec, reg
    try:
        yield rec
    finally:
        _CURRENT, _CURRENT_REGISTRY = prev, prev_reg
        rec.close()
        if rec.dir is not None:
            snap_path = os.path.join(
                rec.dir, f"metrics_{rec.rank:05d}.json"
            )
            with contextlib.suppress(OSError):
                with open(snap_path, "w", encoding="utf-8") as f:
                    json.dump(reg.snapshot(), f, indent=2, sort_keys=True)


def install_signal_dump(
    recorder: FlightRecorder, signals=(signal.SIGTERM,)
) -> None:
    """Chain a flush+dump onto ``signals``' existing handlers (main
    thread only — a Python constraint).  For runs whose SIGTERM is not
    already routed through a ``PreemptionGuard`` (whose fit path dumps
    via :func:`dump_current`); the previous handler still runs, so
    default-terminate behavior is preserved."""
    for sig in signals:
        prev = signal.getsignal(sig)

        def _handler(signum, frame, _prev=prev):
            # non-blocking: the handler runs on the interrupted thread,
            # which may be holding the recorder lock mid-record — a
            # blocking dump there would deadlock instead of terminating
            recorder.dump_nonblocking("signal", signum=int(signum))
            if callable(_prev):
                _prev(signum, frame)
            elif _prev is not signal.SIG_IGN:
                # SIG_DFL, or None (installed from C, unknowable here):
                # never swallow a termination request — restore default
                # and re-raise so the process still dies
                signal.signal(signum, signal.SIG_DFL)
                signal.raise_signal(signum)

        signal.signal(sig, _handler)
