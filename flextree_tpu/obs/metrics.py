"""Metrics registry: counters / gauges / fixed-bucket histograms.

The registry replaces the ad-hoc accounting that accreted around the
runtime and serving layers (per-request stamp lists, scattered EWMA
plumbing, hand-rolled dict counters) with three bounded-memory
instruments and ONE stable JSON snapshot shape, so every report —
``run_report.json``, the serving pool report, bench artifacts — can be a
*view* over the same numbers instead of a parallel bookkeeping path.

Memory is bounded by construction: a counter/gauge is one float, a
histogram is a fixed bucket array (values land in the bucket whose upper
edge first contains them; an overflow bucket catches the tail) plus
running count/sum/min/max.  Percentiles are answered from the buckets —
exact to within one bucket's resolution, which is the honest granularity
an always-on layer can afford (the NumPy-oracle test in
``tests/test_obs.py`` pins the error bound).

Thread-safe: instruments are updated from step loops, daemon heartbeat
threads, and serving rounds concurrently; each mutation takes one short
lock.
"""

from __future__ import annotations

import math
import threading
import time

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "WindowedHistogram",
    "FrozenWindow",
    "MetricsRegistry",
    "DEFAULT_MS_BUCKETS",
    "load_window",
    "merged_window_percentile",
    "prometheus_exposition",
]

# injection point for the windowed-histogram tests (patch this, not
# time.monotonic): interval rotation is pure arithmetic over it, the same
# pattern runtime.supervisor._wall / serving.engine._now use
_now = time.monotonic

#: Default latency bucket upper edges (milliseconds): ~1-2-5 decades from
#: 100 µs to 100 s — wide enough for TTFTs and train steps alike.  13
#: buckets + overflow = bounded whatever the workload does.
DEFAULT_MS_BUCKETS = (
    0.1, 0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 50.0,
    100.0, 500.0, 1_000.0, 10_000.0, 100_000.0,
)


class Counter:
    """Monotonic count.  ``inc`` rejects negative deltas — a counter that
    can go down is a gauge wearing a costume."""

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, delta: float = 1.0) -> None:
        if delta < 0:
            raise ValueError(f"counter delta must be >= 0, got {delta}")
        with self._lock:
            self._value += delta

    @property
    def value(self) -> float:
        return self._value

    def to_payload(self):
        v = self._value
        return int(v) if float(v).is_integer() else v


class Gauge:
    """Last-written value (queue depth, free blocks, alive replicas)."""

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def add(self, delta: float) -> None:
        with self._lock:
            self._value += float(delta)

    @property
    def value(self) -> float:
        return self._value

    def to_payload(self):
        v = self._value
        return int(v) if float(v).is_integer() else v


def _bucket_percentile(q, edges, counts, count, minv, maxv) -> float:
    """The shared percentile-from-buckets interpolation: cumulative
    histograms and windowed snapshots must answer from ONE definition, or
    the arbiter's breach check and ``engine.report()`` could disagree
    about the same samples."""
    if count == 0:
        return math.nan
    target = q / 100.0 * count
    cum = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        lo_edge = 0.0 if i == 0 else edges[i - 1]
        hi_edge = edges[i] if i < len(edges) else maxv
        if cum + c >= target:
            frac = (target - cum) / c
            lo = max(lo_edge, minv if minv is not None else lo_edge)
            return min(lo + frac * (hi_edge - lo), hi_edge)
        cum += c
    return maxv if maxv is not None else math.nan


class Histogram:
    """Fixed-bucket histogram: ``buckets`` are increasing upper edges; an
    implicit overflow bucket catches values past the last edge."""

    def __init__(self, buckets=DEFAULT_MS_BUCKETS):
        edges = tuple(float(b) for b in buckets)
        if not edges or any(nxt <= prev for nxt, prev in zip(edges[1:], edges)):
            raise ValueError(f"bucket edges must strictly increase: {edges}")
        self.edges = edges
        self.counts = [0] * (len(edges) + 1)  # +1: overflow
        self.count = 0
        self.sum = 0.0
        self.min: float | None = None
        self.max: float | None = None
        self._lock = threading.Lock()

    def _bucket_index(self, value: float) -> int:
        # linear scan: bucket lists are ~a dozen edges and most samples
        # land early; a bisect would save nothing measurable
        i = 0
        for i, edge in enumerate(self.edges):  # noqa: B007
            if value <= edge:
                break
        else:
            i = len(self.edges)
        return i

    def observe(self, value: float) -> None:
        value = float(value)
        i = self._bucket_index(value)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum += value
            self.min = value if self.min is None else min(self.min, value)
            self.max = value if self.max is None else max(self.max, value)

    def percentile(self, q: float) -> float:
        """The ``q``-th percentile (0..100), answered from the buckets:
        linear interpolation inside the bucket the target rank lands in,
        so the error is bounded by that bucket's width.  Overflow-bucket
        answers clamp to the observed max (the one exact statistic the
        histogram keeps past the last edge)."""
        if not 0.0 <= q <= 100.0:
            raise ValueError(f"percentile must be in [0, 100], got {q}")
        return _bucket_percentile(
            q, self.edges, self.counts, self.count, self.min, self.max
        )

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else math.nan

    def to_payload(self) -> dict:
        return {
            "count": self.count,
            "sum": round(self.sum, 6),
            "min": self.min,
            "max": self.max,
            "mean": round(self.mean, 6) if self.count else None,
            "p50": round(self.percentile(50), 6) if self.count else None,
            "p95": round(self.percentile(95), 6) if self.count else None,
            "p99": round(self.percentile(99), 6) if self.count else None,
            "buckets": {
                (str(e) if i < len(self.edges) else "+inf"): c
                for i, (e, c) in enumerate(
                    zip(self.edges + (math.inf,), self.counts)
                )
                if c
            },
        }


class _WindowSlot:
    """One interval's sub-histogram: bucket counts + running stats, keyed
    by its absolute interval index so stale slots invalidate lazily."""

    __slots__ = ("k", "counts", "count", "sum", "min", "max")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets
        self.reset(-1)

    def reset(self, k: int) -> None:
        self.k = k
        for i in range(len(self.counts)):
            self.counts[i] = 0
        self.count = 0
        self.sum = 0.0
        self.min: float | None = None
        self.max: float | None = None


class WindowedHistogram(Histogram):
    """A :class:`Histogram` that ALSO answers over a rolling window.

    The cumulative view (everything :class:`Histogram` offers) dilutes a
    fresh breach after a long quiet run — a thousand healthy TTFTs drown
    the ten bad ones an SLO check needs to see *now*.  The windowed view
    keeps a ring of ``intervals`` per-interval sub-histograms (absolute
    interval index = ``now // interval_s``; a slot whose index fell out
    of the window reads as empty), so :meth:`window_percentile` answers
    over the last ``interval_s * intervals`` seconds only, with the SAME
    bucket interpolation as the cumulative percentile — the arbiter's
    breach check and ``engine.report()`` share one definition by
    construction.

    Memory stays bounded: the ring is ``intervals × (edges + 1)`` ints
    regardless of traffic.  The clock is the module's ``_now`` hook
    (monotonic; injectable for tests), or an explicit ``now=`` for
    deterministic replay.
    """

    def __init__(
        self,
        buckets=DEFAULT_MS_BUCKETS,
        *,
        interval_s: float = 1.0,
        intervals: int = 10,
    ):
        super().__init__(buckets)
        if interval_s <= 0 or intervals < 1:
            raise ValueError(
                f"need interval_s > 0 and intervals >= 1, got "
                f"{interval_s}/{intervals}"
            )
        self.interval_s = float(interval_s)
        self.intervals = int(intervals)
        self._slots = [
            _WindowSlot(len(self.edges) + 1) for _ in range(self.intervals)
        ]

    @property
    def window_s(self) -> float:
        return self.interval_s * self.intervals

    def observe(self, value: float, now: float | None = None) -> None:
        # one edge scan and ONE lock acquisition for both views: a
        # concurrent snapshot must never see the sample in the cumulative
        # count but not the window (or pay a second lock on the hot path)
        value = float(value)
        now = _now() if now is None else now
        k = int(now // self.interval_s)
        i = self._bucket_index(value)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum += value
            self.min = value if self.min is None else min(self.min, value)
            self.max = value if self.max is None else max(self.max, value)
            slot = self._slots[k % self.intervals]
            if slot.k != k:
                slot.reset(k)
            slot.counts[i] += 1
            slot.count += 1
            slot.sum += value
            slot.min = value if slot.min is None else min(slot.min, value)
            slot.max = value if slot.max is None else max(slot.max, value)

    def window_counts(self, now: float | None = None):
        """Merged ``(counts, count, sum, min, max)`` over the live window
        — slots whose interval index fell behind ``now`` by more than
        ``intervals`` read as empty (lazy expiry: nothing rotates on a
        quiet histogram)."""
        now = _now() if now is None else now
        k = int(now // self.interval_s)
        counts = [0] * (len(self.edges) + 1)
        count, total = 0, 0.0
        minv: float | None = None
        maxv: float | None = None
        with self._lock:
            for slot in self._slots:
                if not (k - self.intervals < slot.k <= k) or slot.count == 0:
                    continue
                for i, c in enumerate(slot.counts):
                    counts[i] += c
                count += slot.count
                total += slot.sum
                if slot.min is not None:
                    minv = slot.min if minv is None else min(minv, slot.min)
                if slot.max is not None:
                    maxv = slot.max if maxv is None else max(maxv, slot.max)
        return counts, count, total, minv, maxv

    def window_percentile(self, q: float, now: float | None = None) -> float:
        """The ``q``-th percentile over the rolling window (NaN when the
        window holds no samples — the caller decides what "no evidence"
        means; the arbiter treats it as in-SLO)."""
        if not 0.0 <= q <= 100.0:
            raise ValueError(f"percentile must be in [0, 100], got {q}")
        counts, count, _, minv, maxv = self.window_counts(now)
        return _bucket_percentile(q, self.edges, counts, count, minv, maxv)

    def window_count(self, now: float | None = None) -> int:
        return self.window_counts(now)[1]

    def window_slots(self, now: float | None = None) -> list[dict]:
        """The live slots as ``{"age", "counts", "count", "sum", "min",
        "max"}`` dicts, ``age`` = how many whole intervals the slot sits
        behind ``now`` (0 = the current interval).  Ages, not absolute
        interval indices: the ring is keyed off this process's monotonic
        clock, which no other process shares — relative age plus a wall
        stamp is the only coordinate a cross-process reader can use."""
        now = _now() if now is None else now
        k = int(now // self.interval_s)
        out: list[dict] = []
        with self._lock:
            for slot in self._slots:
                if not (k - self.intervals < slot.k <= k) or slot.count == 0:
                    continue
                out.append({
                    "age": k - slot.k,
                    "counts": list(slot.counts),
                    "count": slot.count,
                    "sum": round(slot.sum, 6),
                    "min": slot.min,
                    "max": slot.max,
                })
        out.sort(key=lambda s: s["age"])
        return out

    def to_payload(self) -> dict:
        p = super().to_payload()
        counts, count, total, minv, maxv = self.window_counts()
        p["window"] = {
            "seconds": round(self.window_s, 6),
            "count": count,
            "min": minv,
            "max": maxv,
            "mean": round(total / count, 6) if count else None,
            "p50": round(
                _bucket_percentile(50, self.edges, counts, count, minv, maxv), 6
            ) if count else None,
            "p99": round(
                _bucket_percentile(99, self.edges, counts, count, minv, maxv), 6
            ) if count else None,
            # the cross-process series: everything another process needs
            # to re-answer window_percentile later, aging the slots off
            # the wall stamp as real time passes (satellite fix: without
            # these the window died at snapshot() and no file reader —
            # the arbiter's breach check included — could see a rolling
            # p99, only this instant's summary)
            "interval_s": self.interval_s,
            "intervals": self.intervals,
            "edges": list(self.edges),
            "wall": time.time(),
            "slots": self.window_slots(),
        }
        return p


class FrozenWindow:
    """A :class:`WindowedHistogram`'s rolling window reconstructed from a
    serialized payload — the read side of the cross-process round-trip.

    Quacks like the live histogram where it matters (``edges``,
    ``window_s``, ``window_counts``/``window_percentile``), so
    :func:`merged_window_percentile` merges frozen and live windows with
    one code path.  The clock, though, is WALL time anchored at the
    payload's ``wall`` stamp: a slot that was ``age`` intervals old when
    serialized expires once ``age + elapsed_intervals >= intervals``, so
    a stale metrics file decays to an empty window instead of asserting
    its last breach forever (exactly the lazy-expiry rule the live ring
    applies to its own slots)."""

    def __init__(self, edges, *, interval_s, intervals, wall, slots):
        self.edges = tuple(float(e) for e in edges)
        self.interval_s = float(interval_s)
        self.intervals = int(intervals)
        self.wall = float(wall)
        self._slots = [
            {
                "age": int(s["age"]),
                "counts": [int(c) for c in s["counts"]],
                "count": int(s["count"]),
                "sum": float(s.get("sum") or 0.0),
                "min": s.get("min"),
                "max": s.get("max"),
            }
            for s in slots
        ]

    @property
    def window_s(self) -> float:
        return self.interval_s * self.intervals

    def age_s(self, now: float | None = None) -> float:
        """Seconds of wall clock since the payload was serialized."""
        now = time.time() if now is None else now
        return max(0.0, now - self.wall)

    def window_counts(self, now: float | None = None):
        """Merged ``(counts, count, sum, min, max)`` over the slots still
        inside the window at wall time ``now`` (default: right now)."""
        elapsed = int(self.age_s(now) // self.interval_s)
        counts = [0] * (len(self.edges) + 1)
        count, total = 0, 0.0
        minv: float | None = None
        maxv: float | None = None
        for slot in self._slots:
            if slot["age"] + elapsed >= self.intervals:
                continue
            for i, c in enumerate(slot["counts"]):
                counts[i] += c
            count += slot["count"]
            total += slot["sum"]
            if slot["min"] is not None:
                minv = (
                    slot["min"] if minv is None else min(minv, slot["min"])
                )
            if slot["max"] is not None:
                maxv = (
                    slot["max"] if maxv is None else max(maxv, slot["max"])
                )
        return counts, count, total, minv, maxv

    def window_percentile(self, q: float, now: float | None = None) -> float:
        if not 0.0 <= q <= 100.0:
            raise ValueError(f"percentile must be in [0, 100], got {q}")
        counts, count, _, minv, maxv = self.window_counts(now)
        return _bucket_percentile(q, self.edges, counts, count, minv, maxv)

    def window_count(self, now: float | None = None) -> int:
        return self.window_counts(now)[1]


def load_window(payload: dict) -> FrozenWindow | None:
    """Reconstruct the rolling window from one histogram payload (the
    dict under ``snapshot()["histograms"][name]``).  ``None`` when the
    payload carries no windowed series — a plain histogram, or a file
    written before the series existed (absent ≠ empty window: the caller
    must treat it as "no windowed evidence", not "all clear")."""
    window = payload.get("window") if isinstance(payload, dict) else None
    if not isinstance(window, dict) or "slots" not in window:
        return None
    try:
        return FrozenWindow(
            window["edges"],
            interval_s=window["interval_s"],
            intervals=window["intervals"],
            wall=window["wall"],
            slots=window["slots"],
        )
    except (KeyError, TypeError, ValueError):
        return None


def merged_window_percentile(
    hists, q: float, now: float | None = None
) -> tuple[float, int]:
    """``(percentile, sample_count)`` over the union of several
    :class:`WindowedHistogram` windows — the arbiter's cross-replica SLO
    reading (each serving replica owns its registry; the SLO is a
    property of the POOL).  Histograms must share bucket edges; NaN with
    count 0 when every window is empty."""
    hists = [h for h in hists if h is not None]
    if not hists:
        return math.nan, 0
    edges = hists[0].edges
    for h in hists[1:]:
        if h.edges != edges:
            raise ValueError(
                "merged_window_percentile needs identical bucket edges: "
                f"{h.edges} vs {edges}"
            )
    counts = [0] * (len(edges) + 1)
    count = 0
    minv: float | None = None
    maxv: float | None = None
    for h in hists:
        c, n, _, lo, hi = h.window_counts(now)
        for i, v in enumerate(c):
            counts[i] += v
        count += n
        if lo is not None:
            minv = lo if minv is None else min(minv, lo)
        if hi is not None:
            maxv = hi if maxv is None else max(maxv, hi)
    return _bucket_percentile(q, edges, counts, count, minv, maxv), count


def _prom_name(name: str, prefix: str = "flextree_") -> str:
    """Sanitize a registry metric name into the Prometheus grammar
    (``[a-zA-Z_:][a-zA-Z0-9_:]*``): dots/dashes become underscores,
    anything else invalid is dropped."""
    out = []
    for ch in name:
        if ch.isalnum() or ch in "_:":
            out.append(ch)
        elif ch in ".-/ ":
            out.append("_")
    s = prefix + "".join(out)
    if s and s[0].isdigit():
        s = "_" + s
    return s


def prometheus_exposition(snapshots: dict, prefix: str = "flextree_") -> str:
    """Render registry snapshots as Prometheus text exposition (format
    0.0.4) — ``{label_value: registry.snapshot()}`` keyed by rank (or any
    instance label), so ``python -m flextree_tpu.obs metrics DIR --prom``
    makes the serving SLO instruments scrapeable without parsing
    ``metrics_{rank}.json``.

    Counters/gauges map 1:1; histograms follow the Prometheus histogram
    convention (cumulative ``_bucket{le=...}`` series from the snapshot's
    per-bucket counts, plus ``_sum``/``_count``); a windowed histogram
    additionally exposes its rolling-window view as ``_window_p99`` /
    ``_window_count`` gauges — the exact numbers the arbiter's SLO breach
    check reads, so an external scraper alerts on the same quantity.
    """
    types: dict[str, str] = {}
    lines_by_name: dict[str, list[str]] = {}

    def emit(name: str, kind: str, line: str) -> None:
        types.setdefault(name, kind)
        lines_by_name.setdefault(name, []).append(line)

    for label, snap in sorted(snapshots.items()):
        lbl = f'{{rank="{label}"}}'
        for raw, val in (snap.get("counters") or {}).items():
            n = _prom_name(raw, prefix)
            emit(n, "counter", f"{n}{lbl} {val}")
        for raw, val in (snap.get("gauges") or {}).items():
            n = _prom_name(raw, prefix)
            emit(n, "gauge", f"{n}{lbl} {val}")
        for raw, h in (snap.get("histograms") or {}).items():
            n = _prom_name(raw, prefix)
            types.setdefault(n, "histogram")
            buckets = h.get("buckets") or {}
            parsed = []
            for edge, count in buckets.items():
                e = math.inf if edge == "+inf" else float(edge)
                parsed.append((e, int(count)))
            parsed.sort(key=lambda ec: ec[0])
            cum = 0
            rows = lines_by_name.setdefault(n, [])
            for e, c in parsed:
                cum += c
                le = "+Inf" if math.isinf(e) else repr(e)
                rows.append(
                    f'{n}_bucket{{rank="{label}",le="{le}"}} {cum}'
                )
            total = int(h.get("count", cum))
            if not parsed or not math.isinf(parsed[-1][0]):
                rows.append(f'{n}_bucket{{rank="{label}",le="+Inf"}} {total}')
            rows.append(f"{n}_sum{lbl} {h.get('sum', 0.0)}")
            rows.append(f"{n}_count{lbl} {total}")
            window = h.get("window")
            if isinstance(window, dict):
                p99 = window.get("p99")
                count = window.get("count", 0)
                # a payload carrying the windowed series re-answers at
                # READ time, aged off the wall stamp — a scrape of a
                # stale metrics file must see the window drain, not the
                # last write's summary frozen forever
                frozen = load_window(h)
                if frozen is not None:
                    v = frozen.window_percentile(99.0)
                    p99 = None if math.isnan(v) else round(v, 6)
                    count = frozen.window_count()
                wn = n + "_window_p99"
                if p99 is not None:
                    emit(wn, "gauge", f"{wn}{lbl} {p99}")
                wc = n + "_window_count"
                emit(wc, "gauge", f"{wc}{lbl} {count}")

    out: list[str] = []
    for name in sorted(lines_by_name):
        out.append(f"# TYPE {name} {types[name]}")
        out.extend(lines_by_name[name])
    return "\n".join(out) + ("\n" if out else "")


class MetricsRegistry:
    """Named instruments with create-on-first-use semantics and one
    stable snapshot.  Asking for an existing name with a different
    instrument kind is an error (silent shadowing is how two subsystems
    end up disagreeing about what ``requests`` means)."""

    def __init__(self):
        self._instruments: dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, kind, factory):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = factory()
                self._instruments[name] = inst
            elif not isinstance(inst, kind):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, requested {kind.__name__}"
                )
            return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge, Gauge)

    def histogram(self, name: str, buckets=DEFAULT_MS_BUCKETS) -> Histogram:
        return self._get(name, Histogram, lambda: Histogram(buckets))

    def windowed_histogram(
        self,
        name: str,
        buckets=DEFAULT_MS_BUCKETS,
        *,
        interval_s: float = 1.0,
        intervals: int = 10,
    ) -> WindowedHistogram:
        """A histogram that ALSO answers rolling-window percentiles (the
        arbiter's SLO view).  Create it BEFORE any plain ``histogram()``
        call for the same name: a ``WindowedHistogram`` satisfies later
        ``histogram()`` lookups (it IS one), but a plain histogram cannot
        be upgraded in place."""
        return self._get(
            name,
            WindowedHistogram,
            lambda: WindowedHistogram(
                buckets, interval_s=interval_s, intervals=intervals
            ),
        )

    def __contains__(self, name: str) -> bool:
        return name in self._instruments

    def snapshot(self) -> dict:
        """``{"counters": {...}, "gauges": {...}, "histograms": {...}}``
        with sorted names — the stable JSON shape reports embed."""
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        with self._lock:
            items = sorted(self._instruments.items())
        for name, inst in items:
            if isinstance(inst, Counter):
                out["counters"][name] = inst.to_payload()
            elif isinstance(inst, Gauge):
                out["gauges"][name] = inst.to_payload()
            else:
                out["histograms"][name] = inst.to_payload()
        return out
