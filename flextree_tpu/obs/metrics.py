"""Metrics registry: counters / gauges / fixed-bucket histograms.

The registry replaces the ad-hoc accounting that accreted around the
runtime and serving layers (per-request stamp lists, scattered EWMA
plumbing, hand-rolled dict counters) with three bounded-memory
instruments and ONE stable JSON snapshot shape, so every report —
``run_report.json``, the serving pool report, bench artifacts — can be a
*view* over the same numbers instead of a parallel bookkeeping path.

Memory is bounded by construction: a counter/gauge is one float, a
histogram is a fixed bucket array (values land in the bucket whose upper
edge first contains them; an overflow bucket catches the tail) plus
running count/sum/min/max.  Percentiles are answered from the buckets —
exact to within one bucket's resolution, which is the honest granularity
an always-on layer can afford (the NumPy-oracle test in
``tests/test_obs.py`` pins the error bound).

Thread-safe: instruments are updated from step loops, daemon heartbeat
threads, and serving rounds concurrently; each mutation takes one short
lock.
"""

from __future__ import annotations

import math
import threading

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "DEFAULT_MS_BUCKETS",
]

#: Default latency bucket upper edges (milliseconds): ~1-2-5 decades from
#: 100 µs to 100 s — wide enough for TTFTs and train steps alike.  13
#: buckets + overflow = bounded whatever the workload does.
DEFAULT_MS_BUCKETS = (
    0.1, 0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 50.0,
    100.0, 500.0, 1_000.0, 10_000.0, 100_000.0,
)


class Counter:
    """Monotonic count.  ``inc`` rejects negative deltas — a counter that
    can go down is a gauge wearing a costume."""

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, delta: float = 1.0) -> None:
        if delta < 0:
            raise ValueError(f"counter delta must be >= 0, got {delta}")
        with self._lock:
            self._value += delta

    @property
    def value(self) -> float:
        return self._value

    def to_payload(self):
        v = self._value
        return int(v) if float(v).is_integer() else v


class Gauge:
    """Last-written value (queue depth, free blocks, alive replicas)."""

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def add(self, delta: float) -> None:
        with self._lock:
            self._value += float(delta)

    @property
    def value(self) -> float:
        return self._value

    def to_payload(self):
        v = self._value
        return int(v) if float(v).is_integer() else v


class Histogram:
    """Fixed-bucket histogram: ``buckets`` are increasing upper edges; an
    implicit overflow bucket catches values past the last edge."""

    def __init__(self, buckets=DEFAULT_MS_BUCKETS):
        edges = tuple(float(b) for b in buckets)
        if not edges or any(nxt <= prev for nxt, prev in zip(edges[1:], edges)):
            raise ValueError(f"bucket edges must strictly increase: {edges}")
        self.edges = edges
        self.counts = [0] * (len(edges) + 1)  # +1: overflow
        self.count = 0
        self.sum = 0.0
        self.min: float | None = None
        self.max: float | None = None
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        # linear scan: bucket lists are ~a dozen edges and most samples
        # land early; a bisect would save nothing measurable
        i = 0
        for i, edge in enumerate(self.edges):  # noqa: B007
            if value <= edge:
                break
        else:
            i = len(self.edges)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum += value
            self.min = value if self.min is None else min(self.min, value)
            self.max = value if self.max is None else max(self.max, value)

    def percentile(self, q: float) -> float:
        """The ``q``-th percentile (0..100), answered from the buckets:
        linear interpolation inside the bucket the target rank lands in,
        so the error is bounded by that bucket's width.  Overflow-bucket
        answers clamp to the observed max (the one exact statistic the
        histogram keeps past the last edge)."""
        if not 0.0 <= q <= 100.0:
            raise ValueError(f"percentile must be in [0, 100], got {q}")
        if self.count == 0:
            return math.nan
        target = q / 100.0 * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            lo_edge = 0.0 if i == 0 else self.edges[i - 1]
            hi_edge = self.edges[i] if i < len(self.edges) else self.max
            if cum + c >= target:
                frac = (target - cum) / c
                lo = max(lo_edge, self.min if self.min is not None else lo_edge)
                return min(lo + frac * (hi_edge - lo), hi_edge)
            cum += c
        return self.max if self.max is not None else math.nan

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else math.nan

    def to_payload(self) -> dict:
        return {
            "count": self.count,
            "sum": round(self.sum, 6),
            "min": self.min,
            "max": self.max,
            "mean": round(self.mean, 6) if self.count else None,
            "p50": round(self.percentile(50), 6) if self.count else None,
            "p95": round(self.percentile(95), 6) if self.count else None,
            "p99": round(self.percentile(99), 6) if self.count else None,
            "buckets": {
                (str(e) if i < len(self.edges) else "+inf"): c
                for i, (e, c) in enumerate(
                    zip(self.edges + (math.inf,), self.counts)
                )
                if c
            },
        }


class MetricsRegistry:
    """Named instruments with create-on-first-use semantics and one
    stable snapshot.  Asking for an existing name with a different
    instrument kind is an error (silent shadowing is how two subsystems
    end up disagreeing about what ``requests`` means)."""

    def __init__(self):
        self._instruments: dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, kind, factory):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = factory()
                self._instruments[name] = inst
            elif not isinstance(inst, kind):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, requested {kind.__name__}"
                )
            return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge, Gauge)

    def histogram(self, name: str, buckets=DEFAULT_MS_BUCKETS) -> Histogram:
        return self._get(name, Histogram, lambda: Histogram(buckets))

    def __contains__(self, name: str) -> bool:
        return name in self._instruments

    def snapshot(self) -> dict:
        """``{"counters": {...}, "gauges": {...}, "histograms": {...}}``
        with sorted names — the stable JSON shape reports embed."""
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        with self._lock:
            items = sorted(self._instruments.items())
        for name, inst in items:
            if isinstance(inst, Counter):
                out["counters"][name] = inst.to_payload()
            elif isinstance(inst, Gauge):
                out["gauges"][name] = inst.to_payload()
            else:
                out["histograms"][name] = inst.to_payload()
        return out
