"""Plan provenance for comm events: which plan, and what it predicted.

A bucket span that only says "4.2 MB over dp" answers *what* moved;
the question a cost-model-driven system has to answer is *why* — which
widths/family/codec/sharded plan the planner chose, and what it
predicted the move would cost.  :func:`bucket_provenance` packages that
into the JSON-safe dict ``comm_span`` attaches to the recorded event, so
every merged timeline carries predicted-vs-measured per-phase residual
material for free (the motivation of arXiv:2409.04202's measured-phase
treatment).

Free when telemetry is off: the helper returns ``None`` immediately when
no flight recorder is installed, so tracing a step in an
un-instrumented run never pays the cost-model call.
"""

from __future__ import annotations

import dataclasses

from .recorder import current_recorder

__all__ = ["topo_spec", "bucket_provenance"]


def topo_spec(topo) -> str:
    """The ``FT_TOPO``-style spec of a resolved topology (``"4,2"``,
    ``"3,2+2"``, ``"ring"``); the native-collective sentinel (None) reads
    ``"psum"``."""
    if topo is None:
        return "psum"
    if getattr(topo, "is_ring", False):
        return "ring"
    return str(topo).replace("*", ",")


def bucket_provenance(
    axes,
    topos,
    nbytes: int,
    *,
    n_leaves: int | None = None,
    dtype: str | None = None,
    codec=None,
    chunks: int = 1,
    sharded: bool = False,
    fired: bool = False,
) -> dict | None:
    """The plan-provenance payload for one bucket's comm event, or None
    when no recorder is installed (zero trace-time cost while telemetry
    is off).

    ``axes``/``topos``: the replication axes the bucket reduces over and
    their resolved topologies (``None`` = native psum).  The predicted
    :class:`~flextree_tpu.planner.cost_model.CostBreakdown` is computed
    per scheduled axis with the default calibrated params and summed —
    the same model the planner chose the bucket size with, so the
    residual read off a timeline is against the plan as priced, not a
    re-derivation."""
    if current_recorder() is None:
        return None
    axes = tuple(axes)
    prov: dict = {
        "axes": list(axes),
        "topo": {ax: topo_spec(topos.get(ax)) for ax in axes},
        # world size per axis (None for the native-psum sentinel, whose
        # group size the resolved topology doesn't carry): the residual
        # extractor pairs planned and measured spans on (topo, world,
        # codec, sharded, nbytes) — without the world a "ring" spec is
        # ambiguous across group sizes (planner/feedback.py)
        "world": {
            ax: (
                int(topos.get(ax).num_nodes)
                if topos.get(ax) is not None
                else None
            )
            for ax in axes
        },
        "nbytes": int(nbytes),
        "chunks": int(chunks),
        "codec": getattr(codec, "name", None) or (str(codec) if codec else "f32"),
        "sharded": bool(sharded),
        "fired": bool(fired),
    }
    if n_leaves is not None:
        prov["n_leaves"] = int(n_leaves)
    if dtype is not None:
        prov["dtype"] = str(dtype)
    try:
        from ..planner.calibrate import default_params
        from ..planner.cost_model import allreduce_cost, lonely_allreduce_cost
        from ..schedule.stages import LonelyTopology

        # the LIVE calibrated constants (FLEXTREE_CALIBRATION), not the
        # invented dataclass defaults: the provenance contract is "the
        # plan as priced" — the same params the planner chose the bucket
        # size with, so per-step residuals judge the live model
        params = default_params()
        total = 0.0
        breakdown: dict[str, float] = {}
        for ax in axes:
            topo = topos.get(ax)
            if topo is None:
                continue  # native psum: the model has no term for it
            if isinstance(topo, LonelyTopology):
                cost = lonely_allreduce_cost(
                    topo.tree, topo.lonely, int(nbytes), params, codec=codec
                )
            else:
                cost = allreduce_cost(topo, int(nbytes), params, codec=codec)
            total += cost.total_us
            for key, val in dataclasses.asdict(cost).items():
                breakdown[key] = round(breakdown.get(key, 0.0) + val, 3)
        if breakdown:
            prov["predicted"] = breakdown
            prov["predicted_us"] = round(total, 3)
    except Exception:  # provenance must never break a trace
        prov["predicted_error"] = True
    return prov
