"""Per-step cost attribution: host-timed steps keyed to compile-time plans.

PR 10's provenance made every bucket span carry its predicted
``CostBreakdown`` — but ``bucket_planned`` events fire at *trace* time
(once per compile), and the only *measured* comm points were the feedback
prober's dedicated collectives (PR 12).  This module closes the
granularity gap named in docs/FEEDBACK.md / docs/OBSERVABILITY.md: every
recorded training step becomes a measured sample against the plan that
step ran, with zero extra collectives — the microbenchmark-style phase
dissection of arXiv:1912.03413 obtained from production traffic instead
of offline sweeps.

Mechanics, and what is honestly measurable:

- **the plan**: a freshly-compiled step's bucket plan is captured at
  trace time (``utils.profiling.plan_capture`` hooks the same
  ``comm_span`` calls that emit ``bucket_planned``), so the clock knows
  exactly which (topo, world, codec, sharded, nbytes) points — and which
  predicted per-phase :class:`~flextree_tpu.planner.cost_model.CostBreakdown`
  terms — the step will run;
- **the measurement**: the host times the whole materialized step
  (``fit``'s step scope — the materialization boundary is the only
  per-step instant a fused jitted program exposes to the host; the
  per-bucket collectives inside it are NOT individually host-visible);
- **attribution**: measured comm = step time minus the compute floor
  (``compute_floor_us`` when the caller knows it, else a provisional
  floor from the fastest observed step — see :meth:`StepSpanClock.floor_us`),
  apportioned across the step's buckets by predicted share.  Apportioned
  events are stamped ``apportioned: true``: within one step every
  bucket's measured/predicted ratio is BY CONSTRUCTION the same, so
  per-phase information comes from variation *across* plans (the
  feedback controller's plan rotation, or fleet pooling across runs) —
  never from one step alone.  The fitter respects this
  (``planner.feedback``: apportioned samples feed the phase-scale solve
  and the drift detector, not the point-wise α-β NNLS).

Event contract (consumed by ``obs.timeline.residual_pairs``, the merger,
and the ``obs fleet`` pooling pass):

- ``step_measured``: one per sampled step — ``{step, step_us, floor_us,
  comm_us, predicted_us, plan_sig, n_buckets}``;
- ``bucket_measured`` with ``per_step: true``: one per bucket per sampled
  step, carrying the same pairing keys ``bucket_planned`` uses (topo /
  world / codec / sharded / nbytes) plus the predicted per-phase
  breakdown, the apportioned ``measured_us``, and the ``plan_sig`` that
  groups a step's buckets back together offline.

Honest limits: the host-timed step must be MATERIALIZED (async dispatch
times the enqueue, not the execution — ``fit`` materializes whenever the
clock is armed); the provisional floor can only detect comm
*over*-prediction (an under-predicted wire hides inside the floor —
supply ``compute_floor_us``; the probe-free refit also needs it, to
split its fitted intercept into floor + byte-phase time, after which the
fit's implied floor replaces this one); and the first step after a
(re)compile is excluded (it times tracing + compilation, not the plan).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from collections import deque

from .recorder import record_event
from .timeline import _PHASE_TERMS

__all__ = [
    "PlannedBucket",
    "StepPlan",
    "StepSample",
    "StepSpanClock",
    "plan_from_capture",
    "PHASE_FIXED",
    "PHASE_BYTES",
    "PHASE_CODEC",
]

#: CostBreakdown terms grouped into the three independently-scalable
#: phases the per-phase fit solves for: per-message fixed costs
#: (launch+hop latency+control), byte-proportional costs (wire bandwidth
#: + reduce — structurally collinear on an f32 wire, so they scale as
#: one phase and re-split in the base calibration's ratio), and codec
#: en/decode work (compressed wires only).  ONE definition, owned by
#: ``obs.timeline._PHASE_TERMS`` — a term regrouped there regroups here.
PHASE_FIXED = _PHASE_TERMS["fixed"]
PHASE_BYTES = _PHASE_TERMS["bytes"]
PHASE_CODEC = _PHASE_TERMS["codec"]


@dataclasses.dataclass(frozen=True)
class PlannedBucket:
    """One captured bucket-axis span: the pairing keys plus the predicted
    per-phase breakdown, exactly as ``bucket_planned`` recorded them."""

    name: str
    axis: str
    topo: str
    world: int | None
    nbytes: int
    codec: str
    sharded: bool
    predicted: dict  # per-term CostBreakdown (µs), as recorded
    predicted_us: float

    @property
    def fixed_us(self) -> float:
        return sum(float(self.predicted.get(k, 0.0)) for k in PHASE_FIXED)

    @property
    def bytes_us(self) -> float:
        return sum(float(self.predicted.get(k, 0.0)) for k in PHASE_BYTES)

    @property
    def codec_us(self) -> float:
        return sum(float(self.predicted.get(k, 0.0)) for k in PHASE_CODEC)


@dataclasses.dataclass(frozen=True)
class StepPlan:
    """A compiled step's bucket plan with its per-phase predicted totals
    — one row of the probe-free fit's design matrix."""

    buckets: tuple
    sig: str
    fixed_us: float
    bytes_us: float
    codec_us: float
    predicted_us: float


@dataclasses.dataclass(frozen=True)
class StepSample:
    """One measured step against the plan it ran."""

    step: int
    step_us: float
    plan_sig: str
    fixed_us: float  # the plan's predicted per-phase totals
    bytes_us: float
    codec_us: float
    predicted_us: float


def plan_from_capture(captured) -> StepPlan | None:
    """Build a :class:`StepPlan` from ``plan_capture()`` output.  Spans
    whose provenance carries no prediction (``predicted_error`` — the
    cost model raised at trace time — or a bare span) are skipped, never
    crashed on; ``None`` when nothing usable was captured."""
    buckets = []
    for name, prov in captured:
        if not isinstance(prov, dict) or prov.get("predicted_error"):
            continue
        predicted = prov.get("predicted")
        pred_us = prov.get("predicted_us")
        nbytes = prov.get("nbytes")
        if not isinstance(predicted, dict) or not isinstance(
            pred_us, (int, float)
        ) or nbytes is None:
            continue
        topo = prov.get("topo") or {}
        world = prov.get("world") or {}
        for ax in sorted(topo):
            spec = str(topo[ax])
            if spec == "psum":
                continue  # no cost-model row: nothing to attribute
            if spec == "1":
                spec = "ring"
            w = world.get(ax)
            buckets.append(
                PlannedBucket(
                    name=str(name),
                    axis=str(ax),
                    topo=spec,
                    world=int(w) if w is not None else None,
                    nbytes=int(nbytes),
                    codec=str(prov.get("codec", "f32")),
                    sharded=bool(prov.get("sharded", False)),
                    predicted=dict(predicted),
                    predicted_us=float(pred_us),
                )
            )
    if not buckets:
        return None
    buckets = tuple(buckets)
    sig_src = [
        (b.topo, b.world, b.codec, b.sharded, b.nbytes) for b in buckets
    ]
    sig = hashlib.sha256(
        json.dumps(sig_src, sort_keys=True).encode("utf-8")
    ).hexdigest()[:12]
    return StepPlan(
        buckets=buckets,
        sig=sig,
        fixed_us=sum(b.fixed_us for b in buckets),
        bytes_us=sum(b.bytes_us for b in buckets),
        codec_us=sum(b.codec_us for b in buckets),
        predicted_us=sum(b.predicted_us for b in buckets),
    )


class StepSpanClock:
    """The in-step span clock: hold the current compile's plan, fold each
    materialized step's wall time into per-step measured spans.

    ``compute_floor_us``: the step's non-comm floor when the caller knows
    it (e.g. a timed sync-free twin — zero collectives, so supplying one
    keeps a probe-free run probe-free).  ``None`` derives a provisional
    floor: ``min(step_us − predicted_comm_us)`` over completed steps,
    clamped at 0 — exact enough to *detect* over-predicted comm, refined
    to a fitted intercept by the rotation fit (``planner.feedback``).
    ``sample_every`` thins event emission (samples still accumulate every
    step).  The caller gates on the flight recorder; the clock itself is
    pure host bookkeeping.
    """

    def __init__(
        self,
        compute_floor_us: float | None = None,
        sample_every: int = 1,
        fingerprint: str | None = None,
        max_samples: int = 512,
    ):
        self.compute_floor_us = (
            float(compute_floor_us) if compute_floor_us is not None else None
        )
        self.sample_every = max(1, int(sample_every))
        self.fingerprint = fingerprint
        self.plan: StepPlan | None = None
        self._plan_steps = 0  # steps observed under the current plan
        self._floor_min: float | None = None  # provisional-floor tracker
        # bounded to the recent regime: a healthy run must not grow the
        # buffer forever (the same invariant the controller's residual
        # deque keeps), and a refit should solve from recent windows —
        # 512 steps comfortably covers a full rotation cycle set
        self.samples: deque[StepSample] = deque(
            maxlen=max(int(max_samples), 8)
        )
        self.dropped_first = 0  # compile steps excluded per plan

    # -- plan management -----------------------------------------------

    def set_plan(self, captured) -> StepPlan | None:
        """Adopt a freshly-captured compile-time plan (the step that
        produced the capture is the COMPILING call — its duration will be
        excluded).  Returns the adopted plan, or None when the capture
        held nothing usable (the previous plan is kept)."""
        plan = plan_from_capture(captured)
        if plan is None:
            return None
        self.plan = plan
        self._plan_steps = 0
        return plan

    @property
    def floor_us(self) -> float | None:
        """The best available compute floor: the configured one, else the
        provisional minimum of (step − predicted comm) seen so far."""
        if self.compute_floor_us is not None:
            return self.compute_floor_us
        return self._floor_min

    # -- the per-step hook ---------------------------------------------

    def observe_step(self, step: int, dur_s: float) -> StepSample | None:
        """Fold one materialized step's wall time.  Returns the
        :class:`StepSample` (also appended to ``samples``), or None when
        no plan is known or this is the plan's first (compiling) step."""
        plan = self.plan
        if plan is None:
            return None
        self._plan_steps += 1
        if self._plan_steps == 1:
            # the compiling call: its wall time is tracing+compilation
            self.dropped_first += 1
            return None
        step_us = float(dur_s) * 1e6
        if self.compute_floor_us is None:
            slack = max(step_us - plan.predicted_us, 0.0)
            if self._floor_min is None or slack < self._floor_min:
                self._floor_min = slack
        sample = StepSample(
            step=int(step),
            step_us=step_us,
            plan_sig=plan.sig,
            fixed_us=plan.fixed_us,
            bytes_us=plan.bytes_us,
            codec_us=plan.codec_us,
            predicted_us=plan.predicted_us,
        )
        self.samples.append(sample)
        if (self._plan_steps - 2) % self.sample_every == 0:
            self._emit(sample, plan)
        return sample

    def comm_us(self, sample: StepSample) -> float | None:
        """The sample's measured comm estimate under the current floor
        (None while no floor exists)."""
        floor = self.floor_us
        if floor is None:
            return None
        return max(sample.step_us - floor, 1e-3)

    # -- event emission -------------------------------------------------

    def _emit(self, sample: StepSample, plan: StepPlan) -> None:
        floor = self.floor_us
        comm = self.comm_us(sample)
        record_event(
            "step_measured",
            step=sample.step,
            step_us=round(sample.step_us, 3),
            floor_us=round(floor, 3) if floor is not None else None,
            comm_us=round(comm, 3) if comm is not None else None,
            predicted_us=round(plan.predicted_us, 3),
            plan_sig=plan.sig,
            n_buckets=len(plan.buckets),
        )
        if comm is None or plan.predicted_us <= 0:
            return
        for b in plan.buckets:
            share = b.predicted_us / plan.predicted_us
            record_event(
                "bucket_measured",
                name=b.name,
                axis=b.axis,
                topo={b.axis: b.topo},
                world={b.axis: b.world},
                nbytes=b.nbytes,
                codec=b.codec,
                sharded=b.sharded,
                measured_us=round(comm * share, 3),
                predicted_us=round(b.predicted_us, 3),
                predicted=b.predicted,
                fingerprint=self.fingerprint,
                step=sample.step,
                per_step=True,
                apportioned=True,
                plan_sig=plan.sig,
                floor_us=round(floor, 3),
            )
