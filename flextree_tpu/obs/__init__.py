"""Unified telemetry: flight recorder, metrics registry, trace timeline.

The rest of the package emits *fragments* of observability — ``comm_span``
named scopes, ``RunReport`` accounting, serving timestamps, bench JSON
artifacts.  This package is the single place they meet:

- :mod:`~flextree_tpu.obs.recorder` — a bounded, lock-cheap per-rank
  **flight recorder**: a ring buffer of structured events (step
  boundaries, bucket plans with provenance, heartbeats, lease verdicts,
  shrinks, serving request lifecycle) that spills to an append-only JSONL
  file and writes a **guaranteed dump** on every failure path, so a chaos
  scenario leaves a forensic record instead of only a pass/fail bit;
- :mod:`~flextree_tpu.obs.metrics` — a **metrics registry** of counters /
  gauges / fixed-bucket histograms with bounded memory and a stable JSON
  snapshot, replacing ad-hoc stamp lists;
- :mod:`~flextree_tpu.obs.timeline` — the **cross-rank merger**: fuse
  per-rank event files into one Chrome-trace/Perfetto-loadable JSON
  (ranks as tracks, requests and buckets as flows, every comm event
  carrying its plan provenance and predicted cost).

Instrumentation sites call :func:`record_event` — a module-global read
plus a ``None`` check when no recorder is installed, so the library pays
nothing until a run opts in (``with flight_recorder(dir, rank):`` or the
trainer's ``--obs-dir``/``--flight-recorder`` flags).  See
``docs/OBSERVABILITY.md`` for the event schema and how to open a merged
timeline in Perfetto.
"""

from .metrics import (
    Counter,
    FrozenWindow,
    Gauge,
    Histogram,
    MetricsRegistry,
    WindowedHistogram,
    load_window,
    merged_window_percentile,
    prometheus_exposition,
)
from .provenance import bucket_provenance, topo_spec
from .recorder import (
    FlightRecorder,
    current_recorder,
    dump_current,
    flight_recorder,
    get_registry,
    install_signal_dump,
    record_event,
)
from .stepclock import StepPlan, StepSample, StepSpanClock, plan_from_capture
from .timeline import (
    ResidualSample,
    merge_dir,
    merge_events,
    read_dir,
    read_events,
    residual_group_key,
    residual_pairs,
    residual_table,
    validate_trace,
    write_trace,
)

__all__ = [
    "bucket_provenance",
    "topo_spec",
    "Counter",
    "Gauge",
    "Histogram",
    "WindowedHistogram",
    "FrozenWindow",
    "load_window",
    "merged_window_percentile",
    "prometheus_exposition",
    "MetricsRegistry",
    "StepSpanClock",
    "StepPlan",
    "StepSample",
    "plan_from_capture",
    "FlightRecorder",
    "flight_recorder",
    "current_recorder",
    "record_event",
    "dump_current",
    "get_registry",
    "install_signal_dump",
    "merge_dir",
    "merge_events",
    "read_dir",
    "read_events",
    "ResidualSample",
    "residual_group_key",
    "residual_pairs",
    "residual_table",
    "validate_trace",
    "write_trace",
]
