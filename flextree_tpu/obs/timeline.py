"""Cross-rank timeline merger: per-rank event files → one Chrome trace.

Reads every ``flight_*.jsonl`` (and ``*.dump.json`` sidecar) a run left
in its obs directory and fuses them into one JSON document loadable by
Perfetto (https://ui.perfetto.dev) or ``chrome://tracing``:

- each **rank is a track** (trace ``pid``; the event's ``src`` — train /
  serve / peer — names the process), with the recorder's main lane and
  the heartbeat daemon's lane as separate ``tid``\\ s so beats don't
  visually interleave with steps;
- paired ``*_start``/``*_end`` kinds (steps today; any future pair works
  by naming convention) become **complete events** (``ph: "X"``) whose
  duration is the measured wall-time between the pair;
- ``bucket_planned``/``bucket_fired`` comm events become spans whose
  duration is the *planner's predicted* time and whose ``args`` carry
  the full plan provenance (topo widths/codec/sharded + the predicted
  ``CostBreakdown``), so predicted-vs-measured per-phase residuals can
  be read off any run's timeline;
- serving request lifecycles (``serve_admit`` → ``serve_retire``)
  become **flow arrows** keyed by request id — a re-routed request's
  arrow visibly jumps tracks;
- arbiter decisions (``slo_breach``, ``lease_preempt``/``lease_grant``/
  ``lease_return``, the trainer's ``lease_resize``) render on a
  dedicated **arbiter lane** with the SLO reading in their ``args``, so
  every chip reallocation is visible beside the train/serve spans it
  caused;
- coordination-protocol events (``coord_propose``/``coord_ack``/
  ``coord_commit``/``coord_repropose``/``coord_failover``/
  ``coord_fence``/``coord_apply``, ``runtime/coordination.py``) render
  on a dedicated **coordination lane**, so a merged trace shows which
  rank proposed each control epoch, who acked late, where the commit
  landed and who got fenced;
- everything else is an instant event carrying its fields as ``args``.

Timestamps are wall-clock (the recorders stamp with ``time.time`` for
exactly this reason); the merger rebases to the earliest event so the
trace starts at 0 µs.  :func:`validate_trace` is the schema check the
tests, the chaos driver, and the bench tripwire share — "loadable
Chrome-trace JSON" is machine-checked, not assumed.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import statistics

__all__ = [
    "read_events",
    "read_dir",
    "merge_events",
    "merge_dir",
    "validate_trace",
    "write_trace",
    "ResidualSample",
    "residual_pairs",
    "residual_table",
    "residual_group_key",
    "phase_components",
]

#: kinds rendered on the heartbeat lane (tid 1) instead of the main lane
_HEARTBEAT_KINDS = frozenset({"heartbeat"})

#: arbiter-decision kinds rendered on their own lane (tid 2), so every
#: chip reallocation is visible BESIDE the train/serve spans it caused —
#: slo_breach carries the SLO reading, the lease_* kinds carry the chips
_ARBITER_KINDS = frozenset(
    {"slo_breach", "lease_grant", "lease_preempt", "lease_return",
     "lease_resize"}
)

#: coordination-protocol kinds (runtime/coordination.py) rendered on their
#: own lane (tid 3), the same pattern as the arbiter lane: a merged trace
#: shows which rank proposed, who acked (and who acked late), where the
#: commit landed, who took over after a coordinator death, and who got
#: fenced — plus the control-plane health events (torn control files,
#: wall-clock regressions) beside the decisions they endangered
_COORD_KINDS = frozenset(
    {"coord_propose", "coord_ack", "coord_commit", "coord_repropose",
     "coord_failover", "coord_fence", "coord_apply", "coord_commit_race",
     "torn_control_file", "clock_regression"}
)

#: paired-kind suffixes → complete events
_START_SUFFIX, _END_SUFFIX = "_start", "_end"

#: comm-plan kinds rendered as predicted-duration spans
_PLAN_KINDS = frozenset({"bucket_planned", "bucket_fired", "collective"})

#: measured-comm kinds rendered as spans whose duration is the MEASURED
#: time — the twin of the comm-plan spans above, so Perfetto shows the
#: prediction and the measurement side by side.  ``bucket_measured``
#: comes from the feedback prober's timed collectives (planner/
#: feedback.py) AND from the per-step span clock (obs/stepclock.py:
#: ``per_step: true``, host-timed steps apportioned over the compile-time
#: plan); ``serve_round_measured`` is the serving engine's decode round
#: against the paged-decode cost estimate (serving/costs.py).
_MEASURED_KINDS = frozenset({"bucket_measured", "serve_round_measured"})

#: whole-step measured spans (obs/stepclock.py): duration is the step's
#: host wall time, args carry the comm/floor split and the plan signature
_STEP_MEASURED_KINDS = frozenset({"step_measured"})

_META_KEYS = frozenset({"ts", "rank", "src", "seq", "kind"})


def read_events(path: str) -> list[dict]:
    """Parse one JSONL event file, tolerating a torn final line (the
    writer may have been SIGKILL'd mid-write — everything before the
    tear is still evidence)."""
    out: list[dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue  # torn tail
            if isinstance(ev, dict) and "kind" in ev and "ts" in ev:
                out.append(ev)
    return out


def read_dir(dir: str) -> tuple[list[dict], dict[int, dict]]:
    """(events, dumps-by-rank) from every flight file under ``dir``."""
    events: list[dict] = []
    for path in sorted(glob.glob(os.path.join(dir, "flight_*.jsonl"))):
        events.extend(read_events(path))
    dumps: dict[int, dict] = {}
    for path in sorted(glob.glob(os.path.join(dir, "flight_*.dump.json"))):
        try:
            with open(path, encoding="utf-8") as f:
                d = json.load(f)
            dumps[int(d["rank"])] = d
        except (OSError, ValueError, KeyError):
            continue
    return events, dumps


def _args(ev: dict) -> dict:
    return {k: v for k, v in ev.items() if k not in _META_KEYS}


def _pair_key(ev: dict, base: str):
    """Identity connecting a ``*_start`` to its ``*_end``: the rank plus
    the pair's own id — an explicit ``id`` field wins over ``step``
    (``fit_start``/``fit_end`` share an ``id`` while their ``step``
    fields legitimately differ: a run starts at ``start`` and ends at
    the final step)."""
    return (ev.get("rank", 0), base, ev.get("id", ev.get("step")))


def merge_events(events, dumps: dict[int, dict] | None = None) -> dict:
    """Fuse recorder events into one Chrome-trace JSON document."""
    # defense against duplicated spill lines (a retried batch, a file
    # read twice): identical (rank, seq, ts, kind) is the same event.
    # ts is part of the key because seq restarts at 0 when a later
    # process appends to the same rank's file (the resume-after-SIGTERM
    # pattern) — those are distinct events, not duplicates.
    seen: set = set()
    deduped = []
    for ev in events:
        key = (ev.get("rank", 0), ev.get("seq"), ev["ts"], ev["kind"])
        if key in seen:
            continue
        seen.add(key)
        deduped.append(ev)
    events = sorted(deduped, key=lambda e: (e["ts"], e.get("seq", 0)))
    t0 = events[0]["ts"] if events else 0.0

    def us(ts: float) -> float:
        return round((ts - t0) * 1e6, 1)

    trace: list[dict] = []
    ranks: dict[int, str] = {}
    arbiter_ranks: set = set()
    coord_ranks: set = set()
    open_pairs: dict = {}
    flow_open: set = set()

    for ev in events:
        rank = int(ev.get("rank", 0))
        ranks.setdefault(rank, str(ev.get("src", "rank")))
        kind = str(ev["kind"])
        tid = 1 if kind in _HEARTBEAT_KINDS else 0
        if kind in _ARBITER_KINDS:
            tid = 2
            arbiter_ranks.add(rank)
        elif kind in _COORD_KINDS:
            tid = 3
            coord_ranks.add(rank)
        common = {"pid": rank, "tid": tid, "ts": us(ev["ts"])}

        if kind.endswith(_START_SUFFIX):
            open_pairs[_pair_key(ev, kind[: -len(_START_SUFFIX)])] = ev
            continue
        if kind.endswith(_END_SUFFIX):
            base = kind[: -len(_END_SUFFIX)]
            start = open_pairs.pop(_pair_key(ev, base), None)
            if start is not None:
                pair_id = _pair_key(ev, base)[2]
                name = base if pair_id is None else f"{base} {pair_id}"
                trace.append(
                    {
                        "name": name,
                        "cat": base,
                        "ph": "X",
                        **common,
                        "ts": us(start["ts"]),
                        "dur": max(round((ev["ts"] - start["ts"]) * 1e6, 1), 0.1),
                        "args": {**_args(start), **_args(ev)},
                    }
                )
                continue
            # unmatched end (start predates the ring / the file): instant
            trace.append(
                {"name": kind, "cat": base, "ph": "i", "s": "t", **common,
                 "args": _args(ev)}
            )
            continue

        if kind in _PLAN_KINDS:
            args = _args(ev)
            dur = max(float(args.get("predicted_us") or 1.0), 1.0)
            trace.append(
                {
                    "name": str(args.get("name", kind)),
                    "cat": "comm-plan",
                    "ph": "X",
                    **common,
                    "dur": round(dur, 1),
                    "args": args,
                }
            )
            continue

        if kind in _MEASURED_KINDS:
            args = _args(ev)
            dur = max(float(args.get("measured_us") or 1.0), 1.0)
            trace.append(
                {
                    "name": str(args.get("name", kind)),
                    "cat": (
                        "serve-measured"
                        if kind == "serve_round_measured"
                        else "comm-measured"
                    ),
                    "ph": "X",
                    **common,
                    "dur": round(dur, 1),
                    "args": args,
                }
            )
            continue

        if kind in _STEP_MEASURED_KINDS:
            args = _args(ev)
            dur = max(float(args.get("step_us") or 1.0), 1.0)
            trace.append(
                {
                    "name": f"step_measured {args.get('step', '')}".strip(),
                    "cat": "step-measured",
                    "ph": "X",
                    **common,
                    "dur": round(dur, 1),
                    "args": args,
                }
            )
            continue

        if kind.startswith("serve_") and "rid" in ev:
            rid = int(ev["rid"])
            trace.append(
                {"name": kind, "cat": "serve", "ph": "i", "s": "t", **common,
                 "args": _args(ev)}
            )
            flow = {"name": f"request {rid}", "cat": "request", "id": rid,
                    **common}
            if kind == "serve_admit" and rid not in flow_open:
                flow_open.add(rid)
                trace.append({**flow, "ph": "s"})
            elif kind == "serve_retire" and rid in flow_open:
                flow_open.discard(rid)
                trace.append({**flow, "ph": "f", "bp": "e"})
            elif rid in flow_open:
                trace.append({**flow, "ph": "t"})
            continue

        if kind.startswith("serve_prefix"):
            # rid-less prefix-cache events (``serve_prefix_evict``) still
            # belong on the serve lane, not the generic fallback
            trace.append(
                {"name": kind, "cat": "serve", "ph": "i", "s": "t",
                 **common, "args": _args(ev)}
            )
            continue

        if kind in _ARBITER_KINDS:
            # process-scoped instants: a chip reallocation concerns every
            # lane of the track, not one thread's local moment
            trace.append(
                {"name": kind, "cat": "arbiter", "ph": "i", "s": "p",
                 **common, "args": _args(ev)}
            )
            continue

        if kind in _COORD_KINDS:
            # handshake phases as process-scoped instants on the
            # coordination lane: a control epoch concerns the whole rank
            trace.append(
                {"name": kind, "cat": "coordination", "ph": "i", "s": "p",
                 **common, "args": _args(ev)}
            )
            continue

        scope = "p" if kind in ("dump", "shrink", "preempt") else "t"
        trace.append(
            {"name": kind, "cat": kind, "ph": "i", "s": scope, **common,
             "args": _args(ev)}
        )

    # unmatched starts: the step a rank never finished — the cut-off
    # moment a forensic timeline exists to show — rendered as instants
    for (rank, base, pair_id), start in sorted(
        open_pairs.items(), key=lambda kv: kv[1]["ts"]
    ):
        trace.append(
            {
                "name": (f"{base} {pair_id}" if pair_id is not None else base)
                + " (unfinished)",
                "cat": base,
                "ph": "i",
                "s": "p",
                "pid": int(rank),
                "tid": 0,
                "ts": us(start["ts"]),
                "args": _args(start),
            }
        )

    # track names + dump summaries
    for rank, src in sorted(ranks.items()):
        trace.append(
            {"name": "process_name", "ph": "M", "pid": rank, "tid": 0,
             "args": {"name": f"rank {rank} ({src})"}}
        )
        trace.append(
            {"name": "thread_name", "ph": "M", "pid": rank, "tid": 0,
             "args": {"name": "events"}}
        )
        trace.append(
            {"name": "thread_name", "ph": "M", "pid": rank, "tid": 1,
             "args": {"name": "heartbeat"}}
        )
        if rank in arbiter_ranks:
            trace.append(
                {"name": "thread_name", "ph": "M", "pid": rank, "tid": 2,
                 "args": {"name": "arbiter"}}
            )
        if rank in coord_ranks:
            trace.append(
                {"name": "thread_name", "ph": "M", "pid": rank, "tid": 3,
                 "args": {"name": "coordination"}}
            )

    doc = {
        "traceEvents": trace,
        "displayTimeUnit": "ms",
        "otherData": {
            "tool": "flextree_tpu.obs",
            "ranks": sorted(ranks),
            "events": len(events),
            "epoch_s": t0,
            "dumps": {
                str(r): {"reason": d.get("reason"),
                         "events": len(d.get("events", ()))}
                for r, d in sorted((dumps or {}).items())
            },
        },
    }
    return doc


def merge_dir(dir: str) -> dict:
    """Merge every per-rank flight file under ``dir``."""
    events, dumps = read_dir(dir)
    return merge_events(events, dumps)


_VALID_PH = frozenset("BEXiIsMtfPNODC")


def validate_trace(doc) -> list[str]:
    """Schema-validity violations of a merged timeline (empty = loadable
    Chrome-trace JSON, object format).  The checks mirror what the
    Perfetto/catapult loaders actually require: a ``traceEvents`` list
    whose entries carry ``name``/``ph``/``ts``/``pid``/``tid``, complete
    events with a non-negative ``dur``, and flow starts matched by flow
    finishes."""
    bad: list[str] = []
    if not isinstance(doc, dict) or not isinstance(
        doc.get("traceEvents"), list
    ):
        return ["document is not a dict with a traceEvents list"]
    flows: dict = {}
    for i, ev in enumerate(doc["traceEvents"]):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            bad.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in _VALID_PH:
            bad.append(f"{where}: bad ph {ph!r}")
            continue
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            bad.append(f"{where}: missing name")
        if ph != "M":
            for key in ("ts", "pid", "tid"):
                if not isinstance(ev.get(key), (int, float)):
                    bad.append(f"{where}: missing/non-numeric {key}")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                bad.append(f"{where}: complete event with bad dur {dur!r}")
        if ph in "stf":
            if "id" not in ev:
                bad.append(f"{where}: flow event without id")
            elif ph != "t":
                flows[ev["id"]] = flows.get(ev["id"], 0) + (1 if ph == "s" else -1)
        if "args" in ev:
            try:
                json.dumps(ev["args"])
            except (TypeError, ValueError):
                bad.append(f"{where}: args not JSON-serializable")
    for fid, n in sorted(flows.items()):
        # an s without an f is fine (a request in flight when the rank
        # died is exactly what a forensic timeline shows); an f that was
        # never opened is a merger bug
        if n < 0:
            bad.append(f"flow id {fid}: finish without start")
    return bad


# ---------------------------------------------------------------------------
# predicted-vs-measured residual query (planner feedback, ISSUE 12)
#
# ``bucket_planned`` events carry the planner's predicted CostBreakdown for
# a comm span (obs/provenance.py — per-compile, the plan as priced);
# ``bucket_measured`` events carry a MEASURED wall time for the same
# (topo, world, codec, sharded, nbytes) point (the feedback prober's timed
# collective runs, planner/feedback.py).  Pairing them yields the
# predicted-vs-measured residual samples the closed-loop fitter consumes —
# this module owns the pairing so the ``python -m flextree_tpu.obs
# residuals`` CLI and ``planner.feedback``'s extractor share one code path
# and cannot diverge.
# ---------------------------------------------------------------------------


#: CostBreakdown terms grouped into the three independently-identifiable
#: phases (shared with obs/stepclock.py and the planner.feedback phase
#: fit): per-message fixed costs, byte-proportional costs (wire +
#: reduce, structurally collinear on an f32 wire), and codec work.
_PHASE_TERMS = {
    "fixed": ("latency_us", "control_us"),
    "bytes": ("bandwidth_us", "reduce_us"),
    "codec": ("codec_us",),
}


def phase_components(breakdown: dict | None) -> dict | None:
    """Collapse a per-term ``CostBreakdown`` dict into the three fit
    phases ``{"fixed", "bytes", "codec"}`` (µs).  None in, None out."""
    if not isinstance(breakdown, dict):
        return None
    return {
        phase: sum(float(breakdown.get(t, 0.0)) for t in terms)
        for phase, terms in _PHASE_TERMS.items()
    }


@dataclasses.dataclass(frozen=True)
class ResidualSample:
    """One predicted-vs-measured comm point read off a flight record."""

    topo: str  # FT_TOPO-style spec of the axis's topology ("4,2", "ring")
    world: int | None  # group size on that axis (None: unknown/psum)
    codec: str
    sharded: bool
    nbytes: int
    predicted_us: float
    measured_us: float
    fingerprint: str | None = None  # measuring backend, when recorded
    step: int | None = None
    ts: float | None = None
    #: "paired" when the prediction came from a matching ``bucket_planned``
    #: span; "self" when the measured event carried its own prediction
    #: (the prober prices with the same model the planner used); "step"
    #: for per-step span-clock samples (obs/stepclock.py) — host-timed
    #: step totals apportioned over the compile-time plan, so within one
    #: step their measured/predicted ratios are uniform by construction
    #: (they feed the phase-scale fit and the drift detector, never the
    #: point-wise α-β solve)
    source: str = "paired"
    #: the predicted per-term CostBreakdown behind ``predicted_us`` when
    #: the record carried one — the component-wise residual material the
    #: per-phase fit consumes (planner.feedback.fit_phase_scales)
    predicted_breakdown: dict | None = None

    @property
    def rel_residual(self) -> float:
        """|predicted - measured| / measured — the drift-band quantity."""
        return abs(self.predicted_us - self.measured_us) / max(
            self.measured_us, 1e-9
        )

    @property
    def phases(self) -> dict | None:
        """Predicted µs per fit phase (fixed / bytes / codec), or None
        when the record carried no breakdown."""
        return phase_components(self.predicted_breakdown)


def _plan_points(ev: dict):
    """(topo_spec, world) per axis of a plan/measured event —
    provenance records one event per axis (axes is a 1-tuple at both call
    sites), but tolerate multi-axis payloads by yielding each axis.
    Ring specs are normalized: provenance labels the ring topology
    ``"ring"`` while the wire grammar's sentinel is ``"1"`` — the pairing
    must treat them as one point."""
    topo = ev.get("topo") or {}
    world = ev.get("world") or {}
    for ax in sorted(topo):
        w = world.get(ax)
        spec = str(topo[ax])
        if spec == "1":
            spec = "ring"
        yield spec, (int(w) if w is not None else None)


def _pairing_keys(ev: dict):
    nbytes = ev.get("nbytes")
    if nbytes is None:
        return
    for spec, world in _plan_points(ev):
        yield (
            spec,
            world,
            str(ev.get("codec", "f32")),
            bool(ev.get("sharded", False)),
            int(nbytes),
        )


def residual_pairs(events) -> tuple[list[ResidualSample], dict]:
    """Pair ``bucket_planned`` predictions with ``bucket_measured`` times.

    Returns ``(samples, skipped)`` where ``skipped`` counts events that
    produced no sample and why: ``predicted_error`` (the cost model raised
    at trace time — obs/provenance.py's never-break-a-trace path; such
    spans are skipped, never crashed on), ``unpredicted`` (a measured
    point with no prediction on either side), ``invalid_measured`` (a
    measured event whose ``measured_us`` is missing or non-positive —
    a torn write or producer bug, not a pairing gap), ``unmeasured_plans``
    (planned spans that no probe ever measured — expected: plans are
    per-compile, probes are per-tick).
    """
    skipped = {
        "predicted_error": 0,
        "unpredicted": 0,
        "invalid_measured": 0,
        "unmeasured_plans": 0,
    }
    predicted: dict[tuple, tuple] = {}  # key -> (pred_us, breakdown|None)
    matched: set = set()
    for ev in events:
        if ev.get("kind") != "bucket_planned":
            continue
        if ev.get("predicted_error"):
            skipped["predicted_error"] += 1
            continue
        pred = ev.get("predicted_us")
        if not isinstance(pred, (int, float)):
            continue  # a bare span with no costed prediction: nothing to pair
        breakdown = ev.get("predicted")
        breakdown = dict(breakdown) if isinstance(breakdown, dict) else None
        for key in _pairing_keys(ev):
            # latest prediction wins: a recompile re-prices the same point
            predicted[key] = (float(pred), breakdown)

    samples: list[ResidualSample] = []
    for ev in events:
        if ev.get("kind") != "bucket_measured":
            continue
        meas = ev.get("measured_us")
        if not isinstance(meas, (int, float)) or meas <= 0:
            skipped["invalid_measured"] += 1
            continue
        keys = list(_pairing_keys(ev))
        if not keys:
            skipped["unpredicted"] += 1
            continue
        own_breakdown = ev.get("predicted")
        own_breakdown = (
            dict(own_breakdown) if isinstance(own_breakdown, dict) else None
        )
        per_step = bool(ev.get("per_step"))
        for key in keys:
            spec, world, codec, sharded, nbytes = key
            if key in predicted:
                (pred, breakdown), source = predicted[key], "paired"
                matched.add(key)
                # the measured event's own breakdown is the fresher view
                # (the prober/span clock prices with the live constants)
                breakdown = own_breakdown or breakdown
            elif isinstance(ev.get("predicted_us"), (int, float)):
                pred, source = float(ev["predicted_us"]), "self"
                breakdown = own_breakdown
            else:
                skipped["unpredicted"] += 1
                continue
            samples.append(
                ResidualSample(
                    topo=spec,
                    world=world,
                    codec=codec,
                    sharded=sharded,
                    nbytes=nbytes,
                    predicted_us=pred,
                    measured_us=float(meas),
                    fingerprint=ev.get("fingerprint"),
                    step=ev.get("step"),
                    ts=ev.get("ts"),
                    source="step" if per_step else source,
                    predicted_breakdown=breakdown,
                )
            )
    skipped["unmeasured_plans"] = len(set(predicted) - matched)
    return samples, skipped


def residual_group_key(s: ResidualSample) -> tuple:
    """The CLI/fit grouping of a residual sample: (topo, codec, tier)
    where ``tier`` is the group size plus the sharded flag (the per-tier
    grouping the two-tier roadmap item will refine)."""
    tier = f"n{s.world if s.world is not None else '?'}" + (
        "/sharded" if s.sharded else ""
    )
    return (s.topo, s.codec, tier)


def _phase_mix(grp) -> str:
    """Median predicted per-phase mix of a sample group, as
    ``fixed/bytes/codec`` percentage string (``-`` when no sample in the
    group carried a breakdown)."""
    mixes = []
    for s in grp:
        ph = s.phases
        if ph is None:
            continue
        total = sum(ph.values())
        if total <= 0:
            continue
        mixes.append([ph["fixed"] / total, ph["bytes"] / total,
                      ph["codec"] / total])
    if not mixes:
        return "-"
    med = [
        statistics.median(m[i] for m in mixes) for i in range(3)
    ]
    return "/".join(f"{round(100 * v):d}" for v in med) + "%"


def residual_table(
    samples, skipped: dict | None = None, attribution: dict | None = None
) -> str:
    """Human-readable per-(topo, codec, tier) residual summary — the CLI
    twin of the feedback fitter's extractor (``python -m flextree_tpu.obs
    residuals DIR``).  The ``phases f/b/c`` column is the group's median
    predicted phase mix (fixed/bytes/codec — the component-wise
    ``CostBreakdown`` shares the per-phase fit consumes); ``attribution``
    optionally maps :func:`residual_group_key` keys to a drifted-phase
    string (``planner.feedback.attribute_groups``) rendered as a final
    ``drift`` column."""
    if not samples:
        lines = ["no predicted-vs-measured residual pairs in this record"]
        if skipped and skipped.get("unmeasured_plans"):
            lines.append(
                f"({skipped['unmeasured_plans']} planned span(s) were never "
                "measured: run with the feedback prober on — "
                "docs/FEEDBACK.md)"
            )
        return "\n".join(lines)

    groups: dict[tuple, list[ResidualSample]] = {}
    for s in samples:
        groups.setdefault(residual_group_key(s), []).append(s)
    head = (
        f"{'topo':>10} {'codec':>6} {'tier':>10} {'count':>6} "
        f"{'med pred':>10} {'med meas':>10} {'med |r|':>8} {'max |r|':>8} "
        f"{'phases f/b/c':>13}"
    )
    if attribution:
        head += f" {'drift':>14}"
    lines = [head, "-" * len(head)]
    for key, grp in sorted(groups.items()):
        topo, codec, tier = key
        row = (
            f"{topo:>10} {codec:>6} {tier:>10} {len(grp):>6} "
            f"{statistics.median(s.predicted_us for s in grp):>9.1f}u "
            f"{statistics.median(s.measured_us for s in grp):>9.1f}u "
            f"{statistics.median(s.rel_residual for s in grp):>8.3f} "
            f"{max(s.rel_residual for s in grp):>8.3f} "
            f"{_phase_mix(grp):>13}"
        )
        if attribution:
            row += f" {attribution.get(key, '-'):>14}"
        lines.append(row)
    if skipped:
        parts = [f"{k}={v}" for k, v in sorted(skipped.items()) if v]
        if parts:
            lines.append("skipped: " + ", ".join(parts))
    return "\n".join(lines)


def write_trace(doc: dict, path: str | os.PathLike) -> str:
    path = os.fspath(path)
    dirname = os.path.dirname(path)
    if dirname:
        os.makedirs(dirname, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return path
