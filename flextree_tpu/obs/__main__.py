"""CLI: merge per-rank flight-recorder files into one Chrome trace.

Usage::

    python -m flextree_tpu.obs merge  OBS_DIR --out timeline.json
    python -m flextree_tpu.obs validate timeline.json
    python -m flextree_tpu.obs summary OBS_DIR
    python -m flextree_tpu.obs residuals OBS_DIR [--fingerprint FP] [--json]
    python -m flextree_tpu.obs metrics OBS_DIR [--prom]
    python -m flextree_tpu.obs fleet OBS_DIR [OBS_DIR ...] [--json]
        [--fingerprint FP] [--fit-out CALIBRATION.json] [--backend B]

``merge`` fuses every ``flight_*.jsonl`` (+ ``*.dump.json``) under
OBS_DIR into one timeline (ranks as tracks, requests/buckets as flows)
and validates it before writing — a merge that would not load in
Perfetto exits non-zero.  Open the result at https://ui.perfetto.dev or
``chrome://tracing``.  ``summary`` prints per-rank event/dump counts —
the 10-second "what did this run leave behind".  ``residuals`` prints
the per-(topo, codec, tier) predicted-vs-measured comm residual table —
the human-readable twin of ``planner.feedback``'s extractor, built from
the SAME pairing code (``timeline.residual_pairs``) so the CLI and the
fitter cannot diverge (docs/FEEDBACK.md) — including the per-phase mix
column and drift attribution the per-phase fit consumes;
``--fingerprint`` narrows to one measuring backend and ``--json`` emits
the machine-readable sample list instead of the table.  ``metrics``
prints the per-rank ``metrics_{rank}.json`` registry snapshots; with
``--prom`` they render as Prometheus text exposition (histogram
``_bucket``/``_sum``/``_count`` series plus windowed ``_window_p99``
gauges), so serving SLO instruments are scrapeable without parsing the
JSON.  ``fleet`` is the cross-run pooling pass: it aggregates residual
samples from MANY runs' obs dirs per backend fingerprint and fits the
pooled set (``planner.feedback.fit_residuals_auto``) — one run's sample
is deliberately small, the fleet's is not — reporting each constituent
run's fit conditioning beside the pooled one; ``--fit-out`` persists the
pooled refit as a calibration section (``source="feedback"`` with the
fleet provenance in ``meta``).
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import sys
from collections import Counter as _Counter

from .metrics import prometheus_exposition
from .timeline import (
    merge_events,
    read_dir,
    residual_group_key,
    residual_pairs,
    residual_table,
    validate_trace,
    write_trace,
)


def _sample_json(s) -> dict:
    return {
        "topo": s.topo,
        "world": s.world,
        "codec": s.codec,
        "sharded": s.sharded,
        "nbytes": s.nbytes,
        "predicted_us": s.predicted_us,
        "measured_us": s.measured_us,
        "rel_residual": round(s.rel_residual, 6),
        "fingerprint": s.fingerprint,
        "step": s.step,
        "source": s.source,
        "phases": s.phases,
    }


def _dir_samples(dir: str):
    events, _dumps = read_dir(dir)
    return residual_pairs(events)


def _fit_condition(meta: dict) -> float | None:
    cond = meta.get("condition")
    return float(cond) if isinstance(cond, (int, float)) else None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="flextree_tpu.obs", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    mp = sub.add_parser("merge", help="merge an obs dir into a Chrome trace")
    mp.add_argument("dir")
    mp.add_argument("--out", default="timeline.json")
    vp = sub.add_parser("validate", help="schema-check a merged trace")
    vp.add_argument("trace")
    sp = sub.add_parser("summary", help="per-rank event/dump counts")
    sp.add_argument("dir")
    rp = sub.add_parser(
        "residuals",
        help="per-(topo, codec, tier) predicted-vs-measured residual table",
    )
    rp.add_argument("dir")
    rp.add_argument(
        "--fingerprint",
        help="only samples measured under this backend fingerprint",
    )
    rp.add_argument(
        "--json", action="store_true",
        help="emit the machine-readable sample list instead of the table",
    )
    xp = sub.add_parser(
        "metrics", help="per-rank metrics registry snapshots"
    )
    xp.add_argument("dir")
    xp.add_argument(
        "--prom", action="store_true",
        help="Prometheus text exposition instead of JSON",
    )
    fp = sub.add_parser(
        "fleet",
        help="pool residuals from many runs' obs dirs per fingerprint "
        "and fit the pooled set",
    )
    fp.add_argument("dirs", nargs="+")
    fp.add_argument("--fingerprint", help="fit only this fingerprint")
    fp.add_argument("--json", action="store_true")
    fp.add_argument(
        "--fit-out",
        help="persist the pooled refit as a calibration section "
        "(source='feedback', fleet provenance in meta)",
    )
    fp.add_argument(
        "--backend", default=None,
        help="calibration section name for --fit-out (default: the "
        "ambient jax backend)",
    )
    args = ap.parse_args(argv)

    if args.cmd == "merge":
        events, dumps = read_dir(args.dir)
        if not events:
            print(f"no flight_*.jsonl events under {args.dir}", file=sys.stderr)
            return 1
        doc = merge_events(events, dumps)
        bad = validate_trace(doc)
        if bad:
            for b in bad:
                print(f"invalid: {b}", file=sys.stderr)
            return 1
        path = write_trace(doc, args.out)
        print(
            f"merged {len(events)} events from {len(doc['otherData']['ranks'])} "
            f"rank(s) ({len(dumps)} dump(s)) -> {path}"
        )
        return 0

    if args.cmd == "validate":
        with open(args.trace, encoding="utf-8") as f:
            doc = json.load(f)
        bad = validate_trace(doc)
        for b in bad:
            print(f"invalid: {b}", file=sys.stderr)
        print(f"{args.trace}: {'INVALID' if bad else 'ok'} "
              f"({len(doc.get('traceEvents', []))} trace events)")
        return 1 if bad else 0

    if args.cmd == "residuals":
        samples, skipped = _dir_samples(args.dir)
        if not samples and not any(skipped.values()):
            print(f"no flight_*.jsonl events under {args.dir}", file=sys.stderr)
            return 1
        if args.fingerprint:
            samples = [s for s in samples if s.fingerprint == args.fingerprint]
        if args.json:
            print(json.dumps(
                {
                    "samples": [_sample_json(s) for s in samples],
                    "skipped": skipped,
                },
                indent=1, sort_keys=True,
            ))
            return 0
        # the per-group drift attribution the per-phase fit computes —
        # lazy import keeps obs importable without the planner stack
        attribution = None
        if samples:
            try:
                from ..planner.feedback import attribute_groups

                attribution = attribute_groups(samples)
            except Exception:  # noqa: BLE001 — the table must still print
                attribution = None
        print(residual_table(samples, skipped, attribution=attribution))
        return 0

    if args.cmd == "metrics":
        snaps: dict[str, dict] = {}
        for path in sorted(
            _glob.glob(os.path.join(args.dir, "metrics_*.json"))
        ):
            stem = os.path.splitext(os.path.basename(path))[0]
            rank = stem.split("_", 1)[1] if "_" in stem else stem
            try:
                with open(path, encoding="utf-8") as f:
                    snaps[rank] = json.load(f)
            except (OSError, ValueError) as e:
                print(f"skipping {path}: {e}", file=sys.stderr)
        if not snaps:
            print(f"no metrics_*.json under {args.dir}", file=sys.stderr)
            return 1
        if args.prom:
            sys.stdout.write(prometheus_exposition(snaps))
        else:
            print(json.dumps(snaps, indent=1, sort_keys=True))
        return 0

    if args.cmd == "fleet":
        from ..planner.feedback import FeedbackRefused, fit_residuals_auto

        runs = []
        by_fp: dict = {}
        fp_runs: dict = {}
        for dir in args.dirs:
            samples, skipped = _dir_samples(dir)
            if args.fingerprint:
                samples = [
                    s for s in samples if s.fingerprint == args.fingerprint
                ]
            row = {
                "dir": dir,
                "samples": len(samples),
                "skipped": skipped,
                "condition": None,
                "mode": None,
                "refused": None,
            }
            if samples:
                try:
                    _params, meta = fit_residuals_auto(samples)
                    row["condition"] = _fit_condition(meta)
                    row["mode"] = meta.get("mode")
                except FeedbackRefused as e:
                    row["refused"] = str(e)[:200]
            else:
                row["refused"] = "no residual samples"
            runs.append(row)
            for s in samples:
                by_fp.setdefault(s.fingerprint, []).append(s)
                fp_runs.setdefault(s.fingerprint, set()).add(dir)

        pooled: dict = {}
        fitted_params: dict = {}
        for fpr, samples in sorted(
            by_fp.items(), key=lambda kv: str(kv[0])
        ):
            entry = {
                "samples": len(samples),
                "runs": len(fp_runs.get(fpr, ())),
                "condition": None,
                "mode": None,
                "drifted_phase": None,
                "refused": None,
            }
            try:
                params, meta = fit_residuals_auto(samples)
                entry["condition"] = _fit_condition(meta)
                entry["mode"] = meta.get("mode")
                entry["drifted_phase"] = meta.get("drifted_phase")
                fitted_params[fpr] = (params, meta)
            except FeedbackRefused as e:
                entry["refused"] = str(e)[:200]
            pooled[str(fpr)] = entry

        out_doc = {"runs": runs, "pooled": pooled, "fit_out": None}
        if args.fit_out and fitted_params:
            from ..planner.calibrate import save_calibration

            # persist the pooled fit with the most samples (or the one
            # --fingerprint selected)
            fpr = max(
                fitted_params, key=lambda k: len(by_fp[k])
            )
            params, meta = fitted_params[fpr]
            backend = args.backend
            if backend is None:
                try:
                    import jax

                    backend = jax.default_backend()
                except Exception:  # noqa: BLE001
                    backend = "cpu"
            save_calibration(
                args.fit_out, params, backend=backend,
                fingerprint=fpr, source="feedback",
                meta={
                    "fleet": {
                        "dirs": list(args.dirs),
                        "samples": len(by_fp[fpr]),
                        "fit": meta,
                    }
                },
            )
            out_doc["fit_out"] = args.fit_out
        if args.json:
            print(json.dumps(out_doc, indent=1, sort_keys=True))
        else:
            for r in runs:
                status = (
                    f"condition {r['condition']:.3g} ({r['mode']})"
                    if r["condition"] is not None
                    else f"refused: {r['refused']}"
                )
                print(f"{r['dir']}: {r['samples']} sample(s), {status}")
            for fpr, e in pooled.items():
                status = (
                    f"condition {e['condition']:.3g} ({e['mode']}"
                    + (f", drift {e['drifted_phase']}" if e["drifted_phase"]
                       else "")
                    + ")"
                    if e["condition"] is not None
                    else f"refused: {e['refused']}"
                )
                print(
                    f"pooled[{fpr}]: {e['samples']} sample(s) from "
                    f"{len(args.dirs)} dir(s), {status}"
                )
            if out_doc["fit_out"]:
                print(f"wrote pooled calibration -> {out_doc['fit_out']}")
        # pooling exists because single runs are thin: exit non-zero when
        # NOTHING could be fitted — including when a --fingerprint filter
        # (or empty dirs) left no samples to pool at all
        return 0 if fitted_params else 1

    events, dumps = read_dir(args.dir)
    by_rank: dict[int, _Counter] = {}
    for ev in events:
        by_rank.setdefault(int(ev.get("rank", 0)), _Counter())[ev["kind"]] += 1
    for rank in sorted(by_rank):
        kinds = ", ".join(
            f"{k}={n}" for k, n in sorted(by_rank[rank].items())
        )
        dumped = dumps.get(rank)
        tail = f"  [dump: {dumped['reason']}]" if dumped else ""
        print(f"rank {rank}: {sum(by_rank[rank].values())} events ({kinds}){tail}")
    if not by_rank:
        print(f"no events under {args.dir}")
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
