"""CLI: merge per-rank flight-recorder files into one Chrome trace.

Usage::

    python -m flextree_tpu.obs merge  OBS_DIR --out timeline.json
    python -m flextree_tpu.obs validate timeline.json
    python -m flextree_tpu.obs summary OBS_DIR
    python -m flextree_tpu.obs residuals OBS_DIR

``merge`` fuses every ``flight_*.jsonl`` (+ ``*.dump.json``) under
OBS_DIR into one timeline (ranks as tracks, requests/buckets as flows)
and validates it before writing — a merge that would not load in
Perfetto exits non-zero.  Open the result at https://ui.perfetto.dev or
``chrome://tracing``.  ``summary`` prints per-rank event/dump counts —
the 10-second "what did this run leave behind".  ``residuals`` prints
the per-(topo, codec, tier) predicted-vs-measured comm residual table —
the human-readable twin of ``planner.feedback``'s extractor, built from
the SAME pairing code (``timeline.residual_pairs``) so the CLI and the
fitter cannot diverge (docs/FEEDBACK.md).
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import Counter as _Counter

from .timeline import (
    merge_events,
    read_dir,
    residual_pairs,
    residual_table,
    validate_trace,
    write_trace,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="flextree_tpu.obs", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    mp = sub.add_parser("merge", help="merge an obs dir into a Chrome trace")
    mp.add_argument("dir")
    mp.add_argument("--out", default="timeline.json")
    vp = sub.add_parser("validate", help="schema-check a merged trace")
    vp.add_argument("trace")
    sp = sub.add_parser("summary", help="per-rank event/dump counts")
    sp.add_argument("dir")
    rp = sub.add_parser(
        "residuals",
        help="per-(topo, codec, tier) predicted-vs-measured residual table",
    )
    rp.add_argument("dir")
    args = ap.parse_args(argv)

    if args.cmd == "merge":
        events, dumps = read_dir(args.dir)
        if not events:
            print(f"no flight_*.jsonl events under {args.dir}", file=sys.stderr)
            return 1
        doc = merge_events(events, dumps)
        bad = validate_trace(doc)
        if bad:
            for b in bad:
                print(f"invalid: {b}", file=sys.stderr)
            return 1
        path = write_trace(doc, args.out)
        print(
            f"merged {len(events)} events from {len(doc['otherData']['ranks'])} "
            f"rank(s) ({len(dumps)} dump(s)) -> {path}"
        )
        return 0

    if args.cmd == "validate":
        with open(args.trace, encoding="utf-8") as f:
            doc = json.load(f)
        bad = validate_trace(doc)
        for b in bad:
            print(f"invalid: {b}", file=sys.stderr)
        print(f"{args.trace}: {'INVALID' if bad else 'ok'} "
              f"({len(doc.get('traceEvents', []))} trace events)")
        return 1 if bad else 0

    if args.cmd == "residuals":
        events, _dumps = read_dir(args.dir)
        if not events:
            print(f"no flight_*.jsonl events under {args.dir}", file=sys.stderr)
            return 1
        samples, skipped = residual_pairs(events)
        print(residual_table(samples, skipped))
        return 0

    events, dumps = read_dir(args.dir)
    by_rank: dict[int, _Counter] = {}
    for ev in events:
        by_rank.setdefault(int(ev.get("rank", 0)), _Counter())[ev["kind"]] += 1
    for rank in sorted(by_rank):
        kinds = ", ".join(
            f"{k}={n}" for k, n in sorted(by_rank[rank].items())
        )
        dumped = dumps.get(rank)
        tail = f"  [dump: {dumped['reason']}]" if dumped else ""
        print(f"rank {rank}: {sum(by_rank[rank].values())} events ({kinds}){tail}")
    if not by_rank:
        print(f"no events under {args.dir}")
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
