"""Benchmark harness: A/B the FlexTree allreduce against the platform-native
collective, mirroring the reference's standalone harness
(``allreduce_over_mpi/benchmark.cpp``).

Correspondence:
- CLI flags ``--size --repeat --comm-type --to-file --tag``
    -> ``benchmark.cpp:67-116`` (same names; ``--comm-type`` values are
       ``flextree`` and ``xla`` — the latter standing in for the reference's
       ``mpi`` library baseline, ``benchmark.cpp:161-174``);
- per-rep timing with a completion gate -> ``benchmark.cpp:149-159``
  (``block_until_ready`` instead of ``MPI_Barrier``+``MPI_Wtime``);
- eyeball check of elements 9..19 plus a hard assert
    -> ``benchmark.cpp:180-189`` (ours also asserts; theirs only printed);
- config summary before the run -> ``benchmark.cpp:128-143``;
- result files ``{tag}.{N}.{size}.{topo}.{ar|comm}_test.{time}.json``
    -> ``benchmark.cpp:193-213``.

Reported metric: per-chip algorithmic (bus) bandwidth ``2(N-1)/N * S / t``
per BASELINE.md, plus min/avg wall time like ``benchmark.cpp:215``.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops.reduce import get_op
from ..parallel.mesh import allreduce_over_mesh, flat_mesh
from ..planner.cost_model import bus_bandwidth_GBps
from ..schedule.stages import Topology
from ..utils.logging import get_logger, result_file_name, write_result_file
from ..utils.timing import (
    BenchResult,
    time_chained,
    time_device_loop,
    time_jax_fn,
    time_jax_fn_inplace,
)

__all__ = [
    "BenchConfig",
    "BenchReport",
    "run_allreduce_bench",
    "AttentionBenchConfig",
    "AttentionBenchReport",
    "run_attention_bench",
    "autotune_attention",
    "chip_peak_tflops",
    "GradSyncBenchConfig",
    "run_grad_sync_bench",
    "TrainStepBenchConfig",
    "run_train_step_bench",
    "make_nosync_train_step",
]

log = get_logger("flextree.bench")


@dataclass(frozen=True)
class BenchConfig:
    size: int = 35  # elements per chip (reference default, benchmark.cpp:36)
    repeat: int = 10
    comm_type: str = "flextree"  # flextree | xla
    topo: str | None = None  # FT_TOPO-style spec; None -> env/flat
    devices: int | None = None  # None -> all available
    dtype: str = "float32"
    op: str = "sum"
    tag: str = "flextree"
    to_file: bool = False
    out_dir: str = "."
    # in-place timing (the reference benchmark's MPI_IN_PLACE compounding
    # loop, benchmark.cpp:149-159): each rep's output is the next rep's
    # input and the input buffer is donated.  The xla baseline is timed
    # both donated and non-donated and keeps its best (XLA's fused
    # all-reduce cannot always alias a donated buffer).
    in_place: bool = True


@dataclass(frozen=True)
class BenchReport:
    config: BenchConfig
    num_devices: int
    topo: str
    result: BenchResult
    bus_bw_GBps: float
    correct: bool
    result_path: str | None = None

    def payload(self) -> dict:
        return {
            "config": dataclasses.asdict(self.config),
            "num_devices": self.num_devices,
            "topo": self.topo,
            "times_s": list(self.result.times_s),
            "compile_s": self.result.compile_s,
            "min_s": self.result.min_s,
            "avg_s": self.result.avg_s,
            "bus_bw_GBps": self.bus_bw_GBps,
            "correct": self.correct,
        }


import functools


@functools.lru_cache(maxsize=64)
def _jitted_psum(mesh, axis, donate: bool = False):
    """Cached jitted lax.psum baseline — cached exactly like the flextree
    path's ``_jitted_allreduce`` so the A/B times collectives, not retraces."""

    def per_device(row):
        return lax.psum(row[0], axis)[None]

    return jax.jit(
        jax.shard_map(per_device, mesh=mesh, in_specs=P(axis), out_specs=P(axis)),
        donate_argnums=(0,) if donate else (),
    )


def _xla_psum_over_mesh(stacked, mesh, axis, op):
    """The platform-native baseline (the reference's ``--comm-type mpi``)."""
    if op != "sum":
        raise ValueError("the xla baseline benchmarks psum; use op=sum")
    return _jitted_psum(mesh, axis)(stacked)


def run_allreduce_bench(cfg: BenchConfig) -> BenchReport:
    from ..schedule.ir import resolve_collective

    n = cfg.devices or len(jax.devices())
    mesh = flat_mesh(n, "ft")
    # the widened resolver: IR-family specs ("swing", "gen:4,2@2")
    # benchmark like any legacy topo
    topo = resolve_collective(n, cfg.topo)
    dtype = jnp.dtype(cfg.dtype)
    rop = get_op(cfg.op)
    rop.check_dtype(dtype)

    # data[r, i] = (i % 256) + r, like benchmark.cpp:119-124 but with
    # per-rank-distinct rows so every op has a non-trivial reduction; values
    # are small so float32 sums stay exactly representable and integer
    # wraparound (int8 etc.) is identical on host and device
    base = np.arange(cfg.size, dtype=np.int64) % 256
    data = (base[None, :] + np.arange(n, dtype=np.int64)[:, None]).astype(dtype)
    stacked = jnp.asarray(data)
    if stacked.dtype != dtype:
        # e.g. float64 demoted to float32 when jax_enable_x64 is off; keep
        # the host copy consistent so the correctness check and byte counts
        # describe what actually ran
        log.warning("dtype %s demoted to %s on device", dtype, stacked.dtype)
        dtype = stacked.dtype
        data = data.astype(dtype)

    log.info(
        "bench config: devices=%d size=%d dtype=%s op=%s comm=%s topo=%s repeat=%d",
        n, cfg.size, cfg.dtype, cfg.op, cfg.comm_type, topo, cfg.repeat,
    )

    # ``fn`` is the non-donating variant used for the correctness check;
    # timing uses the in-place chained protocol when cfg.in_place (values
    # compound across reps exactly like the reference's MPI_IN_PLACE loop —
    # they may saturate to inf late in the chain, which is timing-neutral
    # for IEEE arithmetic; correctness is asserted on a pristine call below).
    if cfg.comm_type == "flextree":
        fn = lambda x: allreduce_over_mesh(x, mesh, topo=topo, op=cfg.op)
        if cfg.in_place:
            fn_timed = lambda x: allreduce_over_mesh(
                x, mesh, topo=topo, op=cfg.op, in_place=True
            )
            result = time_jax_fn_inplace(fn_timed, jnp.array(stacked), repeat=cfg.repeat)
        else:
            result = time_jax_fn(fn, stacked, repeat=cfg.repeat)
    elif cfg.comm_type == "xla":
        fn = lambda x: _xla_psum_over_mesh(x, mesh, "ft", cfg.op)
        if cfg.in_place:
            if cfg.op != "sum":
                raise ValueError("the xla baseline benchmarks psum; use op=sum")
            # give the baseline its best shot: donated and non-donated
            r_don = time_jax_fn_inplace(
                _jitted_psum(mesh, "ft", donate=True), jnp.array(stacked),
                repeat=cfg.repeat,
            )
            r_plain = time_jax_fn_inplace(
                _jitted_psum(mesh, "ft", donate=False), jnp.array(stacked),
                repeat=cfg.repeat,
            )
            result = r_don if r_don.min_s <= r_plain.min_s else r_plain
        else:
            result = time_jax_fn(fn, stacked, repeat=cfg.repeat)
    else:
        raise ValueError(f"unknown --comm-type {cfg.comm_type!r} (flextree|xla)")

    out = np.asarray(fn(stacked))
    # fold the op over the host rows in the on-device dtype: integer
    # wraparound then matches the device exactly; floats are compared with
    # tolerance since the collective may reassociate the sum
    expect = data[0]
    for r in range(1, n):
        expect = rop.np_fn(expect, data[r])
    got = out[0]
    if np.issubdtype(dtype, np.inexact) or dtype == jnp.bfloat16:
        correct = bool(
            np.allclose(
                got.astype(np.float64), expect.astype(np.float64),
                rtol=1e-3, atol=1e-3,
            )
        )
    else:
        correct = bool(np.array_equal(got, expect))
    lo, hi = 9, min(20, cfg.size)
    if hi > lo:  # the reference's eyeball print of data[9..19]
        log.info("elements %d..%d: %s (expect %s)", lo, hi - 1,
                 got[lo:hi].tolist(), expect[lo:hi].tolist())

    nbytes = cfg.size * stacked.dtype.itemsize
    bus = bus_bandwidth_GBps(n, nbytes, result.min_s * 1e6)
    log.info(
        "average time %.3f ms / min time %.3f ms / bus bw %.3f GB/s / correct=%s",
        result.avg_s * 1e3, result.min_s * 1e3, bus, correct,
    )

    path = None
    if cfg.to_file:
        name = result_file_name(
            cfg.tag, n, cfg.size, str(topo), comm_test=(cfg.comm_type == "xla")
        )
        report = BenchReport(cfg, n, str(topo), result, bus, correct, None)
        path = str(write_result_file(f"{cfg.out_dir}/{name}", report.payload()))
        log.info("wrote %s", path)

    return BenchReport(cfg, n, str(topo), result, bus, correct, path)


# ---------------------------------------------------------- gradient sync


@dataclass(frozen=True)
class GradSyncBenchConfig:
    """A/B the bucketed/fused gradient sync against per-leaf sync.

    ``n_leaves`` leaves of ``leaf_size`` float32 elements model a
    transformer's small-leaf tail (the many-small-leaves regime where
    per-leaf sync pays k x the per-dispatch overhead); ``n_leaves=1`` with
    a large ``leaf_size`` is the single-large-tensor regime where fusion
    must be a no-op cost-wise.
    """

    n_leaves: int = 48
    leaf_size: int = 16384  # float32 elements per leaf
    devices: int | None = None
    topo: str | None = None  # FT_TOPO-style; None -> env/flat
    repeat: int = 10
    chunks: int = 2  # the ours_chunked row's pipelining factor
    bucket_bytes: int | None = None  # None -> planner-derived
    # extra wire-codec rows (ops/quantize.py), e.g. ("bf16", "int8"):
    # each adds an ``ours_fused_<codec>`` row — excluded from the bitwise
    # identity check (lossy by design) and checked against the codec's
    # documented error bound instead
    codecs: tuple = ()


def run_grad_sync_bench(cfg: GradSyncBenchConfig) -> dict:
    """Rows: ``per_leaf`` (the historical sync), ``ours_fused`` (bucketed),
    ``ours_chunked`` (bucketed + chunk-pipelined) — min/avg ms each, the
    fused rows' speedup vs per-leaf, and a bitwise-identity check between
    the per-leaf and fused outputs (the sync's hard contract)."""
    from ..parallel.bucketing import plan_buckets
    from ..parallel.train import resolve_axis_topos, sync_grads

    n = cfg.devices or len(jax.devices())
    mesh = flat_mesh(n, "dp")
    topos = resolve_axis_topos(mesh, ("dp",), cfg.topo)
    rng = np.random.default_rng(0)
    tree = {
        f"leaf{i}": jnp.asarray(
            rng.standard_normal((n, cfg.leaf_size)).astype(np.float32)
        )
        for i in range(cfg.n_leaves)
    }
    dev_specs = {k: P() for k in tree}  # every leaf replicated -> synced
    io_specs = {k: P("dp") for k in tree}

    def make_fn(bucket_bytes, chunks, codec="f32"):
        def f(t):
            rows = {k: v[0] for k, v in t.items()}
            out = sync_grads(
                rows, dev_specs, ("dp",), topos,
                bucket_bytes=bucket_bytes, chunks=chunks, codec=codec,
            )
            return {k: v[None] for k, v in out.items()}

        return jax.jit(
            jax.shard_map(
                f, mesh=mesh, in_specs=(io_specs,), out_specs=io_specs,
                check_vma=False,
            )
        )

    variants = {
        "per_leaf": make_fn(0, 1),
        "ours_fused": make_fn(cfg.bucket_bytes, 1),
        "ours_chunked": make_fn(cfg.bucket_bytes, cfg.chunks),
    }
    for codec in cfg.codecs:
        variants[f"ours_fused_{codec}"] = make_fn(cfg.bucket_bytes, 1, codec)
    outs = {
        name: jax.block_until_ready(fn(tree))  # also warms the jit
        for name, fn in variants.items()
    }
    rows = _interleaved_times(
        {name: (fn, (tree,)) for name, fn in variants.items()}, cfg.repeat
    )
    for name in rows:
        if name != "per_leaf":
            rows[name]["vs_per_leaf"] = (
                rows["per_leaf"]["min_ms"] / rows[name]["min_ms"]
            )

    identical = all(
        np.asarray(outs["per_leaf"][k]).tobytes()
        == np.asarray(outs["ours_fused"][k]).tobytes()
        == np.asarray(outs["ours_chunked"][k]).tobytes()
        for k in tree
    )
    if not identical:
        raise RuntimeError("fused sync output diverged from per-leaf (bitwise)")
    if cfg.codecs:
        # lossy rows: no bitwise contract — hold them to the codec's
        # documented error bound against the exact per-leaf sync instead
        from ..ops.quantize import get_codec
        from ..schedule.stages import LonelyTopology

        t = Topology.resolve(n, cfg.topo)
        if isinstance(t, LonelyTopology):
            widths, lonely = t.tree.widths, t.lonely
        else:
            widths, lonely = t.widths, 0
        for codec in cfg.codecs:
            c = get_codec(codec)
            worst = 0.0
            for k in tree:
                exact = np.asarray(outs["per_leaf"][k], dtype=np.float64)
                got = np.asarray(
                    outs[f"ours_fused_{codec}"][k], dtype=np.float64
                )
                amax = float(np.abs(np.asarray(tree[k])).max())
                bound = c.error_bound(amax, n, widths, lonely) + 1e-5
                err = float(np.abs(got - exact).max())
                worst = max(worst, err / bound if bound else 0.0)
                if c.lossy and err > bound:
                    raise RuntimeError(
                        f"codec {codec} sync error {err:.5f} exceeds the "
                        f"documented bound {bound:.5f} on leaf {k}"
                    )
            rows[f"ours_fused_{codec}"]["err_over_bound"] = worst
    buckets = plan_buckets(
        [v[0] for v in tree.values()], [P()] * cfg.n_leaves, ("dp",),
        topos=topos, axis_sizes={"dp": n}, bucket_bytes=cfg.bucket_bytes,
    )
    total_mb = cfg.n_leaves * cfg.leaf_size * 4 / 2**20
    log.info(
        "grad sync %d leaves x %d f32 (%.1f MB, %d buckets): per_leaf %.2f ms,"
        " fused %.2f ms (%.2fx), chunked %.2f ms (%.2fx)",
        cfg.n_leaves, cfg.leaf_size, total_mb, len(buckets),
        rows["per_leaf"]["min_ms"],
        rows["ours_fused"]["min_ms"], rows["ours_fused"]["vs_per_leaf"],
        rows["ours_chunked"]["min_ms"], rows["ours_chunked"]["vs_per_leaf"],
    )
    return {
        "config": dataclasses.asdict(cfg),
        "num_devices": n,
        "topo": str(Topology.resolve(n, cfg.topo)),
        "total_mb": total_mb,
        "n_buckets": len(buckets),
        "identical": identical,
        "rows": rows,
    }


def _interleaved_times(calls: dict, repeat: int) -> dict:
    """Per-variant min/avg ms with the timed reps INTERLEAVED per round in
    a (deterministically) shuffled order instead of back-to-back blocks: on
    the timeshared 1-core bench host a sustained contention episode
    otherwise lands entirely on one variant and swings the A/B ratio ~20%
    run-to-run (the BENCH_ALLREDUCE r03/r04 lesson, same fix as bench.py's
    CPU A/B), and a FIXED round-robin order adds a position bias — each
    variant always inherits the cache state its fixed predecessor leaves
    behind.  ``calls`` maps name -> (jitted_fn, args); every fn must
    already be compiled/warm."""
    import random

    from ..utils.timing import Timer

    order = list(calls)
    shuffler = random.Random(0)
    times: dict[str, list[float]] = {name: [] for name in calls}
    for _ in range(repeat):
        shuffler.shuffle(order)
        for name in order:
            fn, fargs = calls[name]
            t = Timer()
            jax.block_until_ready(fn(*fargs))
            times[name].append(t.stop())
    return {
        name: {
            "min_ms": min(ts) * 1e3,
            "avg_ms": sum(ts) / len(ts) * 1e3,
            # raw per-round samples (round i of every variant ran in the
            # same shuffled round), so callers can form PAIRED per-round
            # statistics — on a heavily timeshared host the min of two
            # variants' independent draws swings far more than any
            # per-round ratio does
            "times_ms": [t * 1e3 for t in ts],
        }
        for name, ts in times.items()
    }


@dataclass(frozen=True)
class TrainStepBenchConfig:
    """End-to-end ``train_step_ms``: the full jitted train step (forward +
    backward + sync + AdamW) under per-leaf vs fused vs chunked gradient
    sync.  The default model is the many-small-leaves regime (50 gradient
    leaves, most under 20 KB) on a pure-dp mesh."""

    n_layers: int = 6
    d_model: int = 64
    d_ff: int = 128
    n_heads: int = 4
    vocab_size: int = 256
    batch: int = 8
    seq_len: int = 64
    devices: int | None = None
    topo: str | None = None  # grad_topo for the sync
    repeat: int = 5
    chunks: int = 2
    # add an ``ours_fused_supervised`` row: the fused step wrapped in the
    # runtime supervision host path (step watchdog on its persistent
    # worker thread + heartbeat Supervisor fed per-step durations) — the
    # fault-free overhead the ISSUE-4 acceptance bounds at <= 2%
    supervised: bool = True
    # add the readiness-ordered overlap rows (ISSUE 6): ``no_sync`` (the
    # same forward/backward/AdamW with the gradient sync elided — the
    # exposure baseline), ``ours_overlapped`` (TrainConfig(overlap=True))
    # and ``ours_overlap_serialized`` (its full-backward-barrier twin —
    # equal collective counts, bitwise-equal results).  Every sync row
    # then carries ``exposed_comm_ms`` (step-time delta over no_sync);
    # the overlapped row also carries ``hidden_comm_ms`` = the twin's
    # exposure minus its own — wire time that ran under backward compute.
    # Default False: the overlapped step is the slowest compile in the
    # suite (one vjp per layer) and pre-existing callers' artifacts
    # (BENCH_BUCKETING.json) keep their historical row schema.
    overlap: bool = False
    # add the ZeRO-1 sharded rows (PR 7): ``ours_sharded`` (f32 — updated
    # params asserted bitwise-identical to per-leaf) and
    # ``ours_sharded_int8`` (both wires quantized), each with the
    # per-rank optimizer-state ratio from the live layout
    # (zero.zero_shard_bytes).  Default False for the same
    # artifact-schema reason as ``overlap``.
    sharded: bool = False
    # add an ``ours_fused_recorded`` row (ISSUE 10): the fused step with
    # the flight recorder + metrics registry on its host path (step
    # start/end events with per-step flush to a JSONL spill, one
    # histogram observe) — ``recorder_overhead`` is the ratio the <= 2%
    # telemetry budget is checked against.  Default False for the same
    # artifact-schema reason as ``overlap``.
    recorder: bool = False


def make_nosync_train_step(mesh, model_cfg, train_cfg, axis_names=("dp", "sp", "tp")):
    """The sync-free twin of ``make_train_step``: identical forward,
    backward and AdamW, gradient sync elided — NOT a training step (the
    replicas would diverge) but the exposure baseline the overlap bench
    needs: ``step(with sync) - step(no sync)`` is the sync time that
    actually extended the step (``utils.profiling.exposed_split``)."""
    import jax as _jax

    from ..models.transformer import cross_entropy_loss, forward
    from ..parallel.train import (
        adamw_apply,
        maybe_clip_grads,
        metric_specs,
        state_specs,
        validate_tp,
    )

    dp, sp, tp = axis_names
    validate_tp(model_cfg, mesh.shape[tp])
    sspecs = state_specs(model_cfg, tp, train_cfg)
    data_spec = P(dp, sp)

    def device_step(state, tokens, targets):
        n_total_tokens = (
            tokens.size
            * lax.axis_size(dp)
            * lax.axis_size(sp)
            * lax.axis_size(tp)
        )

        def local_loss(params):
            logits = forward(params, tokens, model_cfg, tp_axis=tp, sp_axis=sp)
            loss_sum, _ = cross_entropy_loss(logits, targets)
            return loss_sum / n_total_tokens

        loss, grads = _jax.value_and_grad(local_loss)(state["params"])
        global_loss = lax.psum(lax.psum(lax.psum(loss, dp), sp), tp)
        metrics = {"loss": global_loss}
        # clip compute stays (compute parity with the real step — only
        # the SYNC is elided), and it also keeps the metrics pytree
        # matching metric_specs when clipping is configured
        grads = maybe_clip_grads(grads, sspecs["params"], train_cfg, metrics)
        new_state = adamw_apply(state, grads, train_cfg)
        return new_state, metrics

    mspec = metric_specs(train_cfg, {"loss": P()})
    return jax.jit(
        jax.shard_map(
            device_step, mesh=mesh, in_specs=(sspecs, data_spec, data_spec),
            out_specs=(sspecs, mspec), check_vma=False,
        )
    )


def run_train_step_bench(cfg: TrainStepBenchConfig) -> dict:
    """Rows of ``train_step_ms`` (min/avg) per sync strategy, plus a
    comm-vs-compute attribution: ``sync_ms`` times the gradient sync alone
    on the model's real gradient tree (the per-bucket ``comm_span`` scopes
    mark the same collectives in profiler traces), so
    ``step - sync = compute`` is readable per row.  With ``cfg.overlap``,
    the readiness-ordered rows and the exposed-vs-hidden comm split are
    added (see :class:`TrainStepBenchConfig`).  Also asserts the fused,
    chunked and overlapped steps' updated parameters are bitwise-identical
    to the per-leaf step's.
    """
    from ..models.transformer import TransformerConfig
    from ..parallel.train import (
        TrainConfig,
        init_train_state,
        make_mesh_nd,
        make_train_step,
        resolve_axis_topos,
        state_specs,
        sync_grads,
    )

    n = cfg.devices or len(jax.devices())
    mesh = make_mesh_nd(n, (n, 1, 1), ("dp", "sp", "tp"))
    model_cfg = TransformerConfig(
        vocab_size=cfg.vocab_size, d_model=cfg.d_model, n_heads=cfg.n_heads,
        n_layers=cfg.n_layers, d_ff=cfg.d_ff,
    )
    state = init_train_state(jax.random.PRNGKey(0), model_cfg)
    n_leaves = len(jax.tree.leaves(state["params"]))
    rng = np.random.default_rng(1)
    toks = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (cfg.batch, cfg.seq_len)), jnp.int32
    )
    tgts = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (cfg.batch, cfg.seq_len)), jnp.int32
    )

    train_cfgs = {
        "per_leaf": TrainConfig(grad_topo=cfg.topo, bucket_bytes=0),
        "ours_fused": TrainConfig(grad_topo=cfg.topo),
        "ours_chunked": TrainConfig(grad_topo=cfg.topo, grad_chunks=cfg.chunks),
    }

    # comm attribution: the sync alone, on gradient-shaped data
    pspecs = state_specs(model_cfg, "tp")["params"]
    topos = resolve_axis_topos(mesh, ("dp", "sp", "tp"), cfg.topo)
    grads = jax.tree.map(
        lambda p: jnp.asarray(
            np.random.default_rng(2).standard_normal(p.shape).astype(np.float32)
        ),
        state["params"],
    )

    def make_sync(tc: TrainConfig):
        def f(g):
            return sync_grads(
                g, pspecs, ("dp", "sp", "tp"), topos,
                bucket_bytes=tc.bucket_bytes, chunks=tc.grad_chunks,
            )

        rep = jax.tree.map(lambda _: P(), pspecs)
        return jax.jit(
            jax.shard_map(
                f, mesh=mesh, in_specs=(rep,), out_specs=rep, check_vma=False
            )
        )

    steps, syncs, states_out = {}, {}, {}
    for name, tc in train_cfgs.items():
        steps[name] = make_train_step(mesh, model_cfg, tc)
        states_out[name], _ = jax.block_until_ready(steps[name](state, toks, tgts))
        syncs[name] = make_sync(tc)
        jax.block_until_ready(syncs[name](grads))

    if cfg.overlap:
        tc_ovl = TrainConfig(grad_topo=cfg.topo, overlap=True)
        steps["ours_overlapped"] = make_train_step(mesh, model_cfg, tc_ovl)
        steps["ours_overlap_serialized"] = make_train_step(
            mesh, model_cfg, tc_ovl, serialize_overlap=True
        )
        steps["no_sync"] = make_nosync_train_step(mesh, model_cfg, tc_ovl)
        for name in ("ours_overlapped", "ours_overlap_serialized", "no_sync"):
            out, _ = jax.block_until_ready(steps[name](state, toks, tgts))
            if name != "no_sync":
                states_out[name] = out

    sharded_states: dict = {}
    shard_bytes = None
    if cfg.sharded:
        import dataclasses as _dc

        from ..models.transformer import init_params, param_specs
        from ..parallel.train import zero_layout_for
        from ..parallel.zero import zero_shard_bytes

        tc_sh = TrainConfig(grad_topo=cfg.topo, shard_optimizer=True)
        for name, tc2 in (
            ("ours_sharded", tc_sh),
            ("ours_sharded_int8", _dc.replace(tc_sh, codec="int8")),
        ):
            st2 = init_train_state(
                jax.random.PRNGKey(0), model_cfg, tc2, mesh=mesh
            )
            steps[name] = make_train_step(mesh, model_cfg, tc2)
            sharded_states[name] = st2
            out, _ = jax.block_until_ready(steps[name](st2, toks, tgts))
            states_out[name] = out
        shapes = jax.eval_shape(
            lambda k: init_params(k, model_cfg), jax.random.PRNGKey(0)
        )
        layout = zero_layout_for(
            mesh, shapes, param_specs(model_cfg, "tp"), ("dp", "sp", "tp")
        )
        # per-variant accounting: the int8 state additionally carries the
        # sharded f32 master copy (lossy=True), so its ratio is higher
        shard_bytes = {
            "ours_sharded": zero_shard_bytes(layout),
            "ours_sharded_int8": zero_shard_bytes(layout, lossy=True),
        }

    supervised_ctx = None
    if cfg.supervised:
        # the fault-free supervision host path around the fused step: the
        # watchdog's queue round-trip to its persistent worker thread, a
        # step_scope timing + EWMA update, and the Supervisor's two-store
        # record_step (the beat itself rides the daemon thread, off-path)
        import tempfile
        import time as _time

        from ..runtime.supervisor import Supervisor, SupervisorConfig
        from ..runtime.watchdog import StepWatchdog
        from ..utils.profiling import Ewma

        hb_dir = tempfile.mkdtemp(prefix="ft_hb_bench_")
        sup = Supervisor(
            SupervisorConfig(rank=0, dir=hb_dir, interval_s=0.25)
        ).start()
        wd = StepWatchdog()
        ewma = Ewma()
        fused = steps["ours_fused"]

        def supervised_step(s, tk, tg):
            t0 = _time.perf_counter()
            out = wd.run(fused, s, tk, tg, timeout_s=60.0, step=0)
            dur = _time.perf_counter() - t0
            ewma.update(dur)
            sup.record_step(0, dur)
            return out

        steps["ours_fused_supervised"] = supervised_step
        supervised_ctx = (sup, wd, hb_dir)  # before warmup: cleanup on raise

    recorder_ctx = None
    if cfg.recorder:
        # the telemetry host path around the fused step: a step_start
        # event, the step, a step_end event whose FLUSH_KINDS membership
        # spills the JSONL buffer (write + flush to page cache, no
        # fsync), and one histogram observe — exactly what fit pays per
        # step with --obs-dir on
        import shutil as _shutil
        import tempfile as _tempfile
        import time as _rec_time

        from ..obs.metrics import MetricsRegistry
        from ..obs.recorder import FlightRecorder

        obs_dir = _tempfile.mkdtemp(prefix="ft_obs_bench_")
        rec = FlightRecorder(obs_dir, rank=0)
        reg = MetricsRegistry()
        hist = reg.histogram("train.step_ms")
        fused_for_rec = steps["ours_fused"]

        def recorded_step(s, tk, tg):
            t0 = _rec_time.perf_counter()
            rec.record("step_start", step=0)
            out = fused_for_rec(s, tk, tg)
            rec.record("step_end", step=0)
            hist.observe((_rec_time.perf_counter() - t0) * 1e3)
            return out

        steps["ours_fused_recorded"] = recorded_step
        recorder_ctx = (rec, obs_dir, _shutil)

    try:
        if supervised_ctx is not None:
            jax.block_until_ready(
                steps["ours_fused_supervised"](state, toks, tgts)
            )
        if recorder_ctx is not None:
            jax.block_until_ready(
                steps["ours_fused_recorded"](state, toks, tgts)
            )
        step_times = _interleaved_times(
            {
                n: (fn, (sharded_states.get(n, state), toks, tgts))
                for n, fn in steps.items()
            },
            cfg.repeat,
        )
        sync_times = _interleaved_times(
            {n: (fn, (grads,)) for n, fn in syncs.items()}, cfg.repeat
        )
    finally:
        if supervised_ctx is not None:  # don't leak threads/tmpdir on raise
            import shutil

            sup, wd, hb_dir = supervised_ctx
            wd.close()
            sup.stop()
            shutil.rmtree(hb_dir, ignore_errors=True)
        if recorder_ctx is not None:
            rec, obs_dir, _shutil = recorder_ctx
            rec.close()
            _shutil.rmtree(obs_dir, ignore_errors=True)
    rows = {}
    for name in train_cfgs:
        rows[name] = {
            "train_step_ms": step_times[name]["min_ms"],
            "train_step_avg_ms": step_times[name]["avg_ms"],
            "sync_ms": sync_times[name]["min_ms"],
            "compute_ms": max(
                step_times[name]["min_ms"] - sync_times[name]["min_ms"], 0.0
            ),
        }
    for name in ("ours_fused", "ours_chunked"):
        rows[name]["vs_per_leaf"] = (
            rows["per_leaf"]["train_step_ms"] / rows[name]["train_step_ms"]
        )
    if cfg.overlap:
        from ..utils.profiling import exposed_split

        nosync_ms = step_times["no_sync"]["min_ms"]
        rows["no_sync"] = {
            "train_step_ms": nosync_ms,
            "train_step_avg_ms": step_times["no_sync"]["avg_ms"],
        }
        # the serialized twin hides nothing, so its exposure IS the
        # overlapped program's comm total (equal collective counts, equal
        # payloads) — the comm_total the overlapped row's split is cut by
        twin_exposed = max(
            step_times["ours_overlap_serialized"]["min_ms"] - nosync_ms, 0.0
        )
        for name in ("ours_overlapped", "ours_overlap_serialized"):
            exp, hid = exposed_split(
                step_times[name]["min_ms"], nosync_ms, twin_exposed
            )
            rows[name] = {
                "train_step_ms": step_times[name]["min_ms"],
                "train_step_avg_ms": step_times[name]["avg_ms"],
                "exposed_comm_ms": exp,
                "hidden_comm_ms": hid,
                "vs_per_leaf": (
                    rows["per_leaf"]["train_step_ms"]
                    / step_times[name]["min_ms"]
                ),
            }
        for name in ("per_leaf", "ours_fused", "ours_chunked"):
            rows[name]["exposed_comm_ms"] = max(
                step_times[name]["min_ms"] - nosync_ms, 0.0
            )
        # clamped denominator: a zero exposure (fully hidden, or noise
        # crossing zero on this host) must not put Infinity into
        # artifacts that embed these rows (BENCH_OVERLAP.json)
        exp_o = rows["ours_overlapped"]["exposed_comm_ms"]
        rows["ours_overlapped"]["exposed_vs_serialized"] = (
            twin_exposed / max(exp_o, 0.1)
        )
    if cfg.supervised:
        t = step_times["ours_fused_supervised"]
        rows["ours_fused_supervised"] = {
            "train_step_ms": t["min_ms"],
            "train_step_avg_ms": t["avg_ms"],
            "sync_ms": sync_times["ours_fused"]["min_ms"],  # same collective
            "compute_ms": max(
                t["min_ms"] - sync_times["ours_fused"]["min_ms"], 0.0
            ),
            # the acceptance number: supervised/unsupervised fused step
            "supervision_overhead": t["min_ms"]
            / rows["ours_fused"]["train_step_ms"],
        }
    if cfg.recorder:
        t = step_times["ours_fused_recorded"]
        rows["ours_fused_recorded"] = {
            "train_step_ms": t["min_ms"],
            "train_step_avg_ms": t["avg_ms"],
            # the ISSUE-10 acceptance number: recorder-on/recorder-off
            # fused step, same protocol as supervision_overhead
            "recorder_overhead": t["min_ms"]
            / rows["ours_fused"]["train_step_ms"],
        }

    if cfg.sharded:
        for name in ("ours_sharded", "ours_sharded_int8"):
            rows[name] = {
                "train_step_ms": step_times[name]["min_ms"],
                "train_step_avg_ms": step_times[name]["avg_ms"],
                "vs_per_leaf": (
                    rows["per_leaf"]["train_step_ms"]
                    / step_times[name]["min_ms"]
                ),
                "opt_state_bytes_ratio": shard_bytes[name]["ratio"],
            }

    identical = True
    variants = ["ours_fused", "ours_chunked"]
    if cfg.overlap:
        variants += ["ours_overlapped", "ours_overlap_serialized"]
    if cfg.sharded:
        variants += ["ours_sharded"]  # int8 is lossy: bounded, not bitwise
    for name in variants:
        same = all(
            np.asarray(a).tobytes() == np.asarray(b).tobytes()
            for a, b in zip(
                jax.tree.leaves(states_out["per_leaf"]["params"]),
                jax.tree.leaves(states_out[name]["params"]),
            )
        )
        if not same:
            raise RuntimeError(
                f"{name} train step diverged from per-leaf (bitwise)"
            )
        identical = identical and same
    log.info(
        "train step (%d leaves): per_leaf %.2f ms, fused %.2f ms (%.2fx), "
        "chunked %.2f ms (%.2fx); sync %.2f -> %.2f ms",
        n_leaves,
        rows["per_leaf"]["train_step_ms"],
        rows["ours_fused"]["train_step_ms"], rows["ours_fused"]["vs_per_leaf"],
        rows["ours_chunked"]["train_step_ms"],
        rows["ours_chunked"]["vs_per_leaf"],
        rows["per_leaf"]["sync_ms"], rows["ours_fused"]["sync_ms"],
    )
    return {
        "config": dataclasses.asdict(cfg),
        "num_devices": n,
        "n_grad_leaves": n_leaves,
        "identical": identical,
        "rows": rows,
    }


# ---------------------------------------------------------------- attention


@dataclass(frozen=True)
class AttentionBenchConfig:
    batch: int = 4
    seq_len: int = 4096
    heads: int = 16
    head_dim: int = 128
    dtype: str = "bfloat16"
    impl: str = "flash"  # flash | reference | stock
    repeat: int = 20
    block_q: int = 256
    block_k: int = 512
    # forward k-walk structure (flash impl only): "loop" | "pipelined" |
    # "kvgrid" — see flextree_tpu.ops.pallas_attention.flash_attention
    variant: str = "loop"
    # "device_loop": in-jit chained fori_loop, slope of two iteration
    # counts — measures DEVICE time only, immune to the tunneled backend's
    # per-dispatch latency (the r01/r02 numbers were dominated by it; see
    # PROFILE_ATTENTION.md).  "chained": per-call python loop with a final
    # fetch — includes dispatch overhead; kept for comparison/CPU tests.
    timing: str = "device_loop"
    # "fwd": forward only.  "grad": grads of sum(attention) wrt (q, k, v) —
    # for flash/stock, exercises the forward-with-residuals plus both
    # blockwise backward kernels; reported FLOPs are per-impl hardware
    # FLOPs (flash & stock 4.5x fwd — qk recomputed in both the dq and dkv
    # kernels; reference 3x, P stored — see grad_flop_scale in
    # run_attention_bench).
    mode: str = "fwd"


from ..utils.device import tpu_generation  # dependency-free normalizer

#: bf16 peak TFLOP/s by generation, for MFU reporting.
_TPU_PEAK_TFLOPS = {
    "v5e": 197.0,
    "v6e": 918.0,
    "v5p": 459.0,
    "v4": 275.0,
    "v3": 123.0,
    "v2": 45.0,
}


def chip_peak_tflops() -> float | None:
    """bf16 peak of device 0, or None off-TPU (MFU then unreported)."""
    dev = jax.devices()[0]
    if dev.platform == "cpu":
        return None
    gen = tpu_generation(getattr(dev, "device_kind", ""))
    return _TPU_PEAK_TFLOPS.get(gen) if gen else None


@dataclass(frozen=True)
class AttentionBenchReport:
    config: AttentionBenchConfig
    per_call_s: float
    tflops: float
    mfu: float | None = None  # tflops / chip bf16 peak, when on TPU
    result_path: str | None = None

    def payload(self) -> dict:
        return {
            "bench": "attention",
            "impl": self.config.impl,
            "mode": self.config.mode,
            "batch": self.config.batch,
            "seq_len": self.config.seq_len,
            "heads": self.config.heads,
            "head_dim": self.config.head_dim,
            "dtype": self.config.dtype,
            "block_q": self.config.block_q,
            "block_k": self.config.block_k,
            "variant": self.config.variant if self.config.impl == "flash" else None,
            "per_call_s": self.per_call_s,
            "tflops": self.tflops,
            "mfu": self.mfu,
        }


def stock_block_sizes(block_q: int, block_k: int):
    """Full ``BlockSizes`` for the stock Pallas flash kernel, forward AND
    backward, derived from one (block_q, block_k) pair.

    The backward blocks mirror the forward derivation (``block_*_major =
    max(block_k, block_q)``), so a single swept pair configures both
    passes — required for the grad A/B baseline (VERDICT r3 item 3: the
    stock bwd raises unless every backward block is set).  segment_ids
    stays None on both sides of the A/B — we don't benchmark segmenting.
    """
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes

    bkM = max(block_k, block_q)
    return BlockSizes(
        block_q=block_q,
        block_k_major=bkM,
        block_k=block_k,
        block_b=1,
        block_q_major_dkv=block_q,
        block_k_major_dkv=bkM,
        block_k_dkv=block_k,
        block_q_dkv=block_q,
        block_k_major_dq=bkM,
        block_k_dq=block_k,
        block_q_dq=block_q,
    )


def run_attention_bench(
    cfg: AttentionBenchConfig,
    *,
    tag: str = "flextree",
    to_file: bool = False,
    out_dir: str = ".",
) -> AttentionBenchReport:
    """Time one attention impl with a data-dependency chain
    (``flextree_tpu.utils.timing.time_chained``) — the completion gate that
    holds even over the tunneled single-chip backend bench.py documents."""
    from ..ops.pallas_attention import flash_attention
    from ..parallel.ring_attention import attention_reference

    layout_bhtd = False  # stock kernel's native layout is (B, H, T, D)
    if cfg.mode not in ("fwd", "grad"):
        raise ValueError(f"unknown mode {cfg.mode!r} (fwd|grad)")
    if cfg.impl == "flash":
        core = lambda q, k, v: flash_attention(  # noqa: E731
            q, k, v, causal=True, block_q=cfg.block_q, block_k=cfg.block_k,
            variant=cfg.variant,
        )
        fn = None  # grad/fwd wrap below
    elif cfg.impl == "reference":
        core = lambda q, k, v: attention_reference(q, k, v, causal=True)  # noqa: E731
        fn = None
    elif cfg.impl == "stock":
        # the stock Pallas TPU flash kernel, measured FAIRLY: inputs are
        # generated directly in its native (B, H, T, D) layout (no timed
        # transposes — the r02 measurement paid them and undersold the
        # baseline) and its block sizes come from the config (bench.py
        # sweeps them; defaults below are the v5e-tuned winners)
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            BlockSizes,
            flash_attention as stock_flash,
        )

        layout_bhtd = True
        bs = stock_block_sizes(cfg.block_q, cfg.block_k)
        core = lambda q, k, v: stock_flash(  # noqa: E731
            q, k, v, causal=True, block_sizes=bs
        )
        fn = None
    else:
        raise ValueError(f"unknown attention impl {cfg.impl!r}")
    if fn is None:  # flash/reference/stock share the grad/fwd wrap
        if cfg.mode == "grad":
            g = jax.grad(lambda q, k, v: core(q, k, v).sum(), argnums=(0, 1, 2))

            def grad_all(q, k, v):
                dq, dk, dv = g(q, k, v)
                # fold all three grads into the chained carry: grad wrt q
                # alone lets XLA DCE the dk/dv backward work that the
                # 4.5x/3x hardware-FLOP scale below charges for
                return dq + dk + dv

            fn = jax.jit(grad_all)
        else:
            fn = jax.jit(core)

    b, t, h, d = cfg.batch, cfg.seq_len, cfg.heads, cfg.head_dim
    rng = np.random.default_rng(0)
    dtype = jnp.dtype(cfg.dtype)
    shape = (b, h, t, d) if layout_bhtd else (b, t, h, d)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.standard_normal(shape).astype(np.float32), dtype=dtype
    )
    q, k, v = mk(), mk(), mk()
    if cfg.timing == "device_loop":
        # cfg.repeat governs only the chained protocol; device_loop's
        # sample counts are its n_lo/n_hi/best_of — say so when the caller
        # set a non-default repeat expecting it to matter
        if cfg.repeat != type(cfg).repeat:
            log.warning(
                "timing='device_loop' ignores repeat=%d (fixed slope "
                "protocol); use timing='chained' if you want a repeat loop",
                cfg.repeat,
            )
        per_call = time_device_loop(fn, q, k, v)
    elif cfg.timing == "chained":
        per_call = time_chained(fn, q, k, v, n_calls=cfg.repeat)
    else:
        raise ValueError(
            f"unknown timing {cfg.timing!r} (device_loop|chained)"
        )
    # hardware-FLOP scale for grad mode, per impl: the flash path re-runs
    # the forward (custom_vjp) then 3 dq-kernel + 4 dkv-kernel matmuls over
    # the visible tiles -> (2+3+4)/2 = 4.5x fwd; XLA autodiff of the
    # full-matrix reference stores P and does 4 backward matmuls, no
    # recompute -> (2+4)/2 = 3x fwd.  The stock Pallas bwd has the same
    # structure as ours (qk recomputed in both the 3-matmul dq and
    # 4-matmul dkv kernels; fwd residuals o/l/m saved) -> 4.5x too.
    if cfg.mode == "grad":
        grad_flop_scale = 3.0 if cfg.impl == "reference" else 4.5
    else:
        grad_flop_scale = 1.0
    flops = 4 * b * h * t * t * d / 2 * grad_flop_scale  # causal
    tflops = flops / per_call / 1e12
    peak = chip_peak_tflops()
    report = AttentionBenchReport(
        cfg, per_call, tflops, round(tflops / peak, 4) if peak else None
    )
    log.info(
        "attention %s: %.3f ms/call, %.2f TFLOP/s%s",
        cfg.impl if cfg.mode == "fwd" else f"{cfg.impl}+grad",
        per_call * 1e3, report.tflops,
        f" ({report.mfu * 100:.1f}% MFU)" if report.mfu is not None else "",
    )
    if to_file:
        name = result_file_name(
            tag=tag,
            num_devices=1,
            size=b * t * h * d,
            topo=f"attn_{cfg.impl}",
        )
        path = str(write_result_file(f"{out_dir}/{name}", report.payload()))
        report = dataclasses.replace(report, result_path=path)
    return report


def autotune_attention(
    cfg: AttentionBenchConfig,
    blocks: tuple[tuple[int, int], ...] = (
        (256, 512), (512, 512), (512, 1024), (1024, 512)
    ),
    repeat: int | None = None,
    impl: str = "flash",
    variants: tuple[str, ...] | None = None,
) -> AttentionBenchReport:
    """Sweep explicit (block_q, block_k) pairs (x forward ``variants`` for
    the flash impl) and return the fastest report (VERDICT r1 item 3's
    autotune).  The default pairs are the top configs from the v5e block
    sweep in PROFILE_ATTENTION.md — a compile over the tunneled backend
    costs ~30 s, so the sweep is a shortlist, not a product.  Works for
    ``impl="stock"`` too (block_k_major and the backward blocks are
    derived in ``run_attention_bench``)."""
    rep_kw = {} if repeat is None else {"repeat": repeat}
    if impl == "reference":
        # block sizes don't reach attention_reference; sweeping them would
        # re-run the identical benchmark len(blocks) times
        return run_attention_bench(
            dataclasses.replace(cfg, impl=impl, **rep_kw)
        )
    if variants is None or impl != "flash":
        variants = (cfg.variant,)
    # fail fast on a bad variant name — the per-combo except below is for
    # combos that don't FIT, and would otherwise silently drop the whole
    # schedule from the sweep
    unknown = set(variants) - {"loop", "pipelined", "kvgrid"}
    if unknown:
        raise ValueError(f"unknown flash variant(s): {sorted(unknown)}")
    best = None
    for variant in variants:
        for bq, bk in blocks:
            c = dataclasses.replace(cfg, impl=impl, block_q=bq, block_k=bk,
                                    variant=variant, **rep_kw)
            try:
                r = run_attention_bench(c)
            except Exception as e:  # noqa: BLE001 — a combo may not fit
                log.warning(
                    "autotune (%s, %d, %d) failed: %s", variant, bq, bk, e
                )
                continue
            if best is None or r.tflops > best.tflops:
                best = r
    if best is None:
        raise RuntimeError("no autotune configuration succeeded")
    return best
