"""Benchmark harness: A/B the FlexTree allreduce against the platform-native
collective, mirroring the reference's standalone harness
(``allreduce_over_mpi/benchmark.cpp``).

Correspondence:
- CLI flags ``--size --repeat --comm-type --to-file --tag``
    -> ``benchmark.cpp:67-116`` (same names; ``--comm-type`` values are
       ``flextree`` and ``xla`` — the latter standing in for the reference's
       ``mpi`` library baseline, ``benchmark.cpp:161-174``);
- per-rep timing with a completion gate -> ``benchmark.cpp:149-159``
  (``block_until_ready`` instead of ``MPI_Barrier``+``MPI_Wtime``);
- eyeball check of elements 9..19 plus a hard assert
    -> ``benchmark.cpp:180-189`` (ours also asserts; theirs only printed);
- config summary before the run -> ``benchmark.cpp:128-143``;
- result files ``{tag}.{N}.{size}.{topo}.{ar|comm}_test.{time}.json``
    -> ``benchmark.cpp:193-213``.

Reported metric: per-chip algorithmic (bus) bandwidth ``2(N-1)/N * S / t``
per BASELINE.md, plus min/avg wall time like ``benchmark.cpp:215``.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops.reduce import get_op
from ..parallel.mesh import allreduce_over_mesh, flat_mesh
from ..planner.cost_model import bus_bandwidth_GBps
from ..schedule.stages import Topology
from ..utils.logging import get_logger, result_file_name, write_result_file
from ..utils.timing import (
    BenchResult,
    time_chained,
    time_device_loop,
    time_jax_fn,
    time_jax_fn_inplace,
)

__all__ = [
    "BenchConfig",
    "BenchReport",
    "run_allreduce_bench",
    "AttentionBenchConfig",
    "AttentionBenchReport",
    "run_attention_bench",
    "autotune_attention",
    "chip_peak_tflops",
]

log = get_logger("flextree.bench")


@dataclass(frozen=True)
class BenchConfig:
    size: int = 35  # elements per chip (reference default, benchmark.cpp:36)
    repeat: int = 10
    comm_type: str = "flextree"  # flextree | xla
    topo: str | None = None  # FT_TOPO-style spec; None -> env/flat
    devices: int | None = None  # None -> all available
    dtype: str = "float32"
    op: str = "sum"
    tag: str = "flextree"
    to_file: bool = False
    out_dir: str = "."
    # in-place timing (the reference benchmark's MPI_IN_PLACE compounding
    # loop, benchmark.cpp:149-159): each rep's output is the next rep's
    # input and the input buffer is donated.  The xla baseline is timed
    # both donated and non-donated and keeps its best (XLA's fused
    # all-reduce cannot always alias a donated buffer).
    in_place: bool = True


@dataclass(frozen=True)
class BenchReport:
    config: BenchConfig
    num_devices: int
    topo: str
    result: BenchResult
    bus_bw_GBps: float
    correct: bool
    result_path: str | None = None

    def payload(self) -> dict:
        return {
            "config": dataclasses.asdict(self.config),
            "num_devices": self.num_devices,
            "topo": self.topo,
            "times_s": list(self.result.times_s),
            "compile_s": self.result.compile_s,
            "min_s": self.result.min_s,
            "avg_s": self.result.avg_s,
            "bus_bw_GBps": self.bus_bw_GBps,
            "correct": self.correct,
        }


import functools


@functools.lru_cache(maxsize=64)
def _jitted_psum(mesh, axis, donate: bool = False):
    """Cached jitted lax.psum baseline — cached exactly like the flextree
    path's ``_jitted_allreduce`` so the A/B times collectives, not retraces."""

    def per_device(row):
        return lax.psum(row[0], axis)[None]

    return jax.jit(
        jax.shard_map(per_device, mesh=mesh, in_specs=P(axis), out_specs=P(axis)),
        donate_argnums=(0,) if donate else (),
    )


def _xla_psum_over_mesh(stacked, mesh, axis, op):
    """The platform-native baseline (the reference's ``--comm-type mpi``)."""
    if op != "sum":
        raise ValueError("the xla baseline benchmarks psum; use op=sum")
    return _jitted_psum(mesh, axis)(stacked)


def run_allreduce_bench(cfg: BenchConfig) -> BenchReport:
    n = cfg.devices or len(jax.devices())
    mesh = flat_mesh(n, "ft")
    topo = Topology.resolve(n, cfg.topo)
    dtype = jnp.dtype(cfg.dtype)
    rop = get_op(cfg.op)
    rop.check_dtype(dtype)

    # data[r, i] = (i % 256) + r, like benchmark.cpp:119-124 but with
    # per-rank-distinct rows so every op has a non-trivial reduction; values
    # are small so float32 sums stay exactly representable and integer
    # wraparound (int8 etc.) is identical on host and device
    base = np.arange(cfg.size, dtype=np.int64) % 256
    data = (base[None, :] + np.arange(n, dtype=np.int64)[:, None]).astype(dtype)
    stacked = jnp.asarray(data)
    if stacked.dtype != dtype:
        # e.g. float64 demoted to float32 when jax_enable_x64 is off; keep
        # the host copy consistent so the correctness check and byte counts
        # describe what actually ran
        log.warning("dtype %s demoted to %s on device", dtype, stacked.dtype)
        dtype = stacked.dtype
        data = data.astype(dtype)

    log.info(
        "bench config: devices=%d size=%d dtype=%s op=%s comm=%s topo=%s repeat=%d",
        n, cfg.size, cfg.dtype, cfg.op, cfg.comm_type, topo, cfg.repeat,
    )

    # ``fn`` is the non-donating variant used for the correctness check;
    # timing uses the in-place chained protocol when cfg.in_place (values
    # compound across reps exactly like the reference's MPI_IN_PLACE loop —
    # they may saturate to inf late in the chain, which is timing-neutral
    # for IEEE arithmetic; correctness is asserted on a pristine call below).
    if cfg.comm_type == "flextree":
        fn = lambda x: allreduce_over_mesh(x, mesh, topo=topo, op=cfg.op)
        if cfg.in_place:
            fn_timed = lambda x: allreduce_over_mesh(
                x, mesh, topo=topo, op=cfg.op, in_place=True
            )
            result = time_jax_fn_inplace(fn_timed, jnp.array(stacked), repeat=cfg.repeat)
        else:
            result = time_jax_fn(fn, stacked, repeat=cfg.repeat)
    elif cfg.comm_type == "xla":
        fn = lambda x: _xla_psum_over_mesh(x, mesh, "ft", cfg.op)
        if cfg.in_place:
            if cfg.op != "sum":
                raise ValueError("the xla baseline benchmarks psum; use op=sum")
            # give the baseline its best shot: donated and non-donated
            r_don = time_jax_fn_inplace(
                _jitted_psum(mesh, "ft", donate=True), jnp.array(stacked),
                repeat=cfg.repeat,
            )
            r_plain = time_jax_fn_inplace(
                _jitted_psum(mesh, "ft", donate=False), jnp.array(stacked),
                repeat=cfg.repeat,
            )
            result = r_don if r_don.min_s <= r_plain.min_s else r_plain
        else:
            result = time_jax_fn(fn, stacked, repeat=cfg.repeat)
    else:
        raise ValueError(f"unknown --comm-type {cfg.comm_type!r} (flextree|xla)")

    out = np.asarray(fn(stacked))
    # fold the op over the host rows in the on-device dtype: integer
    # wraparound then matches the device exactly; floats are compared with
    # tolerance since the collective may reassociate the sum
    expect = data[0]
    for r in range(1, n):
        expect = rop.np_fn(expect, data[r])
    got = out[0]
    if np.issubdtype(dtype, np.inexact) or dtype == jnp.bfloat16:
        correct = bool(
            np.allclose(
                got.astype(np.float64), expect.astype(np.float64),
                rtol=1e-3, atol=1e-3,
            )
        )
    else:
        correct = bool(np.array_equal(got, expect))
    lo, hi = 9, min(20, cfg.size)
    if hi > lo:  # the reference's eyeball print of data[9..19]
        log.info("elements %d..%d: %s (expect %s)", lo, hi - 1,
                 got[lo:hi].tolist(), expect[lo:hi].tolist())

    nbytes = cfg.size * stacked.dtype.itemsize
    bus = bus_bandwidth_GBps(n, nbytes, result.min_s * 1e6)
    log.info(
        "average time %.3f ms / min time %.3f ms / bus bw %.3f GB/s / correct=%s",
        result.avg_s * 1e3, result.min_s * 1e3, bus, correct,
    )

    path = None
    if cfg.to_file:
        name = result_file_name(
            cfg.tag, n, cfg.size, str(topo), comm_test=(cfg.comm_type == "xla")
        )
        report = BenchReport(cfg, n, str(topo), result, bus, correct, None)
        path = str(write_result_file(f"{cfg.out_dir}/{name}", report.payload()))
        log.info("wrote %s", path)

    return BenchReport(cfg, n, str(topo), result, bus, correct, path)


# ---------------------------------------------------------------- attention


@dataclass(frozen=True)
class AttentionBenchConfig:
    batch: int = 4
    seq_len: int = 4096
    heads: int = 16
    head_dim: int = 128
    dtype: str = "bfloat16"
    impl: str = "flash"  # flash | reference | stock
    repeat: int = 20
    block_q: int = 256
    block_k: int = 512
    # forward k-walk structure (flash impl only): "loop" | "pipelined" |
    # "kvgrid" — see flextree_tpu.ops.pallas_attention.flash_attention
    variant: str = "loop"
    # "device_loop": in-jit chained fori_loop, slope of two iteration
    # counts — measures DEVICE time only, immune to the tunneled backend's
    # per-dispatch latency (the r01/r02 numbers were dominated by it; see
    # PROFILE_ATTENTION.md).  "chained": per-call python loop with a final
    # fetch — includes dispatch overhead; kept for comparison/CPU tests.
    timing: str = "device_loop"
    # "fwd": forward only.  "grad": grads of sum(attention) wrt (q, k, v) —
    # for flash/stock, exercises the forward-with-residuals plus both
    # blockwise backward kernels; reported FLOPs are per-impl hardware
    # FLOPs (flash & stock 4.5x fwd — qk recomputed in both the dq and dkv
    # kernels; reference 3x, P stored — see grad_flop_scale in
    # run_attention_bench).
    mode: str = "fwd"


from ..utils.device import tpu_generation  # dependency-free normalizer

#: bf16 peak TFLOP/s by generation, for MFU reporting.
_TPU_PEAK_TFLOPS = {
    "v5e": 197.0,
    "v6e": 918.0,
    "v5p": 459.0,
    "v4": 275.0,
    "v3": 123.0,
    "v2": 45.0,
}


def chip_peak_tflops() -> float | None:
    """bf16 peak of device 0, or None off-TPU (MFU then unreported)."""
    dev = jax.devices()[0]
    if dev.platform == "cpu":
        return None
    gen = tpu_generation(getattr(dev, "device_kind", ""))
    return _TPU_PEAK_TFLOPS.get(gen) if gen else None


@dataclass(frozen=True)
class AttentionBenchReport:
    config: AttentionBenchConfig
    per_call_s: float
    tflops: float
    mfu: float | None = None  # tflops / chip bf16 peak, when on TPU
    result_path: str | None = None

    def payload(self) -> dict:
        return {
            "bench": "attention",
            "impl": self.config.impl,
            "mode": self.config.mode,
            "batch": self.config.batch,
            "seq_len": self.config.seq_len,
            "heads": self.config.heads,
            "head_dim": self.config.head_dim,
            "dtype": self.config.dtype,
            "block_q": self.config.block_q,
            "block_k": self.config.block_k,
            "variant": self.config.variant if self.config.impl == "flash" else None,
            "per_call_s": self.per_call_s,
            "tflops": self.tflops,
            "mfu": self.mfu,
        }


def stock_block_sizes(block_q: int, block_k: int):
    """Full ``BlockSizes`` for the stock Pallas flash kernel, forward AND
    backward, derived from one (block_q, block_k) pair.

    The backward blocks mirror the forward derivation (``block_*_major =
    max(block_k, block_q)``), so a single swept pair configures both
    passes — required for the grad A/B baseline (VERDICT r3 item 3: the
    stock bwd raises unless every backward block is set).  segment_ids
    stays None on both sides of the A/B — we don't benchmark segmenting.
    """
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes

    bkM = max(block_k, block_q)
    return BlockSizes(
        block_q=block_q,
        block_k_major=bkM,
        block_k=block_k,
        block_b=1,
        block_q_major_dkv=block_q,
        block_k_major_dkv=bkM,
        block_k_dkv=block_k,
        block_q_dkv=block_q,
        block_k_major_dq=bkM,
        block_k_dq=block_k,
        block_q_dq=block_q,
    )


def run_attention_bench(
    cfg: AttentionBenchConfig,
    *,
    tag: str = "flextree",
    to_file: bool = False,
    out_dir: str = ".",
) -> AttentionBenchReport:
    """Time one attention impl with a data-dependency chain
    (``flextree_tpu.utils.timing.time_chained``) — the completion gate that
    holds even over the tunneled single-chip backend bench.py documents."""
    from ..ops.pallas_attention import flash_attention
    from ..parallel.ring_attention import attention_reference

    layout_bhtd = False  # stock kernel's native layout is (B, H, T, D)
    if cfg.mode not in ("fwd", "grad"):
        raise ValueError(f"unknown mode {cfg.mode!r} (fwd|grad)")
    if cfg.impl == "flash":
        core = lambda q, k, v: flash_attention(  # noqa: E731
            q, k, v, causal=True, block_q=cfg.block_q, block_k=cfg.block_k,
            variant=cfg.variant,
        )
        fn = None  # grad/fwd wrap below
    elif cfg.impl == "reference":
        core = lambda q, k, v: attention_reference(q, k, v, causal=True)  # noqa: E731
        fn = None
    elif cfg.impl == "stock":
        # the stock Pallas TPU flash kernel, measured FAIRLY: inputs are
        # generated directly in its native (B, H, T, D) layout (no timed
        # transposes — the r02 measurement paid them and undersold the
        # baseline) and its block sizes come from the config (bench.py
        # sweeps them; defaults below are the v5e-tuned winners)
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            BlockSizes,
            flash_attention as stock_flash,
        )

        layout_bhtd = True
        bs = stock_block_sizes(cfg.block_q, cfg.block_k)
        core = lambda q, k, v: stock_flash(  # noqa: E731
            q, k, v, causal=True, block_sizes=bs
        )
        fn = None
    else:
        raise ValueError(f"unknown attention impl {cfg.impl!r}")
    if fn is None:  # flash/reference/stock share the grad/fwd wrap
        if cfg.mode == "grad":
            g = jax.grad(lambda q, k, v: core(q, k, v).sum(), argnums=(0, 1, 2))

            def grad_all(q, k, v):
                dq, dk, dv = g(q, k, v)
                # fold all three grads into the chained carry: grad wrt q
                # alone lets XLA DCE the dk/dv backward work that the
                # 4.5x/3x hardware-FLOP scale below charges for
                return dq + dk + dv

            fn = jax.jit(grad_all)
        else:
            fn = jax.jit(core)

    b, t, h, d = cfg.batch, cfg.seq_len, cfg.heads, cfg.head_dim
    rng = np.random.default_rng(0)
    dtype = jnp.dtype(cfg.dtype)
    shape = (b, h, t, d) if layout_bhtd else (b, t, h, d)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.standard_normal(shape).astype(np.float32), dtype=dtype
    )
    q, k, v = mk(), mk(), mk()
    if cfg.timing == "device_loop":
        # cfg.repeat governs only the chained protocol; device_loop's
        # sample counts are its n_lo/n_hi/best_of — say so when the caller
        # set a non-default repeat expecting it to matter
        if cfg.repeat != type(cfg).repeat:
            log.warning(
                "timing='device_loop' ignores repeat=%d (fixed slope "
                "protocol); use timing='chained' if you want a repeat loop",
                cfg.repeat,
            )
        per_call = time_device_loop(fn, q, k, v)
    elif cfg.timing == "chained":
        per_call = time_chained(fn, q, k, v, n_calls=cfg.repeat)
    else:
        raise ValueError(
            f"unknown timing {cfg.timing!r} (device_loop|chained)"
        )
    # hardware-FLOP scale for grad mode, per impl: the flash path re-runs
    # the forward (custom_vjp) then 3 dq-kernel + 4 dkv-kernel matmuls over
    # the visible tiles -> (2+3+4)/2 = 4.5x fwd; XLA autodiff of the
    # full-matrix reference stores P and does 4 backward matmuls, no
    # recompute -> (2+4)/2 = 3x fwd.  The stock Pallas bwd has the same
    # structure as ours (qk recomputed in both the 3-matmul dq and
    # 4-matmul dkv kernels; fwd residuals o/l/m saved) -> 4.5x too.
    if cfg.mode == "grad":
        grad_flop_scale = 3.0 if cfg.impl == "reference" else 4.5
    else:
        grad_flop_scale = 1.0
    flops = 4 * b * h * t * t * d / 2 * grad_flop_scale  # causal
    tflops = flops / per_call / 1e12
    peak = chip_peak_tflops()
    report = AttentionBenchReport(
        cfg, per_call, tflops, round(tflops / peak, 4) if peak else None
    )
    log.info(
        "attention %s: %.3f ms/call, %.2f TFLOP/s%s",
        cfg.impl if cfg.mode == "fwd" else f"{cfg.impl}+grad",
        per_call * 1e3, report.tflops,
        f" ({report.mfu * 100:.1f}% MFU)" if report.mfu is not None else "",
    )
    if to_file:
        name = result_file_name(
            tag=tag,
            num_devices=1,
            size=b * t * h * d,
            topo=f"attn_{cfg.impl}",
        )
        path = str(write_result_file(f"{out_dir}/{name}", report.payload()))
        report = dataclasses.replace(report, result_path=path)
    return report


def autotune_attention(
    cfg: AttentionBenchConfig,
    blocks: tuple[tuple[int, int], ...] = (
        (256, 512), (512, 512), (512, 1024), (1024, 512)
    ),
    repeat: int | None = None,
    impl: str = "flash",
    variants: tuple[str, ...] | None = None,
) -> AttentionBenchReport:
    """Sweep explicit (block_q, block_k) pairs (x forward ``variants`` for
    the flash impl) and return the fastest report (VERDICT r1 item 3's
    autotune).  The default pairs are the top configs from the v5e block
    sweep in PROFILE_ATTENTION.md — a compile over the tunneled backend
    costs ~30 s, so the sweep is a shortlist, not a product.  Works for
    ``impl="stock"`` too (block_k_major and the backward blocks are
    derived in ``run_attention_bench``)."""
    rep_kw = {} if repeat is None else {"repeat": repeat}
    if impl == "reference":
        # block sizes don't reach attention_reference; sweeping them would
        # re-run the identical benchmark len(blocks) times
        return run_attention_bench(
            dataclasses.replace(cfg, impl=impl, **rep_kw)
        )
    if variants is None or impl != "flash":
        variants = (cfg.variant,)
    # fail fast on a bad variant name — the per-combo except below is for
    # combos that don't FIT, and would otherwise silently drop the whole
    # schedule from the sweep
    unknown = set(variants) - {"loop", "pipelined", "kvgrid"}
    if unknown:
        raise ValueError(f"unknown flash variant(s): {sorted(unknown)}")
    best = None
    for variant in variants:
        for bq, bk in blocks:
            c = dataclasses.replace(cfg, impl=impl, block_q=bq, block_k=bk,
                                    variant=variant, **rep_kw)
            try:
                r = run_attention_bench(c)
            except Exception as e:  # noqa: BLE001 — a combo may not fit
                log.warning(
                    "autotune (%s, %d, %d) failed: %s", variant, bq, bk, e
                )
                continue
            if best is None or r.tflops > best.tflops:
                best = r
    if best is None:
        raise RuntimeError("no autotune configuration succeeded")
    return best
