"""Benchmark harness: the reference benchmark.cpp rebuilt for JAX/TPU."""

from .harness import BenchConfig, BenchReport, run_allreduce_bench

__all__ = ["BenchConfig", "BenchReport", "run_allreduce_bench"]
