"""Benchmark CLI: ``python -m flextree_tpu.bench --size 4096 --repeat 10
--comm-type flextree --topo 4,2``.

Flag set mirrors the reference harness (``benchmark.cpp:67-116``), with
``--devices`` / ``--cpu N`` replacing ``mpirun -np N`` (virtual CPU devices
stand in for ranks when real multi-chip hardware isn't attached) and
``--comm-type xla`` as the library-baseline A/B (``--comm-type mpi`` there).
``--version`` prints the package version like the reference's git-stamped
``--version`` (``benchmark.cpp:109-115``).
"""

from __future__ import annotations

import argparse


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="flextree_tpu.bench")
    ap.add_argument(
        "--bench",
        choices=["allreduce", "attention"],
        default="allreduce",
        help="allreduce A/B (default) or fused-attention kernel benchmark",
    )
    ap.add_argument("--size", type=int, default=35, help="elements per chip")
    ap.add_argument("--repeat", type=int, default=10)
    ap.add_argument("--comm-type", choices=["flextree", "xla"], default="flextree")
    ap.add_argument("--topo", type=str, default=None, help="FT_TOPO-style widths")
    ap.add_argument("--devices", type=int, default=None)
    ap.add_argument(
        "--cpu",
        type=int,
        default=None,
        metavar="N",
        help="run on N virtual CPU devices (must be set before JAX starts real backends)",
    )
    ap.add_argument("--dtype", type=str, default="float32")
    ap.add_argument("--op", type=str, default="sum")
    ap.add_argument(
        "--no-in-place",
        action="store_true",
        help="time without buffer donation (default times the reference's "
        "MPI_IN_PLACE-style compounding loop, benchmark.cpp:149-159)",
    )
    # attention-bench geometry (--bench attention)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq-len", type=int, default=4096)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument(
        "--attn-impl", choices=["flash", "reference", "stock"], default="flash"
    )
    ap.add_argument(
        "--autotune", action="store_true",
        help="sweep the shortlisted (block_q, block_k) pairs from the v5e "
        "block sweep (flash/stock; reference runs once, blocks unused)",
    )
    ap.add_argument("--block-q", type=int, default=256)
    ap.add_argument("--block-k", type=int, default=512)
    ap.add_argument(
        "--attn-variant", choices=["loop", "pipelined", "kvgrid"],
        default="loop",
        help="flash forward k-walk structure (ablation knob for the "
        "MXU/VPU-overlap win; loop = the carry-serialized r03 kernel)",
    )
    ap.add_argument(
        "--attn-mode", choices=["fwd", "grad"], default="fwd",
        help="grad: time grads of sum(attention) wrt (q, k, v) — the "
        "fwd-with-residuals pass plus both blockwise backward kernels "
        "(hw FLOPs incl. recompute); flash, stock, and reference",
    )
    ap.add_argument(
        "--attn-timing", choices=["device_loop", "chained"],
        default="device_loop",
        help="device_loop: in-jit fori_loop slope (device time only, immune "
        "to dispatch latency); chained: per-call python loop (includes it)",
    )
    ap.add_argument(
        "--attn-dtype",
        type=str,
        default="bfloat16",
        help="compute dtype for --bench attention (independent of --dtype)",
    )
    ap.add_argument("--tag", type=str, default="flextree")
    ap.add_argument("--to-file", action="store_true")
    ap.add_argument("--out-dir", type=str, default=".")
    ap.add_argument("--version", action="store_true")
    args = ap.parse_args(argv)

    if args.version:
        from flextree_tpu.utils.buildstamp import version_string

        print(version_string())
        return 0

    if args.cpu:
        import jax

        from flextree_tpu.utils.compat import request_cpu_devices

        jax.config.update("jax_platforms", "cpu")
        # this jax pin has no jax_num_cpu_devices option — the compat
        # shim falls back to XLA_FLAGS (same fix as trainer --cpu)
        request_cpu_devices(args.cpu)

    if args.bench == "attention":
        from .harness import (
            AttentionBenchConfig,
            autotune_attention,
            run_attention_bench,
        )

        acfg_kw = dict(
            batch=args.batch,
            seq_len=args.seq_len,
            heads=args.heads,
            head_dim=args.head_dim,
            dtype=args.attn_dtype,
            impl=args.attn_impl,
            block_q=args.block_q,
            block_k=args.block_k,
            timing=args.attn_timing,
            mode=args.attn_mode,
            variant=args.attn_variant,
        )
        if args.attn_timing == "chained":
            acfg_kw["repeat"] = args.repeat  # device_loop ignores repeat
        acfg = AttentionBenchConfig(**acfg_kw)
        if args.autotune:
            report = autotune_attention(acfg, impl=args.attn_impl)
        else:
            report = run_attention_bench(
                acfg, tag=args.tag, to_file=args.to_file, out_dir=args.out_dir
            )
        mfu = f" ({report.mfu * 100:.1f}% MFU)" if report.mfu is not None else ""
        print(
            f"{report.config.impl}(bq={report.config.block_q}, "
            f"bk={report.config.block_k}): {report.per_call_s * 1e3:.3f} "
            f"ms/call, {report.tflops:.2f} TFLOP/s{mfu}"
            + (f" -> {report.result_path}" if report.result_path else "")
        )
        return 0

    from .harness import BenchConfig, run_allreduce_bench

    cfg = BenchConfig(
        size=args.size,
        repeat=args.repeat,
        comm_type=args.comm_type,
        topo=args.topo,
        devices=args.devices,
        dtype=args.dtype,
        op=args.op,
        tag=args.tag,
        to_file=args.to_file,
        out_dir=args.out_dir,
        in_place=not args.no_in_place,
    )
    report = run_allreduce_bench(cfg)
    return 0 if report.correct else 1


if __name__ == "__main__":
    raise SystemExit(main())
