"""Offline planner: candidate enumeration + TPU cost model + chooser.

The rebuild of the reference's ``cost_model/`` + ``topo_count/`` subsystems.
Unlike the reference (where the planner is a separate binary whose printed
width vector a human pastes into ``FT_TOPO``, SURVEY §1), ours is importable
by the runtime — ``choose_topology(...).topology`` drops straight into
``allreduce(topo=...)`` — while remaining usable offline via
``python -m flextree_tpu.planner``.  A native C++ core (``native/``)
accelerates the enumeration/argmin path, with this package as the
pure-Python fallback and ground truth.
"""

from .cost_model import (
    CostBreakdown,
    DCN_DEFAULT,
    ICI_DEFAULT,
    LinkParams,
    TpuCostParams,
    allreduce_cost,
    bus_bandwidth_GBps,
    ring_cost,
)
from .calibrate import (
    CALIBRATION_SCHEMA,
    MeasuredPoint,
    backend_fingerprint,
    default_params,
    feature_vector,
    fit_cost_params,
    load_calibration,
    measure_points,
    plan_cache_key,
    predict_us,
    save_calibration,
    spearman,
)
from .autotune import (
    DEFAULT_CODECS,
    TunedPlan,
    analytic_shortlist,
    autotune_plan,
    invalidate_plan_cache,
)
from .feedback import (
    DriftDetector,
    FeedbackConfig,
    FeedbackController,
    FeedbackRefused,
    ProbePoint,
    ReplanDecision,
    cache_invalidation_predicate,
    extract_residuals,
    fit_from_samples,
)
from .choose import (
    Candidate,
    Plan,
    candidate_topologies,
    choose_bucket_bytes,
    choose_topology,
    replan_for_survivors,
)
from .factorize import (
    count_ordered_factorizations,
    is_prime,
    ordered_factorizations,
    ordered_factorizations_combinatoric,
    prime_factors,
)
from .shapes import format_shape, parse_shape, shape_taxonomy
from .native import (
    load_native,
    native_available,
    native_choose,
    native_count_shapes,
)

__all__ = [
    "CostBreakdown",
    "LinkParams",
    "TpuCostParams",
    "ICI_DEFAULT",
    "DCN_DEFAULT",
    "allreduce_cost",
    "ring_cost",
    "bus_bandwidth_GBps",
    "MeasuredPoint",
    "measure_points",
    "feature_vector",
    "fit_cost_params",
    "predict_us",
    "spearman",
    "save_calibration",
    "load_calibration",
    "default_params",
    "backend_fingerprint",
    "plan_cache_key",
    "CALIBRATION_SCHEMA",
    "TunedPlan",
    "analytic_shortlist",
    "autotune_plan",
    "invalidate_plan_cache",
    "DEFAULT_CODECS",
    "DriftDetector",
    "FeedbackConfig",
    "FeedbackController",
    "FeedbackRefused",
    "ProbePoint",
    "ReplanDecision",
    "cache_invalidation_predicate",
    "extract_residuals",
    "fit_from_samples",
    "Candidate",
    "Plan",
    "candidate_topologies",
    "choose_bucket_bytes",
    "choose_topology",
    "replan_for_survivors",
    "count_ordered_factorizations",
    "is_prime",
    "ordered_factorizations",
    "ordered_factorizations_combinatoric",
    "prime_factors",
    "format_shape",
    "parse_shape",
    "shape_taxonomy",
    "load_native",
    "native_available",
    "native_choose",
    "native_count_shapes",
]
