"""Cost-model calibration: fit ``TpuCostParams`` from measurements.

The reference's constants were calibrated on its cluster
(``cost_model/CostModel.h:1-30``: lo/co/bo/o fitted to a 16-host Ethernet
fabric); round 1 shipped invented "v5e-flavored defaults" and the verdict
rightly called that out.  This module closes the loop the reference never
automated: run the real collective at a few (topology, size) points on the
*current* backend, then least-squares fit the model's constants so the
planner's argmin tracks measured orderings.

The fit exploits the model's linearity: ``allreduce_cost`` is linear in
(launch_us, latency_us, 1/bandwidth, 1/reduce_bw), so evaluating it with
one-hot "basis" parameter settings yields the feature matrix directly from
the model's own code — the fit can never drift out of sync with the cost
formulas.

Main entry points:

- ``measure_points(topos, sizes, ...)`` — time the collective per point
  (in-place chained protocol, same as the benchmark harness).
- ``fit_cost_params(points)`` — non-negative least-squares fit.
- ``spearman(a, b)`` — rank correlation used by the validation test and
  the committed sweep analysis.
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass

import numpy as np

from ..schedule.stages import Topology
from .cost_model import LinkParams, TpuCostParams, allreduce_cost

__all__ = [
    "MeasuredPoint",
    "measure_points",
    "feature_vector",
    "fit_cost_params",
    "predict_us",
    "spearman",
    "save_calibration",
    "load_calibration",
    "default_params",
    "backend_fingerprint",
    "plan_cache_key",
    "CALIBRATION_SCHEMA",
]

#: Schema version written into every calibration section (and every
#: autotune plan-cache entry).  Bump when the on-disk format changes;
#: loaders refuse sections from a NEWER schema rather than misparse them.
#: Schema 3 adds the split-collective per-phase bandwidth scales
#: (``rs_bw_scale``/``ag_bw_scale``, arXiv:2409.04202's two-halves
#: costing); schema 4 adds the provenance ``source`` stamp
#: ("measured" = tools/calibrate_host.py's direct measurement protocol,
#: "feedback" = the closed-loop refit from flight-record residuals,
#: planner/feedback.py — with sample count and source-run id in ``meta``).
#: Older sections still load, with the neutral defaults and a logged
#: notice (never silently).
CALIBRATION_SCHEMA = 4


def backend_fingerprint() -> str | None:
    """Stable identity of the measuring backend: platform, device kind,
    device count and jax version — the key that keeps constants measured
    on one host from silently pricing another (a 1-core CPU fit must
    never cost a TPU fabric, and a v5e fit must not cost a v4).

    Deliberately built from the *device*, not from a section name:
    calibration sections may be named more specifically than jax platform
    names (``tpu_v5e`` vs ``tpu``), and that naming granularity must not
    defeat the check (or the prefix-fallback lookup).

    Returns None when no backend is initialized and none can be described
    — callers then skip the check rather than guess.  Like
    ``default_params``, this never *initializes* a backend itself.
    """
    import sys

    if "jax" not in sys.modules:
        return None
    jax = sys.modules["jax"]
    try:
        if not jax._src.xla_bridge._backends:  # not initialized: stay lazy
            return None
        devs = jax.devices()
        kind = getattr(devs[0], "device_kind", devs[0].platform)
        return "|".join(
            [
                devs[0].platform,
                str(kind),
                f"n{len(devs)}",
                f"jax{jax.__version__}",
            ]
        )
    except Exception:  # noqa: BLE001 — fingerprinting must never raise
        return None


def plan_cache_key(*parts) -> str:
    """Join key components into the flat string key both the calibration
    fingerprint check and the autotune plan cache use — one helper so the
    two caches cannot diverge in how they identify a measurement context."""
    return "|".join("~" if p is None else str(p) for p in parts)


@dataclass(frozen=True)
class MeasuredPoint:
    widths: tuple[int, ...]  # (1,) = ring
    num_nodes: int
    nbytes: int  # per chip
    measured_us: float
    # full per-repetition sample (µs) when available, so validation can
    # compare fitted-prediction spread against measurement noise instead of
    # asserting rank order on indistinguishable points (VERDICT r2 weak #2)
    times_us: tuple[float, ...] = ()

    @property
    def noise_us(self) -> float:
        """Half the inter-quartile spread of the sample — 0 if unknown."""
        if len(self.times_us) < 4:
            return 0.0
        q1, q3 = np.percentile(self.times_us, [25, 75])
        return 0.5 * float(q3 - q1)


def _params_basis() -> list[TpuCostParams]:
    """One-hot parameter settings s.t. ``cost(p_i)`` is the i-th feature.

    Order: [launch_us, latency_us, inv_link_bw (us/byte), inv_reduce_bw].
    ``bandwidth_GBps=1e-3`` makes ``time_us(nbytes) == nbytes`` (the
    model divides by ``bw*1e3``), i.e. a unit inverse-bandwidth feature.
    """
    big = 1e30  # "infinite" bandwidth: zero contribution
    return [
        TpuCostParams(ici=LinkParams(big, 0.0), dcn=LinkParams(big, 0.0),
                      reduce_bw_GBps=big, control_us_per_width=0.0, launch_us=1.0),
        TpuCostParams(ici=LinkParams(big, 1.0), dcn=LinkParams(big, 1.0),
                      reduce_bw_GBps=big, control_us_per_width=0.0, launch_us=0.0),
        TpuCostParams(ici=LinkParams(1e-3, 0.0), dcn=LinkParams(1e-3, 0.0),
                      reduce_bw_GBps=big, control_us_per_width=0.0, launch_us=0.0),
        TpuCostParams(ici=LinkParams(big, 0.0), dcn=LinkParams(big, 0.0),
                      reduce_bw_GBps=1e-3, control_us_per_width=0.0, launch_us=0.0),
    ]


def feature_vector(widths: tuple[int, ...], n: int, nbytes: int) -> np.ndarray:
    topo = Topology.ring(n) if widths == (1,) else Topology(n, widths)
    return np.array(
        [allreduce_cost(topo, nbytes, p).total_us for p in _params_basis()],
        dtype=np.float64,
    )


def measure_points(
    topos,
    sizes,
    *,
    repeat: int = 10,
    devices: int | None = None,
    stat: str = "median",
) -> list[MeasuredPoint]:
    """Time the FlexTree collective at each (topo, size-in-elements) point
    on the current backend, via the benchmark harness's in-place protocol.

    ``stat``: summary statistic over the ``repeat`` reps — ``"median"``
    (default; robust on a timeshared host where min-of-few is noise-bound,
    VERDICT r2 weak #2) or ``"min"`` (the reference harness's headline,
    ``benchmark.cpp:215``).  The full sample is kept on each point.
    """
    import jax

    from ..bench.harness import BenchConfig, run_allreduce_bench

    if stat not in ("median", "min"):
        raise ValueError(f"stat must be 'median' or 'min', got {stat!r}")
    n = devices or len(jax.devices())
    points = []
    for size in sizes:
        for spec in topos:
            rep = run_allreduce_bench(
                BenchConfig(size=size, repeat=repeat, comm_type="flextree",
                            topo=spec, devices=n)
            )
            widths = (1,) if rep.topo == "1" else tuple(
                int(w) for w in rep.topo.split("*")
            )
            summary = (
                rep.result.median_s if stat == "median" else rep.result.min_s
            )
            points.append(
                MeasuredPoint(
                    widths, n, size * 4, summary * 1e6,
                    tuple(t * 1e6 for t in rep.result.times_s),
                )
            )
    return points


def fit_cost_params(
    points: list[MeasuredPoint], *, relative: bool = True
) -> TpuCostParams:
    """Non-negative least-squares fit of the 4 model constants.

    Plain ``lstsq`` with negative coefficients clipped to ~0 and refit on
    the surviving features (no scipy dependency); 4 parameters over >=8
    points keeps this well-posed.

    ``relative=True`` (default) fits *relative* residuals — each row is
    scaled by ``1/measured`` — so a 20% error on a fast small-payload point
    weighs the same as a 20% error on a slow large-payload one.  The
    planner's job is rank ordering across shapes, and absolute least
    squares lets the largest-payload points dominate and zero out the
    shape-discriminating launch/latency features (the degenerate
    "predictions are shape-independent" fit of VERDICT r2 weak #2).
    """
    if len(points) < 4:
        raise ValueError(f"need >= 4 measured points, got {len(points)}")
    X = np.stack([feature_vector(p.widths, p.num_nodes, p.nbytes) for p in points])
    y = np.array([p.measured_us for p in points])
    if relative:
        w = 1.0 / np.maximum(y, 1e-9)
        Xw = X * w[:, None]
        yw = np.ones_like(y)
    else:
        Xw, yw = X, y
    active = list(range(X.shape[1]))
    theta = np.zeros(X.shape[1])
    for _ in range(X.shape[1]):
        sol, *_ = np.linalg.lstsq(Xw[:, active], yw, rcond=None)
        if (sol >= 0).all():
            theta[:] = 0.0
            theta[active] = sol
            break
        active = [a for a, s in zip(active, sol) if s > 0]
        if not active:
            # every refit round produced negative coefficients: the
            # measurements contradict the model everywhere.  Returning the
            # silent all-zero fit would hand the planner a meaningless
            # ranking (ADVICE r2) — fail loudly instead.
            raise RuntimeError(
                "cost-param fit degenerated: NNLS active set is empty "
                "(all coefficients negative). The measurements are "
                "inconsistent with the cost model; re-measure with more "
                "repeats or check the timing protocol."
            )
    launch, lat, inv_bw, inv_rbw = theta
    tiny = 1e-12
    bw = 1.0 / max(inv_bw, tiny) / 1e3  # us/byte -> GB/s
    rbw = 1.0 / max(inv_rbw, tiny) / 1e3
    return TpuCostParams(
        ici=LinkParams(bandwidth_GBps=bw, latency_us=float(lat)),
        dcn=LinkParams(bandwidth_GBps=bw, latency_us=float(lat)),
        reduce_bw_GBps=rbw,
        control_us_per_width=0.0,
        launch_us=float(launch),
    )


# ---------------------------------------------------------------------------
# persistence: CALIBRATION.json (VERDICT r2 item 5)
#
# The reference's constants are compiled in (CostModel.h:1-30); ours are
# fitted at runtime, so they need a place to live between runs.  The file
# holds one section per backend ("cpu", "tpu_v5e", ...) because constants
# measured on a 1-core CPU host must never silently price a TPU fabric.
# Loading is EXPLICIT (path argument, FLEXTREE_CALIBRATION env var, or the
# planner CLI's --calibration flag) rather than an ambient cwd lookup, so
# library behavior — including the golden tests pinning the invented
# defaults — never depends on what directory you happen to run from.
# ---------------------------------------------------------------------------


def _params_to_dict(p: TpuCostParams) -> dict:
    return {
        "ici_bandwidth_GBps": p.ici.bandwidth_GBps,
        "ici_latency_us": p.ici.latency_us,
        "dcn_bandwidth_GBps": p.dcn.bandwidth_GBps,
        "dcn_latency_us": p.dcn.latency_us,
        "reduce_bw_GBps": p.reduce_bw_GBps,
        "control_us_per_width": p.control_us_per_width,
        "launch_us": p.launch_us,
        "codec_bw_GBps": p.codec_bw_GBps,
        "bwd_GFLOPs": p.bwd_GFLOPs,
        "rs_bw_scale": p.rs_bw_scale,
        "ag_bw_scale": p.ag_bw_scale,
    }


def _params_from_dict(d: dict) -> TpuCostParams:
    if "rs_bw_scale" not in d or "ag_bw_scale" not in d:
        # pre-schema-3 section: the split-collective per-phase scales were
        # not measured — load with the neutral 1.0 (the fused costing),
        # and say so rather than defaulting silently
        from ..utils.logging import get_logger

        get_logger("flextree.planner").info(
            "calibration section predates the split-collective constants "
            "(schema < 3); rs_bw_scale/ag_bw_scale default to 1.0 — "
            "re-run tools/calibrate_host.py to measure them"
        )
    return TpuCostParams(
        ici=LinkParams(d["ici_bandwidth_GBps"], d["ici_latency_us"]),
        dcn=LinkParams(d["dcn_bandwidth_GBps"], d["dcn_latency_us"]),
        reduce_bw_GBps=d["reduce_bw_GBps"],
        control_us_per_width=d["control_us_per_width"],
        launch_us=d["launch_us"],
        # schema-1 files predate the codec term: fall back to the default
        codec_bw_GBps=d.get("codec_bw_GBps", TpuCostParams.codec_bw_GBps),
        # files written before the overlap planner lack the backward-compute
        # constant: 0.0 keeps the backend-resolved default in force
        bwd_GFLOPs=d.get("bwd_GFLOPs", TpuCostParams.bwd_GFLOPs),
        rs_bw_scale=d.get("rs_bw_scale", TpuCostParams.rs_bw_scale),
        ag_bw_scale=d.get("ag_bw_scale", TpuCostParams.ag_bw_scale),
    )


def save_calibration(
    path,
    params: TpuCostParams,
    *,
    backend: str,
    meta: dict | None = None,
    fingerprint: str | None = None,
    source: str = "measured",
) -> None:
    """Write/merge the ``backend`` section of a CALIBRATION.json file.

    ``meta`` should say where the numbers came from (protocol, host,
    measured points, date) — the file is a committed artifact and each
    constant must be traceable to a measurement or labeled as a default.

    Every section is stamped with ``schema`` (:data:`CALIBRATION_SCHEMA`),
    the measuring backend's ``fingerprint``
    (:func:`backend_fingerprint` unless given explicitly) so a fit from
    one host is never silently reused on another — ``load_calibration``
    rejects mismatches — and a provenance ``source``: ``"measured"`` (the
    direct-measurement protocol of ``tools/calibrate_host.py``) or
    ``"feedback"`` (the closed-loop refit from flight-record residuals,
    ``planner/feedback.py`` — its ``meta`` carries the sample count and
    the source-run id).
    """
    import json
    import os

    doc = {}
    if os.path.exists(path):
        with open(path) as f:
            doc = json.load(f)
    doc[backend] = {
        "schema": CALIBRATION_SCHEMA,
        "fingerprint": fingerprint or backend_fingerprint(),
        "source": source,
        "params": _params_to_dict(params),
        "meta": meta or {},
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)


def load_calibration(
    path, *, backend: str, fingerprint: str | None = None
) -> TpuCostParams | None:
    """Load the ``backend`` section; None if the file/section is absent.

    Section names may be more specific than jax platform names (the file
    says ``tpu_v5e``; ``jax.default_backend()`` says ``tpu``), so a miss
    on the exact name falls back to the unique section with the platform
    as a prefix — measured TPU constants must not be silently dropped
    because of a naming-granularity mismatch.  Ambiguity (two ``tpu_*``
    sections) stays a miss: guessing between chips would be worse.

    Fingerprint check: when the section carries one AND the current
    backend's fingerprint is determinable (``fingerprint`` argument, else
    :func:`backend_fingerprint`), a mismatch is a **miss** — constants
    fitted on another host/chip must not silently price this one.
    Sections written before the fingerprint era (no ``fingerprint`` key)
    load with a warning: not silent, and the committed per-backend section
    names still gate the platform.  Sections from a NEWER schema are
    rejected outright rather than misparsed.
    """
    import json
    import os

    from ..utils.logging import get_logger

    if not path or not os.path.exists(path):
        return None
    with open(path) as f:
        doc = json.load(f)
    sec = doc.get(backend)
    if sec is None:
        prefixed = [k for k in doc if k.startswith(backend + "_")]
        if len(prefixed) == 1:
            sec = doc[prefixed[0]]
    if not sec:
        return None
    log = get_logger("flextree.planner")
    if sec.get("schema", 1) > CALIBRATION_SCHEMA:
        log.warning(
            "calibration %s section %r has schema %s > supported %s; ignoring",
            path, backend, sec.get("schema"), CALIBRATION_SCHEMA,
        )
        return None
    # provenance source stamp (schema 4): pre-stamp sections load — the
    # established older-sections-load-non-silently contract — but say so,
    # and every mismatch warning below names where the constants came from
    source = sec.get("source")
    if source is None:
        log.info(
            "calibration %s section %r predates source stamping "
            "(schema < 4); re-run tools/calibrate_host.py to record "
            "whether these constants are measured or feedback-fitted",
            path, backend,
        )
        source = "unstamped"
    saved_fp = sec.get("fingerprint")
    if saved_fp is None:
        log.warning(
            "calibration %s section %r (source=%s) predates fingerprinting; "
            "loading unverified (re-run tools/calibrate_host.py to stamp it)",
            path, backend, source,
        )
    else:
        current_fp = fingerprint or backend_fingerprint()
        if current_fp is not None and current_fp != saved_fp:
            log.warning(
                "calibration %s section %r (source=%s) was fitted on %r but "
                "this backend is %r; ignoring it (re-run "
                "tools/calibrate_host.py on this host)",
                path, backend, source, saved_fp, current_fp,
            )
            return None
    return _params_from_dict(sec["params"])


def default_params(backend: str | None = None) -> TpuCostParams:
    """The planner's default constants: the ``FLEXTREE_CALIBRATION`` file's
    section for ``backend`` when both exist, else the invented
    v5e-flavored ``TpuCostParams()`` defaults.

    ``backend=None`` resolves from ``FLEXTREE_CALIBRATION_BACKEND`` or, if
    jax is already imported and initialized, the active platform — it will
    NOT import/initialize jax itself (backend init can hang on a wedged
    remote tunnel, and the planner must stay usable offline).
    """
    import os
    import sys

    path = os.environ.get("FLEXTREE_CALIBRATION")
    if not path:
        return TpuCostParams()
    if backend is None:
        backend = os.environ.get("FLEXTREE_CALIBRATION_BACKEND")
    if backend is None and "jax" in sys.modules:
        try:
            jax = sys.modules["jax"]
            if jax._src.xla_bridge._backends:  # initialized already?
                backend = jax.default_backend()
        except Exception:  # noqa: BLE001 — stay usable without a backend
            backend = None
    if backend is None:
        # UNRESOLVABLE backend: fall back to the invented defaults, not to
        # some section — guessing (e.g. "cpu") would let 1-core-host
        # constants silently price a TPU fabric, the exact failure the
        # per-backend sections exist to prevent
        return TpuCostParams()
    return load_calibration(path, backend=backend) or TpuCostParams()


def predict_us(params: TpuCostParams, widths, n: int, nbytes: int) -> float:
    topo = Topology.ring(n) if tuple(widths) == (1,) else Topology(n, tuple(widths))
    return allreduce_cost(topo, nbytes, params).total_us


def spearman(a, b) -> float:
    """Spearman rank correlation (ties -> average rank; no scipy)."""

    def rankdata(v):
        v = np.asarray(v, dtype=np.float64)
        order = np.argsort(v, kind="stable")
        ranks = np.empty(len(v))
        ranks[order] = np.arange(1, len(v) + 1)
        for val in np.unique(v):
            m = v == val
            if m.sum() > 1:
                ranks[m] = ranks[m].mean()
        return ranks

    ra, rb = rankdata(a), rankdata(b)
    ra -= ra.mean()
    rb -= rb.mean()
    denom = math.sqrt((ra**2).sum() * (rb**2).sum())
    return float((ra * rb).sum() / denom) if denom else 0.0
