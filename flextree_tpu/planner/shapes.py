"""Shape pretty-printing: the ``2*3+1`` / ``2*2*2-1`` notation of the
reference's ``cost_model/PrintTreeStructure.h`` (and its README taxonomy),
where a trailing ``+1``/``-1`` records that the shape factorizes N∓1 and one
node is treated as extra/missing (the prime-N strategy)."""

from __future__ import annotations

from typing import Sequence

__all__ = ["format_shape", "parse_shape", "shape_taxonomy"]


def format_shape(widths: Sequence[int], delta: int = 0) -> str:
    """``(2, 3)`` -> ``"2*3"``; with ``delta=+1`` -> ``"2*3+1"``."""
    if tuple(widths) == (1,):
        core = "ring"
    else:
        core = "*".join(str(w) for w in widths)
    if delta > 0:
        return f"{core}+{delta}"
    if delta < 0:
        return f"{core}{delta}"
    return core


def parse_shape(text: str) -> tuple[tuple[int, ...], int]:
    """Inverse of :func:`format_shape`: ``"2*3+1"`` -> ``((2, 3), 1)``."""
    text = text.strip()
    delta = 0
    for sign in ("+", "-"):
        # a trailing signed integer after the factor list
        idx = text.rfind(sign)
        if idx > 0 and text[idx + 1 :].isdigit():
            delta = int(text[idx:])
            text = text[:idx]
            break
    if text == "ring":
        return (1,), delta
    widths = tuple(int(tok) for tok in text.split("*"))
    return widths, delta


def shape_taxonomy(n: int) -> list[str]:
    """Worked-example listing for ``n`` in the reference README's style
    (``cost_model/README.md:13-71``): non-prime N lists its factorizations;
    prime N lists the factorizations of N±1 with ``+1``/``-1`` suffixes."""
    from .factorize import is_prime, ordered_factorizations

    if n < 2:
        return []
    if not is_prime(n):
        return [format_shape(w) for w in ordered_factorizations(n)]
    out = [format_shape(w, +1) for w in ordered_factorizations(n - 1)]
    out += [format_shape(w, -1) for w in ordered_factorizations(n + 1)]
    return out
