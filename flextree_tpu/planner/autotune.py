"""Measured plan autotuner: close the analytic -> measured loop.

"Revisiting the Time Cost Model of AllReduce" (PAPERS.md) argues α-β
models must be anchored to measurement; ``calibrate.py`` does that for
the model's *constants* but the final plan pick was still pure argmin.
This module finishes the loop: take the top-K **analytic** candidates
over the (tree shape x wire codec) product, time each with the bench
harness's shuffled-interleaved rep protocol on the live backend, pick the
**measured** winner, and persist it in a plan cache so the second run is
a pure cache hit.

Cache contract: entries are keyed by ``plan_cache_key(fingerprint, n,
nbytes, dtype, codecs)`` — the same fingerprint helper the calibration
file uses (``calibrate.backend_fingerprint``), so a plan measured on one
host/chip is never silently replayed on another; a fingerprint mismatch
is a miss and the candidates are re-measured.  The cache file is JSON
(an explicit ``cache_path``, else ``FLEXTREE_PLAN_CACHE``, else the
user-level :data:`DEFAULT_CACHE_PATH` — persistence must hold out of the
box), one entry per key, schema-versioned by :data:`PLAN_CACHE_SCHEMA` (its
own constant — calibration-file schema bumps must not orphan plan
caches under older checkouts).

The measured winner can only improve on the analytic argmin: the argmin
is always in the shortlist, so ``min(measured)`` is never slower than the
argmin's own measured time (asserted in ``tests/test_autotune.py`` with
an injected fake timer, alongside the first-run-measures /
second-run-cache-hits demo).
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass

from ..schedule.ir import IRFamilySpec
from ..schedule.stages import LonelyTopology, Topology
from .calibrate import (
    backend_fingerprint,
    default_params,
    plan_cache_key,
)
from .choose import choose_topology

#: Plan-cache file schema — deliberately DECOUPLED from
#: ``calibrate.CALIBRATION_SCHEMA``: the two files evolve independently,
#: and stamping plan caches with the calibration constant would make a
#: calibration-only bump (e.g. schema 4's provenance ``source`` stamp)
#: silently discard — and on the next rewrite destroy — a fresh plan
#: cache under any older checkout sharing the user-level cache file.
#: Bump this one only when the plan-cache ENTRY format itself changes.
PLAN_CACHE_SCHEMA = 3

__all__ = [
    "TunedPlan",
    "analytic_shortlist",
    "autotune_plan",
    "invalidate_plan_cache",
    "DEFAULT_CODECS",
    "DEFAULT_IR_FAMILIES",
]

DEFAULT_CODECS = ("f32", "bf16", "int8")

#: schedule-IR families offered to the measured search by default
#: (ISSUE 8): the analytic model ranks them honestly (swing pays its
#: distance-weighted wire, generalized its per-round launches), and when
#: one makes the shortlist the measurement — not the model — decides.
#: They enter only for the identity codec and unsharded plans (no
#: compressed / split-phase lowering for IR families yet).
DEFAULT_IR_FAMILIES = ("swing", "generalized")


@dataclass(frozen=True)
class TunedPlan:
    """Autotuner output: the winning (shape, codec) plus provenance.

    ``family`` records which schedule family won: ``"tree"`` for every
    legacy shape (ring included) or an IR family (``"swing"`` /
    ``"generalized"``).  Cache entries persist it, so an IR winner can
    never be replayed as (or aliased against) a legacy widths vector —
    the no-alias guard of the plan cache."""

    num_nodes: int
    nbytes: int
    dtype: str
    widths: tuple[int, ...]
    lonely: int
    codec: str
    predicted_us: float
    measured_us: float | None
    source: str  # "measured" | "cache" | "analytic"
    #: ranked shortlist rows: (shape, lonely, codec, predicted_us,
    #: measured_us) — ``shape`` is a widths tuple for legacy rows, an
    #: ``"swing"``/``"gen:..."`` spec string for IR rows
    table: tuple = ()
    family: str = "tree"
    ports: int = 0

    def to_ft_topo(self) -> str:
        if self.family == "swing":
            return "swing"
        if self.family == "generalized":
            return f"gen:{','.join(map(str, self.widths))}@{self.ports}"
        spec = ",".join(map(str, self.widths))
        if self.lonely:
            spec += f"+{self.lonely}"
        return spec

    @property
    def topology(self):
        if self.family == "swing":
            return IRFamilySpec("swing", self.num_nodes)
        if self.family == "generalized":
            return IRFamilySpec(
                "generalized", self.num_nodes, self.widths, self.ports
            )
        if self.widths == (1,):
            return Topology.ring(self.num_nodes)
        if self.lonely:
            return LonelyTopology(
                self.num_nodes,
                Topology(self.num_nodes - self.lonely, self.widths),
                self.lonely,
            )
        return Topology(self.num_nodes, self.widths)


def analytic_shortlist(
    n: int,
    nbytes: int,
    codecs=DEFAULT_CODECS,
    params=None,
    top_k: int = 4,
    sharded: bool = False,
    ir_families=DEFAULT_IR_FAMILIES,
) -> list[tuple]:
    """Top-K ``(shape, lonely, codec, predicted_us)`` over the shape x
    codec product, cheapest first — ``shape`` is a widths tuple for
    legacy candidates or an ``IRFamilySpec`` for swing/generalized rows
    (offered under the identity codec only).  The overall analytic
    argmin is rank 0 by construction.  ``sharded`` prices one ZeRO sync
    round (grad reduce-scatter + param all-gather —
    ``choose_topology(collective="sharded")``) instead of the fused
    allreduce, and excludes IR families (no split-phase lowering)."""
    if params is None:
        params = default_params()
    rows: list[tuple] = []
    for codec in codecs:
        offer_ir = (
            tuple(ir_families) if codec == "f32" and not sharded else ()
        )
        plan = choose_topology(
            n, nbytes, params=params, codec=codec,
            collective="sharded" if sharded else "allreduce",
            ir_families=offer_ir,
        )
        for c in plan.candidates:
            if c.family == "tree":
                rows.append((c.widths, c.lonely, codec, c.total_us))
            else:
                fam = (
                    IRFamilySpec("swing", n)
                    if c.family == "swing"
                    else IRFamilySpec("generalized", n, c.widths, c.ports)
                )
                rows.append((fam, 0, codec, c.total_us))
    rows.sort(key=lambda r: r[3])
    return rows[: max(1, top_k)]


# ------------------------------------------------------------- cache


#: Default on-disk plan cache when neither ``cache_path`` nor
#: ``FLEXTREE_PLAN_CACHE`` names one — persistence is the documented
#: contract ("the second run is a pure cache hit"), so it must hold out
#: of the box, not only for users who exported an env var.  Entries are
#: keyed by backend fingerprint, so a shared user-level cache is safe
#: across hosts/backends.
DEFAULT_CACHE_PATH = os.path.join(
    os.path.expanduser("~"), ".cache", "flextree_tpu", "plan_cache.json"
)


def _cache_path(cache_path):
    if cache_path is not None:
        return cache_path
    return os.environ.get("FLEXTREE_PLAN_CACHE") or DEFAULT_CACHE_PATH


def _cache_load(path) -> dict:
    if not path or not os.path.exists(path):
        return {"schema": PLAN_CACHE_SCHEMA, "entries": {}}
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {"schema": PLAN_CACHE_SCHEMA, "entries": {}}
    if doc.get("schema", 1) > PLAN_CACHE_SCHEMA:
        return {"schema": PLAN_CACHE_SCHEMA, "entries": {}}
    doc.setdefault("entries", {})
    return doc


def _cache_store(path, doc) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    os.replace(tmp, path)  # atomic: a concurrent reader never sees a torn file


def invalidate_plan_cache(predicate, cache_path=None) -> int:
    """Drop plan-cache entries matching ``predicate(key, entry) -> bool``;
    returns how many were removed.

    The drift-invalidation seam of the closed feedback loop
    (``planner/feedback.py``, ISSUE 12): when measured comm residuals show
    a cached plan was priced by stale constants, the matching entries are
    removed so the next ``maybe_autotune_grad_topo`` / ``autotune_plan``
    call **re-measures** the shortlist instead of riding the stale winner.
    ``predicate`` receives the flat cache key string
    (:func:`~flextree_tpu.planner.calibrate.plan_cache_key` layout) and
    the stored entry dict (which carries the measuring ``fingerprint``) —
    :func:`flextree_tpu.planner.feedback.cache_invalidation_predicate`
    builds the standard fingerprint+world matcher.  A missing/empty cache
    is a no-op (0), and an untouched cache file is not rewritten.
    """
    path = _cache_path(cache_path)
    if not path or not os.path.exists(path):
        return 0
    doc = _cache_load(path)
    keep = {}
    removed = 0
    for key, entry in doc["entries"].items():
        if predicate(key, entry):
            removed += 1
        else:
            keep[key] = entry
    if removed:
        doc["entries"] = keep
        _cache_store(path, doc)
    return removed


# ------------------------------------------------------------ measure


def _default_timer(candidates, n, nbytes, dtype, repeat, sharded: bool = False):
    """Measure every candidate with the bench harness's shuffled-
    interleaved protocol (one warmed jitted fn per candidate, reps
    interleaved in shuffled rounds so a host-contention episode cannot
    land on one candidate — the BENCH_ALLREDUCE r03/r04 lesson).
    Returns measured seconds per candidate, aligned with ``candidates``.
    ``sharded`` times the split round the ZeRO step actually runs
    (``all_gather(reduce_scatter(x))`` with the codec on both wires).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from ..bench.harness import _interleaved_times
    from ..parallel.allreduce import all_gather, reduce_scatter
    from ..parallel.compressed import compressed_allreduce
    from ..parallel.mesh import flat_mesh

    mesh = flat_mesh(n, "ft")
    size = max(1, nbytes // jnp.dtype(dtype).itemsize)
    rng = np.random.default_rng(0)
    x = jnp.asarray(
        rng.standard_normal((n, size)).astype(np.float32), dtype=jnp.dtype(dtype)
    )

    calls = {}
    for i, (widths, lonely, codec, _pred) in enumerate(candidates):
        if isinstance(widths, IRFamilySpec):
            spec = widths.spec  # "swing" / "gen:...": allreduce resolves it
        else:
            spec = ",".join(map(str, widths)) + (f"+{lonely}" if lonely else "")

        def device_fn(row, spec=spec, codec=codec):
            if sharded:
                shard = reduce_scatter(row[0], "ft", topo=spec, codec=codec)
                return all_gather(
                    shard, "ft", topo=spec, out_shape=row[0].shape, codec=codec
                )[None]
            return compressed_allreduce(row[0], "ft", topo=spec, codec=codec)[None]

        fn = jax.jit(
            jax.shard_map(
                device_fn, mesh=mesh, in_specs=P("ft"), out_specs=P("ft"),
                check_vma=False,
            )
        )
        jax.block_until_ready(fn(x))  # compile outside the timed reps
        calls[str(i)] = (fn, (x,))
    rows = _interleaved_times(calls, repeat)
    return [rows[str(i)]["min_ms"] * 1e-3 for i in range(len(candidates))]


# ------------------------------------------------------------- entry


def autotune_plan(
    n: int,
    nbytes: int,
    *,
    dtype: str = "float32",
    codecs=DEFAULT_CODECS,
    top_k: int = 4,
    params=None,
    cache_path=None,
    timer=None,
    repeat: int = 5,
    use_cache: bool = True,
    overlap: bool = False,
    sharded: bool = False,
    ir_families=DEFAULT_IR_FAMILIES,
) -> TunedPlan:
    """Pick the gradient-sync plan by measurement.

    First run: rank the shape x codec product analytically, measure the
    top-``top_k`` candidates (``timer(candidates, n, nbytes, dtype,
    repeat) -> [seconds]``, defaulting to the live-backend protocol
    above), persist the winner under the backend-fingerprinted key.
    Second run with the same key: pure cache hit — no timing, no compile.

    ``codecs=("f32",)`` tunes shape only (the measured twin of
    ``choose_topology``); the default product also offers the wire codecs
    so the planner can trade shape against precision.

    ``overlap`` tags the cache key: a plan measured for the serialized
    sync must never be silently replayed for the readiness-ordered
    overlapped sync (or vice versa) — the overlapped step issues its
    collectives mid-backward, where the best shape can differ (smaller
    latency-bound buckets win when comm hides under compute).  The
    shortlist and measurement protocol are shared; only the key differs.

    ``sharded`` switches both the analytic costing AND the measured
    protocol to the ZeRO split round (grad reduce-scatter + param
    all-gather), and grows the cache key with a sharding component —
    sharded and replicated plans never alias (same rule as overlap, new
    guard in ``tests/test_sharded.py``).
    """
    codecs = tuple(codecs)
    shortlist = analytic_shortlist(
        n, nbytes, codecs, params=params, top_k=top_k, sharded=sharded,
        ir_families=ir_families,
    )
    fp = backend_fingerprint()
    key = plan_cache_key(
        fp, f"n{n}", f"{nbytes}B", dtype, ",".join(codecs),
        "overlap" if overlap else "serial",
        "sharded" if sharded else "replicated",
    )
    path = _cache_path(cache_path)

    if use_cache and path:
        doc = _cache_load(path)
        hit = doc["entries"].get(key)
        if hit is not None and hit.get("fingerprint") == fp:
            return TunedPlan(
                n, nbytes, dtype,
                tuple(hit["widths"]), int(hit.get("lonely", 0)), hit["codec"],
                float(hit["predicted_us"]), float(hit["measured_us"]),
                source="cache",
                table=tuple(tuple(r) for r in hit.get("table", ())),
                # the no-alias guard: an IR-family winner is stored WITH
                # its family and can never round-trip as a tree widths
                # vector (tests/test_schedule_ir.py pins this)
                family=hit.get("family", "tree"),
                ports=int(hit.get("ports", 0)),
            )

    if timer is None:
        def timer(c, n_, nb, dt, rep, _sharded=sharded):
            return _default_timer(c, n_, nb, dt, rep, sharded=_sharded)
    measured_s = timer(shortlist, n, nbytes, dtype, repeat)
    if len(measured_s) != len(shortlist):
        raise ValueError(
            f"timer returned {len(measured_s)} times for "
            f"{len(shortlist)} candidates"
        )
    def _row_shape(shape):
        return shape.spec if isinstance(shape, IRFamilySpec) else shape

    table = tuple(
        (_row_shape(shape), lonely, codec, pred, t * 1e6)
        for (shape, lonely, codec, pred), t in zip(shortlist, measured_s)
    )
    best_i = min(range(len(shortlist)), key=lambda i: measured_s[i])
    shape, lonely, codec, pred = shortlist[best_i]
    if isinstance(shape, IRFamilySpec):
        family, widths, ports = shape.family, shape.widths, shape.ports
    else:
        family, widths, ports = "tree", shape, 0
    plan = TunedPlan(
        n, nbytes, dtype, widths, lonely, codec, pred,
        measured_s[best_i] * 1e6, source="measured", table=table,
        family=family, ports=ports,
    )
    if use_cache and path:
        doc = _cache_load(path)
        doc["entries"][key] = {
            "fingerprint": fp,
            "widths": list(widths),
            "lonely": lonely,
            "codec": codec,
            "family": family,
            "ports": ports,
            "predicted_us": pred,
            "measured_us": plan.measured_us,
            "table": [
                [list(w) if not isinstance(w, str) else w, l, c, p, m]
                for (w, l, c, p, m) in table
            ],
        }
        _cache_store(path, doc)
    return plan
