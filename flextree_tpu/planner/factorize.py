"""Candidate tree-shape enumeration: ordered factorizations of N.

Rebuilds the reference planner's enumeration layer
(``cost_model/GetWidth.h:7-47`` ``getWidth`` — DFS over divisors — and
``topo_count/factor_count.py`` — the search-space counter) without its
global mutable accumulators (``GetWidth.h:7-8``, known-bug list SURVEY §8).

The reference's legacy second enumerator (``getWidth2``,
``GetWidth.h:51-227``: candidates as products of prime-factor subsets, 9
nested loop levels, and a ``d[p]*d[q]`` typo at ``:198`` that corrupts the
last factor) is rebuilt here as
:func:`ordered_factorizations_combinatoric` — the same combinatoric route
(multiset factorizations from the prime decomposition, then distinct
permutations), depth-unlimited and typo-free, cross-validated against the
DFS enumerator in ``tests/test_planner.py``.

Also provides primality / prime-factorization utilities
(``cost_model/IsPrimeNumber.h``, ``GetPrimeFactor.h``), fixing the
reference's ``is_prime(1) == True`` bug.
"""

from __future__ import annotations

import functools


__all__ = [
    "is_prime",
    "prime_factors",
    "ordered_factorizations",
    "ordered_factorizations_combinatoric",
    "count_ordered_factorizations",
]


def is_prime(n: int) -> bool:
    """Primality by trial division (``IsPrimeNumber.h:4-21``); unlike the
    reference, 1 is correctly not prime."""
    if n < 2:
        return False
    if n < 4:
        return True
    if n % 2 == 0:
        return False
    f = 3
    while f * f <= n:
        if n % f == 0:
            return False
        f += 2
    return True


def prime_factors(n: int) -> list[int]:
    """Multiset of prime factors in ascending order
    (``GetPrimeFactor.h:5-19``)."""
    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    out = []
    f = 2
    while f * f <= n:
        while n % f == 0:
            out.append(f)
            n //= f
        f += 1 if f == 2 else 2
    if n > 1:
        out.append(n)
    return out


def ordered_factorizations(n: int, min_factor: int = 2) -> list[tuple[int, ...]]:
    """All ordered factorizations of ``n`` into factors >= ``min_factor``,
    including the single-factor shape ``(n,)`` — the candidate stage-width
    vectors for ``n`` devices (``GetWidth.h:7-47``).

    Order matters: ``(2, 4)`` and ``(4, 2)`` are distinct tree shapes (a
    wide-then-narrow tree communicates differently than narrow-then-wide).
    """
    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    if n == 1:
        return []
    out: list[tuple[int, ...]] = []

    def dfs(rest: int, prefix: tuple[int, ...]):
        # every proper divisor d (min_factor <= d < rest) can lead; collect
        # both members of each divisor pair around sqrt(rest)
        divs = set()
        d = min_factor
        while d * d <= rest:
            if rest % d == 0:
                divs.add(d)
                divs.add(rest // d)
            d += 1
        divs.discard(rest)
        for d in sorted(divs):
            dfs(rest // d, prefix + (d,))
        out.append(prefix + (rest,))

    dfs(n, ())
    return out


def ordered_factorizations_combinatoric(
    n: int, min_factor: int = 2
) -> list[tuple[int, ...]]:
    """The P2 rebuild: the same candidate set as
    :func:`ordered_factorizations`, derived the way the reference's legacy
    ``getWidth2`` tried to (``GetWidth.h:51-227``) — *unordered* multiset
    factorizations built from the prime decomposition, expanded into their
    distinct orderings — rather than by divisor DFS.

    Differences from the reference, on purpose: depth-unlimited (theirs
    hardcoded 9 nested subset levels), no ``d[p]*d[q]`` typo
    (``GetWidth.h:198`` draws the final factor from the wrong array,
    corrupting candidates once >= 3 factor groups are in play), and no
    flat/ring sentinel rows (``{1,N}``/``{N,1}``, ``:207-225``) — sentinel
    handling lives in :class:`~flextree_tpu.schedule.stages.Topology`
    parsing, not in the enumeration.  Returns a sorted list (deterministic,
    unlike the reference's insertion order); equality with the DFS
    enumerator is pinned by ``tests/test_planner.py``.
    """
    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    if n == 1:
        return []

    def multisets(rest: int, max_f: int) -> list[tuple[int, ...]]:
        """Non-increasing factorizations of ``rest`` with factors
        <= ``max_f`` (each a multiset of divisors >= min_factor)."""
        out = []
        if min_factor <= rest <= max_f:
            out.append((rest,))
        d = min(max_f, rest // min_factor)
        while d >= min_factor:
            if rest % d == 0:
                for tail in multisets(rest // d, d):
                    out.append((d,) + tail)
            d -= 1
        return out

    def distinct_orderings(counts: dict[int, int], length: int):
        """All distinct permutations of a factor multiset, generated
        directly from its counts — multinomial cost, not the factorial
        blow-up of ``itertools.permutations`` on repeated factors (at
        n=4096 the (2,)*12 multiset has ONE ordering, not 12! duplicates
        to dedup)."""
        if length == 0:
            yield ()
            return
        for f in counts:
            if counts[f]:
                counts[f] -= 1
                for tail in distinct_orderings(counts, length - 1):
                    yield (f,) + tail
                counts[f] += 1

    shapes: list[tuple[int, ...]] = []
    for ms in multisets(n, n):
        counts: dict[int, int] = {}
        for f in ms:
            counts[f] = counts.get(f, 0) + 1
        shapes.extend(distinct_orderings(counts, len(ms)))
    return sorted(shapes)


@functools.lru_cache(maxsize=4096)
def count_ordered_factorizations(n: int) -> int:
    """Search-space size — the analog of
    ``topo_count/factor_count.py:1-11``, memoized instead of exponential."""
    if n <= 1:
        return 0

    # f(n) = 1 + sum over divisors d of n (2 <= d < n) of f(n/d):
    # pick the first stage width d, recurse on the rest.  Divisor pairs
    # (d, n/d) around sqrt(n) cover the whole divisor set.
    @functools.lru_cache(maxsize=None)
    def f(rest: int) -> int:
        total = 1  # the single-stage shape (rest,)
        d = 2
        while d * d <= rest:
            if rest % d == 0:
                total += f(rest // d)  # first factor d
                if d != rest // d:
                    total += f(d)  # first factor rest//d
            d += 1
        return total

    return f(n)
