"""ctypes bindings for the native planner core (``native/flextree_planner.cpp``).

The reference's planner is native C++ (``cost_model/*.h``); ours keeps a
native core for the hot enumeration/argmin path with a pure-Python fallback
(``planner.choose``) when the shared library hasn't been built.  Build with
``make -C native`` (no pybind11 in this image — plain C ABI + ctypes).
"""

from __future__ import annotations

import ctypes
import functools
import os
import subprocess
from pathlib import Path

from .cost_model import TpuCostParams

__all__ = [
    "load_native",
    "native_available",
    "native_choose",
    "native_choose_lonely",
    "native_count_shapes",
]

_NATIVE_DIR = Path(__file__).resolve().parents[2] / "native"
_LIB_NAME = "libflextree_planner.so"


def _run_make(force: bool = False) -> bool:
    cmd = ["make", "-C", str(_NATIVE_DIR)] + (["-B"] if force else [])
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, OSError):
        return False


@functools.lru_cache(maxsize=1)
def load_native(build_if_missing: bool = True):
    """Load (building on first use if possible) the native planner library.

    Returns the ctypes CDLL or None if unavailable; all callers must
    fall back to the Python implementation on None.
    """
    lib_path = _NATIVE_DIR / _LIB_NAME
    if not lib_path.exists() and build_if_missing:
        if not _run_make():
            return None
    if not lib_path.exists():
        return None
    try:
        lib = ctypes.CDLL(str(lib_path))
    except OSError:
        return None
    if not hasattr(lib, "ft_enumerate_shapes2"):
        # stale library built from an older source tree (the marker symbol
        # is the NEWEST entry point — bump it whenever the ABI grows, or a
        # prebuilt .so silently lacks the new path).
        # Rebuild, then load through a fresh temp copy: dlopen caches by
        # path, so re-CDLL'ing the same file would return the old mapping.
        if not (build_if_missing and _run_make(force=True)):
            return None
        import shutil
        import tempfile

        tmp = tempfile.NamedTemporaryFile(
            suffix=".so", prefix="flextree_", delete=False
        )
        tmp.close()
        try:
            shutil.copy(lib_path, tmp.name)
            lib = ctypes.CDLL(tmp.name)
        except OSError:
            return None
        if not hasattr(lib, "ft_enumerate_shapes2"):
            return None

    lib.ft_count_shapes.restype = ctypes.c_uint64
    lib.ft_count_shapes.argtypes = [ctypes.c_uint64]
    lib.ft_enumerate_shapes.restype = ctypes.c_int64
    lib.ft_enumerate_shapes.argtypes = [
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.ft_shape_cost.restype = ctypes.c_double
    lib.ft_shape_cost.argtypes = [
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_uint32,
        ctypes.c_uint64,
    ] + [ctypes.c_double] * 6
    lib.ft_choose.restype = ctypes.c_int32
    lib.ft_choose.argtypes = [
        ctypes.c_uint64,
    ] + [ctypes.c_double] * 6 + [
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_double),
    ]
    lib.ft_sweep.restype = ctypes.c_uint64
    lib.ft_sweep.argtypes = [ctypes.c_uint64] + [ctypes.c_double] * 6
    lib.ft_enumerate_shapes2.restype = ctypes.c_int64
    lib.ft_enumerate_shapes2.argtypes = list(lib.ft_enumerate_shapes.argtypes)
    lib.ft_choose2.restype = ctypes.c_int32
    lib.ft_choose2.argtypes = [
        ctypes.c_uint64,
    ] + [ctypes.c_double] * 6 + [
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_uint32),
    ]
    return lib


def native_available() -> bool:
    return load_native() is not None


def _param_args(params: TpuCostParams):
    return (
        params.ici.bandwidth_GBps,
        params.ici.latency_us,
        params.reduce_bw_GBps,
        params.control_us_per_width,
        params.launch_us,
    )


def native_count_shapes(n: int) -> int | None:
    lib = load_native()
    if lib is None:
        return None
    return int(lib.ft_count_shapes(n))


def _read_shape_records(fn, n: int) -> list[tuple[int, ...]] | None:
    needed = ctypes.c_uint64(0)
    fn(n, None, 0, ctypes.byref(needed))
    buf = (ctypes.c_uint32 * max(1, needed.value))()
    cnt = fn(n, buf, needed.value, ctypes.byref(needed))
    if cnt < 0:
        return None
    out, off = [], 0
    for _ in range(cnt):
        k = buf[off]
        out.append(tuple(buf[off + 1 : off + 1 + k]))
        off += 1 + k
    return out


def native_enumerate_shapes(n: int) -> list[tuple[int, ...]] | None:
    lib = load_native()
    if lib is None:
        return None
    return _read_shape_records(lib.ft_enumerate_shapes, n)


def native_enumerate_shapes_combinatoric(n: int) -> list[tuple[int, ...]] | None:
    """The native P2 twin (``ft_enumerate_shapes2``): candidates via
    prime-multiset factorizations + distinct orderings, sorted — the
    reference's legacy ``getWidth2`` route, typo-free (GetWidth.h:198).
    None when the library is unavailable (an older build without the
    symbol triggers load_native's marker-driven rebuild)."""
    lib = load_native()
    if lib is None:
        return None
    return _read_shape_records(lib.ft_enumerate_shapes2, n)


def native_shape_cost(
    widths: tuple[int, ...], n: int, nbytes: float, params: TpuCostParams
) -> float | None:
    lib = load_native()
    if lib is None:
        return None
    arr = (ctypes.c_uint32 * len(widths))(*widths)
    return float(
        lib.ft_shape_cost(arr, len(widths), n, float(nbytes), *_param_args(params))
    )


def native_choose(
    n: int, nbytes: float, params: TpuCostParams = TpuCostParams()
) -> tuple[tuple[int, ...], float] | None:
    """Native IN-TREE argmin; (widths, predicted µs) or None.

    Never returns lonely shapes: the historical contract is that the
    returned widths are directly usable as an ``n``-rank topology
    (product == n, or the ring sentinel).  Use ``native_choose_lonely``
    for the full candidate space including executable ``+1`` shapes.
    """
    lib = load_native()
    if lib is None:
        return None
    out = (ctypes.c_uint32 * 64)()
    cost = ctypes.c_double(0.0)
    k = lib.ft_choose(
        n, float(nbytes), *_param_args(params), out, 64, ctypes.byref(cost)
    )
    if k < 0:
        return None
    return tuple(out[:k]), float(cost.value)


def native_choose_lonely(
    n: int, nbytes: float, params: TpuCostParams = TpuCostParams()
) -> tuple[tuple[int, ...], int, float] | None:
    """(widths, lonely, predicted µs) — lonely is 0 for in-tree winners,
    1 when a tree-over-(n-1)-plus-one-lonely shape wins (prime n); a
    lonely winner's widths are the TREE widths (spec = "w0,..,wk+1")."""
    lib = load_native()
    if lib is None:
        return None
    out = (ctypes.c_uint32 * 64)()
    cost = ctypes.c_double(0.0)
    lonely = ctypes.c_uint32(0)
    k = lib.ft_choose2(
        n, float(nbytes), *_param_args(params), out, 64,
        ctypes.byref(cost), ctypes.byref(lonely),
    )
    if k < 0:
        return None
    return tuple(out[:k]), int(lonely.value), float(cost.value)


def native_sweep(
    n_max: int, nbytes: float, params: TpuCostParams = TpuCostParams()
) -> int | None:
    lib = load_native()
    if lib is None:
        return None
    return int(lib.ft_sweep(n_max, float(nbytes), *_param_args(params)))
