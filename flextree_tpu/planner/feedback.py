"""Closed-loop planner feedback: fit constants from flight records,
detect drift, replan in-run (ISSUE 12).

PR 10 made every gradient-bucket comm span carry its plan provenance plus
the planner's predicted ``CostBreakdown`` — and nothing consumed the
predicted-vs-measured residuals, so a run that started on a
mis-calibrated host rode the wrong plan forever.  This module closes the
loop, per the "Revisiting the Time Cost Model of AllReduce" treatment
(arXiv:2409.04202: α-β models must be anchored to measurement, and
RE-anchored when the measurement disagrees):

1. **Residual extraction** (:func:`extract_residuals`): read a run's
   per-rank ``flight_*.jsonl`` files and pair each provenance-annotated
   ``bucket_planned`` span's prediction against the measured
   ``bucket_measured`` time at the same (topo, world, codec, sharded,
   nbytes) point.  The pairing itself lives in
   ``obs/timeline.py::residual_pairs`` so the ``python -m
   flextree_tpu.obs residuals`` CLI and this fitter share one code path.
2. **Fitting** (:func:`fit_from_samples`): convert the residual samples
   into the :class:`~flextree_tpu.planner.calibrate.MeasuredPoint` form
   ``fit_cost_params`` consumes and solve for updated α-β constants —
   re-using ``calibrate.feature_vector``'s model-derived feature matrix,
   so the refit can never drift out of sync with the cost formulas —
   plus a codec-throughput rescale from compressed samples and a
   bwd-GFLOPs update from compute probes when available.  Starved or
   degenerate sample sets are REFUSED loudly (:class:`FeedbackRefused`):
   a fit from 3 points, or from one shape measured 50 times, would hand
   the planner a confident lie.
3. **Drift detection** (:class:`DriftDetector`): per-(fingerprint,
   world, topo family, codec, sharded) sliding windows of relative
   residuals; the band breach is the replan trigger, and it also
   invalidates matching autotune plan-cache entries
   (``autotune.invalidate_plan_cache``) so the next measured search
   re-measures instead of riding the stale winner.
4. **In-run replanning** (:class:`FeedbackController`):
   ``fit(supervision=Supervision(feedback=...))`` ticks the controller
   every ``every_k`` steps; with the flight recorder on it times a small
   probe set on the live wire, feeds the detector, and — past the band —
   refits, writes the constants back through ``save_calibration``
   (``source="feedback"``), invalidates the plan cache, re-runs
   ``choose_topology`` with the refitted constants and hands ``fit`` a
   rebuilt step through the same swap path ``replan_for_survivors``
   exercises for shrink.  With the recorder off the tick is ONE ``None``
   check (the same check ``record_event`` makes) — zero new overhead,
   machine-checked by ``tools/feedback_convergence.py``.

ISSUE 15 adds the **probe-free** tier on top: with
``FeedbackConfig(probe_free=True)`` no dedicated probe ever runs — every
materialized step is host-timed against its compile-time plan
(``obs/stepclock.py``), drift detection rides the per-step spans, and a
refit solves **per-phase scale factors** (:func:`fit_phase_scales` /
:func:`fit_probe_free`) across a bucket-size rotation of
bitwise-invariant plan variants.  The same per-phase machinery
attributes drift to latency/bandwidth/reduce/codec for the probe path
(``fit_from_samples`` meta) and the residuals CLI
(:func:`attribute_groups`), and :func:`fit_residuals_auto` backs the
``python -m flextree_tpu.obs fleet`` cross-run pooling pass.  Proven by
``tools/probe_free_feedback.py`` → OBS_ATTRIBUTION.json.

Honest limits (docs/FEEDBACK.md): probes measure the collective ALONE on
the live backend — in-step contention is not in the sample (the overlap
planner's pessimism band covers that seam); one-address-space memcpy
wires produce residuals whose bandwidth/latency split the fit cannot
attribute (the same negative control BENCH_QUANT documents); lonely
``+k`` shapes have no feature row, so their samples inform drift but not
the α-β solve; and per-step samples are step totals apportioned over the
plan, so the byte phase is only identifiable against a compute floor and
the fixed-phase launch/latency split keeps the base calibration's ratio.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable

import numpy as np

from ..obs.recorder import current_recorder, record_event
from ..obs.stepclock import StepSpanClock
from ..obs.timeline import (
    ResidualSample,
    read_dir,
    residual_group_key,
    residual_pairs,
    residual_table,
)
from ..schedule.stages import Topology
from ..utils.logging import get_logger
from .autotune import invalidate_plan_cache
from .calibrate import (
    MeasuredPoint,
    _params_from_dict,
    _params_to_dict,
    backend_fingerprint,
    default_params,
    feature_vector,
    fit_cost_params,
    save_calibration,
)
from .choose import choose_topology
from .cost_model import (
    LinkParams,
    TpuCostParams,
    allreduce_cost,
    lonely_allreduce_cost,
)

__all__ = [
    "FeedbackRefused",
    "ProbePoint",
    "ReplanDecision",
    "FeedbackConfig",
    "FeedbackController",
    "DriftDetector",
    "extract_residuals",
    "residual_report",
    "samples_to_points",
    "fit_from_samples",
    "fit_bwd_gflops",
    "fit_phase_scales",
    "fit_phase_scales_from_residuals",
    "fit_probe_free",
    "fit_residuals_auto",
    "scale_params",
    "attribute_groups",
    "predict_spec_us",
    "predict_spec_cost",
    "sample_family",
    "default_probe_points",
    "cache_invalidation_predicate",
]

log = get_logger("flextree.feedback")


class FeedbackRefused(RuntimeError):
    """The residual set cannot support a fit: starved (too few samples /
    too few distinct points) or degenerate (ill-conditioned feature
    matrix, or the NNLS active set emptied).  Raised LOUDLY — the
    alternative, fitting anyway, hands the planner confident garbage,
    which is strictly worse than the stale constants it already has."""


# --------------------------------------------------------------- extraction


def extract_residuals(obs_dir: str) -> tuple[list[ResidualSample], dict]:
    """Predicted-vs-measured residual samples from a run's flight record
    (every ``flight_*.jsonl`` under ``obs_dir``) — the shared pairing of
    ``obs.timeline.residual_pairs``, so this extractor and the
    ``python -m flextree_tpu.obs residuals`` CLI cannot diverge."""
    events, _dumps = read_dir(obs_dir)
    return residual_pairs(events)


def residual_report(obs_dir: str) -> str:
    """The CLI table for a recorded run's directory."""
    samples, skipped = extract_residuals(obs_dir)
    return residual_table(samples, skipped)


def _parse_spec(spec: str) -> tuple[tuple[int, ...] | None, int]:
    """FT_TOPO-style spec -> (widths, lonely); ``(None, 0)`` for specs
    with no cost-model row (the native-psum sentinel)."""
    spec = str(spec).strip()
    if spec in ("psum", ""):
        return None, 0
    if spec in ("ring", "1"):
        return (1,), 0
    lonely = 0
    if "+" in spec:
        spec, tail = spec.rsplit("+", 1)
        lonely = int(tail)
    widths = tuple(int(w) for w in spec.replace("*", ",").split(","))
    if any(w == 1 for w in widths):
        return (1,), lonely
    return widths, lonely


def sample_family(sample: ResidualSample) -> str:
    """Topology family of a residual sample: "ring" / "lonely" / "tree"
    (or "psum" for the native sentinel) — the drift detector's grouping
    granularity."""
    widths, lonely = _parse_spec(sample.topo)
    if widths is None:
        return "psum"
    if lonely:
        return "lonely"
    return "ring" if widths == (1,) else "tree"


def predict_spec_cost(
    spec: str,
    n: int,
    nbytes: int,
    params: TpuCostParams | None = None,
    codec: str | None = None,
):
    """Predicted :class:`CostBreakdown` for an FT_TOPO spec — priced by
    the SAME ``allreduce_cost`` the fit's ``feature_vector`` evaluates,
    so probe residuals and the solve agree on the model.  None for specs
    the model has no row for (psum)."""
    if params is None:
        params = default_params()
    widths, lonely = _parse_spec(spec)
    if widths is None:
        return None
    codec_obj = None
    if codec and codec != "f32":
        from ..ops.quantize import get_codec

        codec_obj = get_codec(codec)
    if lonely:
        tree = Topology(n - lonely, widths)
        return lonely_allreduce_cost(
            tree, lonely, nbytes, params, codec=codec_obj
        )
    topo = Topology.ring(n) if widths == (1,) else Topology(n, widths)
    return allreduce_cost(topo, nbytes, params, codec=codec_obj)


def predict_spec_us(
    spec: str,
    n: int,
    nbytes: int,
    params: TpuCostParams | None = None,
    codec: str | None = None,
) -> float | None:
    """Total predicted allreduce time for an FT_TOPO spec (see
    :func:`predict_spec_cost`)."""
    cost = predict_spec_cost(spec, n, nbytes, params, codec)
    return None if cost is None else cost.total_us


# ------------------------------------------------------------------ fitting


def samples_to_points(samples) -> list[MeasuredPoint]:
    """Residual samples -> the ``MeasuredPoint`` form ``fit_cost_params``
    consumes.  Only samples with a feature row qualify: identity codec
    (compressed wires fold codec time into the measurement — they feed
    the codec rescale instead), unsharded, known world, and tree/ring
    shapes (lonely ``+k`` folds have no ``feature_vector`` row).
    Per-step span-clock samples (``source == "step"``) are excluded too:
    their measured times are a step total *apportioned* over the plan by
    predicted share, so within one step every ratio is identical by
    construction — feeding them to the point-wise NNLS would manufacture
    confident agreement with whatever the model already predicted.  They
    carry per-phase information instead (:func:`fit_phase_scales`)."""
    points = []
    for s in samples:
        if s.codec != "f32" or s.sharded or s.world is None:
            continue
        if s.source == "step":
            continue
        widths, lonely = _parse_spec(s.topo)
        if widths is None or lonely:
            continue
        points.append(MeasuredPoint(widths, s.world, s.nbytes, s.measured_us))
    return points


def fit_bwd_gflops(compute_samples) -> float | None:
    """Median achieved backward GFLOP/s from ``(flops, seconds)`` compute
    probes (>= 2 positive samples required), or None — the overlap
    boundary equalizer's absolute compute scale.  Compute probes need a
    sync-free step to time (``bench.harness.make_nosync_train_step``);
    runs without one keep the backend-resolved default, documented in
    docs/FEEDBACK.md."""
    rates = [
        flops / seconds / 1e9
        for flops, seconds in compute_samples
        if flops > 0 and seconds > 0
    ]
    if len(rates) < 2:
        return None
    return float(np.median(rates))


def fit_from_samples(
    samples,
    *,
    base_params: TpuCostParams | None = None,
    min_samples: int = 8,
    min_distinct: int = 4,
    max_condition: float = 1e8,
    compute_samples=(),
) -> tuple[TpuCostParams, dict]:
    """Solve updated cost constants from flight-record residual samples.

    α-β half: :func:`samples_to_points` + ``calibrate.fit_cost_params``
    (relative NNLS over the model-derived feature matrix).  Guards, all
    raising :class:`FeedbackRefused`:

    - **starved**: fewer than ``min_samples`` eligible samples, or fewer
      than ``min_distinct`` distinct (widths, world, nbytes) points —
      four constants fitted from three points is interpolation theater;
    - **degenerate**: the relative-weighted feature matrix's condition
      number exceeds ``max_condition`` (one shape measured many times
      spans a line, not the 4-dim feature space), or ``fit_cost_params``
      itself empties its NNLS active set (measurements contradict the
      model everywhere).

    Codec half (:func:`_refit_codec`): the α-β solve cannot split the
    byte slope between wire and reduce bandwidth — those features are
    structurally collinear on an f32 wire — but compressed samples
    *can*: an int8 hop moves ¼ the wire bytes while reducing the same
    f32 bytes, so the compressed residual set jointly identifies the
    wire/reduce split AND ``codec_bw_GBps`` (a 2-unknown constrained
    least squares holding the f32-identified combined slope fixed).
    Skipped with a ``meta`` note when the set is too small, degenerate,
    or the codec excess is non-positive — the memcpy-wire case where
    codec time is unattributable.  ``compute_samples`` optionally update
    ``bwd_GFLOPs`` (:func:`fit_bwd_gflops`).

    Returns ``(params, meta)`` where ``meta`` records counts/condition —
    the provenance trail ``save_calibration(source="feedback")`` embeds.
    """
    if base_params is None:
        base_params = default_params()
    # materialize once: a generator would be exhausted by fit_bwd_gflops
    # before the meta sample count below re-iterates it
    compute_samples = tuple(compute_samples)
    points = samples_to_points(samples)
    if len(points) < min_samples:
        raise FeedbackRefused(
            f"starved residual set: {len(points)} eligible sample(s) < "
            f"min_samples={min_samples} (identity-codec, unsharded, "
            "tree/ring samples with a known world qualify)"
        )
    distinct = {(p.widths, p.num_nodes, p.nbytes) for p in points}
    if len(distinct) < min_distinct:
        raise FeedbackRefused(
            f"starved residual set: {len(distinct)} distinct "
            f"(shape, world, nbytes) point(s) < min_distinct={min_distinct} "
            "— re-measuring one point cannot pin 4 constants"
        )
    X = np.stack(
        [feature_vector(p.widths, p.num_nodes, p.nbytes) for p in points]
    )
    y = np.array([p.measured_us for p in points])
    Xw = X / np.maximum(y, 1e-9)[:, None]  # fit_cost_params' relative rows
    # Conditioning guard, on the COLUMN-NORMALIZED matrix (the raw
    # features carry wildly different units — launch counts ~1 vs byte
    # terms ~1e6 — which inflates a naive condition number without making
    # the solve degenerate).  Note the model's bandwidth and reduce
    # features are STRUCTURALLY collinear on a uniform fabric (the
    # telescoping identity makes both byte sums shape-independent,
    # cost_model.py docstring), so full rank 4 is unattainable by design;
    # the fit only needs the 3 identifiable directions (launch, latency,
    # combined byte slope).  Refuse when the measured geometry spans
    # fewer — one shape re-measured many times spans a line — or when the
    # spanned directions are themselves near-dependent.
    col_scale = np.abs(Xw).max(axis=0)
    live = col_scale > 1e-12
    sv = np.linalg.svd(Xw[:, live] / col_scale[live], compute_uv=False)
    need = min(3, int(live.sum()))
    rank = int((sv > sv[0] * 1e-10).sum()) if sv.size else 0
    cond = float(sv[0] / sv[need - 1]) if rank >= need else float("inf")
    if rank < need or cond > max_condition:
        raise FeedbackRefused(
            f"degenerate residual set: measured points span {rank} of the "
            f"{need} identifiable feature directions (condition "
            f"{cond:.3g} vs max {max_condition:.3g}) — add shapes/sizes "
            "instead of re-measuring the same point"
        )
    try:
        fitted = fit_cost_params(points)
    except RuntimeError as e:  # the NNLS empty-active-set refusal
        raise FeedbackRefused(f"degenerate residual set: {e}") from e

    meta: dict = {
        "points": len(points),
        "distinct_points": len(distinct),
        "condition": round(cond, 3),
    }

    # preserve constants the α-β solve does not see
    fitted = dataclasses.replace(
        fitted,
        codec_bw_GBps=base_params.codec_bw_GBps,
        bwd_GFLOPs=base_params.bwd_GFLOPs,
        rs_bw_scale=base_params.rs_bw_scale,
        ag_bw_scale=base_params.ag_bw_scale,
    )
    # The f32 data pins only the COMBINED byte slope (wire and reduce
    # features are structurally collinear — see the conditioning note
    # above), so the NNLS split between them is arbitrary.  Normalize to
    # the base calibration's ratio: every f32 prediction is unchanged,
    # and compressed-wire predictions stay anchored to the last measured
    # split instead of jumping with solver round-off.  Compressed samples
    # below re-solve the split from evidence when they can.
    fitted = _resplit_bytes(fitted, base_params, points[0])

    # ---- codec + wire-split refit from compressed samples
    fitted, codec_meta = _refit_codec(samples, fitted, points)
    meta.update(codec_meta)

    # ---- component-wise attribution (meta only): which phase drifted.
    # The α-β solve consumed totals; the breakdowns the samples carry
    # additionally say WHERE the miss lives — reported alongside the fit
    # so a drift log names the phase, never fatal when unattributable.
    phase_rows = [
        r for r in (_sample_phase_row(s) for s in samples) if r is not None
    ]
    if len(phase_rows) >= 2:
        try:
            scales, _pm = fit_phase_scales(phase_rows, floor_us=0.0)
            meta["phase_scales"] = {
                k: (round(v, 4) if v is not None else None)
                for k, v in scales.items()
            }
            meta["drifted_phase"] = drifted_phase(scales)
        except FeedbackRefused as e:
            meta["phase_attribution"] = f"skipped: {e}"[:160]

    # ---- backward-compute scale from compute probes
    bwd = fit_bwd_gflops(compute_samples)
    if bwd is not None:
        fitted = dataclasses.replace(fitted, bwd_GFLOPs=bwd)
        meta["bwd_GFLOPs"] = round(bwd, 3)
        meta["compute_samples"] = len(compute_samples)
    return fitted, meta


def _resplit_bytes(
    fitted: TpuCostParams, base: TpuCostParams, p0: MeasuredPoint
) -> TpuCostParams:
    """Redistribute the f32-identified combined byte slope ``q = c·inv_bw
    + inv_rbw`` between wire and reduce bandwidth in ``base``'s ratio —
    an f32-prediction-preserving change of the one direction the f32 fit
    cannot see (``c`` is the fixed wire/reduce feature ratio, evaluated
    from the model at ``p0``)."""
    tiny = 1e-12
    fv = feature_vector(p0.widths, p0.num_nodes, p0.nbytes)
    if fv[3] <= tiny:
        return fitted
    c = float(fv[2] / fv[3])
    inv_bw = 1.0 / max(fitted.ici.bandwidth_GBps * 1e3, tiny)
    inv_rbw = 1.0 / max(fitted.reduce_bw_GBps * 1e3, tiny)
    q = c * inv_bw + inv_rbw
    base_inv_bw = 1.0 / max(base.ici.bandwidth_GBps * 1e3, tiny)
    base_inv_rbw = 1.0 / max(base.reduce_bw_GBps * 1e3, tiny)
    denom = c * base_inv_bw + base_inv_rbw
    if q <= tiny or denom <= tiny:
        return fitted
    scale = q / denom
    bw = 1.0 / max(base_inv_bw * scale, tiny) / 1e3
    return dataclasses.replace(
        fitted,
        ici=LinkParams(bandwidth_GBps=bw, latency_us=fitted.ici.latency_us),
        dcn=LinkParams(bandwidth_GBps=bw, latency_us=fitted.dcn.latency_us),
        reduce_bw_GBps=1.0 / max(base_inv_rbw * scale, tiny) / 1e3,
    )


def _codec_feature_basis() -> list[TpuCostParams]:
    """``calibrate._params_basis`` extended with a codec one-hot: 5
    settings s.t. ``allreduce_cost(..., p_i, codec=c).total_us`` is the
    i-th feature of the codec-aware model (launch, latency, inv wire bw,
    inv reduce bw, inv codec bw).  The α-β entries pin ``codec_bw`` to
    "infinite" so their features stay pure."""
    from .calibrate import _params_basis

    big = 1e30
    base = [
        dataclasses.replace(p, codec_bw_GBps=big) for p in _params_basis()
    ]
    codec_one = dataclasses.replace(
        base[0], launch_us=0.0, codec_bw_GBps=1e-3
    )
    return base + [codec_one]


def _refit_codec(samples, fitted, points) -> tuple[TpuCostParams, dict]:
    """Joint wire-split + codec-throughput solve from compressed samples.

    The f32 α-β fit identifies launch, latency, and the COMBINED byte
    slope ``q = c·inv_bw + inv_rbw`` (wire and reduce features are
    structurally collinear on an f32 wire, ``c`` their fixed ratio) — but
    not the split, and the split is exactly what prices a compressed
    wire: int8 moves ``ratio``× the wire bytes while reducing and
    en/decoding full f32 bytes.  Each compressed sample therefore gives

        meas − launch·A_launch − lat·A_lat − q·A_rbw
            = inv_bw·(A_bw − c·A_rbw) + inv_codec·A_codec

    with the A's evaluated by the SAME cost model at one-hot basis params
    (:func:`_codec_feature_basis`).  Two unknowns, relative-weighted
    least squares, ``inv_bw`` clamped to ``[0, q/c]`` so the implied
    reduce bandwidth stays non-negative.  Refuses (returns the params
    untouched plus a ``codec_refit: skipped`` note) on < 3 usable
    samples, a rank-deficient system (one shape at one size cannot
    separate wire savings from codec work), or a non-positive codec
    inverse — measured compressed time at/below the α-β floor, the
    memcpy-wire case where codec time is unattributable."""
    lossy = [s for s in samples if s.codec != "f32" and not s.sharded]
    if not lossy:
        return fitted, {}
    from ..ops.quantize import get_codec

    basis = _codec_feature_basis()
    rows, meas = [], []
    for s in lossy:
        if s.world is None:
            continue
        widths, lonely = _parse_spec(s.topo)
        if widths is None or lonely:
            continue
        try:
            codec_obj = get_codec(s.codec)
        except (KeyError, ValueError):
            continue
        topo = (
            Topology.ring(s.world)
            if widths == (1,)
            else Topology(s.world, widths)
        )
        rows.append(
            np.array(
                [
                    allreduce_cost(topo, s.nbytes, p, codec=codec_obj).total_us
                    for p in basis
                ]
            )
        )
        meas.append(s.measured_us)

    def skipped(reason: str) -> tuple[TpuCostParams, dict]:
        return fitted, {
            "codec_refit": (
                f"skipped: {reason} — codec time unattributable on this wire"
            )
        }

    if len(rows) < 3:
        return skipped(
            f"{len(rows)}/{len(lossy)} usable compressed sample(s) (< 3)"
        )
    A = np.stack(rows)
    y = np.array(meas)
    # the f32-identified constants and combined byte slope
    tiny = 1e-12
    launch = fitted.launch_us
    lat = fitted.ici.latency_us
    inv_bw0 = 1.0 / max(fitted.ici.bandwidth_GBps * 1e3, tiny)
    inv_rbw0 = 1.0 / max(fitted.reduce_bw_GBps * 1e3, tiny)
    p0 = points[0]
    fv = feature_vector(p0.widths, p0.num_nodes, p0.nbytes)
    if fv[3] <= tiny:
        return skipped("reduce feature empty")
    c = float(fv[2] / fv[3])
    q = c * inv_bw0 + inv_rbw0
    rhs = y - launch * A[:, 0] - lat * A[:, 1] - q * A[:, 3]
    M = np.stack([A[:, 2] - c * A[:, 3], A[:, 4]], axis=1)
    w = 1.0 / np.maximum(y, 1e-9)
    Mw, rhsw = M * w[:, None], rhs * w
    sv = np.linalg.svd(Mw, compute_uv=False)
    if sv.size < 2 or sv[1] < sv[0] * 1e-8:
        return skipped(
            "degenerate compressed set (wire-saving and codec columns "
            "collinear; add shapes/sizes)"
        )
    (inv_bw, inv_cod), *_ = np.linalg.lstsq(Mw, rhsw, rcond=None)
    hi = q / c if c > tiny else float("inf")
    if not (0.0 <= inv_bw <= hi):
        # clamp the wire split and re-solve the codec inverse alone
        inv_bw = float(np.clip(inv_bw, 0.0, hi))
        col = Mw[:, 1]
        denom = float(col @ col)
        inv_cod = (
            float(col @ (rhsw - inv_bw * Mw[:, 0])) / denom
            if denom > tiny
            else 0.0
        )
    if not np.isfinite(inv_cod) or inv_cod <= tiny:
        return skipped("non-positive codec excess")
    inv_rbw = max(q - c * inv_bw, tiny)
    bw = 1.0 / max(inv_bw, tiny) / 1e3
    fitted = dataclasses.replace(
        fitted,
        ici=LinkParams(bandwidth_GBps=bw, latency_us=fitted.ici.latency_us),
        dcn=LinkParams(bandwidth_GBps=bw, latency_us=fitted.dcn.latency_us),
        reduce_bw_GBps=1.0 / inv_rbw / 1e3,
        codec_bw_GBps=1.0 / inv_cod / 1e3,
    )
    return fitted, {
        "codec_samples": len(rows),
        "codec_bw_GBps": round(fitted.codec_bw_GBps, 3),
        "wire_bw_GBps": round(fitted.ici.bandwidth_GBps, 3),
    }


# ---------------------------------------------------------- per-phase fit
#
# The α-β solve above needs point-wise measured collectives at varied
# (shape, world, nbytes) geometry — the probe path's currency.  Per-step
# span-clock samples (obs/stepclock.py) and thin fleet records carry a
# different kind of information: each sample's predicted CostBreakdown
# splits into three independently-scalable phases (fixed = launch +
# hop-latency + control; bytes = wire bandwidth + reduce, structurally
# collinear on an f32 wire so they scale together and keep the base
# calibration's split; codec = en/decode work), and the measurement
# constrains a LINEAR COMBINATION of those phases.  Solving for per-phase
# scale factors s_k in  measured ≈ floor + Σ_k s_k · predicted_k  is the
# component-wise residual consumption the ISSUE names: it both *attributes*
# drift to a phase and *corrects* the live constants
# (:func:`scale_params`) without a single dedicated probe.


_PHASE_ORDER = ("fixed", "bytes", "codec")


def _sample_phase_row(s: ResidualSample):
    """(fixed_us, bytes_us, codec_us, measured_us) of one sample, or None
    when it carries no breakdown."""
    ph = s.phases
    if ph is None:
        return None
    return (ph["fixed"], ph["bytes"], ph["codec"], s.measured_us)


def fit_phase_scales(
    rows,
    *,
    floor_us: float = 0.0,
    max_condition: float = 1e6,
) -> tuple[dict, dict]:
    """Solve per-phase scale factors from ``(fixed_us, bytes_us,
    codec_us, measured_us[, weight])`` rows.

    Relative-weighted least squares over the phase columns that actually
    vary; ``floor_us`` is subtracted from every measurement first (the
    per-step fit passes the compute floor; bucket-level fits pass 0).
    Guards, raising :class:`FeedbackRefused`: fewer rows than unknowns, a
    column-normalized condition number past ``max_condition`` (the rows
    don't separate the phases — e.g. one plan re-measured many times), or
    a non-positive / non-finite fitted scale.  A codec column collinear
    with the bytes column (codec work is byte-proportional, so bucket-size
    variation alone cannot split them) folds into it: the codec scale
    then FOLLOWS the bytes scale, noted in ``meta``.

    Returns ``(scales, meta)``: ``scales`` maps phase -> factor (``None``
    for a phase with no predicted mass in any row), ``meta`` carries the
    conditioning trail.
    """
    mat, ys, ws = [], [], []
    for row in rows:
        f, b, c, meas = row[:4]
        w = float(row[4]) if len(row) > 4 else 1.0
        if meas <= 0 or w <= 0:
            continue
        mat.append([float(f), float(b), float(c)])
        ys.append(float(meas) - float(floor_us))
        ws.append(w)
    if not mat:
        raise FeedbackRefused("no usable phase rows (no breakdowns?)")
    A = np.asarray(mat)
    y = np.asarray(ys)
    # relative weighting (same convention as fit_cost_params), times the
    # caller's row weight (step counts behind a plan-aggregate row)
    w = np.sqrt(np.asarray(ws)) / np.maximum(y + floor_us, 1e-9)
    # a phase whose predicted contribution is negligible RELATIVE to the
    # measurements cannot be fitted from them: unresolved, base kept
    tiny = 1e-9 * float(np.abs(y).max() + floor_us)
    unresolved: list[str] = []
    live = []
    for i in range(3):
        if np.abs(A[:, i]).max() > max(tiny, 1e-12):
            live.append(i)
        elif np.abs(A[:, i]).max() > 1e-12:
            unresolved.append(_PHASE_ORDER[i])
    if not live:
        raise FeedbackRefused("every phase column is empty")
    codec_follows_bytes = False
    if 1 in live and 2 in live:
        # codec ∝ bytes across bucket-size variation: drop the codec
        # column when it adds no independent direction
        sub = A[:, [1, 2]] / np.abs(A[:, [1, 2]]).max(axis=0)
        sv = np.linalg.svd(sub * w[:, None], compute_uv=False)
        if sv.size < 2 or sv[-1] < sv[0] * 1e-6:
            live.remove(2)
            codec_follows_bytes = True
    X = A[:, live] * w[:, None]
    if X.shape[0] < len(live):
        raise FeedbackRefused(
            f"{X.shape[0]} phase row(s) cannot pin {len(live)} phase "
            "scale(s) — sample more plans"
        )
    col = np.abs(X).max(axis=0)
    if (col <= 1e-12).any():
        raise FeedbackRefused("a live phase column vanished under weighting")
    sv = np.linalg.svd(X / col, compute_uv=False)
    cond = float(sv[0] / sv[-1]) if sv[-1] > 0 else float("inf")
    if cond > max_condition:
        raise FeedbackRefused(
            f"phase columns are near-collinear (condition {cond:.3g} > "
            f"{max_condition:.3g}) — the sampled plans don't vary the "
            "phase mix; rotate bucket sizes or pool more runs"
        )
    # active-set solve: a phase whose fitted scale comes out non-positive
    # is UNIDENTIFIABLE from these rows (its predicted contribution is
    # below the noise) — drop its column and keep the base constants for
    # that phase rather than inventing a sign-flipped correction.  Refuse
    # only when nothing identifiable remains.
    while True:
        sol, *_ = np.linalg.lstsq(X, y * w, rcond=None)
        bad = [
            (s, i) for s, i in zip(sol, live)
            if not np.isfinite(s) or s <= 0
        ]
        if not bad:
            break
        worst = min(bad)[1]
        unresolved.append(_PHASE_ORDER[worst])
        live.remove(worst)
        if not live:
            raise FeedbackRefused(
                "no phase scale is identifiable from these rows — every "
                "fitted scale came out non-positive (noise dominated the "
                "window, or the floor is too high)"
            )
        X = A[:, live] * w[:, None]
    scales: dict = {p: None for p in _PHASE_ORDER}
    for i, s in zip(live, sol):
        scales[_PHASE_ORDER[i]] = float(s)
    if codec_follows_bytes and scales["bytes"] is not None:
        scales["codec"] = scales["bytes"]
    meta = {
        "phase_condition": round(cond, 3),
        "phase_rows": int(X.shape[0]),
    }
    if codec_follows_bytes:
        meta["codec_follows_bytes"] = True
    if unresolved:
        meta["unresolved_phases"] = unresolved
    return scales, meta


def drifted_phase(scales: dict) -> str | None:
    """The phase whose fitted scale deviates most from 1 (log scale),
    rendered ``"bytes×2.91"`` — the headline of a per-phase drift
    report.  None when nothing was fitted."""
    best, best_dev = None, 0.0
    for p in _PHASE_ORDER:
        s = scales.get(p)
        if s is None or s <= 0:
            continue
        dev = abs(float(np.log(s)))
        if dev > best_dev:
            best, best_dev = p, dev
    if best is None:
        return None
    return f"{best}×{scales[best]:.2f}"


def scale_params(base: TpuCostParams, scales: dict) -> TpuCostParams:
    """Apply fitted per-phase scales to the live constants: fixed-phase
    constants (launch, hop latency, control) multiply by ``fixed``;
    byte-phase bandwidths (wire + reduce) divide by ``bytes`` — scaling
    both preserves the base calibration's wire/reduce split, the one
    direction phase data cannot see (same argument as ``_resplit_bytes``);
    codec throughput divides by ``codec``.  ``None`` scales leave the
    phase untouched."""
    s_fixed = scales.get("fixed")
    s_bytes = scales.get("bytes")
    s_codec = scales.get("codec")
    out = base
    if s_fixed is not None:
        out = dataclasses.replace(
            out,
            launch_us=out.launch_us * s_fixed,
            control_us_per_width=out.control_us_per_width * s_fixed,
            ici=LinkParams(
                bandwidth_GBps=out.ici.bandwidth_GBps,
                latency_us=out.ici.latency_us * s_fixed,
            ),
            dcn=LinkParams(
                bandwidth_GBps=out.dcn.bandwidth_GBps,
                latency_us=out.dcn.latency_us * s_fixed,
            ),
        )
    if s_bytes is not None:
        out = dataclasses.replace(
            out,
            ici=LinkParams(
                bandwidth_GBps=out.ici.bandwidth_GBps / s_bytes,
                latency_us=out.ici.latency_us,
            ),
            dcn=LinkParams(
                bandwidth_GBps=out.dcn.bandwidth_GBps / s_bytes,
                latency_us=out.dcn.latency_us,
            ),
            reduce_bw_GBps=out.reduce_bw_GBps / s_bytes,
        )
    if s_codec is not None:
        out = dataclasses.replace(
            out, codec_bw_GBps=out.codec_bw_GBps / s_codec
        )
    return out


def fit_phase_scales_from_residuals(
    samples,
    *,
    base_params: TpuCostParams | None = None,
    min_samples: int = 6,
    max_condition: float = 1e6,
) -> tuple[TpuCostParams, dict]:
    """Per-phase scale fit over bucket-level residual samples (probe or
    per-step) that carry predicted breakdowns — the fallback when the
    sample geometry cannot support the point-wise α-β solve (fleet
    pooling of thin runs, single-plan records).  Returns ``(params,
    meta)`` like :func:`fit_from_samples`."""
    if base_params is None:
        base_params = default_params()
    rows = []
    for s in samples:
        row = _sample_phase_row(s)
        if row is not None:
            rows.append(row)
    if len(rows) < min_samples:
        raise FeedbackRefused(
            f"starved phase-residual set: {len(rows)} sample(s) with "
            f"breakdowns < min_samples={min_samples}"
        )
    scales, meta = fit_phase_scales(
        rows, floor_us=0.0, max_condition=max_condition
    )
    meta = {
        "mode": "phase-scales",
        "points": len(rows),
        "phase_scales": {
            k: (round(v, 4) if v is not None else None)
            for k, v in scales.items()
        },
        "drifted_phase": drifted_phase(scales),
        "condition": meta["phase_condition"],
        **meta,
    }
    return scale_params(base_params, scales), meta


def fit_probe_free(
    step_samples,
    *,
    base_params: TpuCostParams | None = None,
    compute_floor_us: float,
    min_plans: int = 2,
    min_steps_per_plan: int = 2,
    max_condition: float = 1e6,
) -> tuple[TpuCostParams, dict]:
    """The probe-free refit: per-phase scales from host-timed STEP
    samples spanning several bucket plans (``obs.stepclock.StepSample``).

    Each plan contributes one aggregate row — the MINIMUM step time over
    its (non-compiling) steps against the plan's predicted per-phase
    totals: host contention only ever adds time, so the min over samples
    interleaved across the run's windows is the plan's quiet-host time
    (the bench harness's min-of-reps argument), and contention-spiked
    individual steps cannot steer the solve.

    Identifiability, honestly: total gradient bytes are plan-invariant,
    so across a bucket-size rotation the byte-phase column is CONSTANT
    (the model's telescoping identity — bandwidth does not distinguish
    shapes) while the fixed-phase column varies with the bucket count.
    The solve therefore runs in two regimes:

    - **intercept mode** (the common case — byte column spread < 5%):
      fit ``step = I + s_fixed·F_plan`` directly.  The fixed scale comes
      from paired in-regime step differences (robust even on a noisy
      host); the intercept lumps ``floor + s_bytes·B``, and
      ``compute_floor_us`` (a sync-free twin timing — zero collectives)
      is used ONLY to split that lump: ``bytes ≈ clamp(I − floor, 1µs,
      I)``.  A noisy floor thus bounds the byte-scale error without
      touching the fixed-phase fit, and the IMPLIED floor ``I − bytes``
      is returned in ``meta["floor_implied_us"]`` — the controller
      adopts it for post-refit drift judgement (it is measured in-regime,
      unlike the twin).
    - **direct mode** (byte column varies — e.g. pooled worlds): the
      plain per-phase solve with ``compute_floor_us`` subtracted.

    Plans with fewer than ``min_steps_per_plan`` usable steps are
    dropped; :class:`FeedbackRefused` when fewer than ``min_plans``
    plans remain, the fixed column doesn't vary, or a fitted scale is
    not positive.
    """
    if base_params is None:
        base_params = default_params()
    if compute_floor_us is None:
        raise FeedbackRefused(
            "probe-free refit needs compute_floor_us (time a sync-free "
            "twin — zero collectives — or calibrate the compute estimate)"
        )
    by_plan: dict[str, list] = {}
    for s in step_samples:
        by_plan.setdefault(s.plan_sig, []).append(s)
    rows = []
    plans_meta = {}
    for sig, grp in sorted(by_plan.items()):
        if len(grp) < min_steps_per_plan:
            continue
        # min, not median: host contention is one-sided (it only ever
        # ADDS time), so the minimum over samples interleaved across the
        # run's windows is the plan's quiet-host time — the same
        # min-of-reps argument the bench harness runs on
        quiet_us = float(np.min([s.step_us for s in grp]))
        g0 = grp[0]
        rows.append(
            (g0.fixed_us, g0.bytes_us, g0.codec_us, quiet_us, float(len(grp)))
        )
        plans_meta[sig] = {
            "steps": len(grp),
            "step_us": round(quiet_us, 1),
            "fixed_us": round(g0.fixed_us, 1),
            "bytes_us": round(g0.bytes_us + g0.codec_us, 1),
        }
    if len(rows) < min_plans:
        raise FeedbackRefused(
            f"probe-free fit needs >= {min_plans} plans with >= "
            f"{min_steps_per_plan} steps each; have {len(rows)} "
            "(rotate bucket sizes to vary the phase mix)"
        )
    F = np.array([r[0] for r in rows])
    BC = np.array([r[1] + r[2] for r in rows])  # bytes + codec lump
    Y = np.array([r[3] for r in rows])
    W = np.sqrt(np.array([r[4] for r in rows])) / np.maximum(Y, 1e-9)
    has_codec = any(r[2] > 1e-12 for r in rows)
    bc_spread = (
        (BC.max() - BC.min()) / BC.max() if BC.max() > 1e-12 else 0.0
    )
    floor = float(compute_floor_us)
    meta: dict = {
        "mode": "probe-free",
        "plans": len(rows),
        "steps": int(sum(len(g) for g in by_plan.values())),
        "floor_us": round(floor, 1),
        "plan_rows": plans_meta,
    }
    if bc_spread >= 0.05:
        # byte column varies: the generic per-phase solve identifies it
        try:
            scales, smeta = fit_phase_scales(
                rows, floor_us=floor, max_condition=max_condition
            )
        except FeedbackRefused as e:
            raise FeedbackRefused(f"{e} [plans={plans_meta}]") from e
        meta.update(submode="direct", condition=smeta["phase_condition"],
                    **smeta)
    else:
        # intercept mode: I + s_fixed·F
        if F.max() <= 1e-12 or (F.max() - F.min()) / F.max() < 0.05:
            raise FeedbackRefused(
                "fixed-phase column does not vary across the sampled "
                f"plans (F={np.round(F, 2).tolist()}) — rotation did not "
                "change the bucket count"
            )
        X = np.stack([np.ones_like(F), F], axis=1) * W[:, None]
        col = np.abs(X).max(axis=0)
        sv = np.linalg.svd(X / col, compute_uv=False)
        cond = float(sv[0] / sv[-1]) if sv[-1] > 0 else float("inf")
        if cond > max_condition:
            raise FeedbackRefused(
                f"intercept solve ill-conditioned ({cond:.3g}) — plans "
                "too similar"
            )
        (intercept, s_fixed), *_ = np.linalg.lstsq(X, Y * W, rcond=None)
        if not np.isfinite(s_fixed) or s_fixed <= 0:
            raise FeedbackRefused(
                f"fitted fixed scale {s_fixed:.4g} not positive — step "
                "times do not grow with the bucket count (noise dominated "
                f"the window; plans={plans_meta})"
            )
        intercept = float(max(intercept, 1.0))
        # split the intercept: bytes = I − floor, clamped into [1µs,
        # max(I−1µs, 1µs)] so a noisy twin floor can neither produce
        # negative bytes (s_bytes must stay > 0 — scale_params divides
        # by it) nor a negative implied floor even when the intercept
        # itself collapses to the 1µs clamp
        hi = max(intercept - 1.0, 1.0)
        bytes_lump = float(np.clip(intercept - floor, 1.0, hi))
        s_bytes = bytes_lump / max(float(BC.mean()), 1e-9)
        scales = {
            "fixed": float(s_fixed),
            "bytes": float(s_bytes),
            "codec": float(s_bytes) if has_codec else None,
        }
        meta.update(
            submode="intercept",
            condition=round(cond, 3),
            intercept_us=round(intercept, 1),
            bytes_lump_us=round(bytes_lump, 1),
            floor_implied_us=round(intercept - bytes_lump, 1),
        )
        if has_codec:
            meta["codec_follows_bytes"] = True
    meta["phase_scales"] = {
        k: (round(v, 6) if v is not None else None) for k, v in scales.items()
    }
    meta["drifted_phase"] = drifted_phase(scales)
    return scale_params(base_params, scales), meta


def fit_residuals_auto(
    samples,
    *,
    base_params: TpuCostParams | None = None,
    min_samples: int = 8,
    **kw,
) -> tuple[TpuCostParams, dict]:
    """Fit whatever the residual set supports: the point-wise α-β solve
    when the geometry allows it, else the per-phase scale fit.  The fleet
    pooling pass and the residuals CLI use this so a thin single-plan
    record still yields an honest (phase-level) answer instead of a
    refusal, with ``meta["mode"]`` saying which solve ran."""
    try:
        params, meta = fit_from_samples(
            samples, base_params=base_params, min_samples=min_samples, **kw
        )
        meta.setdefault("mode", "alpha-beta")
        return params, meta
    except FeedbackRefused as ab_err:
        try:
            params, meta = fit_phase_scales_from_residuals(
                samples, base_params=base_params
            )
        except FeedbackRefused as ph_err:
            raise FeedbackRefused(
                f"alpha-beta: {ab_err}; phase-scales: {ph_err}"
            ) from ph_err
        meta["alpha_beta_refused"] = str(ab_err)[:200]
        return params, meta


def attribute_groups(samples) -> dict[tuple, str]:
    """Per-(topo, codec, tier) drift attribution for the residuals CLI:
    run the per-phase solve on each group's samples; where the group's
    geometry cannot split phases (one size, apportioned per-step
    samples), fall back to the overall measured/predicted scale so the
    table still says HOW FAR the group drifted.  Keys match
    ``obs.timeline.residual_group_key``."""
    groups: dict[tuple, list] = {}
    for s in samples:
        groups.setdefault(residual_group_key(s), []).append(s)
    out: dict[tuple, str] = {}
    for key, grp in groups.items():
        rows = [r for r in (_sample_phase_row(s) for s in grp) if r]
        label = None
        if len(rows) >= 2:
            try:
                scales, _meta = fit_phase_scales(rows, floor_us=0.0)
                label = drifted_phase(scales)
            except FeedbackRefused:
                label = None
        if label is None:
            ratios = [
                s.measured_us / s.predicted_us
                for s in grp
                if s.predicted_us > 0
            ]
            if ratios:
                r = float(np.median(ratios))
                label = f"total×{r:.2f}" if abs(r - 1) > 0.1 else "-"
        out[key] = label or "-"
    return out


# ------------------------------------------------------------------- drift


class DriftDetector:
    """Per-key sliding windows of relative residuals |pred-meas|/meas.

    Key: (fingerprint, world, topo family, codec, sharded) — the grouping
    the ISSUE names.  A key *breaches* when its window holds at least
    ``min_window`` samples and their median exceeds ``band``.  The median
    (not the mean, not the last sample) so one contention-spiked probe on
    a timeshared host cannot trigger a replan storm; ``reset()`` after a
    refit so residuals are re-judged against the NEW constants."""

    def __init__(
        self, band: float = 0.5, window: int = 16, min_window: int = 4
    ):
        if band <= 0:
            raise ValueError(f"band must be > 0, got {band}")
        if min_window < 1 or window < min_window:
            raise ValueError(
                f"need window >= min_window >= 1, got {window}/{min_window}"
            )
        self.band = float(band)
        self.window = int(window)
        self.min_window = int(min_window)
        self._windows: dict[tuple, deque] = {}

    def key(self, sample: ResidualSample) -> tuple:
        return (
            sample.fingerprint,
            sample.world,
            sample_family(sample),
            sample.codec,
            sample.sharded,
        )

    def observe(self, sample: ResidualSample) -> None:
        self._windows.setdefault(
            self.key(sample), deque(maxlen=self.window)
        ).append(sample.rel_residual)

    def breaches(self) -> dict[tuple, float]:
        """{key: median rel residual} for every key past the band."""
        out = {}
        for key, win in self._windows.items():
            if len(win) < self.min_window:
                continue
            med = float(np.median(list(win)))
            if med > self.band:
                out[key] = med
        return out

    @property
    def drifted(self) -> bool:
        return bool(self.breaches())

    def reset(self) -> None:
        self._windows.clear()

    # -- cross-rank pooling (follower drift contribution) ---------------

    @staticmethod
    def key_str(key: tuple) -> str:
        """The JSON-safe serialization of a detector key — the same
        ``|``-joined form the controller's drift logs use."""
        return "|".join(str(p) for p in key)

    def summary(self) -> dict:
        """JSON-safe per-key window summary ``{key: {median, count}}`` —
        what a follower ships in its coordination acks so the
        coordinator's propose decision sees pooled cross-rank skew
        (docs/COORDINATION.md), not just its own wire view."""
        out: dict = {}
        for key, win in self._windows.items():
            if not win:
                continue
            out[self.key_str(key)] = {
                "median": round(float(np.median(list(win))), 4),
                "count": len(win),
            }
        return out

    def pooled_breaches(self, peer_summaries=None) -> dict[str, float]:
        """Band breaches over the POOLED view: this rank's windows merged
        with peers' summaries (``{rank: summary-dict}``).  Per key, ranks'
        medians combine count-weighted (the median of rank medians, each
        weighted by its window size) and a key breaches when the pooled
        statistic exceeds the band with at least ``min_window`` samples
        in total — so a skew only ONE follower's wire sees still breaches
        once its window is heavy enough, and a single noisy rank cannot
        out-vote a quiet majority."""
        per_key: dict[str, list] = {}
        for key, win in self._windows.items():
            if win:
                per_key.setdefault(self.key_str(key), []).append(
                    (float(np.median(list(win))), len(win))
                )
        for summ in (peer_summaries or {}).values():
            if not isinstance(summ, dict):
                continue
            for key, ent in summ.items():
                try:
                    med, count = float(ent["median"]), int(ent["count"])
                except (KeyError, TypeError, ValueError):
                    continue
                if count > 0:
                    per_key.setdefault(str(key), []).append((med, count))
        out: dict[str, float] = {}
        for key, entries in per_key.items():
            total = sum(c for _m, c in entries)
            if total < self.min_window:
                continue
            # count-weighted median of rank medians
            entries.sort(key=lambda e: e[0])
            half, acc, pooled = total / 2.0, 0, entries[-1][0]
            for med, count in entries:
                acc += count
                if acc >= half:
                    pooled = med
                    break
            if pooled > self.band:
                out[key] = pooled
        return out


def cache_invalidation_predicate(
    fingerprint: str | None, world: int | None = None
) -> Callable[[str, dict], bool]:
    """The standard drift predicate for ``autotune.invalidate_plan_cache``:
    match entries measured under ``fingerprint`` (the stored entry field —
    the key string embeds the fingerprint but ``|``-splitting it is
    ambiguous because fingerprints contain ``|``), optionally narrowed to
    one world size via the key's ``n{world}`` component.  The world check
    strips the fingerprint prefix first: the fingerprint itself carries an
    ``n{device_count}`` part, and a bare substring match would make
    ``world == device_count`` (the common case) match EVERY same-host key."""

    def predicate(key: str, entry: dict) -> bool:
        if entry.get("fingerprint") != fingerprint:
            return False
        if world is None:
            return True
        rest = key
        # a None fingerprint serializes as plan_cache_key's "~" sentinel
        prefix = "~" if fingerprint is None else fingerprint
        if key.startswith(prefix + "|"):
            rest = key[len(prefix) + 1 :]
        return rest.startswith(f"n{world}|")

    return predicate


# -------------------------------------------------------------- controller


@dataclass(frozen=True)
class ProbePoint:
    """One feedback probe: time the collective at (spec, nbytes, codec)."""

    spec: str
    nbytes: int
    codec: str = "f32"


def default_probe_points(n: int, nbytes: int) -> tuple[ProbePoint, ...]:
    """A small well-conditioned probe set for world ``n``: the flat tree,
    the first multi-stage factorization (when one exists), and the ring,
    each at two payload sizes — 4-6 distinct points covering the launch /
    latency / bandwidth axes, so two ticks clear the default
    ``min_samples`` without ever measuring one shape alone."""
    from .factorize import ordered_factorizations

    specs = [str(n)]
    for widths in ordered_factorizations(n):
        if len(widths) >= 2:
            specs.append(",".join(map(str, widths)))
            break
    if n >= 2:
        specs.append("ring")
    big = max(min(int(nbytes), 4 << 20), 1 << 15)
    small = max(big // 8, 1 << 14)
    sizes = [big] if small >= big else [big, small]
    return tuple(ProbePoint(s, nb) for s in specs for nb in sizes)


@dataclass
class FeedbackConfig:
    """Knobs for the in-run feedback loop (:class:`FeedbackController`).

    ``every_k``: tick cadence in steps.  ``band``/``window``/
    ``min_window``: the drift detector's parameters — breach = replan
    trigger.  ``min_samples``: the fitter's starvation floor.
    ``probes``: explicit :class:`ProbePoint` set (None derives
    :func:`default_probe_points`).  ``repeat``: timed reps per probe per
    tick (shuffled-interleaved, the harness protocol).
    ``calibration_path``: where refits are written back
    (``save_calibration(source="feedback")``); None skips persistence.
    ``plan_cache_path``: the autotune cache to drift-invalidate (None =
    the ambient ``FLEXTREE_PLAN_CACHE``/default).  ``on_replan(plan,
    params)``: rebuild hook — return None to keep the current step, or
    the same 3-/5-tuple ``Supervision.on_shrink`` returns; ``fit`` swaps
    the step through the identical path.  ``max_refits`` bounds how many
    times one run may refit (a loop that refits every tick is chasing
    noise, not drift).  ``max_samples`` bounds the controller's residual
    buffer to the most RECENT measurements — a refit must solve from the
    regime that breached the band, not a run-long mix the old regime
    dominates, and a healthy run must not grow the buffer forever.
    ``run_id`` stamps the calibration provenance.

    Probe-free mode (``probe_free=True``, docs/FEEDBACK.md): no dedicated
    probe collectives ever run.  Every materialized step is host-timed
    against its compile-time plan (``obs.stepclock``); drift detection
    rides the per-step spans, and a refit solves per-phase scale factors
    across PLANS — on a breach the controller rotates the step through
    ``rotation_factors``-scaled bucket sizes via ``on_rotate(bucket_bytes)
    -> rebuilt-tuple`` (bucket size is bitwise-invariant, so a rotation
    step is free production training, not a probe), then fits
    :func:`fit_probe_free` over the accumulated step samples.
    ``compute_floor_us`` is the step's non-comm floor (time a sync-free
    twin: zero collectives) — required for the refit, optional for
    detection (the provisional floor catches over-predicted comm).
    ``rotation_ticks`` = controller ticks spent per rotation plan (each
    tick is ``every_k`` steps of samples); ``min_steps_per_plan`` gates
    the fit; ``step_sample_every`` thins the per-step event stream.
    """

    every_k: int = 50
    band: float = 0.5
    window: int = 16
    min_window: int = 4
    min_samples: int = 8
    max_samples: int = 64
    probes: tuple = ()
    repeat: int = 3
    calibration_path: str | None = None
    backend: str | None = None
    plan_cache_path: str | None = None
    on_replan: Callable | None = None
    max_refits: int = 4
    run_id: str | None = None
    # -- probe-free mode -------------------------------------------------
    probe_free: bool = False
    compute_floor_us: float | None = None
    on_rotate: Callable | None = None
    rotation_factors: tuple = (0.25, 4.0)
    rotation_ticks: int = 1
    # full passes over the variant set (variants + the base size, so the
    # base is re-sampled in later windows too).  >1 interleaves each
    # plan's samples across the run's whole wall-clock window — the step
    # -scale version of the bench harness's shuffled-interleaved rounds:
    # a timeshared host's contention drifts over seconds, and a plan
    # sampled only in one window would absorb that drift as phase signal
    rotation_cycles: int = 2
    min_steps_per_plan: int = 2
    step_sample_every: int = 1


@dataclass
class ReplanDecision:
    """What one drift-triggered refit did — ``fit`` records it and applies
    ``rebuilt`` through the shrink-path swap.  ``rotation=True`` marks a
    probe-free plan-rotation swap (a bucket-size variant of the SAME
    plan, bitwise-invariant — applied like a replan but not counted as
    one; ``plan`` is then None)."""

    plan: Any  # planner.choose.Plan under the refitted constants
    params: TpuCostParams
    drift: dict  # breached detector keys -> median rel residual
    invalidated: int  # plan-cache entries dropped
    fit_meta: dict
    rebuilt: Any = None  # on_replan's 3-/5-tuple, or None
    rotation: bool = False


class FeedbackController:
    """The in-run half of the loop: probe, detect, refit, replan.

    ``n``/``nbytes``: the sync world size and gradient-bytes hint the
    replan prices (the same pair ``replan_for_survivors`` takes).
    ``params``: the constants the RUNNING plan was priced with (defaults
    to ``default_params()`` — i.e. whatever calibration the run started
    from); residuals are judged against these until a refit replaces
    them.  ``timer(probes, n) -> [seconds]`` and ``clock`` are
    injectable for tests; the default timer runs each probe's collective
    on the live backend with the bench harness's shuffled-interleaved
    protocol, compiling once per probe point and caching the jitted fn
    across ticks.

    :meth:`maybe_tick` is the ``fit`` hook.  Its recorder-off cost is
    ONE ``current_recorder() is None`` check — the exact check
    ``record_event`` makes — so un-instrumented runs pay nothing
    (machine-checked by ``tools/feedback_convergence.py``).
    """

    def __init__(
        self,
        n: int,
        nbytes: int,
        cfg: FeedbackConfig | None = None,
        *,
        params: TpuCostParams | None = None,
        coordination=None,
        timer: Callable | None = None,
        clock: Callable[[], float] = time.perf_counter,
    ):
        if n < 1:
            raise ValueError(f"n must be >= 1, got {n}")
        self.n = int(n)
        self.nbytes = int(nbytes)
        self.cfg = cfg or FeedbackConfig()
        self.params = params if params is not None else default_params()
        # multi-process groups: drift refits become PROPOSE-only — the
        # coordinator publishes the refitted constants + plan through the
        # epoch-consensus protocol (runtime.coordination) and EVERY rank
        # applies the committed decision via apply_committed(), lifting
        # docs/FEEDBACK.md's "replans are rank-local" limit.  Probes stay
        # local: only the coordinator's controller ticks — but every
        # rank's DETECTOR observes (probe-free mode times every rank's
        # own steps), and followers ship their window summaries in their
        # coordination acks (drift_provider) so the coordinator's propose
        # decision pools cross-rank skew it cannot see from its own wire.
        self.coordination = coordination
        if coordination is not None and hasattr(
            coordination, "drift_provider"
        ):
            coordination.drift_provider = self._detector_summary
        self._timer = timer
        self._clock = clock
        self._fingerprint = backend_fingerprint()
        self._detector = DriftDetector(
            self.cfg.band, self.cfg.window, self.cfg.min_window
        )
        # bounded to the recent regime: a drift refit fits from the
        # measurements that breached the band, not run-long history
        self.samples: deque[ResidualSample] = deque(
            maxlen=max(self.cfg.max_samples, self.cfg.min_samples)
        )
        self._fns: dict = {}  # compiled probe cache: point -> (fn, args)
        self._inputs: dict = {}  # device probe inputs, shared by (n, size)
        self._last_step: int | None = None
        self._budget_logged = False
        self._starved_logged = False
        self.ticks = 0
        self.refits = 0
        self.refusals = 0
        # -- probe-free state (cfg.probe_free): the per-step span clock
        # and the plan-rotation cycle (docs/FEEDBACK.md)
        self.step_clock: StepSpanClock | None = (
            StepSpanClock(
                compute_floor_us=self.cfg.compute_floor_us,
                sample_every=self.cfg.step_sample_every,
                fingerprint=self._fingerprint,
            )
            if self.cfg.probe_free
            else None
        )
        self._rotation: dict | None = None
        self._rotation_logged = False
        self.rotations = 0

    # -- resolution helpers --------------------------------------------

    @property
    def probes(self) -> tuple[ProbePoint, ...]:
        return tuple(self.cfg.probes) or default_probe_points(
            self.n, self.nbytes
        )

    def _backend_name(self) -> str:
        if self.cfg.backend:
            return self.cfg.backend
        try:
            import jax

            return jax.default_backend()
        except Exception:  # noqa: BLE001 — persistence must not need a backend
            return "cpu"

    # -- the fit hook ---------------------------------------------------

    def maybe_tick(self, step: int) -> ReplanDecision | None:
        """The per-step hook ``fit`` calls.  Recorder off -> one ``None``
        check and out (zero overhead); otherwise tick on the ``every_k``
        cadence."""
        if current_recorder() is None:
            return None
        if self.refits >= self.cfg.max_refits:
            # the refit budget is spent: no tick can ever refit or replan
            # again, so stop paying probe wall-time for the rest of the
            # run (warn once, not per cadence tick)
            if not self._budget_logged:
                self._budget_logged = True
                self._fns.clear()  # compiled probes + device inputs: dead
                self._inputs.clear()
                log.warning(
                    "feedback refit budget (%d) exhausted; probing "
                    "disabled for the rest of the run",
                    self.cfg.max_refits,
                )
            return None
        k = max(1, self.cfg.every_k)
        if step == 0 or step % k != 0 or step == self._last_step:
            return None
        if self.coordination is not None and not self.coordination.is_coordinator:
            # coordinated follower: the refit+replan arrives as a
            # committed group decision (fit's coordination gate →
            # apply_committed); probing here would only burn wall time on
            # a decision this rank has no authority to make.  Checked on
            # the every_k cadence, not per step — is_coordinator polls
            # the membership files.  (In probe-free mode the follower's
            # detector still fills from its own per-step spans — its
            # summaries reach the coordinator through coordination acks.)
            return None
        self._last_step = step
        if self.cfg.probe_free:
            return self.tick_probe_free(step)
        return self.tick(step)

    # -- the probe-free per-step hooks -----------------------------------

    def wants_step_spans(self) -> bool:
        """True when ``fit`` should host-time (materialize) each step and
        feed :meth:`observe_step` — probe-free mode with the recorder on.
        Recorder off -> one ``None`` check, the same contract as
        :meth:`maybe_tick`."""
        return self.step_clock is not None and current_recorder() is not None

    def set_step_plan(self, captured) -> None:
        """Adopt the compile-time bucket plan ``fit`` captured while the
        (re)built step traced (``utils.profiling.plan_capture``)."""
        if self.step_clock is not None:
            self.step_clock.set_plan(captured)

    def observe_step(self, step: int, dur_s: float) -> None:
        """Fold one materialized step's wall time into the span clock,
        the drift detector, and the residual buffer (probe-free mode)."""
        clock = self.step_clock
        if clock is None or current_recorder() is None:
            return
        sample = clock.observe_step(step, dur_s)
        if sample is None:
            return
        plan = clock.plan
        comm = clock.comm_us(sample)
        if plan is None or comm is None or plan.predicted_us <= 0:
            return
        for b in plan.buckets:
            share = b.predicted_us / plan.predicted_us
            rs = ResidualSample(
                topo=b.topo,
                world=b.world,
                codec=b.codec,
                sharded=b.sharded,
                nbytes=b.nbytes,
                predicted_us=b.predicted_us,
                measured_us=max(comm * share, 1e-3),
                fingerprint=self._fingerprint,
                step=int(step),
                source="step",
                predicted_breakdown=b.predicted,
            )
            self.samples.append(rs)
            self._detector.observe(rs)

    def _detector_summary(self) -> dict:
        return self._detector.summary()

    def _pooled_breaches(self) -> dict[str, float]:
        """Band breaches over the pooled cross-rank view when coordinated
        (followers' ack-shipped summaries), else the local windows."""
        peers = None
        if self.coordination is not None and hasattr(
            self.coordination, "peer_drift"
        ):
            try:
                # only summaries written SINCE the last applied decision:
                # an ack is written pre-apply, so older acks carry the
                # pre-refit breach the group already corrected
                applied = getattr(self.coordination, "applied_epoch", -1)
                peers = self.coordination.peer_drift(min_epoch=applied + 1)
            except Exception:  # noqa: BLE001 — pooling must not kill a tick
                peers = None
        if peers:
            return self._detector.pooled_breaches(peers)
        return {
            DriftDetector.key_str(k): v
            for k, v in self._detector.breaches().items()
        }

    def tick_probe_free(self, step: int) -> ReplanDecision | None:
        """One probe-free feedback round: no collectives — advance the
        rotation cycle if one is running, else check the (pooled) drift
        band over the per-step spans and start one on a breach."""
        self.ticks += 1
        clock = self.step_clock
        record_event(
            "feedback_tick", step=int(step), probes=0, probe_free=True,
            step_samples=len(clock.samples) if clock else 0,
        )
        if clock is None:
            return None
        if self._rotation is not None:
            return self._advance_rotation(step)
        if clock.plan is None:
            return None
        breaches = self._pooled_breaches()
        if not breaches:
            return None
        if self.refits >= self.cfg.max_refits:
            log.warning(
                "feedback drift persists after %d refit(s); refit budget "
                "exhausted — holding the current plan", self.refits,
            )
            return None
        return self._start_rotation(step, breaches)

    def _rotation_sizes(self) -> list[int]:
        """Bucket-size variants to rotate through: the current plan's
        largest bucket scaled by ``rotation_factors``, clamped to
        [4 KiB, the backend's bucket cap] and deduplicated against the
        current size.  The upper clamp matters: past the cap (CPU:
        ``CPU_MAX_BUCKET_BYTES``) a bigger bucket gets SLOWER in-step
        from cache pressure — the α-β model's documented blind spot
        (``parallel/bucketing.py``) — and a rotation sample from that
        regime feeds the fixed-phase fit a contradiction (fewer
        dispatches, more time) that refuses or poisons the solve."""
        from ..parallel.bucketing import _default_max_bucket_bytes

        plan = self.step_clock.plan
        base = max(b.nbytes for b in plan.buckets)
        cap = _default_max_bucket_bytes()
        out = []
        for f in self.cfg.rotation_factors:
            bb = min(max(int(base * float(f)), 4096), cap)
            if bb != base and bb not in out:
                out.append(bb)
        return out

    def _start_rotation(self, step: int, breaches: dict):
        if self.cfg.on_rotate is None:
            if not self._rotation_logged:
                self._rotation_logged = True
                self.refusals += 1
                record_event(
                    "feedback_refused", step=int(step),
                    reason="probe-free drift breached but no on_rotate "
                    "hook: cannot vary the plan to attribute phases",
                )
                log.warning(
                    "probe-free drift detected at step %d but no "
                    "on_rotate hook is configured; cannot refit "
                    "(drift: %s)", step, breaches,
                )
            return None
        sizes = self._rotation_sizes()
        if not sizes:
            return None
        base = max(b.nbytes for b in self.step_clock.plan.buckets)
        # interleave: each cycle visits every variant AND re-visits the
        # base size, so every plan's sample median spans the run's whole
        # wall-clock window instead of one contention regime
        queue: list[int] = []
        for _ in range(max(1, self.cfg.rotation_cycles)):
            queue.extend([*sizes, base])
        self._rotation = {
            "queue": queue,
            "breaches": dict(breaches),
            "ticks_left": max(1, self.cfg.rotation_ticks),
        }
        return self._swap_rotation_plan(step)

    def _swap_rotation_plan(self, step: int) -> ReplanDecision | None:
        rot = self._rotation
        bb = rot["queue"].pop(0)
        rot["ticks_left"] = max(1, self.cfg.rotation_ticks)
        rebuilt = self.cfg.on_rotate(bb)
        if rebuilt is None:
            # the hook declined: no way to vary the plan — abandon
            self._rotation = None
            log.warning(
                "probe-free rotation aborted at step %d: on_rotate "
                "declined bucket_bytes=%d", step, bb,
            )
            return None
        self.rotations += 1
        # drop the old plan until the swapped step's compile capture
        # arrives: a rebuilt step that (unexpectedly) does not re-trace
        # must leave the clock blind, never mis-attributing its steps to
        # the previous plan's signature
        self.step_clock.plan = None
        record_event(
            "feedback_rotate", step=int(step), bucket_bytes=int(bb),
            remaining=len(rot["queue"]),
        )
        log.warning(
            "probe-free rotation at step %d: sampling bucket_bytes=%d "
            "(%d variant(s) left)", step, bb, len(rot["queue"]),
        )
        return ReplanDecision(
            plan=None,
            params=self.params,
            drift=dict(rot["breaches"]),
            invalidated=0,
            fit_meta={"rotation_bucket_bytes": int(bb)},
            rebuilt=rebuilt,
            rotation=True,
        )

    def _advance_rotation(self, step: int) -> ReplanDecision | None:
        rot = self._rotation
        rot["ticks_left"] -= 1
        if rot["ticks_left"] > 0:
            return None
        if rot["queue"]:
            return self._swap_rotation_plan(step)
        # every variant sampled: fit per-phase scales across the plans
        self._rotation = None
        return self._refit_probe_free(step, rot["breaches"])

    def _refit_probe_free(self, step: int, drift: dict) -> ReplanDecision | None:
        floor = self.cfg.compute_floor_us
        if floor is None:
            floor = self.step_clock.floor_us
        try:
            if floor is None:
                raise FeedbackRefused(
                    "no compute floor available (set "
                    "FeedbackConfig.compute_floor_us — a sync-free twin "
                    "timing, zero collectives)"
                )
            new_params, meta = fit_probe_free(
                self.step_clock.samples,
                base_params=self.params,
                compute_floor_us=floor,
                min_steps_per_plan=self.cfg.min_steps_per_plan,
            )
        except FeedbackRefused as e:
            self.refusals += 1
            record_event(
                "feedback_refused", step=int(step), reason=str(e)[:300],
                probe_free=True,
            )
            log.warning(
                "probe-free refit refused at step %d: %s", step, e
            )
            # keep accumulating under the rotated plans; a later breach
            # restarts the cycle with more samples per plan
            return None
        drift = {str(k): round(float(v), 4) for k, v in drift.items()}
        implied = meta.get("floor_implied_us")
        if implied is not None:
            # the fit's in-regime floor beats the twin measurement (same
            # loop, same donation pattern, same recorder overhead): adopt
            # it for post-refit drift judgement
            self.step_clock.compute_floor_us = float(implied)
        if self.coordination is not None:
            decision = self._propose_replan(step, new_params, meta, drift)
        else:
            decision = self._apply_refit(step, new_params, meta, drift)
        # post-refit steps run a rebuilt plan priced by NEW constants:
        # both the step-sample buffer and the plan signature restart
        self.step_clock.samples.clear()
        self.step_clock.plan = None
        return decision

    def tick(self, step: int) -> ReplanDecision | None:
        """One feedback round: probe, record, detect; refit + replan on a
        band breach.  Returns the :class:`ReplanDecision` when drift
        fired (even if ``on_replan`` declined a rebuild), else None."""
        self.ticks += 1
        probes = self.probes
        t0 = self._clock()
        secs = (self._timer or self._default_timer)(probes, self.n)
        if len(secs) != len(probes):
            raise ValueError(
                f"probe timer returned {len(secs)} times for "
                f"{len(probes)} probes"
            )
        for p, s in zip(probes, secs):
            measured_us = float(s) * 1e6
            cost = predict_spec_cost(
                p.spec, self.n, p.nbytes, self.params, codec=p.codec
            )
            if cost is None:
                continue
            predicted = cost.total_us
            breakdown = {
                k: round(v, 3) for k, v in dataclasses.asdict(cost).items()
            }
            record_event(
                "bucket_measured",
                name=f"ftfb_probe_{p.spec.replace(',', 'x')}_{p.nbytes}B",
                axis="ftfb",
                topo={"ftfb": p.spec},
                world={"ftfb": self.n},
                nbytes=int(p.nbytes),
                codec=p.codec,
                sharded=False,
                measured_us=round(measured_us, 3),
                predicted_us=round(predicted, 3),
                predicted=breakdown,
                fingerprint=self._fingerprint,
                step=int(step),
            )
            sample = ResidualSample(
                topo="ring" if p.spec in ("1", "ring") else p.spec,
                world=self.n,
                codec=p.codec,
                sharded=False,
                nbytes=int(p.nbytes),
                predicted_us=predicted,
                measured_us=measured_us,
                fingerprint=self._fingerprint,
                step=int(step),
                source="self",
                predicted_breakdown=breakdown,
            )
            self.samples.append(sample)
            self._detector.observe(sample)
        record_event(
            "feedback_tick",
            step=int(step),
            probes=len(probes),
            elapsed_ms=round((self._clock() - t0) * 1e3, 3),
        )
        breaches = self._detector.breaches()
        if not breaches:
            return None
        if len(samples_to_points(self.samples)) < self.cfg.min_samples:
            # the band can breach on the very first tick (a grossly
            # mis-calibrated start) before enough points exist to fit —
            # keep accumulating rather than burn a loud refusal on warm-up.
            # Count ELIGIBLE points (the fitter's own currency), not raw
            # samples: a probe set mixing codecs under a tight max_samples
            # would otherwise pass this gate while the fit can never see
            # min_samples f32 points — a refuse-every-tick livelock
            if (
                len(self.samples) == self.samples.maxlen
                and not self._starved_logged
            ):
                # the buffer is FULL and still short of eligible points:
                # accumulation can never get there — say so once instead
                # of warming up silently forever
                self._starved_logged = True
                log.warning(
                    "feedback sample buffer full (%d) with fewer than "
                    "min_samples=%d eligible f32 points; this probe set "
                    "cannot feed a refit — widen max_samples or add "
                    "identity-codec probes", len(self.samples),
                    self.cfg.min_samples,
                )
            return None
        if self.refits >= self.cfg.max_refits:
            log.warning(
                "feedback drift persists after %d refit(s); refit budget "
                "exhausted — holding the current plan", self.refits,
            )
            return None
        return self._refit_and_replan(step, breaches)

    def _refit_and_replan(self, step: int, breaches: dict) -> ReplanDecision | None:
        drift = {
            "|".join(str(p) for p in key): round(med, 4)
            for key, med in breaches.items()
        }
        try:
            new_params, meta = fit_from_samples(
                self.samples,
                base_params=self.params,
                min_samples=self.cfg.min_samples,
            )
        except FeedbackRefused as e:
            self.refusals += 1
            record_event(
                "feedback_refused", step=int(step), reason=str(e)[:300]
            )
            log.warning("feedback refit refused at step %d: %s", step, e)
            return None
        if self.coordination is not None:
            return self._propose_replan(step, new_params, meta, drift)
        return self._apply_refit(step, new_params, meta, drift)

    def _apply_refit(
        self, step: int, new_params: TpuCostParams, meta: dict, drift: dict
    ) -> ReplanDecision:
        """The local (uncoordinated) refit tail, shared by the probe path
        and the probe-free path: persist, invalidate, replan, rebuild."""
        self.refits += 1
        if self.cfg.calibration_path:
            save_calibration(
                self.cfg.calibration_path,
                new_params,
                backend=self._backend_name(),
                fingerprint=self._fingerprint,
                source="feedback",
                meta={
                    "samples": len(self.samples),
                    "run_id": self.cfg.run_id or f"step{step}",
                    "step": int(step),
                    "fit": meta,
                    "drift": drift,
                },
            )
        removed = invalidate_plan_cache(
            # world=None: the refit replaced the CONSTANTS, which priced
            # every shortlist this backend ever measured — a multi-axis
            # run's other sync worlds (tp beside dp) are exactly as stale
            # as the probed axis, and a surviving entry would cache-hit
            # the rebuilt step straight back onto the stale winner
            cache_invalidation_predicate(self._fingerprint, None),
            cache_path=self.cfg.plan_cache_path,
        )
        plan = choose_topology(self.n, self.nbytes, params=new_params)
        self.params = new_params
        self._detector.reset()  # re-judge residuals against the refit
        record_event(
            "feedback_refit",
            step=int(step),
            topo=plan.to_ft_topo(),
            invalidated=removed,
            drift=drift,
            samples=len(self.samples),
        )
        log.warning(
            "feedback refit at step %d: drift %s; replanned topo %s, "
            "%d plan-cache entr%s invalidated",
            step, drift, plan.to_ft_topo(), removed,
            "y" if removed == 1 else "ies",
        )
        # drop the consumed samples: a LATER refit (a genuine mid-run
        # regime change) must solve from post-refit measurements, not a
        # mix the old regime dominates; the warm-up guard in tick() makes
        # the next breach re-accumulate min_samples before fitting
        self.samples.clear()
        rebuilt = (
            self.cfg.on_replan(plan, new_params)
            if self.cfg.on_replan is not None
            else None
        )
        return ReplanDecision(plan, new_params, drift, removed, meta, rebuilt)

    # -- the coordinated (multi-process) replan path --------------------

    def _propose_replan(
        self, step: int, new_params: TpuCostParams, meta: dict, drift: dict
    ) -> None:
        """Publish the refit as a group decision instead of applying it.

        The payload carries everything a peer needs to apply IDENTICALLY:
        the refitted constants (serialized through the calibration
        schema's dict form) and the topo spec the coordinator's chooser
        picked under them — peers re-run ``choose_topology`` from the
        same constants and assert the same winner.  The apply (for every
        rank, this one included) happens in :meth:`apply_committed` when
        ``fit``'s coordination gate delivers the commit."""
        payload = {
            "params": _params_to_dict(new_params),
            "topo": choose_topology(
                self.n, self.nbytes, params=new_params
            ).to_ft_topo(),
            "drift": drift,
            "fit_meta": meta,
            "samples": len(self.samples),
        }
        epoch = self.coordination.propose(
            "replan",
            payload,
            apply_step=self.coordination.suggest_apply_step(),
        )
        if epoch is None:
            # another decision is mid-handshake (or coordinatorship just
            # moved): keep the samples, re-breach on a later tick
            log.warning(
                "feedback refit at step %d could not propose (control "
                "slot busy); retrying on a later tick", step,
            )
            return None
        self.refits += 1
        self._detector.reset()
        self.samples.clear()
        record_event(
            "feedback_refit", step=int(step), topo=payload["topo"],
            invalidated=0, drift=drift, samples=payload["samples"],
            control_epoch=epoch, proposed=True,
        )
        log.warning(
            "feedback refit at step %d proposed as control epoch %d "
            "(topo %s); group-wide apply on commit", step, epoch,
            payload["topo"],
        )
        return None

    def apply_committed(self, payload: dict, step: int | None = None):
        """Apply a COMMITTED group replan on this rank: reconstruct the
        constants, persist + invalidate, replan, and hand back the same
        :class:`ReplanDecision` a local refit would have — ``fit`` swaps
        the step through the identical path.  Deterministic from the
        payload alone, so every rank lands on the same plan; a chooser
        that disagrees with the broadcast spec (skewed local config)
        follows the group and says so."""
        new_params = _params_from_dict(dict(payload["params"]))
        spec = payload.get("topo")
        if self.cfg.calibration_path:
            save_calibration(
                self.cfg.calibration_path,
                new_params,
                backend=self._backend_name(),
                fingerprint=self._fingerprint,
                source="feedback",
                meta={
                    "samples": payload.get("samples"),
                    "run_id": self.cfg.run_id or f"step{step}",
                    "step": step,
                    "fit": payload.get("fit_meta", {}),
                    "drift": payload.get("drift", {}),
                    "coordinated": True,
                },
            )
        removed = invalidate_plan_cache(
            cache_invalidation_predicate(self._fingerprint, None),
            cache_path=self.cfg.plan_cache_path,
        )
        from ..runtime.coordination import apply_spec_override

        plan = apply_spec_override(
            choose_topology(self.n, self.nbytes, params=new_params),
            spec,
            self.n,
        )
        self.params = new_params
        self._detector.reset()
        self.samples.clear()
        rebuilt = (
            self.cfg.on_replan(plan, new_params)
            if self.cfg.on_replan is not None
            else None
        )
        return ReplanDecision(
            plan,
            new_params,
            dict(payload.get("drift", {})),
            removed,
            dict(payload.get("fit_meta", {})),
            rebuilt,
        )

    # -- the default live-wire probe timer ------------------------------

    def _default_timer(self, probes, n):
        """Time each probe's collective on the live backend — the bench
        harness's shuffled-interleaved protocol over jitted, warmed fns
        (compiled once per probe point, cached across ticks)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from ..bench.harness import _interleaved_times
        from ..parallel.compressed import compressed_allreduce
        from ..parallel.mesh import flat_mesh

        calls = {}
        for i, p in enumerate(probes):
            cached = self._fns.get(p)
            if cached is None:
                mesh = flat_mesh(n, "ftfb")
                size = max(1, p.nbytes // 4)
                # the input depends only on (n, size) — share one device
                # array across the specs/codecs probing the same payload
                # instead of pinning an identical copy per ProbePoint
                x = self._inputs.get((n, size))
                if x is None:
                    rng = np.random.default_rng((n * 1000003 + size) & 0xFFFF)
                    x = jnp.asarray(
                        rng.standard_normal((n, size)).astype(np.float32)
                    )
                    self._inputs[(n, size)] = x
                wire_spec = "1" if p.spec == "ring" else p.spec

                def device_fn(row, spec=wire_spec, codec=p.codec):
                    return compressed_allreduce(
                        row[0], "ftfb", topo=spec, codec=codec
                    )[None]

                fn = jax.jit(
                    jax.shard_map(
                        device_fn, mesh=mesh, in_specs=P("ftfb"),
                        out_specs=P("ftfb"), check_vma=False,
                    )
                )
                jax.block_until_ready(fn(x))  # compile outside the timing
                cached = (fn, (x,))
                self._fns[p] = cached
            calls[str(i)] = cached
        rows = _interleaved_times(calls, max(1, self.cfg.repeat))
        return [rows[str(i)]["min_ms"] * 1e-3 for i in range(len(probes))]
