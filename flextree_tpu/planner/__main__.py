"""Planner CLI: ``python -m flextree_tpu.planner --n 16 --size-mb 256``.

The offline entry point mirroring the reference's ``cost_model`` binary
(``cost_model/main.cpp``): enumerate candidate tree shapes for N devices,
cost each, print the ranked table and the winning ``FT_TOPO`` value.
``--sweep`` reproduces the reference's N=1..max sweep (shape counts +
planning time per N, CSV to stdout).
"""

from __future__ import annotations

import argparse
import sys
import time

from .choose import choose_topology
from .cost_model import TpuCostParams, LinkParams
from .factorize import count_ordered_factorizations
from .native import native_available, native_choose_lonely


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="flextree_tpu.planner",
        description="Choose the cheapest allreduce tree shape for N devices.",
    )
    ap.add_argument("--n", type=int, default=None, help="device count")
    ap.add_argument("--size-mb", type=float, default=256.0, help="payload MB per chip")
    ap.add_argument(
        "--mesh-shape",
        type=str,
        default=None,
        help="physical torus shape, e.g. 16,16 (enables torus-aligned costing)",
    )
    ap.add_argument(
        "--dcn-axes",
        type=str,
        default="",
        help="comma list of mesh-axis indices that are DCN (multi-slice)",
    )
    ap.add_argument("--ici-gbps", type=float, default=45.0)
    ap.add_argument("--ici-latency-us", type=float, default=1.0)
    ap.add_argument(
        "--calibration",
        type=str,
        default=None,
        metavar="PATH",
        help="CALIBRATION.json with measured cost constants (see "
        "planner/calibrate.py); overrides --ici-* when its section exists",
    )
    ap.add_argument(
        "--backend",
        type=str,
        default="cpu",
        help="which CALIBRATION.json section to load (cpu, tpu_v5e, ...)",
    )
    ap.add_argument(
        "--sweep",
        type=int,
        default=None,
        metavar="NMAX",
        help="sweep N=2..NMAX, print CSV (n, num_shapes, chosen, plan_us)",
    )
    ap.add_argument(
        "--native",
        action="store_true",
        help="use the native C++ core (builds it on first use)",
    )
    args = ap.parse_args(argv)

    params = TpuCostParams(
        ici=LinkParams(bandwidth_GBps=args.ici_gbps, latency_us=args.ici_latency_us)
    )
    if args.calibration:
        from .calibrate import load_calibration

        cal = load_calibration(args.calibration, backend=args.backend)
        if cal is None:
            print(
                f"no {args.backend!r} section in {args.calibration}; "
                "using CLI/default constants",
                file=sys.stderr,
            )
        else:
            params = cal
    nbytes = int(args.size_mb * 1e6)

    if args.sweep is not None:
        # resolve (and if needed build) the native lib before timing starts,
        # so the first row doesn't report compile time as planning time
        use_native = args.native and native_available()
        print("n,num_shapes,chosen,plan_us")
        for n in range(2, args.sweep + 1):
            t0 = time.perf_counter()
            lonely = 0
            if use_native:
                widths, lonely, _ = native_choose_lonely(n, nbytes, params)
            else:
                plan = choose_topology(n, nbytes, params)
                widths = plan.widths
                lonely = getattr(plan.topology, "lonely", 0)
            dt = (time.perf_counter() - t0) * 1e6
            shape = "ring" if widths == (1,) else "*".join(map(str, widths))
            if lonely:
                shape += f"+{lonely}"
            print(f"{n},{count_ordered_factorizations(n)},{shape},{dt:.1f}")
        return 0

    if args.n is None:
        ap.error("--n is required unless --sweep is given")

    mesh_shape = (
        tuple(int(t) for t in args.mesh_shape.split(",")) if args.mesh_shape else None
    )
    dcn_axes = (
        tuple(int(t) for t in args.dcn_axes.split(",")) if args.dcn_axes else ()
    )
    plan = choose_topology(
        args.n, nbytes, params, mesh_shape=mesh_shape, dcn_axes=dcn_axes
    )
    print(plan.summary())
    print(f"FT_TOPO={plan.to_ft_topo()}")
    if args.native:
        nat = native_choose_lonely(args.n, nbytes, params)
        if nat is None:
            print("native core unavailable (build failed?)", file=sys.stderr)
        else:
            widths, lonely, cost = nat
            shape = "ring" if widths == (1,) else "*".join(map(str, widths))
            if lonely:
                shape += f"+{lonely}"
            print(f"native argmin: {shape} ({cost:.1f} µs)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
