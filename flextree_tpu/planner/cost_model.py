"""Analytical TPU cost model for topology-parameterized allreduce.

Retargets the reference's 3-term model (``cost_model/CostModel.h``:
latency+control, memory read/write, bandwidth+compute — constants calibrated
for an Ethernet MPI cluster) to the TPU fabric:

- **latency/control**: each stage-``w`` grouped collective on a torus axis is
  ``w-1`` neighbor hops (XLA lowers grouped reduce-scatter/all-gather to a
  ring on the axis), each hop paying the link latency; wide groups add
  control overhead — the TPU analog of the reference's ``co*(width-9)``
  wide-group penalty (``CostModel.h:7-10``).
- **bandwidth**: stage ``i`` moves ``(w_i-1)/w_i * S/g_i`` bytes per chip
  over that stage's axis.  A telescoping identity makes the *sum* over
  stages equal ``(N-1)/N * S`` for every factorization — on a uniform
  fabric, bandwidth does not distinguish shapes (same conclusion as the
  reference's shape-independent ``bandwidth_calculation_overhead``,
  ``CostModel.h:22-30``); shapes win on latency and on *per-axis* bandwidth
  differences (ICI vs DCN), which is the TPU-specific lever.
- **reduce/memory**: phase-1 accumulation writes ``(w_i-1)/(g_i w_i) * S``
  bytes per stage at HBM-bound reduce throughput — the analog of
  ``memory_read_write_overhead`` (``CostModel.h:32-79``) without its
  per-height unrolled formulas (and without its uninitialized-``cost`` and
  ignored-``Chunk_size`` bugs, SURVEY §8).

All times in microseconds, sizes in bytes.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

from ..schedule.ir import swing_rho
from ..schedule.stages import Topology

__all__ = [
    "LinkParams",
    "TpuCostParams",
    "CostBreakdown",
    "allreduce_cost",
    "lonely_allreduce_cost",
    "ring_cost",
    "swing_cost",
    "generalized_cost",
    "reduce_scatter_cost",
    "all_gather_cost",
    "sharded_sync_cost",
]


@dataclass(frozen=True)
class LinkParams:
    """One communication domain (an ICI torus axis, or the DCN)."""

    bandwidth_GBps: float  # per-chip injection bandwidth on this domain
    latency_us: float  # per neighbor-hop / per-message latency

    def time_us(self, nbytes: float) -> float:
        return nbytes / (self.bandwidth_GBps * 1e3)  # GB/s -> bytes/µs


#: TPU v5e-flavored defaults: ICI ~45 GB/s/direction per axis with ~1 µs
#: neighbor-hop latency; DCN ~ 6 GB/s with tens of µs latency.
ICI_DEFAULT = LinkParams(bandwidth_GBps=45.0, latency_us=1.0)
DCN_DEFAULT = LinkParams(bandwidth_GBps=6.0, latency_us=25.0)


@dataclass(frozen=True)
class TpuCostParams:
    """Fabric + chip constants for the model."""

    ici: LinkParams = ICI_DEFAULT
    dcn: LinkParams = DCN_DEFAULT
    # HBM-bound accumulate throughput for the local reduction (read w
    # copies, write one) — the VPU is never the bottleneck, HBM is.
    reduce_bw_GBps: float = 400.0
    # extra control/software overhead per unit of group width beyond 2 —
    # wide groups put more messages in flight per step (TPU analog of
    # CostModel.h:7-10's width>9 penalty, smooth instead of a cliff).
    control_us_per_width: float = 0.05
    # fixed per-collective launch overhead (dispatch, fusion boundary)
    launch_us: float = 2.0
    # wire-codec encode/decode throughput (block-scale quantize +
    # dequantize passes on the accumulation path, ops/quantize.py) — like
    # reduce_bw_GBps this is HBM-bound, not VPU-bound, and calibratable
    # per backend (planner/calibrate.py fits it alongside the others when
    # compressed measurement points are provided)
    codec_bw_GBps: float = 200.0
    # achievable dense-matmul throughput (GFLOP/s) for the backward-compute
    # estimate the overlap boundary equalizer uses
    # (planner.choose.choose_overlap_boundaries): comm can only hide under
    # compute, so the equalizer needs an absolute compute scale, not just
    # wire terms.  0.0 (the default) = resolve per backend at use time
    # (parallel/overlap.py: a CPU host is GFLOP/s-scale, an accelerator
    # TFLOP/s-scale); calibratable like every other constant.
    bwd_GFLOPs: float = 0.0
    # split-collective bandwidth scales ("Revisiting the Time Cost Model of
    # AllReduce", arXiv:2409.04202: the two halves of an allreduce do NOT
    # share one α-β term — the reduce-scatter's critical path carries the
    # fold arithmetic while the allgather is pure forwarding, so their
    # achieved bandwidths differ and a fused fit mis-ranks split
    # schedules).  Achieved-bandwidth multipliers on the link term: 1.0
    # (the default) reproduces the fused costing exactly; calibration can
    # set them per backend (CALIBRATION_SCHEMA 3 round-trips both; older
    # files load with the neutral defaults, non-silently).
    rs_bw_scale: float = 1.0
    ag_bw_scale: float = 1.0


@dataclass(frozen=True)
class CostBreakdown:
    """Predicted time (µs) for one allreduce, by term."""

    latency_us: float
    bandwidth_us: float
    reduce_us: float
    control_us: float
    # wire-codec term: per-hop encode/decode work (0 for identity/bf16 —
    # a dtype cast fuses into the surrounding elementwise work)
    codec_us: float = 0.0

    @property
    def total_us(self) -> float:
        return (
            self.latency_us
            + self.bandwidth_us
            + self.reduce_us
            + self.control_us
            + self.codec_us
        )


def _stage_links(topo: Topology, params: TpuCostParams, dcn_stages=()) -> list[LinkParams]:
    return [
        params.dcn if i in set(dcn_stages) else params.ici
        for i in range(topo.num_stages)
    ]


def _codec_props(codec) -> tuple[float, bool]:
    """(wire_ratio, pays_hop_cost) for ``codec`` (None/name/Codec)."""
    if codec is None:
        return 1.0, False
    from ..ops.quantize import get_codec

    c = get_codec(codec)
    return c.wire_ratio, c.hop_cost


def allreduce_cost(
    topo: Topology,
    nbytes: int,
    params: TpuCostParams = TpuCostParams(),
    dcn_stages: tuple[int, ...] = (),
    codec=None,
) -> CostBreakdown:
    """Predicted wall time of one allreduce of ``nbytes``/chip with ``topo``.

    ``dcn_stages`` marks stages whose groups cross the DCN (multi-slice):
    on a 2-slice system with widths ``(16, 2)``, stage 1 rides DCN.

    ``codec`` (``ops/quantize.py``) scales the wire bytes by the codec's
    ratio and, for codecs with per-hop encode/decode work (int8
    block-scale), adds a codec term: each phase-1 stage encodes its full
    per-chip buffer and decodes the received tiles (~2 passes over
    ``nbytes/g`` at ``codec_bw_GBps``), phase 2 encodes the final tile
    once and decodes the gathered result (~``nbytes`` once).
    """
    ratio, hop_cost = _codec_props(codec)
    if topo.is_ring:
        return ring_cost(topo.num_nodes, nbytes, params, codec=codec)
    links = _stage_links(topo, params, dcn_stages)
    lat = bw = red = ctl = cod = 0.0
    for i, w in enumerate(topo.widths):
        g = topo.gaps[i]
        link = links[i]
        stage_bytes = (w - 1) / w * (nbytes / g)  # per chip, per phase
        hops = w - 1  # ring lowering on the stage's axis
        # two phases: reduce-scatter down, all-gather back up
        lat += 2 * (hops * link.latency_us + params.launch_us)
        bw += 2 * link.time_us(stage_bytes * ratio)
        red += stage_bytes / (params.reduce_bw_GBps * 1e3)  # phase 1 only
        ctl += 2 * params.control_us_per_width * max(0, w - 2)
        if hop_cost:
            # phase-1 per stage: encode nbytes/g, decode ~the same
            cod += 2 * (nbytes / g) / (params.codec_bw_GBps * 1e3)
    if hop_cost:
        # phase 2: one tile encode + one full-output decode
        cod += (nbytes / topo.num_nodes + nbytes) / (params.codec_bw_GBps * 1e3)
    return CostBreakdown(lat, bw, red, ctl, cod)


def lonely_allreduce_cost(
    tree_topo: Topology,
    lonely: int,
    nbytes: int,
    params: TpuCostParams = TpuCostParams(),
    dcn_stages: tuple[int, ...] = (),
    buddy_crosses_dcn: bool = False,
    codec=None,
) -> CostBreakdown:
    """Cost of a ``tree+lonely`` shape (``schedule.stages.LonelyTopology``).

    The tree allreduce over ``m = tree_topo.num_nodes`` ranks plus two
    buddy ``ppermute`` exchanges moving the FULL payload (lonely -> buddy
    fold, buddy -> lonely restore) and one extra fold at the buddy.  Buddy
    pairs span ``m`` ranks (lonely rank ``m+i`` pairs with rank ``i``), so
    on a multi-slice system the hop can cross the DCN boundary — pass
    ``buddy_crosses_dcn=True`` to price the two full-payload exchanges at
    DCN constants (the chooser does whenever ``dcn_axes`` is set; billing
    the dominant 2·S term at ICI would let lonely shapes win on an
    underestimate).  Implementation note: the runtime's lonely tree stages
    ride the ppermute-ring machinery rather than fused grouped collectives
    (``parallel/allreduce.py::lonely_allreduce``), which this model does
    not surcharge — the per-stage traffic is identical and the launch term
    already counts per stage.
    """
    base = allreduce_cost(tree_topo, nbytes, params, dcn_stages=dcn_stages, codec=codec)
    if lonely <= 0:
        return base
    ratio, hop_cost = _codec_props(codec)
    link = params.dcn if buddy_crosses_dcn else params.ici
    lat = base.latency_us + 2 * (link.latency_us + params.launch_us)
    bw = base.bandwidth_us + 2 * link.time_us(nbytes * ratio)
    red = base.reduce_us + nbytes / (params.reduce_bw_GBps * 1e3)
    cod = base.codec_us
    if hop_cost:
        # buddy fold + restore: two extra full-payload encode/decode pairs
        cod += 4 * nbytes / (params.codec_bw_GBps * 1e3)
    return CostBreakdown(lat, bw, red, base.control_us, cod)


def ring_cost(
    n: int,
    nbytes: int,
    params: TpuCostParams = TpuCostParams(),
    crosses_dcn: bool = False,
    codec=None,
) -> CostBreakdown:
    """Ring algorithm: 2(N-1) neighbor steps, each carrying ``S/N`` bytes
    (``mpi_mod.hpp:1113-1163``).  Bandwidth-optimal, latency-heaviest.

    ``crosses_dcn``: a ring spanning multiple slices has cross-DCN neighbor
    links, and every lock-step ring step is gated by its slowest link — so
    the whole ring prices at DCN constants.

    Launch overhead is paid **per step**: the implementation is a
    ``fori_loop`` whose 2(N-1) iterations each dispatch a
    ``collective_permute`` (``parallel/allreduce.py``), unlike a tree stage
    which is one fused grouped collective per phase.  (Round-2 calibration
    charged the ring only 2 launches, making flat-N and ring-N feature
    vectors identical and the fit degenerate — VERDICT r2 weak #2.)"""
    if n <= 1:
        return CostBreakdown(0.0, 0.0, 0.0, 0.0)
    ratio, hop_cost = _codec_props(codec)
    link = params.dcn if crosses_dcn else params.ici
    steps = 2 * (n - 1)
    per_step_bytes = nbytes / n
    lat = steps * (link.latency_us + params.launch_us)
    bw = steps * link.time_us(per_step_bytes * ratio)
    red = (n - 1) / n * nbytes / (params.reduce_bw_GBps * 1e3)
    cod = 0.0
    if hop_cost:
        # (n-1) fold hops each encode+decode one block; phase 2 encodes the
        # owned block once and decodes the full assembled output
        cod = (2 * (n - 1) * per_step_bytes + per_step_bytes + nbytes) / (
            params.codec_bw_GBps * 1e3
        )
    return CostBreakdown(lat, bw, red, 0.0, cod)


# ---------------------------------------------------------------------------
# IR-family costs (ISSUE 8): swing short-cut rings, generalized allreduce
# ---------------------------------------------------------------------------


def swing_cost(
    n: int,
    nbytes: int,
    params: TpuCostParams = TpuCostParams(),
    crosses_dcn: bool = False,
    codec=None,
) -> CostBreakdown:
    """Swing short-cut ring (arXiv:2401.09356, ``schedule.ir.swing_ir``):
    ``log2(P)`` pairwise steps per phase over the largest power-of-two
    core ``P``, step ``s`` moving ``S / 2^(s+1)`` bytes to a peer at ring
    distance ``|rho_s|`` (1, 1, 3, 5, 11, ...).

    Bandwidth term per arXiv:2409.04202's treatment: an alpha-beta model
    that ignores WHERE the bytes go mis-ranks multi-hop algorithms, so
    each step's wire time is weighted by its link occupancy — a
    distance-``d`` permute on a ring fabric holds ``d`` links for the
    whole transfer, so the effective per-chip wire time scales by ``d``
    (min of the two ring directions).  This is what makes the model
    honest about swing vs the tree on a torus: swing's total weighted
    distance ``sum_s d_s / 2^(s+1)`` beats RHD's doubling distances but
    still pays more than a one-axis grouped collective; it wins where
    per-step latency dominates or the fabric is switch-like (calibration
    can flatten the distance penalty via link constants).

    Non-power-of-two ``n``: the ``n - P`` extras pay the lonely buddy
    protocol (two full-payload hops + one fold), same terms as
    :func:`lonely_allreduce_cost`.
    """
    if n <= 1:
        return CostBreakdown(0.0, 0.0, 0.0, 0.0)
    ratio, hop_cost = _codec_props(codec)
    link = params.dcn if crosses_dcn else params.ici
    core = 1 << (n.bit_length() - 1)
    extras = n - core
    k = core.bit_length() - 1
    lat = bw = red = cod = 0.0
    for s in range(k):
        # the canonical displacement sequence the emitter executes
        # (schedule.ir.swing_rho) — never a re-derived copy
        rho = abs(swing_rho(s))
        dist = min(rho % core, core - rho % core) or 1
        step_bytes = nbytes / (1 << (s + 1))
        # two phases (reduce-scatter down, all-gather back)
        lat += 2 * (dist * link.latency_us + params.launch_us)
        bw += 2 * dist * link.time_us(step_bytes * ratio)
        red += step_bytes / (params.reduce_bw_GBps * 1e3)  # phase-1 fold
        if hop_cost:
            cod += 2 * 2 * step_bytes / (params.codec_bw_GBps * 1e3)
    if extras:
        lat += 2 * (link.latency_us + params.launch_us)
        bw += 2 * link.time_us(nbytes * ratio)
        red += nbytes / (params.reduce_bw_GBps * 1e3)
        if hop_cost:
            cod += 4 * nbytes / (params.codec_bw_GBps * 1e3)
    return CostBreakdown(lat, bw, red, 0.0, cod)


def generalized_cost(
    widths: tuple[int, ...],
    ports: int,
    nbytes: int,
    params: TpuCostParams = TpuCostParams(),
    dcn_stages: tuple[int, ...] = (),
    codec=None,
) -> CostBreakdown:
    """The generalized construction (arXiv:2004.09362,
    ``schedule.ir.generalized_ir``): tree-shaped stages executed as
    ``ceil((w-1)/ports)`` pairwise rounds each.  Per stage the byte
    profile equals the tree's (``(w-1)/w * S/g`` per phase — the
    telescoping identity holds for any execution of the same block-map),
    so the family trades on LATENCY: each round pays a launch, and
    ``ports`` rounds-in-flight trade launch count against per-round
    control overhead.  ``widths=(N,), ports=N-1`` prices like the flat
    tree message pattern; ``widths=(2,..,2), ports=1`` like RHD over
    permutes."""
    topo = Topology(math.prod(widths), tuple(widths))
    ratio, hop_cost = _codec_props(codec)
    links = _stage_links(topo, params, dcn_stages)
    lat = bw = red = ctl = cod = 0.0
    for i, w in enumerate(topo.widths):
        g = topo.gaps[i]
        link = links[i]
        p = min(ports, w - 1)
        rounds = -(-(w - 1) // p)
        stage_bytes = (w - 1) / w * (nbytes / g)
        lat += 2 * (rounds * params.launch_us + (w - 1) * link.latency_us)
        bw += 2 * link.time_us(stage_bytes * ratio)
        red += stage_bytes / (params.reduce_bw_GBps * 1e3)
        ctl += 2 * rounds * params.control_us_per_width * max(0, p - 1)
        if hop_cost:
            cod += 2 * (nbytes / g) / (params.codec_bw_GBps * 1e3)
    if hop_cost:
        cod += (nbytes / topo.num_nodes + nbytes) / (params.codec_bw_GBps * 1e3)
    return CostBreakdown(lat, bw, red, ctl, cod)


# ---------------------------------------------------------------------------
# split-collective costs (PR 7): the two phases priced separately
# ---------------------------------------------------------------------------


def _phase_cost(
    topo: Topology,
    nbytes: int,
    params: TpuCostParams,
    phase: str,  # "rs" | "ag"
    dcn_stages: tuple[int, ...] = (),
    codec=None,
) -> CostBreakdown:
    """One phase of the tree/ring schedule: ``reduce_scatter_us`` /
    ``all_gather_us`` as arXiv:2409.04202 argues they should be costed —
    per-phase achieved bandwidth (``rs_bw_scale``/``ag_bw_scale``), the
    fold arithmetic charged to phase 1 only, and the codec term split the
    way ``parallel/compressed.py`` actually spends it (per-stage re-encode
    on the accumulation path vs encode-once + forward + one decode)."""
    ratio, hop_cost = _codec_props(codec)
    scale = params.rs_bw_scale if phase == "rs" else params.ag_bw_scale
    cbw = params.codec_bw_GBps * 1e3
    if topo.is_ring:
        n = topo.num_nodes
        if n <= 1:
            return CostBreakdown(0.0, 0.0, 0.0, 0.0)
        link = params.dcn if dcn_stages else params.ici
        steps = n - 1
        per_step = nbytes / n
        lat = steps * (link.latency_us + params.launch_us)
        bw = steps * link.time_us(per_step * ratio) / max(scale, 1e-9)
        red = (n - 1) / n * nbytes / (params.reduce_bw_GBps * 1e3) if phase == "rs" else 0.0
        cod = 0.0
        if hop_cost:
            cod = (
                2 * steps * per_step / cbw
                if phase == "rs"
                else (per_step + nbytes) / cbw
            )
        return CostBreakdown(lat, bw, red, 0.0, cod)
    links = _stage_links(topo, params, dcn_stages)
    lat = bw = red = ctl = cod = 0.0
    for i, w in enumerate(topo.widths):
        g = topo.gaps[i]
        link = links[i]
        stage_bytes = (w - 1) / w * (nbytes / g)
        hops = w - 1
        lat += hops * link.latency_us + params.launch_us
        bw += link.time_us(stage_bytes * ratio) / max(scale, 1e-9)
        ctl += params.control_us_per_width * max(0, w - 2)
        if phase == "rs":
            red += stage_bytes / (params.reduce_bw_GBps * 1e3)
            if hop_cost:
                cod += 2 * (nbytes / g) / cbw
    if phase == "ag" and hop_cost:
        cod += (nbytes / topo.num_nodes + nbytes) / cbw
    return CostBreakdown(lat, bw, red, ctl, cod)


def reduce_scatter_cost(
    topo: Topology,
    nbytes: int,
    params: TpuCostParams = TpuCostParams(),
    dcn_stages: tuple[int, ...] = (),
    codec=None,
) -> CostBreakdown:
    """Predicted wall time of phase 1 alone (``reduce_scatter_us``):
    ``nbytes``/chip in, a 1/N owned shard out.  With the neutral
    per-phase scales, ``reduce_scatter_cost + all_gather_cost`` matches
    :func:`allreduce_cost` term for term."""
    return _phase_cost(topo, nbytes, params, "rs", dcn_stages, codec)


def all_gather_cost(
    topo: Topology,
    nbytes: int,
    params: TpuCostParams = TpuCostParams(),
    dcn_stages: tuple[int, ...] = (),
    codec=None,
) -> CostBreakdown:
    """Predicted wall time of phase 2 alone (``all_gather_us``): 1/N
    shards in, the full ``nbytes`` buffer out on every chip."""
    return _phase_cost(topo, nbytes, params, "ag", dcn_stages, codec)


def sharded_sync_cost(
    topo: Topology,
    nbytes: int,
    params: TpuCostParams = TpuCostParams(),
    dcn_stages: tuple[int, ...] = (),
    codec=None,
    secondary_topos: tuple = (),
) -> CostBreakdown:
    """One ZeRO-1 sharded sync round on the shard axis: quantized gradient
    reduce-scatter down + quantized parameter all-gather up (same byte
    profile per phase; the codec pays on BOTH wires), plus a shard-sized
    allreduce per secondary replication topology."""
    rs = _phase_cost(topo, nbytes, params, "rs", dcn_stages, codec)
    ag = _phase_cost(topo, nbytes, params, "ag", dcn_stages, codec)
    lat = rs.latency_us + ag.latency_us
    bw = rs.bandwidth_us + ag.bandwidth_us
    red = rs.reduce_us + ag.reduce_us
    ctl = rs.control_us + ag.control_us
    cod = rs.codec_us + ag.codec_us
    shard_bytes = nbytes / max(topo.num_nodes, 1)
    for t2 in secondary_topos:
        sec = allreduce_cost(t2, shard_bytes, params, codec=codec)
        lat += sec.latency_us
        bw += sec.bandwidth_us
        red += sec.reduce_us
        ctl += sec.control_us
        cod += sec.codec_us
    return CostBreakdown(lat, bw, red, ctl, cod)


def bus_bandwidth_GBps(n: int, nbytes: int, time_us: float) -> float:
    """Algorithmic (bus) bandwidth ``2(N-1)/N * S / t`` — the reporting
    metric of BASELINE.md."""
    if time_us <= 0 or n < 1:
        return 0.0
    return (2 * (n - 1) / n) * nbytes / (time_us * 1e3)
