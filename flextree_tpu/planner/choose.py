"""Topology chooser: enumerate candidate tree shapes, cost each, pick argmin.

The rebuild of ``cost_model/ChooseWidth.h`` + ``CostModel.h:82-119``'s
driver loop: enumerate ordered factorizations, evaluate the cost model,
return the cheapest shape (the reference prints it; we return a structured
plan whose ``widths`` drop straight into ``flextree_tpu.allreduce(topo=...)``
or the ``FT_TOPO`` env var).

Prime/odd device counts: the reference's planner proposes shapes for N±1
(``ChooseWidth.h:16-21`` — the disabled "lonely node" idea), but its runtime
aborts unless the width product equals N (``mpi_mod.hpp:914-918``).  We keep
the same contract: for prime N the usable candidates are the flat tree and
the ring, and the N±1 shapes are reported as *advisory* (what you'd get by
resizing the job), matching the reference's printed ``+1``/``-1`` notation.

Torus-aware mode: given a mesh shape (e.g. ``(16, 16)``), only
factorizations whose widths tile the torus axes in order are physical —
each stage's groups then ride a single ICI axis.  ``choose_topology``
prefers those when a mesh shape is provided.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

from ..schedule.stages import Topology
from .cost_model import CostBreakdown, TpuCostParams, allreduce_cost
from .factorize import is_prime, ordered_factorizations

__all__ = ["Candidate", "Plan", "choose_topology", "candidate_topologies"]


@dataclass(frozen=True)
class Candidate:
    widths: tuple[int, ...]
    cost: CostBreakdown
    torus_aligned: bool = False

    @property
    def total_us(self) -> float:
        return self.cost.total_us


@dataclass(frozen=True)
class Plan:
    """Chooser output: the winning topology plus the full ranked table."""

    num_nodes: int
    nbytes: int
    topology: Topology
    candidates: tuple[Candidate, ...]  # ranked, cheapest first
    advisory: tuple[str, ...] = ()  # e.g. prime-N resize suggestions

    @property
    def widths(self) -> tuple[int, ...]:
        return self.topology.widths

    def to_ft_topo(self) -> str:
        """The ``FT_TOPO`` env value selecting this plan."""
        return ",".join(map(str, self.topology.widths))

    def summary(self) -> str:
        lines = [
            f"plan for N={self.num_nodes}, {self.nbytes} bytes: "
            f"topo {self.topology} ({self.candidates[0].total_us:.1f} µs predicted)"
        ]
        for c in self.candidates[:8]:
            mark = " torus" if c.torus_aligned else ""
            shape = "ring" if c.widths == (1,) else "*".join(map(str, c.widths))
            lines.append(
                f"  {shape:>12}: {c.total_us:9.1f} µs "
                f"(lat {c.cost.latency_us:.1f} + bw {c.cost.bandwidth_us:.1f} "
                f"+ red {c.cost.reduce_us:.1f} + ctl {c.cost.control_us:.1f}){mark}"
            )
        for a in self.advisory:
            lines.append(f"  advisory: {a}")
        return "\n".join(lines)


def _stage_axes(
    widths: tuple[int, ...], mesh_shape: tuple[int, ...]
) -> tuple[int, ...] | None:
    """Map each stage to the mesh axis its groups ride, or None if the
    widths don't tile ``mesh_shape`` axis by axis in order.

    Aligned means: each mesh axis is covered by a contiguous run of widths
    whose product equals the axis size (so every stage's groups span exactly
    one physical axis).  The per-stage axis indices are returned so DCN
    stages can be identified by the same traversal that decides alignment.
    """
    ai = 0
    acc = 1
    axes: list[int] = []
    for w in widths:
        if ai >= len(mesh_shape):
            return None
        axes.append(ai)
        acc *= w
        if acc == mesh_shape[ai]:
            ai += 1
            acc = 1
        elif mesh_shape[ai] % acc != 0:
            return None
    if ai == len(mesh_shape) and acc == 1:
        return tuple(axes)
    return None


def candidate_topologies(n: int) -> list[tuple[int, ...]]:
    """All usable stage-width vectors for ``n`` devices: every ordered
    factorization plus the ring sentinel ``(1,)`` (the reference appends
    flat/ring sentinels in ``GetWidth.h:214-219``)."""
    shapes: list[tuple[int, ...]] = list(ordered_factorizations(n))
    shapes.append((1,))
    return shapes


def choose_topology(
    n: int,
    nbytes: int,
    params: TpuCostParams | None = None,
    mesh_shape: tuple[int, ...] | None = None,
    dcn_axes: tuple[int, ...] = (),
) -> Plan:
    """Pick the cheapest topology for ``n`` devices and ``nbytes``/chip.

    ``mesh_shape``: physical torus shape, e.g. ``(16, 16)`` for a v5e-256
    slice; when given, torus-aligned shapes get exact per-axis costing and
    non-aligned shapes are penalized implicitly (their stages still cost as
    single-axis rings, which is optimistic — alignment is reported so the
    caller can filter).  ``dcn_axes``: indices of mesh axes that are DCN
    (multi-slice outer axes).
    """
    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    if params is None:
        # measured constants from $FLEXTREE_CALIBRATION when present
        # (per-backend CALIBRATION.json, see planner/calibrate.py), else
        # the documented v5e-flavored defaults
        from .calibrate import default_params

        params = default_params()
    if dcn_axes and not mesh_shape:
        raise ValueError("dcn_axes requires mesh_shape (which axes are DCN?)")
    if mesh_shape:
        if math.prod(mesh_shape) != n:
            raise ValueError(
                f"mesh_shape {mesh_shape} has {math.prod(mesh_shape)} devices, "
                f"but n is {n}"
            )
        # drop degenerate size-1 axes, remapping dcn_axes indices to match
        keep = [i for i, s in enumerate(mesh_shape) if s > 1]
        dcn_axes = tuple(keep.index(a) for a in dcn_axes if a in keep)
        mesh_shape = tuple(mesh_shape[i] for i in keep) or None
    if n == 1:
        t = Topology.flat(1)
        return Plan(1, nbytes, t, (Candidate((1,), allreduce_cost(t, nbytes, params)),))

    cands: list[Candidate] = []
    for widths in candidate_topologies(n):
        if widths == (1,):
            from .cost_model import ring_cost

            cost = ring_cost(n, nbytes, params, crosses_dcn=bool(dcn_axes))
            cands.append(Candidate((1,), cost, False))
            continue
        topo = Topology(n, widths)
        stage_axes = _stage_axes(widths, mesh_shape) if mesh_shape else None
        aligned = stage_axes is not None
        dcn_stages: tuple[int, ...] = ()
        if dcn_axes:
            if aligned:
                # stages whose mesh axis is DCN pay DCN constants
                dcn_stages = tuple(
                    i for i, a in enumerate(stage_axes) if a in set(dcn_axes)
                )
            else:
                # a shape that doesn't tile the torus axes has groups
                # straddling the DCN boundary: price every stage at DCN
                # (pessimistic) so misaligned shapes can't win on an
                # optimistic ICI-only estimate
                dcn_stages = tuple(range(len(widths)))
        cost = allreduce_cost(topo, nbytes, params, dcn_stages=dcn_stages)
        cands.append(Candidate(widths, cost, aligned))

    # prefer torus-aligned shapes at equal cost; then cheapest
    cands.sort(key=lambda c: (c.total_us, not c.torus_aligned, len(c.widths)))
    best = cands[0]
    topo = Topology.ring(n) if best.widths == (1,) else Topology(n, best.widths)

    advisory: tuple[str, ...] = ()
    if is_prime(n) and n > 3:
        # the reference's ChooseWidth N±1 suggestion (ChooseWidth.h:16-21)
        near = []
        from .shapes import format_shape

        for m, delta in ((n - 1, +1), (n + 1, -1)):
            alt = choose_topology(m, nbytes, params)
            near.append(
                f"N={n} is prime; resizing to {m} would allow "
                f"topo {format_shape(alt.widths, delta)}"
            )
        advisory = tuple(near)

    return Plan(n, nbytes, topo, tuple(cands), advisory)
